"""Replica-movement ordering strategies.

Reference: executor/strategy/ (423 LoC): composable comparator chain deciding
inter-broker execution order — BaseReplicaMovementStrategy,
PostponeUrpReplicaMovementStrategy, PrioritizeLargeReplicaMovementStrategy,
PrioritizeSmallReplicaMovementStrategy,
PrioritizeMinIsrWithOfflineReplicasStrategy. A strategy maps a task to a sort
key; chained strategies compare lexicographically, with the base strategy
(task id order = deterministic) as the implicit tail.
"""
from __future__ import annotations

from typing import Iterable

from cruise_control_tpu.executor.task import ExecutionTask


class ReplicaMovementStrategy:
    name = "ReplicaMovementStrategy"

    def configure(self, config, **extra):
        pass

    def key(self, task: ExecutionTask, context: dict) -> tuple:
        """Sort key component; lower sorts earlier."""
        return ()

    def chain(self, next_strategy: "ReplicaMovementStrategy") -> "ChainedStrategy":
        return ChainedStrategy([self, next_strategy])


class ChainedStrategy(ReplicaMovementStrategy):
    def __init__(self, strategies: list):
        self._strategies = list(strategies)
        self.name = "+".join(s.name for s in strategies)

    def chain(self, next_strategy):
        return ChainedStrategy(self._strategies + [next_strategy])

    def key(self, task, context):
        return tuple(k for s in self._strategies for k in s.key(task, context))


class BaseReplicaMovementStrategy(ReplicaMovementStrategy):
    """Deterministic task-id order."""
    name = "BaseReplicaMovementStrategy"

    def key(self, task, context):
        return (task.task_id,)


class PostponeUrpReplicaMovementStrategy(ReplicaMovementStrategy):
    """Move partitions WITHOUT under-replicated/offline replicas first."""
    name = "PostponeUrpReplicaMovementStrategy"

    def key(self, task, context):
        urp = context.get("under_replicated", set())
        return (1 if task.tp in urp else 0,)


class PrioritizeLargeReplicaMovementStrategy(ReplicaMovementStrategy):
    name = "PrioritizeLargeReplicaMovementStrategy"

    def key(self, task, context):
        sizes = context.get("partition_size_mb", {})
        return (-sizes.get(task.tp, 0.0),)


class PrioritizeSmallReplicaMovementStrategy(ReplicaMovementStrategy):
    name = "PrioritizeSmallReplicaMovementStrategy"

    def key(self, task, context):
        sizes = context.get("partition_size_mb", {})
        return (sizes.get(task.tp, 0.0),)


class PrioritizeMinIsrWithOfflineReplicasStrategy(ReplicaMovementStrategy):
    """(At/Under)-MinISR partitions with offline replicas move first."""
    name = "PrioritizeMinIsrWithOfflineReplicasStrategy"

    def key(self, task, context):
        urgent = context.get("min_isr_with_offline", set())
        return (0 if task.tp in urgent else 1,)


STRATEGY_CLASSES = {c.name: c for c in (
    BaseReplicaMovementStrategy, PostponeUrpReplicaMovementStrategy,
    PrioritizeLargeReplicaMovementStrategy, PrioritizeSmallReplicaMovementStrategy,
    PrioritizeMinIsrWithOfflineReplicasStrategy)}


def strategy_registry(specs: Iterable[str]) -> dict:
    """Resolve ExecutorConfig ``replica.movement.strategies`` — the catalog
    of available strategy classes (built-ins by bare name, plugins by dotted
    path) — into a name -> class map including every built-in."""
    from cruise_control_tpu.config.configdef import resolve_class
    registry = dict(STRATEGY_CLASSES)
    for spec in specs or ():
        if isinstance(spec, str) and spec in registry:
            continue
        cls = resolve_class(spec)
        registry[getattr(cls, "name", cls.__name__)] = cls
    return registry


def build_strategy(names: Iterable[str],
                   registry: dict | None = None) -> ReplicaMovementStrategy:
    """Compose a chain, always terminated by the base strategy for determinism
    (BaseReplicaMovementStrategy is the reference's implicit tie-breaker).
    Unknown names raise — a typo'd strategy silently ignored would reorder an
    entire execution."""
    registry = registry or STRATEGY_CLASSES
    chain = []
    for n in names:
        short = n.rsplit(".", 1)[-1] if isinstance(n, str) else n
        if short not in registry:
            raise ValueError(f"unknown replica movement strategy {n!r}; "
                             f"available: {sorted(registry)}")
        chain.append(registry[short]())
    if not any(isinstance(s, BaseReplicaMovementStrategy) for s in chain):
        chain.append(BaseReplicaMovementStrategy())
    return ChainedStrategy(chain)


def sort_tasks(tasks: list, strategy: ReplicaMovementStrategy, context: dict) -> list:
    return sorted(tasks, key=lambda t: strategy.key(t, context))
