"""Provisioner SPI: cluster right-sizing hook.

Reference: detector/Provisioner.java (SPI; rightsize(recommendations, ...)),
NoopProvisioner.java, and the ProvisionResponse/ProvisionRecommendation/
ProvisionStatus model (UNDER_PROVISIONED / RIGHT_SIZED / OVER_PROVISIONED,
analyzer/ProvisionStatus role).
"""
from __future__ import annotations

import dataclasses
import enum


class ProvisionStatus(enum.Enum):
    UNDER_PROVISIONED = "UNDER_PROVISIONED"
    RIGHT_SIZED = "RIGHT_SIZED"
    OVER_PROVISIONED = "OVER_PROVISIONED"
    UNDECIDED = "UNDECIDED"


@dataclasses.dataclass
class ProvisionRecommendation:
    status: ProvisionStatus
    num_brokers: int = 0
    reason: str = ""

    def to_json(self) -> dict:
        return {"status": self.status.value, "numBrokers": self.num_brokers,
                "reason": self.reason}


class NoopProvisioner:
    def configure(self, config, **extra):
        pass

    def rightsize(self, recommendations: list, context: dict | None = None) -> bool:
        """Returns True if any action was taken (never, for noop)."""
        return False


@dataclasses.dataclass
class ProvisionFloors:
    """Right-sizing floors an OVER_PROVISIONED recommendation must respect
    (AnomalyDetectorConfig overprovisioned.*): never recommend shrinking
    below ``min_brokers``, below ``min_extra_racks`` spare racks beyond the
    max partition RF, or past the point where the average replica count per
    remaining broker exceeds ``max_replicas_per_broker``."""
    min_brokers: int = 3
    min_extra_racks: int = 1
    max_replicas_per_broker: int = 1500

    @classmethod
    def from_config(cls, cfg) -> "ProvisionFloors":
        return cls(
            min_brokers=cfg.get_int("overprovisioned.min.brokers"),
            min_extra_racks=cfg.get_int("overprovisioned.min.extra.racks"),
            max_replicas_per_broker=int(cfg.get_int(
                "overprovisioned.max.replicas.per.broker")))


def recommendation_from_result(res, constraint,
                               floors: ProvisionFloors | None = None,
                               ) -> ProvisionRecommendation:
    """Capacity-math provision recommendation from an OptimizerResult
    (GoalViolationDetector.java:228 -> Provisioner.rightsize path, and the
    ProvisionRecommendation attached to OptimizationFailureException by the
    capacity goals): per resource, total load vs total allowed capacity
    decides how many brokers of average capacity are missing (or spare)."""
    import math

    import numpy as np

    env, st = res.env, res.final_state
    alive = np.asarray(env.broker_alive)
    if not alive.any():
        return ProvisionRecommendation(ProvisionStatus.UNDER_PROVISIONED,
                                       num_brokers=1, reason="no alive brokers")
    util = np.asarray(st.util)[alive]                       # [B, M]
    cap = np.asarray(env.broker_capacity)[alive]
    thresh = np.asarray(constraint.capacity_threshold)
    total_load = util.sum(axis=0)
    avg_cap = cap.mean(axis=0)
    allowed = (cap * thresh[None, :]).sum(axis=0)
    deficit = total_load - allowed                          # [M] >0 = missing
    if (deficit > 0).any():
        from cruise_control_tpu.common.resources import Resource
        r = int(np.argmax(deficit / np.maximum(avg_cap * thresh, 1e-9)))
        need = math.ceil(deficit[r] / max(avg_cap[r] * thresh[r], 1e-9))
        return ProvisionRecommendation(
            ProvisionStatus.UNDER_PROVISIONED, num_brokers=max(1, need),
            reason=f"{Resource(r).name} load {total_load[r]:.1f} exceeds "
                   f"allowed capacity {allowed[r]:.1f}: add >= {max(1, need)} "
                   f"broker(s) of average capacity")
    offline = res.stats_after.get("num_offline_replicas", 0)
    if offline or any(g.violated_after for g in res.goal_results
                      if g.name.endswith("CapacityGoal")):
        return ProvisionRecommendation(
            ProvisionStatus.UNDER_PROVISIONED, num_brokers=1,
            reason="capacity goals unsatisfiable despite aggregate headroom "
                   "(placement infeasibility)")
    low = np.asarray(constraint.low_utilization_threshold)
    n = int(alive.sum())
    active = low > 0
    if active.any() and n > 1:
        avg_util_frac = total_load / np.maximum(cap.sum(axis=0), 1e-9)
        if (avg_util_frac[active] < low[active]).all():
            floors = floors or ProvisionFloors()
            # brokers removable while every resource stays under its allowed
            # aggregate capacity (reference low-utilization OVER_PROVISIONED)
            # AND the overprovisioned.* floors hold
            n_replicas = int(np.asarray(env.replica_valid).sum())
            keep_floor = max(
                1, floors.min_brokers,
                math.ceil(n_replicas / max(floors.max_replicas_per_broker, 1)))
            keep = n
            while keep > keep_floor and (
                    total_load <= avg_cap * thresh * (keep - 1) - 1e-9).all():
                keep -= 1
            # min.extra.racks: keep enough brokers that the cluster retains
            # (racks hosting the max partition RF) + extra racks' worth of
            # spread — shrinking below max-RF racks would make rack-aware
            # placement permanently infeasible. With one broker per rack in
            # the worst case this is a broker floor.
            racks_alive = np.asarray(env.broker_rack)[alive]
            num_racks = len(np.unique(racks_alive))
            if num_racks > 0:
                valid = np.asarray(env.replica_valid)
                parts = np.asarray(env.replica_partition)[valid]
                max_rf = int(np.bincount(parts).max()) if parts.size else 1
                per_rack = n / num_racks
                min_racks = min(num_racks, max_rf + floors.min_extra_racks)
                keep = max(keep, math.ceil(min_racks * per_rack))
            if keep < n:
                return ProvisionRecommendation(
                    ProvisionStatus.OVER_PROVISIONED, num_brokers=n - keep,
                    reason=f"{n - keep} broker(s) removable under the "
                           f"low-utilization thresholds (floors: "
                           f">={keep_floor} brokers)")
    return ProvisionRecommendation(ProvisionStatus.RIGHT_SIZED)
