"""Columnar ClusterSnapshot + warmup/no-retrace coverage.

The tentpole invariants of the columnar monitor->model path:
1. The simulated backend's INCREMENTALLY-maintained snapshot equals the
   protocol shim's derivation from the dict metadata — through every mutator.
2. cluster_model(use_snapshot=True) is bit-identical to the legacy
   partitions()-dict build on a randomized cluster with dead brokers, dead
   disks and offline replicas.
3. Columnar sampling ingests the same windows as per-sample objects.
4. EngineParams pytree leaves normalize numpy scalars (no silent retrace)
   and the module survives re-registration (importlib.reload).
5. GoalOptimizer.warmup pre-compiles everything a same-bucket real cluster
   needs: the follow-up optimizations() triggers ZERO new XLA compiles.
"""
from __future__ import annotations

import dataclasses
import logging

import numpy as np
import pytest

from cruise_control_tpu.backend.interface import snapshot_from_metadata
from cruise_control_tpu.backend.simulated import SimulatedClusterBackend
from cruise_control_tpu.monitor.load_monitor import LoadMonitor
from cruise_control_tpu.monitor.sampling.samplers import SimulatedMetricSampler

ARRAY_FIELDS = ("partition_topic", "partition_leader", "rep_ptr", "rep_bid",
                "rep_leader", "rep_disk", "broker_ids", "broker_alive")
LIST_FIELDS = ("topics", "partition_keys", "broker_rack", "broker_logdirs")


def _rich_backend(seed=0, num_brokers=10, num_partitions=60):
    """Randomized cluster: JBOD brokers, mixed RF, dead broker + dead disk."""
    rng = np.random.default_rng(seed)
    be = SimulatedClusterBackend()
    for b in range(num_brokers):
        be.add_broker(b, f"r{b % 3}",
                      logdirs={f"/d{j}": 50_000.0 for j in range(1 + b % 3)})
    for p in range(num_partitions):
        rf = 1 + int(rng.integers(0, 3))
        reps = [int(x) for x in rng.choice(num_brokers, size=rf,
                                           replace=False)]
        be.create_partition(f"t{p % 6}", p, reps,
                            size_mb=float(rng.uniform(10, 500)),
                            bytes_in_rate=float(rng.uniform(1, 50)),
                            bytes_out_rate=float(rng.uniform(1, 100)),
                            cpu_util=float(rng.uniform(0.1, 5)))
    be.kill_broker(num_brokers - 1)        # offline replicas via dead broker
    be.fail_disk(1, "/d1")                 # offline replicas via dead disk
    return be


def _assert_snapshot_equal(a, b):
    for f in ARRAY_FIELDS:
        va, vb = getattr(a, f), getattr(b, f)
        assert np.array_equal(va, vb), (f, va, vb)
    for f in LIST_FIELDS:
        assert getattr(a, f) == getattr(b, f), f


def test_snapshot_matches_shim_derivation():
    be = _rich_backend()
    _assert_snapshot_equal(be.snapshot(),
                           snapshot_from_metadata(be.brokers(),
                                                  be.partitions()))


def test_snapshot_incremental_after_mutations():
    """Every partition mutator keeps the columnar rows in sync: snapshot()
    after reassignments/advance, leader elections, logdir moves, broker
    death/restart and late partition creation still equals the shim."""
    be = _rich_backend(seed=3)
    be.snapshot()                                   # prime the cache
    be.alter_partition_reassignments({("t0", 0): [2, 3, 4]})
    be.advance(10 * 60_000.0)                       # complete the copy
    info = be.partitions()[("t1", 1)]
    alive = [b for b in info.replicas if be.brokers()[b].alive]
    if len(alive) > 1:
        be.elect_leaders({("t1", 1): alive[-1]})
    (b0,) = [b for b in be.partitions()[("t0", 0)].replicas][:1]
    ld = list(be.brokers()[b0].logdirs)[-1]
    be.alter_replica_logdirs({("t0", 0, b0): ld})
    be.kill_broker(2)
    be.restart_broker(2)
    be.create_partition("late-topic", 999, [0, 2])  # re-sorts the key order
    _assert_snapshot_equal(be.snapshot(),
                           snapshot_from_metadata(be.brokers(),
                                                  be.partitions()))


def _monitored(be, columnar=True, rounds=8):
    lm = LoadMonitor(backend=be,
                     sampler=SimulatedMetricSampler(be, columnar=columnar))
    lm.start_up()
    for i in range(rounds):
        lm.sample_once(now_ms=i * 300_000.0)
    return lm


def test_columnar_model_bit_identical_to_legacy():
    be = _rich_backend(seed=1)
    lm = _monitored(be)
    ct_snap, meta_snap = lm.cluster_model(use_snapshot=True)
    ct_dict, meta_dict = lm.cluster_model(use_snapshot=False)
    assert int(np.asarray(ct_snap.replica_offline).sum()) > 0  # scenario real
    for f in dataclasses.fields(ct_snap):
        a = np.asarray(getattr(ct_snap, f.name))
        b = np.asarray(getattr(ct_dict, f.name))
        assert a.dtype == b.dtype, f.name
        assert np.array_equal(a, b), f.name
    for f in ("topic_names", "partition_ids", "broker_ids", "rack_ids",
              "logdirs", "num_racks", "num_valid_replicas"):
        assert getattr(meta_snap, f) == getattr(meta_dict, f), f


def test_columnar_sampling_equals_per_sample_objects():
    """A columnar sampling round lands in the same aggregator windows as the
    legacy per-partition sample objects (backend noise must be 0)."""
    be = _rich_backend(seed=2)
    lm_col = _monitored(be, columnar=True)
    lm_obj = _monitored(be, columnar=False)
    ct_a, _ = lm_col.cluster_model()
    ct_b, _ = lm_obj.cluster_model()
    np.testing.assert_allclose(np.asarray(ct_a.leader_load),
                               np.asarray(ct_b.leader_load), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(ct_a.broker_utilization()),
                               np.asarray(ct_b.broker_utilization()),
                               rtol=1e-6)


def test_columnar_sampler_emits_blocks():
    be = _rich_backend(seed=4)
    samples = SimulatedMetricSampler(be).get_samples(0.0)
    assert not samples.partition_samples and samples.partition_blocks
    block = samples.partition_blocks[0]
    assert samples.num_partition_samples() == len(block)
    rows = list(samples.all_partition_samples())     # lazy expansion
    assert len(rows) == len(block)
    assert rows[0].values.keys() == {"CPU_USAGE", "DISK_USAGE",
                                     "LEADER_BYTES_IN", "LEADER_BYTES_OUT"}


def test_engine_params_normalizes_numpy_leaves():
    """ADVICE r5: numpy-typed config values must not change the traced-leaf
    dtypes (a silent full retrace of every goal program)."""
    import jax

    from cruise_control_tpu.analyzer.engine import EngineParams
    p_py = EngineParams(max_iters=64, min_gain=1e-9)
    p_np = EngineParams(max_iters=np.int64(64), min_gain=np.float64(1e-9),
                        stall_retries=np.int32(8), stat_slope_min=np.float64(1e-3))
    leaves_py, tree_py = jax.tree_util.tree_flatten(p_py)
    leaves_np, tree_np = jax.tree_util.tree_flatten(p_np)
    assert tree_py == tree_np            # static aux data identical
    assert [type(x) for x in leaves_py] == [type(x) for x in leaves_np]
    assert leaves_py == leaves_np


def test_engine_params_numpy_leaves_zero_retrace():
    """ADVICE r5, the measured form: a jitted program taking EngineParams as
    a pytree argument must NOT retrace when equivalent budgets arrive as
    numpy scalars (config values) instead of Python ints/floats — the
    normalized leaves hash to the same signature, cache size stays 1."""
    import jax
    import jax.numpy as jnp

    from cruise_control_tpu.analyzer.engine import EngineParams

    @jax.jit
    def prog(p: EngineParams):
        return jnp.asarray(p.max_iters) + jnp.asarray(p.stall_retries)

    prog(EngineParams(max_iters=64, min_gain=1e-9, stall_retries=8))
    assert prog._cache_size() == 1
    prog(EngineParams(max_iters=np.int64(64), min_gain=np.float64(1e-9),
                      stall_retries=np.int32(8),
                      tail_pass_budget=np.int16(64)))
    assert prog._cache_size() == 1, "numpy-typed budget leaves forced a retrace"
    # different budget VALUES reuse the executable too (traced leaves)
    prog(EngineParams(max_iters=128, stall_retries=4))
    assert prog._cache_size() == 1


def test_engine_module_reload_safe():
    """ADVICE r5: module-level pytree registration must survive
    importlib.reload (ValueError on re-registration)."""
    import importlib

    import cruise_control_tpu.analyzer.engine as engine
    importlib.reload(engine)             # would raise before the guard
    importlib.reload(engine)


@pytest.mark.slow
def test_warmup_then_zero_retrace():
    """GoalOptimizer.warmup on a shape-matched synthetic cluster compiles
    everything: a real same-bucket cluster then optimizes with ZERO new XLA
    compiles (the retrace-regression certificate for the compile-cache +
    warmup work)."""
    import jax

    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer

    goals = ["ReplicaCapacityGoal", "ReplicaDistributionGoal",
             "LeaderReplicaDistributionGoal"]
    opt = GoalOptimizer()
    opt.warmup(num_brokers=10, num_replicas=500, num_partitions=240,
               num_topics=6, num_racks=3, logdirs_per_broker=3,
               max_replication=3, goal_names=goals)

    be = _rich_backend(seed=7, num_brokers=10, num_partitions=240)
    lm = _monitored(be)
    ct, meta = lm.cluster_model()

    records = []
    handler = logging.Handler()
    handler.emit = records.append
    prev = bool(jax.config.jax_log_compiles)
    jax.config.update("jax_log_compiles", True)
    logging.getLogger("jax").addHandler(handler)
    try:
        res = opt.optimizations(ct, meta, goal_names=goals,
                                raise_on_failure=False,
                                skip_hard_goal_check=True)
    finally:
        logging.getLogger("jax").removeHandler(handler)
        jax.config.update("jax_log_compiles", prev)
    assert res.goal_results
    compiles = [r.getMessage() for r in records
                if "Compiling" in r.getMessage()]
    assert not compiles, compiles[:5]
