"""Per-goal deterministic tests (DeterministicClusterTest role,
reference analyzer/DeterministicClusterTest.java:60)."""
import numpy as np
import pytest

from cruise_control_tpu.analyzer import make_env, init_state, optimize_goal
from cruise_control_tpu.analyzer.env import BalancingConstraint
from cruise_control_tpu.analyzer.goals import make_goal
from cruise_control_tpu.analyzer.state import refresh
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.model.fixtures import (
    capacity_violated, dead_broker_cluster, leaders_skewed, rack_violated,
    small_cluster, unbalanced_two_brokers,
)


def _setup(fixture):
    ct, meta = fixture() if callable(fixture) else fixture
    env = make_env(ct, meta)
    st = init_state(env, ct.replica_broker, ct.replica_is_leader,
                    ct.replica_offline, ct.replica_disk)
    return env, st


def _run(env, st, name, prev=(), **goal_kw):
    g = make_goal(name, **goal_kw)
    st, info = optimize_goal(env, st, g, tuple(prev))
    return g, st, info


def test_rack_aware_goal_fixes_violations():
    env, st = _setup(rack_violated)
    g, st, info = _run(env, st, "RackAwareGoal")
    assert not bool(info["violated_after"])
    # each partition now spans both racks
    rack = np.asarray(env.broker_rack)[np.asarray(st.replica_broker)]
    part = np.asarray(env.replica_partition)
    valid = np.asarray(env.replica_valid)
    for p in np.unique(part[valid]):
        racks = rack[valid & (part == p)]
        assert len(set(racks.tolist())) == len(racks)


def test_disk_capacity_goal_sheds_load():
    env, st = _setup(capacity_violated)
    g, st, info = _run(env, st, "DiskCapacityGoal")
    assert not bool(info["violated_after"])
    util = np.asarray(st.util[:, Resource.DISK])
    cap = np.asarray(env.broker_capacity[:, Resource.DISK])
    assert (util <= 0.8 * cap + 100).all()


def test_disk_distribution_uses_swaps():
    env, st = _setup(unbalanced_two_brokers)
    g, st, info = _run(env, st, "DiskUsageDistributionGoal")
    assert not bool(info["violated_after"])
    util = np.asarray(st.util[:, Resource.DISK])
    avg_pct = util.sum() / np.asarray(env.broker_capacity[:, Resource.DISK]).sum()
    cap = np.asarray(env.broker_capacity[:, Resource.DISK])
    assert (util <= avg_pct * 1.09 * cap + 100).all()
    assert (util >= avg_pct * 0.91 * cap - 100).all()


def test_leader_distribution_balances_leaders():
    env, st = _setup(leaders_skewed)
    g, st, info = _run(env, st, "LeaderReplicaDistributionGoal")
    assert not bool(info["violated_after"])
    assert np.asarray(st.leader_count).max() <= 1 + 1  # ceil(2/3*(1.09)) + margin


def test_self_healing_moves_all_offline_replicas():
    env, st = _setup(dead_broker_cluster)
    prev = []
    for name in ("RackAwareGoal", "ReplicaCapacityGoal", "DiskCapacityGoal"):
        g, st, info = _run(env, st, name, prev)
        prev.append(g)
    offline = np.asarray(st.replica_offline & env.replica_valid)
    assert offline.sum() == 0
    # nothing remains on the dead broker
    dead = ~np.asarray(env.broker_alive)
    broker_of = np.asarray(st.replica_broker)[np.asarray(env.replica_valid)]
    assert not dead[broker_of].any()


def test_replica_capacity_goal():
    env, st = _setup(small_cluster)
    constraint = BalancingConstraint(max_replicas_per_broker=3)
    g, st, info = _run(env, st, "ReplicaCapacityGoal", constraint=constraint)
    assert not bool(info["violated_after"])
    assert np.asarray(st.replica_count).max() <= 3


def test_incremental_state_matches_refresh():
    """The engine's scatter bookkeeping must equal a from-scratch recompute
    (LoadConsistencyTest role)."""
    env, st = _setup(unbalanced_two_brokers)
    for name in ("DiskUsageDistributionGoal", "NetworkOutboundUsageDistributionGoal"):
        g, st, info = _run(env, st, name)
    fresh = refresh(env, st)
    np.testing.assert_allclose(np.asarray(st.util), np.asarray(fresh.util),
                               rtol=1e-4, atol=1e-2)
    np.testing.assert_array_equal(np.asarray(st.replica_count),
                                  np.asarray(fresh.replica_count))
    np.testing.assert_array_equal(np.asarray(st.leader_count),
                                  np.asarray(fresh.leader_count))
    np.testing.assert_array_equal(np.asarray(st.part_rack_count),
                                  np.asarray(fresh.part_rack_count))
    np.testing.assert_array_equal(np.asarray(st.topic_broker_count),
                                  np.asarray(fresh.topic_broker_count))
    np.testing.assert_allclose(np.asarray(st.disk_util), np.asarray(fresh.disk_util),
                               rtol=1e-4, atol=1e-2)


def test_prev_goal_acceptance_respected():
    """After RackAwareGoal, later goals must not recreate co-rack duplicates."""
    env, st = _setup(rack_violated)
    g1, st, _ = _run(env, st, "RackAwareGoal")
    g2, st, _ = _run(env, st, "DiskUsageDistributionGoal", prev=[g1])
    g3, st, _ = _run(env, st, "ReplicaDistributionGoal", prev=[g1, g2])
    # rack invariant still holds
    rack = np.asarray(env.broker_rack)[np.asarray(st.replica_broker)]
    part = np.asarray(env.replica_partition)
    valid = np.asarray(env.replica_valid)
    for p in np.unique(part[valid]):
        racks = rack[valid & (part == p)]
        assert len(set(racks.tolist())) == len(racks)


def test_preferred_leader_election():
    from cruise_control_tpu.analyzer.goals.leader_election import PreferredLeaderElectionGoal
    ct, meta = leaders_skewed()
    env = make_env(ct, meta)
    st = init_state(env, ct.replica_broker, ct.replica_is_leader,
                    ct.replica_offline, ct.replica_disk)
    ple = PreferredLeaderElectionGoal()
    # fixture's leaders are already at position 0 -> no-op
    before = np.asarray(st.replica_is_leader).copy()
    st2 = ple.apply(env, st)
    np.testing.assert_array_equal(before, np.asarray(st2.replica_is_leader))
    # flip leadership away then re-elect
    st3 = ple.apply(env, refresh(env, st2.__class__(**{
        **{f.name: getattr(st2, f.name) for f in st2.__dataclass_fields__.values()},
        "replica_is_leader": st2.replica_is_leader.at[0].set(False).at[1].set(True),
    })))
    assert bool(st3.replica_is_leader[0])
    assert not bool(st3.replica_is_leader[1])


def test_group_cumsum_and_wave_admission_math():
    """Unit checks of the budgeted-wave machinery (engine._group_cumsum):
    per-group inclusive prefix sums in the given row order + in-group ranks,
    against a straightforward numpy oracle."""
    import numpy as np
    from cruise_control_tpu.analyzer.engine import _group_cumsum

    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    K, DIMS = 64, 3
    groups = rng.integers(0, 9, K).astype(np.int32)
    d = rng.uniform(0.0, 2.0, (K, DIMS)).astype(np.float32)
    cum, rank = _group_cumsum(jnp.asarray(groups), jnp.asarray(d))
    cum = np.asarray(cum)
    rank = np.asarray(rank)
    seen: dict = {}
    run: dict = {}
    for i in range(K):
        g = int(groups[i])
        run[g] = run.get(g, np.zeros(DIMS)) + d[i]
        # f32 global-cumsum-minus-base incurs ~1e-6 cancellation error
        np.testing.assert_allclose(cum[i], run[g], rtol=1e-4, atol=1e-5)
        assert rank[i] == seen.get(g, 0)
        seen[g] = seen.get(g, 0) + 1


def test_budgeted_wave_respects_capacity_band():
    """A wave may drain an overloaded broker with MANY moves at once, but the
    per-destination cumulative budget must keep every destination under the
    capacity limit — the multi-move analogue of accept_move's band check."""
    from cruise_control_tpu.analyzer import (
        EngineParams, init_state, make_env, optimize_goal,
    )
    from cruise_control_tpu.analyzer.goals import make_goal
    from cruise_control_tpu.model.builder import ClusterModelBuilder

    b = ClusterModelBuilder()
    for i in range(6):
        b.add_broker(i, rack="r0")
    # broker 0 hosts 30 partitions of 600 MB; capacity threshold 0.8 of
    # 500k MB -> plenty of room, but disk-distribution bands are tight
    for p in range(30):
        b.add_replica("hot", p, 0, is_leader=True,
                      load=[1.0, 10.0, 10.0, 600.0])
    for p in range(3):
        b.add_replica("cold", p, 1 + (p % 5), is_leader=True,
                      load=[1.0, 10.0, 10.0, 100.0])
    ct, meta = b.build()
    env = make_env(ct, meta)
    st = init_state(env, ct.replica_broker, ct.replica_is_leader,
                    ct.replica_offline, ct.replica_disk)
    goal = make_goal("DiskUsageDistributionGoal")
    # stall_retries=0: this test bounds the number of PRODUCTIVE passes a
    # budgeted wave needs; exploration retries would pad the count
    st, info = optimize_goal(env, st, goal, (),
                             EngineParams(max_iters=64, stall_retries=0))
    util = np.asarray(st.util)[:, 3]
    alive_utils = util[:6]
    # cluster balances: no broker outside the band afterwards
    assert not bool(info["violated_after"])
    # and the work took FEW passes (the wave drains broker 0 in bulk) —
    # one-per-broker waves would need ~25 passes for 25+ moves off broker 0
    assert int(info["passes"]) <= 10, int(info["passes"])
    assert abs(alive_utils.sum() - (30 * 600.0 + 3 * 100.0)) < 1.0


def test_satisfied_goal_exits_with_clamped_tail():
    """A goal that starts satisfied must exit after the clamped
    sat_stall_retries tail (EngineParams.sat_*), not burn the full violated-
    goal exploration budget — the clamp is what keeps the 7k/1M chain's
    satisfied goals nearly free."""
    from cruise_control_tpu.analyzer.engine import EngineParams
    env, st = _setup(small_cluster)
    g = make_goal("RackAwareGoal")
    # first run fixes any violation; the second run starts satisfied
    st, info = optimize_goal(env, st, g, ())
    assert not bool(info["violated_after"])
    params = EngineParams()
    st, info2 = optimize_goal(env, st, g, (), params)
    assert not bool(info2["violated_after"])
    assert int(info2["iterations"]) == 0
    # pass count: 1 discovery pass + sat_stall_retries + exit margin,
    # far below the violated-goal budget (stall_retries + tail_pass_budget)
    assert int(info2["passes"]) <= params.sat_stall_retries + 3
    assert not bool(info2["hit_max_iters"])


def test_leadership_primary_prefers_transfers_over_moves():
    """LeaderReplicaDistributionGoal is leadership-primary: on a cluster
    where transfers alone can balance leader counts, it must fix the skew
    without relocating any replica (the reference's transfer-first ordering,
    LeaderReplicaDistributionGoal.java:369)."""
    env, st = _setup(leaders_skewed)
    before_brokers = np.asarray(st.replica_broker).copy()
    g, st, info = _run(env, st, "LeaderReplicaDistributionGoal")
    assert g.leadership_primary
    assert not bool(info["violated_after"])
    assert np.array_equal(np.asarray(st.replica_broker), before_brokers), \
        "leadership-primary goal moved replicas although transfers sufficed"
