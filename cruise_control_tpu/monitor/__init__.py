from cruise_control_tpu.monitor.aggregator.sample_aggregator import (
    AggregationResult, Extrapolation, MetricSampleAggregator,
)
from cruise_control_tpu.monitor.load_monitor import (
    LoadMonitor, LoadMonitorState, ModelCompletenessRequirements, ModelGeneration,
    NotEnoughValidWindowsError,
)
from cruise_control_tpu.monitor.metricdef import (
    BROKER_METRIC_DEF, PARTITION_METRIC_DEF, RAW_METRIC_TYPES,
)

__all__ = [
    "AggregationResult", "Extrapolation", "MetricSampleAggregator",
    "LoadMonitor", "LoadMonitorState", "ModelCompletenessRequirements",
    "ModelGeneration", "NotEnoughValidWindowsError",
    "BROKER_METRIC_DEF", "PARTITION_METRIC_DEF", "RAW_METRIC_TYPES",
]
