"""HTTP client with async-task polling.

Reference: cruise-control-client/cruisecontrolclient/client/ — Endpoint.py
(one class per REST endpoint, each declaring its allowed parameters),
Query.py (URL building), Responder.py (the retry/poll loop that follows
202 + User-Task-ID until the final response). Parameter validation reuses the
server's endpoint specs (cruise_control_tpu.api.endpoints) — single source of
truth instead of the reference's duplicated CCParameter classes.
"""
from __future__ import annotations

import json
import time
import urllib.error
import urllib.parse
import urllib.request

from cruise_control_tpu.api.endpoints import (
    COMMON_PARAMS, ENDPOINT_PARAMS, GET_ENDPOINTS, EndPoint,
)
from cruise_control_tpu.api.user_tasks import USER_TASK_HEADER_NAME

URL_PREFIX = "/kafkacruisecontrol"


class CruiseControlClientError(Exception):
    def __init__(self, message: str, status: int = 0, body: dict | None = None):
        super().__init__(message)
        self.status = status
        self.body = body or {}


def _encode_value(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (list, tuple)):
        return ",".join(str(x) for x in v)
    return str(v)


class CruiseControlClient:
    """One method per endpoint; async 202 responses are polled to completion
    via the User-Task-ID header (Responder.py retry loop role)."""

    def __init__(self, address: str, timeout_s: float = 300.0,
                 poll_interval_s: float = 1.0, auth: tuple | None = None):
        if "://" not in address:
            address = f"http://{address}"
        self.base_url = address.rstrip("/")
        if not self.base_url.endswith(URL_PREFIX):
            self.base_url += URL_PREFIX
        self.timeout_s = timeout_s
        self.poll_interval_s = poll_interval_s
        # session cookie jar (the reference client rides requests.Session;
        # the server's CCSESSIONID scopes user-task affinity per session)
        self._session_cookie: str | None = None
        self._auth_header = None
        if auth is not None:
            import base64
            user, password = auth
            self._auth_header = "Basic " + base64.b64encode(
                f"{user}:{password}".encode()).decode()

    # ------------------------------------------------------------ plumbing
    def _validate(self, endpoint: EndPoint, params: dict) -> dict:
        spec = {**COMMON_PARAMS, **ENDPOINT_PARAMS[endpoint]}
        clean = {}
        for k, v in params.items():
            if v is None:
                continue
            if k not in spec:
                raise CruiseControlClientError(
                    f"unknown parameter {k!r} for {endpoint.path} "
                    f"(allowed: {sorted(spec)})")
            clean[k] = _encode_value(v)
        return clean

    def _request_once(self, method: str, endpoint: EndPoint, query: dict,
                      task_id: str | None):
        url = f"{self.base_url}/{endpoint.path}"
        if query:
            url += "?" + urllib.parse.urlencode(query)
        headers = {}
        if task_id:
            headers[USER_TASK_HEADER_NAME] = task_id
        if self._auth_header:
            headers["Authorization"] = self._auth_header
        if self._session_cookie:
            headers["Cookie"] = self._session_cookie
        req = urllib.request.Request(url, method=method, headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                set_cookie = resp.headers.get("Set-Cookie")
                if set_cookie:
                    self._session_cookie = set_cookie.split(";", 1)[0]
                return resp.status, json.loads(resp.read().decode()), \
                    resp.headers.get(USER_TASK_HEADER_NAME)
        except urllib.error.HTTPError as e:
            body = {}
            try:
                body = json.loads(e.read().decode() or "{}")
            except json.JSONDecodeError:
                pass
            raise CruiseControlClientError(
                body.get("errorMessage", str(e)), status=e.code,
                body=body) from None

    def request(self, endpoint: EndPoint, **params) -> dict:
        """Issue a request, following the 202-progress protocol to the final
        response. Returns the response body dict."""
        method = "GET" if endpoint in GET_ENDPOINTS else "POST"
        query = self._validate(endpoint, params)
        deadline = time.time() + self.timeout_s
        status, body, task_id = self._request_once(method, endpoint, query, None)
        while status == 202 and "reviewResult" not in body:
            if time.time() > deadline:
                raise CruiseControlClientError(
                    f"{endpoint.path} still in progress after "
                    f"{self.timeout_s}s (task {task_id})", status=202, body=body)
            time.sleep(self.poll_interval_s)
            status, body, task_id = self._request_once(
                method, endpoint, query, task_id)
        return body

    # ---------------------------------------------------------- endpoints
    def state(self, **p) -> dict:
        return self.request(EndPoint.STATE, **p)

    def kafka_cluster_state(self, **p) -> dict:
        return self.request(EndPoint.KAFKA_CLUSTER_STATE, **p)

    def load(self, **p) -> dict:
        return self.request(EndPoint.LOAD, **p)

    def partition_load(self, **p) -> dict:
        return self.request(EndPoint.PARTITION_LOAD, **p)

    def proposals(self, **p) -> dict:
        return self.request(EndPoint.PROPOSALS, **p)

    def rebalance(self, **p) -> dict:
        return self.request(EndPoint.REBALANCE, **p)

    def add_broker(self, brokerid, **p) -> dict:
        return self.request(EndPoint.ADD_BROKER, brokerid=brokerid, **p)

    def remove_broker(self, brokerid, **p) -> dict:
        return self.request(EndPoint.REMOVE_BROKER, brokerid=brokerid, **p)

    def demote_broker(self, brokerid, **p) -> dict:
        return self.request(EndPoint.DEMOTE_BROKER, brokerid=brokerid, **p)

    def fix_offline_replicas(self, **p) -> dict:
        return self.request(EndPoint.FIX_OFFLINE_REPLICAS, **p)

    def stop_proposal_execution(self, **p) -> dict:
        return self.request(EndPoint.STOP_PROPOSAL_EXECUTION, **p)

    def pause_sampling(self, **p) -> dict:
        return self.request(EndPoint.PAUSE_SAMPLING, **p)

    def resume_sampling(self, **p) -> dict:
        return self.request(EndPoint.RESUME_SAMPLING, **p)

    def user_tasks(self, **p) -> dict:
        return self.request(EndPoint.USER_TASKS, **p)

    def bootstrap(self, **p) -> dict:
        return self.request(EndPoint.BOOTSTRAP, **p)

    def train(self, **p) -> dict:
        return self.request(EndPoint.TRAIN, **p)

    def admin(self, **p) -> dict:
        return self.request(EndPoint.ADMIN, **p)

    def review(self, **p) -> dict:
        return self.request(EndPoint.REVIEW, **p)

    def review_board(self, **p) -> dict:
        return self.request(EndPoint.REVIEW_BOARD, **p)

    def topic_configuration(self, topic: str, replication_factor: int, **p) -> dict:
        return self.request(EndPoint.TOPIC_CONFIGURATION, topic=topic,
                            replication_factor=replication_factor, **p)
