"""Topic anomaly finders.

Reference: detector/TopicAnomalyDetector.java (52),
TopicReplicationFactorAnomalyFinder.java (topics whose RF differs from the
desired RF) and PartitionSizeAnomalyFinder.java (partitions larger than the
configured threshold).
"""
from __future__ import annotations

from cruise_control_tpu.detector.anomalies import AnomalyType, TopicAnomaly


class TopicReplicationFactorAnomalyFinder:
    def __init__(self, target_rf: int = 3):
        self.target_rf = target_rf

    def configure(self, config, **extra):
        if config is not None:
            self.target_rf = config.get_int("self.healing.target.topic.replication.factor")

    def anomalies(self, backend, now_ms: float) -> list:
        bad: dict[str, dict] = {}
        for (topic, _p), info in backend.partitions().items():
            rf = len(info.replicas)
            if rf != self.target_rf:
                entry = bad.setdefault(topic, {"targetRF": self.target_rf,
                                               "partitionsWithBadRF": 0})
                entry["partitionsWithBadRF"] += 1
        if not bad:
            return []
        return [TopicAnomaly(
            anomaly_type=AnomalyType.TOPIC_ANOMALY, detected_ms=now_ms,
            bad_topics=bad,
            description=f"topics with replication factor != {self.target_rf}: "
                        f"{sorted(bad)}")]


class PartitionSizeAnomalyFinder:
    def __init__(self, threshold_mb: float = 1_000_000.0):
        self.threshold_mb = threshold_mb

    def configure(self, config, **extra):
        if config is not None:
            self.threshold_mb = config.get_double("provision.partition.size.threshold.mb")

    def anomalies(self, backend, now_ms: float) -> list:
        oversized = {f"{t}-{p}": info.size_mb
                     for (t, p), info in backend.partitions().items()
                     if info.size_mb > self.threshold_mb}
        if not oversized:
            return []
        return [TopicAnomaly(
            anomaly_type=AnomalyType.TOPIC_ANOMALY, detected_ms=now_ms,
            bad_topics={}, fixable=False,
            description=f"oversized partitions (> {self.threshold_mb} MB): "
                        f"{sorted(oversized)}")]
