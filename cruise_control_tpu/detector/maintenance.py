"""Maintenance events.

Reference: detector/MaintenanceEventDetector.java (83) +
MaintenanceEventTopicReader.java — operators submit maintenance plans
(ADD_BROKER/REMOVE_BROKER/DEMOTE_BROKER/REBALANCE/FIX_OFFLINE_REPLICAS/
TOPIC_REPLICATION_FACTOR) to a Kafka topic; IdempotenceCache.java dedups
re-delivered plans. Here the reader SPI pulls from a JSONL spool directory
(one plan per line: {"type": "REMOVE_BROKER", "brokers": [3], "ts": ...}).
"""
from __future__ import annotations

import json
import os

from cruise_control_tpu.detector.anomalies import AnomalyType, MaintenanceEvent


class IdempotenceCache:
    """Drops plans already seen within the retention window, remembering at
    most ``max_size`` recent plans (detector/IdempotenceCache.java;
    AnomalyDetectorConfig maintenance.event.{enable.idempotence,
    max.idempotence.cache.size, idempotence.retention.ms}). ``enabled=False``
    turns the cache into a pass-through."""

    def __init__(self, retention_ms: float = 180_000.0, max_size: int = 25,
                 enabled: bool = True):
        self._retention = retention_ms
        self._max = max_size
        self._enabled = enabled
        self._seen: dict[str, float] = {}

    def seen_before(self, key: str, now_ms: float) -> bool:
        if not self._enabled:
            return False
        self._seen = {k: t for k, t in self._seen.items()
                      if now_ms - t < self._retention}
        if key in self._seen:
            return True
        if len(self._seen) >= self._max:
            oldest = min(self._seen, key=self._seen.get)
            del self._seen[oldest]
        self._seen[key] = now_ms
        return False


def _event_from_dict(d: dict, now_ms: float, event_cls=MaintenanceEvent):
    """One parsed plan dict -> MaintenanceEvent (shared by every reader);
    ``event_cls`` is the pluggable maintenance.event.class."""
    return event_cls(
        anomaly_type=AnomalyType.MAINTENANCE_EVENT,
        detected_ms=now_ms, plan_type=d.get("type", ""),
        brokers=d.get("brokers", []), topics=d.get("topics", {}),
        description=f"maintenance plan {d.get('type')}")


class FileMaintenanceEventReader:
    def __init__(self, path: str = ""):
        self._path = path
        self._offset = 0
        self._event_cls = MaintenanceEvent

    def configure(self, config, **extra):
        path = extra.get("path") or (config.get_string("maintenance.event.path")
                                     if config is not None else "")
        if path:
            self._path = path
        if config is not None:
            self._event_cls = (config.get_class("maintenance.event.class")
                               or MaintenanceEvent)

    def read_events(self, now_ms: float) -> list:
        if not self._path:
            return []
        spool = os.path.join(self._path, "maintenance_events.jsonl")
        if not os.path.exists(spool):
            return []
        events = []
        with open(spool) as f:
            f.seek(self._offset)
            for line in f:
                try:
                    d = json.loads(line)
                except json.JSONDecodeError:
                    continue
                events.append(_event_from_dict(d, now_ms, self._event_cls))
            self._offset = f.tell()
        return events


class TopicMaintenanceEventReader:
    """Maintenance plans consumed from a TOPIC transport
    (detector/MaintenanceEventTopicReader.java role: the reference reads the
    __MaintenanceEvent Kafka topic from a stored offset forward; here the
    same length-prefixed topic-log transport the metrics reporter uses,
    reporter/topic.FileMetricsTopic, carries JSON-encoded plans and the
    reader tracks its consumer offset). Producers submit with
    :func:`submit_maintenance_plan`."""

    def __init__(self, path: str = ""):
        self._path = path
        self._topic = None
        self._offset = 0
        self._event_cls = MaintenanceEvent

    def configure(self, config, **extra):
        path = extra.get("path") or (
            config.get_string("maintenance.event.topic.path")
            if config is not None else "")
        if path:
            self._path = path
        if config is not None:
            self._event_cls = (config.get_class("maintenance.event.class")
                               or MaintenanceEvent)

    def _ensure(self):
        if self._topic is None and self._path:
            from cruise_control_tpu.reporter.topic import FileMetricsTopic
            self._topic = FileMetricsTopic(self._path)
        return self._topic

    def read_events(self, now_ms: float) -> list:
        topic = self._ensure()
        if topic is None:
            return []
        events = []
        for next_offset, payload in topic.consume(self._offset):
            self._offset = next_offset
            try:
                d = json.loads(payload.decode())
            except (json.JSONDecodeError, UnicodeDecodeError):
                continue
            events.append(_event_from_dict(d, now_ms, self._event_cls))
        return events


def submit_maintenance_plan(path: str, plan_type: str, brokers=(),
                            topics=None) -> None:
    """Operator-side producer (MaintenanceEventTopicReader's write
    counterpart): append one plan to the maintenance topic log."""
    from cruise_control_tpu.reporter.topic import FileMetricsTopic
    FileMetricsTopic(path).append([json.dumps(
        {"type": plan_type, "brokers": list(brokers),
         "topics": dict(topics or {})}).encode()])
