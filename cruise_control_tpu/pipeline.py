"""Continuous pipelined service loop: overlap sampling, sync, optimize and
execute — kill the round.

The blocking service loop is strictly sample -> sync -> optimize -> execute:
at the 7k-broker rung sampling alone is ~10 s/round on the critical path
(BENCH_r05) even though the optimizer never needed it to be — PR 3's delta
scatters and PR 5's donation-safe sessions already built the incremental
half of an overlapped design. This module is the other half: a four-stage
pipeline whose steady-state critical path is the warm optimizer alone.

Stages (each a thread in the live service, or one deterministic unit of work
per ``step()`` in lockstep mode):

- **ingest** — the sampling driver: fetch one round of samples
  (``LoadMonitor.fetch_samples``) and push the un-ingested batch into a
  host-side per-shape-bucket ring buffer. Never touches the aggregators.
- **sync** — drain the ring into the aggregators (``ingest_samples``), then
  bring the resident session up to date (``ResidentClusterSession.sync``):
  delta payload assembly + double-buffered device uploads. Because the
  session's finalize program materializes the next round's (env, state) into
  FRESH buffers from host mirrors, this runs safely while the PREVIOUS
  round's fused chain is still executing on the donated state — the shadow
  upload slot (session.shadow_syncs counts exactly these).
- **optimize** — when the synced generation advanced AND
  ``meetCompletenessRequirements`` holds, refresh the proposal cache from
  the resident state. Completeness is the explicit BACKPRESSURE signal: an
  unmet requirement STALLS this stage (counted, visible in state_json)
  instead of erroring, and the stage releases on its own once live sampling
  fills the windows (no ``GET /bootstrap`` needed — the monitor's unified
  service-mode clock makes windows form from live sampling alone).
- **execute** — drain submitted proposal rounds asynchronously so the next
  round's ingest/sync/optimize start while the executor moves replicas.
  Every submission carries a generation tag; a set whose metadata
  generation is stale — or that a newer set has superseded — is DROPPED,
  not executed (``pipeline-stale-rounds-dropped``).

Determinism: the sim drives ``step(now_ms)`` — stage hand-offs are keyed by
the tick's simulated clock and run in a fixed order within the tick, so the
pipelined loop stays bit-reproducible per (scenario, seed). The threaded
mode is the same stage code free-running.

Overlap proof: stage spans are noted on the app's FlightRecorder
(``note_stage``), which measures, at note time, how much of each span ran
under an in-flight optimize round — every RoundTrace then carries per-stage
lanes + overlap fractions (``trace.stages`` / ``trace.overlap``), the
flight-recorder evidence that sampling_s/sync_s are off the critical path.
"""
from __future__ import annotations

import dataclasses
import logging
import threading
import time
from collections import deque

from cruise_control_tpu.monitor.load_monitor import (
    ModelCompletenessRequirements, NotEnoughValidWindowsError,
)

LOG = logging.getLogger(__name__)

# Priority lanes shared with the fleet admission engine (fleet.py): lower
# value drains first. Heals (detector FIX/PREDICTED verdicts) preempt
# user-initiated hygiene rebalances, which preempt background refresh.
LANE_HEAL = 0
LANE_REBALANCE = 1
LANE_REFRESH = 2
LANE_NAMES = ("heal", "rebalance", "refresh")


def _bucket(n: int, minimum: int = 64) -> int:
    """Power-of-two shape bucket (the model's bucketing policy, host-side)."""
    b = max(minimum, 1)
    n = max(n, 1)
    while b < n:
        b *= 2
    return b


class SampleRingBuffer:
    """Bounded host-side ring of fetched-but-not-ingested sample batches,
    keyed by shape bucket (bucketed partition/broker sample counts) so
    steady-state batches of one cluster shape reuse one lane. Push never
    blocks: a full bucket drops its OLDEST batch (counted) — sampling
    backpressure is window ageing, never an unbounded queue. Drain returns
    batches in global arrival order regardless of bucket, so ingestion order
    is deterministic."""

    def __init__(self, capacity: int = 8):
        self.capacity = max(int(capacity), 1)
        self._lock = threading.Lock()
        self._buckets: dict[tuple, deque] = {}
        self._seq = 0
        self.pushed = 0
        self.dropped = 0

    @staticmethod
    def bucket_key(samples) -> tuple:
        np_ = sum(len(b.entities) for b in
                  getattr(samples, "partition_blocks", ())) \
            + len(getattr(samples, "partition_samples", ()) or ())
        nb = len(getattr(samples, "broker_samples", ()) or ())
        return (_bucket(np_), _bucket(nb, 16))

    def push(self, now_ms: float, samples, fetch_s: float = 0.0) -> tuple:
        key = self.bucket_key(samples)
        with self._lock:
            lane = self._buckets.setdefault(key, deque())
            if len(lane) >= self.capacity:
                lane.popleft()
                self.dropped += 1
            lane.append((self._seq, float(now_ms), samples, float(fetch_s)))
            self._seq += 1
            self.pushed += 1
        return key

    def drain(self) -> list:
        """Pop every pending batch, globally ordered by arrival."""
        with self._lock:
            out = [item for lane in self._buckets.values() for item in lane]
            for lane in self._buckets.values():
                lane.clear()
        out.sort(key=lambda item: item[0])
        return out

    def __len__(self) -> int:
        with self._lock:
            return sum(len(lane) for lane in self._buckets.values())

    def state_json(self) -> dict:
        with self._lock:
            return {"capacity": self.capacity, "pushed": self.pushed,
                    "dropped": self.dropped,
                    "depth": sum(len(v) for v in self._buckets.values()),
                    "buckets": {str(k): len(v)
                                for k, v in self._buckets.items()}}


@dataclasses.dataclass
class ProposalRound:
    """One generation-tagged execution submission. ``sticky`` rounds are
    self-healing FIX executions routed through the execute stage (PR 13):
    they are never dropped as stale/superseded — a heal computed against a
    slightly older metadata generation still beats no heal, and the
    executor's own per-task re-validation DEADs anything that genuinely no
    longer applies."""
    seq: int
    metadata_generation: int
    proposals: list
    execute_kw: dict = dataclasses.field(default_factory=dict)
    submitted_ms: float = 0.0
    sticky: bool = False
    # admission-lane priority (LANE_*): drain order is (lane, seq) so a
    # re-queued refresh round can never jump ahead of a queued heal
    lane: int = LANE_REFRESH
    # launch-in-flight install seam (fleet admission engine): when set to
    # (result, computed_ms), the drain installs the proposal cache instead
    # of executing — exempt from staleness/supersede drops (idempotent
    # cache write, the install itself records its generation)
    install: tuple | None = None


class PipelinedServiceLoop:
    """The four-stage continuous controller over one :class:`CruiseControl`.

    Lockstep mode (sim/bench/tests): call ``step(now_ms)`` per tick — stages
    run once each in a fixed order (execute-drain, ingest, sync, optimize),
    hand-offs keyed by the tick clock. Threaded mode (the live service):
    ``start()``/``stop()`` run the same stage methods on four daemon
    threads. ``pipelined_round`` is the measured unit bench/tests use: one
    optimize round with the NEXT round's ingest+sync overlapped under it.
    """

    def __init__(self, cc, config=None):
        self.cc = cc
        config = config or cc.config
        self.monitor = cc.load_monitor
        self.recorder = cc.flight_recorder
        self.sensors = cc.sensors
        self.ring = SampleRingBuffer(
            capacity=config.get_int("service.pipeline.ring.capacity"))
        self._interval_ms = float(
            config.get_int("metric.sampling.interval.ms"))
        self._req = ModelCompletenessRequirements(
            min_required_num_windows=config.get_int(
                "service.pipeline.min.windows"))
        # backpressure + staleness observability
        self._stall_meter = self.sensors.meter("pipeline-backpressure-stalls")
        self._stale_meter = self.sensors.meter("pipeline-stale-rounds-dropped")
        self._exec_meter = self.sensors.meter("pipeline-executions-drained")
        self.sensors.gauge("pipeline-ring-depth", lambda: len(self.ring))
        self.stalled = False          # optimize stage currently backpressured
        self.stall_count = 0
        self.release_count = 0
        self.optimize_rounds = 0
        self.ingest_rounds = 0
        self.sync_rounds = 0
        self._synced_generation = -1  # session.sync_generation at last sync
        self._optimized_generation = -1
        self._exec_queue: deque[ProposalRound] = deque()
        self._exec_seq = 0
        self._exec_lock = threading.Lock()
        self.stale_rounds_dropped = 0
        self.executions_drained = 0
        self.installs_drained = 0
        self._last_exec_seq = -1
        # threaded mode
        self._stop = threading.Event()
        self._wake_sync = threading.Event()
        self._wake_opt = threading.Event()
        self._wake_exec = threading.Event()
        self._threads: list[threading.Thread] = []

    # ------------------------------------------------------------- stages
    def ingest_once(self, now_ms: float | None = None) -> int:
        """Ingest stage: fetch one sampling round into the ring (no
        aggregator writes). Returns #batches pushed (0 or 1)."""
        t0 = time.monotonic()
        fetched = self.monitor.fetch_samples(now_ms)
        if fetched is None:
            return 0
        samples, now, fetch_s = fetched
        self.ring.push(now, samples, fetch_s)
        self.ingest_rounds += 1
        self.recorder.note_stage("ingest", t0, time.monotonic())
        return 1

    def sync_once(self) -> dict:
        """Sync stage: drain the ring into the aggregators, then bring the
        resident session up to the new windows/metadata (the shadow-slot
        upload when an optimize round is in flight). Returns the sync info
        (``{"mode": ...}`` or ``{"skipped": ...}``)."""
        t0 = time.monotonic()
        drained = self.ring.drain()
        ingested = 0
        for _seq, _now, samples, fetch_s in drained:
            ingested += self.monitor.ingest_samples(samples, fetch_s=fetch_s)
        info: dict = {"ingested": ingested, "batches": len(drained)}
        sess = self.cc.resident_session
        if sess is not None:
            try:
                info.update(sess.sync())
                self._synced_generation = sess.sync_generation
                # the sync -> optimize hand-off (PR 16): what the next
                # optimize round's incremental eligibility check will see —
                # accumulated churn, dirty-set sizes, load drift
                info["pending_delta"] = sess.pending_delta_json()
            except NotEnoughValidWindowsError as e:
                info["skipped"] = str(e)    # backpressure: windows not filled
        else:
            # no resident session: the optimize stage's model build is the
            # sync; generation bumps track the aggregator
            self._synced_generation += 1 if ingested else 0
        if drained:
            self.sync_rounds += 1
            self.recorder.note_stage("sync", t0, time.monotonic(),
                                     batches=len(drained))
        return info

    def backpressured(self) -> bool:
        """The explicit backpressure signal: meetCompletenessRequirements
        (SURVEY §2.3) gates the optimize stage — unmet requirements STALL the
        stage (no error, no round) until live sampling fills the windows."""
        return not self.monitor.meet_completeness_requirements(self._req)

    def optimize_once(self, force_refresh: bool = False) -> dict:
        """Optimize stage: refresh the proposal cache from the synced
        resident state, unless backpressured or nothing new was synced."""
        if self.backpressured():
            if not self.stalled:
                self.stalled = True
                self.stall_count += 1
                LOG.info("pipeline optimize stage STALLED on completeness "
                         "backpressure (windows not filled)")
            self._stall_meter.mark()
            return {"stalled": True}
        if self.stalled:
            self.stalled = False
            self.release_count += 1
            LOG.info("pipeline optimize stage released (windows filled)")
        if (not force_refresh
                and self._optimized_generation == self._synced_generation
                and self.optimize_rounds > 0):
            return {"skipped": "nothing new synced"}
        gen = self._synced_generation
        try:
            res = self.cc.cached_proposals(force_refresh=force_refresh)
        except NotEnoughValidWindowsError:
            # raced a window roll-out between the check and the build: treat
            # exactly like backpressure (stall, retry next step)
            self._stall_meter.mark()
            return {"stalled": True}
        self._optimized_generation = gen
        self.optimize_rounds += 1
        out = {"optimized": True, "generation": gen}
        mode = getattr(res, "round_mode", None)
        if mode is not None:
            out["round_mode"] = mode      # full | reduced | revalidated
        return out

    # ------------------------------------------------------------ execute
    def accepts_fix_routing(self) -> bool:
        """Whether self-healing FIX executions may be handed to this loop's
        execute stage (app._route_fixes_async): only the THREADED mode — a
        lockstep (sim) pipeline keeps heals blocking so scenario timelines
        stay bit-identical per (scenario, seed)."""
        return bool(self._threads)

    def submit_execution(self, proposals: list, execute_kw: dict | None = None,
                         sticky: bool = False,
                         lane: int | None = None) -> ProposalRound:
        """Queue one generation-tagged proposal set for async execution.
        The tag is the monitor's CURRENT metadata generation; the drain
        drops the set if the metadata generation moved (the cluster the plan
        was computed against no longer exists) or a newer set superseded it.
        ``sticky`` (routed FIX heals) exempts the round from both drops.
        ``lane`` defaults to the heal lane for sticky rounds and the refresh
        lane otherwise; the drain processes (lane, seq) order."""
        gen = self.monitor.model_generation().metadata_generation
        if lane is None:
            lane = LANE_HEAL if sticky else LANE_REFRESH
        with self._exec_lock:
            rnd = ProposalRound(seq=self._exec_seq, metadata_generation=gen,
                                proposals=list(proposals),
                                execute_kw=dict(execute_kw or {}),
                                submitted_ms=self.cc._now_ms(),
                                sticky=sticky, lane=int(lane))
            self._exec_seq += 1
            self._exec_queue.append(rnd)
        self._wake_exec.set()
        return rnd

    def submit_install(self, result, computed_ms: float | None = None,
                       lane: int = LANE_REFRESH) -> ProposalRound:
        """Queue a proposal-cache install to ride the execute stage — the
        fleet admission engine's launch-in-flight seam: the scheduler hands
        a completed tenant's batched result here and starts its next vmapped
        launch immediately; the install lands on this loop's thread."""
        with self._exec_lock:
            rnd = ProposalRound(seq=self._exec_seq, metadata_generation=-1,
                                proposals=[],
                                submitted_ms=self.cc._now_ms(),
                                lane=int(lane),
                                install=(result, computed_ms))
            self._exec_seq += 1
            self._exec_queue.append(rnd)
        self._wake_exec.set()
        return rnd

    def drain_executions(self, blocking: bool = True) -> dict:
        """Execute stage: run the newest still-fresh proposal round, dropping
        stale ones. ``blocking`` executes synchronously (lockstep mode);
        threaded mode passes False and lets the executor's own thread drain."""
        t0 = time.monotonic()
        with self._exec_lock:
            pending = list(self._exec_queue)
            self._exec_queue.clear()
        if not pending:
            return {"executed": 0, "dropped": 0, "installed": 0}
        # lane-aware drain order: heals before hygiene rebalances before
        # background refresh, seq within a lane — so a round re-queued while
        # an execution owned the executor can never jump ahead of a heal
        # that arrived after it
        pending.sort(key=lambda r: (r.lane, r.seq))
        current_gen = self.monitor.model_generation().metadata_generation
        executed = 0
        dropped = 0
        installed = 0
        # sticky (routed-heal) rounds never supersede or get superseded by
        # the precompute's rebalance rounds — newest-wins applies to the
        # ordinary rounds only; install rounds are cache writes, exempt
        ordinary = [r.seq for r in pending if not r.sticky and r.install is None]
        newest = max(ordinary) if ordinary else -1
        for i, rnd in enumerate(pending):
            if rnd.install is not None:
                res, computed_ms = rnd.install
                self.cc.install_proposal_cache(res, computed_ms=computed_ms)
                installed += 1
                self.installs_drained += 1
                continue
            stale = (not rnd.sticky
                     and (rnd.metadata_generation != current_gen
                          or rnd.seq != newest))
            if stale or not rnd.proposals:
                if rnd.proposals:
                    dropped += 1
                    self.stale_rounds_dropped += 1
                    self._stale_meter.mark()
                    LOG.info(
                        "dropping stale proposal round %d (generation %d != "
                        "%d or superseded by %d)", rnd.seq,
                        rnd.metadata_generation, current_gen, newest)
                continue
            if self.cc.executor.has_ongoing_execution():
                # an in-flight execution owns the executor: re-queue this
                # round AND everything still unprocessed behind it (sticky
                # heals made multi-execute drains possible — dropping the
                # tail here would lose queued heals)
                with self._exec_lock:
                    for r in reversed(pending[i:]):
                        self._exec_queue.appendleft(r)
                break
            self.cc.executor.execute_proposals(
                rnd.proposals, blocking=blocking,
                generation=rnd.metadata_generation, **rnd.execute_kw)
            executed += 1
            self.executions_drained += 1
            self._exec_meter.mark()
            self._last_exec_seq = rnd.seq
        if executed or dropped or installed:
            self.recorder.note_stage("execute", t0, time.monotonic(),
                                     executed=executed, dropped=dropped,
                                     installed=installed)
        return {"executed": executed, "dropped": dropped,
                "installed": installed}

    # ----------------------------------------------------------- lockstep
    def step(self, now_ms: float | None = None, optimize: bool = True) -> dict:
        """One deterministic pipeline step (the sim's per-tick drive): stage
        hand-offs keyed by ``now_ms`` — the tick clock — never wall clock.
        Fixed order: execute-drain, ingest, sync, optimize."""
        out: dict = {}
        out["execute"] = self.drain_executions(blocking=True)
        out["ingested"] = self.ingest_once(now_ms)
        out["sync"] = self.sync_once()
        if optimize:
            out["optimize"] = self.optimize_once()
        return out

    # ------------------------------------------------- the measured round
    def pipelined_round(self, now_ms: float | None = None,
                        join_timeout_s: float = 900.0) -> dict:
        """ONE steady service round with the hand-offs overlapped — the
        bench/test unit: round N's optimize runs on its own thread while
        round N+1's ingest + sync (the shadow-slot upload) run under it.
        Returns {"result", "wall_s", "sync_info", "trace"} where ``trace``
        is the recorded RoundTrace carrying the stage lanes + overlap
        fractions for the NEXT round to consume."""
        box: dict = {}

        def _optimize():
            try:
                box["result"] = self.cc.cached_proposals(force_refresh=True)
            except Exception as e:   # noqa: BLE001 — surfaced to the caller
                box["error"] = e

        t0 = time.monotonic()
        t = threading.Thread(target=_optimize, name="pipeline-optimize")
        t.start()
        # wait for the optimize round to take the session state (its sync
        # memo-hits and the chain dispatches) before bumping the aggregator
        # generation underneath it — otherwise the optimize thread redoes
        # the sync and the overlap is lost
        deadline = time.monotonic() + 10.0
        while (not self.recorder.optimize_in_flight() and t.is_alive()
               and time.monotonic() < deadline):
            time.sleep(0.002)
        # round N+1's ingest + sync, overlapped with the in-flight chain
        self.ingest_once(now_ms)
        sync_info = self.sync_once()
        t.join(join_timeout_s)
        if "error" in box:
            raise box["error"]
        self.optimize_rounds += 1
        self._optimized_generation = self._synced_generation
        return {"result": box.get("result"),
                "wall_s": time.monotonic() - t0,
                "sync_info": sync_info,
                "trace": self.recorder.last()}

    # ----------------------------------------------------------- threaded
    def start(self) -> None:
        """Free-running mode: four daemon stage threads. The ingest thread
        owns the sampling cadence (and advances a simulated backend clock by
        the interval, like the legacy SamplingLoop did)."""
        if self._threads:
            return
        self._stop.clear()
        backend = self.cc.backend

        def ingest_loop():
            while not self._stop.wait(self._interval_ms / 1000.0):
                try:
                    if hasattr(backend, "advance"):
                        backend.advance(self._interval_ms)
                    if self.ingest_once():
                        self._wake_sync.set()
                except Exception:    # noqa: BLE001
                    LOG.exception("pipeline ingest round failed")

        def sync_loop():
            while not self._stop.is_set():
                self._wake_sync.wait(self._interval_ms / 1000.0)
                self._wake_sync.clear()
                if self._stop.is_set():
                    return
                try:
                    if len(self.ring):
                        self.sync_once()
                        self._wake_opt.set()
                except Exception:    # noqa: BLE001
                    LOG.exception("pipeline sync round failed")

        def optimize_loop():
            while not self._stop.is_set():
                self._wake_opt.wait(self._interval_ms / 1000.0)
                self._wake_opt.clear()
                if self._stop.is_set():
                    return
                try:
                    self.optimize_once()
                except Exception:    # noqa: BLE001
                    LOG.exception("pipeline optimize round failed")

        def execute_loop():
            while not self._stop.is_set():
                self._wake_exec.wait(1.0)
                self._wake_exec.clear()
                if self._stop.is_set():
                    return
                try:
                    # blocking inside this thread: executions serialize here
                    # while ingest/sync/optimize free-run on their threads
                    self.drain_executions(blocking=True)
                except Exception:    # noqa: BLE001
                    LOG.exception("pipeline execution drain failed")

        for name, fn in (("pipeline-ingest", ingest_loop),
                         ("pipeline-sync", sync_loop),
                         ("pipeline-optimize", optimize_loop),
                         ("pipeline-execute", execute_loop)):
            th = threading.Thread(target=fn, name=name, daemon=True)
            th.start()
            self._threads.append(th)

    def stop(self) -> None:
        self._stop.set()
        for ev in (self._wake_sync, self._wake_opt, self._wake_exec):
            ev.set()
        for th in self._threads:
            th.join(30.0)
        self._threads.clear()

    # -------------------------------------------------------------- state
    def state_json(self) -> dict:
        return {
            "mode": "threaded" if self._threads else "lockstep",
            "stalled": self.stalled,
            "stallCount": self.stall_count,
            "releaseCount": self.release_count,
            "ingestRounds": self.ingest_rounds,
            "syncRounds": self.sync_rounds,
            "optimizeRounds": self.optimize_rounds,
            "executionsDrained": self.executions_drained,
            "installsDrained": self.installs_drained,
            "staleRoundsDropped": self.stale_rounds_dropped,
            "syncedGeneration": self._synced_generation,
            "optimizedGeneration": self._optimized_generation,
            "ring": self.ring.state_json(),
        }
