"""Predictive control plane: training-free workload forecasting.

The reactive loops (detector/, analyzer/) act only on the *current* windowed
load; this package projects each partition's per-metric history forward a
configurable horizon so goal violations can be detected — and healed —
before they exist. See docs/DESIGN.md §21.
"""
from cruise_control_tpu.forecast.forecaster import (
    ForecastKnobs,
    ForecastResult,
    WorkloadForecaster,
    forecast_batch,
    forecast_reference,
)

__all__ = [
    "ForecastKnobs",
    "ForecastResult",
    "WorkloadForecaster",
    "forecast_batch",
    "forecast_reference",
]
