"""Soft-goal plateau fixpoint proof (VERDICT r2 weak #3 / next-step #4).

The random rungs end with several soft distribution goals violated. The
reference's greedy has exactly one termination condition: NO single legal,
acceptance-approved, self-satisfying action improves the goal
(AbstractGoal.java:98-103 — the per-broker loop ends when no balancing
action applies). So the honest question is whether our engine stops at that
same fixpoint or merely starves (approximate top-k hiding candidates).

This test re-runs the default chain on the BENCH rung-2 cluster and, for
every goal still violated at the end, EXHAUSTIVELY scores every
(replica, destination) move, every leadership transfer and every swap pair
against the final state — exact top-k over ALL replicas, no approximation —
under the full legitimacy + previously-optimized-goal acceptance masks. If
zero positive-gain actions survive, the violated end-state is a true greedy
fixpoint: the Java optimizer's own loop, faced with this state, would stop
too (violated soft goals are then a property of the instance, not of the
engine's search). Any surviving positive action is an engine search hole —
and fails the test.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.analyzer.engine import EngineParams
from cruise_control_tpu.analyzer.goals.base import (
    legit_leadership_mask, legit_move_mask, legit_swap_mask,
)
from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
from cruise_control_tpu.model.random_cluster import RandomClusterSpec, generate

MIN_GAIN = EngineParams().min_gain


def _chunked_positive_moves(env, st, goal, prev, chunk=2048) -> int:
    """Count positive-gain, fully-accepted moves over ALL replicas."""
    if not goal.uses_replica_moves:
        return 0
    R = env.num_replicas
    # the goal's move_score contract only covers its OWN candidate-eligible
    # replicas (replica_key > -inf) — e.g. the leader-count goal scores
    # assuming the candidate IS a leader
    sev = goal.broker_severity(env, st)
    eligible = np.asarray(goal.replica_key(env, st, sev)) > -np.inf
    total = 0
    for lo in range(0, R, chunk):
        cand = jnp.arange(lo, min(lo + chunk, R), dtype=jnp.int32)
        mask = legit_move_mask(env, st, cand, goal.options)
        mask = mask & jnp.asarray(eligible[lo:lo + chunk])[:, None]
        for g in prev:
            mask = mask & g.accept_move(env, st, cand)
        score = jnp.where(mask, goal.move_score(env, st, cand), -jnp.inf)
        total += int((np.asarray(score) > MIN_GAIN).sum())
    return total


def _chunked_positive_leaderships(env, st, goal, prev, chunk=2048) -> int:
    if not goal.uses_leadership_moves:
        return 0
    R = env.num_replicas
    sev = goal.broker_severity(env, st)
    eligible = np.asarray(goal.leader_key(env, st, sev)) > -np.inf
    total = 0
    for lo in range(0, R, chunk):
        cand = jnp.arange(lo, min(lo + chunk, R), dtype=jnp.int32)
        mask = legit_leadership_mask(env, st, cand)
        mask = mask & jnp.asarray(eligible[lo:lo + chunk])[:, None]
        for g in prev:
            mask = mask & g.accept_leadership(env, st, cand)
        score = jnp.where(mask, goal.leadership_score(env, st, cand), -jnp.inf)
        total += int((np.asarray(score) > MIN_GAIN).sum())
    return total


def _sampled_positive_swaps(env, st, goal, prev, k=512) -> int:
    """Swaps are O(R^2); check the k x k most promising pairs by the goal's
    own swap keys (exact top-k) — the same candidate frontier the engine's
    swap phase would see with an oversized pool."""
    if not goal.uses_swaps:
        return 0
    sev = goal.broker_severity(env, st)
    okey = goal.swap_out_key(env, st, sev)
    ikey = goal.swap_in_key(env, st, sev)
    k = min(k, env.num_replicas)
    _, cand_out = jax.lax.top_k(okey, k)
    _, cand_in = jax.lax.top_k(ikey, k)
    mask = legit_swap_mask(env, st, cand_out, cand_in)
    for g in prev:
        mask = mask & g.accept_swap(env, st, cand_out, cand_in)
    score = jnp.where(mask, goal.swap_score(env, st, cand_out, cand_in),
                      -jnp.inf)
    return int((np.asarray(score) > MIN_GAIN).sum())


@pytest.mark.slow
def test_rung2_violated_goals_are_greedy_fixpoints():
    ct, meta = generate(RandomClusterSpec(
        num_brokers=100, num_racks=10, num_topics=40, num_partitions=5000,
        max_replication=3, skew=1.0, seed=3140, target_cpu_util=0.45))
    opt = GoalOptimizer()
    res = opt.optimizations(ct, meta, raise_on_failure=False,
                            skip_hard_goal_check=True)
    assert res.violated_goals_after, "nothing violated — plateau test is moot"
    from cruise_control_tpu.analyzer.goals import make_goals
    goals = make_goals([g.name for g in res.goal_results
                        if g.name != "PreferredLeaderElectionGoal"],
                       opt.constraint)
    env, st = res.env, res.final_state
    holes = {}
    for i, g in enumerate(goals):
        if g.name not in res.violated_goals_after:
            continue
        prev = tuple(goals[:i])
        n_moves = _chunked_positive_moves(env, st, g, prev)
        n_leads = _chunked_positive_leaderships(env, st, g, prev)
        n_swaps = _sampled_positive_swaps(env, st, g, prev)
        if n_moves or n_leads or n_swaps:
            holes[g.name] = (n_moves, n_leads, n_swaps)
    assert not holes, (
        f"engine stopped with applicable actions remaining (search holes): "
        f"{holes} — violated goals: {res.violated_goals_after}")

    # the engine's OWN in-program certificate (engine._finisher exhaustive
    # scans) must agree with this host-side oracle: every violated survivor
    # is flagged fixpoint-proven and none reads as budget-exhausted
    by_name = {g.name: g for g in res.goal_results}
    for name in res.violated_goals_after:
        gr = by_name[name]
        assert gr.fixpoint_proven, (
            f"{name}: host oracle proves the fixpoint but the engine's "
            f"certificate disagrees (moves={gr.moves_remaining}, "
            f"leads={gr.leads_remaining}, swaps={gr.swap_window_remaining})")
        assert not gr.hit_max_iters, name
