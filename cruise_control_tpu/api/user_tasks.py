"""Async user-task tracking with UUID handles.

Reference: servlet/UserTaskManager.java (836 LoC) — every async request gets a
UUID returned in the ``User-Task-ID`` response header; a repeated identical
request from the same client resumes the same task instead of spawning a new
one; completed tasks are retained per endpoint type for a configurable window
and listed by GET /user_tasks.

Differences from the reference: session affinity is (client_ip, endpoint,
query-params) rather than a servlet HttpSession cookie — same dedup contract,
no cookie jar needed — and expiry runs inline on access instead of on a
5-second scanner thread (deterministic under test clocks).
"""
from __future__ import annotations

import enum
import threading
import time
import uuid as uuid_mod
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable

from cruise_control_tpu.api.endpoints import EndPoint
from cruise_control_tpu.api.progress import OperationProgress

USER_TASK_HEADER_NAME = "User-Task-ID"


class UserTaskLimitError(RuntimeError):
    """max.active.user.tasks overflow — the servlet maps this to the
    reference's 429 Too Many Requests (not a generic 500)."""


class TaskState(enum.Enum):
    """UserTaskManager.TaskState (ACTIVE/IN_EXECUTION/COMPLETED/COMPLETED_WITH_ERROR)."""
    ACTIVE = "Active"
    IN_EXECUTION = "InExecution"
    COMPLETED = "Completed"
    COMPLETED_WITH_ERROR = "CompletedWithError"


class UserTaskInfo:
    def __init__(self, task_id: str, endpoint: EndPoint, method: str,
                 params: dict[str, Any], client: str, start_ms: float):
        self.task_id = task_id
        self.endpoint = endpoint
        self.method = method
        self.params = params
        self.client = client
        self.start_ms = start_ms
        self.progress = OperationProgress(endpoint.path)
        self.future: Future | None = None
        self.execution_began_ms: float | None = None
        self.execution_finished_ms: float | None = None
        self.completed_ms: float | None = None
        self.state = TaskState.ACTIVE

    @property
    def done(self) -> bool:
        return self.future is not None and self.future.done()

    def result_json(self) -> dict:
        assert self.future is not None
        return self.future.result()

    def to_json(self) -> dict:
        status = self.state.value
        if self.state is TaskState.ACTIVE and self.done:
            status = (TaskState.COMPLETED_WITH_ERROR.value
                      if self.future.exception() else TaskState.COMPLETED.value)
        return {
            "UserTaskId": self.task_id,
            "RequestURL": f"{self.method} /{self.endpoint.path}",
            "ClientIdentity": self.client,
            "StartMs": int(self.start_ms),
            "Status": status,
        }


class UserTaskManager:
    """UUID-per-async-request tracking (UserTaskManager.java:221-276)."""

    def __init__(self, max_active_tasks: int = 25,
                 completed_task_retention_ms: float = 24 * 3600 * 1000.0,
                 session_expiry_ms: float = 60 * 1000.0,
                 max_workers: int = 8,
                 time_fn: Callable[[], float] | None = None,
                 max_cached_completed: int = 100,
                 max_cached_completed_by_type: dict | None = None):
        self._max_active = max_active_tasks
        self._retention_ms = completed_task_retention_ms
        self._session_expiry_ms = session_expiry_ms
        # completed-task cache caps: global (UserTaskManagerConfig
        # max.cached.completed.user.tasks) + per endpoint type
        # (max.cached.completed.{kafka.admin,kafka.monitor,...}.user.tasks;
        # None entries fall back to the global cap)
        self._max_completed = max_cached_completed
        self._max_completed_by_type = dict(max_cached_completed_by_type or {})
        self._time = time_fn or (lambda: time.time() * 1000.0)
        self._lock = threading.Lock()
        self._executor = ThreadPoolExecutor(max_workers=max_workers,
                                            thread_name_prefix="user-task")
        # session key -> task id (UserTaskManager._sessionKeyToUserTaskIdMap)
        self._session_to_task: dict[tuple, tuple[str, float]] = {}
        self._active: dict[str, UserTaskInfo] = {}
        self._completed: dict[str, UserTaskInfo] = {}

    @staticmethod
    def _session_key(client: str, endpoint: EndPoint, params: dict) -> tuple:
        frozen = tuple(sorted((k, str(v)) for k, v in params.items()))
        return (client, endpoint, frozen)

    def _expire(self) -> None:
        now = self._time()
        for tid, task in list(self._active.items()):
            if task.done:
                task.state = (TaskState.COMPLETED_WITH_ERROR
                              if task.future.exception() else TaskState.COMPLETED)
                task.completed_ms = now
                self._completed[tid] = task
                del self._active[tid]
        for key, (tid, ts) in list(self._session_to_task.items()):
            task = self._active.get(tid) or self._completed.get(tid)
            if task is None:
                if now - ts > self._session_expiry_ms:
                    del self._session_to_task[key]
                continue
            # sessions stay bound while the task runs; the expiry clock starts
            # when the task completes (UserTaskManager.expireOldSessions keeps
            # sessions alive across long-running operations the same way)
            if task.done and now - (task.completed_ms or ts) > self._session_expiry_ms:
                del self._session_to_task[key]
        for tid, task in list(self._completed.items()):
            # retention runs from completion, not start: a long-running task
            # must still be retrievable for the full window after it finishes
            if now - (task.completed_ms or task.start_ms) > self._retention_ms:
                del self._completed[tid]
        # enforce the per-endpoint-type completed caps, oldest evicted first
        by_type: dict = {}
        for tid, task in self._completed.items():
            by_type.setdefault(task.endpoint.endpoint_type, []).append((tid, task))
        for etype, entries in by_type.items():
            cap = self._max_completed_by_type.get(etype)
            cap = self._max_completed if cap is None else cap
            if len(entries) > cap:
                entries.sort(key=lambda e: e[1].completed_ms or e[1].start_ms)
                for tid, _ in entries[:len(entries) - cap]:
                    del self._completed[tid]
        # ... and the GLOBAL completed cap across all types
        if len(self._completed) > self._max_completed:
            ordered = sorted(self._completed.items(),
                             key=lambda e: e[1].completed_ms or e[1].start_ms)
            for tid, _ in ordered[:len(ordered) - self._max_completed]:
                del self._completed[tid]

    def get_or_create_task(self, client: str, endpoint: EndPoint, method: str,
                           params: dict[str, Any],
                           work: Callable[[OperationProgress], dict],
                           task_id: str | None = None,
                           idempotent: bool = True) -> UserTaskInfo:
        """Resume the task named by the User-Task-ID header, or the one bound
        to this (client, endpoint, params) session, or start a new one.

        ``idempotent=False`` (mutating ops: non-dry-run rebalance etc.) only
        resumes session-bound tasks that are still running — a COMPLETED
        mutating op must not be silently replayed from cache for a fresh
        request; the reference avoids this via HttpSession cookies that a new
        client invocation would not carry."""
        with self._lock:
            self._expire()
            if task_id is not None:
                task = self._active.get(task_id) or self._completed.get(task_id)
                if task is None:
                    raise KeyError(f"unknown User-Task-ID {task_id!r}")
                if (task.endpoint, task.params) != (endpoint, params):
                    raise KeyError(
                        f"User-Task-ID {task_id!r} was created by a different "
                        f"request ({task.endpoint.path})")
                # bind the CALLER's session too: a poll from a fresh session
                # that resumes by header must leave that session able to
                # find the task by cookie alone afterwards (the reference
                # re-associates the HttpSession on every request)
                self._session_to_task[self._session_key(client, endpoint,
                                                        params)] = (
                    task.task_id, self._time())
                return task
            skey = self._session_key(client, endpoint, params)
            bound = self._session_to_task.get(skey)
            if bound is not None:
                task = self._active.get(bound[0]) or self._completed.get(bound[0])
                if task is not None and (idempotent or not task.done):
                    return task
            if len(self._active) >= self._max_active:
                raise UserTaskLimitError(
                    f"there are already {len(self._active)} active user tasks, "
                    f"which has reached the limit {self._max_active}")
            tid = str(uuid_mod.uuid4())
            task = UserTaskInfo(tid, endpoint, method, params, client, self._time())
            task.future = self._executor.submit(work, task.progress)
            self._active[tid] = task
            self._session_to_task[skey] = (tid, self._time())
            return task

    def get_task(self, task_id: str) -> UserTaskInfo | None:
        with self._lock:
            self._expire()
            return self._active.get(task_id) or self._completed.get(task_id)

    def mark_execution_began(self, task_id: str) -> None:
        """markTaskExecutionBegan (:400) — proposal execution started."""
        with self._lock:
            task = self._active.get(task_id) or self._completed.get(task_id)
            if task is not None:
                task.state = TaskState.IN_EXECUTION
                task.execution_began_ms = self._time()

    def mark_execution_finished(self, task_id: str, error: bool = False) -> None:
        with self._lock:
            task = self._active.get(task_id) or self._completed.get(task_id)
            if task is not None:
                task.state = (TaskState.COMPLETED_WITH_ERROR if error
                              else TaskState.COMPLETED)
                task.execution_finished_ms = self._time()

    def all_tasks(self) -> list[UserTaskInfo]:
        with self._lock:
            self._expire()
            tasks = list(self._active.values()) + list(self._completed.values())
        return sorted(tasks, key=lambda t: t.start_ms)

    def close(self) -> None:
        self._executor.shutdown(wait=False, cancel_futures=True)
