"""Topic-granularity goals.

Reference: analyzer/goals/TopicReplicaDistributionGoal.java:598 (each topic's
replicas spread evenly: per-broker count within gap-clamped ceil/floor limits
around the topic average, gapBasedBalanceLimit :119-131) and
MinTopicLeadersPerBrokerGoal.java:452 (configured topics must keep >= N leader
replicas on every eligible broker).

State: the engine maintains ``st.topic_broker_count`` / ``st.topic_leader_count``
[T, B] incrementally, so per-candidate checks are gathers.
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from cruise_control_tpu.analyzer.env import BALANCE_MARGIN, ClusterEnv
from cruise_control_tpu.analyzer.goals.base import NEG_INF, GoalKernel
from cruise_control_tpu.analyzer.state import EngineState


@dataclasses.dataclass(frozen=True)
class TopicReplicaDistributionGoal(GoalKernel):
    def __post_init__(self):
        object.__setattr__(self, "name", "TopicReplicaDistributionGoal")
        # swaps are the count-neutral escape when replica-count bands veto
        # plain moves (TopicReplicaDistributionGoal.java swap rebalancing)
        object.__setattr__(self, "uses_swaps", True)

    def _limits(self, env: ClusterEnv, st: EngineState):
        """(lower[T], upper[T]) per-topic per-broker count limits."""
        n_alive = jnp.maximum(jnp.sum(env.broker_alive), 1).astype(st.util.dtype)
        # compact tables: sum the int16 counts in int32 (a topic CAN hold
        # >32k replicas cluster-wide even though no single (topic, broker)
        # cell does), then cast to the compute dtype
        topic_total = jnp.sum(st.topic_broker_count.astype(jnp.int32),
                              axis=1).astype(st.util.dtype)  # [T]
        avg = topic_total / n_alive
        pct = self.constraint.topic_replica_balance_percentage
        if self.options.triggered_by_goal_violation:
            pct *= self.constraint.goal_violation_distribution_threshold_multiplier
        adj = (pct - 1.0) * BALANCE_MARGIN
        upper = jnp.ceil(avg * (1.0 + adj))
        lower = jnp.floor(avg * jnp.maximum(0.0, 1.0 - adj))
        # gap clamp (gapBasedBalanceLimit)
        min_gap = self.constraint.topic_replica_balance_min_gap
        max_gap = self.constraint.topic_replica_balance_max_gap
        up_min = jnp.ceil(avg) + min_gap
        up_max = jnp.ceil(avg) + max_gap
        upper = jnp.clip(upper, up_min, up_max)
        lo_max = jnp.maximum(0.0, jnp.floor(avg) - min_gap)
        lo_min = jnp.maximum(0.0, jnp.floor(avg) - max_gap)
        lower = jnp.clip(lower, lo_min, lo_max)
        return lower, upper

    def broker_severity(self, env: ClusterEnv, st: EngineState):
        lower, upper = self._limits(env, st)                        # [T]
        c = st.topic_broker_count.astype(st.util.dtype)               # [T, B]
        over = jnp.maximum(c - upper[:, None], 0.0)
        under = jnp.maximum(lower[:, None] - c, 0.0)
        sev = jnp.sum(over + under, axis=0)                         # [B]
        return jnp.where(env.broker_alive, sev,
                         jnp.maximum(sev, st.replica_count.astype(st.util.dtype)))

    def replica_key(self, env: ClusterEnv, st: EngineState, severity):
        lower, upper = self._limits(env, st)
        c = st.topic_broker_count.astype(st.util.dtype)
        t = env.replica_topic
        b = st.replica_broker
        over = c[t, b] > upper[t]
        any_deficit_t = jnp.any(lower[:, None] - c > 0, axis=1)     # [T]
        donor = c[t, b] - 1 >= lower[t]
        load = jnp.sum(st.effective_load(env), axis=1)
        movable = env.replica_valid & (over | (any_deficit_t[t] & donor))
        offline = st.replica_offline & env.replica_valid
        key = jnp.where(movable | offline, -load, NEG_INF)
        return jnp.where(offline, key + 1e12, key)

    def _limits_from_avg(self, avg):
        """Per-topic limits from the topic's per-alive-broker average; same
        math as _limits but over an already-gathered [K] average, so the
        per-candidate path never touches the full [T, B] table."""
        pct = self.constraint.topic_replica_balance_percentage
        if self.options.triggered_by_goal_violation:
            pct *= self.constraint.goal_violation_distribution_threshold_multiplier
        adj = (pct - 1.0) * BALANCE_MARGIN
        upper = jnp.ceil(avg * (1.0 + adj))
        lower = jnp.floor(avg * jnp.maximum(0.0, 1.0 - adj))
        min_gap = self.constraint.topic_replica_balance_min_gap
        max_gap = self.constraint.topic_replica_balance_max_gap
        upper = jnp.clip(upper, jnp.ceil(avg) + min_gap, jnp.ceil(avg) + max_gap)
        lower = jnp.clip(lower, jnp.maximum(0.0, jnp.floor(avg) - max_gap),
                         jnp.maximum(0.0, jnp.floor(avg) - min_gap))
        return lower, upper

    def move_score(self, env: ClusterEnv, st: EngineState, cand):
        t = env.replica_topic[cand]
        src = st.replica_broker[cand]
        rows = st.topic_broker_count[t].astype(st.util.dtype)         # [K, B]
        n_alive = jnp.maximum(jnp.sum(env.broker_alive), 1).astype(st.util.dtype)
        # topic totals are invariant under moves -> row sums are exact
        lower, upper = self._limits_from_avg(jnp.sum(rows, axis=1) / n_alive)
        K = cand.shape[0]
        c_src = rows[jnp.arange(K), src][:, None]                   # [K, 1]
        c_dst = rows                                                # [K, B]
        lo = lower[:, None]
        up = upper[:, None]
        excess_red = jnp.minimum(jnp.maximum(c_src - up, 0.0), 1.0)
        deficit_red = jnp.minimum(jnp.maximum(lo - c_dst, 0.0), 1.0)
        new_excess_dst = jnp.maximum(c_dst + 1.0 - up, 0.0)
        new_deficit_src = jnp.maximum(lo - (c_src - 1.0), 0.0)
        gain = excess_red + deficit_red
        feasible = (new_excess_dst <= 0.0) & (new_deficit_src <= 0.0)
        offline = st.replica_offline[cand]
        heal = 1.0 + jnp.maximum(up - c_dst - 1.0, 0.0) / (up + 1.0)
        return jnp.where(offline[:, None], heal,
                         jnp.where(feasible & (gain > 0), gain, NEG_INF))

    def accept_move(self, env: ClusterEnv, st: EngineState, cand):
        t = env.replica_topic[cand]
        src = st.replica_broker[cand]
        rows = st.topic_broker_count[t].astype(st.util.dtype)         # [K, B]
        n_alive = jnp.maximum(jnp.sum(env.broker_alive), 1).astype(st.util.dtype)
        lower, upper = self._limits_from_avg(jnp.sum(rows, axis=1) / n_alive)
        K = cand.shape[0]
        dst_ok = rows + 1.0 <= upper[:, None]
        src_c = rows[jnp.arange(K), src]
        src_ok = ((src_c - 1.0 >= lower) | (src_c > upper))[:, None]
        return dst_ok & src_ok

    # -- swaps: exchange replicas of two topics so both counts improve while
    # every broker's total replica count is untouched (the count-neutral
    # escape when ReplicaDistributionGoal's band vetoes plain moves) --
    def swap_out_key(self, env: ClusterEnv, st: EngineState, severity):
        t = env.replica_topic
        b = st.replica_broker
        lower, upper = self._limits(env, st)
        over = st.topic_broker_count[t, b].astype(st.util.dtype) > upper[t]
        load = jnp.sum(st.effective_load(env), axis=1)
        ok = env.replica_valid & over & ~st.replica_offline
        return jnp.where(ok, -load, NEG_INF)

    def swap_in_key(self, env: ClusterEnv, st: EngineState, severity):
        t = env.replica_topic
        b = st.replica_broker
        lower, _upper = self._limits(env, st)
        can_leave = (st.topic_broker_count[t, b].astype(st.util.dtype) - 1.0
                     >= lower[t])
        load = jnp.sum(st.effective_load(env), axis=1)
        ok = env.replica_valid & can_leave & ~st.replica_offline
        return jnp.where(ok, -load, NEG_INF)

    def swap_score(self, env: ClusterEnv, st: EngineState, cand_out, cand_in):
        to = env.replica_topic[cand_out]                      # [K1]
        ti = env.replica_topic[cand_in]                       # [K2]
        bo = st.replica_broker[cand_out]
        bi = st.replica_broker[cand_in]
        lower, upper = self._limits(env, st)
        c = st.topic_broker_count.astype(st.util.dtype)

        def viol(cc, lo, up):
            return jnp.maximum(cc - up, 0.0) + jnp.maximum(lo - cc, 0.0)

        # out-replica's topic: (to, bo) loses one, (to, bi) gains one
        lo_o, up_o = lower[to][:, None], upper[to][:, None]
        c_oo = c[to, bo][:, None]                             # [K1, 1]
        c_oi = c[to[:, None], bi[None, :]]                    # [K1, K2]
        g_out = (viol(c_oo, lo_o, up_o) - viol(c_oo - 1.0, lo_o, up_o)
                 + viol(c_oi, lo_o, up_o) - viol(c_oi + 1.0, lo_o, up_o))
        new_viol_out = ((viol(c_oo - 1.0, lo_o, up_o) > viol(c_oo, lo_o, up_o))
                        | (viol(c_oi + 1.0, lo_o, up_o) > viol(c_oi, lo_o, up_o)))
        # in-replica's topic: (ti, bi) loses one, (ti, bo) gains one
        lo_i, up_i = lower[ti][None, :], upper[ti][None, :]
        c_ii = c[ti, bi][None, :]                             # [1, K2]
        c_io = c[ti[None, :], bo[:, None]]                    # [K1, K2]
        g_in = (viol(c_ii, lo_i, up_i) - viol(c_ii - 1.0, lo_i, up_i)
                + viol(c_io, lo_i, up_i) - viol(c_io + 1.0, lo_i, up_i))
        new_viol_in = ((viol(c_ii - 1.0, lo_i, up_i) > viol(c_ii, lo_i, up_i))
                       | (viol(c_io + 1.0, lo_i, up_i) > viol(c_io, lo_i, up_i)))
        same_topic = to[:, None] == ti[None, :]
        gain = g_out + g_in
        feasible = ~new_viol_out & ~new_viol_in & ~same_topic
        # discount vs moves so a tie prefers the cheaper action
        return jnp.where(feasible & (gain > 0), gain * 0.95, NEG_INF)

    def wave_topic_budgets(self, env: ClusterEnv, st: EngineState, topics,
                           src_b, dst_b, d_count, d_leader):
        """Cumulative form of accept_move's per-(topic, broker) band: a wave
        may shed a pair down to the topic's lower limit and fill one up to
        its upper limit (topic totals are move-invariant, so the pre-wave
        limits hold throughout the wave)."""
        n_alive = jnp.maximum(jnp.sum(env.broker_alive), 1).astype(st.util.dtype)
        topic_total = jnp.sum(st.topic_broker_count.astype(jnp.int32),
                              axis=1)                               # [T]
        avg = topic_total[topics].astype(st.util.dtype) / n_alive   # [K]
        lower, upper = self._limits_from_avg(avg)
        c_src = st.topic_broker_count[topics, src_b].astype(st.util.dtype)
        c_dst = st.topic_broker_count[topics, dst_b].astype(st.util.dtype)
        return d_count, c_src - lower, upper - c_dst


@dataclasses.dataclass(frozen=True)
class MinTopicLeadersPerBrokerGoal(GoalKernel):
    """Hard goal: topics flagged in env.topic_min_leaders must keep at least
    ``constraint.min_topic_leaders_per_broker`` leaders on each eligible broker."""

    def __post_init__(self):
        object.__setattr__(self, "name", "MinTopicLeadersPerBrokerGoal")
        object.__setattr__(self, "is_hard", True)
        object.__setattr__(self, "uses_leadership_moves", True)
        object.__setattr__(self, "leadership_primary", True)

    def _min(self) -> int:
        return self.constraint.min_topic_leaders_per_broker

    def _eligible(self, env: ClusterEnv):
        return (env.broker_alive & ~env.broker_excluded_for_leadership
                & ~env.broker_demoted)

    def _deficit(self, env: ClusterEnv, st: EngineState):
        """f32[T, B] missing leaders per (min-leader topic, eligible broker)."""
        c = st.topic_leader_count.astype(st.util.dtype)
        need = jnp.where(env.topic_min_leaders[:, None] & self._eligible(env)[None, :],
                         float(self._min()), 0.0)
        return jnp.maximum(need - c, 0.0)

    def broker_severity(self, env: ClusterEnv, st: EngineState):
        return jnp.sum(self._deficit(env, st), axis=0)

    def violated(self, env: ClusterEnv, st: EngineState):
        return jnp.any(self._deficit(env, st) > 0)

    # replicas: move leader replicas of min-leader topics toward deficient brokers
    def replica_key(self, env: ClusterEnv, st: EngineState, severity):
        t = env.replica_topic
        b = st.replica_broker
        surplus = st.topic_leader_count[t, b].astype(st.util.dtype) > float(self._min())
        is_min_topic = env.topic_min_leaders[t]
        load = jnp.sum(st.effective_load(env), axis=1)
        movable = (env.replica_valid & st.replica_is_leader & is_min_topic
                   & surplus & ~st.replica_offline)
        offline = st.replica_offline & env.replica_valid
        key = jnp.where(movable | offline, -load, NEG_INF)
        return jnp.where(offline, key + 1e12, key)

    def _deficit_rows(self, env: ClusterEnv, st: EngineState, t):
        """f32[K, B] deficit rows for candidate topics (gather-first: never
        materializes a full [T, B] float table in per-candidate paths)."""
        c = st.topic_leader_count[t].astype(st.util.dtype)            # [K, B]
        need = jnp.where(env.topic_min_leaders[t][:, None]
                         & self._eligible(env)[None, :], float(self._min()), 0.0)
        return jnp.maximum(need - c, 0.0)

    def move_score(self, env: ClusterEnv, st: EngineState, cand):
        t = env.replica_topic[cand]
        gain = jnp.minimum(self._deficit_rows(env, st, t), 1.0)     # [K, B]
        offline = st.replica_offline[cand]
        heal = jnp.ones_like(gain)
        return jnp.where(offline[:, None], heal,
                         jnp.where(gain > 0, gain, NEG_INF))

    def accept_move(self, env: ClusterEnv, st: EngineState, cand):
        """Veto moving a leader of a min-leader topic off a broker that would
        drop below the minimum."""
        t = env.replica_topic[cand]
        src = st.replica_broker[cand]
        c_ts = st.topic_leader_count[t, src].astype(st.util.dtype)    # [K]
        guarded = (env.topic_min_leaders[t] & st.replica_is_leader[cand]
                   & self._eligible(env)[src])
        src_ok = (c_ts - 1.0 >= float(self._min())) | ~guarded
        return jnp.broadcast_to(src_ok[:, None], (cand.shape[0], env.num_brokers))

    # leadership: grant leadership to followers on deficient brokers
    def leader_key(self, env: ClusterEnv, st: EngineState, severity):
        t = env.replica_topic
        b = st.replica_broker
        surplus = st.topic_leader_count[t, b].astype(st.util.dtype) > float(self._min())
        ok = (env.replica_valid & st.replica_is_leader & env.topic_min_leaders[t]
              & surplus & ~st.replica_offline)
        return jnp.where(ok, 1.0, NEG_INF)

    def leadership_score(self, env: ClusterEnv, st: EngineState, cand):
        members = env.partition_replicas[env.replica_partition[cand]]
        m = jnp.clip(members, 0)
        dst_broker = st.replica_broker[m]
        t = env.replica_topic[cand]
        rows = self._deficit_rows(env, st, t)                       # [K, B]
        K = cand.shape[0]
        gain = jnp.minimum(rows[jnp.arange(K)[:, None], dst_broker], 1.0)
        return jnp.where(gain > 0, gain, NEG_INF)

    def accept_leadership(self, env: ClusterEnv, st: EngineState, cand):
        t = env.replica_topic[cand]
        src = st.replica_broker[cand]
        c_ts = st.topic_leader_count[t, src].astype(st.util.dtype)    # [K]
        guarded = env.topic_min_leaders[t] & self._eligible(env)[src]
        src_ok = (c_ts - 1.0 >= float(self._min())) | ~guarded
        return jnp.broadcast_to(src_ok[:, None], (cand.shape[0], env.max_rf))

    def wave_topic_budgets(self, env: ClusterEnv, st: EngineState, topics,
                           src_b, dst_b, d_count, d_leader):
        """Cumulative form of the leader-minimum veto: a wave may drain
        leaders of a guarded (topic, src) pair down to the minimum; gaining
        leaders never violates a minimum (dst unconstrained)."""
        c_ts = st.topic_leader_count[topics, src_b].astype(st.util.dtype)
        guarded = env.topic_min_leaders[topics] & self._eligible(env)[src_b]
        src_slack = jnp.where(guarded, c_ts - float(self._min()), jnp.inf)
        dst_slack = jnp.full_like(src_slack, jnp.inf)
        return d_leader, src_slack, dst_slack
