"""Response schema renderers.

Reference: servlet/response/ (23 classes). Every JSON body carries a
``version`` field (servlet/response/JsonResponseField.java convention); the
``/load`` body mirrors ClusterLoad/BrokerStats (response/stats/BrokerStats.java)
with per-broker and per-host rows.
"""
from __future__ import annotations

import numpy as np

JSON_VERSION = 1


def wrap(body: dict) -> dict:
    out = {"version": JSON_VERSION}
    out.update(body)
    return out


def error_json(message: str, stack_trace: str | None = None) -> dict:
    out = wrap({"errorMessage": message})
    if stack_trace:
        out["stackTrace"] = stack_trace
    return out


def broker_stats_json(ct, meta, populate_disk_info: bool = False,
                      capacity_only: bool = False) -> dict:
    """GET /load body (response/stats/BrokerStats.java role).

    Rows: one per broker with leader/follower network split, CPU %, disk MB
    and percentage-of-capacity columns; plus host-level aggregation (broker ==
    host here: the tensor model carries no separate host axis)."""
    from cruise_control_tpu.common.resources import Resource

    cap = np.asarray(ct.broker_capacity, dtype=np.float64)
    alive = np.asarray(ct.broker_alive)
    rows = []
    if capacity_only:
        util = np.zeros_like(cap)
        lead_util = util
        pnw = util
        nrep = np.zeros(cap.shape[0], dtype=np.int64)
        nlead = nrep
    else:
        util = np.asarray(ct.broker_utilization(), dtype=np.float64)
        lead_util = np.asarray(ct.broker_leader_utilization(), dtype=np.float64)
        pnw = np.asarray(ct.potential_leader_load(), dtype=np.float64)
        nrep = np.asarray(ct.broker_replica_count())
        nlead = np.asarray(ct.broker_leader_count())
    disk_cap = np.asarray(ct.broker_disk_capacity, dtype=np.float64)
    disk_util = (np.asarray(ct.broker_disk_utilization(), dtype=np.float64)
                 if populate_disk_info and not capacity_only else None)

    for i, bid in enumerate(meta.broker_ids):
        disk_mb = float(util[i, Resource.DISK])
        disk_cap_mb = float(cap[i, Resource.DISK])
        row = {
            "Broker": int(bid),
            "Host": f"host-{bid}",
            "Rack": meta.rack_ids[int(ct.broker_rack[i])],
            "BrokerState": "ALIVE" if bool(alive[i]) else "DEAD",
            "DiskMB": round(disk_mb, 3),
            "DiskPct": round(100.0 * disk_mb / disk_cap_mb, 3) if disk_cap_mb else 0.0,
            "CpuPct": round(float(util[i, Resource.CPU]), 3),
            "LeaderNwInRate": round(float(lead_util[i, Resource.NW_IN]), 3),
            "FollowerNwInRate": round(
                float(util[i, Resource.NW_IN] - lead_util[i, Resource.NW_IN]), 3),
            "NwOutRate": round(float(util[i, Resource.NW_OUT]), 3),
            "PnwOutRate": round(float(pnw[i, Resource.NW_OUT]), 3),
            "Leaders": int(nlead[i]),
            "Replicas": int(nrep[i]),
            # capacity columns make capacity_only responses meaningful
            "DiskCapacityMB": round(disk_cap_mb, 3),
            "CpuCapacity": round(float(cap[i, Resource.CPU]), 3),
            "NwInCapacity": round(float(cap[i, Resource.NW_IN]), 3),
            "NwOutCapacity": round(float(cap[i, Resource.NW_OUT]), 3),
        }
        if disk_util is not None:
            row["DiskState"] = {
                meta.logdirs[i][d] if d < len(meta.logdirs[i]) else f"disk-{d}": {
                    "DiskMB": round(float(disk_util[i, d]), 3),
                    "DiskPct": round(100.0 * float(disk_util[i, d])
                                     / float(disk_cap[i, d]), 3)
                    if disk_cap[i, d] else 0.0,
                }
                for d in range(disk_cap.shape[1]) if disk_cap[i, d] > 0
            }
        rows.append(row)

    hosts = [dict(r, Host=r["Host"]) for r in rows]  # broker==host aggregation
    return wrap({"brokers": rows, "hosts": hosts})
