"""Per-segment wall profile of the segmented chain at rung 4 (blocking)."""
import os, time
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_cc_tpu")
import jax
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_cc_tpu")
import numpy as np
from cruise_control_tpu.model.random_cluster import RandomClusterSpec, generate_scale
from cruise_control_tpu.model.cluster_tensor import pad_cluster
from cruise_control_tpu.analyzer.optimizer import (
    GoalOptimizer, _compiled_prefix_chain, _compiled_chain_final)
from cruise_control_tpu.analyzer.engine import optimize_goal
from cruise_control_tpu.analyzer.env import make_env, padded_partition_table
from cruise_control_tpu.analyzer.state import init_state

ct, meta = generate_scale(RandomClusterSpec(
    num_brokers=7000, num_racks=40, num_topics=2000,
    num_partitions=500000, max_replication=3, skew=1.0, seed=3142,
    target_cpu_util=0.45))
opt = GoalOptimizer()
ct, meta = pad_cluster(ct, meta)
goals = opt._make_goal_objs(None) if hasattr(opt, '_make_goal_objs') else None
from cruise_control_tpu.analyzer.goals import make_goals
goals = make_goals(opt.default_goal_names, opt.constraint)
params = opt._params
import dataclasses
params = dataclasses.replace(params)  # defaults as bench uses
for rep in range(2):
    env = make_env(ct, meta, partition_table=padded_partition_table(ct))
    st = init_state(env, ct.replica_broker, ct.replica_is_leader,
                    ct.replica_offline, ct.replica_disk)
    split = next((i for i, g in enumerate(goals)
                  if getattr(g, "deep_tail", False)), len(goals))
    t0 = time.monotonic()
    st, out = _compiled_prefix_chain(tuple(type(g) for g in goals),
                                     tuple(goals), split)(env, st, params)
    jax.block_until_ready(st.util)
    print(f"rep{rep} prefix({split} goals): {time.monotonic()-t0:.2f}s", flush=True)
    prev = tuple(goals[:split])
    for g in goals[split:]:
        t0 = time.monotonic()
        st, info = optimize_goal(env, st, g, prev, params)
        jax.block_until_ready(st.util)
        info = jax.device_get(info)
        print(f"rep{rep} {g.name}: {time.monotonic()-t0:.2f}s passes={info['passes']} "
              f"fin={info['finisher_rounds']} proven={info['fixpoint_proven']} "
              f"m={info['moves_remaining']} l={info['leads_remaining']} "
              f"sw={info['swap_window_remaining']}", flush=True)
        prev = prev + (g,)
    t0 = time.monotonic()
    st, fin = _compiled_chain_final(tuple(type(g) for g in goals),
                                    tuple(goals), None)(env, st)
    out = jax.device_get(fin)
    print(f"rep{rep} final: {time.monotonic()-t0:.2f}s", flush=True)
