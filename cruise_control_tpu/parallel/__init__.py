from cruise_control_tpu.parallel.sharding import (
    BROKER_AXIS, make_mesh, shard_cluster,
)

__all__ = ["BROKER_AXIS", "make_mesh", "shard_cluster"]
