"""HA controller tests (cruise_control_tpu/ha): lease-based leader
election on the backend CAS, journal-tailing warm standby, census-adopting
failover, and the leader_kill chaos certification.

Fast units first — double-leader impossibility, epoch fencing, the journal
tail/rotation seams, census mirroring, adopt_census semantics, the tool
surfaces — then one full ha-micro campaign episode: kill the leader
mid-heal and prove the promoted standby converges to the same verdicts and
final assignment as a single-controller run (zero aborted-by-failover
tasks), which is the PR's acceptance gate."""
import importlib.util
import io
import json
import pathlib

import numpy as np
import pytest

from cruise_control_tpu.backend import SimulatedClusterBackend
from cruise_control_tpu.common.tracing import EventJournal, JournalTailer
from cruise_control_tpu.executor import Executor
from cruise_control_tpu.ha import LeaderElector, StandbyController
from cruise_control_tpu.monitor import LoadMonitor
from cruise_control_tpu.monitor.sampling.sample_store import FileSampleStore
from cruise_control_tpu.monitor.sampling.samplers import SimulatedMetricSampler


def _tool(name: str):
    spec = importlib.util.spec_from_file_location(
        name, pathlib.Path(__file__).parent.parent / "tools" / f"{name}.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _backend():
    be = SimulatedClusterBackend()
    for b, rack in ((0, "r0"), (1, "r0"), (2, "r1"), (3, "r1")):
        be.add_broker(b, rack)
    be.create_partition("t", 0, [0, 1], size_mb=100.0, bytes_in_rate=10)
    be.create_partition("t", 1, [1, 2], size_mb=200.0, bytes_in_rate=10)
    be.create_partition("t", 2, [2, 0], size_mb=50.0, bytes_in_rate=10)
    return be


# ------------------------------------------------------------ lease election

def test_double_leader_impossible_under_cas_race():
    """Two contenders racing the same key: the backend CAS serializes them,
    so at every instant at most one elector holds the leader role."""
    be = _backend()
    a = LeaderElector(be, "cc-a", ttl_ms=30_000, renew_ms=10_000)
    b = LeaderElector(be, "cc-b", ttl_ms=30_000, renew_ms=10_000)
    assert a.tick() == "leader"
    assert b.tick() == "standby"
    for _ in range(20):
        be.advance(5_000.0)
        roles = {a.tick(), b.tick()}
        assert [a.role, b.role].count("leader") == 1
        assert "leader" in roles       # someone always holds the lease
    assert a.role == "leader" and b.role == "standby"


def test_lease_expiry_promotes_standby_and_fences_old_leader():
    be = _backend()
    a = LeaderElector(be, "cc-a", ttl_ms=30_000, renew_ms=10_000)
    b = LeaderElector(be, "cc-b", ttl_ms=30_000, renew_ms=10_000)
    assert a.tick() == "leader"
    assert a.epoch == 1
    # a stops renewing (process death); b's acquire only grants after a
    # full TTL on the BACKEND clock
    be.advance(29_000.0)
    assert b.tick() == "standby"
    be.advance(2_000.0)
    assert b.tick() == "leader"
    assert b.epoch == 2                      # ownership change bumps epoch
    assert b.elected_ms == be.now_ms()
    # the zombie leader's next renew is refused: it steps down, never
    # split-brains
    assert a.tick() == "standby"
    assert a.lost_ms == be.now_ms()
    assert be.lease_get(a.key)["holder"] == "cc-b"


def test_leader_renewal_keeps_epoch_stable():
    """Renewals (and re-acquiring your own expired lease after a long
    blocking heal) never hand the lease away; only ownership CHANGES bump
    the fencing epoch."""
    be = _backend()
    a = LeaderElector(be, "cc-a", ttl_ms=30_000, renew_ms=10_000)
    assert a.tick() == "leader"
    for _ in range(5):
        be.advance(10_000.0)
        assert a.tick() == "leader"
    assert be.lease_get(a.key)["epoch"] == 1
    # lapse without a contender: the owner re-acquires and stays leader,
    # and the fencing token does NOT move (no ownership change) — nor does
    # the elector report a stale one
    be.advance(120_000.0)
    assert a.tick() == "leader"
    assert a.role == "leader"
    assert be.lease_get(a.key)["epoch"] == 1
    assert a.epoch == 1


def test_resign_releases_lease_immediately():
    be = _backend()
    a = LeaderElector(be, "cc-a", ttl_ms=30_000, renew_ms=10_000)
    b = LeaderElector(be, "cc-b", ttl_ms=30_000, renew_ms=10_000)
    assert a.tick() == "leader"
    a.resign()
    # no TTL wait: the standby's very next tick wins the freed lease
    assert b.tick() == "leader"
    assert be.lease_get(b.key)["holder"] == "cc-b"


# ----------------------------------------------------------- journal tailing

def test_event_journal_tail_from_arbitrary_offsets():
    clock = [0.0]
    j = EventJournal(clock_ms=lambda: clock[0], memory_lines=64)
    for i in range(10):
        j.append("task", i=i)
    cur, lines, dropped = j.tail(0)
    assert (cur, len(lines), dropped) == (10, 10, 0)
    # arbitrary mid-stream cursor: exactly the suffix, no drops
    cur, lines, dropped = j.tail(7)
    assert dropped == 0
    assert [json.loads(ln)["i"] for ln in lines] == [7, 8, 9]
    # caught up: empty
    assert j.tail(cur) == (10, [], 0)


def test_event_journal_tail_reports_ring_evictions():
    j = EventJournal(memory_lines=16)        # floor of the bounded ring
    for i in range(40):
        j.append("task", i=i)
    cur, lines, dropped = j.tail(0)
    assert cur == 40
    assert dropped == 24                     # evicted before the tail began
    assert [json.loads(ln)["i"] for ln in lines] == list(range(24, 40))


def test_journal_tailer_survives_rotations_without_drop_or_dup(tmp_path):
    """Satellite (f): the file follower across ``journal.max.bytes.per.file``
    rotation seams — every appended line is delivered exactly once even when
    several rotations land between polls."""
    clock = [0.0]
    path = str(tmp_path / "journal.jsonl")
    j = EventJournal(path=path, max_bytes=4096, max_files=8, fsync="always",
                     clock_ms=lambda: clock[0])
    tailer = JournalTailer(path)
    assert tailer.poll() == []      # attach at offset 0, before any appends
    seen = []
    for i in range(400):
        clock[0] += 1.0
        j.append("task", i=i, pad="x" * 80)   # ~37 lines per 4 KiB file
        if i % 100 == 99:                     # ≥2 rotations between polls
            seen.extend(tailer.poll())
    j.close()
    seen.extend(tailer.poll())
    tailer.close()
    assert j.rotations >= 5
    assert [json.loads(ln)["i"] for ln in seen] == list(range(400))


def test_journal_view_follow_prints_tailed_events(tmp_path):
    jv = _tool("journal_view")
    path = str(tmp_path / "journal.jsonl")
    j = EventJournal(path=path, clock_ms=lambda: 1000.0)
    j.append("task", i=0, st="PENDING")
    j.append("ha", ev="promoted", holder="cc-b")
    j.close()
    buf = io.StringIO()
    assert jv.follow(path, max_events=2, out=buf) == 0
    lines = buf.getvalue().strip().splitlines()
    assert len(lines) == 2
    assert "task" in lines[0] and "st=PENDING" in lines[0]
    assert "ha" in lines[1] and "ev=promoted" in lines[1]
    # drained before max_events: returns instead of blocking
    buf2 = io.StringIO()
    assert jv.follow(path, max_events=10, out=buf2) == 0
    assert len(buf2.getvalue().strip().splitlines()) == 2


# ------------------------------------------------- standby mirror + adoption

class _StubSensors:
    def gauge(self, name, fn):
        return None


class _StubExecutor:
    def __init__(self):
        self.records = None
        self.stopped = None

    def adopt_census(self, records, context=None):
        self.records = records
        return {"adopted": len(records), "inFlight": sum(
            1 for r in records if r["st"] == "IN_PROGRESS")}

    def stop_execution(self, force=False):
        self.stopped = {"force": force}


class _StubCC:
    """The minimal facade surface StandbyController touches."""

    def __init__(self, backend):
        self.backend = backend
        self.sensors = _StubSensors()
        self.resident_session = None
        self.load_monitor = None
        self.journal = EventJournal(clock_ms=backend.now_ms)
        self.executor = _StubExecutor()
        self.ha = None


def _task_row(j, span, i, st, payload=True, **extra):
    fields = dict(i=i, tp=["t", i], ty="INTER_BROKER_REPLICA_ACTION",
                  st=st, span=span, trace="tr", **extra)
    if payload:
        fields.update(ol=0, nl=1, orp=[[0, 0], [1, 0]], nrp=[[1, 0], [2, 0]])
    j.append("task", **fields)


def test_standby_census_adopts_only_the_incomplete_execution():
    """Span-end events mark executions that finished cleanly; a killed
    leader never journals one, which is how promote() finds the execution
    to adopt — with the rows' LAST journaled states merged in."""
    be = _backend()
    leader_j = EventJournal(clock_ms=be.now_ms)
    cc = _StubCC(be)
    sb = StandbyController(cc, leader_journal=leader_j,
                           elector=None, sync_interval_ms=1e18)
    # execution e1 completed cleanly (span end journaled)
    _task_row(leader_j, "e1", 0, "PENDING")
    _task_row(leader_j, "e1", 0, "COMPLETED", payload=False)
    leader_j.append("span", span="e1", span_kind="execution", name="op")
    # execution e2: the leader died inside it — no span end
    _task_row(leader_j, "e2", 0, "PENDING")
    _task_row(leader_j, "e2", 0, "COMPLETED", payload=False)
    _task_row(leader_j, "e2", 1, "PENDING")
    _task_row(leader_j, "e2", 1, "IN_PROGRESS", payload=False)
    _task_row(leader_j, "e2", 2, "PENDING")
    out = sb.tick()
    assert out == {"promoted": False, "events": 8, "samples": 0}
    assert sb.journal_lag_events() == 0
    res = sb.promote()
    assert res["promoted"] is True
    assert res["adoption"] == {"adopted": 3, "inFlight": 1}
    by_i = {r["i"]: r["st"] for r in cc.executor.records}
    # merged census: payload row + latest state, one record per plan index
    assert by_i == {0: "COMPLETED", 1: "IN_PROGRESS", 2: "PENDING"}
    assert sb.role == "leader"


def test_standby_tail_from_mid_stream_counts_drops_and_skips_adoption():
    """A standby attached after the ring evicted the payload rows reports
    the loss and refuses to adopt partial censuses (payload-less rows are
    not adoptable)."""
    be = _backend()
    leader_j = EventJournal(clock_ms=be.now_ms, memory_lines=16)
    for i in range(30):                       # evicts the early rows
        _task_row(leader_j, "e1", i, "PENDING", payload=(i < 10))
    cc = _StubCC(be)
    sb = StandbyController(cc, leader_journal=leader_j,
                           elector=None, sync_interval_ms=1e18)
    sb.tick()
    assert sb.dropped_events == 14            # 30 appended - 16 ring slots
    res = sb.promote()
    # the surviving rows are all payload-less -> nothing adoptable
    assert res["adoption"] is None
    assert cc.executor.records is None


def test_standby_promotes_via_elector_when_lease_lapses():
    be = _backend()
    leader_j = EventJournal(clock_ms=be.now_ms)
    leader = LeaderElector(be, "cc-a", ttl_ms=30_000, renew_ms=10_000)
    assert leader.tick() == "leader"
    cc = _StubCC(be)
    elector = LeaderElector(be, "cc-b", ttl_ms=30_000, renew_ms=10_000)
    sb = StandbyController(cc, leader_journal=leader_j, elector=elector,
                           sync_interval_ms=1e18)
    # leader alive and renewing: the standby stays warm, never promotes
    for _ in range(3):
        be.advance(10_000.0)
        leader.tick()
        assert sb.tick()["promoted"] is False
    # leader dies; the standby's tick wins the lease once the TTL lapses
    be.advance(31_000.0)
    out = sb.tick()
    assert out["promoted"] is True
    assert sb.promoted_ms == be.now_ms()
    assert elector.role == "leader" and elector.epoch == 2
    # the takeover is journaled on the STANDBY's own journal
    ha_events = [json.loads(ln) for ln in cc.journal.lines()]
    assert any(e["kind"] == "ha" and e["ev"] == "promoted"
               for e in ha_events)


def test_promoted_standby_keeps_renewing_and_steps_down_when_fenced():
    """The leader role is only held while the lease keeps being renewed:
    post-promotion ticks renew it (a restarted old leader can never win
    against a live survivor), and a survivor that froze past the TTL steps
    down on its first refused renewal instead of split-braining."""
    be = _backend()
    leader_j = EventJournal(clock_ms=be.now_ms)
    cc = _StubCC(be)
    elector = LeaderElector(be, "cc-b", ttl_ms=30_000, renew_ms=10_000)
    sb = StandbyController(cc, leader_journal=leader_j, elector=elector,
                           sync_interval_ms=1e18)
    assert sb.tick()["promoted"] is True        # free lease: first tick wins
    # the dead leader restarts as a fresh contender; while the promoted
    # node keeps ticking, its renewals hold the lease across many TTLs
    old = LeaderElector(be, "cc-a", ttl_ms=30_000, renew_ms=10_000)
    for _ in range(8):
        be.advance(10_000.0)
        assert sb.tick() == {"promoted": False, "events": 0, "samples": 0}
        assert old.tick() == "standby"
    assert sb.role == "leader"
    assert be.lease_get(elector.key)["holder"] == "cc-b"
    # the survivor freezes (no ticks) past a full TTL: the contender takes
    # over, and the zombie's next tick learns it was fenced and steps down
    be.advance(31_000.0)
    assert old.tick() == "leader"
    out = sb.tick()
    assert out == {"promoted": False, "demoted": True}
    assert sb.role == "standby" and elector.role == "standby"
    assert sb.promoted_ms is None
    # fencing stops the executor gracefully — in-flight backend moves are
    # the NEW leader's to adopt, not cancelled out from under it
    assert cc.executor.stopped == {"force": False}
    ha_events = [json.loads(ln) for ln in cc.journal.lines()]
    demoted = [e for e in ha_events
               if e["kind"] == "ha" and e["ev"] == "demoted"]
    assert demoted and demoted[-1]["to"] == "cc-a"
    # fenced standby resumes contending: once the new leader lapses, it
    # can promote again through the normal path
    be.advance(62_000.0)
    assert sb.tick()["promoted"] is True
    assert sb.role == "leader"


def test_adopt_census_resumes_exactly_pending_and_in_progress():
    """Satellite (c): terminal rows are skipped, PENDING rows re-enter a
    fresh planner, IN_PROGRESS inter-broker moves resume mid-batch off the
    backend's still-live reassignment — nothing is aborted."""
    be = _backend()
    # the dead leader's in-flight move: the backend still holds it
    be.alter_partition_reassignments({("t", 1): [3, 2]})
    records = [
        {"i": 0, "tp": ["t", 0], "ty": "INTER_BROKER_REPLICA_ACTION",
         "st": "COMPLETED", "ol": 0, "nl": 0,
         "orp": [[0, 0], [1, 0]], "nrp": [[0, 0], [1, 0]]},
        {"i": 1, "tp": ["t", 1], "ty": "INTER_BROKER_REPLICA_ACTION",
         "st": "IN_PROGRESS", "ol": 1, "nl": 3,
         "orp": [[1, 0], [2, 0]], "nrp": [[3, 0], [2, 0]]},
        {"i": 2, "tp": ["t", 2], "ty": "INTER_BROKER_REPLICA_ACTION",
         "st": "PENDING", "ol": 2, "nl": 1,
         "orp": [[2, 0], [0, 0]], "nrp": [[1, 0], [0, 0]]},
    ]
    ex = Executor(be)
    out = ex.adopt_census(records,
                          context={"operation": "failover census adoption"})
    assert out == {"adopted": 2, "inFlight": 1}
    parts = be.partitions()
    assert sorted(parts[("t", 1)].replicas) == [2, 3]   # adopted in-flight
    assert parts[("t", 1)].leader == 3
    assert sorted(parts[("t", 2)].replicas) == [0, 1]   # adopted pending
    assert sorted(parts[("t", 0)].replicas) == [0, 1]   # terminal: untouched
    st = ex.state_json()
    by_state = st.get("numTasksByState", {})
    assert by_state.get("COMPLETED") == 2
    for bad in ("ABORTED", "ABORTING", "DEAD"):
        assert not by_state.get(bad)


def test_adopt_census_refuses_concurrent_execution():
    be = _backend()
    ex = Executor(be)
    rec = [{"i": 0, "tp": ["t", 0], "ty": "LEADER_ACTION", "st": "PENDING",
            "ol": 0, "nl": 1, "orp": [[0, 0], [1, 0]],
            "nrp": [[0, 0], [1, 0]]}]
    from cruise_control_tpu.executor.executor import ExecutorState
    ex._state = ExecutorState.STARTING_EXECUTION
    with pytest.raises(RuntimeError):
        ex.adopt_census(rec)


def test_adopt_census_resubmits_in_progress_logdir_move_idempotently():
    """An IN_PROGRESS intra-broker row is only journaled AFTER the dead
    leader's alter_replica_logdirs returned, so the move already landed
    backend-side. Adoption re-arms it as PENDING and re-submits — the call
    is declarative (assigns the replica to a target log dir), so the
    re-submission re-asserts the same assignment: no error, no abort."""
    be = SimulatedClusterBackend()
    dirs = {"/d0": 500_000.0, "/d1": 500_000.0}
    for b, rack in ((0, "r0"), (1, "r1")):
        be.add_broker(b, rack, logdirs=dict(dirs))
    be.create_partition("t", 0, [0, 1], size_mb=100.0, bytes_in_rate=10)
    # the dead leader's submission already took effect
    be.alter_replica_logdirs({("t", 0, 0): "/d1"})
    records = [
        {"i": 0, "tp": ["t", 0], "ty": "INTRA_BROKER_REPLICA_ACTION",
         "st": "IN_PROGRESS", "ol": 0, "nl": 0,
         "orp": [[0, 0], [1, 0]], "nrp": [[0, 1], [1, 0]]},
    ]
    ex = Executor(be)
    out = ex.adopt_census(records,
                          context={"operation": "failover census adoption"})
    assert out == {"adopted": 1, "inFlight": 0}
    assert be.partitions()[("t", 0)].logdir_by_broker[0] == "/d1"
    by_state = ex.state_json().get("numTasksByState", {})
    assert by_state.get("COMPLETED") == 1
    for bad in ("ABORTED", "ABORTING", "DEAD"):
        assert not by_state.get(bad)


# --------------------------------------------------- sample-tail bit-identity

def test_standby_monitor_is_bit_identical_to_fresh_store_replay(tmp_path):
    """The standby's aggregators after tailing the leader's FileSampleStore
    at arbitrary chunk boundaries are bit-identical to a fresh monitor
    replaying the same files in one shot — same windows, same model."""
    from cruise_control_tpu.ha.standby import SampleTailer

    be = _backend()
    store = FileSampleStore(str(tmp_path))
    store.configure(None)
    leader = LoadMonitor(backend=be, sampler=SimulatedMetricSampler(be),
                         sample_store=store)
    leader.start_up()
    standby = LoadMonitor(backend=be, sampler=SimulatedMetricSampler(be))
    standby.start_up()
    tailer = SampleTailer(str(tmp_path))
    for i in range(20):
        leader.sample_once(now_ms=i * 60_000.0)
        if i % 3 == 2:                        # arbitrary tail offsets
            batch = tailer.poll()
            if batch is not None:
                standby._ingest(batch)
    batch = tailer.poll()                     # final catch-up
    if batch is not None:
        standby._ingest(batch)
    leader.shutdown()
    # the oracle: a fresh monitor replaying the same store prefix at once
    store2 = FileSampleStore(str(tmp_path))
    store2.configure(None)
    fresh = LoadMonitor(backend=be, sampler=SimulatedMetricSampler(be),
                        sample_store=store2)
    fresh.start_up()
    assert standby.num_valid_windows == fresh.num_valid_windows
    ct_s, _ = standby.cluster_model()
    ct_f, _ = fresh.cluster_model()
    np.testing.assert_array_equal(np.asarray(ct_s.broker_utilization()),
                                  np.asarray(ct_f.broker_utilization()))
    np.testing.assert_array_equal(np.asarray(ct_s.leader_load),
                                  np.asarray(ct_f.leader_load))
    standby.shutdown()
    fresh.shutdown()


# -------------------------------------------------------------- tool gating

def _ha_doc(promote_p95=5000.0, first_p95=40_000.0, parity=True, aborted=0):
    return {"episodes": 1,
            "detect_lease_loss_ms": {"n": 1, "p50": promote_p95,
                                     "p95": promote_p95, "max": promote_p95},
            "promote_ms": {"n": 1, "p50": promote_p95, "p95": promote_p95,
                           "max": promote_p95},
            "first_proposal_ms": {"n": 1, "p50": first_p95, "p95": first_p95,
                                  "max": first_p95},
            "parity_ok": parity, "aborted_by_failover": aborted}


def test_slo_diff_extract_and_compare_ha():
    sd = _tool("slo_diff")
    base = _ha_doc()
    assert sd.extract_ha({"ha": base}) == base
    assert sd.extract_ha({"failover": base}) == base
    assert sd.extract_ha({"campaign": {"failover": base}}) == base
    assert sd.extract_ha({}) == {}
    # within threshold: no regression
    rows, regs = sd.compare_ha(base, _ha_doc(promote_p95=6000.0))
    assert regs == []
    assert len(rows) == 3
    # p95 blowout, parity loss, and failover aborts all gate
    _, regs = sd.compare_ha(base, _ha_doc(promote_p95=12_000.0))
    assert any(r["field"] in ("promote_ms", "detect_lease_loss_ms")
               for r in regs)
    _, regs = sd.compare_ha(base, _ha_doc(parity=False))
    assert any(r["field"] == "parity_ok" for r in regs)
    _, regs = sd.compare_ha(base, _ha_doc(aborted=3))
    assert any(r["field"] == "aborted_by_failover" for r in regs)
    # coverage lost: the candidate stopped measuring a failover SLO
    cand = _ha_doc()
    del cand["first_proposal_ms"]
    _, regs = sd.compare_ha(base, cand)
    assert any(r["field"] == "first_proposal_ms" for r in regs)


# ------------------------------------------- leader_kill chaos certification

@pytest.fixture(scope="module")
def ha_campaign():
    """One ha-micro campaign: broker death, leader killed mid-heal, standby
    promotes, plus the single-controller oracle run for the parity gate."""
    from cruise_control_tpu.sim import run_campaign
    return run_campaign("ha-micro", seed=0)


def test_leader_kill_episode_converges_with_zero_aborts(ha_campaign):
    assert len(ha_campaign.episodes) == 1
    r = ha_campaign.episodes[0]
    r.assert_ok()
    assert r.converged
    fo = r.failover
    assert fo["promoted"] is True
    assert fo["aborted_tasks"] == 0           # adopt, never abort
    assert fo["adopted_tasks"] > 0
    assert fo["parity_ok"] is True            # same verdicts + assignment
    # the failover SLO chain is ordered and bounded by the lease TTL window
    assert 0.0 < fo["detect_lease_loss_ms"] <= fo["promote_ms"]
    assert fo["promote_ms"] < fo["first_proposal_ms"]
    assert fo["journal_lag_events"] == 0      # caught up at promotion
    assert fo["dropped_events"] == 0


def test_leader_kill_episode_timeline_records_takeover(ha_campaign):
    r = ha_campaign.episodes[0]
    kinds = [e["kind"] for e in r.timeline]
    assert "ha_promoted" in kinds
    # the promoted controller re-ran detection to its own FIX verdict
    t_prom = next(e["t"] for e in r.timeline if e["kind"] == "ha_promoted")
    assert any(e["kind"] == "anomaly" and e["action"] == "FIX"
               and e["t"] >= t_prom for e in r.timeline)


def test_campaign_json_carries_failover_distributions(ha_campaign):
    doc = ha_campaign.to_json()
    fo = doc["failover"]
    assert fo["episodes"] == 1
    for field in ("detect_lease_loss_ms", "promote_ms", "first_proposal_ms"):
        d = fo[field]
        assert d["n"] == 1 and d["p95"] is not None and d["p95"] > 0
    assert fo["aborted_by_failover"] == 0
    assert fo["parity_ok"] is True
    # the slo_diff gate consumes exactly this block
    sd = _tool("slo_diff")
    assert sd.extract_ha(doc) == fo
    _, regs = sd.compare_ha(fo, fo)
    assert regs == []
