"""Kahan-residual survival across the resident-session lifecycle (PR 7).

The compensated-accounting residuals (``EngineState.util_residual`` /
``leader_util_residual``) are DERIVED accounting state: every path that
rebuilds the engine state from the observed assignment — delta-ingest
rounds, the donation protocol's ``_sync_finalize`` rematerialization, and
epoch fallback — must come back with a correctly REBUILT residual (zeros:
the finalize runs ``refresh``, the from-scratch truth, so the compensation
restarts), never a stale one compensating an accumulator that no longer
exists, and never a missing leaf.
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
from cruise_control_tpu.analyzer.session import ResidentClusterSession
from cruise_control_tpu.config import cruise_control_config

GOALS = ["ReplicaCapacityGoal", "ReplicaDistributionGoal"]


def _session_fixture(seed=0):
    from cruise_control_tpu.backend.simulated import SimulatedClusterBackend
    from cruise_control_tpu.monitor.load_monitor import LoadMonitor
    from cruise_control_tpu.monitor.sampling.samplers import (
        SimulatedMetricSampler,
    )

    rng = np.random.default_rng(seed)
    be = SimulatedClusterBackend()
    for b in range(10):
        be.add_broker(b, f"r{b % 3}")
    for p in range(60):
        reps = [int(x) for x in rng.choice(10, size=2, replace=False)]
        be.create_partition(f"t{p % 6}", p, reps,
                            size_mb=float(rng.uniform(10, 500)),
                            bytes_in_rate=float(rng.uniform(1, 50)),
                            bytes_out_rate=float(rng.uniform(1, 100)),
                            cpu_util=float(rng.uniform(0.1, 5)))
    lm = LoadMonitor(backend=be, sampler=SimulatedMetricSampler(be))
    lm.start_up()
    for i in range(6):
        lm.sample_once(now_ms=i * 300_000.0)
    return be, lm


def _assert_residuals_rebuilt(st, label):
    assert st.util_residual.dtype == jnp.float32, label
    assert st.util_residual.shape == st.util.shape, label
    assert st.leader_util_residual.shape == st.util.shape, label
    assert float(jnp.abs(st.util_residual).max()) == 0.0, label
    assert float(jnp.abs(st.leader_util_residual).max()) == 0.0, label


def test_residuals_across_delta_and_donation_rounds():
    _, lm = _session_fixture(seed=11)
    sess = ResidentClusterSession(lm)
    assert sess.sync()["mode"] == "rebuild"
    _assert_residuals_rebuilt(sess.state, "epoch start")
    opt = GoalOptimizer()
    for rnd in range(2):
        res = opt.optimizations(None, session=sess, goal_names=GOALS,
                                raise_on_failure=False,
                                skip_hard_goal_check=True)
        # the round's result CARRIES the residual leaves (the engine
        # maintained them through its applied waves) ...
        assert res.final_state.util_residual.shape == sess.env.broker_capacity.shape
        assert bool(jnp.all(jnp.isfinite(res.final_state.util_residual)))
        # ... and under donation the resident slot was lent out
        assert sess.state is None
        lm.sample_once(now_ms=(6 + rnd) * 300_000.0)
        assert sess.sync()["mode"] == "delta"
        # delta ingest rematerializes from the host mirrors via
        # _sync_finalize -> refresh: residuals correctly rebuilt (zeros)
        _assert_residuals_rebuilt(sess.state, f"delta round {rnd}")


def test_residuals_across_back_to_back_rematerialization():
    """Two optimizer rounds with no sync between: the second round's input
    state is rematerialized from mirrors and must carry rebuilt residuals
    (optimizer_inputs -> _ensure_state path)."""
    _, lm = _session_fixture(seed=12)
    sess = ResidentClusterSession(lm)
    sess.sync()
    opt = GoalOptimizer()
    opt.optimizations(None, session=sess, goal_names=GOALS,
                      raise_on_failure=False, skip_hard_goal_check=True)
    assert sess.state is None
    # optimizer_inputs rematerializes before lending again
    env, st, *_rest = sess.optimizer_inputs()
    _assert_residuals_rebuilt(st, "back-to-back rematerialize")


def test_residuals_across_epoch_fallback():
    """invalidate() forces the next sync onto the rebuild (new epoch) path;
    the fresh epoch's state must carry rebuilt residuals, and a
    donation-off session's defensive copies must too."""
    _, lm = _session_fixture(seed=13)
    sess = ResidentClusterSession(lm, config=cruise_control_config(
        {"analyzer.session.donation": False}))
    sess.sync()
    opt = GoalOptimizer()
    opt.optimizations(None, session=sess, goal_names=GOALS,
                      raise_on_failure=False, skip_hard_goal_check=True)
    # donation off: the resident state survives the round untouched
    assert sess.state is not None
    _assert_residuals_rebuilt(sess.state, "donation-off resident")
    sess.invalidate()
    lm.sample_once(now_ms=7 * 300_000.0)
    info = sess.sync()
    assert info["mode"] == "rebuild"
    _assert_residuals_rebuilt(sess.state, "epoch fallback")
    # the rebuilt epoch still serves optimizer rounds
    res = opt.optimizations(None, session=sess, goal_names=GOALS,
                            raise_on_failure=False,
                            skip_hard_goal_check=True)
    assert res.final_state.util_residual is not None


def test_refresh_rebuilds_residuals_after_engine_waves():
    """After real engine waves mutate the accounting, refresh() (the
    bit-exactness oracle the session's finalize runs) zeroes the residuals
    while reproducing the tallies — stale compensation can never leak into
    a rebuilt state."""
    from cruise_control_tpu.analyzer.env import (
        make_env, padded_partition_table,
    )
    from cruise_control_tpu.analyzer.state import init_state, refresh
    from cruise_control_tpu.analyzer.engine import EngineParams, optimize_goal
    from cruise_control_tpu.analyzer.goals import make_goals
    from cruise_control_tpu.model.cluster_tensor import pad_cluster
    from cruise_control_tpu.model.random_cluster import (
        RandomClusterSpec, generate,
    )

    ct, meta = generate(RandomClusterSpec(
        num_brokers=16, num_racks=4, num_topics=8, num_partitions=200,
        max_replication=2, skew=2.0, seed=7))
    ct, meta = pad_cluster(ct, meta)
    env = make_env(ct, meta, partition_table=padded_partition_table(ct))
    st = init_state(env, ct.replica_broker, ct.replica_is_leader,
                    ct.replica_offline, ct.replica_disk)
    (goal,) = make_goals(["DiskUsageDistributionGoal"])
    st, info = optimize_goal(env, st, goal, (), EngineParams())
    assert int(info["iterations"]) > 0
    r = refresh(env, st)
    _assert_residuals_rebuilt(r, "refresh")
    np.testing.assert_array_equal(np.asarray(st.replica_count),
                                  np.asarray(r.replica_count))
