import sys, os
sys.path.insert(0, "/root/repo")
os.environ.setdefault('JAX_COMPILATION_CACHE_DIR', '/tmp/jax_cache_cc_tpu')
import jax, jax.numpy as jnp
jax.config.update('jax_compilation_cache_dir', '/tmp/jax_cache_cc_tpu')
import time
from cruise_control_tpu.model.random_cluster import RandomClusterSpec, generate_scale
from cruise_control_tpu.model.cluster_tensor import pad_cluster
from cruise_control_tpu.analyzer.env import make_env, padded_partition_table, BalancingConstraint, OptimizationOptions, resource_balance_limits
from cruise_control_tpu.analyzer.state import init_state
from cruise_control_tpu.analyzer.goals import make_goals
from cruise_control_tpu.analyzer.goals.base import broker_lookup, NEG_INF
from cruise_control_tpu.analyzer.goals.capacity import RESOURCE_EPS

shape = sys.argv[1] if len(sys.argv) > 1 else "r3"
spec = (RandomClusterSpec(num_brokers=1000, num_racks=20, num_topics=400,
                          num_partitions=50000, max_replication=3, skew=1.0,
                          seed=3141, target_cpu_util=0.45) if shape == "r3" else
        RandomClusterSpec(num_brokers=7000, num_racks=40, num_topics=2000,
                          num_partitions=500000, max_replication=3, skew=1.0,
                          seed=3142, target_cpu_util=0.45))
ct, meta = generate_scale(spec)
ct, meta = pad_cluster(ct, meta)
env = make_env(ct, meta, partition_table=padded_partition_table(ct))
st = init_state(env, ct.replica_broker, ct.replica_is_leader,
                ct.replica_offline, ct.replica_disk)
goal = make_goals(["DiskUsageDistributionGoal"], BalancingConstraint(), OptimizationOptions())[0]
res = goal.resource
print("R", ct.num_replicas, "B", ct.num_brokers, flush=True)

def f_limits(env, st):
    return goal._limits(env, st)

def f_lookup(env, st):
    lower, upper = goal._limits(env, st)
    util = st.util[:, res]
    return broker_lookup(st.replica_broker, util - upper, util, lower, upper)

def f_eff(env, st):
    return st.effective_load(env)[:, res]

def f_headroom(env, st):
    lower, upper = goal._limits(env, st)
    util = st.util[:, res]
    headroom = jnp.where(env.dst_candidate, upper - util, NEG_INF)
    return jnp.max(headroom)

def f_key(env, st):
    return goal.replica_key(env, st, goal.broker_severity(env, st))

for name, fn in (("limits", f_limits), ("lookup", f_lookup), ("eff_load", f_eff),
                 ("headroom", f_headroom), ("key_full", f_key)):
    f = jax.jit(fn)
    r = f(env, st); jax.block_until_ready(jax.tree_util.tree_leaves(r)[0])
    t0 = time.monotonic()
    for _ in range(30):
        r = f(env, st)
    jax.block_until_ready(jax.tree_util.tree_leaves(r)[0])
    print(f"{name}: {(time.monotonic()-t0)/30*1e3:.2f}ms", flush=True)
