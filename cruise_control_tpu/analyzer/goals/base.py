"""Goal kernel SPI.

Reference: the Goal interface (analyzer/goals/Goal.java:39-163 — optimize,
actionAcceptance, stats comparator, isHardGoal) and the AbstractGoal template
(AbstractGoal.java:45 — init -> per-broker rebalance loop -> monotonicity
assertion; maybeApplyBalancingAction :224-266 = legitMove -> selfSatisfied ->
acceptance-by-optimized-goals -> mutate).

Here a goal is a frozen (hashable, jit-static) dataclass exposing pure
functions over (ClusterEnv, EngineState):

- ``broker_severity``  f32[B]: >0 where the goal needs work on that broker
  (drives candidate-source selection; replaces brokersToBalance + the
  per-broker while loop).
- ``replica_key``      f32[R]: ranking of replicas worth moving for this goal
  (-inf = not a candidate). Replaces the reference's sorted-replica scan
  (SortedReplicas.java) with a top-k.
- ``move_score``       f32[K, B]: improvement score for moving candidate k to
  broker b; -inf where the move is not self-satisfied. Positive = progress.
  This is the vectorized selfSatisfied + improvement ordering.
- ``accept_move`` / ``accept_leadership``  bool[K, B] / bool[K, F]: the goal's
  veto when it has ALREADY been optimized (ActionAcceptance ACCEPT vs
  REPLICA_REJECT/BROKER_REJECT collapse to a boolean mask here).
- leadership candidates via ``leader_key`` f32[R] and ``leadership_score``
  f32[K, F] for goals that move leadership.
- ``violated`` -> bool scalar: any broker violating (for OptimizerResult and
  the goal-violation detector).

The common legit-move mask (dst hosts no copy, topic not excluded, dst alive /
allowed destination, offline-only filtering) is shared in
:func:`legit_move_mask` — the analogue of AbstractGoal's legitMove +
GoalUtils.filterReplicas.
"""
from __future__ import annotations

import contextlib
import dataclasses

import jax
import jax.numpy as jnp

from cruise_control_tpu.analyzer.env import BalancingConstraint, ClusterEnv, OptimizationOptions
from cruise_control_tpu.analyzer.state import EngineState

Array = jax.Array
NEG_INF = -jnp.inf

# Budgeted-wave delta dimensions (engine-wide convention, see
# engine._move_branch_batched / _leadership_branch_batched): what one applied
# action adds to its destination broker / removes from its source broker.
#   0..3  utilization delta (CPU, NW_IN, NW_OUT, DISK) — a replica move
#         carries the replica's current-role load; a leadership transfer
#         carries (leader_load - follower_load)
#   4     replica count (1 for moves, 0 for leadership)
#   5     leader count (1 iff the action moves leadership)
#   6     potential NW_OUT (leader-mode NW_OUT; 0 for leadership transfers)
#   7     leader NW_IN (what leader_util[:, NW_IN] shifts by). Deliberately 0
#         in MOVE waves: no goal vetoes replica moves on leader bytes-in
#         (LeaderBytesInDistributionGoal has no accept_move, matching the
#         reference), so budgets on this dim only bind leadership waves.
WAVE_DIMS = 8
WAVE_COUNT = 4
WAVE_LEADER_COUNT = 5
WAVE_POT_NW_OUT = 6
WAVE_LEADER_NW_IN = 7

# Wave-delta dims whose ZERO-delta rows are exempt from accept_move_rooms
# comparisons: the leader-count dim encodes CONDITIONAL acceptance
# (LeaderReplicaDistributionGoal accepts every follower move outright — only
# rows that actually relocate a leader are band-checked), whereas a
# zero-valued resource/count delta still probes the destination's band
# position in the goals' own mask arithmetic (a zero-load replica may NOT
# land on a broker already above its upper bound).
WAVE_ZERO_EXEMPT_DIMS = (WAVE_LEADER_COUNT,)


@dataclasses.dataclass(frozen=True)
class GoalKernel:
    """Base goal. Subclasses override the kernel methods; all fields static."""
    constraint: BalancingConstraint = BalancingConstraint()
    options: OptimizationOptions = OptimizationOptions()

    # --- identity ---
    name: str = dataclasses.field(default="GoalKernel", init=False)
    is_hard: bool = dataclasses.field(default=False, init=False)
    uses_replica_moves: bool = dataclasses.field(default=True, init=False)
    uses_leadership_moves: bool = dataclasses.field(default=False, init=False)
    uses_swaps: bool = dataclasses.field(default=False, init=False)
    uses_disk_moves: bool = dataclasses.field(default=False, init=False)
    # True when leadership transfers are this goal's PREFERRED action (e.g.
    # LeaderReplicaDistributionGoal.java:369 tries transfers before moving
    # leader replicas): the engine then runs the cheap [KL, F] leadership
    # branch every pass and gates replica moves behind it, instead of paying
    # a full [K, B] move-scoring pass just to discover "no moves" first.
    leadership_primary: bool = dataclasses.field(default=False, init=False)
    # True when this goal's accept_move cannot be broken by a multi-move wave
    # given the engine's per-partition first-touch and per-(topic, broker)
    # first-use rules (e.g. rack/topic count goals). Goals with broker-level
    # band acceptance provide wave_budgets instead; a goal with neither forces
    # the engine back to the one-move-per-broker wave.
    wave_safe: bool = dataclasses.field(default=False, init=False)
    # True for goals whose greedy tail is unbounded on skewed instances
    # (the soft distribution goals: near their plateau every pass lands a
    # dribble of actions and salted exploration can run for hundreds of
    # passes). The optimizer runs the chain's fused program only up to the
    # first deep-tail goal; each deep-tail goal then runs as its OWN
    # bounded program (salted tail + exhaustive finisher) — one long fused
    # program containing those tails reproducibly gets the axon TPU
    # worker killed mid-execution.
    deep_tail: bool = dataclasses.field(default=False, init=False)

    # --- kernel methods (override) ---
    def broker_severity(self, env: ClusterEnv, st: EngineState) -> Array:
        raise NotImplementedError

    def replica_key(self, env: ClusterEnv, st: EngineState, severity: Array) -> Array:
        """f32[R] candidate ranking; default: effective load magnitude of
        replicas on positive-severity brokers (offline replicas get
        priority). Severity reaches replica granularity via one packed
        gather (see broker_lookup)."""
        on_bad = broker_lookup(st.replica_broker, severity)[:, 0] > 0
        load = jnp.sum(st.effective_load(env), axis=1)
        key = jnp.where(on_bad & env.replica_valid, load, NEG_INF)
        return jnp.where(st.replica_offline & env.replica_valid, key + 1e12, key)

    def move_score(self, env: ClusterEnv, st: EngineState, cand: Array) -> Array:
        raise NotImplementedError

    def accept_move(self, env: ClusterEnv, st: EngineState, cand: Array) -> Array:
        """bool[K, B] veto as a previously-optimized goal. Default: accept."""
        return jnp.ones((cand.shape[0], env.num_brokers), bool)

    def wave_budgets(self, env: ClusterEnv, st: EngineState):
        """Optional ``(src_slack[B, WAVE_DIMS], dst_slack[B, WAVE_DIMS])``.

        A goal whose accept_move/move feasibility is an interval constraint on
        per-broker monotone quantities exposes it here as remaining slack in
        delta units (+inf where unconstrained): the engine admits multiple
        same-broker moves per wave while every cumulative delta stays within
        the combined slack — the admitted set then satisfies this goal's
        acceptance in ANY application order (prefix sums of nonnegative deltas
        are monotone). Return None when not applicable (see ``wave_safe``)."""
        return None

    def accept_move_rooms(self, env: ClusterEnv, st: EngineState):
        """Optional ``{dim: (src_room[B] | None, dst_room[B] | None)}``: this
        goal's accept_move veto in per-broker INTERVAL form. A move whose
        wave-delta row is ``d[WAVE_DIMS]`` (engine convention, see WAVE_DIMS)
        is accepted iff for every listed dim ``d[dim] <= src_room[src]`` and
        ``d[dim] <= dst_room[dst]`` (None = that side unconstrained; dims in
        WAVE_ZERO_EXEMPT_DIMS additionally accept zero-delta rows outright).

        The engine folds every chain goal's rooms into ONE combined table
        per pass (min over goals per dim) and applies a single vectorized
        comparison, replacing one [K, B] mask per prev goal per branch (and
        per exhaustive-scan chunk) — the pass-invariant chain cache. The
        room form must be EXACTLY the goal's accept_move (bitwise up to one
        f32 ulp at a band edge from the per-broker subtraction; certified in
        tests/test_pass_pipeline.py). Return None when the veto has no
        interval form (topic/rack-structured vetoes keep their masks)."""
        return None

    def wave_topic_budgets(self, env: ClusterEnv, st: EngineState,
                           topics: Array, src_b: Array, dst_b: Array,
                           d_count: Array, d_leader: Array):
        """Optional ``(delta[K], src_slack[K], dst_slack[K])``: this goal's
        per-(topic, broker) count constraint in wave form. ``delta`` is what
        each row subtracts from its (topic, src) pair and adds to its
        (topic, dst) pair in this goal's counting unit; the slacks are the
        remaining room at the row's own pairs measured from the pre-wave
        state (+inf where unconstrained). The engine admits rows while the
        cumulative per-pair delta stays within slack (rank-0 rows exempt —
        their single action was validated exactly by the acceptance masks).
        ``d_count``/``d_leader`` [K] are the wave's replica-count and
        leader-count deltas per row (moves: 1 / is_leader; leadership
        transfers: 0 / 1). Return None when the goal has no per-topic
        constraint."""
        return None

    def segment_room_key(self, env: ClusterEnv, st: EngineState):
        """Optional f32[B] DESTINATION-room ranking for the segment-parallel
        finisher's broker coloring (engine._segment_broker_order): how much
        of this goal's work a wave could still land on each broker, in the
        goal's own accounting units (larger = more room; the engine masks
        non-candidate destinations itself). The greedy coloring ranks
        brokers by this key and deals them round-robin into segments so
        every segment holds comparable admission headroom — a pure
        LOAD-BALANCING heuristic: correctness of the segmented wave rests
        on the cumulative-budget admission, never on the coloring. Return
        None to fall back to the chain's combined accept_move room tables
        (or the static capacity stripe when the chain has none).

        ACCOUNTING NOTE (Kahan residuals): like every accounting read, room
        keys are computed from ``st.util`` — the raw f32 accumulator. The
        compensated sums (``st.util + st.util_residual``) are what the bf16
        sweep policy reads (engine._sweep_state); kernels never need to add
        the residual themselves."""
        return None

    def wave_gain_budgets(self, env: ClusterEnv, st: EngineState):
        """Optional ``(src_gain[B], dst_gain[B], dim)`` for the ACTIVE goal:
        the remaining genuinely-useful shed (src excess above its target) and
        fill (dst deficit below its target) in units of the wave delta column
        ``dim``. The engine rejects wave rows whose cumulative delta exceeds
        BOTH budgets — per-row scores are computed against the pre-wave state,
        so without this cap a wave admits band-legal but zero-gain churn
        (shedding past the upper bound all the way to lower). None = every
        scored row is genuinely gainful (e.g. rack fixes, partition-exact
        goals)."""
        return None

    def leader_key(self, env: ClusterEnv, st: EngineState, severity: Array) -> Array:
        return jnp.full(env.num_replicas, NEG_INF)

    def leadership_score(self, env: ClusterEnv, st: EngineState, cand: Array) -> Array:
        return jnp.full((cand.shape[0], env.max_rf), NEG_INF)

    def accept_leadership(self, env: ClusterEnv, st: EngineState, cand: Array) -> Array:
        """bool[K, F] veto of leadership transfer cand k -> its partition's
        f-th replica, as a previously-optimized goal. Default: accept."""
        return jnp.ones((cand.shape[0], env.max_rf), bool)

    # --- swaps (SWAP balancing action, ResourceDistributionGoal.java:598-783) ---
    def swap_out_key(self, env: ClusterEnv, st: EngineState, severity: Array) -> Array:
        """f32[R] ranking of replicas to swap OUT of violating brokers."""
        return jnp.full(env.num_replicas, NEG_INF)

    def swap_in_key(self, env: ClusterEnv, st: EngineState, severity: Array) -> Array:
        """f32[R] ranking of replicas to swap IN (from non-violating brokers)."""
        return jnp.full(env.num_replicas, NEG_INF)

    def swap_score(self, env: ClusterEnv, st: EngineState, cand_out: Array,
                   cand_in: Array) -> Array:
        return jnp.full((cand_out.shape[0], cand_in.shape[0]), NEG_INF)

    def accept_swap(self, env: ClusterEnv, st: EngineState, cand_out: Array,
                    cand_in: Array) -> Array:
        """bool[K1, K2] veto of a swap as a previously-optimized goal.
        Default: both directed moves must be individually acceptable
        (conservative; net-aware goals override)."""
        acc_out = self.accept_move(env, st, cand_out)          # [K1, B]
        acc_in = self.accept_move(env, st, cand_in)            # [K2, B]
        b_in = st.replica_broker[cand_in]                      # [K2]
        b_out = st.replica_broker[cand_out]                    # [K1]
        return acc_out[:, b_in] & acc_in[:, b_out].T

    # --- intra-broker disk moves (IntraBroker*Goal.java) ---
    def disk_move_score(self, env: ClusterEnv, st: EngineState, cand: Array) -> Array:
        """f32[K, D]: improvement from moving candidate k to logdir d of its
        OWN broker; -inf where not self-satisfied. Only intra-broker goals
        implement this."""
        return jnp.full((cand.shape[0], env.broker_disk_capacity.shape[1]), NEG_INF)

    def accept_disk_move(self, env: ClusterEnv, st: EngineState, cand: Array) -> Array:
        """bool[K, D] veto of an intra-broker move as a previously-optimized
        goal. Default: accept (broker-level goals are indifferent to logdir
        placement)."""
        return jnp.ones((cand.shape[0], env.broker_disk_capacity.shape[1]), bool)

    def violated(self, env: ClusterEnv, st: EngineState) -> Array:
        return jnp.any(self.broker_severity(env, st) > 0)

    # --- stats comparator (monotonicity; ClusterModelStatsComparator role) ---
    def stat(self, env: ClusterEnv, st: EngineState) -> Array:
        """Scalar the goal tries to reduce; optimizer asserts no increase."""
        return jnp.sum(jnp.maximum(self.broker_severity(env, st), 0.0))

    def seeded_work_probe(self, env: ClusterEnv, st: EngineState,
                          seed_mask: Array) -> Array:
        """bool[]: would ANY seed-mask candidate rank eligible (> NEG_INF)
        for ANY action kind this goal uses? The engine's reduced-round
        candidate selection masks each key array by the seed mask before
        top-k, so ``False`` here proves every selection pool the goal's
        pass loop could build is all-NEG_INF: zero actions can admit and
        the goal program is a bit-exact no-op on its state (the PR 19
        chain-level short-circuit's one [B]-reduction probe, paired with
        ``violated``). Conservative by construction — it reuses the exact
        key kernels the engine ranks with (the swap probe checks only the
        OUT side, matching the engine's seed-mask placement)."""
        sev = self.broker_severity(env, st)

        def masked_any(key):
            return jnp.any(jnp.where(seed_mask, key, NEG_INF) > NEG_INF)

        has = jnp.bool_(False)
        if self.uses_replica_moves or self.uses_disk_moves:
            has = has | masked_any(self.replica_key(env, st, sev))
        if self.uses_leadership_moves:
            has = has | masked_any(self.leader_key(env, st, sev))
        if self.uses_swaps:
            has = has | masked_any(self.swap_out_key(env, st, sev))
        return has


def broker_lookup(rb: Array, *cols: Array) -> Array:
    """f32[R, len(cols)]: per-broker columns gathered at replica positions in
    ONE packed gather.

    TPU random-access gathers pay per index, not per byte: profiling the
    rung-4 engine showed a single-column [R]<-[B] gather at ~7 ms while a
    packed [R,4]<-[B,4] row gather is ~2 ms — the seven broker-value gathers
    inside one scoring pass were ~75% of the whole pass. Every kernel that
    needs several broker-level values at replica granularity must fetch them
    through one packed table, padded to >= 4 columns for the fast path.

    The packed table follows the COLUMNS' float dtype (precision policy):
    under the bf16 compute policy the goals' broker columns arrive bf16 and
    the [R]<-[B, 4] gather moves half the bytes; int columns alone fall back
    to float32, so f32 callers are bit-identical to the pre-policy table."""
    k = len(cols)
    cols = list(cols) + [cols[0]] * max(0, 4 - k)
    dt = jnp.result_type(*cols)
    if not jnp.issubdtype(dt, jnp.floating):
        dt = jnp.float32
    table = jnp.stack([c.astype(dt) for c in cols], axis=1)
    return table[rb][:, :k]


# Shard-explicit keying hook (parallel/shard_ops.py): while a replica-sharded
# keying body traces, this holds the shard's GLOBAL replica-id offset
# (axis_index * R_local, a traced uint32 scalar) so that index-hashed helpers
# — spread_jitter is the only one — reconstruct global ids from local iotas
# and produce bit-identical values to the unsharded sweep's slice. None
# outside a sharded keying region (the default, zero-cost path).
_REPLICA_SHARD_OFFSET = None


@contextlib.contextmanager
def replica_shard_offset(offset):
    """Publish the global replica-id offset of the shard being traced."""
    global _REPLICA_SHARD_OFFSET
    prev = _REPLICA_SHARD_OFFSET
    _REPLICA_SHARD_OFFSET = offset
    try:
        yield
    finally:
        _REPLICA_SHARD_OFFSET = prev


def spread_jitter(num_replicas: int, dtype=jnp.float32) -> Array:
    """[R] deterministic per-replica multiplier in [0.5, 1.0) used to mix
    candidate keys ACROSS brokers. Count-goal keys of the form
    ``1 - load/broker_total`` are ~1.0 for EVERY light replica of a broker
    with many of them, so one such broker would monopolize the top-k pool
    and starve other violating brokers (pass-count explosion). Scaling each
    key by a hash-derived factor gives every broker top-k representation
    roughly proportional to its candidate count while still preferring
    lighter replicas. Pure elementwise — no gathers. ``dtype`` follows the
    caller's compute dtype so a bf16 key sweep stays bf16 end to end.

    The hash input is the GLOBAL replica id: inside a replica-sharded keying
    (shard_ops.replica_key_select) ``num_replicas`` is the LOCAL shard size
    and the published shard offset re-bases the iota, so sharded and
    unsharded sweeps hash identical ids."""
    idx = jnp.arange(num_replicas, dtype=jnp.uint32)
    if _REPLICA_SHARD_OFFSET is not None:
        idx = idx + _REPLICA_SHARD_OFFSET
    h = idx * jnp.uint32(2654435761)
    return (0.5 + (h >> 9).astype(jnp.float32) / jnp.float32(1 << 24)) \
        .astype(dtype)


def candidate_load(env: ClusterEnv, st: EngineState, cand: Array) -> Array:
    """f32[K, M] current effective load rows of the candidate replicas."""
    lead = st.replica_is_leader[cand][:, None]
    return jnp.where(lead, env.leader_load[cand], env.follower_load[cand])


def legit_move_mask(env: ClusterEnv, st: EngineState, cand: Array,
                    options: OptimizationOptions) -> Array:
    """bool[K, B] — the action-independent legitMove checks
    (AbstractGoal.java:244-256 legit-move + GoalUtils.filterReplicas):

    - destination is an allowed candidate broker (alive, not move-excluded)
    - destination != current broker
    - destination hosts no replica of the candidate's partition
    - candidate replica is valid, and its topic isn't excluded (offline
      replicas of excluded topics may still move — self-healing overrides)
    - in fix-offline-only mode, only offline replicas move
    - candidate slots that are top-k padding (key was -inf) are filtered by
      the engine via score, not here
    """
    K = cand.shape[0]
    B = env.num_brokers
    dst_ok = jnp.broadcast_to(env.dst_candidate[None, :], (K, B))
    # new-broker mode (OptimizationVerifier NEW_BROKERS contract, reference
    # GoalUtils.eligibleBrokers:163 `b.isNew() || b == replica.
    # originalBroker()`): when the cluster has new brokers, a replica may
    # only move ONTO a new broker or BACK to its own original broker
    new_any = jnp.any(env.broker_new)
    orig_b = env.replica_original_broker[cand]                        # [K]
    back_home = jnp.arange(B)[None, :] == orig_b[:, None]             # [K, B]
    new_ok = (~new_any) | env.broker_new[None, :] | back_home
    dst_ok = dst_ok & new_ok
    cur = st.replica_broker[cand]
    not_self = jnp.arange(B)[None, :] != cur[:, None]
    # duplicate-partition check via the partition membership table: [K, F]
    members = env.partition_replicas[env.replica_partition[cand]]          # i32[K, F]
    member_valid = members >= 0
    member_broker = st.replica_broker[jnp.clip(members, 0)]                # i32[K, F]
    not_me = members != cand[:, None]
    # broker b hosts a sibling replica iff any member (not the candidate itself)
    # sits on b
    sib_on = jnp.zeros((K, B), bool)
    sib_on = sib_on.at[jnp.arange(K)[:, None], member_broker].max(
        member_valid & not_me)
    no_dup = ~sib_on
    valid = env.replica_valid[cand]
    offline = st.replica_offline[cand]
    topic_ok = ~env.topic_excluded[env.replica_topic[cand]] | offline
    replica_ok = valid & topic_ok
    if options.fix_offline_replicas_only:
        replica_ok = replica_ok & offline
    return dst_ok & not_self & no_dup & replica_ok[:, None]


def legit_swap_mask(env: ClusterEnv, st: EngineState, cand_out: Array,
                    cand_in: Array) -> Array:
    """bool[K1, K2] — legitimacy of swapping cand_out[i] <-> cand_in[j]:
    different brokers, neither destination hosts a sibling of the incoming
    partition, both replicas online+valid, topics not excluded, and both
    brokers are allowed destinations."""
    b_out = st.replica_broker[cand_out]                     # [K1]
    b_in = st.replica_broker[cand_in]                       # [K2]
    diff_broker = b_out[:, None] != b_in[None, :]

    def sib_on(cand, brokers):
        # [K, Kb]: does brokers[j] host a replica of cand[i]'s partition (≠ cand[i])?
        members = env.partition_replicas[env.replica_partition[cand]]   # [K, F]
        mvalid = members >= 0
        mb = st.replica_broker[jnp.clip(members, 0)]                    # [K, F]
        not_me = members != cand[:, None]
        hit = (mb[:, :, None] == brokers[None, None, :]) & (mvalid & not_me)[:, :, None]
        return jnp.any(hit, axis=1)                                     # [K, Kb]

    out_ok = ~sib_on(cand_out, b_in)                        # [K1, K2] out's partition not on in's broker
    in_ok = ~sib_on(cand_in, b_out).T                       # [K1, K2]
    ok_r = (env.replica_valid & ~st.replica_offline
            & ~env.replica_topic_excluded)
    dst_ok = env.dst_candidate[b_in][None, :] & env.dst_candidate[b_out][:, None]
    # new-broker mode: each directed leg must target a new broker or the
    # moving replica's own original broker (same rule as legit_move_mask)
    new_any = jnp.any(env.broker_new)
    orig_out = env.replica_original_broker[cand_out]                  # [K1]
    orig_in = env.replica_original_broker[cand_in]                    # [K2]
    out_home = b_in[None, :] == orig_out[:, None]                     # [K1, K2]
    in_home = b_out[:, None] == orig_in[None, :]                      # [K1, K2]
    new_ok = ((~new_any)
              | ((env.broker_new[b_in][None, :] | out_home)
                 & (env.broker_new[b_out][:, None] | in_home)))
    return (diff_broker & out_ok & in_ok & dst_ok & new_ok
            & ok_r[cand_out][:, None] & ok_r[cand_in][None, :])


def legit_disk_move_mask(env: ClusterEnv, st: EngineState, cand: Array) -> Array:
    """bool[K, D] — legitimacy of moving candidate k to logdir d of its own
    broker (IntraBrokerDiskCapacityGoal legit-move analogue): destination disk
    alive (and has capacity configured), != current disk, broker alive,
    replica valid; excluded topics may still heal off dead disks."""
    b = st.replica_broker[cand]                                    # [K]
    D = env.broker_disk_capacity.shape[1]
    dst_alive = env.broker_disk_alive[b] & (env.broker_disk_capacity[b] > 0)
    cur = st.replica_disk[cand]
    not_self = jnp.arange(D)[None, :] != cur[:, None]
    valid = env.replica_valid[cand] & env.broker_alive[b]
    on_dead_disk = ~env.broker_disk_alive[b, jnp.clip(cur, 0)]
    topic_ok = ~env.topic_excluded[env.replica_topic[cand]] | on_dead_disk
    return dst_alive & not_self & (valid & topic_ok)[:, None]


def legit_leadership_mask(env: ClusterEnv, st: EngineState, cand: Array) -> Array:
    """bool[K, F] — legit leadership-transfer targets for candidate leaders:
    the f-th replica of the candidate's partition must exist, not be the
    candidate, be online, and sit on an alive, non-demoted,
    non-leadership-excluded broker."""
    members = env.partition_replicas[env.replica_partition[cand]]          # [K, F]
    member_valid = members >= 0
    m = jnp.clip(members, 0)
    not_me = members != cand[:, None]
    dst_broker = st.replica_broker[m]
    broker_ok = (env.broker_alive[dst_broker] & ~env.broker_demoted[dst_broker]
                 & ~env.broker_excluded_for_leadership[dst_broker])
    online = ~st.replica_offline[m]
    src_is_leader = st.replica_is_leader[cand] & env.replica_valid[cand]
    topic_ok = ~env.topic_excluded[env.replica_topic[cand]]
    return (member_valid & not_me & broker_ok & online
            & (src_is_leader & topic_ok)[:, None])
