"""Soft distribution goals.

Reference: analyzer/goals/ResourceDistributionGoal.java (1,077 lines; balance
thresholds :239-282, per-broker rebalance via move-out/move-in/leadership
:384-862) + its 4 per-resource subclasses, ReplicaDistributionAbstractGoal.java
(limit math :70-90) with ReplicaDistributionGoal.java and
LeaderReplicaDistributionGoal.java.

Threshold semantics preserved exactly:
- resource: avg utilization % over alive brokers, limits
  avg*(1 ± (balance_pct-1)*0.9) with low-utilization special cases
  (GoalUtils.java:515).
- counts: ceil/floor of avg*(1 ± (pct-1)*0.9)
  (ReplicaDistributionAbstractGoal.java:80,:90).

Scoring is gain-based: score = strict decrease of the total violation measure
(sum of per-broker excess + deficit), with masks forbidding a move from
creating a NEW violation at either endpoint — the vectorized equivalent of the
reference's selfSatisfied checks. Monotone decrease guarantees termination.
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from cruise_control_tpu.analyzer.env import (
    BALANCE_MARGIN, ClusterEnv, resource_balance_limits,
)
from cruise_control_tpu.analyzer.goals.base import (
    NEG_INF, WAVE_COUNT, WAVE_DIMS, WAVE_LEADER_COUNT, GoalKernel,
    broker_lookup, candidate_load, spread_jitter,
)
from cruise_control_tpu.analyzer.goals.capacity import RESOURCE_EPS
from cruise_control_tpu.analyzer.state import EngineState


def _violation(u, lower, upper):
    """Distance outside the [lower, upper] band."""
    return jnp.maximum(u - upper, 0.0) + jnp.maximum(lower - u, 0.0)


def _gain(util_src, util_dst, l, lower_src, upper_src, lower_dst, upper_dst):
    """Violation-measure decrease for transferring quantity ``l`` src->dst
    (l may be negative for net swaps), plus feasibility: neither endpoint's
    violation may increase — the vectorized selfSatisfied contract."""
    v_src_old = _violation(util_src, lower_src, upper_src)
    v_dst_old = _violation(util_dst, lower_dst, upper_dst)
    v_src_new = _violation(util_src - l, lower_src, upper_src)
    v_dst_new = _violation(util_dst + l, lower_dst, upper_dst)
    gain = (v_src_old - v_src_new) + (v_dst_old - v_dst_new)
    feasible = (v_src_new <= v_src_old) & (v_dst_new <= v_dst_old)
    return gain, feasible


@dataclasses.dataclass(frozen=True)
class ResourceDistributionGoal(GoalKernel):
    resource: int = 3  # DISK

    def __post_init__(self):
        object.__setattr__(self, "uses_leadership_moves", self.resource in (0, 2))
        object.__setattr__(self, "deep_tail", True)
        object.__setattr__(self, "uses_swaps", True)

    # -- limits --
    def _limits(self, env: ClusterEnv, st: EngineState):
        """(lower[B], upper[B]) absolute utilization limits; dead broker: 0/0."""
        alive = env.broker_alive
        cap = env.broker_capacity[:, self.resource]
        total_util = jnp.sum(jnp.where(alive, st.util[:, self.resource], 0.0))
        total_cap = jnp.maximum(jnp.sum(jnp.where(alive, cap, 0.0)), 1e-6)
        avg_pct = total_util / total_cap
        lower_pct, upper_pct = resource_balance_limits(
            avg_pct, self.constraint, self.resource,
            self.options.triggered_by_goal_violation)
        lower = jnp.where(alive, lower_pct * cap, 0.0)
        upper = jnp.where(alive, upper_pct * cap, 0.0)
        return lower, upper

    def broker_severity(self, env: ClusterEnv, st: EngineState):
        lower, upper = self._limits(env, st)
        util = st.util[:, self.resource]
        eps = RESOURCE_EPS[self.resource]
        return jnp.maximum(util - upper - eps, lower - util - eps)

    def replica_key(self, env: ClusterEnv, st: EngineState, severity):
        lower, upper = self._limits(env, st)
        util = st.util[:, self.resource]
        eps = RESOURCE_EPS[self.resource]
        # ONE packed gather for every broker-level value this key needs
        # (broker_lookup: single-column gathers at R scale are the engine's
        # dominant cost)
        per = broker_lookup(st.replica_broker, util - upper, util, lower, upper)
        excess_src = per[:, 0] > eps
        any_deficit = jnp.any((lower - util) > eps)
        load = st.effective_load(env)[:, self.resource]
        # donors for move-in: any broker that can shed without going deficient
        donor = (per[:, 1] - load) >= per[:, 2]
        # only replicas that can actually LAND somewhere: a replica larger
        # than every destination's remaining band headroom scores -inf for all
        # dsts, and a top-k full of such replicas stalls the goal — filter
        # them out so smaller, feasible replicas become candidates instead
        headroom = jnp.where(env.dst_candidate, upper - util, NEG_INF)
        fits = load <= jnp.max(headroom) + eps
        movable = (env.replica_valid & (load > 0) & fits
                   & (excess_src | (any_deficit & donor)))
        offline = st.replica_offline & env.replica_valid
        # spread candidates across source brokers WITHOUT per-replica rank
        # machinery (rank_within_broker cost 3 R-sized gathers/scatters per
        # pass): each replica keys by its fraction of its own broker's
        # utilization, so every broker's dominant replicas surface near the
        # top regardless of the broker's absolute load
        frac = load / jnp.maximum(per[:, 1], 1e-9)
        key = jnp.where(movable | offline, frac, NEG_INF)
        return jnp.where(offline, key + 1e12, key)

    def move_score(self, env: ClusterEnv, st: EngineState, cand):
        l = candidate_load(env, st, cand)[:, self.resource]              # [K]
        lower, upper = self._limits(env, st)
        util = st.util[:, self.resource]
        src = st.replica_broker[cand]
        gain, feasible = _gain(util[src][:, None], util[None, :], l[:, None],
                               lower[src][:, None], upper[src][:, None],
                               lower[None, :], upper[None, :])
        offline = st.replica_offline[cand]
        # offline healing: soft goal omits its balance limit (reference
        # _fixOfflineReplicasOnly relaxation); capacity hard goals still veto
        # via their accept_move during later-goal runs.
        cap = jnp.maximum(env.broker_capacity[:, self.resource], 1e-6)[None, :]
        heal_score = 1.0 + jnp.maximum(upper[None, :] - util[None, :] - l[:, None], 0.0) / cap
        score = jnp.where(offline[:, None], heal_score,
                          jnp.where(feasible & (gain > 0), gain, NEG_INF))
        return score

    def wave_budgets(self, env: ClusterEnv, st: EngineState):
        """Band slack on this resource: a wave may shed util down to lower and
        fill up to upper (the cumulative form of accept_move's band checks;
        conservative vs the single-move excess exception)."""
        lower, upper = self._limits(env, st)
        util = st.util[:, self.resource]
        eps = RESOURCE_EPS[self.resource]
        B = env.num_brokers
        src = jnp.full((B, WAVE_DIMS), jnp.inf, util.dtype)
        dst = jnp.full((B, WAVE_DIMS), jnp.inf, util.dtype)
        src = src.at[:, self.resource].set(util - lower + eps)
        dst = dst.at[:, self.resource].set(upper - util + eps)
        return src, dst

    def wave_gain_budgets(self, env: ClusterEnv, st: EngineState):
        lower, upper = self._limits(env, st)
        util = st.util[:, self.resource]
        return (jnp.maximum(util - upper, 0.0), jnp.maximum(lower - util, 0.0),
                self.resource)

    def segment_room_key(self, env: ClusterEnv, st: EngineState):
        """Segment coloring key: room to this resource's upper band limit —
        deficit brokers (the wave's real destinations) rank first."""
        _lower, upper = self._limits(env, st)
        return upper - st.util[:, self.resource]

    def accept_move(self, env: ClusterEnv, st: EngineState, cand):
        """Veto (as an already-optimized goal): moving cand -> dst must not push
        dst above upper, nor drop src below lower
        (ResourceDistributionGoal actionAcceptance REPLICA/BROKER_REJECT)."""
        l = candidate_load(env, st, cand)[:, self.resource]
        lower, upper = self._limits(env, st)
        util = st.util[:, self.resource]
        src = st.replica_broker[cand]
        eps = RESOURCE_EPS[self.resource]
        dst_ok = util[None, :] + l[:, None] <= upper[None, :] + eps
        src_ok = (util[src] - l >= lower[src] - eps)[:, None]
        # moves that reduce an existing excess at src are always fine for src
        src_was_excess = (util[src] > upper[src])[:, None]
        return dst_ok & (src_ok | src_was_excess)

    def accept_move_rooms(self, env: ClusterEnv, st: EngineState):
        """Interval form of accept_move: the resource delta must fit the
        destination's room to its upper bound and the source's room to its
        lower bound; an already-excess source may shed anything."""
        lower, upper = self._limits(env, st)
        util = st.util[:, self.resource]
        eps = RESOURCE_EPS[self.resource]
        src = jnp.where(util > upper, jnp.inf, util - lower + eps)
        return {int(self.resource): (src, upper - util + eps)}

    # -- leadership (CPU & NW_OUT follow leadership) --
    def leader_key(self, env: ClusterEnv, st: EngineState, severity):
        lower, upper = self._limits(env, st)
        util = st.util[:, self.resource]
        on_excess = (broker_lookup(st.replica_broker, util - upper)[:, 0]
                     > RESOURCE_EPS[self.resource])
        delta = env.leader_load[:, self.resource] - env.follower_load[:, self.resource]
        ok = env.replica_valid & st.replica_is_leader & on_excess & (delta > 0) \
            & ~st.replica_offline
        return jnp.where(ok, delta, NEG_INF)

    def leadership_score(self, env: ClusterEnv, st: EngineState, cand):
        members = env.partition_replicas[env.replica_partition[cand]]     # [K, F]
        m = jnp.clip(members, 0)
        dst_broker = st.replica_broker[m]
        lower, upper = self._limits(env, st)
        util = st.util[:, self.resource]
        src = st.replica_broker[cand]
        delta_src = (env.leader_load[cand, self.resource]
                     - env.follower_load[cand, self.resource])[:, None]
        delta_dst = (env.leader_load[m, self.resource]
                     - env.follower_load[m, self.resource])
        # src sheds delta_src; dst gains delta_dst
        excess_red_src = jnp.minimum(jnp.maximum(util[src][:, None] - upper[src][:, None], 0.0),
                                     delta_src)
        new_excess_dst = jnp.maximum(util[dst_broker] + delta_dst - upper[dst_broker], 0.0)
        gain = excess_red_src
        feasible = new_excess_dst <= 0.0
        return jnp.where(feasible & (gain > 0), gain, NEG_INF)

    def accept_leadership(self, env: ClusterEnv, st: EngineState, cand):
        members = env.partition_replicas[env.replica_partition[cand]]
        m = jnp.clip(members, 0)
        dst_broker = st.replica_broker[m]
        _lower, upper = self._limits(env, st)
        delta_dst = (env.leader_load[m, self.resource]
                     - env.follower_load[m, self.resource])
        eps = RESOURCE_EPS[self.resource]
        return st.util[dst_broker, self.resource] + delta_dst <= upper[dst_broker] + eps

    # -- swaps (rebalanceBySwappingLoadOut/In, ResourceDistributionGoal.java:598,:697) --
    def swap_out_key(self, env: ClusterEnv, st: EngineState, severity):
        """Replicas on out-of-band brokers, largest resource load first."""
        on_bad = broker_lookup(st.replica_broker, severity)[:, 0] > 0
        load = st.effective_load(env)[:, self.resource]
        ok = env.replica_valid & on_bad & ~st.replica_offline
        return jnp.where(ok, load, NEG_INF)

    def swap_in_key(self, env: ClusterEnv, st: EngineState, severity):
        """Counterparty replicas on brokers not above the upper limit (deficit
        brokers are prime counterparties: they trade a small replica for a big
        one); smallest loads first so a swap can shed a small net amount."""
        _lower, upper = self._limits(env, st)
        not_excess = broker_lookup(
            st.replica_broker, st.util[:, self.resource] - upper)[:, 0] <= 0
        load = st.effective_load(env)[:, self.resource]
        ok = env.replica_valid & not_excess & ~st.replica_offline
        return jnp.where(ok, -load, NEG_INF)

    def swap_score(self, env: ClusterEnv, st: EngineState, cand_out, cand_in):
        l_out = candidate_load(env, st, cand_out)[:, self.resource]       # [K1]
        l_in = candidate_load(env, st, cand_in)[:, self.resource]         # [K2]
        net = l_out[:, None] - l_in[None, :]                              # [K1, K2]
        lower, upper = self._limits(env, st)
        util = st.util[:, self.resource]
        b_out = st.replica_broker[cand_out]
        b_in = st.replica_broker[cand_in]
        gain, feasible = _gain(util[b_out][:, None], util[b_in][None, :], net,
                               lower[b_out][:, None], upper[b_out][:, None],
                               lower[b_in][None, :], upper[b_in][None, :])
        # moves are cheaper than swaps: discount so a tie prefers the move
        return jnp.where(feasible & (gain > 0), gain * 0.95, NEG_INF)

    def accept_swap(self, env: ClusterEnv, st: EngineState, cand_out, cand_in):
        """Net-aware veto: after the exchange neither endpoint may be newly
        out of band."""
        l_out = candidate_load(env, st, cand_out)[:, self.resource]
        l_in = candidate_load(env, st, cand_in)[:, self.resource]
        net = l_out[:, None] - l_in[None, :]
        lower, upper = self._limits(env, st)
        util = st.util[:, self.resource]
        b_out = st.replica_broker[cand_out]
        b_in = st.replica_broker[cand_in]
        _gain_v, feasible = _gain(util[b_out][:, None], util[b_in][None, :], net,
                                  lower[b_out][:, None], upper[b_out][:, None],
                                  lower[b_in][None, :], upper[b_in][None, :])
        return feasible


@dataclasses.dataclass(frozen=True)
class CpuUsageDistributionGoal(ResourceDistributionGoal):
    resource: int = 0

    def __post_init__(self):
        super().__post_init__()
        object.__setattr__(self, "name", "CpuUsageDistributionGoal")


@dataclasses.dataclass(frozen=True)
class NetworkInboundUsageDistributionGoal(ResourceDistributionGoal):
    resource: int = 1

    def __post_init__(self):
        super().__post_init__()
        object.__setattr__(self, "name", "NetworkInboundUsageDistributionGoal")


@dataclasses.dataclass(frozen=True)
class NetworkOutboundUsageDistributionGoal(ResourceDistributionGoal):
    resource: int = 2

    def __post_init__(self):
        super().__post_init__()
        object.__setattr__(self, "name", "NetworkOutboundUsageDistributionGoal")


@dataclasses.dataclass(frozen=True)
class DiskUsageDistributionGoal(ResourceDistributionGoal):
    resource: int = 3

    def __post_init__(self):
        super().__post_init__()
        object.__setattr__(self, "name", "DiskUsageDistributionGoal")


# ---------------------------------------------------------------------------
# Count-based distribution
# ---------------------------------------------------------------------------
def _count_limits(counts_total, n_alive, balance_pct, triggered, multiplier):
    """(lower, upper) integer limits (ReplicaDistributionAbstractGoal.java:70-90)."""
    avg = counts_total / jnp.maximum(n_alive, 1)
    pct = jnp.where(triggered, balance_pct * multiplier, balance_pct)
    adj = (pct - 1.0) * BALANCE_MARGIN
    upper = jnp.ceil(avg * (1.0 + adj))
    lower = jnp.floor(avg * jnp.maximum(0.0, 1.0 - adj))
    return lower, upper


@dataclasses.dataclass(frozen=True)
class ReplicaDistributionGoal(GoalKernel):
    """Even replica counts (ReplicaDistributionGoal.java:356)."""

    def __post_init__(self):
        object.__setattr__(self, "name", "ReplicaDistributionGoal")

    def _limits(self, env: ClusterEnv, st: EngineState):
        n_alive = jnp.sum(env.broker_alive)
        # all replicas count toward the average — replicas on dead brokers must
        # land on alive ones (ReplicaDistributionAbstractGoal._avgReplicasOnAliveBroker)
        total = jnp.sum(st.replica_count)
        lower, upper = _count_limits(
            total.astype(st.util.dtype), n_alive.astype(st.util.dtype),
            self.constraint.replica_balance_percentage,
            self.options.triggered_by_goal_violation,
            self.constraint.goal_violation_distribution_threshold_multiplier)
        lower = jnp.where(env.broker_alive, lower, 0.0)
        upper = jnp.where(env.broker_alive, upper, 0.0)
        return lower, upper

    def broker_severity(self, env: ClusterEnv, st: EngineState):
        lower, upper = self._limits(env, st)
        c = st.replica_count.astype(st.util.dtype)
        return jnp.maximum(c - upper, lower - c)

    def replica_key(self, env: ClusterEnv, st: EngineState, severity):
        lower, upper = self._limits(env, st)
        c = st.replica_count.astype(st.util.dtype)
        per = broker_lookup(st.replica_broker, c - upper, c - 1.0 - lower,
                            jnp.sum(st.util, axis=1))
        over = per[:, 0] > 0
        any_deficit = jnp.any(lower - c > 0)
        donor = per[:, 1] >= 0
        load = jnp.sum(st.effective_load(env), axis=1)
        movable = env.replica_valid & (over | (any_deficit & donor))
        offline = st.replica_offline & env.replica_valid
        # prefer light replicas (less data moved per count unit); the hash
        # jitter keeps one many-light-replica broker from monopolizing the
        # top-k pool (see spread_jitter)
        tiebreak = ((1.0 - load / jnp.maximum(per[:, 2], 1e-9))
                    * spread_jitter(env.num_replicas, st.util.dtype))
        key = jnp.where(movable | offline, tiebreak, NEG_INF)
        return jnp.where(offline, key + 1e12, key)

    def move_score(self, env: ClusterEnv, st: EngineState, cand):
        lower, upper = self._limits(env, st)
        c = st.replica_count.astype(st.util.dtype)
        src = st.replica_broker[cand]
        gain, feasible = _gain(c[src][:, None], c[None, :], 1.0,
                               lower[src][:, None], upper[src][:, None],
                               lower[None, :], upper[None, :])
        offline = st.replica_offline[cand]
        heal = 1.0 + jnp.maximum(upper[None, :] - c[None, :] - 1.0, 0.0) / (upper[None, :] + 1.0)
        return jnp.where(offline[:, None], heal,
                         jnp.where(feasible & (gain > 0), gain, NEG_INF))

    def accept_move(self, env: ClusterEnv, st: EngineState, cand):
        lower, upper = self._limits(env, st)
        c = st.replica_count.astype(st.util.dtype)
        src = st.replica_broker[cand]
        dst_ok = c[None, :] + 1 <= upper[None, :]
        src_ok = ((c[src] - 1 >= lower[src]) | (c[src] > upper[src]))[:, None]
        return dst_ok & src_ok

    def accept_move_rooms(self, env: ClusterEnv, st: EngineState):
        """Interval form of accept_move on the count dim (every move's count
        delta is exactly 1; counts are f32-exact, so this is bitwise the
        mask's band check)."""
        lower, upper = self._limits(env, st)
        c = st.replica_count.astype(st.util.dtype)
        src = jnp.where(c > upper, jnp.inf, c - lower)
        return {WAVE_COUNT: (src, upper - c)}

    def wave_budgets(self, env: ClusterEnv, st: EngineState):
        """Replica-count band slack (cumulative form of accept_move: shedding
        stepwise from excess may continue down to lower)."""
        lower, upper = self._limits(env, st)
        c = st.replica_count.astype(st.util.dtype)
        B = env.num_brokers
        src = jnp.full((B, WAVE_DIMS), jnp.inf, c.dtype)
        dst = jnp.full((B, WAVE_DIMS), jnp.inf, c.dtype)
        src = src.at[:, WAVE_COUNT].set(c - lower)
        dst = dst.at[:, WAVE_COUNT].set(upper - c)
        return src, dst

    def wave_gain_budgets(self, env: ClusterEnv, st: EngineState):
        lower, upper = self._limits(env, st)
        c = st.replica_count.astype(st.util.dtype)
        return (jnp.maximum(c - upper, 0.0), jnp.maximum(lower - c, 0.0),
                WAVE_COUNT)

    def segment_room_key(self, env: ClusterEnv, st: EngineState):
        """Segment coloring key: replica-count room to the upper band."""
        _lower, upper = self._limits(env, st)
        return upper - st.replica_count.astype(st.util.dtype)

    def accept_swap(self, env: ClusterEnv, st: EngineState, cand_out, cand_in):
        """Swaps are count-neutral -> always accepted
        (ReplicaDistributionGoal.java:122 INTER_BROKER_REPLICA_SWAP: ACCEPT)."""
        return jnp.ones((cand_out.shape[0], cand_in.shape[0]), bool)


@dataclasses.dataclass(frozen=True)
class LeaderReplicaDistributionGoal(GoalKernel):
    """Even leader counts (LeaderReplicaDistributionGoal.java:369): prefers
    leadership transfers, falls back to moving leader replicas."""

    def __post_init__(self):
        object.__setattr__(self, "name", "LeaderReplicaDistributionGoal")
        object.__setattr__(self, "uses_leadership_moves", True)
        object.__setattr__(self, "leadership_primary", True)
        object.__setattr__(self, "deep_tail", True)

    def _limits(self, env: ClusterEnv, st: EngineState):
        n_alive = jnp.sum(env.broker_alive)
        total = jnp.sum(st.leader_count)
        lower, upper = _count_limits(
            total.astype(st.util.dtype), n_alive.astype(st.util.dtype),
            self.constraint.leader_replica_balance_percentage,
            self.options.triggered_by_goal_violation,
            self.constraint.goal_violation_distribution_threshold_multiplier)
        lower = jnp.where(env.broker_alive, lower, 0.0)
        upper = jnp.where(env.broker_alive, upper, 0.0)
        return lower, upper

    def broker_severity(self, env: ClusterEnv, st: EngineState):
        lower, upper = self._limits(env, st)
        c = st.leader_count.astype(st.util.dtype)
        return jnp.maximum(c - upper, lower - c)

    # replica moves: only leaders help
    def replica_key(self, env: ClusterEnv, st: EngineState, severity):
        lower, upper = self._limits(env, st)
        c = st.leader_count.astype(st.util.dtype)
        over = broker_lookup(st.replica_broker, c - upper)[:, 0] > 0
        load = jnp.sum(st.effective_load(env), axis=1)
        movable = env.replica_valid & st.replica_is_leader & over & ~st.replica_offline
        return jnp.where(movable, -load, NEG_INF)

    def move_score(self, env: ClusterEnv, st: EngineState, cand):
        lower, upper = self._limits(env, st)
        c = st.leader_count.astype(st.util.dtype)
        src = st.replica_broker[cand]
        gain, feasible = _gain(c[src][:, None], c[None, :], 1.0,
                               lower[src][:, None], upper[src][:, None],
                               lower[None, :], upper[None, :])
        # leadership transfer is cheaper; replica moves score slightly lower
        return jnp.where(feasible & (gain > 0), gain * 0.9, NEG_INF)

    def accept_move(self, env: ClusterEnv, st: EngineState, cand):
        lower, upper = self._limits(env, st)
        c = st.leader_count.astype(st.util.dtype)
        src = st.replica_broker[cand]
        is_leader = st.replica_is_leader[cand]
        dst_ok = c[None, :] + 1 <= upper[None, :]
        src_ok = ((c[src] - 1 >= lower[src]) | (c[src] > upper[src]))[:, None]
        moving_leader = is_leader[:, None]
        return jnp.where(moving_leader, dst_ok & src_ok, True)

    def accept_move_rooms(self, env: ClusterEnv, st: EngineState):
        """Interval form of accept_move: only rows whose leader-count delta
        is 1 (moving a leader) are band-checked — follower moves carry a
        zero delta and the leader-count dim is zero-exempt
        (WAVE_ZERO_EXEMPT_DIMS), reproducing the mask's conditional."""
        lower, upper = self._limits(env, st)
        c = st.leader_count.astype(st.util.dtype)
        src = jnp.where(c > upper, jnp.inf, c - lower)
        return {WAVE_LEADER_COUNT: (src, upper - c)}

    def wave_budgets(self, env: ClusterEnv, st: EngineState):
        """Leader-count band slack; follower moves carry a zero leader-count
        delta, so the conditionality of accept_move is preserved exactly."""
        lower, upper = self._limits(env, st)
        c = st.leader_count.astype(st.util.dtype)
        B = env.num_brokers
        src = jnp.full((B, WAVE_DIMS), jnp.inf, c.dtype)
        dst = jnp.full((B, WAVE_DIMS), jnp.inf, c.dtype)
        src = src.at[:, WAVE_LEADER_COUNT].set(c - lower)
        dst = dst.at[:, WAVE_LEADER_COUNT].set(upper - c)
        return src, dst

    def wave_gain_budgets(self, env: ClusterEnv, st: EngineState):
        lower, upper = self._limits(env, st)
        c = st.leader_count.astype(st.util.dtype)
        return (jnp.maximum(c - upper, 0.0), jnp.maximum(lower - c, 0.0),
                WAVE_LEADER_COUNT)

    def segment_room_key(self, env: ClusterEnv, st: EngineState):
        """Segment coloring key: leader-count room to the upper band."""
        _lower, upper = self._limits(env, st)
        return upper - st.leader_count.astype(st.util.dtype)

    def leader_key(self, env: ClusterEnv, st: EngineState, severity):
        lower, upper = self._limits(env, st)
        c = st.leader_count.astype(st.util.dtype)
        per = broker_lookup(st.replica_broker, c - upper,
                            st.leader_util[:, 2])
        over = per[:, 0] > 0
        nw = env.leader_load[:, 2] - env.follower_load[:, 2]
        ok = env.replica_valid & st.replica_is_leader & over & ~st.replica_offline
        # light partitions first; hash jitter prevents one leader-heavy
        # broker from monopolizing the pool (see spread_jitter)
        tiebreak = ((1.0 - nw / jnp.maximum(per[:, 1], 1e-9))
                    * spread_jitter(env.num_replicas, st.util.dtype))
        return jnp.where(ok, tiebreak, NEG_INF)

    def leadership_score(self, env: ClusterEnv, st: EngineState, cand):
        members = env.partition_replicas[env.replica_partition[cand]]
        m = jnp.clip(members, 0)
        dst_broker = st.replica_broker[m]
        lower, upper = self._limits(env, st)
        c = st.leader_count.astype(st.util.dtype)
        src = st.replica_broker[cand]
        gain, feasible = _gain(c[src][:, None], c[dst_broker], 1.0,
                               lower[src][:, None], upper[src][:, None],
                               lower[dst_broker], upper[dst_broker])
        return jnp.where(feasible & (gain > 0), gain, NEG_INF)

    def accept_leadership(self, env: ClusterEnv, st: EngineState, cand):
        members = env.partition_replicas[env.replica_partition[cand]]
        m = jnp.clip(members, 0)
        dst_broker = st.replica_broker[m]
        lower, upper = self._limits(env, st)
        c = st.leader_count.astype(st.util.dtype)
        src = st.replica_broker[cand]
        dst_ok = c[dst_broker] + 1 <= upper[dst_broker]
        src_ok = ((c[src] - 1 >= lower[src]) | (c[src] > upper[src]))[:, None]
        return dst_ok & src_ok
