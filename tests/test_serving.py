"""Serving certification (PR 18): the request-admission engine.

The continuous-batching contracts:

1. **Zero-pressure parity** — with no queue pressure the admission round
   (poll -> enqueue due -> drain) installs per-tenant violation/
   certificate/proposal sets and final assignment arrays BIT-IDENTICAL to
   the legacy static bucket round (``fleet.admission.enabled`` off).
2. **Admission determinism** — the admitted set and the admission journal
   are pure functions of (scenario, seed): the same scripted arrival
   stream replayed into a fresh fleet reproduces them exactly, and the
   Poisson driver's arrival stream is seed-stable.
3. **Priority lanes** — a heal request enqueued LAST preempts earlier
   hygiene rebalances, which preempt background refreshes; lane dispatch
   across the prewarmed K ladder costs ZERO new XLA compiles.
4. **Mid-launch arrivals** — a request arriving after a dispatch admitted
   its batch is NOT lost: it rides the next dispatch.
5. **Pad-to-join vs split-launch** — NEAR buckets join (the smaller
   tenants rebuild with pad floors into the larger bucket, one launch)
   exactly when measured queue pressure reaches the threshold, and split
   into per-bucket launches below it.
6. **Launch-failure surfacing** — a failed batched launch lands in the
   report's ``failed`` map; heal-lane requests re-enqueue with a bounded
   retry budget instead of being dropped.

Shapes and the 2-goal chain are deliberately tiny and shared across every
test so the whole module rides a handful of compiled programs.
"""
from __future__ import annotations

import numpy as np
import pytest

from cruise_control_tpu.common.tracing import XlaCompileListener
from cruise_control_tpu.config import cruise_control_config
from cruise_control_tpu.backend.simulated import SimulatedClusterBackend
from cruise_control_tpu.fleet import FleetScheduler
from cruise_control_tpu.pipeline import (
    LANE_HEAL, LANE_REBALANCE, LANE_REFRESH,
)
from cruise_control_tpu.sim.runner import ServingLoadDriver

WINDOW_MS = 300_000.0
T0 = 2_000_000.0
GOALS = ["ReplicaCapacityGoal", "ReplicaDistributionGoal"]
SEEDS = (21, 22, 23)


def _backend(seed, num_brokers=10, num_partitions=60, rf=2):
    rng = np.random.default_rng(seed)
    be = SimulatedClusterBackend()
    for b in range(num_brokers):
        be.add_broker(b, f"r{b % 3}")
    for p in range(num_partitions):
        reps = [int(x) for x in rng.choice(num_brokers, size=rf,
                                           replace=False)]
        be.create_partition(f"t{p % 6}", p, reps,
                            size_mb=float(rng.uniform(10, 500)),
                            bytes_in_rate=float(rng.uniform(1, 50)),
                            bytes_out_rate=float(rng.uniform(1, 100)),
                            cpu_util=float(rng.uniform(0.1, 5)))
    return be


def _cfg(**over):
    props = {"anomaly.detection.interval.ms": 10_000_000,
             "goals": ",".join(GOALS),
             "hard.goals": "ReplicaCapacityGoal"}
    props.update(over)
    return cruise_control_config(props)


def _sample(cc, lo=0, hi=6):
    for i in range(lo, hi):
        cc.load_monitor.sample_once(now_ms=i * WINDOW_MS)


def _goal_sets(res):
    """(violated set, certificate rows, proposal rows) — the parity unit."""
    return (
        sorted(g.name for g in res.goal_results if g.violated_after),
        sorted((g.name, g.fixpoint_proven, g.moves_remaining,
                g.leads_remaining, g.swap_window_remaining)
               for g in res.goal_results),
        sorted((p.topic, p.partition, p.new_leader, p.new_replicas)
               for p in res.proposals))


def _build_fleet(prefix: str, seeds=SEEDS, **cfg_over):
    fleet = FleetScheduler(config=_cfg(**cfg_over))
    for s in seeds:
        t = fleet.add_tenant(f"{prefix}-{s}", backend=_backend(s),
                             config=_cfg(**cfg_over))
        _sample(t.cc)
    return fleet


@pytest.fixture(scope="module")
def engine3():
    """Three same-bucket tenants past their first admission round, with the
    K in {3, 2, 1} launch variants prewarmed so lane tests compile nothing."""
    fleet = _build_fleet("tenant")
    report = fleet.run_round(now_ms=T0)
    assert sorted(report["optimized"]) == sorted(
        f"tenant-{s}" for s in SEEDS), report
    for k in (2, 1):
        for s in SEEDS[:k]:
            fleet.enqueue(f"tenant-{s}", LANE_REFRESH, "prewarm",
                          now_ms=T0 + 1_000.0)
        d = fleet.dispatch_once(now_ms=T0 + 2_000.0)
        assert d is not None and len(d["admitted"]) == k, d
    yield fleet
    fleet.shutdown()


# ------------------------------------------------------ zero-pressure parity
def test_zero_pressure_bit_parity_vs_static_round():
    """Contract 1: no queue pressure => the admission round is the static
    round — same launches/optimized report, bit-identical installs."""
    fa = _build_fleet("par")                                  # admission on
    fb = _build_fleet("par", **{"fleet.admission.enabled": False})
    try:
        ra = fa.run_round(now_ms=T0)
        rb = fb.run_round(now_ms=T0)
        assert ra["launches"] == rb["launches"] == 1
        assert sorted(ra["buckets"]) == sorted(rb["buckets"])
        assert sorted(ra["optimized"]) == sorted(rb["optimized"])
        assert ra["skipped"] == rb["skipped"] == {}
        for s in SEEDS:
            a = fa.app_for(f"par-{s}").cached_proposals()
            b = fb.app_for(f"par-{s}").cached_proposals()
            assert _goal_sets(a) == _goal_sets(b), f"tenant {s}"
            for leaf in ("replica_broker", "replica_is_leader",
                         "replica_disk"):
                va = np.asarray(getattr(a.final_state, leaf))
                vb = np.asarray(getattr(b.final_state, leaf))
                assert np.array_equal(va, vb), f"tenant {s} {leaf}"
        # a second zero-pressure round skips everybody identically
        assert fa.run_round(now_ms=T0 + 100.0)["skipped"] \
            == fb.run_round(now_ms=T0 + 100.0)["skipped"]
    finally:
        fa.shutdown()
        fb.shutdown()


# --------------------------------------------------- admission determinism
def _scripted_drive(fleet, prefix: str) -> tuple[list[str], dict]:
    cids = [f"{prefix}-{s}" for s in SEEDS]
    fleet.max_batch = 2
    fleet.enqueue(cids[0], LANE_HEAL, "verdict", now_ms=T0 + 100.0)
    fleet.enqueue(cids[1], LANE_REBALANCE, "hygiene", now_ms=T0 + 200.0)
    fleet.enqueue(cids[2], LANE_REFRESH, "due", now_ms=T0 + 300.0)
    fleet.enqueue(cids[1], LANE_HEAL, "verdict", now_ms=T0 + 400.0)
    fleet.enqueue(cids[0], LANE_HEAL, "verdict dup", now_ms=T0 + 500.0)
    for _ in range(6):
        d = fleet.dispatch_once(now_ms=T0 + 1_000.0)
        if d is None or (d["launches"] == 0 and not d["failed"]):
            break
    lines = [ln for ln in fleet.journal.lines() if '"admission"' in ln]
    adm = fleet.admission_state_json()
    return lines, adm


def test_admission_deterministic_per_seed():
    """Contract 2: identical scripted streams into fresh fleets reproduce
    the admission journal and counters exactly; the Poisson driver's
    arrival stream is a pure function of its seed."""
    d7a = ServingLoadDriver(None, ["a", "b", "c"], seed=7)
    d7b = ServingLoadDriver(None, ["a", "b", "c"], seed=7)
    d8 = ServingLoadDriver(None, ["a", "b", "c"], seed=8)
    ev7a = d7a.arrivals(0.0, 120_000.0)
    assert ev7a == d7b.arrivals(0.0, 120_000.0)
    assert ev7a != d8.arrivals(0.0, 120_000.0)
    assert ev7a, "empty arrival stream"

    f1 = _build_fleet("det")
    f2 = _build_fleet("det")
    try:
        f1.run_round(now_ms=T0)
        f2.run_round(now_ms=T0)
        lines1, adm1 = _scripted_drive(f1, "det")
        lines2, adm2 = _scripted_drive(f2, "det")
        assert lines1 == lines2
        assert any('"ev":"coalesce"' in ln for ln in lines1)
        for key in ("enqueued", "coalesced", "admitted", "dispatches",
                    "queueDepth", "healAdmissionP95Ms"):
            assert adm1[key] == adm2[key], key
        assert adm1["queueDepth"] == 0
    finally:
        f1.shutdown()
        f2.shutdown()


# ------------------------------------------------------------ priority lanes
def test_heal_preempts_hygiene_preempts_refresh(engine3):
    """Contract 3: admission order is (lane, seq) — the LAST-enqueued heal
    dispatches first — and the prewarmed ladder keeps toggles compile-free."""
    fleet = engine3
    cids = [f"tenant-{s}" for s in SEEDS]
    old_k = fleet.max_batch
    listener = XlaCompileListener.install()
    c0 = listener.count
    try:
        fleet.max_batch = 1
        fleet.enqueue(cids[2], LANE_REFRESH, "due", now_ms=T0 + 10_000.0)
        fleet.enqueue(cids[1], LANE_REBALANCE, "hygiene",
                      now_ms=T0 + 11_000.0)
        fleet.enqueue(cids[0], LANE_HEAL, "verdict", now_ms=T0 + 12_000.0)
        order = []
        for _ in range(3):
            d = fleet.dispatch_once(now_ms=T0 + 13_000.0)
            order.extend(d["admitted"])
        assert order == [cids[0], cids[1], cids[2]]
        assert fleet.queue_depth() == 0
    finally:
        fleet.max_batch = old_k
    assert listener.count - c0 == 0, "lane/K toggle dispatches compiled"


# --------------------------------------------------------- mid-launch arrival
def test_mid_launch_arrival_rides_next_dispatch(engine3):
    """Contract 4: a request landing after a batch was admitted is picked
    up by the NEXT dispatch, not dropped and not joined retroactively."""
    fleet = engine3
    cids = [f"tenant-{s}" for s in SEEDS]
    old_k = fleet.max_batch
    try:
        fleet.max_batch = 2
        fleet.enqueue(cids[0], LANE_REFRESH, "due", now_ms=T0 + 20_000.0)
        fleet.enqueue(cids[1], LANE_REFRESH, "due", now_ms=T0 + 20_500.0)
        d1 = fleet.dispatch_once(now_ms=T0 + 21_000.0)
        assert sorted(d1["admitted"]) == sorted(cids[:2])
        # "mid-launch": lands while d1's batch installs
        fleet.enqueue(cids[2], LANE_HEAL, "verdict", now_ms=T0 + 21_500.0)
        assert fleet.queue_depth() == 1
        d2 = fleet.dispatch_once(now_ms=T0 + 22_000.0)
        assert d2["admitted"] == [cids[2]]
        assert fleet.queue_depth() == 0
    finally:
        fleet.max_batch = old_k


# ------------------------------------------------- pad-to-join vs split
def test_near_join_vs_split_both_sides_of_threshold():
    """Contract 5: below the pressure threshold NEAR buckets split-launch;
    at the threshold the smaller bucket's tenants pad-to-join the larger
    one and ride a single launch."""
    assert FleetScheduler.near_buckets(
        (1024, 16, 256, 16, 2, 1, 3), (1024, 20, 256, 16, 2, 1, 3))
    assert not FleetScheduler.near_buckets(      # tail differs: racks
        (1024, 16, 256, 16, 2, 1, 3), (1024, 20, 256, 16, 2, 1, 4))
    assert not FleetScheduler.near_buckets(      # > 2x on a padded dim
        (1024, 16, 256, 16, 2, 1, 3), (1024, 40, 256, 16, 2, 1, 3))

    fleet = FleetScheduler(
        config=_cfg(**{"fleet.admission.near.join.pressure": 3}))
    a, b = f"near-{SEEDS[0]}", f"near-{SEEDS[1]}"
    c = "near-wide"
    for cid, seed, brokers in ((a, SEEDS[0], 10), (b, SEEDS[1], 10),
                               (c, 24, 17)):     # 17 brokers -> B=20 bucket
        t = fleet.add_tenant(cid, backend=_backend(seed,
                                                   num_brokers=brokers),
                             config=_cfg())
        _sample(t.cc)
    try:
        for cid in (a, b, c):
            fleet.tenants[cid].session.sync()
        small = fleet.bucket_key(fleet.tenants[a].session)
        large = fleet.bucket_key(fleet.tenants[c].session)
        assert small[1] == 16 and large[1] == 20
        assert FleetScheduler.near_buckets(small, large)

        # below threshold (pressure 2 < 3): split-launch per bucket
        fleet.enqueue(a, LANE_REFRESH, "due", now_ms=T0)
        fleet.enqueue(c, LANE_REFRESH, "due", now_ms=T0 + 100.0)
        d1 = fleet.dispatch_once(now_ms=T0 + 1_000.0)
        assert d1["split"] is True and d1["joined"] == []
        assert d1["admitted"] == [a]
        d2 = fleet.dispatch_once(now_ms=T0 + 2_000.0)
        assert d2["admitted"] == [c]
        assert fleet.splits == 1 and fleet.joins == 0

        # at threshold (pressure 3): pad-to-join into the large bucket
        fleet.enqueue(a, LANE_REFRESH, "due", now_ms=T0 + 10_000.0)
        fleet.enqueue(b, LANE_REFRESH, "due", now_ms=T0 + 10_100.0)
        fleet.enqueue(c, LANE_REFRESH, "due", now_ms=T0 + 10_200.0)
        d3 = fleet.dispatch_once(now_ms=T0 + 11_000.0)
        assert d3["joined"] == sorted([a, b]), d3
        assert sorted(d3["admitted"]) == sorted([a, b, c])
        assert d3["launches"] == 1
        assert fleet.joins == 1
        # sticky floors: the joined tenants now LIVE in the large bucket
        for cid in (a, b):
            sess = fleet.tenants[cid].session
            assert sess.bucket_floors == {"min_replicas": large[0],
                                          "min_brokers": large[1],
                                          "min_partitions": large[2],
                                          "min_topics": large[3]}
            assert fleet.bucket_key(sess) == large
    finally:
        fleet.shutdown()


# ------------------------------------------------- launch-failure surfacing
def test_launch_failure_surfaced_and_heal_requeued(engine3):
    """Contract 6: a batched launch failure surfaces per tenant in the
    report's ``failed`` map; the heal request survives with a retry budget
    and installs on the next healthy dispatch."""
    fleet = engine3
    cid = f"tenant-{SEEDS[0]}"
    real = fleet.optimizer.optimizations_batched

    def boom(sessions, **kw):
        raise RuntimeError("injected launch failure")

    fleet.optimizer.optimizations_batched = boom
    try:
        fleet.enqueue(cid, LANE_HEAL, "verdict", now_ms=T0 + 30_000.0)
        fleet.enqueue(f"tenant-{SEEDS[1]}", LANE_REFRESH, "due",
                      now_ms=T0 + 30_100.0)
        d = fleet.dispatch_once(now_ms=T0 + 31_000.0)
        assert d["launches"] == 0
        assert d["failed"].get(cid) == "launch failed: RuntimeError"
        # heal re-enqueued (retries bumped); the refresh request dropped
        assert fleet.queue_depth() == 1
        req = fleet._requests[cid][LANE_HEAL]
        assert req.retries == 1
    finally:
        fleet.optimizer.optimizations_batched = real
    d = fleet.dispatch_once(now_ms=T0 + 32_000.0)
    assert d["admitted"] == [cid] and d["launches"] == 1
    assert fleet.queue_depth() == 0
