"""Warm standby controller: journal-tailing state mirror + census adoption.

The standby owns a full CruiseControl facade over the SAME backend as the
leader but with its own (empty) journal and no sample store of its own. It
stays warm by tailing two leader artifacts:

- the leader's **event journal** — in-process via ``EventJournal.tail()``
  (cursor = absolute event index) or cross-process via ``JournalTailer``
  (rotation-seam-safe file follower). Task-census rows ({"kind": "task"})
  accumulate into a per-execution-span mirror; an execution whose span-end
  event ({"kind": "span", "span_kind": "execution"}) never arrives is, by
  construction, the one the leader died inside.
- the leader's **FileSampleStore** JSONL files — replayed through the
  monitor's ``_ingest`` (the same store-replay path ``start_up`` uses), so
  the standby's aggregator windows are bit-identical to a monitor that
  loaded the same prefix (asserted at arbitrary offsets in tests/test_ha.py).

On promotion the standby re-drains both tails one final time, hands the
frozen census of the incomplete execution to ``Executor.adopt_census``
(in-flight moves resume mid-batch — zero failover aborts), and flips the
facade's role so REST writes open up.
"""
from __future__ import annotations

import json
import os

from cruise_control_tpu.common.tracing import JournalTailer
from cruise_control_tpu.monitor.sampling.sample_store import FileSampleStore
from cruise_control_tpu.monitor.sampling.samplers import (
    BrokerSample, PartitionSample, Samples,
)


class SampleTailer:
    """Incremental follower of a leader's FileSampleStore directory.

    Byte-offset based: each poll reads only the appended suffix of the two
    JSONL files, holding torn tail lines in a buffer until their newline
    arrives (the leader's appends are line-atomic but flushes are not)."""

    def __init__(self, path: str):
        self.path = path
        self._pos = {FileSampleStore.PARTITION_FILE: 0,
                     FileSampleStore.BROKER_FILE: 0}
        self._buf = {FileSampleStore.PARTITION_FILE: "",
                     FileSampleStore.BROKER_FILE: ""}

    def _read_new(self, fname: str) -> list:
        full = os.path.join(self.path, fname)
        try:
            with open(full, encoding="utf-8") as f:
                f.seek(self._pos[fname])
                chunk = f.read()
                self._pos[fname] = f.tell()
        except OSError:
            return []
        if not chunk:
            return []
        data = self._buf[fname] + chunk
        lines = data.split("\n")
        self._buf[fname] = lines.pop()
        return [ln for ln in lines if ln]

    def poll(self) -> Samples | None:
        """New complete sample rows since the last poll, or None."""
        psamples = []
        for ln in self._read_new(FileSampleStore.PARTITION_FILE):
            try:
                d = json.loads(ln)
            except json.JSONDecodeError:
                continue
            psamples.append(PartitionSample(topic=d["t"], partition=d["p"],
                                            ts_ms=d["ts"], values=d["v"]))
        bsamples = []
        for ln in self._read_new(FileSampleStore.BROKER_FILE):
            try:
                d = json.loads(ln)
            except json.JSONDecodeError:
                continue
            bsamples.append(BrokerSample(broker_id=d["b"], ts_ms=d["ts"],
                                         values=d["v"]))
        if not psamples and not bsamples:
            return None
        return Samples(psamples, bsamples)


class StandbyController:
    """Tick-driven warm standby over a fully-wired CruiseControl facade."""

    def __init__(self, cc, leader_journal=None,
                 leader_journal_path: str | None = None,
                 leader_sample_path: str | None = None, elector=None,
                 sync_interval_ms: float = 30_000.0):
        if leader_journal is None and leader_journal_path is None:
            raise ValueError("standby needs a leader journal to tail "
                             "(in-process object or file path)")
        self.cc = cc
        cc.ha = self
        self.elector = elector
        self._mem_journal = leader_journal
        self._cursor = 0              # EventJournal.tail absolute event index
        self._tailer = (JournalTailer(leader_journal_path)
                        if leader_journal is None else None)
        self._samples = (SampleTailer(leader_sample_path)
                         if leader_sample_path else None)
        # census mirror: execution-span id -> {plan index -> merged row}
        # (first row per index carries the proposal payload; later rows only
        # advance "st")
        self._census: dict = {}
        self._census_order: list = []
        self._ended_execs: set = set()
        self.events_seen = 0
        self.dropped_events = 0       # bounded-ring evictions (in-process)
        self.samples_replayed = 0
        self.role = "standby"
        self.promoted_ms: float | None = None
        self.adoption: dict | None = None
        self._sync_interval_ms = float(sync_interval_ms)
        self._last_sync_ms = -1e18
        cc.sensors.gauge("ha-journal-lag-events",
                         lambda: self.journal_lag_events())
        cc.sensors.gauge("ha-standby-events-seen", lambda: self.events_seen)

    # -------------------------------------------------------------- tailing
    def journal_lag_events(self) -> int:
        """Events the leader has journaled that this standby has not yet
        consumed (exact in-process; file followers report pending complete
        lines as 0 between polls — see ``pending_bytes`` in state_json)."""
        if self._mem_journal is not None:
            return max(int(self._mem_journal.events_appended) - self._cursor,
                       0)
        return 0

    def _drain_journal(self) -> int:
        if self._mem_journal is not None:
            self._cursor, lines, dropped = self._mem_journal.tail(self._cursor)
            self.dropped_events += dropped
        else:
            lines = self._tailer.poll()
        for ln in lines:
            self._consume(ln)
        return len(lines)

    def _consume(self, line: str) -> None:
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            return                      # torn tail write (file follower)
        self.events_seen += 1
        kind = rec.get("kind")
        if kind == "task":
            span = rec.get("span")
            rows = self._census.get(span)
            if rows is None:
                rows = self._census[span] = {}
                self._census_order.append(span)
            i = int(rec["i"])
            row = rows.get(i)
            if row is None:
                rows[i] = dict(rec)
            else:
                row["st"] = rec.get("st", row.get("st"))
        elif kind == "span" and rec.get("span_kind") == "execution":
            # the execution finished cleanly — a killed leader never
            # journals this, which is exactly how promote() finds the
            # execution to adopt
            self._ended_execs.add(rec.get("span"))

    def _replay_samples(self) -> int:
        if self._samples is None:
            return 0
        batch = self._samples.poll()
        if batch is None:
            return 0
        # _ingest is the store-replay path (start_up uses it): no timers, no
        # tracer noise — the standby's aggregators stay bit-identical to a
        # fresh monitor loading the same prefix
        n = self.cc.load_monitor._ingest(batch)
        self.samples_replayed += n
        return n

    # ----------------------------------------------------------------- tick
    def tick(self) -> dict:
        """One standby step: tail the journal, replay new samples, keep the
        resident session warm, and run the election. Returns the promote()
        result when this tick won the lease, and the demote() result when a
        PROMOTED instance's renewal was refused (it has been fenced).

        Ticking must continue after promotion: the leader role is only held
        while the lease keeps being renewed. A promoted instance that
        stopped ticking would let its lease lapse, hand the CAS to any other
        contender (a restarted old leader, a third node), and keep accepting
        writes with role=='leader' — exactly the split brain the lease
        exists to prevent."""
        drained = self._drain_journal()
        replayed = self._replay_samples()
        sess = self.cc.resident_session
        now = float(self.cc.backend.now_ms())
        if (self.role == "standby" and sess is not None
                and now - self._last_sync_ms >= self._sync_interval_ms):
            # warmth is a STANDBY concern; once promoted the live control
            # loop owns the session's sync cadence
            self._last_sync_ms = now
            try:
                sess.sync()
            except Exception:
                # warmth is best-effort pre-promotion (the monitor may not
                # have enough windows yet); correctness is asserted on the
                # monitor/optimizer inputs, not on early sync attempts
                pass
        if self.elector is not None:
            if self.role == "standby":
                if self.elector.tick() == "leader":
                    return self.promote()
            elif self.elector.tick() != "leader":
                # refused renewal: someone else won the CAS while this
                # instance held the role (e.g. it froze past the TTL) —
                # step down, never split-brain
                return self.demote()
        return {"promoted": False, "events": drained, "samples": replayed}

    # -------------------------------------------------------------- takeover
    def _incomplete_execution(self):
        """Latest execution span with census rows but no span-end event —
        the one the dead leader was inside. Returns (found, span_id)."""
        for span in reversed(self._census_order):
            if span in self._ended_execs:
                continue
            rows = self._census[span]
            if any(r.get("st") in ("PENDING", "IN_PROGRESS")
                   for r in rows.values()):
                return True, span
        return False, None

    def promote(self) -> dict:
        """Take over: final tail catch-up, adopt the frozen census (zero
        aborts — in-flight moves resume mid-batch), flip the role."""
        self._drain_journal()
        self._replay_samples()
        self.role = "leader"
        self.promoted_ms = float(self.cc.backend.now_ms())
        self.cc.journal.append("ha", ev="promoted",
                               holder=getattr(self.elector, "holder", None),
                               epoch=getattr(self.elector, "epoch", None))
        adoption = None
        found, span = self._incomplete_execution()
        if found:
            # rows tailed from mid-execution offsets may lack the proposal
            # payload (initial PENDING row already evicted); only payloaded
            # rows are adoptable — a standby that tailed from the start
            # always has all of them
            records = [dict(r) for r in self._census[span].values()
                       if "ol" in r]
            if records:
                adoption = self.cc.executor.adopt_census(
                    records,
                    context={"operation": "failover census adoption"})
        self.adoption = adoption
        return {"promoted": True, "adoption": adoption}

    def demote(self) -> dict:
        """Step down after being fenced: a refused renewal means another
        contender now holds the lease. Writes close immediately (the
        facade's role gate reads ``self.role``) and the executor stops
        GRACEFULLY — no further task submissions, but in-flight backend
        moves are left for the new leader to adopt from the census, not
        cancelled out from under it."""
        self.role = "standby"
        self.promoted_ms = None
        self.cc.executor.stop_execution(force=False)
        lease = (self.elector.lease or {}) if self.elector is not None else {}
        self.cc.journal.append("ha", ev="demoted",
                               holder=getattr(self.elector, "holder", None),
                               to=lease.get("holder"),
                               epoch=lease.get("epoch"))
        return {"promoted": False, "demoted": True}

    def retry_after_s(self) -> float:
        if self.elector is not None:
            return self.elector.retry_after_s()
        return 1.0

    def state_json(self) -> dict:
        out = {"role": self.role, "eventsSeen": self.events_seen,
               "droppedEvents": self.dropped_events,
               "journalLagEvents": self.journal_lag_events(),
               "samplesReplayed": self.samples_replayed,
               "promotedMs": self.promoted_ms, "adoption": self.adoption,
               "lease": (self.elector.state_json()
                         if self.elector is not None else None)}
        if self._tailer is not None:
            out["pendingBytes"] = self._tailer.pending_bytes()
        return out
