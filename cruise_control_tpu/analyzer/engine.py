"""The greedy optimization engine: masked-argmax action loop under jit.

This replaces the reference's quadruple-nested sequential scan
(AbstractGoal.java:98-103 `while(!finished) for broker: rebalanceForBroker`,
e.g. ResourceDistributionGoal.java:384-862: per sorted replica x sorted
candidate broker, legitMove -> selfSatisfied -> acceptance over previously
optimized goals -> mutate) with a vectorized loop:

    while progress and not done:
        1. severity  = goal.broker_severity(state)            f32[B]
        2. cand      = top_k(goal.replica_key(state), K)      i32[K]
        3. score     = goal.move_score(state, cand)           f32[K, B]
                       & legit_move_mask & AND(prev.accept_move)
        4. (leadership variant when the goal moves leadership)
        5. best      = argmax(score); apply if score > 0      scatter update

One iteration = one applied action (replica move or leadership transfer), but
every candidate x destination pair in the cluster was scored to choose it —
the per-iteration work is a handful of fused [K, B] kernels regardless of
cluster size, which is what makes 7k-broker clusters tractable on TPU.

Scores are construct-positive gains: each goal defines score as the strict
decrease of its violation measure, so total violation is monotonically
decreasing and the loop cannot cycle (the tensor analogue of the reference's
stats-comparator monotonicity assertion, AbstractGoal.java:110-119).

Offline (dead-broker / dead-disk) replicas are priority candidates
(replica_key +1e12) and goals relax their own balance limits for them,
mirroring the reference's fix-offline-first behavior and
_fixOfflineReplicasOnly relaxation (ReplicaDistributionAbstractGoal.java:31).
"""
from __future__ import annotations

import dataclasses
from functools import lru_cache, partial

import jax
import jax.numpy as jnp

from cruise_control_tpu.analyzer.env import ClusterEnv
from cruise_control_tpu.analyzer.goals.base import (
    GoalKernel, legit_leadership_mask, legit_move_mask, legit_swap_mask,
)
from cruise_control_tpu.analyzer.state import (
    EngineState, apply_leadership, apply_move, apply_swap,
)

Array = jax.Array
NEG_INF = -jnp.inf


@dataclasses.dataclass(frozen=True)
class EngineParams:
    max_iters: int = 4096
    num_candidates: int = 64          # K: replica-move candidates per iteration
    num_leader_candidates: int = 32   # KL: leadership candidates per iteration
    num_swap_candidates: int = 32     # K1/K2: swap-out / swap-in candidates
    min_gain: float = 1e-9            # scores below this count as no progress
    batch_moves: bool = True          # apply many non-conflicting moves per scoring pass


def _move_branch(env: ClusterEnv, st: EngineState, goal: GoalKernel,
                 prev_goals: tuple, params: EngineParams, severity: Array):
    key = goal.replica_key(env, st, severity)
    kv, cand = jax.lax.top_k(key, min(params.num_candidates, env.num_replicas))
    mask = legit_move_mask(env, st, cand, goal.options)
    for g in prev_goals:
        mask = mask & g.accept_move(env, st, cand)
    score = goal.move_score(env, st, cand)
    score = jnp.where(mask & (kv > NEG_INF)[:, None], score, NEG_INF)
    flat = jnp.argmax(score)
    k, b = jnp.unravel_index(flat, score.shape)
    return score.reshape(-1)[flat], cand[k], jnp.asarray(b, jnp.int32)


def _leadership_branch(env: ClusterEnv, st: EngineState, goal: GoalKernel,
                       prev_goals: tuple, params: EngineParams, severity: Array):
    lkey = goal.leader_key(env, st, severity)
    lkv, lcand = jax.lax.top_k(lkey, min(params.num_leader_candidates, env.num_replicas))
    lmask = legit_leadership_mask(env, st, lcand)
    for g in prev_goals:
        lmask = lmask & g.accept_leadership(env, st, lcand)
    lscore = goal.leadership_score(env, st, lcand)
    lscore = jnp.where(lmask & (lkv > NEG_INF)[:, None], lscore, NEG_INF)
    flat = jnp.argmax(lscore)
    k, f = jnp.unravel_index(flat, lscore.shape)
    dst_replica = env.partition_replicas[env.replica_partition[lcand[k]], f]
    return lscore.reshape(-1)[flat], lcand[k], jnp.clip(dst_replica, 0)


def _move_branch_batched(env: ClusterEnv, st: EngineState, goal: GoalKernel,
                         prev_goals: tuple, params: EngineParams, severity: Array):
    """Score once, apply MANY moves: the scored [K, B] matrix is reused for up
    to K independent moves under three conflict rules — at most one move out
    of any source broker, one into any destination broker, and one per
    partition. Under those rules every accepted move's scored feasibility and
    acceptance stay exact (balance limits depend only on cluster totals, which
    moves preserve; per-broker state changes by at most the one scored move).
    This is the main lever that turns ~N sequential scoring passes into
    ~N/K passes at 7k-broker scale."""
    key = goal.replica_key(env, st, severity)
    kv, cand = jax.lax.top_k(key, min(params.num_candidates, env.num_replicas))
    mask = legit_move_mask(env, st, cand, goal.options)
    for g in prev_goals:
        mask = mask & g.accept_move(env, st, cand)
    score = goal.move_score(env, st, cand)
    score = jnp.where(mask & (kv > NEG_INF)[:, None], score, NEG_INF)

    K = score.shape[0]
    best_dst = jnp.argmax(score, axis=1).astype(jnp.int32)          # [K]
    best_val = jnp.max(score, axis=1)                               # [K]
    order = jnp.argsort(-best_val)                                  # best first

    def body(i, carry):
        st, used_src, used_dst, used_part, n_applied = carry
        k = order[i]
        r = cand[k]
        d = best_dst[k]
        v = best_val[k]
        src = st.replica_broker[r]
        p = env.replica_partition[r]
        ok = ((v > params.min_gain) & ~used_src[src] & ~used_dst[d]
              & ~used_part[p])
        st = jax.lax.cond(ok, lambda s: apply_move(env, s, r, d), lambda s: s, st)
        used_src = used_src.at[src].set(used_src[src] | ok)
        used_dst = used_dst.at[d].set(used_dst[d] | ok)
        used_part = used_part.at[p].set(used_part[p] | ok)
        return st, used_src, used_dst, used_part, n_applied + ok.astype(jnp.int32)

    B = env.num_brokers
    init = (st, jnp.zeros(B, bool), jnp.zeros(B, bool),
            jnp.zeros(env.num_partitions, bool), jnp.int32(0))
    st, _, _, _, n_applied = jax.lax.fori_loop(0, K, body, init)
    return st, n_applied


def _swap_branch(env: ClusterEnv, st: EngineState, goal: GoalKernel,
                 prev_goals: tuple, params: EngineParams, severity: Array):
    k = min(params.num_swap_candidates, env.num_replicas)
    okey = goal.swap_out_key(env, st, severity)
    ikey = goal.swap_in_key(env, st, severity)
    okv, cand_out = jax.lax.top_k(okey, k)
    ikv, cand_in = jax.lax.top_k(ikey, k)
    mask = legit_swap_mask(env, st, cand_out, cand_in)
    for g in prev_goals:
        mask = mask & g.accept_swap(env, st, cand_out, cand_in)
    score = goal.swap_score(env, st, cand_out, cand_in)
    score = jnp.where(mask & (okv > NEG_INF)[:, None] & (ikv > NEG_INF)[None, :],
                      score, NEG_INF)
    flat = jnp.argmax(score)
    i, j = jnp.unravel_index(flat, score.shape)
    return score.reshape(-1)[flat], cand_out[i], cand_in[j]


def optimize_goal(env: ClusterEnv, st: EngineState, goal: GoalKernel,
                  prev_goals: tuple = (), params: EngineParams = EngineParams()):
    """Run one goal to completion. Returns (state, info dict)."""
    fn = _compiled_optimize(type(goal), goal, tuple(prev_goals), params)
    return fn(env, st)


@lru_cache(maxsize=256)
def _compiled_optimize(goal_cls, goal: GoalKernel, prev_goals: tuple, params: EngineParams):
    """Build + cache the jitted loop for a (goal, prev_goals, params) combo.

    Goals are frozen dataclasses, hashable by value, so the cache key is the
    full static configuration — the analogue of GoalOptimizer's per-goal
    setup, paid once per goal config per process.
    """
    del goal_cls  # participates in the cache key only

    @jax.jit
    def run(env: ClusterEnv, st: EngineState):
        def step(carry):
            st, it, n_applied, _progress = carry
            severity = goal.broker_severity(env, st)

            n_moves = jnp.int32(0)
            if goal.uses_replica_moves and params.batch_moves:
                st_moved, n_moves = _move_branch_batched(env, st, goal, prev_goals,
                                                         params, severity)
            elif goal.uses_replica_moves:
                mscore, mrep, mdst = _move_branch(env, st, goal, prev_goals,
                                                  params, severity)
                do_move = jnp.asarray(mscore, jnp.float32) > params.min_gain
                st_moved = jax.lax.cond(do_move,
                                        lambda s: apply_move(env, s, mrep, mdst),
                                        lambda s: s, st)
                n_moves = do_move.astype(jnp.int32)
            else:
                st_moved = st

            # leadership/swap scores were computed against the pre-move state,
            # so they only apply when no replica move landed this pass
            if goal.uses_leadership_moves:
                lscore, lsrc, ldst = _leadership_branch(env, st, goal, prev_goals,
                                                        params, severity)
            else:
                lscore, lsrc, ldst = NEG_INF, jnp.int32(0), jnp.int32(0)
            if goal.uses_swaps:
                sscore, sout, sin_ = _swap_branch(env, st, goal, prev_goals,
                                                  params, severity)
            else:
                sscore, sout, sin_ = NEG_INF, jnp.int32(0), jnp.int32(0)

            lscore = jnp.asarray(lscore, jnp.float32)
            sscore = jnp.asarray(sscore, jnp.float32)
            no_move = n_moves == 0
            do_lead = no_move & (lscore >= sscore) & (lscore > params.min_gain)
            do_swap = no_move & (~do_lead) & (sscore > params.min_gain)

            st = jax.lax.cond(
                do_lead,
                lambda s: apply_leadership(env, s, lsrc, ldst),
                lambda s: jax.lax.cond(
                    do_swap,
                    lambda s2: apply_swap(env, s2, sout, sin_),
                    lambda s2: s2, s),
                st_moved)
            applied = n_moves + do_lead.astype(jnp.int32) + do_swap.astype(jnp.int32)
            progress = applied > 0
            return st, it + 1, n_applied + applied, progress

        def cond_fn(carry):
            _st, it, _n, progress = carry
            return progress & (it < params.max_iters)

        st, _iters, n_applied, _ = jax.lax.while_loop(
            cond_fn, step, (st, jnp.int32(0), jnp.int32(0), jnp.bool_(True)))
        violated = goal.violated(env, st)
        return st, {"iterations": n_applied, "violated_after": violated,
                    "stat": goal.stat(env, st)}

    return run
