"""Device-mesh sharding of the optimization engine.

The reference scales by threading on one JVM (SURVEY §2.10); the TPU-native
scale-out axis is the candidate-destination (broker) dimension: every
per-iteration kernel in the engine is either

- [B]- or [B, M]-shaped broker state (utilization, counts, limits),
- [K, B] candidate x destination score/mask matrices, or
- [R]-shaped replica state reduced into broker bins via segment ops,

so sharding the broker axis across a 1-D ``Mesh(("brokers",))`` splits the
scoring work and state while XLA inserts the collectives (argmax over the
sharded axis becomes a cross-device reduce; scatter updates stay local to the
owning shard).

REPLICA-axis leaves (the [R]-shaped load rows, assignment, candidate keys)
shard along the SAME 1-D device axis: per-replica key computation and the
packed broker-table gathers run on local shards (the broker tables are
small and replicated), segment-sums into broker bins become per-shard
partials + cross-device reduce (psum / reduce_scatter, inserted by GSPMD),
and top-k over the sharded replica axis lowers to per-shard top-k + a
cross-device merge. At the 7k-broker / 1M-replica north star this splits
the ~44 MB of per-replica state and the dominant O(R) key work n ways
instead of replicating it.

This module only *places* data: the engine code is unchanged — jit propagates
input shardings through the whole while_loop (GSPMD), which is exactly the
"annotate shardings, let XLA insert collectives" recipe.

NOTE (PR 9): this GSPMD placement is now the LEGACY mode (``tpu.shard.map``
off). The default multichip path is the SHARD-EXPLICIT engine in
``shard_ops.py`` — broker state replicated, the engine's candidate/replica
row axes shard_map'd, one small all-gather per admission wave — whose results
are bit-identical to the single-device program (GSPMD's inserted float
reductions are only semantically equivalent). The placement maps below stay
the single source of truth for which leaves carry a replica axis; the
shard-explicit keying reuses them for its in_specs.
"""
from __future__ import annotations

import dataclasses

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cruise_control_tpu.analyzer.env import ClusterEnv
from cruise_control_tpu.analyzer.state import EngineState

BROKER_AXIS = "brokers"

# env leaves sharded along their broker dimension (axis index given)
_ENV_BROKER_AXES = {
    "broker_capacity": 0, "broker_rack": 0, "broker_alive": 0, "broker_new": 0,
    "broker_demoted": 0, "broker_excluded_for_replica_move": 0,
    "broker_excluded_for_leadership": 0, "broker_disk_capacity": 0,
    "broker_disk_alive": 0, "dst_candidate": 0,
}
_STATE_BROKER_AXES = {
    "util": 0, "leader_util": 0, "potential_nw_out": 0, "replica_count": 0,
    "leader_count": 0, "topic_broker_count": 1, "topic_leader_count": 1,
    "disk_util": 0, "util_residual": 0, "leader_util_residual": 0,
}
# replica-dim leaves sharded along the same device axis
_ENV_REPLICA_AXES = {
    "leader_load": 0, "follower_load": 0, "replica_partition": 0,
    "replica_topic": 0, "replica_topic_excluded": 0, "replica_valid": 0,
    "replica_original_broker": 0,
}
_STATE_REPLICA_AXES = {
    "replica_broker": 0, "replica_is_leader": 0, "replica_offline": 0,
    "replica_disk": 0, "moved": 0, "leadership_moved": 0,
}


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (BROKER_AXIS,))


def _spec_for(ndim: int, axis: int | None) -> P:
    if axis is None:
        return P()
    parts = [None] * ndim
    parts[axis] = BROKER_AXIS
    return P(*parts)


def _place(obj, axes_map: dict, mesh: Mesh):
    updates = {}
    for f in dataclasses.fields(obj):
        val = getattr(obj, f.name)
        if not hasattr(val, "ndim"):
            continue
        axis = axes_map.get(f.name)
        sharding = NamedSharding(mesh, _spec_for(val.ndim, axis))
        updates[f.name] = jax.device_put(val, sharding)
    return dataclasses.replace(obj, **updates)


def pad_brokers(ct_arrays_factory, num_brokers: int, multiple: int) -> int:
    """Brokers must pad to a multiple of the mesh size; dead padded brokers
    are invisible to every goal (alive=False, capacity=0)."""
    rem = num_brokers % multiple
    return num_brokers if rem == 0 else num_brokers + (multiple - rem)


def _axes_maps(shard_replicas: bool) -> tuple[dict, dict]:
    """(env_axes, state_axes) for a placement — single source of truth for
    shard_cluster and per_device_bytes."""
    env_axes = dict(_ENV_BROKER_AXES)
    st_axes = dict(_STATE_BROKER_AXES)
    if shard_replicas:
        env_axes.update(_ENV_REPLICA_AXES)
        st_axes.update(_STATE_REPLICA_AXES)
    return env_axes, st_axes


def shard_cluster(env: ClusterEnv, st: EngineState, mesh: Mesh,
                  shard_replicas: bool = True):
    """Place (env, state) on the mesh: broker-dim leaves sharded along the
    device axis, replica-dim leaves likewise (``shard_replicas=False`` keeps
    the v1 replicated-replica placement), everything else replicated. Broker
    and replica counts must divide evenly by the mesh size (the shape
    buckets of pad_cluster make the replica axis a multiple of 8)."""
    B = env.num_brokers
    n = mesh.devices.size
    if B % n != 0:
        raise ValueError(f"num_brokers={B} must be a multiple of mesh size {n}; "
                         f"pad the cluster with dead brokers (pad_brokers)")
    if shard_replicas and env.num_replicas % n != 0:
        raise ValueError(f"num_replicas={env.num_replicas} must be a "
                         f"multiple of mesh size {n} (use pad_cluster)")
    env_axes, st_axes = _axes_maps(shard_replicas)
    env_s = _place(env, env_axes, mesh)
    st_s = _place(st, st_axes, mesh)
    return env_s, st_s


def per_device_bytes(env: ClusterEnv, st: EngineState, mesh: Mesh,
                     shard_replicas: bool = True) -> dict:
    """Analytic per-device memory footprint of the placed (env, state):
    sharded leaves contribute nbytes / mesh-size, replicated leaves their
    full size. Returns {"sharded": ..., "replicated": ..., "total": ...}."""
    n = mesh.devices.size
    env_axes, st_axes = _axes_maps(shard_replicas)
    sharded = replicated = 0
    for obj, axes in ((env, env_axes), (st, st_axes)):
        for f in dataclasses.fields(obj):
            v = getattr(obj, f.name)
            if not hasattr(v, "nbytes"):
                continue
            if f.name in axes:
                sharded += v.nbytes // n
            else:
                replicated += v.nbytes
    return {"sharded": sharded, "replicated": replicated,
            "total": sharded + replicated}


def replicate(tree, mesh: Mesh):
    return jax.device_put(tree, NamedSharding(mesh, P()))


# ---------------------------------------------------------------------------
# multichip evidence helpers (dryrun_multichip / tools/shard_ab.py)
# ---------------------------------------------------------------------------
_COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                   "collective-permute", "all-to-all")


def count_collectives(hlo_text: str) -> dict:
    """{op: count} of collective-instruction DEFINITIONS in a compiled
    module's optimized HLO (``compiled.as_text()``) — the measured evidence
    that the shard-explicit engine's cross-device traffic is the handful of
    small all-gathers/reduces it claims, not a GSPMD surprise. ``-start``
    variants count, ``-done`` halves don't (one op, two instructions)."""
    import re
    counts = {op: 0 for op in _COLLECTIVE_OPS}
    defn = re.compile(
        r"=\s+\S+\s+(" + "|".join(re.escape(op) for op in _COLLECTIVE_OPS)
        + r")(-start)?\(")
    for line in hlo_text.splitlines():
        m = defn.search(line)
        if m:
            counts[m.group(1)] += 1
    counts["total"] = sum(counts.values())
    return counts


def committed_per_device_bytes(tree) -> dict:
    """{device_id: bytes} actually resident per device for a pytree of
    committed jax.Arrays (``addressable_shards`` metadata only — no sync, no
    copies). Replicated leaves count fully on every device; sharded leaves
    count their shard — the honest per-device footprint of whatever
    placement (GSPMD-sharded or shard-explicit replicated) is in use."""
    per = {}
    for leaf in jax.tree_util.tree_leaves(tree):
        if not hasattr(leaf, "addressable_shards"):
            continue
        for shard in leaf.addressable_shards:
            d = shard.device.id
            per[d] = per.get(d, 0) + int(np.prod(shard.data.shape)
                                         * shard.data.dtype.itemsize)
    return per
