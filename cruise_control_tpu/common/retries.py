"""Fault tolerance at the backend boundary: retries + circuit breakers.

The reference survives flaky admin RPCs because every backend call sits
behind ``AdminClient`` request timeouts with retries and the executor's
progress loop simply re-polls (SURVEY §2.7-2.9); our port terminated the RPC
sidecar permanently on one timeout and had no retry path for a failed
movement submission. This module is the unified layer both gaps wire into:

- :class:`RetryPolicy` — exponential backoff with jitter. Deterministic by
  construction: the jitter comes from an *injected* ``random.Random`` and
  elapsed time from an *injected* clock, so the simulated chaos campaigns
  (sim/campaign.py, sim/api_fuzz.py) keep their bit-identical
  (scenario, seed) timelines with the retry layer live.
- :class:`CircuitBreaker` — the classic CLOSED -> OPEN -> HALF_OPEN state
  machine per *operation class* ("executor.submit", "executor.verify",
  "monitor.sample", ...). ``backend.circuit.failure.threshold`` consecutive
  failures open the circuit; after ``backend.circuit.reset.timeout.ms`` a
  bounded number of HALF_OPEN probes may test the backend, and one success
  closes it again.
- :class:`BackendFaultTolerance` — the facade the executor / monitor / app
  share: ``call(op_class, fn, ...)`` retries transient failures under the
  policy, trips the class' breaker on sustained failure, raises
  :class:`CircuitOpenError` without touching the backend while OPEN, and
  lands every attempt/trip in the sensor registry (``*-backend-retries``,
  ``*-backend-failures`` meters + ``backend-circuit-*-state`` gauges), so
  the PR-6 flight recorder / ``GET /metrics`` surface the layer's health.

Degradation contract (consumed by app.py / api/server.py): while any
breaker is OPEN the service is *degraded* — reads serve the resident
session's cached proposals flagged ``stale: true``, writes surface
:class:`ServiceUnavailableError` (HTTP 503 + Retry-After), and the anomaly
detector defers FIX verdicts instead of burning consecutive failures.
"""
from __future__ import annotations

import dataclasses
import random
import threading


# Deterministic request REJECTIONS (validation errors): retrying cannot
# change the outcome and they say nothing about backend health, so the call
# wrapper re-raises them immediately without touching the breaker — the
# executor aborts the execution like the pre-retry-layer behavior instead of
# pausing forever on an invalid move.
NON_RETRYABLE_ERRORS = (ValueError, KeyError, TypeError)


class CircuitOpenError(Exception):
    """The operation class' circuit is OPEN: the backend was not called."""

    def __init__(self, op_class: str, retry_after_ms: float):
        super().__init__(
            f"circuit for {op_class!r} is open; retry in "
            f"{max(retry_after_ms, 0.0):.0f} ms")
        self.op_class = op_class
        self.retry_after_ms = max(retry_after_ms, 0.0)


class ServiceUnavailableError(Exception):
    """Degraded mode: the operation is rejected, retry later (HTTP 503)."""

    def __init__(self, message: str, retry_after_s: float = 30.0):
        super().__init__(message)
        self.retry_after_s = max(retry_after_s, 1.0)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff + jitter (backend.retry.* keys)."""
    max_attempts: int = 4
    base_backoff_ms: float = 100.0
    max_backoff_ms: float = 10_000.0
    jitter: float = 0.2          # symmetric fraction of the backoff

    @classmethod
    def from_config(cls, config) -> "RetryPolicy":
        if config is None:
            return cls()
        return cls(
            max_attempts=config.get_int("backend.retry.max.attempts"),
            base_backoff_ms=float(config.get_int("backend.retry.base.backoff.ms")),
            max_backoff_ms=float(config.get_int("backend.retry.max.backoff.ms")),
            jitter=config.get_double("backend.retry.jitter"))

    def backoff_ms(self, failure_count: int, rng: random.Random) -> float:
        """Backoff before retry number ``failure_count`` (1-based)."""
        base = min(self.base_backoff_ms * (2.0 ** max(failure_count - 1, 0)),
                   self.max_backoff_ms)
        if self.jitter <= 0:
            return base
        return base * (1.0 + self.jitter * (2.0 * rng.random() - 1.0))


class CircuitBreaker:
    """CLOSED -> OPEN -> HALF_OPEN per operation class (backend.circuit.*)."""

    CLOSED = "CLOSED"
    OPEN = "OPEN"
    HALF_OPEN = "HALF_OPEN"

    def __init__(self, op_class: str, failure_threshold: int = 5,
                 reset_timeout_ms: float = 60_000.0, half_open_probes: int = 1,
                 clock_ms=None):
        self.op_class = op_class
        self._threshold = max(failure_threshold, 1)
        self._reset_timeout_ms = reset_timeout_ms
        self._max_probes = max(half_open_probes, 1)
        self._clock_ms = clock_ms or (lambda: 0.0)
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._consecutive_failures = 0
        self._opened_ms = -1.0
        self._probes_in_flight = 0
        self.open_count = 0          # lifetime trips (sensor + test surface)
        # optional observer ``(op_class, old_state, new_state)`` — the
        # fault-tolerance facade journals every transition through it
        # (called OUTSIDE the breaker lock, after the transition landed)
        self.on_transition = None

    def _set_state(self, new: str) -> tuple | None:
        """Caller holds the lock; returns the (old, new) transition to flush
        through ``on_transition`` after release, or None."""
        old = self._state
        if old == new:
            return None
        self._state = new
        return (old, new)

    def _flush(self, *transitions) -> None:
        """Fire the observer for each real transition, lock NOT held."""
        hook = self.on_transition
        if hook is None:
            return
        for t in transitions:
            if t is not None:
                try:
                    hook(self.op_class, t[0], t[1])
                except Exception:  # noqa: BLE001 — observers must never break a call
                    import logging
                    logging.getLogger(__name__).exception(
                        "breaker transition observer failed")

    @property
    def state(self) -> str:
        # surface the time-based OPEN -> HALF_OPEN transition on read
        with self._lock:
            t = self._maybe_half_open()
            out = self._state
        self._flush(t)
        return out

    def _maybe_half_open(self) -> tuple | None:
        """Caller holds the lock."""
        if (self._state == self.OPEN
                and self._clock_ms() - self._opened_ms >= self._reset_timeout_ms):
            self._probes_in_flight = 0
            return self._set_state(self.HALF_OPEN)
        return None

    def allow(self) -> bool:
        """May the caller attempt the backend right now? HALF_OPEN admits at
        most ``backend.circuit.half.open.probes`` concurrent probes."""
        with self._lock:
            t = self._maybe_half_open()
            if self._state == self.CLOSED:
                out = True
            elif self._state == self.HALF_OPEN:
                if self._probes_in_flight < self._max_probes:
                    self._probes_in_flight += 1
                    out = True
                else:
                    out = False
            else:
                out = False
        self._flush(t)
        return out

    def retry_after_ms(self) -> float:
        with self._lock:
            if self._state != self.OPEN:
                return 0.0
            return max(self._opened_ms + self._reset_timeout_ms
                       - self._clock_ms(), 0.0)

    def on_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            self._probes_in_flight = 0
            t = self._set_state(self.CLOSED)
        self._flush(t)

    def on_failure(self) -> None:
        t2 = None
        with self._lock:
            t1 = self._maybe_half_open()
            self._consecutive_failures += 1
            if self._state == self.HALF_OPEN:
                # a failed probe re-opens immediately (and restarts the timer)
                t2 = self._set_state(self.OPEN)
                self._opened_ms = self._clock_ms()
                self.open_count += 1
                self._probes_in_flight = 0
            elif (self._state == self.CLOSED
                    and self._consecutive_failures >= self._threshold):
                t2 = self._set_state(self.OPEN)
                self._opened_ms = self._clock_ms()
                self.open_count += 1
        self._flush(t1, t2)

    def to_json(self) -> dict:
        return {"opClass": self.op_class, "state": self.state,
                "consecutiveFailures": self._consecutive_failures,
                "openCount": self.open_count,
                "retryAfterMs": round(self.retry_after_ms(), 1)}


_STATE_GAUGE = {CircuitBreaker.CLOSED: 0, CircuitBreaker.HALF_OPEN: 1,
                CircuitBreaker.OPEN: 2}


class BackendFaultTolerance:
    """Shared retry + breaker facade for every backend-boundary caller.

    One instance per CruiseControl app: the executor, monitor and facade all
    consult the SAME breakers, so a backend outage observed by the executor
    degrades REST serving too. ``clock_ms`` is the backend clock (simulated
    in sims), ``rng`` seeds deterministically per instance.
    """

    def __init__(self, config=None, clock_ms=None, sensors=None,
                 rng: random.Random | None = None, journal=None):
        # durable event journal (common/tracing.EventJournal): every breaker
        # state transition lands as a {"kind": "breaker"} event — the
        # anomaly->heal lineage can then explain WHY a fix deferred
        self._journal = journal
        self.policy = RetryPolicy.from_config(config)
        self._failure_threshold = (config.get_int(
            "backend.circuit.failure.threshold") if config is not None else 5)
        self._reset_timeout_ms = float(config.get_int(
            "backend.circuit.reset.timeout.ms")) if config is not None \
            else 60_000.0
        self._half_open_probes = (config.get_int(
            "backend.circuit.half.open.probes") if config is not None else 1)
        self._clock_ms = clock_ms or (lambda: 0.0)
        self._sensors = sensors
        # string-seeded: deterministic across processes (PYTHONHASHSEED-free)
        self._rng = rng or random.Random("backend-fault-tolerance")
        self._lock = threading.Lock()
        self._breakers: dict[str, CircuitBreaker] = {}

    def breaker(self, op_class: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(op_class)
            if br is None:
                br = CircuitBreaker(
                    op_class, failure_threshold=self._failure_threshold,
                    reset_timeout_ms=self._reset_timeout_ms,
                    half_open_probes=self._half_open_probes,
                    clock_ms=self._clock_ms)
                self._breakers[op_class] = br
                if self._journal is not None:
                    journal = self._journal

                    def on_transition(op, old, new):
                        journal.append("breaker", op=op, frm=old, to=new)
                    br.on_transition = on_transition
                if self._sensors is not None:
                    self._sensors.gauge(
                        f"backend-circuit-{op_class}-state",
                        lambda b=br: _STATE_GAUGE[b.state])
            return br

    def _meter(self, name: str):
        if self._sensors is not None:
            self._sensors.meter(name).mark()

    def call(self, op_class: str, fn, *args, sleep_ms=None, **kwargs):
        """Run ``fn(*args, **kwargs)`` under the class' retry + breaker.

        ``sleep_ms``: callable honoring the backoff between attempts (the
        executor passes its injected clock's ``sleep_ms`` so sim campaigns
        back off in simulated time); ``None`` retries immediately — right
        for periodic callers (sampling) that must not stall their round.

        Raises :class:`CircuitOpenError` without calling when the breaker is
        OPEN, or the last exception once ``backend.retry.max.attempts`` is
        exhausted (the breaker accumulates the failures either way).
        """
        br = self.breaker(op_class)
        if not br.allow():
            self._meter(f"{op_class}-backend-rejections")
            raise CircuitOpenError(op_class, br.retry_after_ms())
        failures = 0
        while True:
            try:
                result = fn(*args, **kwargs)
            except NON_RETRYABLE_ERRORS:
                raise
            except Exception:
                failures += 1
                br.on_failure()
                self._meter(f"{op_class}-backend-failures")
                if failures >= self.policy.max_attempts or not br.allow():
                    raise
                self._meter(f"{op_class}-backend-retries")
                if sleep_ms is not None:
                    sleep_ms(self.policy.backoff_ms(failures, self._rng))
                continue
            br.on_success()
            return result

    # ------------------------------------------------------------ degradation
    def open_circuits(self) -> list[str]:
        """Operation classes whose breaker is OPEN right now. HALF_OPEN is
        deliberately NOT degraded: a half-open breaker admits probe calls,
        and the next write/fix attempt IS that probe — counting it as
        degraded would defer the very call that can close the circuit."""
        with self._lock:
            breakers = list(self._breakers.values())
        return sorted(b.op_class for b in breakers
                      if b.state == CircuitBreaker.OPEN)

    def degraded(self) -> bool:
        """Any breaker OPEN ⇒ the backend boundary is unhealthy."""
        return bool(self.open_circuits())

    def retry_after_s(self) -> float:
        with self._lock:
            breakers = list(self._breakers.values())
        waits = [b.retry_after_ms() for b in breakers
                 if b.state == CircuitBreaker.OPEN]
        return max(waits) / 1000.0 if waits else 1.0

    def state_json(self) -> dict:
        with self._lock:
            breakers = dict(self._breakers)
        return {"degraded": self.degraded(),
                "breakers": {name: br.to_json()
                             for name, br in sorted(breakers.items())}}
