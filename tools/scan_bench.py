import os, time
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_cc_tpu")
import jax, jax.numpy as jnp
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_cc_tpu")
from cruise_control_tpu.model.random_cluster import RandomClusterSpec, generate_scale
from cruise_control_tpu.analyzer.env import make_env, padded_partition_table
from cruise_control_tpu.analyzer.state import init_state
from cruise_control_tpu.analyzer.goals import make_goals
from cruise_control_tpu.analyzer.goals.base import legit_move_mask
from cruise_control_tpu.analyzer.env import BalancingConstraint, OptimizationOptions

print("generating...", flush=True)
ct, meta = generate_scale(RandomClusterSpec(
    num_brokers=7000, num_racks=40, num_topics=2000,
    num_partitions=500000, max_replication=3, skew=1.0, seed=3142,
    target_cpu_util=0.45))
env = make_env(ct, meta, partition_table=padded_partition_table(ct))
st = init_state(env, ct.replica_broker, ct.replica_is_leader,
                ct.replica_offline, ct.replica_disk)
goals = make_goals(["DiskUsageDistributionGoal"], BalancingConstraint(), OptimizationOptions())
goal = goals[0]
NEG_INF = -jnp.inf
R = env.num_replicas
print("R =", R, "B =", env.num_brokers, flush=True)

def scan(env, st, chunk):
    n_chunks = -(-R // chunk)
    def body(i, carry):
        gain, dst = carry
        base = i * chunk
        idx = base + jnp.arange(chunk, dtype=jnp.int32)
        cand = jnp.minimum(idx, R - 1)
        mask = legit_move_mask(env, st, cand, goal.options)
        score = jnp.where(mask, goal.move_score(env, st, cand), NEG_INF)
        d = jnp.argmax(score, axis=1).astype(jnp.int32)
        v = score[jnp.arange(chunk), d]
        v = jnp.where(idx < R, v, NEG_INF)
        gain = jax.lax.dynamic_update_slice(gain, v, (base,))
        dst = jax.lax.dynamic_update_slice(dst, d, (base,))
        return gain, dst
    gain0 = jnp.full(n_chunks * chunk, NEG_INF, jnp.float32)
    dst0 = jnp.zeros(n_chunks * chunk, jnp.int32)
    return jax.lax.fori_loop(0, n_chunks, body, (gain0, dst0))

for chunk in (1024, 1760):
    f = jax.jit(lambda e, s, c=chunk: scan(e, s, c))
    t0 = time.monotonic()
    g, d = f(env, st)
    jax.block_until_ready(g)
    cold = time.monotonic() - t0
    t0 = time.monotonic()
    for _ in range(3):
        g, d = f(env, st)
    jax.block_until_ready(g)
    warm = (time.monotonic() - t0) / 3
    npos = int((g > 1e-9).sum())
    print(f"chunk={chunk}: cold={cold:.2f}s warm={warm*1000:.0f}ms positives={npos}", flush=True)
