"""ClusterModelStats analogue.

Reference: model/ClusterModelStats.java:30-44 computes per-resource
AVG/MAX/MIN/ST_DEV over alive brokers, replica-count stats, topic-replica
stats and potential-NW-out stats; goals use these via their
ClusterModelStatsComparator to assert no regression after optimization
(AbstractGoal.java:110-119). Here it's one jitted pure function over the
ClusterTensor producing a flat stats pytree.
"""
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.model.cluster_tensor import ClusterTensor

Array = jax.Array


@partial(jax.tree_util.register_dataclass,
         data_fields=["avg", "max", "min", "std",
                      "replica_count_avg", "replica_count_max", "replica_count_min",
                      "replica_count_std", "leader_count_avg", "leader_count_max",
                      "potential_nw_out_avg", "potential_nw_out_max", "potential_nw_out_std",
                      "num_alive_brokers", "num_replicas", "num_offline_replicas"],
         meta_fields=[])
@dataclasses.dataclass(frozen=True)
class ClusterStats:
    avg: Array   # f32[M] mean broker utilization over alive brokers
    max: Array   # f32[M]
    min: Array   # f32[M]
    std: Array   # f32[M]
    replica_count_avg: Array
    replica_count_max: Array
    replica_count_min: Array
    replica_count_std: Array
    leader_count_avg: Array
    leader_count_max: Array
    potential_nw_out_avg: Array
    potential_nw_out_max: Array
    potential_nw_out_std: Array
    num_alive_brokers: Array
    num_replicas: Array
    num_offline_replicas: Array


@jax.jit
def cluster_stats(ct: ClusterTensor) -> ClusterStats:
    util = ct.broker_utilization()                          # [B, M]
    alive = ct.broker_alive
    n_alive = jnp.maximum(jnp.sum(alive), 1)
    alive_f = alive.astype(util.dtype)[:, None]

    def _stats(x):
        mean = jnp.sum(x * alive_f, axis=0) / n_alive
        mx = jnp.max(jnp.where(alive[:, None], x, -jnp.inf), axis=0)
        mn = jnp.min(jnp.where(alive[:, None], x, jnp.inf), axis=0)
        var = jnp.sum(((x - mean) ** 2) * alive_f, axis=0) / n_alive
        return mean, mx, mn, jnp.sqrt(var)

    mean, mx, mn, std = _stats(util)
    counts = ct.broker_replica_count().astype(util.dtype)
    cmean = jnp.sum(counts * alive) / n_alive
    cmax = jnp.max(jnp.where(alive, counts, -jnp.inf))
    cmin = jnp.min(jnp.where(alive, counts, jnp.inf))
    cstd = jnp.sqrt(jnp.sum(((counts - cmean) ** 2) * alive) / n_alive)
    lcounts = ct.broker_leader_count().astype(util.dtype)
    lmean = jnp.sum(lcounts * alive) / n_alive
    lmax = jnp.max(jnp.where(alive, lcounts, -jnp.inf))
    pot = ct.potential_leader_load()[:, Resource.NW_OUT]
    pmean = jnp.sum(pot * alive) / n_alive
    pmax = jnp.max(jnp.where(alive, pot, -jnp.inf))
    pstd = jnp.sqrt(jnp.sum(((pot - pmean) ** 2) * alive) / n_alive)

    return ClusterStats(
        avg=mean, max=mx, min=mn, std=std,
        replica_count_avg=cmean, replica_count_max=cmax, replica_count_min=cmin,
        replica_count_std=cstd, leader_count_avg=lmean, leader_count_max=lmax,
        potential_nw_out_avg=pmean, potential_nw_out_max=pmax, potential_nw_out_std=pstd,
        num_alive_brokers=jnp.sum(alive),
        num_replicas=jnp.sum(ct.replica_valid),
        num_offline_replicas=jnp.sum(ct.replica_offline & ct.replica_valid),
    )
