import os, time, sys
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_cc_tpu")
import jax
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_cc_tpu")
from cruise_control_tpu.model.random_cluster import RandomClusterSpec, generate_scale
from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
from cruise_control_tpu.analyzer.engine import EngineParams
import dataclasses, json
ov = json.loads(os.environ.get("CC_ENGINE_OVERRIDES", "{}"))
ct, meta = generate_scale(RandomClusterSpec(
    num_brokers=7000, num_racks=40, num_topics=2000,
    num_partitions=500000, max_replication=3, skew=1.0, seed=3142,
    target_cpu_util=0.45))
opt = GoalOptimizer(engine_params=dataclasses.replace(EngineParams(), **ov))
for i in range(int(sys.argv[1]) if len(sys.argv) > 1 else 2):
    t0 = time.monotonic()
    res = opt.optimizations(ct, meta, raise_on_failure=False,
                            skip_hard_goal_check=True)
    print(f"run {i}: {time.monotonic()-t0:.2f}s viol={len(res.violated_goals_after)} "
          f"exhausted={[g.name for g in res.goal_results if g.hit_max_iters]} "
          f"proven={[g.name for g in res.goal_results if g.violated_after and g.fixpoint_proven]}",
          flush=True)
