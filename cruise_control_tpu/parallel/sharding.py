"""Device-mesh sharding of the optimization engine.

The reference scales by threading on one JVM (SURVEY §2.10); the TPU-native
scale-out axis is the candidate-destination (broker) dimension: every
per-iteration kernel in the engine is either

- [B]- or [B, M]-shaped broker state (utilization, counts, limits),
- [K, B] candidate x destination score/mask matrices, or
- [R]-shaped replica state reduced into broker bins via segment ops,

so sharding the broker axis across a 1-D ``Mesh(("brokers",))`` splits the
scoring work and state while XLA inserts the collectives (argmax over the
sharded axis becomes a cross-device reduce; scatter updates stay local to the
owning shard). Replica-axis arrays are replicated in v1 — at the 7k-broker /
1M-replica north star the [K, B] scoring and [B]-state dominate; replica
sharding (segment-sum via reduce_scatter) is the next step up.

This module only *places* data: the engine code is unchanged — jit propagates
input shardings through the whole while_loop (GSPMD), which is exactly the
"annotate shardings, let XLA insert collectives" recipe.
"""
from __future__ import annotations

import dataclasses

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cruise_control_tpu.analyzer.env import ClusterEnv
from cruise_control_tpu.analyzer.state import EngineState

BROKER_AXIS = "brokers"

# env leaves sharded along their broker dimension (axis index given)
_ENV_BROKER_AXES = {
    "broker_capacity": 0, "broker_rack": 0, "broker_alive": 0, "broker_new": 0,
    "broker_demoted": 0, "broker_excluded_for_replica_move": 0,
    "broker_excluded_for_leadership": 0, "broker_disk_capacity": 0,
    "broker_disk_alive": 0, "dst_candidate": 0,
}
_STATE_BROKER_AXES = {
    "util": 0, "leader_util": 0, "potential_nw_out": 0, "replica_count": 0,
    "leader_count": 0, "topic_broker_count": 1, "topic_leader_count": 1,
    "disk_util": 0,
}


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (BROKER_AXIS,))


def _spec_for(ndim: int, axis: int | None) -> P:
    if axis is None:
        return P()
    parts = [None] * ndim
    parts[axis] = BROKER_AXIS
    return P(*parts)


def _place(obj, axes_map: dict, mesh: Mesh):
    updates = {}
    for f in dataclasses.fields(obj):
        val = getattr(obj, f.name)
        if not hasattr(val, "ndim"):
            continue
        axis = axes_map.get(f.name)
        sharding = NamedSharding(mesh, _spec_for(val.ndim, axis))
        updates[f.name] = jax.device_put(val, sharding)
    return dataclasses.replace(obj, **updates)


def pad_brokers(ct_arrays_factory, num_brokers: int, multiple: int) -> int:
    """Brokers must pad to a multiple of the mesh size; dead padded brokers
    are invisible to every goal (alive=False, capacity=0)."""
    rem = num_brokers % multiple
    return num_brokers if rem == 0 else num_brokers + (multiple - rem)


def shard_cluster(env: ClusterEnv, st: EngineState, mesh: Mesh):
    """Place (env, state) on the mesh: broker-dim leaves sharded, rest
    replicated. The broker count must divide evenly by the mesh size."""
    B = env.num_brokers
    n = mesh.devices.size
    if B % n != 0:
        raise ValueError(f"num_brokers={B} must be a multiple of mesh size {n}; "
                         f"pad the cluster with dead brokers (pad_brokers)")
    env_s = _place(env, _ENV_BROKER_AXES, mesh)
    st_s = _place(st, _STATE_BROKER_AXES, mesh)
    return env_s, st_s


def replicate(tree, mesh: Mesh):
    return jax.device_put(tree, NamedSharding(mesh, P()))
