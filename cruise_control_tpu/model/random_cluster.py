"""Randomized synthetic cluster generator.

Analogue of the reference's property-test generator
(cruise-control/src/test/java/com/linkedin/kafka/cruisecontrol/model/
RandomCluster.java:36 — generate :53, populate :102) which drives
RandomClusterTest / RandomSelfHealingTest and the BASELINE scale ladder
(100/10k -> 1k/100k -> 7k/1M). Load distributions: exponential, linear or
uniform per-resource, mirroring RandomCluster's ClusterProperty knobs.
"""
from __future__ import annotations

import dataclasses

import numpy as np

from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.model.builder import ClusterModelBuilder


@dataclasses.dataclass
class RandomClusterSpec:
    """ClusterProperty analogue (common/ClusterProperty in reference tests)."""
    num_brokers: int = 40
    num_racks: int = 10
    num_topics: int = 50
    num_partitions: int = 1000          # total partitions across topics
    min_replication: int = 1
    max_replication: int = 3
    mean_cpu: float = 1.0               # mean per-replica CPU %
    mean_disk: float = 100.0            # MB
    mean_nw_in: float = 100.0           # KB/s
    mean_nw_out: float = 100.0
    distribution: str = "exponential"   # exponential | linear | uniform
    cpu_capacity: float = 100.0
    disk_capacity: float = 500_000.0
    nw_in_capacity: float = 50_000.0
    nw_out_capacity: float = 50_000.0
    num_dead_brokers: int = 0
    num_brokers_with_dead_disk: int = 0
    logdirs_per_broker: int = 1
    leader_to_follower_ratio: float = 2.0   # unused when builder splits loads
    skew: float = 0.0                   # extra placement skew toward low-id brokers
    seed: int = 3140                    # TestConstants.SEED_BASE
    target_cpu_util: float | None = None
    """When set, rescale per-replica CPU loads so cluster-mean CPU utilization
    equals this fraction. The raw mean_cpu knob scales with P/B, so large
    rungs silently drift infeasible (mean util above the 0.7 capacity
    threshold means NO assignment can satisfy CpuCapacityGoal — the engine
    then burns its whole iteration budget proving it). Benchmarks pin this
    to a feasible-but-skewed operating point instead."""


def _sample(rng: np.random.Generator, dist: str, mean: float, n: int) -> np.ndarray:
    if dist == "exponential":
        return rng.exponential(mean, n)
    if dist == "linear":
        return mean * 2.0 * rng.uniform(0.0, 1.0, n)
    if dist == "uniform":
        return rng.uniform(0.5 * mean, 1.5 * mean, n)
    raise ValueError(f"unknown distribution {dist!r}")


def _calibrate_cpu(ct, target_util: float):
    """Rescale CPU loads so mean CPU utilization over alive brokers hits
    ``target_util`` (shape and skew preserved; only the scale changes)."""
    import jax.numpy as jnp

    lead = np.asarray(ct.leader_load)
    fol = np.asarray(ct.follower_load)
    is_lead = np.asarray(ct.replica_is_leader)
    valid = np.asarray(ct.replica_valid)
    eff = np.where(is_lead, lead[:, Resource.CPU], fol[:, Resource.CPU])
    total = float(eff[valid].sum())
    cap = np.asarray(ct.broker_capacity)[np.asarray(ct.broker_alive),
                                         Resource.CPU].sum()
    if total <= 0.0 or cap <= 0.0:
        return ct
    scale = target_util * float(cap) / total
    lead = lead.copy()
    fol = fol.copy()
    lead[:, Resource.CPU] *= scale
    fol[:, Resource.CPU] *= scale
    return dataclasses.replace(ct, leader_load=jnp.asarray(lead),
                       follower_load=jnp.asarray(fol))


def generate(spec: RandomClusterSpec):
    """Build a (ClusterTensor, ClusterMeta) random cluster per spec."""
    rng = np.random.default_rng(spec.seed)
    b = ClusterModelBuilder()
    capacity = {Resource.CPU: spec.cpu_capacity, Resource.DISK: spec.disk_capacity,
                Resource.NW_IN: spec.nw_in_capacity, Resource.NW_OUT: spec.nw_out_capacity}
    logdirs = [f"/mnt/i{d:02d}" for d in range(spec.logdirs_per_broker)]
    dead_brokers = set(rng.choice(spec.num_brokers, spec.num_dead_brokers, replace=False).tolist()) \
        if spec.num_dead_brokers else set()
    dead_disk_brokers = set()
    if spec.num_brokers_with_dead_disk:
        if spec.logdirs_per_broker < 2:
            raise ValueError("num_brokers_with_dead_disk requires logdirs_per_broker >= 2 "
                             "(a broker's only disk dying is broker death, not disk failure)")
        pool = [x for x in range(spec.num_brokers) if x not in dead_brokers]
        dead_disk_brokers = set(rng.choice(pool, spec.num_brokers_with_dead_disk,
                                           replace=False).tolist())
    for broker in range(spec.num_brokers):
        b.add_broker(broker, rack=f"r{broker % spec.num_racks}", capacity=capacity,
                     alive=broker not in dead_brokers, logdirs=logdirs,
                     dead_disks={logdirs[-1]} if broker in dead_disk_brokers and
                                 spec.logdirs_per_broker > 1 else set())

    # topic sizes ~ popularity-weighted (TOPIC_POPULARITY_SEED role)
    popularity = rng.exponential(1.0, spec.num_topics)
    popularity /= popularity.sum()
    parts_per_topic = np.maximum(1, np.round(popularity * spec.num_partitions).astype(int))

    # placement: round-robin start offset + optional skew toward low broker ids
    broker_order = np.arange(spec.num_brokers)
    for t in range(spec.num_topics):
        n_parts = int(parts_per_topic[t])
        rf = int(rng.integers(spec.min_replication, spec.max_replication + 1))
        rf = min(rf, spec.num_brokers)
        cpu = _sample(rng, spec.distribution, spec.mean_cpu, n_parts)
        disk = _sample(rng, spec.distribution, spec.mean_disk, n_parts)
        nw_in = _sample(rng, spec.distribution, spec.mean_nw_in, n_parts)
        nw_out = _sample(rng, spec.distribution, spec.mean_nw_out, n_parts)
        for p in range(n_parts):
            if spec.skew > 0:
                # biased sample without replacement: favors low-indexed brokers
                w = np.exp(-spec.skew * broker_order / spec.num_brokers)
                w /= w.sum()
                brokers = rng.choice(spec.num_brokers, rf, replace=False, p=w)
            else:
                start = int(rng.integers(spec.num_brokers))
                brokers = [(start + k) % spec.num_brokers for k in range(rf)]
            load = [cpu[p], nw_in[p], nw_out[p], disk[p]]
            for i, broker in enumerate(brokers):
                logdir = logdirs[int(rng.integers(spec.logdirs_per_broker))]
                b.add_replica(f"topic{t}", p, int(broker), is_leader=(i == 0),
                              load=load, logdir=logdir)
    ct, meta = b.build()
    if spec.target_cpu_util is not None:
        ct = _calibrate_cpu(ct, spec.target_cpu_util)
    return ct, meta


def generate_scale(spec: RandomClusterSpec):
    """Vectorized generator for the BASELINE scale ladder (1k/100k, 7k/1M).

    Same knobs and semantics as :func:`generate` (RandomCluster.java:53
    analogue) but builds the ClusterTensor arrays directly with numpy — the
    per-replica builder path is O(R) Python and takes minutes at the
    1M-replica north star.

    Placement draws each partition's rf brokers from a (optionally skewed)
    categorical distribution, re-drawing any within-partition duplicates; with
    B >> rf the redraw loop converges in a handful of vectorized rounds.
    """
    import jax.numpy as jnp

    from cruise_control_tpu.model.cluster_tensor import ClusterMeta, ClusterTensor

    rng = np.random.default_rng(spec.seed)
    B = spec.num_brokers
    M = 4

    # ---- topics / partitions ----
    popularity = rng.exponential(1.0, spec.num_topics)
    popularity /= popularity.sum()
    parts_per_topic = np.maximum(1, np.round(popularity * spec.num_partitions).astype(int))
    P = int(parts_per_topic.sum())
    partition_topic = np.repeat(np.arange(spec.num_topics, dtype=np.int32),
                                parts_per_topic)
    rf_per_topic = rng.integers(spec.min_replication, spec.max_replication + 1,
                                spec.num_topics)
    rf_per_topic = np.minimum(rf_per_topic, B)
    rf_per_part = rf_per_topic[partition_topic]                  # [P]
    R = int(rf_per_part.sum())
    F = int(rf_per_part.max())

    # ---- per-replica partition / topic / leadership ----
    replica_partition = np.repeat(np.arange(P, dtype=np.int32), rf_per_part)
    replica_topic = partition_topic[replica_partition]
    first_of_part = np.zeros(R, bool)
    first_of_part[np.concatenate([[0], np.cumsum(rf_per_part)[:-1]])] = True
    replica_is_leader = first_of_part
    pos_in_part = np.arange(R) - np.repeat(
        np.concatenate([[0], np.cumsum(rf_per_part)[:-1]]), rf_per_part)

    # ---- placement: weighted categorical + duplicate redraw ----
    if spec.skew > 0:
        w = np.exp(-spec.skew * np.arange(B) / B)
        w /= w.sum()
    else:
        w = np.full(B, 1.0 / B)
    replica_broker = rng.choice(B, size=R, p=w).astype(np.int32)
    # resolve duplicates within a partition: a replica collides if an earlier
    # position in the same partition already sits on its broker
    for _ in range(64):
        key = replica_partition.astype(np.int64) * B + replica_broker
        order = np.lexsort((pos_in_part, key))
        sk = key[order]
        dup_sorted = np.zeros(R, bool)
        dup_sorted[1:] = sk[1:] == sk[:-1]
        dup = np.zeros(R, bool)
        dup[order] = dup_sorted
        n_dup = int(dup.sum())
        if n_dup == 0:
            break
        replica_broker[dup] = rng.choice(B, size=n_dup, p=w).astype(np.int32)
    else:
        raise RuntimeError("placement redraw did not converge")

    # ---- loads (per partition, shared by its replicas) ----
    loads = np.stack([
        _sample(rng, spec.distribution, spec.mean_cpu, P),
        _sample(rng, spec.distribution, spec.mean_nw_in, P),
        _sample(rng, spec.distribution, spec.mean_nw_out, P),
        _sample(rng, spec.distribution, spec.mean_disk, P),
    ], axis=1).astype(np.float32)                                 # [P, M] CPU,NWIN,NWOUT,DISK
    leader_load = loads[replica_partition]
    follower_load = leader_load.copy()
    follower_load[:, Resource.NW_OUT] = 0.0
    follower_load[:, Resource.CPU] *= 0.5        # builder FOLLOWER_CPU_FRACTION

    # ---- brokers ----
    dead = np.zeros(B, bool)
    if spec.num_dead_brokers:
        dead[rng.choice(B, spec.num_dead_brokers, replace=False)] = True
    D = spec.logdirs_per_broker
    disk_cap = np.full((B, D), spec.disk_capacity / D, np.float32)
    disk_alive = np.ones((B, D), bool) & ~dead[:, None]
    dead_disk = np.zeros(B, bool)
    if spec.num_brokers_with_dead_disk:
        if D < 2:
            raise ValueError("dead disks require logdirs_per_broker >= 2")
        pool = np.flatnonzero(~dead)
        chosen = rng.choice(pool, spec.num_brokers_with_dead_disk, replace=False)
        dead_disk[chosen] = True
        disk_alive[chosen, D - 1] = False
    replica_disk = rng.integers(0, D, R).astype(np.int32)
    replica_offline = (dead[replica_broker]
                       | ~disk_alive[replica_broker, replica_disk])

    capacity = np.tile(np.array([[spec.cpu_capacity, spec.nw_in_capacity,
                                  spec.nw_out_capacity, spec.disk_capacity]],
                                np.float32), (B, 1))

    ct = ClusterTensor(
        replica_broker=jnp.asarray(replica_broker),
        replica_disk=jnp.asarray(replica_disk),
        replica_partition=jnp.asarray(replica_partition),
        replica_topic=jnp.asarray(replica_topic),
        replica_is_leader=jnp.asarray(replica_is_leader),
        replica_valid=jnp.ones(R, bool),
        replica_offline=jnp.asarray(replica_offline),
        replica_original_broker=jnp.asarray(replica_broker.copy()),
        leader_load=jnp.asarray(leader_load),
        follower_load=jnp.asarray(follower_load),
        broker_capacity=jnp.asarray(capacity),
        broker_rack=jnp.asarray((np.arange(B) % spec.num_racks).astype(np.int32)),
        broker_alive=jnp.asarray(~dead),
        broker_new=jnp.zeros(B, bool),
        broker_demoted=jnp.zeros(B, bool),
        broker_excluded_for_replica_move=jnp.zeros(B, bool),
        broker_excluded_for_leadership=jnp.zeros(B, bool),
        broker_disk_capacity=jnp.asarray(disk_cap),
        broker_disk_alive=jnp.asarray(disk_alive),
        topic_excluded=jnp.zeros(spec.num_topics, bool),
        partition_topic=jnp.asarray(partition_topic),
    )
    part_counter = np.zeros(spec.num_topics, np.int64)
    partition_ids = []
    for t in partition_topic:
        partition_ids.append((f"topic{t}", int(part_counter[t])))
        part_counter[t] += 1
    meta = ClusterMeta(
        topic_names=[f"topic{t}" for t in range(spec.num_topics)],
        partition_ids=partition_ids,
        broker_ids=list(range(B)),
        rack_ids=[f"r{k}" for k in range(spec.num_racks)],
        logdirs=[[f"/mnt/i{d:02d}" for d in range(D)]] * B,
        num_racks=spec.num_racks,
        num_valid_replicas=R,
    )
    if spec.target_cpu_util is not None:
        ct = _calibrate_cpu(ct, spec.target_cpu_util)
    return ct, meta
