"""Intra-broker (JBOD) disk goals.

Reference: analyzer/goals/IntraBrokerDiskCapacityGoal.java:1-293 (hard: every
alive logdir under ``capacity * disk-capacity-threshold``; replicas on dead
disks relocate to healthy disks of the same broker) and
IntraBrokerDiskUsageDistributionGoal.java:1-518 (soft: each logdir's
utilization percentage within the balance band around its broker's average
disk utilization, band = avg ± (balance% - 1) * BALANCE_MARGIN).

Actions are INTRA_BROKER_REPLICA_MOVEMENT only: destinations are the D
logdirs of the candidate's own broker, scored as [K, D] tensors over
``st.disk_util`` / ``env.broker_disk_capacity`` — broker-level tallies are
untouched, so these goals are transparent to every inter-broker goal's
acceptance mask.
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from cruise_control_tpu.analyzer.env import BALANCE_MARGIN, ClusterEnv
from cruise_control_tpu.analyzer.goals.base import NEG_INF, GoalKernel
from cruise_control_tpu.analyzer.state import EngineState
from cruise_control_tpu.common.resources import EPSILON_ABS, Resource

DISK_EPS = EPSILON_ABS[Resource.DISK]   # 100 MB absolute tolerance
PCT_EPS = 1e-4


def _disk_valid(env: ClusterEnv) -> jnp.ndarray:
    """bool[B, D]: configured, alive logdirs on alive brokers."""
    return (env.broker_disk_alive & (env.broker_disk_capacity > 0)
            & env.broker_alive[:, None])


def _candidate_disk_load(env: ClusterEnv, st: EngineState, cand) -> jnp.ndarray:
    """f32[K] DISK load of each candidate replica in its current role."""
    lead = st.replica_is_leader[cand]
    return jnp.where(lead, env.leader_load[cand, Resource.DISK],
                     env.follower_load[cand, Resource.DISK])


def _on_dead_disk(env: ClusterEnv, st: EngineState) -> jnp.ndarray:
    """bool[R]: replica sits on a dead/unconfigured logdir of an alive broker
    (the intra-broker healing case; dead-broker replicas are inter-broker)."""
    b = st.replica_broker
    d = jnp.clip(st.replica_disk, 0)
    bad_disk = ~(env.broker_disk_alive[b, d] & (env.broker_disk_capacity[b, d] > 0))
    return env.replica_valid & env.broker_alive[b] & bad_disk


@dataclasses.dataclass(frozen=True)
class IntraBrokerDiskCapacityGoal(GoalKernel):
    """Hard: no alive logdir above threshold*capacity; nothing on dead disks
    (IntraBrokerDiskCapacityGoal.java)."""

    def __post_init__(self):
        object.__setattr__(self, "name", "IntraBrokerDiskCapacityGoal")
        object.__setattr__(self, "is_hard", True)
        object.__setattr__(self, "uses_replica_moves", False)
        object.__setattr__(self, "uses_disk_moves", True)

    def _limit(self, env: ClusterEnv) -> jnp.ndarray:
        """f32[B, D]: allowed utilization per logdir; 0 for dead disks."""
        thresh = self.constraint.capacity_threshold[Resource.DISK]
        return jnp.where(_disk_valid(env),
                         thresh * env.broker_disk_capacity, 0.0)

    def broker_severity(self, env: ClusterEnv, st: EngineState):
        excess = jnp.maximum(st.disk_util - self._limit(env), 0.0)   # [B, D]
        # anything sitting on a dead disk counts fully
        sev = jnp.sum(jnp.where(_disk_valid(env), excess,
                                st.disk_util), axis=1)
        return jnp.where(env.broker_alive, sev - DISK_EPS, 0.0)

    def replica_key(self, env: ClusterEnv, st: EngineState, severity):
        b = st.replica_broker
        d = jnp.clip(st.replica_disk, 0)
        over = st.disk_util[b, d] > self._limit(env)[b, d] + DISK_EPS
        dead = _on_dead_disk(env, st)
        load = _candidate_disk_load(env, st, jnp.arange(env.num_replicas))
        movable = env.replica_valid & env.broker_alive[b] & (over | dead)
        key = jnp.where(movable, load, NEG_INF)
        return jnp.where(dead, key + 1e12, key)

    def disk_move_score(self, env: ClusterEnv, st: EngineState, cand):
        l = _candidate_disk_load(env, st, cand)                      # [K]
        b = st.replica_broker[cand]                                  # [K]
        limit = self._limit(env)[b]                                  # [K, D]
        util = st.disk_util[b]                                       # [K, D]
        feasible = util + l[:, None] <= limit
        cur = jnp.clip(st.replica_disk[cand], 0)
        src_over = util[jnp.arange(cand.shape[0]), cur] > (
            limit[jnp.arange(cand.shape[0]), cur] + DISK_EPS)
        dead = _on_dead_disk(env, st)[cand]
        headroom = jnp.maximum(limit - util, 0.0)
        cap = jnp.maximum(env.broker_disk_capacity[b], 1e-6)
        score = l[:, None] + 0.01 * headroom / cap
        score = jnp.where(dead[:, None], 1.0 + headroom / cap, score)
        return jnp.where(feasible & (src_over | dead)[:, None], score, NEG_INF)

    def accept_disk_move(self, env: ClusterEnv, st: EngineState, cand):
        l = _candidate_disk_load(env, st, cand)
        b = st.replica_broker[cand]
        return st.disk_util[b] + l[:, None] <= self._limit(env)[b] + DISK_EPS

    def violated(self, env: ClusterEnv, st: EngineState):
        return jnp.any(self.broker_severity(env, st) > 0)


@dataclasses.dataclass(frozen=True)
class IntraBrokerDiskUsageDistributionGoal(GoalKernel):
    """Soft: every logdir's utilization percentage within the balance band
    around its broker's average disk utilization
    (IntraBrokerDiskUsageDistributionGoal.java; band = avg ± (disk-balance%
    - 1) * BALANCE_MARGIN, GoalUtils balance-threshold math)."""

    def __post_init__(self):
        object.__setattr__(self, "name", "IntraBrokerDiskUsageDistributionGoal")
        object.__setattr__(self, "uses_replica_moves", False)
        object.__setattr__(self, "uses_disk_moves", True)

    def _band(self, env: ClusterEnv, st: EngineState):
        """(pct[B,D], lower[B], upper[B], valid[B,D])."""
        valid = _disk_valid(env)
        cap = jnp.where(valid, env.broker_disk_capacity, 0.0)
        util = jnp.where(valid, st.disk_util, 0.0)
        avg = jnp.sum(util, axis=1) / jnp.maximum(jnp.sum(cap, axis=1), 1e-6)
        dev = (self.constraint.resource_balance_percentage[Resource.DISK] - 1.0) \
            * BALANCE_MARGIN
        upper = avg * (1.0 + dev)
        lower = avg * (1.0 - dev)
        pct = st.disk_util / jnp.maximum(env.broker_disk_capacity, 1e-6)
        return pct, lower, upper, valid

    def _violation(self, env: ClusterEnv, st: EngineState):
        """f32[B, D] distance outside the band (0 inside)."""
        pct, lower, upper, valid = self._band(env, st)
        out = jnp.maximum(pct - upper[:, None], 0.0) \
            + jnp.maximum(lower[:, None] - pct, 0.0)
        return jnp.where(valid, out, 0.0)

    def broker_severity(self, env: ClusterEnv, st: EngineState):
        return jnp.sum(self._violation(env, st), axis=1) - PCT_EPS

    def replica_key(self, env: ClusterEnv, st: EngineState, severity):
        pct, lower, upper, valid = self._band(env, st)
        b = st.replica_broker
        d = jnp.clip(st.replica_disk, 0)
        avg = (lower + upper) / 2.0
        # candidates: any replica on an above-AVERAGE disk of a violating
        # broker — not only above-upper ones, because a below-lower disk is
        # filled by draining in-band disks that sit above the mean (the
        # reference's rebalanceByMovingLoadIn path); the score function
        # rejects moves with no band-violation gain
        donor = pct[b, d] > avg[b] + PCT_EPS
        load = _candidate_disk_load(env, st, jnp.arange(env.num_replicas))
        movable = env.replica_valid & (severity[b] > 0) & donor & (load > 0)
        return jnp.where(movable, load, NEG_INF)

    def disk_move_score(self, env: ClusterEnv, st: EngineState, cand):
        l = _candidate_disk_load(env, st, cand)                      # [K]
        b = st.replica_broker[cand]
        cap = jnp.maximum(env.broker_disk_capacity[b], 1e-6)         # [K, D]
        pct, lower, upper, valid = self._band(env, st)
        K = cand.shape[0]
        cur = jnp.clip(st.replica_disk[cand], 0)
        dl = l[:, None] / cap                                        # pct delta at dst
        src_pct = pct[b][jnp.arange(K), cur]                         # [K]
        src_cap = cap[jnp.arange(K), cur]
        up, lo = upper[b], lower[b]                                  # [K]

        def band_viol(p, up, lo):
            return jnp.maximum(p - up, 0.0) + jnp.maximum(lo - p, 0.0)

        v_src_before = band_viol(src_pct, up, lo)                    # [K]
        v_src_after = band_viol(src_pct - l / src_cap, up, lo)
        v_dst_before = band_viol(pct[b], up[:, None], lo[:, None])   # [K, D]
        v_dst_after = band_viol(pct[b] + dl, up[:, None], lo[:, None])
        gain = (v_src_before - v_src_after)[:, None] \
            + (v_dst_before - v_dst_after)
        return jnp.where(valid[b], gain, NEG_INF)

    def accept_disk_move(self, env: ClusterEnv, st: EngineState, cand):
        """As a previously-optimized goal: the destination logdir must not
        leave the band (REPLICA_REJECT analogue)."""
        l = _candidate_disk_load(env, st, cand)
        b = st.replica_broker[cand]
        cap = jnp.maximum(env.broker_disk_capacity[b], 1e-6)
        pct, lower, upper, valid = self._band(env, st)
        after = pct[b] + l[:, None] / cap
        return ~valid[b] | (after <= upper[b][:, None] + PCT_EPS)

    def violated(self, env: ClusterEnv, st: EngineState):
        return jnp.any(self._violation(env, st) > PCT_EPS)
