#!/usr/bin/env python
"""Render chaos-campaign results: episode logs + SLO distribution tables.

Input (file path or ``-`` for stdin), any of:
  - a CampaignResult JSON (``run_campaign(...).to_json()`` /
    ``episode_log_json()`` — what bench writes to CAMPAIGN_<name>_s<seed>.json)
  - a bench summary carrying a ``campaign`` block (BENCH_*.json /
    BENCH_partial.json / the compact final line)
  - a single ScenarioResult JSON (an episode entry)

Usage:
  tools/campaign_view.py CAMPAIGN.json [--episodes] [--timeline N]

  --episodes     per-episode one-liners (faults, convergence, latencies)
  --timeline N   dump episode N's full timeline (requires a log with
                 timelines, i.e. episode_log_json output)

Default output: the campaign header (episodes converged, verifier and
invariant verdicts, provisioner actuations) and the per-fault-type SLO
table — time-to-detect / time-to-heal / actions-per-heal p50/p95/max in
simulated ms.
"""
from __future__ import annotations

import json
import sys


def _find_campaign(doc) -> dict | None:
    if not isinstance(doc, dict):
        return None
    if "slo" in doc and ("episodes" in doc or "campaign" in doc):
        return doc
    if isinstance(doc.get("campaign"), dict):
        return doc["campaign"]
    return None


def _fmt_ms(v) -> str:
    if v is None:
        return "-"
    return f"{v / 1000.0:.1f}s" if v >= 1000 else f"{v:.0f}ms"


def render_slo_table(slo: dict) -> str:
    if not slo:
        return "  (no SLO samples)"
    head = (f"  {'fault':<18} {'n':>3} | {'detect p50':>10} {'p95':>10} "
            f"{'max':>10} | {'heal p50':>10} {'p95':>10} {'max':>10} "
            f"| {'acts p50':>8} {'max':>6} | miss")
    lines = [head, "  " + "-" * (len(head) - 2)]
    for kind, d in slo.items():
        det, heal, acts = (d["time_to_detect_ms"], d["time_to_heal_ms"],
                           d["actions_per_heal"])
        miss = []
        if d.get("undetected"):
            miss.append(f"{d['undetected']}D")
        if d.get("unhealed"):
            miss.append(f"{d['unhealed']}H")
        lines.append(
            f"  {kind:<18} {det['n']:>3} | {_fmt_ms(det['p50']):>10} "
            f"{_fmt_ms(det['p95']):>10} {_fmt_ms(det['max']):>10} | "
            f"{_fmt_ms(heal['p50']):>10} {_fmt_ms(heal['p95']):>10} "
            f"{_fmt_ms(heal['max']):>10} | "
            f"{acts['p50'] if acts['p50'] is not None else '-':>8} "
            f"{acts['max'] if acts['max'] is not None else '-':>6} | "
            f"{','.join(miss) or '-'}")
    return "\n".join(lines)


def render_forecast_block(fc: dict) -> str:
    """The predictive-control rollup (sim/campaign.aggregate_forecast):
    prevented-vs-reacted counts, time under violation, speculative hits."""
    dist = fc.get("time_under_violation_dist") or {}
    lines = [
        f"  forecast ({fc.get('episodes', 0)} episodes): "
        f"prevented={fc.get('prevented_violations', 0)} "
        f"predicted={fc.get('predicted_violations', 0)} "
        f"reacted={fc.get('reacted_violations', 0)}",
        f"    time under violation: total "
        f"{_fmt_ms(fc.get('time_under_violation_ms'))}"
        + (f" · p50 {_fmt_ms(dist.get('p50'))} p95 {_fmt_ms(dist.get('p95'))}"
           f" max {_fmt_ms(dist.get('max'))}" if dist.get("n") else ""),
        f"    speculative proposals: {fc.get('speculative_hits', 0)}/"
        f"{fc.get('speculative_installs', 0)} hits "
        f"(rate {fc.get('speculative_hit_rate', 0.0)})",
    ]
    return "\n".join(lines)


def render_episode_line(i: int, ep: dict) -> str:
    spec = ep.get("scenario_spec", {})
    events = ",".join(e["kind"] for e in spec.get("events", [])) or "?"
    flags = []
    if ep.get("verifier_violations"):
        flags.append(f"VERIFIER x{len(ep['verifier_violations'])}")
    if ep.get("num_invariant_violations"):
        flags.append(f"INVARIANT x{ep['num_invariant_violations']}")
    prov = ",".join(a["action"] for a in ep.get("provision_actions", []))
    return (f"  ep{i} {ep.get('scenario'):<28} [{events}] "
            f"{'OK ' if ep.get('converged') and not ep.get('failures') else 'FAIL'}"
            f" detect={_fmt_ms(ep.get('time_to_detect_ms'))}"
            f" heal={_fmt_ms(ep.get('time_to_heal_ms'))}"
            f" verified={ep.get('verified_optimizations', 0)}"
            f" adjust={ep.get('concurrency_adjustments', 0)}"
            + (f" prevented={ep.get('prevented_violations', 0)}"
               f" reacted={ep.get('reacted_violations', 0)}"
               f" tuv={_fmt_ms(ep.get('time_under_violation_ms'))}"
               if ep.get("forecast")
               or ep.get("time_under_violation_ms") is not None else "")
            + (f" provision={prov}" if prov else "")
            + (f"  !! {' '.join(flags)}" if flags else ""))


def render(doc: dict, show_episodes: bool = False,
           timeline_of: int | None = None) -> str:
    lines = []
    name = doc.get("campaign") if isinstance(doc.get("campaign"), str) \
        else doc.get("name", "?")
    lines.append(
        f"campaign {name} · seed {doc.get('seed')} · "
        f"{doc.get('converged_episodes')}/{doc.get('num_episodes')} episodes "
        f"converged · {doc.get('total_verified_optimizations', 0)} "
        f"optimizations verified "
        f"({doc.get('total_verifier_violations', 0)} verifier / "
        f"{doc.get('total_invariant_violations', 0)} invariant violations)")
    prov = doc.get("provision_actions") or []
    if prov:
        lines.append("  provision: " + "; ".join(
            f"{a['action']}(broker {a['broker']}) @ {_fmt_ms(a['ms'])}"
            for a in prov))
    for f in doc.get("failures", []):
        lines.append(f"  FAILURE: {f}")
    lines.append("")
    lines.append(render_slo_table(doc.get("slo", {})))
    fc = doc.get("forecast")
    if isinstance(fc, dict) and fc:
        lines.append("")
        lines.append(render_forecast_block(fc))
    episodes = doc.get("episodes", [])
    if show_episodes and episodes:
        lines.append("")
        for i, ep in enumerate(episodes):
            lines.append(render_episode_line(i, ep))
    if timeline_of is not None:
        if timeline_of >= len(episodes):
            lines.append(f"\n(no episode {timeline_of})")
        else:
            tl = episodes[timeline_of].get("timeline")
            lines.append(f"\nepisode {timeline_of} timeline:")
            if tl is None:
                lines.append("  (document carries no timelines — use the "
                             "CAMPAIGN_*.json episode log, not the summary)")
            else:
                for e in tl:
                    lines.append("  " + json.dumps(e))
    return "\n".join(lines)


def main(argv: list[str]) -> int:
    args = [a for a in argv if not a.startswith("--")]
    timeline_of = None
    if "--timeline" in argv:
        timeline_of = int(argv[argv.index("--timeline") + 1])
        args = [a for a in args if a != str(timeline_of)]
    if not args:
        print(__doc__, file=sys.stderr)
        return 2
    raw = sys.stdin.read() if args[0] == "-" else open(args[0]).read()
    doc = None
    for line in [raw] + raw.strip().splitlines()[::-1]:
        try:
            candidate = json.loads(line)
        except json.JSONDecodeError:
            continue
        doc = _find_campaign(candidate)
        if doc is not None:
            break
    if doc is None:
        print("no campaign document found", file=sys.stderr)
        return 1
    print(render(doc, show_episodes="--episodes" in argv,
                 timeline_of=timeline_of))
    return 0


if __name__ == "__main__":
    import signal
    signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    raise SystemExit(main(sys.argv[1:]))
