"""Prometheus sampler, parallel fetcher manager and capacity-file tests.

Reference test roles: PrometheusMetricSamplerTest (canned query responses),
MetricFetcherManager partition assignment, BrokerCapacityConfigFileResolver
capacity*.json parsing.
"""
import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from cruise_control_tpu.backend import SimulatedClusterBackend
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.monitor import LoadMonitor
from cruise_control_tpu.monitor.capacity import FileCapacityResolver
from cruise_control_tpu.monitor.fetcher import MetricFetcherManager, assign_partitions
from cruise_control_tpu.monitor.sampling.prometheus import (
    PrometheusAdapter, PrometheusMetricSampler,
)
from cruise_control_tpu.monitor.sampling.samplers import SimulatedMetricSampler


# --------------------------------------------------------------- prometheus
def _series(instance, values, topic=None, partition=None):
    metric = {"instance": instance}
    if topic is not None:
        metric.update(topic=topic, partition=str(partition))
    return {"metric": metric, "values": [[i * 60, str(v)]
                                         for i, v in enumerate(values)]}


class _FakePrometheus(BaseHTTPRequestHandler):
    """Serves canned /api/v1/query_range responses keyed by query content."""

    def log_message(self, *a):
        pass

    def do_GET(self):
        q = urllib.parse.parse_qs(urllib.parse.urlparse(self.path).query)
        query = q["query"][0]
        if "node_cpu_seconds_total" in query:
            result = [_series("host-0:7071", [20.0, 40.0]),
                      _series("host-1:7071", [10.0, 10.0])]
        elif 'name="BytesInPerSec",topic=""' in query:
            result = [_series("host-0:7071", [1000.0]),
                      _series("host-1:7071", [500.0])]
        elif 'name="Size"' in query:
            result = [_series("host-0:7071", [4096.0], topic="t", partition=0),
                      _series("host-1:7071", [8192.0], topic="t", partition=1)]
        elif 'name="BytesInPerSec",topic!=""' in query:
            result = [_series("host-0:7071", [100.0, 200.0], topic="t", partition=0)]
        else:
            result = []
        body = json.dumps({"status": "success",
                           "data": {"resultType": "matrix", "result": result}})
        payload = body.encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)


@pytest.fixture()
def prometheus_url():
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _FakePrometheus)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}"
    httpd.shutdown()
    httpd.server_close()


def test_prometheus_adapter_query_range(prometheus_url):
    adapter = PrometheusAdapter(prometheus_url)
    result = adapter.query_range('up{name="Size"}', 0, 120, 60)
    assert result and result[0]["metric"]["topic"] == "t"


def test_prometheus_sampler_maps_instances_to_brokers(prometheus_url):
    sampler = PrometheusMetricSampler(
        endpoint=prometheus_url,
        broker_id_by_host={"host-0": 0, "host-1": 1})
    samples = sampler.get_samples(now_ms=240_000.0)
    by_broker = {s.broker_id: s.values for s in samples.broker_samples}
    assert by_broker[0]["BROKER_CPU_UTIL"] == pytest.approx(30.0)  # avg 20,40
    assert by_broker[1]["ALL_TOPIC_BYTES_IN"] == pytest.approx(500.0)
    by_tp = {(s.topic, s.partition): s.values for s in samples.partition_samples}
    assert by_tp[("t", 0)]["DISK_USAGE"] == pytest.approx(4096.0)
    assert by_tp[("t", 0)]["LEADER_BYTES_IN"] == pytest.approx(150.0)
    assert by_tp[("t", 1)]["DISK_USAGE"] == pytest.approx(8192.0)


def test_prometheus_sampler_partition_subset(prometheus_url):
    sampler = PrometheusMetricSampler(
        endpoint=prometheus_url, broker_id_by_host={"host-0": 0, "host-1": 1})
    samples = sampler.get_samples(now_ms=240_000.0, partitions=[("t", 1)])
    assert {(s.topic, s.partition) for s in samples.partition_samples} == {("t", 1)}


def test_prometheus_sampler_feeds_load_monitor(prometheus_url):
    """Full path: Prometheus -> aggregator -> cluster model."""
    be = SimulatedClusterBackend()
    be.add_broker(0, "r0").add_broker(1, "r1")
    be.create_partition("t", 0, [0, 1])
    be.create_partition("t", 1, [1, 0])
    sampler = PrometheusMetricSampler(
        endpoint=prometheus_url, broker_id_by_host={"host-0": 0, "host-1": 1})
    lm = LoadMonitor(backend=be, sampler=sampler)
    lm.start_up()
    for i in range(8):
        lm.sample_once(now_ms=i * 300_000.0)
    ct, meta = lm.cluster_model()
    import numpy as np
    util = np.asarray(ct.broker_utilization())
    assert util[0, Resource.DISK] == pytest.approx(4096.0 + 8192.0, rel=1e-3)


# ------------------------------------------------------------ fetcher pool
def test_assign_partitions_round_robin():
    tps = [("t", i) for i in range(10)]
    groups = assign_partitions(tps, 4)
    assert len(groups) == 4
    assert sorted(sum(groups, [])) == sorted(tps)
    sizes = sorted(len(g) for g in groups)
    assert sizes == [2, 2, 3, 3]


class _CountingSampler(SimulatedMetricSampler):
    def __init__(self, backend):
        super().__init__(backend)
        self.calls = []
        self._lock = threading.Lock()

    def get_samples(self, now_ms, partitions=None, include_broker_samples=True):
        with self._lock:
            self.calls.append(partitions)
        return super().get_samples(
            now_ms, partitions=partitions,
            include_broker_samples=include_broker_samples)


def test_fetcher_manager_parallel_merge():
    be = SimulatedClusterBackend()
    for b in range(2):
        be.add_broker(b, f"r{b}")
    for p in range(9):
        be.create_partition("t", p, [p % 2, (p + 1) % 2], size_mb=10.0)
    sampler = _CountingSampler(be)
    mgr = MetricFetcherManager(sampler, num_fetchers=3)
    samples = mgr.fetch_once(1000.0, list(be.partitions()))
    assert len(sampler.calls) == 3                      # one call per fetcher
    assert all(c is not None for c in sampler.calls)    # each got a subset
    tps = {(s.topic, s.partition) for s in samples.partition_samples}
    assert len(tps) == 9                                # merged, no loss
    brokers = [s.broker_id for s in samples.broker_samples]
    assert sorted(brokers) == [0, 1]                    # deduped
    mgr.close()


def test_load_monitor_with_fetcher_pool():
    from cruise_control_tpu.config import cruise_control_config
    be = SimulatedClusterBackend()
    for b in range(3):
        be.add_broker(b, f"r{b}")
    for p in range(7):
        be.create_partition("t", p, [p % 3, (p + 1) % 3], size_mb=100.0,
                            bytes_in_rate=10.0)
    cfg = cruise_control_config({"num.metric.fetchers": 4,
                                 "min.samples.per.metrics.window": 1})
    lm = LoadMonitor(config=cfg, backend=be, sampler=SimulatedMetricSampler(be))
    lm.start_up()
    for i in range(8):
        lm.sample_once(now_ms=i * 300_000.0)
    ct, meta = lm.cluster_model()
    assert int(ct.replica_valid.sum()) == 14
    lm.shutdown()


# --------------------------------------------------------- capacity files
def test_file_capacity_resolver_jbod(tmp_path):
    path = tmp_path / "capacityJBOD.json"
    path.write_text(json.dumps({"brokerCapacities": [
        {"brokerId": "-1", "capacity": {
            "CPU": "100", "NW_IN": "10000", "NW_OUT": "10000",
            "DISK": {"/a": "250000", "/b": "250000"}}},
        {"brokerId": "0", "capacity": {
            "CPU": "200", "NW_IN": "20000", "NW_OUT": "20000",
            "DISK": {"/a": "100000", "/b": "300000", "/c": "100000"}}},
    ]}))
    r = FileCapacityResolver(str(path))
    info0 = r.capacity_for(0)
    assert info0.capacity[Resource.CPU] == 200.0
    assert info0.capacity[Resource.DISK] == 500_000.0
    assert info0.disk_capacity_by_logdir == {"/a": 100_000.0, "/b": 300_000.0,
                                             "/c": 100_000.0}
    # unknown broker falls through to the -1 default entry
    info9 = r.capacity_for(9)
    assert info9.capacity[Resource.NW_IN] == 10_000.0
    assert info9.disk_capacity_by_logdir == {"/a": 250_000.0, "/b": 250_000.0}


def test_file_capacity_resolver_via_config_plugin(tmp_path):
    from cruise_control_tpu.config import cruise_control_config
    path = tmp_path / "capacity.json"
    path.write_text(json.dumps({"brokerCapacities": [
        {"brokerId": "-1", "capacity": {"CPU": "100", "NW_IN": "9999",
                                        "NW_OUT": "9999", "DISK": "777"}}]}))
    cfg = cruise_control_config({"capacity.config.file": str(path),
                                 "min.samples.per.metrics.window": 1})
    be = SimulatedClusterBackend()
    be.add_broker(0, "r0")
    be.create_partition("t", 0, [0], size_mb=10.0)
    lm = LoadMonitor(config=cfg, backend=be)
    lm.start_up()
    for i in range(6):
        lm.sample_once(now_ms=i * 300_000.0)
    ct, meta = lm.cluster_model()
    import numpy as np
    cap = np.asarray(ct.broker_capacity)
    assert cap[0, Resource.DISK] == pytest.approx(777.0)
    assert cap[0, Resource.NW_IN] == pytest.approx(9999.0)


def test_fetcher_manager_isolates_failures():
    """One failing fetcher must not discard the other fetchers' samples
    (SamplingFetcher per-task error isolation)."""
    be = SimulatedClusterBackend()
    be.add_broker(0, "r0")
    for p in range(6):
        be.create_partition("t", p, [0], size_mb=10.0)

    class Flaky(SimulatedMetricSampler):
        def get_samples(self, now_ms, partitions=None,
                        include_broker_samples=True):
            if partitions and ("t", 1) in partitions:
                raise ConnectionError("transient fetch failure")
            return super().get_samples(
                now_ms, partitions=partitions,
                include_broker_samples=include_broker_samples)

    mgr = MetricFetcherManager(Flaky(be), num_fetchers=3)
    samples = mgr.fetch_once(1000.0, list(be.partitions()))
    got = {(s.topic, s.partition) for s in samples.partition_samples}
    assert got and ("t", 1) not in got          # partial, not empty
    mgr.close()

    class AlwaysBroken(SimulatedMetricSampler):
        def get_samples(self, *a, **kw):
            raise ConnectionError("down")

    mgr2 = MetricFetcherManager(AlwaysBroken(be), num_fetchers=2)
    with pytest.raises(RuntimeError, match="all metric fetchers failed"):
        mgr2.fetch_once(1000.0, list(be.partitions()))
    mgr2.close()
