"""Test harness: force an 8-device virtual CPU platform so sharding/pjit
paths are exercised without TPU hardware (the driver separately dry-runs
multichip via __graft_entry__.dryrun_multichip)."""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
# Keep compile times sane in CI: 64-bit off (f32 everywhere, matching TPU).
os.environ.setdefault("JAX_ENABLE_X64", "0")
# Persistent compilation cache: the engine compiles one loop per
# (goal, prev-goals) combo — cache them across test runs.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_cc_tpu")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
