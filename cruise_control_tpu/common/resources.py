"""Resource taxonomy.

Reference: cruise-control/src/main/java/com/linkedin/kafka/cruisecontrol/common/Resource.java:18-26
defines CPU, NW_IN, NW_OUT, DISK with host/broker scoping and epsilon-tolerant
comparison (Resource.java:92-94). Here each resource is also an index into the
trailing resource axis of every load/capacity tensor, so goal kernels can slice
one resource column without gather ops.
"""
from __future__ import annotations

import enum


class Resource(enum.IntEnum):
    """A balanceable resource; the value is the tensor column index."""

    CPU = 0
    NW_IN = 1
    NW_OUT = 2
    DISK = 3

    @property
    def is_host_resource(self) -> bool:
        # CPU and network are shared at host level; disk is per-broker.
        # Reference: Resource.java (isHostResource flags).
        return self in (Resource.CPU, Resource.NW_IN, Resource.NW_OUT)

    @property
    def is_broker_resource(self) -> bool:
        return True

    def epsilon(self, v1: float, v2: float) -> float:
        """Scale-aware comparison tolerance (Resource.java:92-94).

        The reference notes float precision matters at ~800k replicas
        (Resource.java:30-32); we accumulate in float64 on host and float32
        on device, keeping the same epsilon contract.
        """
        return max(EPSILON_ABS[self], EPSILON_PERCENT * (v1 + v2))


# Absolute epsilon per resource (reference Resource.java enum constants:
# CPU 0.001, NW 10 KB, DISK 100 MB — units: CPU %, KB/s, MB).
# Single source of truth — the analyzer's violation tolerances index this too.
EPSILON_ABS = {
    Resource.CPU: 0.001,
    Resource.NW_IN: 10.0,
    Resource.NW_OUT: 10.0,
    Resource.DISK: 100.0,
}
EPSILON_PERCENT = 0.0008

RESOURCES = tuple(Resource)
NUM_RESOURCES = len(RESOURCES)

# Priority order used by BalancingConstraint.setResources (descending balancing
# priority: DISK, CPU, NW_IN, NW_OUT per reference defaults).
DEFAULT_RESOURCE_PRIORITY = (Resource.DISK, Resource.CPU, Resource.NW_IN, Resource.NW_OUT)
