#!/usr/bin/env python
"""Generate PARITY.md: the DeterministicClusterTest matrix, Java outcome
(transcribed from the reference test's assertions) vs this implementation's
outcome (measured by running the same combination).

Usage: PYTHONPATH=. JAX_PLATFORMS=cpu python tools/gen_parity_table.py
"""
from __future__ import annotations

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_cc_cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")   # sitecustomize may preload axon
jax.config.update("jax_compilation_cache_dir",
                  os.environ["JAX_COMPILATION_CACHE_DIR"])
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cruise_control_tpu.analyzer.optimizer import OptimizationFailureError  # noqa: E402
from cruise_control_tpu.detector.provisioner import ProvisionStatus  # noqa: E402
from tests.test_java_parity_matrix import MATRIX, run_row  # noqa: E402

HEADER = """# PARITY — violation-outcome parity vs the Java optimizer

The JVM toolchain cannot run in this environment, so the Java side of this
table is TRANSCRIBED from the reference's own test assertions
(`DeterministicClusterTest.java:97-247`): every parameterized combination
must optimize successfully (hard goals satisfied, OptimizationVerifier
REGRESSION check passing) except (a) combinations whose failure is an
"Insufficient capacity" / UNDER_PROVISIONED one — explicitly tolerated by
the Java test's catch block (`:263-274`) — and (b) the two rows
parameterized with `expectedException=OptimizationFailureException`.

The TPU column is measured by `tests/test_java_parity_matrix.py` (same
fixtures — loads transcribed verbatim from `DeterministicCluster.java` —
same constraints from `TestConstants.java`, same goal chains).

| row | fixture | goals | constraint | Java outcome | TPU outcome | match |
|---|---|---|---|---|---|---|
"""


def describe_outcome(expected: str) -> str:
    return {"ok": "optimizes, hard goals satisfied",
            "ok_or_underprovisioned": "optimizes OR insufficient-capacity",
            "raise": "OptimizationFailureException"}[expected]


def run_one(row_index: int) -> None:
    """Run ONE matrix row and print a JSON verdict line (subprocess mode —
    a single long-lived process accumulating every row's XLA:CPU programs
    eventually crashes LLVM on this host)."""
    import json
    row_id, factory, chain, constraint, pattern, expected = MATRIX[row_index]
    try:
        _ct, _meta, res = run_row(factory, chain, constraint, pattern)
        hard = [g.name for g in res.goal_results
                if g.violated_after and g.name in (
                    "RackAwareGoal", "MinTopicLeadersPerBrokerGoal",
                    "ReplicaCapacityGoal", "DiskCapacityGoal",
                    "NetworkInboundCapacityGoal",
                    "NetworkOutboundCapacityGoal", "CpuCapacityGoal",
                    "KafkaAssignerEvenRackAwareGoal")]
        got = ("hard goals violated: " + ",".join(hard)) if hard else             f"optimized ({len(res.violated_goals_after)} soft violated)"
        ok = not hard and expected in ("ok", "ok_or_underprovisioned")
    except OptimizationFailureError as e:
        under = (e.recommendation is not None and
                 e.recommendation.status == ProvisionStatus.UNDER_PROVISIONED)
        got = ("raises (UNDER_PROVISIONED)" if under else "raises")
        ok = (expected == "raise"
              or (expected == "ok_or_underprovisioned" and under))
    print(json.dumps({"row": row_id, "got": got, "ok": ok}), flush=True)


def main() -> None:
    import json
    import subprocess

    rows = []
    all_match = True
    for i, (row_id, factory, chain, constraint, pattern, expected) in enumerate(MATRIX):
        t0 = time.monotonic()
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--row", str(i)],
                capture_output=True, text=True, timeout=1800)
            verdict = json.loads(proc.stdout.strip().splitlines()[-1])
        except subprocess.TimeoutExpired:
            verdict = {"row": row_id, "got": "subprocess timed out (1800s)",
                       "ok": False}
        except (IndexError, json.JSONDecodeError):
            verdict = {"row": row_id,
                       "got": f"subprocess failed rc={proc.returncode}",
                       "ok": False}
            print(proc.stderr[-2000:], file=sys.stderr, flush=True)
        got, ok = verdict["got"], verdict["ok"]
        all_match &= ok
        chain_desc = (f"{len(chain)}-goal default chain" if len(chain) > 3
                      else "+".join(chain))
        cdesc = (f"bal={constraint.resource_balance_percentage[0]} "
                 f"cap={constraint.capacity_threshold[0]}")
        rows.append(f"| {row_id} | {factory.__name__ if hasattr(factory, '__name__') else row_id} "
                    f"| {chain_desc} | {cdesc} | {describe_outcome(expected)} "
                    f"| {got} | {'yes' if ok else 'NO'} |")
        print(f"{row_id:32s} {got:50s} {'OK' if ok else 'MISMATCH'} "
              f"({time.monotonic() - t0:.1f}s)", file=sys.stderr, flush=True)

    _write(rows, all_match)


def _write(rows, all_match) -> None:
    with open("PARITY.md", "w") as f:
        f.write(HEADER)
        f.write("\n".join(rows) + "\n")
        f.write(f"\n**{len(rows)} rows, "
                f"{'all matching' if all_match else 'MISMATCHES PRESENT'}.**\n\n"
                "Regenerate with `python tools/gen_parity_table.py` "
                "(tests/test_java_parity_matrix.py asserts the same "
                "contract in CI).\n")
    print(f"PARITY.md written ({len(rows)} rows, match={all_match})",
          file=sys.stderr)


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--row":
        run_one(int(sys.argv[2]))
    else:
        main()
