#!/usr/bin/env python
"""Render recorded flight-recorder round traces as a text flamegraph/table.

Input (file path or ``-`` for stdin), any of:
  - a ``/state?substates=ROUND_TRACES`` response (or its ``RoundTraces`` value)
  - a BENCH_*.json summary (rungs[].last_round_trace)
  - a raw RoundTrace JSON object or a JSON list of them

Usage:
  tools/trace_view.py TRACES.json [--last] [--width 48]

Span mode: when the document carries causal spans instead of round traces —
a ``/state?substates=TRACES`` response, an EventJournal JSONL file, or a
campaign episode's ``journal`` slice — the spans are rendered as indented
trace trees (kind:name, [t0..t1] extent, attrs). ``tools/journal_view.py``
is the full-featured viewer (Perfetto export, SLOs); this mode is the quick
look.

Per trace it prints the round header (operation, wall, sampling/sync split,
compiles, device bytes) and a per-goal table with bars: bar length tracks
``duration_s`` when the trace carries honest per-goal seconds
(``durations_measured`` — analyzer.profile.level=stage or --profile runs)
and the applied-action count otherwise, with pass/wave/finisher counters
alongside — the pass-level profile every trace carries for free.
"""
from __future__ import annotations

import json
import sys


def _collect(doc) -> list[dict]:
    """Find RoundTrace dicts in any of the accepted document shapes."""
    if isinstance(doc, list):
        return [t for t in doc if isinstance(t, dict) and "goals" in t]
    if not isinstance(doc, dict):
        return []
    if "goals" in doc and "round_id" in doc:
        return [doc]
    out: list[dict] = []
    # /state response: {"RoundTraces": {"traces": [...]}} (maybe nested in
    # the wrap() envelope); recorder snapshot: {"traces": [...]}
    for key in ("RoundTraces", "json"):
        if key in doc:
            out.extend(_collect(doc[key]))
    if "traces" in doc:
        out.extend(_collect(doc["traces"]))
    # BENCH summary: rungs[].last_round_trace
    for rung in doc.get("rungs", []) or []:
        if isinstance(rung, dict) and rung.get("last_round_trace"):
            out.extend(_collect(rung["last_round_trace"]))
    if doc.get("last_round_trace"):
        out.extend(_collect(doc["last_round_trace"]))
    return out


def _bar(frac: float, width: int) -> str:
    n = max(0, min(width, round(frac * width)))
    return "█" * n + "·" * (width - n)


def _fmt_bytes(n) -> str:
    n = float(n or 0)
    for unit in ("B", "KB", "MB", "GB"):
        if n < 1024 or unit == "GB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}GB"


def render(trace: dict, width: int = 48) -> str:
    lines = []
    head = (f"round {trace.get('round_id')}"
            f" · {trace.get('operation') or 'OPTIMIZE'}"
            f" · wall {trace.get('wall_s', 0):.3f}s"
            f" · {trace.get('compiles', 0)} compiles"
            f" · profile={trace.get('profile_level', 'off')}")
    # incremental rounds (PR 16): surface the memo / dirty-seeded modes and
    # what the certificate re-check itself cost
    mode = trace.get("round_mode") or "full"
    if mode != "full":
        head += f" · {mode}"
        if trace.get("revalidate_s"):
            head += f" ({trace['revalidate_s']:.3f}s re-check)"
    # convergence-gated pass scheduling (PR 19): dispatched vs quiesce-
    # skipped pass budget and the goals the gate retired early
    if trace.get("passes_skipped") or trace.get("early_exit_goals") \
            or trace.get("skipped_goals"):
        head += (f" · passes {trace.get('passes_dispatched', 0)}"
                 f"(+{trace.get('passes_skipped', 0)} skipped,"
                 f" {trace.get('early_exit_goals', 0)} early-exit,"
                 f" {trace.get('skipped_goals', 0)} short-circuit)")
    lines.append(head)
    parts = []
    if trace.get("sampling_s") is not None:
        parts.append(f"sampling {trace['sampling_s']:.3f}s")
    if trace.get("sync_mode"):
        parts.append(f"sync {trace['sync_s']:.3f}s ({trace['sync_mode']}"
                     f"{', donated' if trace.get('donated') else ''})")
    parts.append(f"env {_fmt_bytes(trace.get('env_bytes'))}")
    parts.append(f"state {_fmt_bytes(trace.get('state_bytes'))}")
    parts.append(f"{trace.get('num_proposals', 0)} proposals")
    lines.append("  " + " · ".join(parts))
    # pipelined-loop stage lanes (PR 11): one bar per ingest/sync/execute
    # span that PREPARED this round, the part spent UNDER an in-flight
    # optimize round shaded solid (█ = overlapped, ░ = on the critical path)
    stages = trace.get("stages") or []
    if stages:
        lines.append("  pipeline lanes (█ overlapped with optimize, ░ not):")
        wall = max(trace.get("wall_s", 0) or 0,
                   max(s.get("dur_s", 0) for s in stages), 1e-9)
        lane_w = max((len(s["stage"]) for s in stages), default=5)
        for s in stages:
            dur = float(s.get("dur_s", 0) or 0)
            ov = float(s.get("overlap_s", 0) or 0)
            n = max(1, round(dur / wall * width)) if dur else 0
            n_ov = min(n, round((ov / dur) * n)) if dur else 0
            bar = "█" * n_ov + "░" * (n - n_ov) + "·" * (width - n)
            frac = (ov / dur) if dur else 0.0
            lines.append(f"  {s['stage']:<{lane_w}}    {bar} "
                         f"{dur:8.3f}s  overlap {100 * frac:5.1f}%")
        summary = trace.get("overlap") or {}
        if summary:
            lines.append("  overlap summary: " + " · ".join(
                f"{k} {100 * v.get('overlap_frac', 0):.1f}%"
                for k, v in sorted(summary.items())))
    # ragged fleet gating (PR 20): one row per tenant lane of a batched
    # launch — which lanes ran reduced, how much pass budget each skipped,
    # and which parked early / were compacted out of the working stack
    lanes = trace.get("fleet_lanes") or []
    if lanes:
        lines.append("  fleet lanes (disp=passes dispatched, "
                     "skip=passes skipped, sc=short-circuited goals):")
        for ln in lanes:
            marks = "".join((
                "P" if ln.get("parked_early") else "·",
                "C" if ln.get("compacted_out") else "·"))
            lines.append(
                f"  lane {ln.get('tenant', '?'):>3} {marks} "
                f"{ln.get('round_mode', 'full'):<8} "
                f"disp={ln.get('passes_dispatched', 0):<5} "
                f"skip={ln.get('passes_skipped', 0):<5} "
                f"early-exit={ln.get('early_exit_goals', 0)} "
                f"sc={ln.get('skipped_goals', 0)}")
    goals = trace.get("goals", [])
    measured = bool(trace.get("durations_measured")) and any(
        g.get("duration_s", 0) > 0 for g in goals)
    metric = "duration_s" if measured else "iterations"
    top = max((g.get(metric, 0) or 0 for g in goals), default=0) or 1
    unit = "s" if measured else " actions"
    lines.append(f"  per-goal bars: {metric}"
                 f"{'' if measured else ' (per-goal seconds need profile.level=stage)'}")
    name_w = max((len(g["name"]) for g in goals), default=4)
    for g in goals:
        v = g.get(metric, 0) or 0
        flags = "".join((
            "V" if g.get("violated_after") else "·",
            "v" if g.get("violated_before") else "·",
            # per-goal execution mode: R=revalidated (carried, not re-run),
            # r=reduced (dirty-seeded candidates), S=short-circuited to one
            # [B] probe (PR 19), ·=full
            {"revalidated": "R", "reduced": "r",
             "skipped": "S"}.get(g.get("mode"), "·")))
        detail = (f"p={g.get('passes', 0):<4} w={g.get('waves', 0):<4} "
                  f"m={g.get('moves', 0)} l={g.get('leads', 0)} "
                  f"s={g.get('swaps', 0)} d={g.get('disk', 0)} "
                  f"f={g.get('finisher', 0)}")
        # convergence gate (PR 19): passes the quiesce break avoided and the
        # chunk index it fired at — only where the gate actually fired
        if g.get("passes_skipped"):
            detail += (f" skip={g['passes_skipped']}"
                       f"@c{g.get('quiesce_chunk', -1)}")
        # segment-parallel finisher phase (fin_segments=0 = legacy waves):
        # show segments + boundary re-validations only where the phase ran
        if g.get("fin_segments"):
            detail += (f" seg={g['fin_segments']}"
                       f" b={g.get('fin_boundary', 0)}")
        val = f"{v:.3f}{unit}" if measured else f"{int(v)}{unit}"
        lines.append(f"  {g['name']:<{name_w}} {flags} "
                     f"{_bar(v / top, width)} {val:>12}  {detail}")
    return "\n".join(lines)


def render_span_trees(raw: str) -> str | None:
    """Span mode: render causal trace trees when the input carries spans
    (journal JSONL / TRACES substate / episode journal slice) — delegates
    parsing + tree building to tools/journal_view.py's shared helpers."""
    import importlib.util
    import pathlib
    spec = importlib.util.spec_from_file_location(
        "journal_view", pathlib.Path(__file__).parent / "journal_view.py")
    jv = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(jv)
    events = jv.load_events(raw)
    spans = jv.spans_of(events)
    if not spans:
        return None
    from cruise_control_tpu.common.tracing import build_trace_trees
    trees = build_trace_trees(spans)
    return "\n".join(jv.render_tree(t, events) for t in trees)


def main(argv: list[str]) -> int:
    args = [a for a in argv if not a.startswith("--")]
    width = 48
    if "--width" in argv:
        width = int(argv[argv.index("--width") + 1])
        args = [a for a in args if a != str(width)]
    if not args:
        print(__doc__, file=sys.stderr)
        return 2
    raw = (sys.stdin.read() if args[0] == "-"
           else open(args[0]).read())
    # BENCH files are one JSON document per line; scan from the last line
    # back and take the first parseable document that CARRIES traces (the
    # bench's compact machine-parseable final line strips the bulky
    # last_round_trace blobs — the full document is the pretty block /
    # earlier line above it)
    traces: list[dict] = []
    parsed_any = False
    for line in [raw] + raw.strip().splitlines()[::-1]:
        try:
            doc = json.loads(line)
        except json.JSONDecodeError:
            continue
        parsed_any = True
        traces = _collect(doc)
        if traces:
            break
    if not traces:
        # span mode: journals / TRACES substates carry spans, not rounds
        spans_out = render_span_trees(raw)
        if spans_out is not None:
            print(spans_out)
            return 0
    if not parsed_any:
        print("no parseable JSON document found", file=sys.stderr)
        return 1
    if not traces:
        print("no round traces found in document", file=sys.stderr)
        return 1
    if "--last" in argv:
        traces = traces[-1:]
    for t in traces:
        print(render(t, width=width))
        print()
    return 0


if __name__ == "__main__":
    # die quietly when the pipe closes (`trace_view ... | head`)
    import signal
    signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    raise SystemExit(main(sys.argv[1:]))
