"""Provisioner SPI: cluster right-sizing hook.

Reference: detector/Provisioner.java (SPI; rightsize(recommendations, ...)),
NoopProvisioner.java, and the ProvisionResponse/ProvisionRecommendation/
ProvisionStatus model (UNDER_PROVISIONED / RIGHT_SIZED / OVER_PROVISIONED,
analyzer/ProvisionStatus role).
"""
from __future__ import annotations

import dataclasses
import enum


class ProvisionStatus(enum.Enum):
    UNDER_PROVISIONED = "UNDER_PROVISIONED"
    RIGHT_SIZED = "RIGHT_SIZED"
    OVER_PROVISIONED = "OVER_PROVISIONED"
    UNDECIDED = "UNDECIDED"


@dataclasses.dataclass
class ProvisionRecommendation:
    status: ProvisionStatus
    num_brokers: int = 0
    reason: str = ""

    def to_json(self) -> dict:
        return {"status": self.status.value, "numBrokers": self.num_brokers,
                "reason": self.reason}


class NoopProvisioner:
    def configure(self, config, **extra):
        pass

    def rightsize(self, recommendations: list, context: dict | None = None) -> bool:
        """Returns True if any action was taken (never, for noop)."""
        return False


class SimulatedProvisioner:
    """Actuating Provisioner for simulated backends.

    The reference ships the SPI plus NoopProvisioner and leaves real
    actuation to deployment plugins (a cloud autoscaler behind
    ``Provisioner.rightsize``). Against a SimulatedClusterBackend the loop
    can be closed for real: UNDER_PROVISIONED adds brokers to the backend
    (rack chosen to balance the existing rack layout, capacities cloned from
    an existing broker), OVER_PROVISIONED drains the emptiest high-id brokers
    through the facade and decommissions them. Every actuation lands in
    ``history`` (on the backend clock) so scenario timelines and chaos
    campaigns can assert the detect -> rightsize -> actuate -> re-converge
    chain deterministically.

    Guard rails: a cooldown between actuations (``provision.actuation.
    cooldown.ms`` — a detector re-asserting UNDER before the resize has
    effect must not add again) and a lifetime add cap (``provision.max.
    added.brokers`` — also keeps sim clusters inside their padded engine
    shape bucket). Actuation is skipped while a proposal execution is in
    flight: resizing under a moving cluster is how real autoscalers cause
    outages.
    """

    def __init__(self):
        self._backend = None
        self._cc = None
        self.cooldown_ms = 600_000.0
        self.max_added_brokers = 4
        self.num_added = 0
        self.history: list[dict] = []
        self._last_action_ms = -1e18

    def configure(self, config, backend=None, cruise_control=None, **extra):
        if backend is not None:
            self._backend = backend
        if cruise_control is not None:
            self._cc = cruise_control
        # the app wiring reads the keys once and hands them down; direct
        # construction (tests/tools) may pass a config instead
        if "actuation_cooldown_ms" in extra:
            self.cooldown_ms = float(extra["actuation_cooldown_ms"])
        elif config is not None:
            self.cooldown_ms = float(config.get_int(
                "provision.actuation.cooldown.ms"))
        if "max_added_brokers" in extra:
            self.max_added_brokers = int(extra["max_added_brokers"])
        elif config is not None:
            self.max_added_brokers = config.get_int(
                "provision.max.added.brokers")

    # ------------------------------------------------------------------ SPI
    def rightsize(self, recommendations: list, context: dict | None = None) -> bool:
        be = self._backend
        if be is None or not hasattr(be, "add_broker"):
            return False
        now = float(be.now_ms())
        if now - self._last_action_ms < self.cooldown_ms:
            return False
        cc = self._cc
        if cc is not None and cc.executor.has_ongoing_execution():
            return False
        acted = False
        for rec in recommendations:
            if rec.status is ProvisionStatus.UNDER_PROVISIONED:
                acted = self._add_brokers(rec, now) or acted
            elif rec.status is ProvisionStatus.OVER_PROVISIONED:
                acted = self._remove_brokers(rec, now) or acted
        if acted:
            self._last_action_ms = now
        return acted

    # ------------------------------------------------------------ actuation
    def _add_brokers(self, rec: "ProvisionRecommendation", now: float) -> bool:
        be = self._backend
        brokers = be.brokers()
        n = min(max(rec.num_brokers, 1),
                self.max_added_brokers - self.num_added)
        if n <= 0 or not brokers:
            return False
        # clone the lowest-id alive broker's hardware shape; place each new
        # broker on the currently least-populated rack (ties by rack name) so
        # rack-aware goals stay satisfiable as the cluster grows
        template_id = min(b for b, node in brokers.items() if node.alive)
        template = brokers[template_id]
        rack_counts: dict[str, int] = {}
        for node in brokers.values():
            rack_counts[node.rack] = rack_counts.get(node.rack, 0) + 1
        next_id = max(brokers) + 1
        for i in range(n):
            rack = min(sorted(rack_counts), key=lambda r: rack_counts[r])
            be.add_broker(next_id + i, rack=rack,
                          logdirs=dict(template.logdirs),
                          cpu_capacity=template.cpu_capacity,
                          nw_in_capacity=template.nw_in_capacity,
                          nw_out_capacity=template.nw_out_capacity)
            rack_counts[rack] += 1
            self.history.append({"ms": now, "action": "add_broker",
                                 "broker": next_id + i, "rack": rack,
                                 "reason": rec.reason})
        self.num_added += n
        return True

    def _remove_brokers(self, rec: "ProvisionRecommendation", now: float) -> bool:
        be = self._backend
        brokers = be.brokers()
        counts = {b: 0 for b, node in brokers.items() if node.alive}
        for info in be.partitions().values():
            for b in info.replicas:
                if b in counts:
                    counts[b] += 1
        # emptiest first, highest id breaking ties (scale-down retires the
        # newest hardware first)
        candidates = sorted(counts, key=lambda b: (counts[b], -b))
        n = max(rec.num_brokers, 1)
        acted = False
        for b in candidates[:n]:
            if counts[b] > 0:
                if self._cc is None:
                    continue
                # drain through the same facade path operators use; any
                # failure (unsatisfiable evacuation) simply leaves the broker
                self._cc.remove_brokers(
                    [b], reason=f"provisioner right-size: {rec.reason}")
                if any(b in info.replicas
                       for info in be.partitions().values()):
                    continue
            be.decommission_broker(b)
            self.history.append({"ms": now, "action": "remove_broker",
                                 "broker": b, "reason": rec.reason})
            acted = True
        return acted


@dataclasses.dataclass
class ProvisionFloors:
    """Right-sizing floors an OVER_PROVISIONED recommendation must respect
    (AnomalyDetectorConfig overprovisioned.*): never recommend shrinking
    below ``min_brokers``, below ``min_extra_racks`` spare racks beyond the
    max partition RF, or past the point where the average replica count per
    remaining broker exceeds ``max_replicas_per_broker``."""
    min_brokers: int = 3
    min_extra_racks: int = 1
    max_replicas_per_broker: int = 1500

    @classmethod
    def from_config(cls, cfg) -> "ProvisionFloors":
        return cls(
            min_brokers=cfg.get_int("overprovisioned.min.brokers"),
            min_extra_racks=cfg.get_int("overprovisioned.min.extra.racks"),
            max_replicas_per_broker=int(cfg.get_int(
                "overprovisioned.max.replicas.per.broker")))


def recommendation_from_result(res, constraint,
                               floors: ProvisionFloors | None = None,
                               ) -> ProvisionRecommendation:
    """Capacity-math provision recommendation from an OptimizerResult
    (GoalViolationDetector.java:228 -> Provisioner.rightsize path, and the
    ProvisionRecommendation attached to OptimizationFailureException by the
    capacity goals): per resource, total load vs total allowed capacity
    decides how many brokers of average capacity are missing (or spare)."""
    import math

    import numpy as np

    env, st = res.env, res.final_state
    alive = np.asarray(env.broker_alive)
    if not alive.any():
        return ProvisionRecommendation(ProvisionStatus.UNDER_PROVISIONED,
                                       num_brokers=1, reason="no alive brokers")
    util = np.asarray(st.util)[alive]                       # [B, M]
    cap = np.asarray(env.broker_capacity)[alive]
    thresh = np.asarray(constraint.capacity_threshold)
    total_load = util.sum(axis=0)
    avg_cap = cap.mean(axis=0)
    allowed = (cap * thresh[None, :]).sum(axis=0)
    deficit = total_load - allowed                          # [M] >0 = missing
    if (deficit > 0).any():
        from cruise_control_tpu.common.resources import Resource
        r = int(np.argmax(deficit / np.maximum(avg_cap * thresh, 1e-9)))
        need = math.ceil(deficit[r] / max(avg_cap[r] * thresh[r], 1e-9))
        return ProvisionRecommendation(
            ProvisionStatus.UNDER_PROVISIONED, num_brokers=max(1, need),
            reason=f"{Resource(r).name} load {total_load[r]:.1f} exceeds "
                   f"allowed capacity {allowed[r]:.1f}: add >= {max(1, need)} "
                   f"broker(s) of average capacity")
    offline = res.stats_after.get("num_offline_replicas", 0)
    if offline or any(g.violated_after for g in res.goal_results
                      if g.name.endswith("CapacityGoal")):
        return ProvisionRecommendation(
            ProvisionStatus.UNDER_PROVISIONED, num_brokers=1,
            reason="capacity goals unsatisfiable despite aggregate headroom "
                   "(placement infeasibility)")
    low = np.asarray(constraint.low_utilization_threshold)
    n = int(alive.sum())
    active = low > 0
    if active.any() and n > 1:
        avg_util_frac = total_load / np.maximum(cap.sum(axis=0), 1e-9)
        if (avg_util_frac[active] < low[active]).all():
            floors = floors or ProvisionFloors()
            # brokers removable while every resource stays under its allowed
            # aggregate capacity (reference low-utilization OVER_PROVISIONED)
            # AND the overprovisioned.* floors hold
            n_replicas = int(np.asarray(env.replica_valid).sum())
            keep_floor = max(
                1, floors.min_brokers,
                math.ceil(n_replicas / max(floors.max_replicas_per_broker, 1)))
            keep = n
            while keep > keep_floor and (
                    total_load <= avg_cap * thresh * (keep - 1) - 1e-9).all():
                keep -= 1
            # min.extra.racks: keep enough brokers that the cluster retains
            # (racks hosting the max partition RF) + extra racks' worth of
            # spread — shrinking below max-RF racks would make rack-aware
            # placement permanently infeasible. With one broker per rack in
            # the worst case this is a broker floor.
            racks_alive = np.asarray(env.broker_rack)[alive]
            num_racks = len(np.unique(racks_alive))
            if num_racks > 0:
                valid = np.asarray(env.replica_valid)
                parts = np.asarray(env.replica_partition)[valid]
                max_rf = int(np.bincount(parts).max()) if parts.size else 1
                per_rack = n / num_racks
                min_racks = min(num_racks, max_rf + floors.min_extra_racks)
                keep = max(keep, math.ceil(min_racks * per_rack))
            if keep < n:
                return ProvisionRecommendation(
                    ProvisionStatus.OVER_PROVISIONED, num_brokers=n - keep,
                    reason=f"{n - keep} broker(s) removable under the "
                           f"low-utilization thresholds (floors: "
                           f">={keep_floor} brokers)")
    return ProvisionRecommendation(ProvisionStatus.RIGHT_SIZED)
