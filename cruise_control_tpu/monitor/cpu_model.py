"""CPU attribution model.

Reference: model/ModelUtils.java:61-141 — static-weight attribution of a
broker's CPU utilization to its partitions by their share of weighted network
throughput (leader.network.inbound.weight.for.cpu.util = 0.6,
follower.network.inbound.weight = 0.3, leader.network.outbound.weight = 0.1 —
MonitorConfig defaults), plus the experimental linear-regression model
(ModelParameters.java / LinearRegressionModelParameters.java:379) which is
config-gated off by default (use.linear.regression.model).
"""
from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class CpuModelParams:
    leader_nw_in_weight: float = 0.6
    follower_nw_in_weight: float = 0.3
    leader_nw_out_weight: float = 0.1

    @classmethod
    def from_config(cls, cfg) -> "CpuModelParams":
        return cls(
            leader_nw_in_weight=cfg.get_double("leader.network.inbound.weight.for.cpu.util"),
            follower_nw_in_weight=cfg.get_double("follower.network.inbound.weight.for.cpu.util"),
            leader_nw_out_weight=cfg.get_double("leader.network.outbound.weight.for.cpu.util"),
        )


def estimate_leader_cpu_util(broker_cpu_util, broker_leader_bytes_in,
                             broker_leader_bytes_out, broker_follower_bytes_in,
                             partition_bytes_in, partition_bytes_out,
                             params: CpuModelParams = CpuModelParams()):
    """CPU share of a leader partition (ModelUtils.estimateLeaderCpuUtil :92-124).

    All args may be scalars or aligned numpy arrays (vectorized attribution for
    a whole broker's partitions at once).
    """
    total_weighted = (params.leader_nw_in_weight * broker_leader_bytes_in
                      + params.leader_nw_out_weight * broker_leader_bytes_out
                      + params.follower_nw_in_weight * broker_follower_bytes_in)
    share = np.where(np.asarray(total_weighted) > 0,
                     (params.leader_nw_in_weight * partition_bytes_in
                      + params.leader_nw_out_weight * partition_bytes_out)
                     / np.maximum(total_weighted, 1e-12),
                     0.0)
    return broker_cpu_util * share


def estimate_follower_cpu_util(leader_cpu_util, leader_bytes_in, leader_bytes_out,
                               params: CpuModelParams = CpuModelParams()):
    """Follower CPU from the leader's (ModelUtils.estimateFollowerCpuUtil):
    followers do replication-in work only."""
    denom = (params.leader_nw_in_weight * leader_bytes_in
             + params.leader_nw_out_weight * leader_bytes_out)
    ratio = np.where(np.asarray(denom) > 0,
                     params.follower_nw_in_weight * leader_bytes_in
                     / np.maximum(denom, 1e-12), 0.0)
    return leader_cpu_util * ratio


class LinearRegressionCpuModel:
    """Experimental CPU model (LinearRegressionModelParameters role): fits
    cpu ~ a*bytes_in + b*bytes_out from training samples.

    ``bucket_size_pct`` (MonitorConfig linear.regression.model.cpu.util.
    bucket.size): training coverage is tracked per CPU-utilization bucket —
    the model reports itself trainable only once samples span enough distinct
    buckets to pin the regression down (the reference's
    LinearRegressionModelParameters.modelCoefficientTrainingCompleteness)."""

    MIN_BUCKETS = 2   # below this the fit rests on one utilization regime

    def __init__(self, bucket_size_pct: int = 5):
        self._coef = None
        self._bucket_pct = max(1, bucket_size_pct)
        self._buckets_seen: set[int] = set()

    def train(self, bytes_in: np.ndarray, bytes_out: np.ndarray, cpu: np.ndarray) -> None:
        X = np.stack([np.asarray(bytes_in), np.asarray(bytes_out)], axis=1)
        y = np.asarray(cpu)
        self._buckets_seen.update(int(v // self._bucket_pct) for v in y)
        self._coef, *_ = np.linalg.lstsq(X, y, rcond=None)

    def training_completeness(self) -> dict:
        """Coverage report (LinearRegressionModelParameters
        .modelCoefficientTrainingCompleteness role): distinct
        CPU-utilization buckets the training data spanned."""
        return {"bucketSizePct": self._bucket_pct,
                "bucketsSeen": sorted(self._buckets_seen),
                "sufficient": len(self._buckets_seen) >= self.MIN_BUCKETS}

    @property
    def trained(self) -> bool:
        return self._coef is not None

    def predict(self, bytes_in, bytes_out):
        if self._coef is None:
            raise RuntimeError("model not trained")
        return self._coef[0] * np.asarray(bytes_in) + self._coef[1] * np.asarray(bytes_out)
