"""Network-centric soft goals.

Reference: analyzer/goals/PotentialNwOutGoal.java:372 (keep each broker's
*potential* outbound — the NW_OUT it would serve if every hosted replica became
leader — under the NW_OUT capacity threshold) and
LeaderBytesInDistributionGoal.java:293 (balance leader-side bytes-in across
brokers via leadership transfers).
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from cruise_control_tpu.analyzer.env import ClusterEnv, resource_balance_limits
from cruise_control_tpu.analyzer.goals.base import (
    NEG_INF, WAVE_DIMS, WAVE_LEADER_NW_IN, WAVE_POT_NW_OUT, GoalKernel,
)
from cruise_control_tpu.analyzer.goals.capacity import RESOURCE_EPS
from cruise_control_tpu.analyzer.state import EngineState
from cruise_control_tpu.common.resources import Resource

NW_IN = int(Resource.NW_IN)
NW_OUT = int(Resource.NW_OUT)


@dataclasses.dataclass(frozen=True)
class PotentialNwOutGoal(GoalKernel):
    def __post_init__(self):
        object.__setattr__(self, "name", "PotentialNwOutGoal")

    def _limit(self, env: ClusterEnv) -> jnp.ndarray:
        thresh = self.constraint.capacity_threshold[NW_OUT]
        return jnp.where(env.broker_alive,
                         thresh * env.broker_capacity[:, NW_OUT], 0.0)

    def broker_severity(self, env: ClusterEnv, st: EngineState):
        return st.potential_nw_out - self._limit(env) - RESOURCE_EPS[NW_OUT]

    def replica_key(self, env: ClusterEnv, st: EngineState, severity):
        on_bad = severity[st.replica_broker] > 0
        pot = env.leader_load[:, NW_OUT]
        offline = st.replica_offline & env.replica_valid
        ok = env.replica_valid & on_bad & ((pot > 0) | offline)
        key = jnp.where(ok, pot, NEG_INF)
        return jnp.where(offline, key + 1e12, key)

    def move_score(self, env: ClusterEnv, st: EngineState, cand):
        pot = env.leader_load[cand, NW_OUT]                     # [K]
        limit = self._limit(env)
        feasible = st.potential_nw_out[None, :] + pot[:, None] <= limit[None, :]
        offline = st.replica_offline[cand]
        cap = jnp.maximum(env.broker_capacity[:, NW_OUT], 1e-6)[None, :]
        headroom = jnp.maximum(limit - st.potential_nw_out, 0.0)[None, :]
        score = pot[:, None] + 0.01 * headroom / cap
        score = jnp.where(offline[:, None], 1.0 + headroom / cap, score)
        return jnp.where(feasible, score, NEG_INF)

    def accept_move(self, env: ClusterEnv, st: EngineState, cand):
        pot = env.leader_load[cand, NW_OUT]
        limit = self._limit(env) + RESOURCE_EPS[NW_OUT]
        return st.potential_nw_out[None, :] + pot[:, None] <= limit[None, :]

    def accept_move_rooms(self, env: ClusterEnv, st: EngineState):
        """Interval form: the move's potential-NW_OUT delta must fit the
        destination's headroom to the potential limit."""
        limit = self._limit(env) + RESOURCE_EPS[NW_OUT]
        return {WAVE_POT_NW_OUT: (None, limit - st.potential_nw_out)}

    def wave_budgets(self, env: ClusterEnv, st: EngineState):
        """Destination headroom to the potential-NW_OUT limit."""
        limit = self._limit(env) + RESOURCE_EPS[NW_OUT]
        B = env.num_brokers
        src = jnp.full((B, WAVE_DIMS), jnp.inf, st.potential_nw_out.dtype)
        dst = jnp.full((B, WAVE_DIMS), jnp.inf, st.potential_nw_out.dtype)
        dst = dst.at[:, WAVE_POT_NW_OUT].set(limit - st.potential_nw_out)
        return src, dst

    def wave_gain_budgets(self, env: ClusterEnv, st: EngineState):
        excess = jnp.maximum(st.potential_nw_out - self._limit(env), 0.0)
        return excess, jnp.zeros_like(excess), WAVE_POT_NW_OUT

    def segment_room_key(self, env: ClusterEnv, st: EngineState):
        """Segment coloring key: potential-NW_OUT headroom to the limit."""
        return self._limit(env) - st.potential_nw_out


@dataclasses.dataclass(frozen=True)
class LeaderBytesInDistributionGoal(GoalKernel):
    """Balance leader bytes-in; leadership transfers only
    (LeaderBytesInDistributionGoal acts on leadership, not replica placement)."""

    def __post_init__(self):
        object.__setattr__(self, "name", "LeaderBytesInDistributionGoal")
        object.__setattr__(self, "uses_replica_moves", False)
        object.__setattr__(self, "uses_leadership_moves", True)
        object.__setattr__(self, "deep_tail", True)

    def _limits(self, env: ClusterEnv, st: EngineState):
        alive = env.broker_alive
        cap = env.broker_capacity[:, NW_IN]
        total = jnp.sum(jnp.where(alive, st.leader_util[:, NW_IN], 0.0))
        total_cap = jnp.maximum(jnp.sum(jnp.where(alive, cap, 0.0)), 1e-6)
        avg_pct = total / total_cap
        lower_pct, upper_pct = resource_balance_limits(
            avg_pct, self.constraint, NW_IN, self.options.triggered_by_goal_violation)
        del lower_pct  # the reference goal only enforces the upper bound
        upper = jnp.where(alive, upper_pct * cap, 0.0)
        return upper

    def broker_severity(self, env: ClusterEnv, st: EngineState):
        upper = self._limits(env, st)
        return st.leader_util[:, NW_IN] - upper - RESOURCE_EPS[NW_IN]

    def leader_key(self, env: ClusterEnv, st: EngineState, severity):
        on_bad = severity[st.replica_broker] > 0
        lin = env.leader_load[:, NW_IN]
        ok = (env.replica_valid & st.replica_is_leader & on_bad & (lin > 0)
              & ~st.replica_offline)
        return jnp.where(ok, lin, NEG_INF)

    def leadership_score(self, env: ClusterEnv, st: EngineState, cand):
        members = env.partition_replicas[env.replica_partition[cand]]
        m = jnp.clip(members, 0)
        dst_broker = st.replica_broker[m]
        upper = self._limits(env, st)
        util = st.leader_util[:, NW_IN]
        src = st.replica_broker[cand]
        lin = env.leader_load[cand, NW_IN][:, None]             # same partition: dst gains it
        excess_red = jnp.minimum(jnp.maximum(util[src][:, None] - upper[src][:, None], 0.0), lin)
        new_excess_dst = jnp.maximum(util[dst_broker] + lin - upper[dst_broker], 0.0)
        feasible = new_excess_dst <= 0.0
        return jnp.where(feasible & (excess_red > 0), excess_red, NEG_INF)

    def accept_leadership(self, env: ClusterEnv, st: EngineState, cand):
        members = env.partition_replicas[env.replica_partition[cand]]
        m = jnp.clip(members, 0)
        dst_broker = st.replica_broker[m]
        upper = self._limits(env, st)
        lin = env.leader_load[cand, NW_IN][:, None]
        eps = RESOURCE_EPS[NW_IN]
        return st.leader_util[dst_broker, NW_IN] + lin <= upper[dst_broker] + eps

    def wave_budgets(self, env: ClusterEnv, st: EngineState):
        """Destination leader-bytes-in headroom; binds leadership waves only
        (move-wave deltas carry 0 on the leader-NW_IN dim, mirroring the
        absence of an accept_move veto)."""
        upper = self._limits(env, st) + RESOURCE_EPS[NW_IN]
        lu = st.leader_util[:, NW_IN]
        B = env.num_brokers
        src = jnp.full((B, WAVE_DIMS), jnp.inf, lu.dtype)
        dst = jnp.full((B, WAVE_DIMS), jnp.inf, lu.dtype)
        dst = dst.at[:, WAVE_LEADER_NW_IN].set(upper - lu)
        return src, dst

    def wave_gain_budgets(self, env: ClusterEnv, st: EngineState):
        upper = self._limits(env, st)
        excess = jnp.maximum(st.leader_util[:, NW_IN] - upper, 0.0)
        return excess, jnp.zeros_like(excess), WAVE_LEADER_NW_IN

    def segment_room_key(self, env: ClusterEnv, st: EngineState):
        """Segment coloring key: leader-bytes-in headroom to the upper
        limit (leadership transfer destinations)."""
        return self._limits(env, st) - st.leader_util[:, NW_IN]
