"""Metrics-reporter module: broker-side metric emission + serde + transport.

Reference: cruise-control-metrics-reporter/ — the in-broker
CruiseControlMetricsReporter plugin snapshots broker metrics, serializes them
(metric/MetricSerde.java) and produces them to the __CruiseControlMetrics
topic; the monitor's CruiseControlMetricsReporterSampler consumes that topic.
Here the transport is a file-backed append log (FileMetricsTopic) — the
zero-dependency stand-in for a Kafka topic, with the same offset-consumption
contract — and the reporter snapshots a ClusterBackend.
"""
from cruise_control_tpu.reporter.metrics import (
    BrokerMetric, CruiseControlMetric, PartitionMetric, TopicMetric,
    metric_from_bytes, metric_to_bytes,
)
from cruise_control_tpu.reporter.reporter import CruiseControlMetricsReporter
from cruise_control_tpu.reporter.topic import FileMetricsTopic

__all__ = [
    "BrokerMetric", "CruiseControlMetric", "PartitionMetric", "TopicMetric",
    "metric_from_bytes", "metric_to_bytes",
    "CruiseControlMetricsReporter", "FileMetricsTopic",
]
