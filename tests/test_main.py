"""Process bootstrap tests (KafkaCruiseControlMain/App role)."""
import json

import pytest

from cruise_control_tpu.client import CruiseControlClient
from cruise_control_tpu.config import cruise_control_config
from cruise_control_tpu.main import (
    build_app, build_server, load_properties, seed_backend_from_spec,
)


def test_load_properties(tmp_path):
    p = tmp_path / "cruisecontrol.properties"
    p.write_text("""
# comment
webserver.http.port=0
num.metrics.windows = 7
goals=RackAwareGoal,DiskCapacityGoal
hard.goals=RackAwareGoal,DiskCapacityGoal
default.goals=RackAwareGoal,DiskCapacityGoal
anomaly.detection.goals=RackAwareGoal

self.healing.enabled=true
""")
    props = load_properties(str(p))
    assert props["webserver.http.port"] == "0"
    assert props["num.metrics.windows"] == "7"
    assert props["goals"] == "RackAwareGoal,DiskCapacityGoal"
    cfg = cruise_control_config(props)
    assert cfg.get_int("num.metrics.windows") == 7
    assert cfg.get_list("goals") == ["RackAwareGoal", "DiskCapacityGoal"]
    assert cfg.get_boolean("self.healing.enabled") is True


def test_bootstrap_end_to_end(tmp_path):
    """properties + cluster spec -> booted service answering REST requests."""
    spec = {
        "brokers": [{"id": b, "rack": f"r{b % 2}"} for b in range(4)],
        "partitions": [
            {"topic": "t", "partition": p, "replicas": [p % 4, (p + 1) % 4],
             "sizeMb": 100.0 + 10 * p, "bytesInRate": 10.0, "cpuUtil": 1.0}
            for p in range(8)
        ],
    }
    spec_path = tmp_path / "cluster.json"
    spec_path.write_text(json.dumps(spec))
    props = tmp_path / "cc.properties"
    props.write_text("webserver.http.port=0\n"
                     "min.samples.per.metrics.window=1\n"
                     "webserver.request.maxBlockTimeMs=120000\n")
    config = cruise_control_config(load_properties(str(props)))
    cc = build_app(config)
    seed_backend_from_spec(cc.backend, json.loads(spec_path.read_text()))
    cc.start_up()
    for i in range(8):
        cc.load_monitor.sample_once(now_ms=i * 300_000.0)
    server = build_server(cc, config)
    server.start()
    try:
        client = CruiseControlClient(f"127.0.0.1:{server.port}", timeout_s=300)
        state = client.state()
        assert state["MonitorState"]["state"] == "RUNNING"
        ks = client.kafka_cluster_state()
        assert ks["KafkaBrokerState"]["Summary"]["Replicas"] == 16
        assert len(ks["KafkaBrokerState"]["ReplicaCountByBrokerId"]) == 4
    finally:
        server.stop()
        cc.shutdown()


def test_security_enable_requires_credentials(tmp_path):
    config = cruise_control_config({"webserver.security.enable": True})
    cc = build_app(config)
    with pytest.raises(ValueError, match="credentials"):
        build_server(cc, config)


def test_env_config_provider(tmp_path, monkeypatch):
    """${env:VAR} indirection in property values (EnvConfigProvider.java
    role); unset variables fail loudly."""
    from cruise_control_tpu.main import load_properties

    monkeypatch.setenv("CC_TEST_PORT", "1234")
    p = tmp_path / "cc.properties"
    p.write_text("webserver.http.port=${env:CC_TEST_PORT}\n")
    assert load_properties(str(p))["webserver.http.port"] == "1234"
    p.write_text("jwt.secret.file=${env:CC_TEST_UNSET_VAR}\n")
    with pytest.raises(ValueError, match="CC_TEST_UNSET_VAR"):
        load_properties(str(p))
