"""Continuous pipelined service loop certification (PR 11).

Contracts:

1. **Backpressure stall/release** — ``meetCompletenessRequirements`` is the
   optimize stage's explicit backpressure signal: a cold monitor STALLS the
   stage (no error, no round); live sampling alone fills the windows on the
   UNIFIED service-mode clock (the backend's canonical ``now_ms``) and the
   stage releases on its own — no ``GET /bootstrap`` backfill required
   (the cold-start gating bug observed pre-PR-10).
2. **Shadow-slot upload path** — the sync stage runs while the previous
   round's fused chain is in flight on the DONATED resident state; the
   finalize program lands in fresh buffers (``session.shadow_syncs``) with
   ZERO new XLA compiles once warm, and steady rounds stay delta-mode /
   donated.
3. **Stale-generation drop** — a queued proposal round whose metadata
   generation moved (or that a newer round superseded) is DROPPED, never
   executed.
4. **Pipelined == blocking** — a pipelined steady round produces the same
   violation/certificate sets and proposal count as the blocking loop on
   the same windows, with the recorded RoundTrace carrying stage lanes +
   overlap fractions.
5. **Determinism** — the sim's lockstep drive (stage hand-offs keyed by
   tick, never wall clock): same (scenario, seed) => bit-identical timeline
   with pipelining ON, and identical to the blocking loop's timeline.
6. **Finisher scan/apply overlap** (the PERF round-11 engine lever):
   outcome parity with the legacy round body on the seeded fixtures.
"""
from __future__ import annotations

import json

import numpy as np
import pytest

from cruise_control_tpu.app import CruiseControl
from cruise_control_tpu.backend.simulated import SimulatedClusterBackend
from cruise_control_tpu.config import cruise_control_config
from cruise_control_tpu.pipeline import PipelinedServiceLoop, SampleRingBuffer

WINDOW_MS = 60_000.0


def _backend(brokers=8, partitions=60, seed=0):
    be = SimulatedClusterBackend()
    for b in range(brokers):
        be.add_broker(b, f"r{b % 4}")
    rng = np.random.default_rng(seed)
    for p in range(partitions):
        be.create_partition("t%d" % (p % 6), p,
                            [int(p % brokers), int((p + 1) % brokers)],
                            size_mb=float(rng.exponential(100.0)),
                            bytes_in_rate=5.0, bytes_out_rate=3.0,
                            cpu_util=0.2)
    return be


def _app(be, **props):
    cfg = {"num.metrics.windows": 3, "min.samples.per.metrics.window": 1,
           "metrics.window.ms": int(WINDOW_MS)}
    cfg.update(props)
    cc = CruiseControl(be, cruise_control_config(cfg))
    cc.start_up()
    return cc


@pytest.fixture(scope="module")
def warm_loop():
    """One app + pipeline with windows filled and the first (epoch-paying)
    rounds behind it — shared by the steady-path contracts."""
    be = _backend()
    cc = _app(be)
    pipe = PipelinedServiceLoop(cc)
    cc.service_pipeline = pipe
    for _ in range(4):
        be.advance(WINDOW_MS)
        pipe.step(optimize=True)
    return be, cc, pipe


# ------------------------------------------------------------- ring buffer
def test_ring_buffer_drops_oldest_and_preserves_order():
    class Batch:
        def __init__(self, n):
            self.partition_samples = [None] * n
            self.broker_samples = [None] * 4
            self.partition_blocks = ()

    ring = SampleRingBuffer(capacity=2)
    keys = {ring.push(float(i), Batch(50)) for i in range(3)}
    assert len(keys) == 1                     # one shape bucket
    assert ring.dropped == 1 and ring.pushed == 3
    drained = ring.drain()
    # oldest batch dropped; arrival order preserved
    assert [now for _seq, now, _s, _f in drained] == [1.0, 2.0]
    assert len(ring) == 0
    # a different shape lands in its own bucket lane
    ring.push(9.0, Batch(50))
    ring.push(10.0, Batch(5000))
    assert len(ring.state_json()["buckets"]) == 2
    assert [now for _seq, now, _s, _f in ring.drain()] == [9.0, 10.0]


# ------------------------------------------- backpressure + unified clock
def test_backpressure_stalls_then_releases_from_live_sampling_alone():
    """Cold start: the optimize stage STALLS on completeness (no raise);
    windows fill from live sampling on the backend clock alone — no
    GET /bootstrap — and the stage releases."""
    be = _backend()
    cc = _app(be)
    pipe = PipelinedServiceLoop(cc)
    out = pipe.step(optimize=True)
    assert out["optimize"] == {"stalled": True}
    assert pipe.stalled and pipe.stall_count == 1
    for _ in range(4):
        be.advance(WINDOW_MS)
        out = pipe.step(optimize=True)
    assert out["optimize"].get("optimized") is True
    assert not pipe.stalled and pipe.release_count == 1
    # the proposal cache is genuinely servable now
    assert cc.cached_proposals() is not None
    cc.shutdown()


def test_unified_clock_sampling_fills_windows_without_bootstrap():
    """The cold-start gating fix: ``sample_once`` stamps from the backend's
    canonical clock, so advancing the service's own clock fills windows.
    (Before PR 11 samples were stamped with WALL time regardless — a
    sim-clocked service could never fill windows by sampling and stayed
    completeness-gated until a bootstrap backfilled them.)"""
    from cruise_control_tpu.monitor.load_monitor import (
        ModelCompletenessRequirements, NotEnoughValidWindowsError,
    )
    be = _backend()
    cc = _app(be)
    lm = cc.load_monitor
    with pytest.raises(NotEnoughValidWindowsError):
        lm.cluster_model()
    for _ in range(3):
        be.advance(WINDOW_MS)
        lm.sample_once()            # no explicit now_ms: the unified clock
    assert lm.meet_completeness_requirements(
        ModelCompletenessRequirements(min_required_num_windows=2))
    ct, _meta = lm.cluster_model()
    assert int(np.asarray(ct.replica_valid).sum()) == 120
    # bootstrap's default range ends on the SAME clock: backfilling now can
    # only add samples to the same windows, never strand the live ones
    out = cc.bootstrap(clear_metrics=False)
    assert out["endMs"] == int(be.now_ms())
    cc.shutdown()


# --------------------------------------------------- shadow slot + compiles
def test_shadow_slot_sync_runs_while_state_is_lent(warm_loop):
    be, cc, pipe = warm_loop
    sess = cc.resident_session
    before = sess.shadow_syncs
    be.advance(WINDOW_MS)
    out = pipe.pipelined_round()
    assert out["result"] is not None
    # the overlapped sync ran while the optimize round held the donated
    # state (shadow-slot path) and stayed delta-mode
    assert sess.shadow_syncs > before
    assert out["sync_info"].get("mode") == "delta"
    assert sess.donated_rounds > 0


def test_shadow_slot_upload_path_zero_new_compiles(warm_loop):
    """Once warm, a pipelined round — optimize in flight + overlapped
    shadow-slot sync — compiles NOTHING new."""
    from cruise_control_tpu.common.tracing import count_compiles
    be, cc, pipe = warm_loop
    be.advance(WINDOW_MS)
    pipe.pipelined_round()          # burn any first-round variance
    be.advance(WINDOW_MS)
    with count_compiles() as cnt:
        out = pipe.pipelined_round()
    assert cnt.count == 0, f"shadow-slot round compiled {cnt.count} programs"
    assert out["sync_info"].get("mode") == "delta"


def test_round_trace_carries_stage_lanes_and_overlap(warm_loop):
    be, cc, pipe = warm_loop
    be.advance(WINDOW_MS)
    pipe.pipelined_round()
    be.advance(WINDOW_MS)
    out = pipe.pipelined_round()
    trace = out["trace"]
    stages = {s["stage"] for s in trace.stages}
    assert "ingest" in stages and "sync" in stages
    assert set(trace.overlap) >= {"ingest", "sync"}
    for lane in trace.overlap.values():
        assert 0.0 <= lane["overlap_frac"] <= 1.0
    # the JSON document serves the lanes too (/state?substates=ROUND_TRACES)
    doc = trace.to_json()
    assert doc["stages"] and doc["overlap"]
    # and the PIPELINE substate surfaces the loop's counters
    state = cc.state_json(substates=["PIPELINE"])
    assert state["PipelineState"]["optimizeRounds"] > 0


def test_pipelined_round_matches_blocking_round(warm_loop):
    """The A/B contract at test scale: same windows => the pipelined round's
    violation/certificate sets and proposal count are identical to the
    blocking loop's."""
    be, cc, pipe = warm_loop

    def sets(res):
        return [(g.name, g.violated_before, g.violated_after,
                 g.fixpoint_proven) for g in res.goal_results]

    be.advance(WINDOW_MS)
    # blocking round on the current windows
    cc.load_monitor.sample_once()
    blocking = cc.cached_proposals(force_refresh=True)
    # pipelined round on the SAME windows (its overlapped ingest/sync only
    # prepare the NEXT round; this round optimizes what the blocking round
    # just saw)
    piped = pipe.pipelined_round()["result"]
    assert sets(piped) == sets(blocking)
    assert len(piped.proposals) == len(blocking.proposals)


def test_session_sync_memo_skips_unchanged_inputs(warm_loop):
    be, cc, pipe = warm_loop
    sess = cc.resident_session
    be.advance(WINDOW_MS)
    cc.load_monitor.sample_once()
    first = sess.sync()
    assert "memo" not in first
    again = sess.sync()             # nothing changed since
    assert again.get("memo") is True
    assert again["mode"] == first["mode"]


# -------------------------------------------------------- stale generations
def test_stale_generation_round_dropped_not_executed(warm_loop):
    be, cc, pipe = warm_loop
    res = cc.cached_proposals()
    assert res.proposals
    execs_before = cc.executor.state_json()["numExecutions"]
    dropped_before = pipe.stale_rounds_dropped
    pipe.submit_execution(res.proposals[:2])
    be.add_broker(90 + dropped_before, "r9")   # metadata generation bump
    out = pipe.drain_executions()
    assert out == {"executed": 0, "dropped": 1, "installed": 0}
    assert pipe.stale_rounds_dropped == dropped_before + 1
    assert cc.executor.state_json()["numExecutions"] == execs_before


def test_superseded_round_dropped_newest_executes(warm_loop):
    be, cc, pipe = warm_loop
    res = cc.cached_proposals(force_refresh=True)
    assert len(res.proposals) >= 2
    pipe.submit_execution(res.proposals[:1])
    rnd = pipe.submit_execution(res.proposals[1:2])   # supersedes the first
    dropped_before = pipe.stale_rounds_dropped
    out = pipe.drain_executions()
    assert out["dropped"] == 1 and out["executed"] == 1
    assert pipe.stale_rounds_dropped == dropped_before + 1
    st = cc.executor.state_json()
    # the generation tag rides into the executor's state for observability
    assert st["proposalGeneration"] == rnd.metadata_generation


# ----------------------------------------------- routed FIX executions
def test_fix_routed_through_execute_stage_with_span_lineage():
    """PR 13 satellite (PR 11 residual c): with the THREADED pipeline, a
    self-healing operation submits its execution to the execute stage and
    returns immediately — the heal drains async on the pipeline's thread,
    the round is STICKY (a metadata-generation bump cannot drop it), and
    the PR 12 span lineage survives: the operation span has an "execution"
    child in the trace tree."""
    import time as _time
    # skewed placement: every replica on brokers 0-2 of 8 — the
    # self-healing chain (ReplicaDistributionGoal) must emit a real heal
    be = SimulatedClusterBackend()
    for b in range(8):
        be.add_broker(b, f"r{b % 4}")
    rng = np.random.default_rng(5)
    for p in range(60):
        be.create_partition("t%d" % (p % 6), p, [p % 3, (p + 1) % 3],
                            size_mb=float(rng.exponential(100.0)),
                            bytes_in_rate=5.0, bytes_out_rate=3.0,
                            cpu_util=0.2)
    cc = _app(be)
    for _ in range(4):
        be.advance(WINDOW_MS)
        cc.load_monitor.sample_once()
    pipe = PipelinedServiceLoop(cc)
    cc.service_pipeline = pipe
    # lockstep mode never routes (sim determinism) ...
    assert not pipe.accepts_fix_routing()
    assert not cc._route_fixes_async()
    pipe.start()
    try:
        # ... the threaded pipeline does
        assert pipe.accepts_fix_routing()
        assert cc._route_fixes_async()
        out = cc.rebalance(self_healing=True, dry_run=False,
                           reason="routed heal")
        assert out["executed"] is True
        # the execution drains on the pipeline's execute thread
        deadline = _time.monotonic() + 120.0
        while _time.monotonic() < deadline:
            st = cc.executor.state_json()
            if (pipe.executions_drained >= 1 and st["numExecutions"] >= 1
                    and not cc.executor.has_ongoing_execution()):
                break
            _time.sleep(0.05)
        assert pipe.executions_drained >= 1
        assert cc.executor.state_json()["numExecutions"] >= 1
        assert cc.sensors.meter(
            "pipeline-routed-fixes").to_json()["count"] == 1
    finally:
        pipe.stop()
    # span lineage: operation span -> execution child, walkable in the tree
    trees = cc.tracer.to_json()["trees"]
    op_nodes = [n for t in trees for n in t["roots"]
                if n["span_kind"] == "operation" and n["name"] == "REBALANCE"]
    assert op_nodes, trees
    kinds = {c["span_kind"] for n in op_nodes for c in n["children"]}
    assert "execution" in kinds, op_nodes


def test_sticky_round_survives_generation_bump():
    """A routed heal (sticky) executes even after the metadata generation
    moved; an ordinary round beside it is still dropped."""
    be = _backend(seed=6)
    cc = _app(be)
    for _ in range(4):
        be.advance(WINDOW_MS)
        cc.load_monitor.sample_once()
    pipe = PipelinedServiceLoop(cc)
    cc.service_pipeline = pipe
    res = cc.cached_proposals()
    assert len(res.proposals) >= 2
    pipe.submit_execution(res.proposals[:1])                  # ordinary
    pipe.submit_execution(res.proposals[1:2], sticky=True)    # routed heal
    be.add_broker(97, "r9")                  # metadata generation bump
    out = pipe.drain_executions()
    assert out == {"executed": 1, "dropped": 1, "installed": 0}


# ------------------------------------------------------------- determinism
@pytest.mark.slow
def test_sim_pipelined_timeline_bit_identical_and_matches_blocking():
    """Lockstep pipelined drive: same (scenario, seed) => bit-identical
    timeline with pipelining ON — and identical to the blocking loop's
    timeline (per-tick stage work is a deterministic function of the tick
    clock; ring hand-offs never reorder within a tick)."""
    from cruise_control_tpu.sim.catalog import SCENARIOS
    from cruise_control_tpu.sim.runner import ScenarioRunner
    sc = SCENARIOS["broker-death-smoke"]

    def timeline(pipelined):
        r = ScenarioRunner(sc, seed=3, pipelined=pipelined).run()
        r.assert_ok()
        return json.dumps(r.timeline, sort_keys=True), r

    t1, r1 = timeline(True)
    t2, r2 = timeline(True)
    assert t1 == t2
    assert r1.pipeline == r2.pipeline
    assert r1.pipeline["ingestRounds"] > 0
    t0, _ = timeline(False)
    assert t1 == t0


# --------------------------------------------- finisher scan/apply overlap
@pytest.mark.slow
def test_finisher_overlap_outcome_parity():
    """The PERF round-11 engine lever: overlap ON (leadership scan against
    the round-entry state, overlapping the move wave's apply) == overlap OFF
    on violation sets, certificate sets and proposal counts for the seeded
    parity fixtures, finisher forced on."""
    from cruise_control_tpu.analyzer.engine import EngineParams
    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
    from cruise_control_tpu.model.random_cluster import (
        RandomClusterSpec, generate,
    )
    chain = ["RackAwareGoal", "DiskCapacityGoal", "CpuCapacityGoal",
             "ReplicaDistributionGoal", "DiskUsageDistributionGoal",
             "LeaderReplicaDistributionGoal"]
    cfg = cruise_control_config({"analyzer.finisher.min.replicas": 0})

    def run(ct, meta, overlap):
        opt = GoalOptimizer(config=cfg, engine_params=EngineParams(
            finisher_overlap=overlap))
        r = opt.optimizations(ct, meta, goal_names=chain,
                              raise_on_failure=False,
                              skip_hard_goal_check=True)
        return ([(g.name, g.violated_after, g.fixpoint_proven)
                 for g in r.goal_results], len(r.proposals))

    for seed in (777, 881):
        ct, meta = generate(RandomClusterSpec(
            num_brokers=24, num_racks=4, num_topics=12, num_partitions=300,
            max_replication=2, skew=2.0, seed=seed))
        off_sets, off_props = run(ct, meta, False)
        on_sets, on_props = run(ct, meta, True)
        assert on_sets == off_sets
        assert on_props == off_props
