"""HTTP server: endpoint dispatch onto the CruiseControl facade.

Reference: servlet/KafkaCruiseControlServlet.java:40-120 (doGetOrPost
dispatch), KafkaCruiseControlApp.java:36-62 (server bootstrap; Jetty there,
stdlib ThreadingHTTPServer here — the control plane is host-side Python, the
TPU only ever sees the optimizer kernels), handler/sync + handler/async
(async ops respond 202 + progress until the future completes, resumable via
the User-Task-ID header), UserTaskManager.java, purgatory/Purgatory.java.

URL shape matches the reference: /kafkacruisecontrol/<endpoint>?... (the
prefix is optional here).
"""
from __future__ import annotations

import http.cookies
import json
import threading
from concurrent import futures
import traceback
import urllib.parse
import uuid as uuid_mod
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

# the reference's servlet-container session cookie (JSESSIONID role)
SESSION_COOKIE = "CCSESSIONID"

from cruise_control_tpu.api.endpoints import (
    ASYNC_ENDPOINTS, GET_ENDPOINTS, POST_ENDPOINTS, EndPoint, ParameterError,
    parse_params,
)
from cruise_control_tpu.api.progress import (
    GENERATING_CLUSTER_MODEL, OPTIMIZATION_FOR_GOAL, PENDING,
)
from cruise_control_tpu.api.purgatory import Purgatory
from cruise_control_tpu.api.responses import error_json, wrap
from cruise_control_tpu.api.security import AuthError, NoopSecurityProvider
from cruise_control_tpu.api.user_tasks import (
    USER_TASK_HEADER_NAME, UserTaskLimitError, UserTaskManager,
)
from cruise_control_tpu.common.retries import ServiceUnavailableError

URL_PREFIX = "/kafkacruisecontrol"


class AccessLog:
    """NCSA combined-ish access log (WebServerConfig webserver.accesslog.*:
    Jetty's RequestLogWriter role). Rotates daily — the current file is
    ``path``, finished days move to ``path.YYYY-MM-DD`` — and deletes rotated
    files older than the retention window (checked at startup and on each
    rotation, like Jetty's retainDays sweep)."""

    def __init__(self, path: str, retention_days: int = 14):
        import time as _t
        self._path = path
        self._retention_days = retention_days
        self._lock = threading.Lock()
        self._sweep()
        self._f = open(path, "a", buffering=1)
        self._day = _t.strftime("%Y-%m-%d")

    def _sweep(self) -> None:
        import glob
        import os
        import time as _t
        cutoff = _t.time() - self._retention_days * 86_400
        for old in glob.glob(self._path + ".*"):
            try:
                if os.path.getmtime(old) < cutoff:
                    os.unlink(old)
            except OSError:
                pass

    def _maybe_rotate(self) -> None:
        """Caller holds the lock. On day change, the open file is renamed to
        path.<previous-day> and a fresh one started."""
        import os
        import time as _t
        day = _t.strftime("%Y-%m-%d")
        if day == self._day:
            return
        try:
            self._f.close()
            os.replace(self._path, f"{self._path}.{self._day}")
        except OSError:
            pass
        self._f = open(self._path, "a", buffering=1)
        self._day = day
        self._sweep()

    def log(self, client_ip: str, method: str, path: str, status: int,
            length: int) -> None:
        import time as _t
        ts = _t.strftime("%d/%b/%Y:%H:%M:%S %z")
        with self._lock:
            self._maybe_rotate()
            self._f.write(f'{client_ip} - - [{ts}] "{method} {path} '
                          f'HTTP/1.1" {status} {length}\n')

    def close(self) -> None:
        self._f.close()


class CruiseControlServer:
    """Serves the 20 endpoints over HTTP against a CruiseControl facade."""

    def __init__(self, app, host: str = "127.0.0.1", port: int = 0,
                 security_provider=None, two_step_verification: bool = False,
                 max_block_ms: float = 10_000.0, max_active_user_tasks: int = 25,
                 completed_user_task_retention_ms: float = 24 * 3600 * 1000.0,
                 ssl_context=None, config=None, fleet=None):
        """``ssl_context``: an ``ssl.SSLContext`` to serve HTTPS
        (KafkaCruiseControlApp.java:100-121 webserver.ssl.* role).
        ``config``: the framework Config — consumed for the webserver.* key
        families (CORS, access log, UI serving, reason requirement, session
        path, per-endpoint parameters/request class overrides, purgatory and
        user-task cache caps).
        ``fleet``: a :class:`~cruise_control_tpu.fleet.FleetScheduler` —
        enables cluster-scoped routing: every endpoint accepts
        ``?cluster_id=<id>`` and dispatches to that tenant's facade with a
        per-tenant user-task quota (fleet.max.active.user.tasks.per.tenant);
        an unknown id is a declared 404, a malformed one a 400, and task ids
        never resolve across tenants (each tenant has its own task manager).
        ``app`` stays the default (un-scoped) facade."""
        self.app = app
        self.fleet = fleet
        self._tenant_user_tasks: dict[str, UserTaskManager] = {}
        self._tenant_tasks_lock = threading.Lock()
        self.security = security_provider or NoopSecurityProvider()
        self.two_step = two_step_verification
        cfg = config if config is not None else getattr(app, "config", None)
        if self.two_step and cfg is not None:
            self.purgatory = Purgatory(
                retention_ms=float(cfg.get_int(
                    "two.step.purgatory.retention.time.ms")),
                max_requests=cfg.get_int("two.step.purgatory.max.requests"),
                max_cached_completed=cfg.get_int(
                    "two.step.purgatory.max.cached.completed.requests"))
        else:
            self.purgatory = Purgatory() if two_step_verification else None
        by_type = {}
        if cfg is not None:
            from cruise_control_tpu.api.endpoints import EndpointType
            for etype, key in (
                    (EndpointType.KAFKA_ADMIN,
                     "max.cached.completed.kafka.admin.user.tasks"),
                    (EndpointType.KAFKA_MONITOR,
                     "max.cached.completed.kafka.monitor.user.tasks"),
                    (EndpointType.CRUISE_CONTROL_ADMIN,
                     "max.cached.completed.cruise.control.admin.user.tasks"),
                    (EndpointType.CRUISE_CONTROL_MONITOR,
                     "max.cached.completed.cruise.control.monitor.user.tasks")):
                by_type[etype] = cfg.get(key)
        self.user_tasks = UserTaskManager(
            max_active_tasks=max_active_user_tasks,
            completed_task_retention_ms=completed_user_task_retention_ms,
            session_expiry_ms=(float(cfg.get_int(
                "webserver.session.maxExpiryTime")) if cfg is not None
                else 60_000.0),
            max_cached_completed=(cfg.get_int(
                "max.cached.completed.user.tasks") if cfg is not None else 100),
            max_cached_completed_by_type=by_type)
        # cluster-scoped requests get a PER-TENANT task manager: quota
        # isolation (one tenant's burst 429s alone) and no cross-tenant
        # task-id resolution (wrong-tenant resumption is a 404)
        self._tenant_task_quota = (
            cfg.get_int("fleet.max.active.user.tasks.per.tenant")
            if cfg is not None else 10)
        self._tenant_task_retention_ms = completed_user_task_retention_ms
        self.max_block_ms = max_block_ms
        # webserver.http.cors.*: headers attached to every response (+ the
        # OPTIONS preflight) when enabled
        self._cors: dict[str, str] | None = None
        if cfg is not None and cfg.get_boolean("webserver.http.cors.enabled"):
            self._cors = {
                "Access-Control-Allow-Origin":
                    cfg.get_string("webserver.http.cors.origin"),
                "Access-Control-Allow-Methods":
                    cfg.get_string("webserver.http.cors.allowmethods"),
                "Access-Control-Expose-Headers":
                    cfg.get_string("webserver.http.cors.exposeheaders"),
            }
            # on EVERY response, not just the preflight: a credentialed
            # fetch (session cookie / Authorization) is discarded by the
            # browser unless the actual response grants credentials too.
            # The Fetch spec forbids credentials with a wildcard origin, so
            # the grant only applies when a concrete origin is configured.
            if cfg.get_string("webserver.http.cors.origin") != "*":
                self._cors["Access-Control-Allow-Credentials"] = "true"
        self._reason_required = bool(
            cfg is not None and cfg.get_boolean("request.reason.required"))
        self._session_path = (cfg.get_string("webserver.session.path")
                              if cfg is not None else "/")
        # webserver.ui.diskpath/urlprefix: static cruise-control-ui serving
        self._ui_dir = (cfg.get_string("webserver.ui.diskpath")
                        if cfg is not None else "")
        self._ui_prefix = ((cfg.get_string("webserver.ui.urlprefix")
                            if cfg is not None else "/*").rstrip("*") or "/")
        # webserver.api.urlprefix (WebServerConfig.java:73-75): the API mount
        # point; "/kafkacruisecontrol/*" by default. The trailing * matches
        # the reference's servlet-spec wildcard
        self._api_prefix = ((cfg.get_string("webserver.api.urlprefix")
                             if cfg is not None else URL_PREFIX + "/*")
                            .rstrip("*").rstrip("/") or URL_PREFIX)
        self._access_log = None
        if cfg is not None and cfg.get_boolean("webserver.accesslog.enabled"):
            self._access_log = AccessLog(
                cfg.get_string("webserver.accesslog.path"),
                retention_days=cfg.get_int("webserver.accesslog.retention.days"))
        # per-endpoint parameter-parser / request-handler overrides
        # (CruiseControlParametersConfig / CruiseControlRequestConfig)
        self._param_overrides: dict[EndPoint, object] = {}
        self._request_overrides: dict[EndPoint, object] = {}
        if cfg is not None:
            from cruise_control_tpu.config.defaults import endpoint_config_stem
            for ep in EndPoint:
                stem = endpoint_config_stem(ep.path)
                pc = cfg.get_class(f"{stem}.parameters.class")
                if pc is not None:
                    self._param_overrides[ep] = cfg.configure_instance(pc)
                rc = cfg.get_class(f"{stem}.request.class")
                if rc is not None:
                    self._request_overrides[ep] = cfg.configure_instance(rc)
        self._ssl = ssl_context
        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer((host, port), handler)
        if ssl_context is not None:
            self._httpd.socket = ssl_context.wrap_socket(
                self._httpd.socket, server_side=True)
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------ lifecycle
    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def base_url(self) -> str:
        host = self._httpd.server_address[0]
        scheme = "https" if self._ssl is not None else "http"
        return f"{scheme}://{host}:{self.port}{URL_PREFIX}"

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="cc-http", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self.user_tasks.close()
        with self._tenant_tasks_lock:
            for ut in self._tenant_user_tasks.values():
                ut.close()
            self._tenant_user_tasks.clear()
        if self._access_log is not None:
            self._access_log.close()

    # ------------------------------------------------------- fleet routing
    def tenant_binding(self, cluster_id: str):
        """(facade, task manager) for one tenant, or None when no fleet is
        mounted / the id is unknown (the dispatcher's declared-404 signal).
        Task managers are per tenant, created lazily with the per-tenant
        quota — a task id from tenant A can never resume under tenant B."""
        app = (self.fleet.app_for(cluster_id)
               if self.fleet is not None else None)
        if app is None:
            return None
        with self._tenant_tasks_lock:
            ut = self._tenant_user_tasks.get(cluster_id)
            if ut is None:
                ut = UserTaskManager(
                    max_active_tasks=self._tenant_task_quota,
                    completed_task_retention_ms=self._tenant_task_retention_ms)
                self._tenant_user_tasks[cluster_id] = ut
        return app, ut

    # ----------------------------------------------------------- dispatch
    def handle(self, method: str, endpoint: EndPoint, params: dict,
               client: str, task_id_header: str | None,
               app=None, user_tasks=None):
        """Returns (status_code, body_dict, extra_headers). ``app`` /
        ``user_tasks`` select a fleet tenant's facade + task manager; None
        = the default (un-scoped) instance."""
        import time as _time
        app = app if app is not None else self.app
        user_tasks = user_tasks if user_tasks is not None else self.user_tasks
        t0 = _time.monotonic()
        sensors = getattr(app, "sensors", None)
        # causal journal: one ROOT span per REST request (endpoint + method
        # + final status), on the app's clock — the per-endpoint latency
        # record tools/slo_diff.py gates journal p99s from
        tracer = getattr(app, "tracer", None)
        span = (tracer.span("request", endpoint.path, method=method)
                if tracer is not None else None)
        try:
            status, body, headers = self._handle(method, endpoint, params,
                                                 client, task_id_header,
                                                 app, user_tasks)
        except Exception as e:
            # parameter/validation errors raised mid-handling surface as
            # 4xx/5xx upstream — they are failed executions too
            if span is not None:
                span.end(error=type(e).__name__)
            if sensors is not None:
                sensors.timer(f"{endpoint.path}-failed-request-execution-timer"
                              ).record(_time.monotonic() - t0)
            raise
        if span is not None:
            span.end(status=status)
        # per-endpoint success/failure timers (KafkaCruiseControlServlet
        # .java:64 successfulRequestExecutionTimer + its failed twin); 202
        # progress polls / purgatory parks are NEITHER completed NOR failed
        # executions — recording them would make the timers describe polling
        if sensors is not None and status == 200:
            sensors.timer(f"{endpoint.path}-successful-request-execution-timer"
                          ).record(_time.monotonic() - t0)
        elif sensors is not None and status >= 400:
            sensors.timer(f"{endpoint.path}-failed-request-execution-timer"
                          ).record(_time.monotonic() - t0)
        return status, body, headers

    def _handle(self, method: str, endpoint: EndPoint, params: dict,
                client: str, task_id_header: str | None,
                app=None, user_tasks=None):
        headers: dict[str, str] = {}
        app = app if app is not None else self.app
        user_tasks = user_tasks if user_tasks is not None else self.user_tasks

        # <endpoint>.request.class override: the configured handler replaces
        # the built-in request processing wholesale
        override = self._request_overrides.get(endpoint)
        if override is not None:
            return override.handle(self, method, endpoint, params, client,
                                   task_id_header)

        # two-step verification: POSTs (except /review) must be reviewed
        # first. A request resuming an async task via User-Task-ID already
        # passed review when it was first submitted — re-submitting it to the
        # purgatory would dead-end the poll (SUBMITTED -> SUBMITTED).
        reviewed_rid = None
        if (self.purgatory is not None and method == "POST"
                and endpoint is not EndPoint.REVIEW
                and not (endpoint in ASYNC_ENDPOINTS and task_id_header)):
            rid = params.get("review_id")
            if rid is None:
                info = self.purgatory.add(endpoint, params, client)
                return 202, wrap({"reviewResult": info.to_json()}), headers
            # only consume the approval (APPROVED -> SUBMITTED) once the
            # operation is actually dispatched; a failed dispatch stays
            # APPROVED and can be retried
            self.purgatory.ensure_approved(rid, endpoint)
            params = {**self.purgatory.request_params(rid), "review_id": rid}
            reviewed_rid = rid

        if endpoint in ASYNC_ENDPOINTS:
            result = self._handle_async(method, endpoint, params, client,
                                        task_id_header, headers, app,
                                        user_tasks)
            if reviewed_rid is not None and result[0] in (200, 202):
                self.purgatory.submit(reviewed_rid, endpoint)
            return result
        result = 200, self._run_sync(endpoint, params, app), headers
        if reviewed_rid is not None:
            self.purgatory.submit(reviewed_rid, endpoint)
        return result

    # ------------------------------------------------------------- async
    def _handle_async(self, method, endpoint, params, client, task_id_header,
                      headers, app=None, user_tasks=None):
        app = app if app is not None else self.app
        user_tasks = user_tasks if user_tasks is not None else self.user_tasks
        # parameter problems must 400 before a task slot is consumed
        if params.get("excluded_topics"):
            import re
            try:
                re.compile(params["excluded_topics"])
            except re.error as e:
                raise ParameterError(
                    f"invalid excluded_topics regex "
                    f"{params['excluded_topics']!r}: {e}")
        if endpoint is EndPoint.TOPIC_CONFIGURATION and (
                not params["topic"] or params["replication_factor"] is None):
            raise ParameterError(
                "topic_configuration requires topic and replication_factor")
        if params.get("replica_movement_strategies"):
            try:
                app.executor.validate_strategies(
                    params["replica_movement_strategies"])
            except ValueError as e:
                raise ParameterError(str(e)) from None
        if (endpoint in (EndPoint.REBALANCE, EndPoint.PROPOSALS)
                and params.get("rebalance_disk") and params.get("goals")):
            intra = app.config.get_list("intra.broker.goals")
            bad = [g for g in params["goals"] if g not in intra]
            if bad:
                raise ParameterError(
                    f"rebalance_disk only accepts intra-broker goals; got {bad}"
                    f" (allowed: {intra})")
        # degraded-mode write gate: a mutating request against an unhealthy
        # backend boundary 503s up front (Retry-After = breaker reset)
        # WITHOUT consuming a user-task slot; a resumption poll by header is
        # a read of the existing task and passes through
        if (method == "POST" and params.get("dryrun", True) is not True
                and not task_id_header):
            # HA write gate: only the lease-holding leader mutates the
            # cluster — a standby 503s with Retry-After = its election
            # cadence, without consuming a user-task slot
            ha = getattr(app, "ha", None)
            if ha is not None and ha.role != "leader":
                raise ServiceUnavailableError(
                    f"{endpoint.path} rejected: this instance is a "
                    f"{ha.role}, not the leader",
                    retry_after_s=ha.retry_after_s())
            degraded = getattr(app, "degraded", None)
            if degraded is not None and degraded():
                raise ServiceUnavailableError(
                    f"{endpoint.path} rejected: backend degraded (open "
                    f"circuits: {app.fault_tolerance.open_circuits()})",
                    retry_after_s=app.fault_tolerance.retry_after_s())
        work = self._async_work(endpoint, params, app)
        # non-dry-run ops mutate the cluster: a completed one must not be
        # replayed from the session cache for a fresh request
        idempotent = method == "GET" or params.get("dryrun", True) is True
        try:
            task = user_tasks.get_or_create_task(
                client, endpoint, method, params, work, task_id=task_id_header,
                idempotent=idempotent)
        except KeyError as e:
            # unknown User-Task-ID: the task does not exist IN THIS SCOPE —
            # for cluster-scoped requests that includes another tenant's
            # task id (per-tenant managers never share ids). A declared
            # 404, never a 500 and never cross-tenant data.
            return 404, error_json(str(e)), headers
        except UserTaskLimitError as e:
            # the reference's servlet surfaces user-task overflow as 429 Too
            # Many Requests with a Retry-After, never a generic error — the
            # client backs off and resumes via User-Task-ID like a purgatory
            # park (UserTaskManager.java wrapAndThrowTooManyRequests role)
            headers["Retry-After"] = "1"
            return 429, error_json(str(e)), headers
        headers[USER_TASK_HEADER_NAME] = task.task_id
        try:
            result = task.future.result(timeout=self.max_block_ms / 1000.0)
            return 200, result, headers
        except futures.TimeoutError:
            # NB: concurrent.futures.TimeoutError only became an alias of the
            # builtin TimeoutError in Python 3.11 — catching the builtin alone
            # turns every still-running op into a 500 on 3.10
            return 202, wrap({"progress": task.progress.to_json(),
                              "operation": endpoint.path}), headers
        except TimeoutError:
            return 202, wrap({"progress": task.progress.to_json(),
                              "operation": endpoint.path}), headers
        except ServiceUnavailableError as e:
            # degraded-mode result: 503 + Retry-After, not a 500
            headers["Retry-After"] = str(int(e.retry_after_s))
            return 503, error_json(str(e)), headers
        except Exception as e:  # noqa: BLE001 — rendered as the error body
            if self._is_degraded_read_error(e):
                headers["Retry-After"] = "30"
                return 503, error_json(f"{type(e).__name__}: {e}"), headers
            return 500, error_json(f"{type(e).__name__}: {e}",
                                   traceback.format_exc()), headers

    @staticmethod
    def _is_degraded_read_error(e: Exception) -> bool:
        """Completeness gating / open-breaker failures are DECLARED
        degradation (503 + Retry-After), never undeclared 500s."""
        from cruise_control_tpu.common.retries import CircuitOpenError
        from cruise_control_tpu.monitor.load_monitor import (
            NotEnoughValidWindowsError,
        )
        return isinstance(e, (CircuitOpenError, NotEnoughValidWindowsError))

    def _async_work(self, endpoint: EndPoint, p: dict, app=None):
        """Build the callable for an async endpoint: runs on the user-task
        pool, reports progress, returns the response body dict."""
        app = app if app is not None else self.app

        def run(progress):
            progress.add_step(PENDING)
            try:
                if endpoint is EndPoint.LOAD:
                    progress.add_step(GENERATING_CLUSTER_MODEL)
                    return app.broker_load_json(
                        populate_disk_info=p["populate_disk_info"],
                        capacity_only=p["capacity_only"])
                if endpoint is EndPoint.PARTITION_LOAD:
                    progress.add_step(GENERATING_CLUSTER_MODEL)
                    from cruise_control_tpu.api.responses import (
                        partition_load_records_json,
                    )
                    return partition_load_records_json(app.partition_load(
                        sort_by=p["resource"], limit=p["entries"],
                        min_valid_partition_ratio=p["min_valid_partition_ratio"]))
                if endpoint is EndPoint.PROPOSALS:
                    progress.add_step(OPTIMIZATION_FOR_GOAL)
                    goals = p["goals"] or None
                    # mode flags preview the same goal chain /rebalance runs
                    if p["rebalance_disk"] and not goals:
                        goals = app.config.get_list("intra.broker.goals")
                    if p["kafka_assigner"]:
                        from cruise_control_tpu.analyzer.goals import (
                            kafka_assigner_goal_names,
                        )
                        goals = kafka_assigner_goal_names(goals or [])
                    res, freshness = app.cached_proposals_verbose(
                        force_refresh=p["ignore_proposal_cache"],
                        goal_names=goals,
                        excluded_topics=p["excluded_topics"])
                    body = {"summary": res.to_json(),
                            "stale": freshness["stale"]}
                    if freshness["stale"]:
                        # degraded read: cached proposals with provenance
                        # (model generation + age on the backend clock)
                        body["staleGeneration"] = freshness["generation"]
                        body["staleAgeMs"] = freshness["ageMs"]
                        body["staleReason"] = freshness["reason"]
                    return wrap(body)
                if endpoint is EndPoint.REBALANCE:
                    progress.add_step(OPTIMIZATION_FOR_GOAL)
                    if app.fleet_request_sink is not None:
                        # fleet admission engine (PR 18): a user rebalance
                        # also queues a rebalance-lane request, so the
                        # tenant's NEXT cache refresh preempts background
                        # precompute (heals still outrank it)
                        from cruise_control_tpu.pipeline import LANE_REBALANCE
                        app.fleet_request_sink(
                            LANE_REBALANCE, p["reason"] or "rebalance request")
                    return wrap(app.rebalance(
                        goal_names=p["goals"] or None, dry_run=p["dryrun"],
                        skip_hard_goal_check=p["skip_hard_goal_check"],
                        rebalance_disk=p["rebalance_disk"],
                        kafka_assigner=p["kafka_assigner"],
                        excluded_topics=p["excluded_topics"],
                        exclude_recently_removed_brokers=
                        p["exclude_recently_removed_brokers"],
                        exclude_recently_demoted_brokers=
                        p["exclude_recently_demoted_brokers"],
                        replica_movement_strategies=
                        p["replica_movement_strategies"] or None,
                        reason=p["reason"] or "rebalance request"))
                if endpoint is EndPoint.ADD_BROKER:
                    progress.add_step(OPTIMIZATION_FOR_GOAL)
                    return wrap(app.add_brokers(
                        p["brokerid"] or [], dry_run=p["dryrun"],
                        excluded_topics=p["excluded_topics"],
                        exclude_recently_removed_brokers=
                        p["exclude_recently_removed_brokers"],
                        exclude_recently_demoted_brokers=
                        p["exclude_recently_demoted_brokers"],
                        reason=p["reason"] or "add brokers"))
                if endpoint is EndPoint.REMOVE_BROKER:
                    progress.add_step(OPTIMIZATION_FOR_GOAL)
                    return wrap(app.remove_brokers(
                        p["brokerid"] or [], dry_run=p["dryrun"],
                        excluded_topics=p["excluded_topics"],
                        exclude_recently_removed_brokers=
                        p["exclude_recently_removed_brokers"],
                        exclude_recently_demoted_brokers=
                        p["exclude_recently_demoted_brokers"],
                        reason=p["reason"] or "remove brokers"))
                if endpoint is EndPoint.DEMOTE_BROKER:
                    progress.add_step(OPTIMIZATION_FOR_GOAL)
                    return wrap(app.demote_brokers(
                        p["brokerid"] or [], dry_run=p["dryrun"],
                        reason=p["reason"] or "demote brokers"))
                if endpoint is EndPoint.FIX_OFFLINE_REPLICAS:
                    progress.add_step(OPTIMIZATION_FOR_GOAL)
                    return wrap(app.fix_offline_replicas(
                        dry_run=p["dryrun"],
                        excluded_topics=p["excluded_topics"],
                        exclude_recently_removed_brokers=
                        p["exclude_recently_removed_brokers"],
                        exclude_recently_demoted_brokers=
                        p["exclude_recently_demoted_brokers"],
                        reason=p["reason"] or "fix offline replicas"))
                if endpoint is EndPoint.TOPIC_CONFIGURATION:
                    return wrap(app.fix_topic_replication_factor(
                        {p["topic"]: p["replication_factor"]},
                        reason=p["reason"] or "topic configuration"))
                raise AssertionError(f"unhandled async endpoint {endpoint}")
            finally:
                progress.finish()

        return run

    # -------------------------------------------------------------- sync
    def _run_sync(self, endpoint: EndPoint, p: dict, app=None) -> dict:
        app = app if app is not None else self.app
        # standby reads serve, but carry an explicit staleness marker: the
        # mirror trails the leader by the journal/sample tail lag
        ha = getattr(app, "ha", None)
        standby = ha is not None and ha.role != "leader"
        if endpoint is EndPoint.STATE:
            out = app.state_json(substates=p["substates"] or None)
            if (self.fleet is not None
                    and "FLEET" in [x.upper() for x in (p["substates"] or [])]):
                out["FleetState"] = self.fleet.state_json()
            if standby:
                out["stale"] = True
                out["staleReason"] = "standby mirror"
            return wrap(out)
        if endpoint is EndPoint.KAFKA_CLUSTER_STATE:
            out = app.kafka_cluster_state(verbose=bool(p["verbose"]))
            if standby:
                out["stale"] = True
                out["staleReason"] = "standby mirror"
            return wrap(out)
        if endpoint is EndPoint.PAUSE_SAMPLING:
            return wrap(app.pause_sampling(p["reason"] or "operator request"))
        if endpoint is EndPoint.RESUME_SAMPLING:
            return wrap(app.resume_sampling(p["reason"] or "operator request"))
        if endpoint is EndPoint.STOP_PROPOSAL_EXECUTION:
            return wrap(app.stop_proposal_execution(force=p["force_stop"]))
        if endpoint is EndPoint.BOOTSTRAP:
            return wrap(app.bootstrap(p["start"], p["end"],
                                      clear_metrics=p["clearmetrics"]))
        if endpoint is EndPoint.TRAIN:
            return wrap(app.train(p["start"], p["end"]))
        if endpoint is EndPoint.ADMIN:
            return wrap(app.admin(
                disable_self_healing_for=p["disable_self_healing_for"],
                enable_self_healing_for=p["enable_self_healing_for"],
                concurrent_partition_movements_per_broker=
                p["concurrent_partition_movements_per_broker"],
                concurrent_intra_broker_partition_movements=
                p["concurrent_intra_broker_partition_movements"],
                concurrent_leader_movements=p["concurrent_leader_movements"],
                execution_progress_check_interval_ms=
                p["execution_progress_check_interval_ms"],
                drop_recently_removed_brokers=p["drop_recently_removed_brokers"],
                drop_recently_demoted_brokers=p["drop_recently_demoted_brokers"]))
        if endpoint is EndPoint.USER_TASKS:
            tasks = self.user_tasks.all_tasks()
            wanted_ids = set(p["user_task_ids"] or [])
            wanted_clients = set(p["client_ids"] or [])
            wanted_eps = {e.lower() for e in (p["endpoints"] or [])}
            wanted_types = {t.lower() for t in (p["types"] or [])}
            rows = []
            for t in tasks:
                row = t.to_json()
                if wanted_ids and t.task_id not in wanted_ids:
                    continue
                if wanted_clients and t.client not in wanted_clients:
                    continue
                if wanted_eps and t.endpoint.path not in wanted_eps:
                    continue
                if wanted_types and row["Status"].lower() not in wanted_types:
                    continue
                if p["fetch_completed_task"] and t.done and not t.future.exception():
                    row["originalResponse"] = t.result_json()
                rows.append(row)
            return wrap({"userTasks": rows[:p["entries"]]})
        if endpoint is EndPoint.REVIEW_BOARD:
            if self.purgatory is None:
                raise ParameterError("two-step verification is not enabled")
            return wrap({"RequestInfo": self.purgatory.board(p["review_ids"])})
        if endpoint is EndPoint.REVIEW:
            if self.purgatory is None:
                raise ParameterError("two-step verification is not enabled")
            rows = []
            for rid in (p["approve"] or []):
                rows.append(self.purgatory.approve(
                    rid, p["reason"] or "approved").to_json())
            for rid in (p["discard"] or []):
                rows.append(self.purgatory.discard(
                    rid, p["reason"] or "discarded").to_json())
            return wrap({"RequestInfo": rows})
        raise AssertionError(f"unhandled sync endpoint {endpoint}")


def _make_handler(server: CruiseControlServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # NCSA access log -> quiet in-process
            pass

        def _send(self, status: int, body: dict, headers: dict[str, str]):
            payload = json.dumps(body, indent=2).encode("utf-8")
            self._send_raw(status, payload, "application/json", headers)

        def _send_raw(self, status: int, payload: bytes, ctype: str,
                      headers: dict[str, str]):
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(payload)))
            if server._cors is not None:
                for k, v in server._cors.items():
                    self.send_header(k, v)
            for k, v in headers.items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(payload)
            if server._access_log is not None:
                server._access_log.log(self.client_address[0],
                                       self.command, self.path, status,
                                       len(payload))

        def _serve_ui(self, path: str) -> bool:
            """Static cruise-control-ui files from webserver.ui.diskpath."""
            import mimetypes
            import os
            if not server._ui_dir or not path.startswith(server._ui_prefix):
                return False
            rel = path[len(server._ui_prefix):].lstrip("/") or "index.html"
            full = os.path.realpath(os.path.join(server._ui_dir, rel))
            root = os.path.realpath(server._ui_dir)
            if not full.startswith(root + os.sep) and full != root:
                return False   # traversal attempts fall through to the API 404
            if not os.path.isfile(full):
                return False
            with open(full, "rb") as f:
                data = f.read()
            ctype = mimetypes.guess_type(full)[0] or "application/octet-stream"
            self._send_raw(200, data, ctype, {})
            return True

        def do_OPTIONS(self):
            # CORS preflight (webserver.http.cors.enabled). The reference's
            # handleOptions (KafkaCruiseControlServletUtils.java:258-268) also
            # grants the request headers (reusing the exposeheaders value) and
            # credentials — without them a browser sending Authorization or
            # User-Task-ID fails preflight even with CORS enabled.
            if server._cors is None:
                self._send(405, error_json("OPTIONS unsupported"), {})
                return
            headers = dict(server._cors)
            headers["Access-Control-Allow-Headers"] = server._cors.get(
                "Access-Control-Expose-Headers", "")
            self._send_raw(204, b"", "text/plain", headers)

        def _resolve_cluster(self, cid: str):
            """Resolve one ?cluster_id= value to (facade, task manager).
            Sends the DECLARED error response itself and returns None when
            the id is malformed (400) or unknown / no fleet mounted (404) —
            wrong-tenant access is never a 500 and never another tenant's
            data."""
            from cruise_control_tpu.fleet import valid_cluster_id
            if not valid_cluster_id(cid):
                self._send(400, error_json(
                    f"malformed cluster_id {cid!r}"), {})
                return None
            binding = server.tenant_binding(cid)
            if binding is None:
                self._send(404, error_json(
                    f"unknown cluster_id {cid!r}"), {})
                return None
            return binding

        def _scoped_app(self, parsed):
            """The facade a pre-dispatch text endpoint (/metrics, /health)
            serves: the tenant's when ?cluster_id= rides the query, else the
            default app. None = an error response was already sent."""
            vals = urllib.parse.parse_qs(parsed.query).get("cluster_id")
            if not vals:
                return server.app
            binding = self._resolve_cluster(vals[-1])
            return binding[0] if binding is not None else None

        def _dispatch(self, method: str):
            parsed = urllib.parse.urlparse(self.path)
            path = parsed.path
            prefix = getattr(server, "_api_prefix", URL_PREFIX)
            if path.startswith(prefix):
                path = path[len(prefix):]
            elif path.startswith(URL_PREFIX):
                # the canonical prefix keeps working under a custom mount
                path = path[len(URL_PREFIX):]
            name = path.strip("/").split("/")[0]
            if name == "metrics" and method == "GET":
                # GET /metrics: Prometheus text exposition of the whole
                # MetricRegistry + flight-recorder last-round gauges. Not an
                # EndPoint enum member (the reference's 20-endpoint catalog
                # stays intact); authorized like /state — a monitor-level
                # read — and served as text/plain, not JSON.
                try:
                    _, role = server.security.authenticate(
                        self.headers, client_ip=self.client_address[0])
                    if not server.security.authorize(role, EndPoint.STATE,
                                                     "GET"):
                        raise AuthError(
                            f"role {role} may not access GET /metrics", 403)
                except AuthError as e:
                    self._send(e.status, error_json(str(e)), {})
                    return
                app = self._scoped_app(parsed)
                if app is None:
                    return
                try:
                    text = app.metrics_text()
                except Exception as e:  # noqa: BLE001 — rendered as the error body
                    self._send(500, error_json(f"{type(e).__name__}: {e}",
                                               traceback.format_exc()), {})
                    return
                self._send_raw(
                    200, text.encode("utf-8"),
                    "text/plain; version=0.0.4; charset=utf-8", {})
                return
            if name == "health" and method == "GET":
                # GET /health: live SLO attainment (detect/heal/request
                # targets from health.slo.*) + breaker/pipeline degradation
                # state, computed from the sensor registry. Like /metrics:
                # not an EndPoint enum member, authorized as a STATE-level
                # read, always 200 (the verdict is the body's "status").
                try:
                    _, role = server.security.authenticate(
                        self.headers, client_ip=self.client_address[0])
                    if not server.security.authorize(role, EndPoint.STATE,
                                                     "GET"):
                        raise AuthError(
                            f"role {role} may not access GET /health", 403)
                except AuthError as e:
                    self._send(e.status, error_json(str(e)), {})
                    return
                app = self._scoped_app(parsed)
                if app is None:
                    return
                try:
                    self._send(200, app.health_json(), {})
                except Exception as e:  # noqa: BLE001 — rendered as the error body
                    self._send(500, error_json(f"{type(e).__name__}: {e}",
                                               traceback.format_exc()), {})
                return
            endpoint = EndPoint.from_path(name)
            if endpoint is None:
                if method == "GET" and self._serve_ui(parsed.path):
                    return
                self._send(404, error_json(f"unknown endpoint {name!r}"), {})
                return
            allowed = GET_ENDPOINTS if method == "GET" else POST_ENDPOINTS
            if endpoint not in allowed:
                other = "POST" if method == "GET" else "GET"
                self._send(405, error_json(
                    f"{endpoint.path} only supports {other}"), {})
                return
            # the reference's trusted-proxy contract names the end user in the
            # ?doas= parameter; surface it to providers as the doAs header
            doas_vals = urllib.parse.parse_qs(parsed.query).get("doas")
            if doas_vals and not self.headers.get("X-Do-As"):
                self.headers["X-Do-As"] = doas_vals[0]
            try:
                principal, role = server.security.authenticate(
                    self.headers, client_ip=self.client_address[0])
                if not server.security.authorize(role, endpoint, method):
                    raise AuthError(f"role {role} may not access "
                                    f"{method} /{endpoint.path}", 403)
            except AuthError as e:
                challenge = getattr(server.security, "challenge", "Basic")
                hdrs = ({"WWW-Authenticate":
                         f'{challenge} realm="cruise-control"'
                         if challenge == "Basic" else challenge}
                        if e.status == 401 else {})
                # jwt.authentication.provider.url: browsers are bounced to
                # the login service; the original URL rides along as
                # ?origin=<url> so the login service can send the user back
                # (the reference JwtAuthenticator's {redirect}?origin= shape)
                hdrs.update(getattr(e, "extra_headers", None) or {})
                loc = hdrs.get("Location")
                if loc and "origin=" not in loc:
                    origin = urllib.parse.quote(
                        f"{'https' if server._ssl else 'http'}://"
                        f"{self.headers.get('Host', '')}{self.path}", safe="")
                    hdrs["Location"] = (
                        f"{loc}{'&' if '?' in loc else '?'}origin={origin}")
                self._send(e.status, error_json(str(e)), hdrs)
                return
            # per-session identity for user-task affinity (the reference's
            # HttpSession cookie, UserTaskManager.java): requests without a
            # session cookie get a fresh session — NAT'd clients no longer
            # collide on client-ip; cookie-less clients resume via the
            # explicit User-Task-ID header only
            cookies = http.cookies.SimpleCookie(self.headers.get("Cookie", ""))
            session_id = (cookies[SESSION_COOKIE].value
                          if SESSION_COOKIE in cookies else None)
            new_session = session_id is None
            if new_session:
                session_id = uuid_mod.uuid4().hex
            query = urllib.parse.parse_qs(parsed.query, keep_blank_values=True)
            if method == "POST":
                # form-encoded POST bodies fold into the query params
                try:
                    length = int(self.headers.get("Content-Length") or 0)
                    if length:
                        body = self.rfile.read(length).decode("utf-8")
                        ctype = self.headers.get("Content-Type", "")
                        if "json" in ctype:
                            parsed_body = json.loads(body or "{}")
                            if not isinstance(parsed_body, dict):
                                raise ValueError(
                                    "JSON body must be an object of parameters")
                            for k, v in parsed_body.items():
                                sval = (",".join(str(x) for x in v)
                                        if isinstance(v, list) else str(v))
                                query.setdefault(k, [sval])
                        else:
                            for k, vs in urllib.parse.parse_qs(
                                    body, keep_blank_values=True).items():
                                query.setdefault(k, vs)
                except (ValueError, UnicodeDecodeError) as e:
                    self._send(400, error_json(f"malformed request body: {e}"), {})
                    return
            scoped_app = scoped_tasks = None
            cid_vals = query.pop("cluster_id", None)
            if cid_vals:
                # cluster-scoped routing (?cluster_id=): select the tenant's
                # facade + per-tenant task manager before parameter parsing
                # (the id is a routing selector, not an endpoint parameter)
                binding = self._resolve_cluster(cid_vals[-1])
                if binding is None:
                    return
                scoped_app, scoped_tasks = binding
            if (server._reason_required and method == "POST"
                    and not query.get("reason", [""])[0]):
                # WebServerConfig request.reason.required
                self._send(400, error_json(
                    "a reason parameter is required on POST requests "
                    "(request.reason.required=true)"), {})
                return
            try:
                override = server._param_overrides.get(endpoint)
                if override is not None:
                    # <endpoint>.parameters.class: configured parser
                    parse = getattr(override, "parse", override)
                    params = parse(endpoint, query)
                else:
                    params = parse_params(endpoint, query)
            except ParameterError as e:
                self._send(400, error_json(str(e)), {})
                return
            client = f"{principal}@{session_id}"
            try:
                status, body, headers = server.handle(
                    method, endpoint, params, client,
                    self.headers.get(USER_TASK_HEADER_NAME),
                    app=scoped_app, user_tasks=scoped_tasks)
                if new_session:
                    headers = dict(headers or {})
                    headers["Set-Cookie"] = (
                        f"{SESSION_COOKIE}={session_id}; "
                        f"Path={server._session_path}; HttpOnly")
            except (ParameterError, KeyError, ValueError) as e:
                self._send(400, error_json(str(e)), {})
                return
            except ServiceUnavailableError as e:
                # degraded mode (writes while a breaker is open, reads with
                # nothing cached): 503 + Retry-After, the declared signal
                self._send(503, error_json(str(e)),
                           {"Retry-After": str(int(e.retry_after_s))})
                return
            except Exception as e:  # noqa: BLE001
                if CruiseControlServer._is_degraded_read_error(e):
                    self._send(503, error_json(f"{type(e).__name__}: {e}"),
                               {"Retry-After": "30"})
                    return
                self._send(500, error_json(f"{type(e).__name__}: {e}",
                                           traceback.format_exc()), {})
                return
            self._send(status, body, headers)

        def do_GET(self):
            self._dispatch("GET")

        def do_POST(self):
            self._dispatch("POST")

    return Handler
