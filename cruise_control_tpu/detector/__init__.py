from cruise_control_tpu.detector.anomalies import (
    Anomaly, AnomalyType, BrokerFailures, DiskFailures, GoalViolations,
    MaintenanceEvent, MetricAnomaly, SlowBrokers, TopicAnomaly,
)
from cruise_control_tpu.detector.detectors import (
    BrokerFailureDetector, DiskFailureDetector, GoalViolationDetector,
    SlowBrokerFinder,
)
from cruise_control_tpu.detector.maintenance import (
    FileMaintenanceEventReader, IdempotenceCache,
)
from cruise_control_tpu.detector.manager import AnomalyDetectorManager
from cruise_control_tpu.detector.metric_anomaly import PercentileMetricAnomalyFinder
from cruise_control_tpu.detector.notifier import (
    Action, AlertaSelfHealingNotifier, AlertFileNotifier, NoopNotifier,
    SelfHealingNotifier, SlackSelfHealingNotifier,
)
from cruise_control_tpu.detector.provisioner import (
    NoopProvisioner, ProvisionRecommendation, ProvisionStatus,
)
from cruise_control_tpu.detector.topic_anomaly import (
    PartitionSizeAnomalyFinder, TopicReplicationFactorAnomalyFinder,
)

__all__ = [
    "Anomaly", "AnomalyType", "BrokerFailures", "DiskFailures", "GoalViolations",
    "MaintenanceEvent", "MetricAnomaly", "SlowBrokers", "TopicAnomaly",
    "BrokerFailureDetector", "DiskFailureDetector", "GoalViolationDetector",
    "SlowBrokerFinder", "FileMaintenanceEventReader", "IdempotenceCache",
    "AnomalyDetectorManager", "PercentileMetricAnomalyFinder",
    "Action", "AlertaSelfHealingNotifier", "AlertFileNotifier", "NoopNotifier",
    "SelfHealingNotifier", "SlackSelfHealingNotifier",
    "NoopProvisioner", "ProvisionRecommendation", "ProvisionStatus",
    "PartitionSizeAnomalyFinder", "TopicReplicationFactorAnomalyFinder",
]
