"""Replay of the reference's DeterministicClusterTest parameter matrix.

Golden expectations are TRANSCRIBED from
cruise-control/src/test/java/com/linkedin/kafka/cruisecontrol/analyzer/
DeterministicClusterTest.java:97-247 (the JVM cannot run in this
environment, so the Java optimizer's contract is taken from the test's own
assertions rather than a live run):

- each (fixture, constraint, goal chain) combination must OPTIMIZE
  SUCCESSFULLY — no hard-goal OptimizationFailure — and pass the
  OptimizationVerifier checks (REGRESSION here; NEW_BROKERS/BROKEN_BROKERS
  are no-ops for these all-alive fixtures, OptimizationVerifier.java:185-206),
- EXCEPT (a) combinations whose hard-goal failure carries an
  "Insufficient capacity" / UNDER_PROVISIONED recommendation, which the Java
  test explicitly tolerates (DeterministicClusterTest.java:263-274 catch
  block), and (b) the two rows parameterized with
  expectedException=OptimizationFailureException
  (rackAwareUnsatisfiable x kafka-assigner goals,
  leaderReplicaPerBrokerUnsatisfiable x MinTopicLeadersPerBrokerGoal),
  which MUST raise.

Constraint values from TestConstants.java:36-46. PARITY.md tabulates each
row's transcribed Java outcome against this implementation's outcome.
"""
from __future__ import annotations

import dataclasses

import pytest

# engine-path compile-heavy; the fast tier (-m 'not slow') covers the engine via
# test_model/test_analyzer_goals/test_optimizer
pytestmark = pytest.mark.slow

from cruise_control_tpu.analyzer.env import BalancingConstraint
from cruise_control_tpu.analyzer.optimizer import (
    GoalOptimizer, OptimizationFailureError,
)
from cruise_control_tpu.detector.provisioner import ProvisionStatus
from cruise_control_tpu.model import fixtures
from tests.optimization_verifier import verify

# TestConstants.java:36-46
ZERO, LOW, MEDIUM, HIGH = 1.00, 1.05, 1.25, 1.65
CAP_HIGH, CAP_MEDIUM, CAP_LOW = 0.9, 0.8, 0.7
LARGE_CAP, MEDIUM_CAP, SMALL_CAP = 300_000.0, 200_000.0, 10.0

# DeterministicClusterTest.java:101-118 goal order
FULL_CHAIN = [
    "RackAwareGoal", "RackAwareDistributionGoal",
    "MinTopicLeadersPerBrokerGoal", "ReplicaCapacityGoal", "DiskCapacityGoal",
    "NetworkInboundCapacityGoal", "NetworkOutboundCapacityGoal",
    "CpuCapacityGoal", "ReplicaDistributionGoal", "PotentialNwOutGoal",
    "DiskUsageDistributionGoal", "NetworkInboundUsageDistributionGoal",
    "NetworkOutboundUsageDistributionGoal", "CpuUsageDistributionGoal",
    "LeaderReplicaDistributionGoal", "LeaderBytesInDistributionGoal",
    "TopicReplicaDistributionGoal", "PreferredLeaderElectionGoal",
]
KAFKA_ASSIGNER_CHAIN = ["KafkaAssignerEvenRackAwareGoal",
                        "KafkaAssignerDiskUsageDistributionGoal"]
MIN_LEADER_CHAIN = ["MinTopicLeadersPerBrokerGoal"]


def _constraint(balance_pct=None, capacity_threshold=None,
                max_replicas=6, min_topic_leaders=1):
    """Matrix constraint: DeterministicClusterTest's
    getDefaultCruiseControlProperties sets MAX_REPLICAS_PER_BROKER=6; the
    setters apply one value to all four resources."""
    kw = dict(max_replicas_per_broker=max_replicas,
              min_topic_leaders_per_broker=min_topic_leaders)
    if balance_pct is not None:
        kw["resource_balance_percentage"] = (balance_pct,) * 4
    if capacity_threshold is not None:
        kw["capacity_threshold"] = (capacity_threshold,) * 4
    return dataclasses.replace(BalancingConstraint(), **kw)


def _cap(value):
    from cruise_control_tpu.common.resources import Resource
    return {Resource.CPU: value, Resource.DISK: value,
            Resource.NW_IN: value, Resource.NW_OUT: value}


# The transcribed matrix: (row id, fixture factory, chain, constraint,
# min-leader topic regex, expected outcome).
# expected: "ok" = must succeed (verifications pass),
#           "ok_or_underprovisioned" = Java tolerates insufficient-capacity
#           failures (the SMALL_CAP rows), "raise" = must raise.
MATRIX = [
    # ----- REPLICA SWAP OPERATIONS (zero balance %) :123-129
    ("swap-disk-dist", lambda: fixtures.unbalanced_two_brokers(),
     ["DiskUsageDistributionGoal"], _constraint(balance_pct=ZERO), None, "ok"),
    ("swap-intra-disk", lambda: fixtures.unbalanced_two_brokers(),
     ["IntraBrokerDiskUsageDistributionGoal"], _constraint(balance_pct=ZERO),
     None, "ok"),
    # ----- TEST DECK 1: small cluster x balance % (cap thr MEDIUM,
    # min-leader topic T2) :136-144
    *[(f"small-bal-{pct}", fixtures.small_cluster_java, FULL_CHAIN,
       _constraint(balance_pct=pct, capacity_threshold=CAP_MEDIUM), "T2", "ok")
      for pct in (HIGH, MEDIUM, LOW)],
    # ----- TEST DECK 2: medium cluster x balance % (min-leader topic A) :146-155
    *[(f"medium-bal-{pct}", fixtures.medium_cluster_java, FULL_CHAIN,
       _constraint(balance_pct=pct, capacity_threshold=CAP_MEDIUM), "A", "ok")
      for pct in (HIGH, MEDIUM, LOW)],
    # ----- TEST DECK 3: small cluster x capacity thresholds :163-170
    *[(f"small-cap-{thr}", fixtures.small_cluster_java, FULL_CHAIN,
       _constraint(balance_pct=MEDIUM, capacity_threshold=thr), None, "ok")
      for thr in (CAP_HIGH, CAP_MEDIUM, CAP_LOW)],
    # ----- TEST DECK 4: medium cluster x capacity thresholds :171-178
    *[(f"medium-cap-{thr}", fixtures.medium_cluster_java, FULL_CHAIN,
       _constraint(balance_pct=MEDIUM, capacity_threshold=thr), None, "ok")
      for thr in (CAP_HIGH, CAP_MEDIUM, CAP_LOW)],
    # ----- TEST DECK 5: broker capacities (constraint left at MEDIUM
    # balance / LOW capacity threshold by the preceding loops) :180-198
    *[(f"small-cluster-capacity-{cap}",
       (lambda c: (lambda: fixtures.small_cluster_java(_cap(c))))(cap),
       FULL_CHAIN, _constraint(balance_pct=MEDIUM, capacity_threshold=CAP_LOW),
       None, "ok" if cap != SMALL_CAP else "ok_or_underprovisioned")
      for cap in (LARGE_CAP, MEDIUM_CAP, SMALL_CAP)],
    *[(f"medium-cluster-capacity-{cap}",
       (lambda c: (lambda: fixtures.medium_cluster_java(_cap(c))))(cap),
       FULL_CHAIN, _constraint(balance_pct=MEDIUM, capacity_threshold=CAP_LOW),
       None, "ok" if cap != SMALL_CAP else "ok_or_underprovisioned")
      for cap in (LARGE_CAP, MEDIUM_CAP, SMALL_CAP)],
    # ----- kafka-assigner mode :200-214
    ("ka-small", fixtures.small_cluster_java, KAFKA_ASSIGNER_CHAIN,
     _constraint(balance_pct=MEDIUM, capacity_threshold=CAP_LOW), None, "ok"),
    ("ka-medium", fixtures.medium_cluster_java, KAFKA_ASSIGNER_CHAIN,
     _constraint(balance_pct=MEDIUM, capacity_threshold=CAP_LOW), None, "ok"),
    ("ka-rack-satisfiable", fixtures.rack_aware_satisfiable,
     KAFKA_ASSIGNER_CHAIN,
     _constraint(balance_pct=MEDIUM, capacity_threshold=CAP_LOW), None, "ok"),
    ("ka-rack-unsatisfiable", fixtures.rack_aware_unsatisfiable,
     KAFKA_ASSIGNER_CHAIN,
     _constraint(balance_pct=MEDIUM, capacity_threshold=CAP_LOW), None,
     "raise"),
    # ----- MinTopicLeadersPerBrokerGoal rows :216-246
    ("minlead-satisfiable", fixtures.min_leader_satisfiable,
     MIN_LEADER_CHAIN, _constraint(), fixtures.TOPIC_MIN_LEADER, "ok"),
    ("minlead-satisfiable2", fixtures.min_leader_satisfiable2,
     MIN_LEADER_CHAIN, _constraint(), fixtures.TOPIC_MIN_LEADER, "ok"),
    ("minlead-unsatisfiable", fixtures.min_leader_unsatisfiable,
     MIN_LEADER_CHAIN, _constraint(), fixtures.TOPIC_MIN_LEADER, "raise"),
    ("minlead-satisfiable3", fixtures.min_leader_satisfiable3,
     MIN_LEADER_CHAIN, _constraint(min_topic_leaders=4),
     fixtures.TOPIC_MIN_LEADER, "ok"),
    ("minlead-satisfiable4", fixtures.min_leader_satisfiable4,
     MIN_LEADER_CHAIN, _constraint(), r"topic\d", "ok"),
]


def run_row(fixture_factory, chain, constraint, pattern):
    ct, meta = fixture_factory()
    opt = GoalOptimizer(constraint=constraint)
    return ct, meta, opt.optimizations(
        ct, meta, goal_names=chain, skip_hard_goal_check=True,
        min_leader_topic_pattern=pattern)


# first half here; tests/test_java_parity_matrix2.py runs the rest — the
# split halves the per-xdist-worker XLA:CPU compile count (a single worker
# compiling the whole matrix trips the 1-core host's compiler crash)
MATRIX_A = MATRIX[:len(MATRIX) // 2]
MATRIX_B = MATRIX[len(MATRIX) // 2:]


def _run_matrix_row(fixture_factory, chain, constraint, pattern, expected,
                    row_index=None):
    """Each row runs in a fresh SUBPROCESS (tools/gen_parity_table.py --row):
    one pytest worker accumulating every row's XLA:CPU programs crashes the
    LLVM compiler on this 1-core host; short-lived children + the persistent
    compile cache avoid it. The child applies the full contract (hard-goal
    satisfaction, tolerated insufficient-capacity, mandated raises,
    REGRESSION verification is covered by tests/optimization_verifier usage
    in the deterministic suite)."""
    import json
    import os
    import subprocess
    import sys

    tool = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "gen_parity_table.py")
    proc = subprocess.run(
        [sys.executable, tool, "--row", str(row_index)],
        capture_output=True, text=True, timeout=1700)
    assert proc.returncode == 0, proc.stderr[-2000:]
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    assert verdict["ok"], verdict


@pytest.mark.parametrize("row_index", range(len(MATRIX_A)),
                         ids=[m[0] for m in MATRIX_A])
def test_java_matrix(row_index):
    row = MATRIX[row_index]
    _run_matrix_row(*row[1:], row_index=row_index)
