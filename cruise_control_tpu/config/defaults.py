"""Framework config surface.

Analogue of the reference's 8 config-constants classes
(cruise-control/src/main/java/com/linkedin/kafka/cruisecontrol/config/constants/
AnalyzerConfig.java, MonitorConfig.java, ExecutorConfig.java,
AnomalyDetectorConfig.java, WebServerConfig.java, UserTaskManagerConfig.java, …),
which together `.define(...)` ~245 keys. The subset here covers everything the
current framework consumes; defaults mirror the reference's documented defaults
so behavior parity holds out of the box (e.g. AnalyzerConfig.java:52-219 for
balance/capacity thresholds).
"""
from __future__ import annotations

from cruise_control_tpu.config.configdef import (
    ConfigDef, ConfigKey, Importance, Type, at_least, between, in_set,
)

# --------------------------------------------------------------------------
# Goal catalog names (priority order = reference AnalyzerConfig DEFAULT_GOALS).
# --------------------------------------------------------------------------
DEFAULT_GOALS = [
    # the chain RUN by default (reference AnalyzerConfig
    # DEFAULT_DEFAULT_GOALS, :295-310): TopicReplicaDistribution runs BEFORE
    # the leader goals, and PreferredLeaderElectionGoal is deliberately NOT
    # here — it transfers leadership unconditionally (no acceptance checks,
    # PreferredLeaderElectionGoal.java:139), so running it after the leader
    # goals would re-violate them; it stays available on request via the
    # supported-goals list / explicit goal parameters.
    "RackAwareGoal",
    "MinTopicLeadersPerBrokerGoal",
    "ReplicaCapacityGoal",
    "DiskCapacityGoal",
    "NetworkInboundCapacityGoal",
    "NetworkOutboundCapacityGoal",
    "CpuCapacityGoal",
    "ReplicaDistributionGoal",
    "PotentialNwOutGoal",
    "DiskUsageDistributionGoal",
    "NetworkInboundUsageDistributionGoal",
    "NetworkOutboundUsageDistributionGoal",
    "CpuUsageDistributionGoal",
    "TopicReplicaDistributionGoal",
    "LeaderReplicaDistributionGoal",
    "LeaderBytesInDistributionGoal",
]
# the full supported-goal catalog is the goal registry itself
# (analyzer/goals/__init__.py GOAL_CLASSES) — surfaced via /state AnalyzerState

DEFAULT_HARD_GOALS = [
    "RackAwareGoal",
    "MinTopicLeadersPerBrokerGoal",
    "ReplicaCapacityGoal",
    "DiskCapacityGoal",
    "NetworkInboundCapacityGoal",
    "NetworkOutboundCapacityGoal",
    "CpuCapacityGoal",
]

DEFAULT_INTRA_BROKER_GOALS = [
    "IntraBrokerDiskCapacityGoal",
    "IntraBrokerDiskUsageDistributionGoal",
]

DEFAULT_ANOMALY_DETECTION_GOALS = [
    "RackAwareGoal",
    "ReplicaCapacityGoal",
    "DiskCapacityGoal",
]

_D = ConfigDef()

# --------------------------------------------------------------------------
# Analyzer (reference: config/constants/AnalyzerConfig.java)
# --------------------------------------------------------------------------
for _res, _bal in (("cpu", 1.10), ("disk", 1.10), ("network.inbound", 1.10), ("network.outbound", 1.10)):
    _D.define(name=f"{_res}.balance.threshold", type=Type.DOUBLE, default=_bal,
              validator=at_least(1.0), validator_doc=">= 1",
              doc=f"Max allowed ratio of {_res} utilization vs cluster average (1.10 = 10% slack).")
for _res, _cap in (("cpu", 0.7), ("disk", 0.8), ("network.inbound", 0.8), ("network.outbound", 0.8)):
    _D.define(name=f"{_res}.capacity.threshold", type=Type.DOUBLE, default=_cap,
              validator=lambda v: 0.0 < v <= 1.0, validator_doc="in (0, 1]",
              doc=f"Fraction of {_res} capacity usable before the capacity goal flags a broker.")
for _res in ("cpu", "disk", "network.inbound", "network.outbound"):
    _D.define(name=f"{_res}.low.utilization.threshold", type=Type.DOUBLE, default=0.0,
              validator=between(0.0, 1.0), validator_doc="in [0, 1]",
              doc=f"Below this avg utilization the {_res} distribution goal treats the cluster as low-utilization.")

_D.define(name="max.replicas.per.broker", type=Type.LONG, default=10000, validator=at_least(1),
          doc="ReplicaCapacityGoal limit (AnalyzerConfig.java:219).")
_D.define(name="replica.count.balance.threshold", type=Type.DOUBLE, default=1.10, validator=at_least(1.0),
          doc="ReplicaDistributionGoal balance percentage.")
_D.define(name="leader.replica.count.balance.threshold", type=Type.DOUBLE, default=1.10, validator=at_least(1.0),
          doc="LeaderReplicaDistributionGoal balance percentage.")
_D.define(name="topic.replica.count.balance.threshold", type=Type.DOUBLE, default=3.00, validator=at_least(1.0),
          doc="TopicReplicaDistributionGoal balance percentage.")
_D.define(name="topic.replica.count.balance.min.gap", type=Type.INT, default=2, validator=at_least(0),
          doc="Min gap between per-broker topic replica count limits.")
_D.define(name="topic.replica.count.balance.max.gap", type=Type.INT, default=40, validator=at_least(0),
          doc="Max gap between per-broker topic replica count limits.")
_D.define(name="goal.violation.distribution.threshold.multiplier", type=Type.DOUBLE, default=1.0,
          validator=at_least(1.0),
          doc="Extra leniency on distribution goals when triggered by the goal-violation detector.")
_D.define(name="topics.excluded.from.partition.movement", type=Type.STRING, default="",
          doc="Regex of topics no proposal may move/touch "
              "(AnalyzerConfig topics.excluded.from.partition.movement); "
              "per-request excluded_topics overrides it.")
_D.define(name="goals", type=Type.LIST, default=DEFAULT_GOALS, importance=Importance.HIGH,
          doc="Inter-broker goals in descending priority (AnalyzerConfig DEFAULT_GOALS order).")
_D.define(name="hard.goals", type=Type.LIST, default=DEFAULT_HARD_GOALS, importance=Importance.HIGH,
          doc="Goals that must be satisfied (skip only with skip_hard_goal_check).")
_D.define(name="default.goals", type=Type.LIST, default=None,
          doc="Goals used for proposal precomputation; when unset, falls back to `goals`.")
_D.define(name="intra.broker.goals", type=Type.LIST, default=DEFAULT_INTRA_BROKER_GOALS,
          doc="Intra-broker (cross-disk) goals in priority order.")
_D.define(name="min.topic.leaders.per.broker", type=Type.INT, default=1, validator=at_least(0),
          doc="MinTopicLeadersPerBrokerGoal per-broker minimum for matching topics.")
_D.define(name="topics.with.min.leaders.per.broker", type=Type.STRING, default="",
          doc="Regex of topics that must keep a minimum leader count on each broker.")
_D.define(name="proposal.expiration.ms", type=Type.LONG, default=900_000, validator=at_least(0),
          doc="Precomputed proposal freshness budget (AnalyzerConfig.java:208-209); "
              "0 = refresh continuously.")
_D.define(name="num.proposal.precompute.threads", type=Type.INT, default=1, validator=at_least(1),
          doc="Proposal precompute workers (host-side; AnalyzerConfig.java:225-230). "
              "One device program runs at a time on the TPU — extra threads only "
              "pipeline model builds against device execution.")
_D.define(name="analyzer.max.iterations", type=Type.INT, default=4096, validator=at_least(1),
          doc="TPU-specific: hard cap on greedy-engine iterations per goal per round.")
_D.define(name="analyzer.finisher.min.replicas", type=Type.INT, default=8192,
          doc="TPU-specific: clusters below this replica count compile their "
              "goal programs WITHOUT the exhaustive finisher phase (the "
              "finisher subprogram multiplies small-cluster compile times "
              "for certificates the plateau-fixpoint proof already covers "
              "at that scale). -1 always compiles it.")
_D.define(name="analyzer.candidate.replicas.per.broker", type=Type.INT, default=64, validator=at_least(1),
          doc="TPU-specific: top-K replicas per source broker considered per engine iteration "
              "(replaces the reference's sorted-replica scan, SortedReplicas.java).")
_D.define(name="analyzer.leader.candidates.per.iteration", type=Type.INT, default=32,
          validator=at_least(1),
          doc="TPU-specific: leadership-transfer candidate pool per engine pass.")
_D.define(name="analyzer.swap.candidates.per.iteration", type=Type.INT, default=32,
          validator=at_least(1),
          doc="TPU-specific: swap-out/in candidate pools per engine pass "
              "(hard-clamped at the TPU-safe bound in the engine).")
_D.define(name="analyzer.destination.spread", type=Type.INT, default=16, validator=at_least(1),
          doc="TPU-specific: destination affinity classes per wave (row fan-out width).")
_D.define(name="analyzer.stall.retries", type=Type.INT, default=8, validator=at_least(0),
          doc="TPU-specific: consecutive fruitless passes explored with salted "
              "candidate ranking before a goal exits.")
_D.define(name="analyzer.tail.pass.budget", type=Type.INT, default=64, validator=at_least(0),
          doc="TPU-specific: cumulative low-yield passes allowed per goal — the "
              "bounded convergence tail (reference analogue: the 1 s-per-broker "
              "swap cap, ResourceDistributionGoal.java:58).")
_D.define(name="analyzer.finisher.segments", type=Type.INT, default=8,
          validator=at_least(0),
          doc="TPU-specific: destination-segment spread of the exhaustive "
              "finisher's applied waves — brokers are partitioned into this "
              "many interaction-disjoint segments (greedy room-ranked "
              "striped coloring over the chain's combined acceptance room "
              "tables) and every scan candidate contributes its best "
              "destination PER SEGMENT, so one [K, B] re-score lands up to "
              "segments x K actions in a single batched admission+apply "
              "instead of K. Cross-segment boundary rows are re-validated "
              "by the cumulative-budget admission, so the applied set stays "
              "certified equivalent to some sequential order (the "
              "_finisher_wave argument). 0 or 1 = legacy single-destination "
              "waves. The active count is a traced budget leaf (toggling "
              "reuses compiled programs); the configured value also sets "
              "the static spread width.")
_D.define(name="analyzer.pass.waves", type=Type.INT, default=4, validator=at_least(1),
          doc="TPU-specific: rank-banded admission waves per budgeted engine "
              "pass — one O(R) candidate keying feeds up to this many scored "
              "[K, B] waves against the live state (engine pass pipeline; "
              "1 = legacy single-wave passes, bit-identical to pre-wave "
              "behavior). Traced budget leaf: changing it reuses compiled "
              "programs. The optimizer additionally raises it to 4 at "
              ">= 256k-replica clusters.")
_D.define(name="analyzer.compact.keying", type=Type.BOOLEAN, default=False,
          doc="TPU-specific: run per-pass candidate selection (stall salt + "
              "top-k) over the goal's compacted eligible prefix when it fits "
              "the pool, so selection cost tracks remaining work instead of "
              "R (engine._select_candidates; exact on CPU, exactness UPGRADE "
              "over approx top-k on TPU). Default off: on CPU hosts the "
              "compaction scatter costs more than the full-R selection it "
              "replaces (docs/PERF.md round 6); enable on accelerators.")
_D.define(name="analyzer.chain.cache", type=Type.BOOLEAN, default=True,
          doc="TPU-specific: fold interval-form prev-goal accept_move vetoes "
              "into one combined per-broker room table per pass "
              "(GoalKernel.accept_move_rooms) instead of one [K, B] mask per "
              "chain goal per branch and per finisher-scan chunk. "
              "Mathematically exact; bitwise within one f32 ulp of the "
              "per-goal masks at band edges. Off = per-goal masks.")
_D.define(name="analyzer.compute.dtype", type=Type.STRING, default="auto",
          validator=in_set("auto", "float32", "bfloat16"),
          validator_doc="one of: auto, float32, bfloat16",
          doc="TPU-specific: precision policy of the engine's wide score "
              "sweeps. bfloat16 halves the [R, M] per-replica load streams "
              "— the HBM-bandwidth wall of the [K, B]/[KL, F] scoring and "
              "[R] keying fusions — while the broker-level accumulators "
              "the scores difference read the f32 Kahan-COMPENSATED sums "
              "(util + residual; engine._sweep_state), and gain accounting, "
              "min-gain application, severity/violation measures and the "
              "fixpoint-certificate scans ALWAYS stay float32. Violation "
              "counts and certificate sets match the f32 pipeline on the "
              "certified parity fixtures (tests/test_dtype_policy.py). "
              "'auto' resolves to bfloat16 at >= 256k replicas and float32 "
              "below (the compensated accounting + segment-parallel "
              "finisher closed the rung-4 violation gap that held auto-on "
              "back through round 7; docs/PERF.md round 9). STATIC knob: "
              "changing it recompiles the engine programs (documented; "
              "budget knobs stay traced).")
_D.define(name="analyzer.compact.tables", type=Type.BOOLEAN, default=True,
          doc="TPU-specific: store the device cluster tables compact — "
              "int16 broker/rack/topic index columns where the axis fits, "
              "int8 logdir indices, int16 (topic x broker) / (partition x "
              "rack) count tables, bit-packed eligibility-mask uploads — "
              "cutting the cold env upload and the per-pass gather/scatter "
              "bytes. Index values are exact in any integer dtype and every "
              "overflow-capable arithmetic site upcasts to int32, so results "
              "are bit-identical to int32 tables (certified in "
              "tests/test_dtype_policy.py). Off = int32 everywhere.")
_D.define(name="analyzer.session.donation", type=Type.BOOLEAN, default=True,
          doc="TPU-specific: resident-session double-buffer protocol — hand "
              "the device-RESIDENT EngineState to the optimizer for buffer "
              "DONATION (the fused chain reuses its input buffers for the "
              "round's result) instead of defensively copying the full "
              "state every round; the next sync rematerializes the observed "
              "state from the session's host assignment mirrors inside the "
              "finalize program it already runs. Eliminates a full-state "
              "device copy (and its allocation spike) from every steady "
              "round. Off = defensive copy (pre-PR-5 behavior).")
_D.define(name="analyzer.fused.chain.min.replicas", type=Type.INT, default=65_536,
          doc="TPU-specific: at/above this cluster size the whole goal chain "
              "compiles into ONE device program (one dispatch instead of one "
              "per goal — each execution costs ~1 s fixed overhead on a "
              "tunneled TPU); below it per-goal programs keep compiles small. "
              "-1 disables fusion.")
_D.define(name="analyzer.resident.session.enabled", type=Type.BOOLEAN, default=True,
          doc="TPU-specific: keep ONE device-resident padded ClusterEnv/"
              "EngineState per shape bucket (analyzer/session.py) and feed it "
              "monitor/backend DELTAS between proposal rounds, so the "
              "steady-state precompute and self-healing FIX rounds skip the "
              "snapshot->pad->upload model rebuild (the reference's "
              "continuously-updated ClusterModel + GoalOptimizer precompute "
              "thread role). Requests with custom topic/broker exclusions "
              "fall back to the full build automatically.")
_D.define(name="analyzer.session.max.delta.fraction", type=Type.DOUBLE, default=0.25,
          validator=at_least(0.0),
          doc="Resident-session churn budget: when the replica slots touched "
              "by deltas since the epoch's rebuild exceed this fraction of "
              "the cluster's replicas, the next round rebuilds from scratch "
              "(a fresh epoch) instead of applying further deltas.")
_D.define(name="analyzer.incremental.enabled", type=Type.BOOLEAN, default=True,
          doc="Incremental re-optimization master switch: the resident "
              "session tracks per-round deltas (dirty brokers/topics, load "
              "drift, broker-axis flips) and persists the previous round's "
              "violation verdicts + fixpoint certificates as host-side "
              "carryover, and the optimizer compiles its chain programs with "
              "a traced bool[R] seed-mask argument (all-ones on full rounds "
              "— bit-identical to the unmasked program) so the revalidate/"
              "seeding knobs below toggle without recompiling. Off = "
              "pre-PR-16 behavior: every round re-runs the full chain.")
_D.define(name="analyzer.incremental.revalidate", type=Type.BOOLEAN, default=True,
          doc="Certificate re-validation fast path: a steady round whose "
              "deltas since the last optimize carry ZERO structural churn, "
              "no broker-axis change, and load-row drift within "
              "analyzer.incremental.revalidate.tolerance re-checks every "
              "goal's carried verdict with ONE [B]-level violation reduction "
              "per goal (no donation, no selection/passes/finisher) and, "
              "when all verdicts match, returns the carried result — "
              "sub-second instead of the full chain. Any mismatch falls "
              "through to the full goal programs. Requires at least one real "
              "delta sync since the last optimize (forced re-runs of an "
              "unchanged model stay full rounds).")
_D.define(name="analyzer.incremental.revalidate.tolerance", type=Type.DOUBLE,
          default=0.0, validator=at_least(0.0),
          doc="Max accumulated relative load-row drift (vs the rows the "
              "carried round optimized) a re-validated round may carry. 0.0 "
              "= bit-stable loads only, which keeps the fast path exact: the "
              "carried result was computed on an identical state. Nonzero "
              "values trade exactness for hit rate under jittery metrics — "
              "the verdict re-check still guards every goal.")
_D.define(name="analyzer.incremental.seed.dirty", type=Type.BOOLEAN, default=False,
          doc="Dirty-set candidate seeding: on delta rounds under the churn "
              "budget, goals that were SATISFIED last round key their "
              "budgeted selection pools only from replicas on brokers/topics "
              "touched by the delta (engine._mask_key); goals violated last "
              "round and the exhaustive finisher scans stay full-R, and any "
              "seeded goal that ends violated without a certificate re-runs "
              "unmasked (traced fallback), so parity is one-sided: "
              "violations only shrink, certificates only appear (the PR 13 "
              "escalation precedent; gated by tools/churn_ab.py + "
              "tools/slo_diff.py). Off by default like compact keying: an "
              "opt-in perf lever with a documented contract.")
_D.define(name="analyzer.pass.chunk", type=Type.INT, default=8,
          validator=at_least(0),
          doc="Convergence-gated pass scheduling (PR 19): dispatch each "
              "goal's budgeted loop in host-gated chunks of this many "
              "passes; after each chunk one cheap device->host probe stops "
              "dispatching as soon as the goal QUIESCES (a whole chunk "
              "admitted zero actions while the loop's own exit condition "
              "still held — provably bit-identical state, so the remaining "
              "salted budget could only re-rank the same starved pools). "
              "Same compiled pass program, fewer invocations; 0 restores "
              "the monolithic single-dispatch loop. Traced budget leaf: "
              "resizing the chunk reuses compiled programs.")
_D.define(name="analyzer.pass.chunk.min.replicas", type=Type.INT, default=8192,
          validator=at_least(-1),
          doc="Cluster-size floor for chunked dispatch: below this many "
              "(padded) replicas the per-chunk host sync costs more than "
              "the passes it saves and goals run the legacy monolithic "
              "program; -1 disables chunking everywhere. The sharded "
              "engine and the measured-durations debug path always use "
              "the monolithic dispatch.")
_D.define(name="analyzer.pass.adaptive.budgets", type=Type.BOOLEAN, default=True,
          doc="Churn-adaptive budgets (PR 19): on dirty-seeded reduced "
              "rounds, clamp each reduced goal's stall/tail/finisher-round "
              "budgets to what the MEASURED dirty-set size can need "
              "(ceil(dirty / candidate pool) + 1 passes drain the set once "
              "and one more proves quiescence), floored at "
              "analyzer.pass.adaptive.floor.passes. Every clamped field is "
              "a traced leaf — reduced<->full flips reuse the compiled "
              "programs — and fallback re-runs keep the static budgets as "
              "their floor, so the one-sided seeding contract is untouched.")
_D.define(name="analyzer.pass.adaptive.floor.passes", type=Type.INT, default=4,
          validator=at_least(1),
          doc="Minimum per-goal stall/pass budget an adaptive reduced round "
              "may clamp down to (keeps salted exploration alive on "
              "pathological seeds).")
_D.define(name="analyzer.pass.certificate.skip", type=Type.BOOLEAN, default=True,
          doc="Certificate-gated finisher skip (PR 19): a goal that carried "
              "a violated-at-fixpoint certificate from the previous round, "
              "quiesced with ZERO actions this reduced round, and saw zero "
              "actions from earlier chain goals skips the exhaustive "
              "finisher scans — the carried certificate (re-stamped with "
              "its measured remaining counts) stands in as the proof no "
              "work remains, the DESIGN §20 memo argument at per-goal "
              "granularity. The full-R fallback sweep and escalation treat "
              "the goal exactly like any persistent proven violation.")
_D.define(name="analyzer.pass.goal.shortcircuit", type=Type.BOOLEAN, default=True,
          doc="Chain-level short-circuit (PR 19): a reduced-round goal that "
              "enters the chain SATISFIED and whose seeded candidate keys "
              "rank zero dirty replicas eligible for any of its action "
              "kinds runs as ONE [B]-level probe instead of its full "
              "program (GoalResult.mode == 'skipped'). Bit-exact by "
              "construction: all-NEG_INF selection pools admit nothing, so "
              "the skipped program could only no-op.")
_D.define(name="analyzer.profile.level", type=Type.STRING, default="off",
          validator=in_set("off", "pass", "stage"),
          validator_doc="one of: off, pass, stage",
          doc="TPU-specific: per-round engine profiling depth (retires the "
              "CC_PROFILE_SEGMENTS env hack; the env var is still honored as "
              "a deprecated alias for 'stage' when this key is left at its "
              "default). 'pass' surfaces the already-traced pass-level "
              "profile (passes, per-branch action split, admission waves, "
              "finisher actions) into the flight recorder at ZERO device "
              "cost — the async dispatch pipeline is untouched; 'stage' "
              "additionally blocks per fused-chain segment "
              "(block_until_ready) so GoalResult.duration_s carries honest "
              "per-segment seconds — debug only, it serializes the dispatch "
              "pipeline it measures. Host-side knob: toggling it never "
              "triggers a recompile (certified in tests/test_tracing.py).")
_D.define(name="flight.recorder.capacity", type=Type.INT, default=64,
          validator=at_least(1),
          doc="Flight recorder ring-buffer size: how many per-round traces "
              "(common/tracing.py RoundTrace) are retained and served by "
              "/state?substates=ROUND_TRACES. Recording is always on; the "
              "buffer bound is the memory cap.")
_D.define(name="journal.path", type=Type.STRING, default="",
          doc="Durable event journal file (common/tracing.EventJournal): "
              "append-only JSONL of spans, round summaries, executor task "
              "census transitions, breaker state changes and pipeline stage "
              "notes — the tail target an HA standby consumes. Empty "
              "(default) keeps the journal in-memory only (the bounded ring "
              "still feeds /state?substates=TRACES and the sim's episode "
              "journal slices).")
_D.define(name="journal.fsync", type=Type.STRING, default="never",
          validator=in_set("never", "rotate", "always"),
          validator_doc="one of: never, rotate, always",
          doc="Journal durability policy: 'never' (OS page cache only), "
              "'rotate' (fsync when a file fills), 'always' (fsync every "
              "append — the HA-standby tail setting; costs one fsync per "
              "control-plane event, never on the device path).")
_D.define(name="journal.max.bytes.per.file", type=Type.INT, default=16_777_216,
          validator=at_least(4096),
          doc="Journal size rotation threshold: the active file rotates to "
              "journal.path.1..N once it would exceed this many bytes.")
_D.define(name="journal.max.files", type=Type.INT, default=8,
          validator=at_least(1),
          doc="How many rotated journal files to keep (journal.path.1 is "
              "the most recently rotated; older files are deleted).")
_D.define(name="journal.memory.lines", type=Type.INT, default=65_536,
          validator=at_least(16),
          doc="Bounded in-memory ring of recent journal lines (kept with or "
              "without a journal.path) — what ScenarioResult.journal and "
              "path-less deployments read.")
_D.define(name="ha.lease.key", type=Type.STRING,
          default="cruise-control/leader",
          doc="Coordination-lease key for HA leader election "
              "(cruise_control_tpu/ha/): one lease per served cluster, "
              "compare-and-swapped in the backend (ClusterBackend."
              "lease_acquire) so at most one controller holds the leader "
              "role at any backend-clock instant.")
_D.define(name="ha.lease.ttl.ms", type=Type.LONG, default=30_000,
          validator=at_least(1),
          doc="Leader lease time-to-live on the backend clock: a leader "
              "that fails to renew within this window loses the lease and a "
              "standby's next acquire attempt wins. Failover detection time "
              "is bounded by this TTL plus the standby's tick cadence.")
_D.define(name="ha.lease.renew.ms", type=Type.LONG, default=10_000,
          validator=at_least(1),
          doc="How often the leader renews its lease (must be well under "
              "ha.lease.ttl.ms; renewal is a same-holder lease_acquire, so "
              "the fencing epoch is unchanged while leadership holds).")
_D.define(name="journal.trace.capacity", type=Type.INT, default=1024,
          validator=at_least(16),
          doc="Span-tracer ring size: how many FINISHED spans are retained "
              "for /state?substates=TRACES trace-tree serving (the journal "
              "keeps the full history; this bounds the live query surface).")
_D.define(name="health.slo.detect.p95.ms", type=Type.INT, default=120_000,
          validator=at_least(1),
          doc="GET /health SLO target: p95 of anomaly-detection-to-fix-timer "
              "(detection -> fix dispatched) must stay at/below this many "
              "milliseconds for the detect SLO to count as attained.")
_D.define(name="health.slo.heal.p95.ms", type=Type.INT, default=900_000,
          validator=at_least(1),
          doc="GET /health SLO target: p95 of every per-type "
              "*-self-healing-fix-timer (detection -> heal execution "
              "complete, injected-clock seconds) must stay at/below this "
              "many milliseconds.")
_D.define(name="health.slo.request.p99.ms", type=Type.INT, default=2_000,
          validator=at_least(1),
          doc="GET /health SLO target: p99 of each per-endpoint "
              "*-successful-request-execution-timer must stay at/below this "
              "many milliseconds.")
_D.define(name="goal.balancedness.priority.weight", type=Type.DOUBLE, default=1.1,
          validator=at_least(1.0),
          doc="Balancedness score: weight step per goal priority rank "
              "(AnalyzerConfig goal.balancedness.priority.weight).")
_D.define(name="goal.balancedness.strictness.weight", type=Type.DOUBLE, default=1.5,
          validator=at_least(1.0),
          doc="Balancedness score: extra weight of hard goals "
              "(AnalyzerConfig goal.balancedness.strictness.weight).")
_D.define(name="allow.capacity.estimation.on.proposal.precompute", type=Type.BOOLEAN,
          default=True,
          doc="Whether proposal precompute may run on estimated broker "
              "capacities (AnalyzerConfig.java); the explicit /proposals "
              "allow_capacity_estimation parameter governs user requests.")
_D.define(name="optimization.options.generator.class", type=Type.CLASS,
          default="cruise_control_tpu.analyzer.options.DefaultOptimizationOptionsGenerator",
          doc="Pluggable OptimizationOptions generator "
              "(AnalyzerConfig optimization.options.generator.class).")
_D.define(name="analyzer.finisher.escalation", type=Type.BOOLEAN, default=True,
          doc="Certificate-driven budget escalation (the BENCH_r05 Leader*/"
              "LeaderBytesIn tail closer): a goal whose budgeted loop AND "
              "finisher exit still-violated WITHOUT a fixpoint certificate, "
              "but with a small measured remaining-action count, re-enters "
              "its finisher once at the end of the chain with widened "
              "windows (rounds/swap passes x the escalation factor) and "
              "EVERY other chain goal's acceptance veto in force — so "
              "violation sets only shrink and certificates only appear "
              "(one-sided outcome parity, tests/test_escalation.py). "
              "Engages only where the finisher runs at all "
              "(analyzer.finisher.min.replicas).")
_D.define(name="analyzer.finisher.escalation.max.remaining", type=Type.INT,
          default=2048, validator=at_least(0),
          doc="Escalate only goals whose finisher scans measured at most "
              "this many remaining accepted positive-gain actions (moves + "
              "transfers + swap-window pairs): a small count means the tail "
              "is close and widened windows can close it; a large one means "
              "the cluster genuinely cannot converge under the chain's "
              "vetoes and more budget is waste.")
_D.define(name="analyzer.finisher.escalation.factor", type=Type.INT, default=4,
          validator=at_least(1),
          doc="Window widening of an escalated finisher re-entry: "
              "finisher_rounds and finisher_swap_passes are multiplied by "
              "this factor (the budgeted loop is skipped outright — the "
              "escalation is pure exhaustive-scan convergence).")
_D.define(name="analyzer.finisher.overlap", type=Type.BOOLEAN, default=False,
          doc="TPU-specific (PERF round-11 lever): dispatch the exhaustive "
              "finisher's leadership scan against the round-ENTRY state so "
              "it overlaps the move wave's apply in the compiled dataflow "
              "graph (they touch disjoint state until admission; every "
              "application still re-scores exact against the live state). "
              "Outcome-parity exploration like analyzer.pass.waves>1: "
              "intermediate trajectories may differ, fixpoint certificates "
              "are only ever claimed from an exact (apply-free) final round. "
              "STATIC engine field: toggling recompiles the goal programs.")

# --------------------------------------------------------------------------
# Pipelined service loop (PR 11: overlap sampling/sync/optimize/execute)
# --------------------------------------------------------------------------
_D.define(name="service.pipeline.enabled", type=Type.BOOLEAN, default=True,
          doc="Run the live service's steady loop as the four-stage pipeline "
              "(cruise_control_tpu/pipeline.py): sampling ingest -> ring "
              "buffer -> sync (shadow-slot device uploads overlapped with "
              "the in-flight optimize round) -> optimize (backpressured by "
              "meetCompletenessRequirements) -> async generation-tagged "
              "execution drain. Off restores the blocking "
              "sample->sync->optimize->execute round (main.py SamplingLoop "
              "+ proposal precompute threads).")
_D.define(name="service.pipeline.ring.capacity", type=Type.INT, default=8,
          validator=at_least(1),
          doc="Per-shape-bucket capacity of the ingest stage's host-side "
              "sample ring buffer; a full bucket drops its OLDEST batch "
              "(counted in pipeline-ring state) instead of blocking the "
              "sampling thread.")
_D.define(name="service.pipeline.min.windows", type=Type.INT, default=1,
          validator=at_least(1),
          doc="Completeness backpressure bar of the pipeline's optimize "
              "stage: the stage STALLS (no error) until the monitor holds "
              "at least this many valid windows, and releases on its own "
              "once live sampling fills them (meetCompletenessRequirements "
              "as the explicit backpressure signal, SURVEY §2.3).")
_D.define(name="service.pipeline.route.fixes", type=Type.BOOLEAN, default=True,
          doc="Route self-healing FIX executions through the pipeline's "
              "execute stage (PR 11 residual c): the detection thread "
              "returns as soon as the heal is optimized + submitted, the "
              "execution drains async on the pipeline's execute thread, and "
              "the anomaly->heal span lineage survives the hand-off. Routed "
              "heals are STICKY rounds (never dropped as stale/superseded). "
              "Only the THREADED pipeline routes — the sim's lockstep mode "
              "keeps heals blocking so (scenario, seed) timelines stay "
              "bit-identical.")

# --------------------------------------------------------------------------
# Fleet mode (PR 13: batched multi-tenant optimization, one device)
# --------------------------------------------------------------------------
_D.define(name="fleet.device.memory.budget.bytes", type=Type.LONG, default=-1,
          doc="Global device-memory budget for every fleet tenant's resident "
              "env/state (cruise_control_tpu/fleet.py). When the fleet's "
              "resident footprint exceeds it after a round, cold tenants are "
              "LRU-spilled to host mirrors (paused tenants first, then "
              "least-recently-optimized); a spilled tenant's next touch "
              "re-admits it bit-identically through the session's own "
              "_sync_finalize program with zero new compiles inside its "
              "shape bucket. -1 = unlimited.")
_D.define(name="fleet.max.active.user.tasks.per.tenant", type=Type.INT,
          default=10, validator=at_least(1),
          doc="Per-tenant active user-task quota for cluster-scoped REST "
              "requests (?cluster_id=): each tenant gets its own "
              "UserTaskManager with this cap, so one tenant's async-request "
              "burst 429s (Too Many Requests + Retry-After) without starving "
              "another tenant's slots — and a task id can never resume "
              "across tenants (wrong-tenant access is a declared 404).")
_D.define(name="fleet.precompute.interval.ms", type=Type.INT, default=30_000,
          validator=at_least(100),
          doc="Cadence of the fleet scheduler's precompute loop "
              "(FleetScheduler.start_precompute): each round syncs every "
              "unpaused tenant (delta path), batches the due ones per shape "
              "bucket into ONE vmapped engine launch, installs per-tenant "
              "proposal caches and enforces the memory budget.")
_D.define(name="fleet.admission.enabled", type=Type.BOOLEAN, default=True,
          doc="Request-admission engine (PR 18, DESIGN §22): fleet rounds "
              "drain per-tenant priority-lane request queues (heal < "
              "rebalance < refresh) with up to fleet.admission.max.batch "
              "tenants admitted per vmapped launch, instead of the legacy "
              "static bucket sweep. At zero queue pressure a round is "
              "bit-identical to the static sweep; off = legacy sweep only. "
              "Host-side policy: toggling never creates new compiles "
              "within a shape bucket.")
_D.define(name="fleet.admission.max.batch", type=Type.INT, default=16,
          validator=at_least(1),
          doc="K: max tenants admitted into one vmapped launch at dispatch "
              "time (continuous-batching admission). Queued requests beyond "
              "K ride the NEXT dispatch, keeping heal-lane latency bounded "
              "by one launch instead of one full round. Host-side policy "
              "leaf — changing it reuses the per-(chain, bucket, K) "
              "compiled programs, no new compiles for already-seen K.")
_D.define(name="fleet.admission.quantize.batch", type=Type.BOOLEAN,
          default=False,
          doc="Quantize the admitted launch size to a power-of-two ladder "
              "(1, 2, 4, ... max.batch), bounding the compiled K-variants a "
              "long-tail arrival mix can create within a bucket (the "
              "serving bench turns this on). Off admits min(pending, K) "
              "exactly — the static-sweep-parity grouping.")
_D.define(name="fleet.admission.near.join.pressure", type=Type.INT,
          default=4, validator=at_least(1),
          doc="Pad-to-join vs split-launch policy for NEAR shape buckets "
              "(same max_rf/disks/racks, every dim <= and <= 2x): when the "
              "combined queued-tenant pressure of a NEAR pair reaches this "
              "threshold, the smaller bucket's tenants rebuild with the "
              "larger bucket's dims as pad floors (session.bucket_floors) "
              "and join its launches; below it they split-launch (no "
              "rebuild cost).")
_D.define(name="fleet.admission.heal.retry.limit", type=Type.INT, default=2,
          validator=at_least(0),
          doc="Launch-failure isolation: heal-lane requests of a failed "
              "batched launch re-enqueue up to this many times (a dropped "
              "heal is a stranded anomaly); rebalance/refresh requests "
              "drop with the failure surfaced in the round report.")
_D.define(name="fleet.pass.gating.enabled", type=Type.BOOLEAN, default=True,
          doc="Ragged fleet convergence gating (PR 20): promote the PR 19 "
              "solo-only levers — churn-adaptive pass budgets, chain-level "
              "short-circuit probes, certificate finisher-skip — to "
              "per-lane traced operands of the batched launch, so each "
              "tenant's lane gates independently inside one compiled "
              "program (bit-identical per tenant to K gated solo runs; "
              "zero new compiles on budget/mask value changes). Off "
              "restores the PR 19 per-lane-freeze chunked path verbatim. "
              "Requires analyzer.incremental.seed.dirty (the per-lane "
              "budgets derive from the per-tenant dirty counts).")
_D.define(name="fleet.pass.compaction.enabled", type=Type.BOOLEAN,
          default=True,
          doc="Quiesced-lane compaction (PR 20): when parked/quiesced "
              "lanes let the batched launch drop a rung on the pow2 K "
              "ladder, re-stack the still-active tenant subset between "
              "goals so later chunk programs pay for active lanes only. "
              "Value-only: the gathered lanes' results are bit-identical; "
              "sub-stack programs compile once per (chain, bucket, K) "
              "like any other fleet variant. No-op without "
              "fleet.pass.gating.enabled.")
_D.define(name="fleet.pass.early.install.enabled", type=Type.BOOLEAN,
          default=True,
          doc="Early install landing (PR 20): dispatch_once installs a "
              "tenant's proposals the moment its lane finishes (parked at "
              "a goal boundary or the launch unwinds), riding the "
              "existing submit_install install-only rounds, instead of "
              "waiting for the whole batched launch — a low-churn "
              "tenant's heal-admission latency stops being hostage to a "
              "high-churn bucket-mate. Install order still respects "
              "(lane, seq) within each tenant.")
_D.define(name="fleet.cluster.ids", type=Type.LIST, default=[],
          doc="Service-mode multi-tenant boot (main.py): cluster ids to "
              "register as fleet tenants behind one server. Non-empty "
              "builds a FleetScheduler over per-tenant CruiseControl apps "
              "(resident sessions on) and serves them via ?cluster_id= "
              "routing; per-tenant config overlays come from "
              "fleet.tenant.<id>.<key> properties. The base backend serves "
              "the first id; additional tenants need overlay-provided "
              "backends (backend.client.provider args) or share the base.")

# --------------------------------------------------------------------------
# Monitor (reference: config/constants/MonitorConfig.java)
# --------------------------------------------------------------------------
_D.define(name="num.metrics.windows", type=Type.INT, default=5, validator=at_least(1),
          doc="Number of load-history windows retained (partition metrics).")
_D.define(name="metrics.window.ms", type=Type.LONG, default=300_000, validator=at_least(1),
          doc="Window span in ms.")
_D.define(name="min.samples.per.metrics.window", type=Type.INT, default=3, validator=at_least(1),
          doc="Samples required for a window to be valid without extrapolation.")
_D.define(name="num.broker.metrics.windows", type=Type.INT, default=20, validator=at_least(1),
          doc="Broker-metric window count (broker aggregator).")
_D.define(name="broker.metrics.window.ms", type=Type.LONG, default=300_000, validator=at_least(1))
_D.define(name="min.samples.per.broker.metrics.window", type=Type.INT, default=1, validator=at_least(1))
_D.define(name="max.allowed.extrapolations.per.partition", type=Type.INT, default=5, validator=at_least(0),
          doc="Per-entity extrapolation budget before samples are invalid.")
_D.define(name="max.allowed.extrapolations.per.broker", type=Type.INT, default=5, validator=at_least(0))
_D.define(name="metric.sampling.interval.ms", type=Type.LONG, default=120_000, validator=at_least(1),
          doc="Sampler period.")
_D.define(name="metric.sampler.class", type=Type.CLASS,
          default="cruise_control_tpu.monitor.sampling.samplers.SimulatedMetricSampler",
          doc="MetricSampler plugin (reference default consumes the metrics-reporter topic).")
_D.define(name="num.metric.fetchers", type=Type.INT, default=1, validator=at_least(1),
          doc="Parallel sampling fetchers (MetricFetcherManager.java:37 thread pool).")
_D.define(name="prometheus.server.endpoint", type=Type.STRING, default="",
          doc="Prometheus HTTP endpoint for PrometheusMetricSampler "
              "(PrometheusMetricSampler.java PROMETHEUS_SERVER_ENDPOINT_CONFIG).")
_D.define(name="prometheus.query.resolution.step.ms", type=Type.INT, default=60_000,
          validator=at_least(1000))
_D.define(name="prometheus.query.supplier", type=Type.STRING, default="",
          doc="Custom PrometheusQuerySupplier class ('' = default node/JMX exporter map).")
_D.define(name="metrics.reporter.topic.path", type=Type.STRING, default="",
          doc="File-backed __CruiseControlMetrics transport consumed by "
              "CruiseControlMetricsReporterSampler (reporter/ module).")
_D.define(name="prometheus.broker.id.by.instance", type=Type.STRING, default="",
          doc='JSON map of Prometheus instance label -> broker id, e.g. '
              '{"kafka-3.prod:7071": 3}; empty = host-<id> convention.')
_D.define(name="sample.store.class", type=Type.CLASS,
          default="cruise_control_tpu.monitor.sampling.sample_store.FileSampleStore",
          doc="Durable sample history; replayed on startup (KafkaSampleStore analogue).")
_D.define(name="sample.store.path", type=Type.STRING, default="",
          doc="Directory for FileSampleStore ('' disables persistence).")
_D.define(name="broker.capacity.config.resolver.class", type=Type.CLASS,
          default="cruise_control_tpu.monitor.capacity.FileCapacityResolver",
          doc="BrokerCapacityConfigResolver plugin.")
_D.define(name="capacity.config.file", type=Type.STRING, default="",
          doc="JSON capacity file (config/capacity.json / capacityJBOD.json analogue).")
_D.define(name="default.broker.capacity.cpu", type=Type.DOUBLE, default=100.0,
          doc="Fallback per-broker CPU capacity (percent, 100 = all cores).")
_D.define(name="default.broker.capacity.disk", type=Type.DOUBLE, default=500_000.0,
          doc="Fallback per-broker disk capacity (MB).")
_D.define(name="default.broker.capacity.nw.in", type=Type.DOUBLE, default=50_000.0,
          doc="Fallback network-in capacity (KB/s).")
_D.define(name="default.broker.capacity.nw.out", type=Type.DOUBLE, default=50_000.0,
          doc="Fallback network-out capacity (KB/s).")
_D.define(name="monitor.state.update.interval.ms", type=Type.LONG, default=30_000,
          doc="Monitor state/sensor refresh cadence (MonitorConfig.java:346-347): "
              "state_json recomputation is cached for this long.")
_D.define(name="min.valid.partition.ratio", type=Type.DOUBLE, default=0.995,
          validator=between(0.0, 1.0),
          doc="Default min fraction of partitions with valid samples a "
              "/partition_load model build requires when the request passes no "
              "min_valid_partition_ratio (MonitorConfig.java:230-233, "
              "PartitionLoadRunnable.java).")
_D.define(name="leader.network.inbound.weight.for.cpu.util", type=Type.DOUBLE, default=0.6,
          doc="Static CPU attribution weights (ModelUtils.java:61-141).")
_D.define(name="follower.network.inbound.weight.for.cpu.util", type=Type.DOUBLE, default=0.3)
_D.define(name="leader.network.outbound.weight.for.cpu.util", type=Type.DOUBLE, default=0.1)
_D.define(name="use.linear.regression.model", type=Type.BOOLEAN, default=False,
          doc="Experimental linear-regression CPU model (LinearRegressionModelParameters.java).")
_D.define(name="linear.regression.model.cpu.util.bucket.size", type=Type.INT, default=5,
          validator=between(1, 100),
          doc="CPU-utilization bucket width (percent) for linreg training "
              "coverage tracking (MonitorConfig.java).")
# reference spellings of the window keys (MonitorConfig names the partition
# aggregator's keys `*.partition.metrics.*`; the canonical names here predate
# the broker aggregator split)
_D.define(name="num.partition.metrics.windows", type=Type.INT, alias_of="num.metrics.windows",
          doc="Reference spelling of num.metrics.windows (MonitorConfig.java).")
_D.define(name="partition.metrics.window.ms", type=Type.LONG, alias_of="metrics.window.ms",
          doc="Reference spelling of metrics.window.ms.")
_D.define(name="min.samples.per.partition.metrics.window", type=Type.INT,
          alias_of="min.samples.per.metrics.window",
          doc="Reference spelling of min.samples.per.metrics.window.")
_D.define(name="skip.loading.samples", type=Type.BOOLEAN, default=False,
          doc="Skip sample-store replay at startup (MonitorConfig "
              "skip.loading.samples; LOADING state is skipped entirely).")
_D.define(name="sampling.allow.cpu.capacity.estimation", type=Type.BOOLEAN, default=True,
          doc="Allow samplers to estimate CPU capacity (cores) when the "
              "capacity resolver does not provide it (MonitorConfig).")
_D.define(name="metric.sampler.partition.assignor.class", type=Type.CLASS,
          default="cruise_control_tpu.monitor.fetcher.DefaultPartitionAssignor",
          doc="Partition -> fetcher assignment plugin "
              "(MetricSamplerPartitionAssignor SPI).")
_D.define(name="metadata.max.age.ms", type=Type.LONG, default=300_000, validator=at_least(1),
          doc="Backend cluster-metadata refresh budget; reads newer than this "
              "reuse the cached topology (MonitorConfig metadata.max.age.ms role).")
_D.define(name="metadata.factor.exponent", type=Type.DOUBLE, default=1.0,
          validator=at_least(0.0),
          doc="Exponent of the metadata factor ((#replicas * #brokers^exp) "
              "used by cluster-size sensors/provision math (MonitorConfig).")
_D.define(name="network.client.provider.class", type=Type.CLASS,
          default="cruise_control_tpu.backend.rpc.DefaultBackendClientProvider",
          doc="Factory for the backend wire client (MonitorConfig "
              "network.client.provider.class role: how the framework reaches "
              "the cluster it manages).")
_D.define(name="topic.config.provider.class", type=Type.CLASS,
          default="cruise_control_tpu.backend.topic_config.BackendTopicConfigProvider",
          doc="TopicConfigProvider SPI: per-topic configs (min.insync.replicas "
              "feeds the concurrency adjuster's min-ISR check).")
_D.define(name="sample.partition.metric.store.on.execution.class", type=Type.CLASS,
          default=None,
          doc="Extra SampleStore that records partition metrics DURING "
              "execution (KafkaCruiseControlConfig "
              "sample.partition.metric.store.on.execution.class); None disables.")

# --------------------------------------------------------------------------
# Executor (reference: config/constants/ExecutorConfig.java)
# --------------------------------------------------------------------------
_D.define(name="num.concurrent.partition.movements.per.broker", type=Type.INT, default=5,
          validator=at_least(1), doc="Per-broker in-flight inter-broker replica move cap.")
_D.define(name="max.num.cluster.partition.movements", type=Type.INT, default=1250, validator=at_least(1),
          doc="Cluster-wide in-flight inter-broker move cap.")
_D.define(name="num.concurrent.intra.broker.partition.movements", type=Type.INT, default=2,
          validator=at_least(1))
_D.define(name="num.concurrent.leader.movements", type=Type.INT, default=1000, validator=at_least(1))
_D.define(name="max.num.cluster.movements", type=Type.INT, default=1250, validator=at_least(1),
          doc="Upper bound of total ongoing movements.")
_D.define(name="execution.progress.check.interval.ms", type=Type.LONG, default=10_000, validator=at_least(1))
_D.define(name="default.replication.throttle", type=Type.LONG, default=-1,
          doc="Bytes/sec replication throttle applied during execution (-1 = none).")
_D.define(name="replica.movement.strategies", type=Type.LIST,
          default=["BaseReplicaMovementStrategy"],
          doc="Composable strategy chain ordering inter-broker moves (executor/strategy/).")
_D.define(name="default.replica.movement.strategies", type=Type.LIST,
          default=["BaseReplicaMovementStrategy"])
_D.define(name="concurrency.adjuster.enabled", type=Type.BOOLEAN, default=False,
          doc="Dynamic concurrency adjustment from broker metrics (Executor.java:335-448).")
_D.define(name="concurrency.adjuster.interval.ms", type=Type.LONG, default=360_000)
_D.define(name="concurrency.adjuster.max.partition.movements.per.broker", type=Type.INT, default=12,
          validator=at_least(1))
_D.define(name="concurrency.adjuster.min.partition.movements.per.broker", type=Type.INT, default=1,
          validator=at_least(1))
_D.define(name="concurrency.adjuster.max.leadership.movements", type=Type.INT, default=1125,
          validator=at_least(1))
_D.define(name="concurrency.adjuster.min.leadership.movements", type=Type.INT, default=100,
          validator=at_least(1))
# AIMD limits per broker metric (ExecutorConfig DEFAULT_CONCURRENCY_ADJUSTER_LIMIT_*)
_D.define(name="concurrency.adjuster.limit.log.flush.time.ms", type=Type.DOUBLE, default=2000.0)
_D.define(name="concurrency.adjuster.limit.follower.fetch.local.time.ms", type=Type.DOUBLE,
          default=500.0)
_D.define(name="concurrency.adjuster.limit.produce.local.time.ms", type=Type.DOUBLE,
          default=1000.0)
_D.define(name="concurrency.adjuster.limit.consumer.fetch.local.time.ms", type=Type.DOUBLE,
          default=500.0)
_D.define(name="concurrency.adjuster.limit.request.queue.size", type=Type.DOUBLE, default=1000.0)
_D.define(name="concurrency.adjuster.additive.increase.inter.broker.replica", type=Type.INT,
          default=1, validator=at_least(1))
_D.define(name="concurrency.adjuster.additive.increase.leadership", type=Type.INT,
          default=100, validator=at_least(1))
_D.define(name="concurrency.adjuster.multiplicative.decrease.inter.broker.replica",
          type=Type.INT, default=2, validator=at_least(2))
_D.define(name="concurrency.adjuster.multiplicative.decrease.leadership", type=Type.INT,
          default=2, validator=at_least(2))
_D.define(name="leader.movement.timeout.ms", type=Type.LONG, default=180_000)
_D.define(name="task.execution.alerting.threshold.ms", type=Type.LONG, default=90_000)
_D.define(name="executor.backend.class", type=Type.CLASS,
          default="cruise_control_tpu.backend.simulated.SimulatedClusterBackend",
          doc="ClusterBackend plugin: simulated (tests/dev) or adapter to a real cluster "
              "(the reference actuates via ZK znodes + AdminClient, Executor.java:1272).")
_D.define(name="demotion.history.retention.time.ms", type=Type.LONG, default=1_209_600_000,
          doc="How long a demoted broker stays in the recently-demoted "
              "blocklist (ExecutorConfig.java:197-199; default 336 h).")
_D.define(name="removal.history.retention.time.ms", type=Type.LONG, default=1_209_600_000,
          doc="How long a removed broker stays in the recently-removed "
              "blocklist (ExecutorConfig.java:205; default 336 h).")
_D.define(name="min.execution.progress.check.interval.ms", type=Type.LONG, default=5_000,
          validator=at_least(1),
          doc="Floor for the (admin-adjustable) execution progress-check "
              "interval (ExecutorConfig.java).")
_D.define(name="slow.task.alerting.backoff.ms", type=Type.LONG, default=60_000,
          validator=at_least(0),
          doc="Backoff between repeated slow-task alerts for the same task "
              "(ExecutorConfig.java).")
_D.define(name="admin.client.request.timeout.ms", type=Type.LONG, default=180_000,
          validator=at_least(1),
          doc="Timeout for backend admin requests (list/alter/describe; "
              "ExecutorConfig admin.client.request.timeout.ms).")
_D.define(name="logdir.response.timeout.ms", type=Type.LONG, default=10_000,
          validator=at_least(1),
          doc="Timeout for backend logdir describe requests "
              "(ExecutorConfig logdir.response.timeout.ms).")
# -- fault tolerance at the backend boundary (common/retries.py): retry
# policy + per-operation-class circuit breakers wired into executor
# submission/verification, monitor sampling and the RPC sidecar client --
_D.define(name="backend.retry.max.attempts", type=Type.INT, default=4,
          validator=at_least(1),
          doc="Attempts per backend call before the failure propagates "
              "(1 = no retries). Jittered exponential backoff between "
              "attempts (common/retries.py RetryPolicy).")
_D.define(name="backend.retry.base.backoff.ms", type=Type.LONG, default=100,
          validator=at_least(0),
          doc="First-retry backoff; doubles per retry up to "
              "backend.retry.max.backoff.ms.")
_D.define(name="backend.retry.max.backoff.ms", type=Type.LONG, default=10_000,
          validator=at_least(0),
          doc="Backoff ceiling for the exponential retry schedule.")
_D.define(name="backend.retry.jitter", type=Type.DOUBLE, default=0.2,
          validator=between(0.0, 1.0),
          doc="Symmetric jitter fraction applied to each backoff (drawn "
              "from the injected deterministic RNG).")
_D.define(name="backend.circuit.failure.threshold", type=Type.INT, default=5,
          validator=at_least(1),
          doc="Consecutive failures of one operation class that OPEN its "
              "circuit breaker (CLOSED->OPEN->HALF_OPEN; common/retries.py).")
_D.define(name="backend.circuit.reset.timeout.ms", type=Type.LONG,
          default=60_000, validator=at_least(1),
          doc="Time an OPEN circuit waits before admitting HALF_OPEN probes.")
_D.define(name="backend.circuit.half.open.probes", type=Type.INT, default=1,
          validator=at_least(1),
          doc="Concurrent probe calls a HALF_OPEN circuit admits; one "
              "success closes it, one failure re-opens it.")
_D.define(name="backend.sidecar.max.respawns", type=Type.INT, default=3,
          validator=at_least(0),
          doc="Bounded sidecar respawn budget for the RPC backend client: a "
              "timed-out/dead sidecar is killed and relaunched at most this "
              "many times per client (meter: sidecar-restarts) instead of "
              "staying permanently down.")
_D.define(name="executor.notifier.class", type=Type.CLASS,
          default="cruise_control_tpu.executor.notifier.LoggingExecutorNotifier",
          doc="ExecutorNotifier SPI: notified when a proposal execution "
              "finishes (success/failure/stopped; ExecutorConfig).")
_D.define(name="failed.brokers.storage.path", type=Type.STRING, default="",
          doc="File persisting failed-broker first-seen times across restarts "
              "(the reference stores these under failed.brokers.zk.path; "
              "'' keeps them in-memory only).")
_D.define(name="failed.brokers.zk.path", type=Type.STRING,
          alias_of="failed.brokers.storage.path",
          doc="Reference spelling: accepted and used as the persistence path.")
_D.define(name="zookeeper.security.enabled", type=Type.BOOLEAN, default=False,
          doc="Accepted for config-file compatibility. This framework has no "
              "ZooKeeper path (the backend seam actuates instead); setting "
              "true is rejected at load.")
_D.define(name="concurrency.adjuster.inter.broker.replica.enabled", type=Type.BOOLEAN,
          default=True,
          doc="Whether AIMD adjustment covers inter-broker replica moves "
              "(ExecutorConfig).")
_D.define(name="concurrency.adjuster.leadership.enabled", type=Type.BOOLEAN,
          default=True,
          doc="Whether AIMD adjustment covers leadership movements.")
_D.define(name="concurrency.adjuster.min.isr.check.enabled", type=Type.BOOLEAN,
          default=False,
          doc="Pause concurrency increases (and decrease) while any sampled "
              "partition is at/below its topic's min.insync.replicas "
              "(ExecutorConfig concurrency.adjuster.min.isr.check.enabled).")
_D.define(name="concurrency.adjuster.min.isr.cache.size", type=Type.INT, default=5000,
          validator=at_least(1),
          doc="Max (topic -> min.insync.replicas) entries cached.")
_D.define(name="concurrency.adjuster.min.isr.retention.ms", type=Type.LONG,
          default=720_000, validator=at_least(1),
          doc="Cached min-ISR entry freshness budget.")
_D.define(name="concurrency.adjuster.num.min.isr.check", type=Type.INT, default=100,
          validator=at_least(1),
          doc="Partitions sampled per min-ISR check round.")

# --------------------------------------------------------------------------
# Anomaly detector (reference: config/constants/AnomalyDetectorConfig.java)
# --------------------------------------------------------------------------
_D.define(name="anomaly.detection.interval.ms", type=Type.LONG, default=300_000, validator=at_least(1))
_D.define(name="goal.violation.detection.interval.ms", type=Type.LONG, default=-1,
          doc="-1 = use anomaly.detection.interval.ms.")
_D.define(name="metric.anomaly.detection.interval.ms", type=Type.LONG, default=-1)
_D.define(name="disk.failure.detection.interval.ms", type=Type.LONG, default=-1)
_D.define(name="topic.anomaly.detection.interval.ms", type=Type.LONG, default=-1)
_D.define(name="predicted.goal.violation.detection.interval.ms", type=Type.LONG, default=-1,
          doc="Cadence of the forecast-driven pre-breach detector; "
              "-1 = use anomaly.detection.interval.ms.")
_D.define(name="anomaly.detection.use.resident.session", type=Type.BOOLEAN, default=True,
          doc="Route GoalViolationDetector rounds through the synced resident "
              "session when one is enabled: repeated zero-churn re-checks then "
              "ride the incremental revalidation memo (one compiled violation "
              "re-check re-serves the carried verdicts) instead of re-running "
              "the full goal chain.")
_D.define(name="broker.failure.detection.backoff.ms", type=Type.LONG, default=300_000)
_D.define(name="anomaly.notifier.class", type=Type.CLASS,
          default="cruise_control_tpu.detector.notifier.SelfHealingNotifier",
          doc="AnomalyNotifier plugin returning FIX/CHECK/IGNORE.")
_D.define(name="anomaly.detection.goals", type=Type.LIST, default=DEFAULT_ANOMALY_DETECTION_GOALS,
          doc="Goals the GoalViolationDetector re-checks.")
_D.define(name="slack.self.healing.notifier.webhook", type=Type.STRING, default="",
          doc="Slack incoming-webhook URL (SlackSelfHealingNotifier.java).")
_D.define(name="slack.self.healing.notifier.channel", type=Type.STRING, default="")
_D.define(name="alerta.self.healing.notifier.api.url", type=Type.STRING, default="",
          doc="Alerta API base URL (AlertaSelfHealingNotifier.java).")
_D.define(name="alerta.self.healing.notifier.api.key", type=Type.PASSWORD, default="")
_D.define(name="alerta.self.healing.notifier.environment", type=Type.STRING,
          default="Production")
_D.define(name="self.healing.enabled", type=Type.BOOLEAN, default=False,
          doc="Master switch for self-healing (per-type switches in the notifier).")
_D.define(name="self.healing.exclude.recently.demoted.brokers", type=Type.BOOLEAN, default=True)
_D.define(name="self.healing.exclude.recently.removed.brokers", type=Type.BOOLEAN, default=True)
# Per-type switches are tri-state: unset (None) falls back to
# self.healing.enabled; an explicit value overrides the master switch
# (SelfHealingNotifier.java per-type config semantics).
_D.define(name="broker.failures.self.healing.enabled", type=Type.BOOLEAN, default=None)
_D.define(name="goal.violations.self.healing.enabled", type=Type.BOOLEAN, default=None)
_D.define(name="disk.failures.self.healing.enabled", type=Type.BOOLEAN, default=None)
_D.define(name="metric.anomaly.self.healing.enabled", type=Type.BOOLEAN, default=None)
_D.define(name="topic.anomaly.self.healing.enabled", type=Type.BOOLEAN, default=None)
_D.define(name="maintenance.event.self.healing.enabled", type=Type.BOOLEAN, default=None)
_D.define(name="predicted.goal.violations.self.healing.enabled", type=Type.BOOLEAN, default=None,
          doc="Tri-state like the other per-type switches: whether PREDICTED "
              "goal-violation verdicts may execute their precomputed heal "
              "before the breach exists.")
# --------------------------------------------------------------------------
# Predictive control plane (forecast/, DESIGN §21)
# --------------------------------------------------------------------------
_D.define(name="forecast.enabled", type=Type.BOOLEAN, default=False,
          doc="Master switch for the predictive control plane: the workload "
              "forecaster + PredictedGoalViolationDetector.")
_D.define(name="forecast.horizon.ms", type=Type.LONG, default=300_000,
          validator=at_least(1),
          doc="How far ahead the forecaster projects each partition's load.")
_D.define(name="forecast.ewma.alpha", type=Type.DOUBLE, default=0.45,
          validator=between(0.0, 1.0),
          doc="Level/EWMA smoothing weight (traced leaf: no recompile).")
_D.define(name="forecast.trend.beta", type=Type.DOUBLE, default=0.25,
          validator=between(0.0, 1.0),
          doc="Holt trend smoothing weight (traced leaf: no recompile).")
_D.define(name="forecast.blend", type=Type.DOUBLE, default=0.5,
          validator=between(0.0, 1.0),
          doc="Weight of the Holt (level+trend) term vs the flat EWMA term.")
_D.define(name="forecast.max.scale", type=Type.DOUBLE, default=8.0,
          validator=at_least(1.0),
          doc="Clamp on predicted forecast/current load ratios — a noisy "
              "series cannot project an unbounded surge.")
_D.define(name="forecast.speculative.proposals", type=Type.BOOLEAN, default=True,
          doc="Install the predicted-violation heal as the speculative "
              "proposal cache, keyed on the model generation at install "
              "time; the existing staleness rules drop it if the "
              "prediction does not hold.")
_D.define(name="forecast.slo.tracking.enabled", type=Type.BOOLEAN, default=False,
          doc="Sim-only: probe goal violations each tick to measure "
              "time-under-violation and prevented-vs-reacted SLOs.")
_D.define(name="broker.failure.alert.threshold.ms", type=Type.LONG, default=900_000,
          doc="SelfHealingNotifier grace: alert after this long.")
_D.define(name="broker.failure.self.healing.threshold.ms", type=Type.LONG, default=1_800_000,
          doc="SelfHealingNotifier grace: fix after this long.")
_D.define(name="metric.anomaly.finder.class", type=Type.CLASS,
          default="cruise_control_tpu.detector.metric_anomaly.PercentileMetricAnomalyFinder",
          doc="MetricAnomalyFinder plugin (core SPI).")
_D.define(name="metric.anomaly.percentile.upper.threshold", type=Type.DOUBLE, default=95.0,
          validator=between(0.0, 100.0))
_D.define(name="metric.anomaly.percentile.lower.threshold", type=Type.DOUBLE, default=2.0,
          validator=between(0.0, 100.0))
_D.define(name="slow.broker.bytes.rate.detection.threshold", type=Type.DOUBLE, default=1024.0)
_D.define(name="slow.broker.log.flush.time.threshold.ms", type=Type.DOUBLE, default=1000.0)
_D.define(name="slow.broker.demotion.score", type=Type.INT, default=5)
_D.define(name="slow.broker.decommission.score", type=Type.INT, default=50)
_D.define(name="slow.broker.self.healing.unfixable.ratio", type=Type.DOUBLE, default=0.1,
          validator=between(0.0, 1.0),
          doc="Max fraction of cluster brokers that may be slow before the "
              "anomaly is reported unfixable (alert-only) — mass slowness "
              "looks like an external cause, not per-broker degradation "
              "(SlowBrokerFinder.java:105-132).")
_D.define(name="provisioner.class", type=Type.CLASS,
          default="cruise_control_tpu.detector.provisioner.NoopProvisioner",
          doc="Provisioner SPI for cluster right-sizing.")
_D.define(name="provision.partition.size.threshold.mb", type=Type.DOUBLE, default=1_000_000.0)
_D.define(name="provision.actuation.cooldown.ms", type=Type.LONG, default=600_000,
          doc="Minimum simulated/wall ms between two provisioner actuations "
              "(SimulatedProvisioner): a detection round re-asserting "
              "UNDER_PROVISIONED before the previous resize took effect must "
              "not add brokers again.")
_D.define(name="provision.max.added.brokers", type=Type.INT, default=4,
          validator=at_least(1),
          doc="Lifetime cap on brokers the SimulatedProvisioner may add — "
              "bounds runaway scale-up and keeps sim clusters inside their "
              "padded engine shape bucket.")
_D.define(name="topic.anomaly.finder.class", type=Type.LIST,
          default=["cruise_control_tpu.detector.topic_anomaly.TopicReplicationFactorAnomalyFinder"])
_D.define(name="self.healing.target.topic.replication.factor", type=Type.INT, default=3)
_D.define(name="maintenance.event.reader.class", type=Type.CLASS,
          default="cruise_control_tpu.detector.maintenance.FileMaintenanceEventReader",
          doc="MaintenanceEventReader plugin (reference reads a Kafka topic).")
_D.define(name="maintenance.event.topic.path", type=Type.STRING, default="",
          doc="Topic-log file carrying operator maintenance plans "
              "(MaintenanceEventTopicReader.java maintenance.event.topic role); "
              "when set, the topic reader is wired alongside the file-spool one.")
_D.define(name="maintenance.event.path", type=Type.STRING, default="",
          doc="Spool directory for FileMaintenanceEventReader.")
_D.define(name="maintenance.event.idempotence.retention.ms", type=Type.LONG, default=180_000)
_D.define(name="maintenance.event.enable.idempotence", type=Type.BOOLEAN, default=True,
          doc="Drop duplicate maintenance events seen within the idempotence "
              "retention window (AnomalyDetectorConfig).")
_D.define(name="maintenance.event.max.idempotence.cache.size", type=Type.INT, default=25,
          validator=at_least(1),
          doc="Max remembered recent maintenance events for dedup.")
_D.define(name="maintenance.event.stop.ongoing.execution", type=Type.BOOLEAN, default=False,
          doc="Whether a maintenance event stops an ongoing proposal "
              "execution before being handled.")
_D.define(name="anomaly.detection.allow.capacity.estimation", type=Type.BOOLEAN, default=True,
          doc="Whether detector-triggered optimizations may run on estimated "
              "broker capacities (AnomalyDetectorConfig).")
_D.define(name="num.cached.recent.anomaly.states", type=Type.INT, default=10,
          validator=between(1, 100),
          doc="Recent anomalies of each type retained for /state "
              "(AnomalyDetectorConfig num.cached.recent.anomaly.states).")
_D.define(name="self.healing.goals", type=Type.LIST, default=[],
          doc="Goal names self-healing fixes optimize ([] = the default "
              "goals; AnomalyDetectorConfig self.healing.goals).")
_D.define(name="fixable.failed.broker.count.threshold", type=Type.INT, default=10,
          validator=at_least(0),
          doc="More simultaneously failed brokers than this is treated as "
              "unfixable (likely a network partition, not broker death).")
_D.define(name="fixable.failed.broker.percentage.threshold", type=Type.DOUBLE, default=0.4,
          validator=between(0.0, 1.0),
          doc="Failed-broker fraction above which self-healing refuses to fix.")
# pluggable anomaly classes: the detector manager instantiates these when
# materializing anomalies (AnomalyDetectorConfig {broker.failures, goal.
# violations, disk.failures, metric.anomaly, topic.anomaly, maintenance.
# event}.class; custom classes must subclass the built-in they replace)
_D.define(name="broker.failures.class", type=Type.CLASS,
          default="cruise_control_tpu.detector.anomalies.BrokerFailures")
_D.define(name="goal.violations.class", type=Type.CLASS,
          default="cruise_control_tpu.detector.anomalies.GoalViolations")
_D.define(name="disk.failures.class", type=Type.CLASS,
          default="cruise_control_tpu.detector.anomalies.DiskFailures")
_D.define(name="metric.anomaly.class", type=Type.CLASS,
          default="cruise_control_tpu.detector.anomalies.MetricAnomaly")
_D.define(name="maintenance.event.class", type=Type.CLASS,
          default="cruise_control_tpu.detector.anomalies.MaintenanceEvent")
# provisioner right-sizing floors (AnomalyDetectorConfig overprovisioned.*)
_D.define(name="overprovisioned.min.brokers", type=Type.INT, default=3, validator=at_least(1),
          doc="Never recommend shrinking below this broker count.")
_D.define(name="overprovisioned.min.extra.racks", type=Type.INT, default=1,
          validator=at_least(0),
          doc="Extra racks beyond max replication factor required before an "
              "over-provisioned verdict.")
_D.define(name="overprovisioned.max.replicas.per.broker", type=Type.LONG, default=1500,
          validator=at_least(1),
          doc="Replica density above which the cluster is NOT over-provisioned.")

# --------------------------------------------------------------------------
# Web server + user tasks (reference: WebServerConfig.java, UserTaskManagerConfig.java)
# --------------------------------------------------------------------------
_D.define(name="webserver.http.port", type=Type.INT, default=9090, validator=between(0, 65535))
_D.define(name="webserver.http.address", type=Type.STRING, default="127.0.0.1")
_D.define(name="webserver.api.urlprefix", type=Type.STRING, default="/kafkacruisecontrol/*")
_D.define(name="webserver.session.maxExpiryTime", type=Type.LONG, default=60_000)
_D.define(name="webserver.request.maxBlockTimeMs", type=Type.LONG, default=10_000)
_D.define(name="max.active.user.tasks", type=Type.INT, default=5, validator=at_least(1))
_D.define(name="completed.user.task.retention.time.ms", type=Type.LONG, default=86_400_000)
_D.define(name="max.cached.completed.user.tasks", type=Type.INT, default=100)
_D.define(name="two.step.verification.enabled", type=Type.BOOLEAN, default=False,
          doc="Park POSTs in the purgatory for review (servlet/purgatory/Purgatory.java).")
_D.define(name="two.step.purgatory.retention.time.ms", type=Type.LONG, default=1_209_600_000)
_D.define(name="two.step.purgatory.max.requests", type=Type.INT, default=25)
_D.define(name="webserver.security.enable", type=Type.BOOLEAN, default=False)
_D.define(name="webserver.auth.credentials.file", type=Type.STRING, default="")
_D.define(name="webserver.ssl.enable", type=Type.BOOLEAN, default=False,
          doc="Serve HTTPS (KafkaCruiseControlApp.java:100-121 ssl block).")
_D.define(name="webserver.ssl.cert.location", type=Type.STRING, default="",
          doc="PEM certificate chain file (webserver.ssl.keystore.location "
              "role for the stdlib ssl stack).")
_D.define(name="webserver.ssl.key.location", type=Type.STRING, default="",
          doc="PEM private-key file; may equal the cert file.")
_D.define(name="webserver.ssl.key.password", type=Type.PASSWORD, default="",
          doc="Private-key passphrase (webserver.ssl.key.password).")
_D.define(name="webserver.security.provider", type=Type.STRING, default="BASIC",
          validator=in_set("BASIC", "JWT", "TRUSTED_PROXY", "SPNEGO"),
          doc="Auth scheme when webserver.security.enable "
              "(servlet/security/: Basic, jwt/, trustedproxy/, spnego/).")
_D.define(name="spnego.principal.secret.file", type=Type.STRING, default="",
          doc="Shared secret for the SPNEGO token-validator stub (the "
              "GSS/keytab seam; spnego.keytab.file role).")
_D.define(name="spnego.principal.roles.file", type=Type.STRING, default="",
          doc="htpasswd-style file mapping SPNEGO principals to roles.")
_D.define(name="jwt.secret.file", type=Type.STRING, default="",
          doc="Shared-secret file for HS256 JWT verification "
              "(jwt.authentication.provider.url RS256 role).")
_D.define(name="jwt.principal.claim", type=Type.STRING, default="sub",
          doc="JWT claim carrying the principal (JwtAuthenticator "
              "JWT_TOKEN_PRINCIPAL role).")
_D.define(name="trusted.proxy.services", type=Type.LIST, default="",
          doc="Principals allowed to delegate via the doAs header "
              "(trusted.proxy.services).")
_D.define(name="trusted.proxy.fallback.enabled", type=Type.BOOLEAN, default=True,
          doc="Whether a trusted-proxy request without doAs falls back to the "
              "proxy's own identity (trusted.proxy.spnego.fallback.enabled role).")
_D.define(name="trusted.proxy.services.ip.regex", type=Type.STRING, default="",
          doc="Regex of client IPs allowed to act as trusted proxies "
              "('' = any; WebServerConfig trusted.proxy.services.ip.regex).")
_D.define(name="webserver.session.maxExpiryTimeMs", type=Type.LONG,
          alias_of="webserver.session.maxExpiryTime",
          doc="Reference spelling of webserver.session.maxExpiryTime.")
_D.define(name="webserver.session.path", type=Type.STRING, default="/",
          doc="Path attribute of the session cookie (WebServerConfig "
              "webserver.session.path).")
_D.define(name="webserver.accesslog.enabled", type=Type.BOOLEAN, default=False,
          doc="NCSA-style access log (WebServerConfig webserver.accesslog.*).")
_D.define(name="webserver.accesslog.path", type=Type.STRING, default="access.log",
          doc="Access-log file path.")
_D.define(name="webserver.accesslog.retention.days", type=Type.INT, default=14,
          validator=at_least(0),
          doc="Rotated access logs older than this are deleted at startup.")
_D.define(name="webserver.http.cors.enabled", type=Type.BOOLEAN, default=False,
          doc="CORS headers + OPTIONS preflight (WebServerConfig cors block).")
_D.define(name="webserver.http.cors.origin", type=Type.STRING, default="*",
          doc="Access-Control-Allow-Origin value.")
_D.define(name="webserver.http.cors.allowmethods", type=Type.STRING,
          default="OPTIONS, GET, POST",
          doc="Access-Control-Allow-Methods value.")
_D.define(name="webserver.http.cors.exposeheaders", type=Type.STRING,
          default="User-Task-ID",
          doc="Access-Control-Expose-Headers value.")
_D.define(name="webserver.ui.diskpath", type=Type.STRING, default="",
          doc="Directory of cruise-control-ui static files to serve "
              "('' disables the UI; WebServerConfig webserver.ui.diskpath).")
_D.define(name="webserver.ui.urlprefix", type=Type.STRING, default="/*",
          doc="URL prefix the UI is served under.")
_D.define(name="request.reason.required", type=Type.BOOLEAN, default=False,
          doc="Require a ?reason= on POST requests (WebServerConfig).")
_D.define(name="two.step.purgatory.max.cached.completed.requests", type=Type.INT,
          default=100, validator=at_least(0),
          doc="Completed (submitted/discarded) purgatory requests retained "
              "for the review board.")
_D.define(name="max.cached.completed.kafka.admin.user.tasks", type=Type.INT, default=None,
          doc="Per-type completed-task cache cap for KAFKA_ADMIN endpoints "
              "(None = max.cached.completed.user.tasks; UserTaskManagerConfig).")
_D.define(name="max.cached.completed.kafka.monitor.user.tasks", type=Type.INT, default=None,
          doc="Per-type completed-task cache cap for KAFKA_MONITOR endpoints.")
_D.define(name="max.cached.completed.cruise.control.admin.user.tasks", type=Type.INT,
          default=None,
          doc="Per-type completed-task cache cap for CRUISE_CONTROL_ADMIN endpoints.")
_D.define(name="max.cached.completed.cruise.control.monitor.user.tasks", type=Type.INT,
          default=None,
          doc="Per-type completed-task cache cap for CRUISE_CONTROL_MONITOR endpoints.")
# --- SSL: reference keystore spellings onto the PEM-based stdlib stack ---
_D.define(name="webserver.ssl.keystore.location", type=Type.STRING,
          alias_of="webserver.ssl.cert.location",
          doc="Reference spelling: the certificate (PEM) file.")
_D.define(name="webserver.ssl.keystore.password", type=Type.PASSWORD,
          alias_of="webserver.ssl.key.password",
          doc="Reference spelling: the private-key passphrase.")
_D.define(name="webserver.ssl.keystore.type", type=Type.STRING, default="PEM",
          doc="Only PEM is supported by the stdlib ssl stack; JKS/PKCS12 "
              "files must be converted (rejected at load otherwise).")
_D.define(name="webserver.ssl.protocol", type=Type.STRING, default="TLS",
          validator=in_set("TLS", "TLSv1.2", "TLSv1.3"),
          doc="Minimum TLS protocol version for the HTTPS listener.")
_D.define(name="webserver.ssl.include.ciphers", type=Type.LIST, default=None,
          doc="Explicit OpenSSL cipher list for TLSv1.2 ('None' = defaults).")
_D.define(name="webserver.ssl.exclude.ciphers", type=Type.LIST, default=None,
          doc="Ciphers removed from the TLSv1.2 cipher list.")
_D.define(name="webserver.ssl.include.protocols", type=Type.LIST, default=None,
          doc="Allowed TLS protocol versions (subset of TLSv1.2/TLSv1.3).")
_D.define(name="webserver.ssl.exclude.protocols", type=Type.LIST, default=None,
          doc="TLS protocol versions to disable.")
# --- JWT/SPNEGO reference keys ---
_D.define(name="jwt.cookie.name", type=Type.STRING, default="",
          doc="Cookie carrying the JWT ('' = Authorization header only; "
              "WebServerConfig jwt.cookie.name).")
_D.define(name="jwt.expected.audiences", type=Type.LIST, default=None,
          doc="Accepted 'aud' claim values (None = audience not checked).")
_D.define(name="jwt.authentication.provider.url", type=Type.STRING, default="",
          doc="Login-service URL unauthenticated browsers are redirected to "
              "({redirect}?origin=<url> contract of the reference's "
              "JwtAuthenticator); '' returns a plain 401.")
_D.define(name="jwt.auth.certificate.location", type=Type.STRING, default="",
          doc="RS256 public certificate (PEM). The stdlib stack verifies "
              "HS256 via jwt.secret.file; setting this selects RS256 "
              "verification of the token signature instead.")
_D.define(name="spnego.principal", type=Type.STRING, default="",
          doc="Service principal expected in Negotiate tokens "
              "(WebServerConfig spnego.principal; '' accepts any).")
_D.define(name="spnego.keytab.file", type=Type.STRING,
          alias_of="spnego.principal.secret.file",
          doc="Reference spelling: the credential file backing the SPNEGO "
              "token-validator seam.")

# --------------------------------------------------------------------------
# Pluggable per-endpoint request/parameter classes
# (reference: CruiseControlParametersConfig.java + CruiseControlRequestConfig
# .java — one `<endpoint>.parameters.class` + `<endpoint>.request.class` pair
# per endpoint). None = the built-in parser/handler. A parameters class is a
# callable ``(endpoint, query) -> params dict``; a request class exposes
# ``handle(server, method, endpoint, params, client, task_id_header) ->
# (status, body, headers)``. Consumed by api/server.py dispatch.
# --------------------------------------------------------------------------
from cruise_control_tpu.api.endpoints import EndPoint as _EndPoint  # noqa: E402


def endpoint_config_stem(path: str) -> str:
    """Endpoint URL path -> reference config-key stem
    (CruiseControlParametersConfig.java naming; one irregular case)."""
    return {"stop_proposal_execution": "stop.proposal"}.get(
        path, path.replace("_", "."))


for _ep in _EndPoint:
    _stem = endpoint_config_stem(_ep.path)
    _D.define(name=f"{_stem}.parameters.class", type=Type.CLASS, default=None,
              doc=f"Parameter-parser override for /{_ep.path} "
                  f"(CruiseControlParametersConfig).")
    _D.define(name=f"{_stem}.request.class", type=Type.CLASS, default=None,
              doc=f"Request-handler override for /{_ep.path} "
                  f"(CruiseControlRequestConfig).")

# --------------------------------------------------------------------------
# TPU placement / parallelism (no reference analogue — TPU-native surface)
# --------------------------------------------------------------------------
_D.define(name="tpu.mesh.axis.brokers", type=Type.INT, default=1, validator=at_least(1),
          doc="Device-mesh size along the candidate-destination (broker) axis for sharded scoring.")
_D.define(name="tpu.shard.map", type=Type.BOOLEAN, default=True,
          doc="With tpu.mesh.axis.brokers > 1: run the SHARD-EXPLICIT engine "
              "(broker state replicated on the mesh, candidate/replica row "
              "axes shard_map'd with one small all-gather per admission "
              "wave; results bit-identical to single-device — "
              "parallel/shard_ops.py). False restores the legacy "
              "annotate-inputs GSPMD placement (shard_cluster), which is "
              "only semantically equivalent.")
_D.define(name="jax.compilation.cache.dir", type=Type.STRING,
          default="/tmp/jax_cache_cc_tpu",
          doc="Persistent XLA compilation cache directory, applied at "
              "GoalOptimizer construction (configure_compilation_cache): a "
              "restarted process reloads its compiled goal programs instead "
              "of re-tracing the whole chain. '' disables; an explicit "
              "JAX_COMPILATION_CACHE_DIR env var / prior jax.config setup "
              "always wins.")
_D.define(name="jax.persistent.cache.min.entry.size.bytes", type=Type.LONG, default=0,
          doc="Smallest compiled executable worth persisting (0 = keep all; "
              "jax_persistent_cache_min_entry_size_bytes).")
_D.define(name="jax.persistent.cache.min.compile.time.secs", type=Type.DOUBLE, default=1.0,
          doc="Shortest compile worth persisting "
              "(jax_persistent_cache_min_compile_time_secs).")
_D.define(name="analyzer.warmup.on.start", type=Type.BOOLEAN, default=False,
          doc="Pre-compile the bucketed engine programs for the current "
              "cluster shape in a background thread at service startup "
              "(GoalOptimizer.warmup): the first real proposal then runs at "
              "warm speed instead of paying the full trace+compile wall.")
_D.define(name="monitor.use.columnar.snapshot", type=Type.BOOLEAN, default=True,
          doc="Build cluster models from the backend's columnar "
              "ClusterSnapshot (array joins; seconds at 500k partitions) "
              "instead of the per-partition metadata dict (legacy path, "
              "kept for equivalence testing).")
_D.define(name="tpu.donate.state", type=Type.BOOLEAN, default=False,
          doc="Donate engine state buffers between per-goal programs to halve "
              "peak HBM. Off by default: ownership transfer serializes the "
              "async dispatch pipeline on a tunneled TPU (measured slower); "
              "enable only when HBM-bound.")

CRUISE_CONTROL_CONFIG_DEF = _D


def configure_compilation_cache(config=None) -> bool:
    """Library-level persistent-compile-cache setup (the jax.compilation.*
    keys). Called from GoalOptimizer construction so EVERY process using the
    library — the e2e service, not just bench.py — amortizes goal-program
    compiles across restarts. Idempotent, and deliberately deferential: an
    already-configured cache dir (JAX_COMPILATION_CACHE_DIR env var, which
    jax folds into its config at import, or an explicit jax.config.update by
    the host process) is never overridden. Returns True when this call
    applied the config."""
    import jax

    if config is not None:
        cache_dir = config.get_string("jax.compilation.cache.dir")
        min_entry = int(config.get_int(
            "jax.persistent.cache.min.entry.size.bytes"))
        min_secs = float(config.get_double(
            "jax.persistent.cache.min.compile.time.secs"))
    else:
        cache_dir = CRUISE_CONTROL_CONFIG_DEF.keys()[
            "jax.compilation.cache.dir"].default
        min_entry, min_secs = 0, 1.0
    if getattr(configure_compilation_cache, "_done", False):
        return False
    configure_compilation_cache._done = True
    if jax.config.jax_compilation_cache_dir:
        return False        # env var / bench.py / conftest got there first
    if not cache_dir:
        return False
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", min_entry)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", min_secs)
    return True


def cruise_control_config(props=None, ignore_unknown: bool = False):
    """Build a validated framework Config (KafkaCruiseControlConfig analogue)."""
    from cruise_control_tpu.config.configdef import Config
    cfg = Config(CRUISE_CONTROL_CONFIG_DEF, props or {}, ignore_unknown=ignore_unknown)
    _sanity_check(cfg)
    return cfg


def effective_default_goals(cfg) -> list:
    """Goals for proposal precompute: `default.goals`, falling back to `goals`
    (reference: AnalyzerConfig default.goals falls back to the configured goals)."""
    return cfg.get_list("default.goals") or cfg.get_list("goals")


def _sanity_check(cfg) -> None:
    """Cross-key checks (reference: config/KafkaCruiseControlConfig.java sanityCheck*)."""
    from cruise_control_tpu.config.configdef import ConfigException
    goals = cfg.get_list("goals")
    hard = cfg.get_list("hard.goals")
    missing = [g for g in hard if g not in goals]
    if missing:
        raise ConfigException(f"hard.goals {missing} not in goals list")
    default_goals = cfg.get_list("default.goals")
    bad_defaults = [g for g in default_goals if g not in goals]
    if bad_defaults:
        raise ConfigException(f"default.goals {bad_defaults} not in goals list")
    if cfg.get_int("num.metrics.windows") < 1:
        raise ConfigException("num.metrics.windows must be >= 1")
    if cfg.get_int("max.num.cluster.movements") < cfg.get_int("num.concurrent.leader.movements"):
        # mirrors sanityCheckConcurrency: cluster cap must cover leadership concurrency
        raise ConfigException("max.num.cluster.movements < num.concurrent.leader.movements")
    import re
    for rx_key in ("topics.excluded.from.partition.movement",
                   "trusted.proxy.services.ip.regex"):
        pattern = cfg.get_string(rx_key)
        if pattern:
            try:
                re.compile(pattern)
            except re.error as e:
                raise ConfigException(
                    f"{rx_key} is not a valid regex: {e}") from None
    # keys accepted for reference config-file compatibility whose JVM-specific
    # values this framework cannot honor are rejected loudly, not ignored
    if cfg.get_boolean("zookeeper.security.enabled"):
        raise ConfigException(
            "zookeeper.security.enabled=true: this framework has no ZooKeeper "
            "path — actuation goes through the backend seam "
            "(executor.backend.class); secure that transport instead")
    if cfg.get_string("webserver.ssl.keystore.type").upper() != "PEM":
        raise ConfigException(
            "webserver.ssl.keystore.type: only PEM is supported by the "
            "stdlib ssl stack — convert JKS/PKCS12 keystores "
            "(openssl pkcs12 -in ks.p12 -out ks.pem)")
    allowed_tls = {"TLSv1.2", "TLSv1.3"}
    for proto_key in ("webserver.ssl.include.protocols",
                      "webserver.ssl.exclude.protocols"):
        vals = cfg.get(proto_key)
        bad = [v for v in (vals or []) if v not in allowed_tls]
        if bad:
            raise ConfigException(
                f"{proto_key}: unsupported protocol(s) {bad} "
                f"(allowed: {sorted(allowed_tls)})")
