"""cccli — argparse console client.

Reference: cruise-control-client/cruisecontrolclient/client/cccli.py (console
script ``cccli``, setup.py:5-27) + Display.py (human-readable rendering).
Subcommands and their flags are GENERATED from the server's endpoint
parameter specs, so the CLI surface tracks the API surface automatically
(one add-broker flag per typed CCParameter in the reference).

Usage:
    cccli -a localhost:9090 state
    cccli -a localhost:9090 rebalance --dryrun --goals DiskCapacityGoal
    cccli -a localhost:9090 remove_broker --brokerid 3,4
"""
from __future__ import annotations

import argparse
import json
import sys

from cruise_control_tpu.api.endpoints import (
    COMMON_PARAMS, ENDPOINT_PARAMS, EndPoint, ParamType,
)
from cruise_control_tpu.client.client import (
    CruiseControlClient, CruiseControlClientError,
)

_SKIP_COMMON = {"json", "get_response_schema", "doas"}  # always-JSON client


def _add_params(sub: argparse.ArgumentParser, endpoint: EndPoint) -> None:
    spec = {**{k: v for k, v in COMMON_PARAMS.items() if k not in _SKIP_COMMON},
            **ENDPOINT_PARAMS[endpoint]}
    for name, ps in sorted(spec.items()):
        flag = f"--{name.replace('_', '-')}"
        if ps.type is ParamType.BOOL:
            if ps.default is True:
                # tri-state: --dryrun / --no-dryrun, absent = server default
                sub.add_argument(flag, dest=name, action="store_true",
                                 default=None)
                sub.add_argument(f"--no-{name.replace('_', '-')}", dest=name,
                                 action="store_false")
            else:
                sub.add_argument(flag, dest=name, action="store_true",
                                 default=None)
        elif ps.type is ParamType.INT:
            sub.add_argument(flag, dest=name, type=int, default=None)
        elif ps.type is ParamType.DOUBLE:
            sub.add_argument(flag, dest=name, type=float, default=None)
        else:  # STRING / lists: comma-separated string passed through
            sub.add_argument(flag, dest=name, type=str, default=None)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="cccli", description="Cruise Control (TPU) command-line client")
    parser.add_argument("-a", "--address", required=True,
                        help="host:port of the cruise-control server")
    parser.add_argument("--timeout", type=float, default=300.0,
                        help="overall request timeout incl. async polling (s)")
    parser.add_argument("--user", default=None, help="basic-auth user")
    parser.add_argument("--password", default=None, help="basic-auth password")
    parser.add_argument("--raw", action="store_true",
                        help="print the raw JSON response body")
    subs = parser.add_subparsers(dest="endpoint", required=True)
    for ep in EndPoint:
        sub = subs.add_parser(ep.path, help=f"{ep.path} endpoint")
        _add_params(sub, ep)
    return parser


def _render(endpoint: EndPoint, body: dict, raw: bool, out) -> None:
    if raw or endpoint not in _TABLES:
        json.dump(body, out, indent=2)
        out.write("\n")
        return
    _TABLES[endpoint](body, out)


def _render_load(body: dict, out) -> None:
    cols = ("Broker", "Rack", "BrokerState", "DiskMB", "DiskPct", "CpuPct",
            "LeaderNwInRate", "NwOutRate", "Leaders", "Replicas")
    rows = body.get("brokers", [])
    widths = {c: max(len(c), *(len(str(r.get(c, ""))) for r in rows), 1)
              for c in cols}
    out.write("  ".join(c.ljust(widths[c]) for c in cols) + "\n")
    for r in rows:
        out.write("  ".join(str(r.get(c, "")).ljust(widths[c]) for c in cols)
                  + "\n")


def _render_user_tasks(body: dict, out) -> None:
    for t in body.get("userTasks", []):
        out.write(f"{t['UserTaskId']}  {t['Status']:22s} {t['RequestURL']}"
                  f"  client={t['ClientIdentity']}\n")


_TABLES = {EndPoint.LOAD: _render_load, EndPoint.USER_TASKS: _render_user_tasks}


def main(argv=None, out=sys.stdout) -> int:
    args = build_parser().parse_args(argv)
    endpoint = EndPoint.from_path(args.endpoint)
    auth = (args.user, args.password) if args.user else None
    client = CruiseControlClient(args.address, timeout_s=args.timeout,
                                 auth=auth)
    reserved = {"address", "timeout", "user", "password", "raw", "endpoint"}
    params = {k: v for k, v in vars(args).items()
              if k not in reserved and v is not None}
    try:
        body = client.request(endpoint, **params)
    except CruiseControlClientError as e:
        print(f"error ({e.status}): {e}", file=sys.stderr)
        return 1
    _render(endpoint, body, args.raw, out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
