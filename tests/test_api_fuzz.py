"""REST fuzzing inside chaos episodes (sim/api_fuzz.py tentpole).

Fast tier: FaultyBackend units, the lockstep fuzz smoke on the shared
12-broker compile bucket (invariants: no undeclared 500s, user-task census,
no duplicate executions), bit-identical (scenario, fuzz-seed) episode logs,
the transient-regime contract (heals with retries, breaker never trips) and
the sustained-failure contract (degraded serving mid-outage, recovery after
clearance), plus the tools/slo_diff.py regression gate. Slow tier: the full
micro campaign with the fuzzer on every episode.
"""
import importlib.util
import json
import pathlib

import pytest

from cruise_control_tpu.backend import SimulatedClusterBackend
from cruise_control_tpu.common.retries import ServiceUnavailableError
from cruise_control_tpu.sim import (
    FaultyBackend, FuzzSpec, ScenarioRunner, TransientBackendError,
    run_fuzz_episode,
)
from cruise_control_tpu.sim.scenario import ClusterSpec, Scenario, broker_death

_SMALL = ClusterSpec(num_brokers=12, num_racks=3,
                     topics=(("t0", 60, 2), ("t1", 60, 2)),
                     logdirs_per_broker=2)

# a short single-death scenario on the shared small-fixture compile bucket:
# the fuzz tier-1 rung (3-goal healing chain like the smoke scenario)
_FUZZ_SCENARIO = Scenario(
    name="fuzz-smoke", cluster=_SMALL,
    events=(broker_death(20_000.0, [3]),),
    duration_ms=900_000.0, tick_ms=15_000.0,
    config=(("goal.violation.detection.interval.ms", 10_000_000_000),
            ("broker.failure.detection.backoff.ms", 120_000),
            ("self.healing.goals",
             "ReplicaCapacityGoal,DiskCapacityGoal,ReplicaDistributionGoal")),
    expects_heal=True, expect_detect_types=("BROKER_FAILURE",))

_FUZZ_SPEC = FuzzSpec(ops=35, ticks=26)


# ------------------------------------------------------------- FaultyBackend
def _tiny():
    be = SimulatedClusterBackend()
    be.add_broker(0, "r0").add_broker(1, "r1")
    be.create_partition("t", 0, [0, 1], size_mb=10.0, bytes_in_rate=1.0)
    return be


def test_faulty_backend_verdicts_are_stateless_and_windowed():
    inner = _tiny()
    fb = FaultyBackend(inner, seed=3, windows=((100.0, 1_000.0),),
                       error_rate=1.0)
    # outside the window: clean passthrough
    assert set(fb.brokers()) == {0, 1}
    inner.advance(500.0)          # inside the window, error_rate 1.0
    with pytest.raises(TransientBackendError):
        fb.brokers()
    # stateless: the verdict for (method, bucket) never shifts with call
    # count — N failures in a bucket stay N failures
    with pytest.raises(TransientBackendError):
        fb.brokers()
    inner.advance(1_000.0)        # past the window
    assert set(fb.brokers()) == {0, 1}
    # the simulation surface is never faulted
    assert fb.now_ms() == inner.now_ms()
    assert fb.inner is inner


def test_faulty_backend_partial_responses_subset_per_broker_maps():
    inner = _tiny()
    fb = FaultyBackend(inner, seed=1, windows=((0.0, float("inf")),),
                       error_rate=0.0, partial_rate=1.0)
    full = inner.broker_metrics()
    got = fb.broker_metrics()
    assert set(got) <= set(full)   # a deterministic subset
    assert got == fb.broker_metrics()   # stable within the bucket


def test_faulty_backend_latency_spike_burns_simulated_time():
    inner = _tiny()
    fb = FaultyBackend(inner, seed=0, windows=((0.0, float("inf")),),
                       error_rate=0.0, latency_rate=1.0, latency_ms=250.0)
    t0 = inner.now_ms()
    fb.partitions()
    assert inner.now_ms() == t0 + 250.0


# ----------------------------------------------------------- fuzz smoke tier
@pytest.fixture(scope="module")
def fuzz_smoke():
    return run_fuzz_episode(_FUZZ_SCENARIO, fuzz_seed=1, fuzz_spec=_FUZZ_SPEC)


def test_fuzz_smoke_invariants_hold(fuzz_smoke):
    """No undeclared 500s, user-task census consistent, no duplicate
    executions — and the chaos episode still converges under REST load."""
    fuzz_smoke.assert_ok()
    assert fuzz_smoke.scenario_result.converged
    assert fuzz_smoke.requests > 0
    statuses = {e["status"] for e in fuzz_smoke.fuzz_log}
    assert "5xx" not in statuses and "500" not in statuses


def test_fuzz_smoke_covers_the_surface(fuzz_smoke):
    kinds = {e["kind"] for e in fuzz_smoke.fuzz_log}
    # the schedule drew reads (incl. the PR-11 monitor read family),
    # mutating triggers and stop for this seed
    assert {"state", "proposals", "rebalance_dryrun",
            "rebalance_execute", "stop",
            "load", "partition_load", "kafka_cluster_state"} <= kinds
    executed = [e for e in fuzz_smoke.fuzz_log
                if e["kind"] == "rebalance_execute" and e["status"] == "2xx"]
    assert executed, "no mutating trigger completed"
    for e in executed:
        # User-Task-ID resumption replayed the cached result: same task,
        # 200, and the executor never re-executed
        assert e["resume_status"] == "2xx"
        assert e["resume_same_task"] is True
        assert e["dup_execution"] is False


def test_fuzz_episode_log_is_bit_identical(fuzz_smoke):
    """Same (scenario, fuzz-seed) => bit-identical episode log: timeline,
    fuzz log, verdicts — byte-for-byte over the JSON document."""
    again = run_fuzz_episode(_FUZZ_SCENARIO, fuzz_seed=1,
                             fuzz_spec=_FUZZ_SPEC)
    assert (json.dumps(again.to_json(), sort_keys=True)
            == json.dumps(fuzz_smoke.to_json(), sort_keys=True))


def test_fuzz_different_seed_changes_the_schedule(fuzz_smoke):
    other = ApiFuzzerScheduleProbe(0)
    mine = ApiFuzzerScheduleProbe(1)
    assert other.schedule != mine.schedule


class ApiFuzzerScheduleProbe:
    def __init__(self, seed):
        from cruise_control_tpu.sim.api_fuzz import ApiFuzzer
        self.schedule = ApiFuzzer(_FUZZ_SPEC, fuzz_seed=seed,
                                  name="fuzz-smoke")._draw_schedule()


# -------------------------------------------------- transient-regime contract
def test_transient_fault_episode_heals_with_retries_breaker_never_trips():
    """FaultyBackend transient-error regime: the retry layer absorbs every
    injected failure (retries observed), NO circuit ever opens, and the
    episode heals on schedule."""
    holder = {}

    def wrap(be):
        fb = FaultyBackend(be, seed=5, windows=((30_000.0, 210_000.0),),
                           error_rate=0.12, latency_rate=0.08,
                           partial_rate=0.05)
        holder["fb"] = fb
        return fb

    runner = ScenarioRunner(_FUZZ_SCENARIO, backend_wrap=wrap)
    res = runner.run()
    res.assert_ok()
    assert res.converged
    assert holder["fb"].fault_counts["error"] > 0     # faults really flew
    breakers = runner.cc.fault_tolerance.state_json()["breakers"]
    assert breakers, "no backend call ever rode the fault-tolerance layer"
    assert all(br["openCount"] == 0 for br in breakers.values()), breakers
    sensors = runner.cc.sensors.to_json()
    retries = sum(v["count"] for k, v in sensors.items()
                  if k.endswith("-backend-retries"))
    assert retries > 0


# ------------------------------------------------- sustained-failure contract
def test_sustained_failure_degrades_then_recovers():
    """Total backend outage mid-episode: reads serve the cached proposals
    flagged stale, writes 503 with Retry-After, the detector defers its fix
    instead of burning failures — and after fault clearance the episode
    heals with zero self-healing failures."""
    sc = Scenario(
        name="sustained", cluster=_SMALL,
        events=(broker_death(20_000.0, [3]),),
        duration_ms=1_800_000.0, tick_ms=15_000.0,
        config=_FUZZ_SCENARIO.config,
        expects_heal=True, expect_detect_types=("BROKER_FAILURE",))
    obs = {"primed": False, "degraded": False, "stale": False, "w503": False,
           "retry_after": None}

    def hook(runner, now):
        rel = now - runner._t0
        cc = runner.cc
        if not obs["primed"] and rel < 45_000:
            cc.cached_proposals()            # prime the cache pre-outage
            obs["primed"] = True
        if 120_000 <= rel <= 210_000 and cc.degraded() and not obs["w503"]:
            obs["degraded"] = True
            cached, fresh = cc.cached_proposals_verbose(force_refresh=True)
            obs["stale"] = bool(fresh.get("stale"))
            obs["stale_age_ok"] = fresh.get("ageMs", -1.0) >= 0.0
            try:
                cc.rebalance(dry_run=False, reason="should-503")
            except ServiceUnavailableError as e:
                obs["w503"] = True
                obs["retry_after"] = e.retry_after_s

    def wrap(be):
        # window 1: outage before detection (degraded serving); window 2:
        # outage landing on the heal attempt (fix deferral path)
        return FaultyBackend(be, seed=7,
                             windows=((60_000.0, 240_000.0),
                                      (380_000.0, 430_000.0)),
                             error_rate=1.0)

    runner = ScenarioRunner(sc, backend_wrap=wrap, tick_hook=hook)
    res = runner.run()
    res.assert_ok()
    assert res.converged
    assert obs == {**obs, "primed": True, "degraded": True, "stale": True,
                   "w503": True}
    assert obs["stale_age_ok"] and obs["retry_after"] >= 1.0
    sensors = runner.cc.sensors.to_json()
    assert sensors.get("self-healing-fix-failures", {}).get("count", 0) == 0
    assert sensors["self-healing-fix-deferrals"]["count"] >= 1
    assert sensors["stale-proposals-served"]["count"] >= 1
    # the monitor breaker tripped during the outage and recovered after
    breakers = runner.cc.fault_tolerance.state_json()["breakers"]
    assert breakers["monitor.sample"]["openCount"] >= 1
    assert not runner.cc.degraded()


# ------------------------------------------------------------------ slo_diff
def _load_slo_diff():
    path = pathlib.Path(__file__).resolve().parent.parent / "tools" / "slo_diff.py"
    spec = importlib.util.spec_from_file_location("slo_diff", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _slo(kind_p95_heal, undetected=0):
    return {"time_to_detect_ms": {"n": 2, "p50": 10.0, "p95": 20.0,
                                  "max": 20.0},
            "time_to_heal_ms": {"n": 2, "p50": kind_p95_heal / 2,
                                "p95": kind_p95_heal, "max": kind_p95_heal},
            "actions_per_heal": {"n": 2, "p50": 4, "p95": 6, "max": 6},
            "undetected": undetected, "unhealed": 0}


def test_slo_diff_flags_p95_regressions_and_coverage_loss():
    mod = _load_slo_diff()
    base = {"broker_death": _slo(100.0), "disk_failure": _slo(50.0)}
    cand = {"broker_death": _slo(200.0),      # 2x heal p95 -> regression
            "disk_failure": _slo(55.0)}       # inside the 25% envelope
    rows, regs = mod.compare_slos(base, cand, threshold=0.25)
    assert len(regs) == 1 and regs[0]["kind"] == "broker_death"
    # undetected growth is a regression even with equal latencies
    rows, regs = mod.compare_slos(
        {"rf_drop": _slo(10.0)}, {"rf_drop": _slo(10.0, undetected=1)})
    assert regs and regs[0]["field"] == "undetected"
    # no candidate samples for a kind the baseline measured = coverage lost
    gone = {"rf_drop": {"time_to_detect_ms": {"n": 0, "p50": None,
                                              "p95": None, "max": None},
                        "time_to_heal_ms": {"n": 0, "p50": None, "p95": None,
                                            "max": None},
                        "actions_per_heal": {"n": 0, "p50": None,
                                             "p95": None, "max": None},
                        "undetected": 0, "unhealed": 0}}
    rows, regs = mod.compare_slos({"rf_drop": _slo(10.0)}, gone)
    assert any("coverage lost" in r.get("regression", "") for r in regs)


def test_slo_diff_cli_exit_codes(tmp_path):
    mod = _load_slo_diff()
    base = {"slo": {"broker_death": _slo(100.0)}}
    good = {"slo": {"broker_death": _slo(110.0)}}
    bad = {"slo": {"broker_death": _slo(300.0)}}
    pb, pg, pbad = (tmp_path / n for n in ("b.json", "g.json", "r.json"))
    pb.write_text(json.dumps(base))
    pg.write_text(json.dumps(good))
    pbad.write_text(json.dumps(bad))
    assert mod.main([str(pb), str(pg)]) == 0
    assert mod.main([str(pb), str(pbad)]) == 1
    # bench summary documents (campaign block) are auto-detected
    summary = {"campaign": {"name": "micro", "slo": {"broker_death":
                                                     _slo(100.0)}}}
    ps = tmp_path / "s.json"
    ps.write_text(json.dumps(summary))
    assert mod.main([str(ps), str(pg)]) == 0


def test_slo_diff_pass_gating_gates(tmp_path):
    """PR 19 gates: a reduced-round wall regression past the threshold and
    a pass early-exit that stopped firing both fail the diff; a candidate
    that still skips passes (or early-exits goals) passes."""
    mod = _load_slo_diff()

    def rung(reduced_s, skipped, early, mode="reduced"):
        return {"rungs": [{
            "config": "e2e-1000b-50000p",
            "round_s_steady": 40.0,
            "round_s_reduced": reduced_s,
            "churn_sweep": {"low": {"round_s": reduced_s,
                                    "round_mode": mode,
                                    "passes_skipped": skipped,
                                    "early_exit_goals": early,
                                    "skipped_goals": 0}}}]}

    base = mod.extract_steady(rung(12.0, 400, 3))
    ok = mod.extract_steady(rung(13.0, 380, 3))
    rows, regs = mod.compare_steady(base, ok, threshold=0.25)
    assert not regs, regs
    # wall regression on the reduced round
    slow = mod.extract_steady(rung(56.0, 400, 3))
    rows, regs = mod.compare_steady(base, slow, threshold=0.25)
    assert any(r["field"] == "round_s_reduced" for r in regs), regs
    # the convergence gate stopped firing: zero skipped, zero early exits
    dead = mod.extract_steady(rung(12.0, 0, 0))
    rows, regs = mod.compare_steady(base, dead, threshold=0.25)
    assert any(r["field"] == "low_churn_passes_skipped" for r in regs), regs
    # the reduced chain itself stopped firing
    full = mod.extract_steady(rung(12.0, 0, 0, mode="full"))
    rows, regs = mod.compare_steady(base, full, threshold=0.25)
    assert any(r["field"] == "low_churn_mode" for r in regs), regs


# ------------------------------------------------------------- slow matrices
@pytest.mark.slow
def test_fuzz_micro_campaign_matrix():
    """The full micro campaign with the fuzzer + FaultyBackend on every
    episode: invariants hold across the matrix and the document reproduces
    bit-identically."""
    from cruise_control_tpu.sim import run_fuzz_campaign
    doc = run_fuzz_campaign("micro", seed=0, fuzz_seed=0)
    assert doc["failures"] == []
    assert doc["converged_episodes"] == doc["num_episodes"]
    again = run_fuzz_campaign("micro", seed=0, fuzz_seed=0)
    assert json.dumps(doc, sort_keys=True) == json.dumps(again, sort_keys=True)
