"""Executor: throttled, concurrency-capped proposal execution.

Reference: executor/Executor.java:76 (1,636) — execution lifecycle:
reservation, ``executeProposals`` (:567), the ProposalExecutionRunnable's
three phases (:1079-1130): inter-broker moves -> intra-broker moves ->
leadership; progress polling against cluster metadata; user-initiated stop and
force-stop (:873-899); ReplicationThrottleHelper (:28-46) wraps the moves with
a replication throttle and cleans it up after; ConcurrencyAdjuster
(:335-448) raises/lowers the per-broker cap between checks; history of
recently removed/demoted brokers (:449-506).

Actuation goes through the ClusterBackend SPI (the reference writes ZK
reassignment znodes + calls AdminClient). Time is injected: the SimClock
advances the simulated backend, a WallClock sleeps — same executor code for
tests and a real deployment.
"""
from __future__ import annotations

import dataclasses
import logging
import threading
import time as _time
from collections import deque

LOG = logging.getLogger(__name__)

from cruise_control_tpu.common.retries import NON_RETRYABLE_ERRORS
from cruise_control_tpu.executor.planner import ExecutionTaskPlanner
from cruise_control_tpu.executor.strategy import build_strategy
from cruise_control_tpu.executor.task import ExecutionTask, TaskState, TaskType


class ExecutorKilledError(RuntimeError):
    """Raised inside an execution when :meth:`Executor.kill` severed the
    controller mid-batch (HA leader-kill fault). Unlike a stop, NOTHING is
    cleaned up — in-flight reassignments keep running backend-side, throttles
    stay set, the execution span never ends — so the journaled task census
    freezes at exactly the kill point and a promoted standby can adopt the
    execution from it (``Executor.adopt_census``)."""


class WallClock:
    def __init__(self):
        self._t0 = _time.time()

    def now_ms(self) -> float:
        return (_time.time() - self._t0) * 1000.0

    def sleep_ms(self, ms: float) -> None:
        _time.sleep(ms / 1000.0)


class SimClock:
    """Advances the simulated backend instead of sleeping."""

    def __init__(self, backend):
        self._backend = backend

    def now_ms(self) -> float:
        return float(self._backend.now_ms())

    def sleep_ms(self, ms: float) -> None:
        self._backend.advance(ms)


class ExecutorState:
    NO_TASK_IN_PROGRESS = "NO_TASK_IN_PROGRESS"
    STARTING_EXECUTION = "STARTING_EXECUTION"
    INTER_BROKER_REPLICA_MOVEMENT = "INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS"
    INTRA_BROKER_REPLICA_MOVEMENT = "INTRA_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS"
    LEADER_MOVEMENT = "LEADER_MOVEMENT_TASK_IN_PROGRESS"
    STOPPING_EXECUTION = "STOPPING_EXECUTION"


@dataclasses.dataclass
class ExecutorConfigView:
    per_broker_cap: int = 5
    cluster_cap: int = 1250
    intra_broker_cap: int = 2
    leadership_cap: int = 1000
    progress_check_interval_ms: float = 10_000.0
    throttle_bytes_per_sec: int | None = None
    adjuster_enabled: bool = False
    adjuster_max_per_broker: int = 12
    adjuster_min_per_broker: int = 1
    adjuster_max_leadership: int = 1125
    adjuster_min_leadership: int = 100
    adjuster_limits: tuple = (
        ("BROKER_LOG_FLUSH_TIME_MS_999TH", 2000.0),
        ("BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_999TH", 500.0),
        ("BROKER_PRODUCE_LOCAL_TIME_MS_999TH", 1000.0),
        ("BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_999TH", 500.0),
        ("BROKER_REQUEST_QUEUE_SIZE", 1000.0),
    )
    adjuster_add_replica: int = 1
    adjuster_add_leadership: int = 100
    adjuster_div_replica: int = 2
    adjuster_div_leadership: int = 2
    # per-movement-type AIMD gates + the min-ISR safety check
    # (ExecutorConfig concurrency.adjuster.{inter.broker.replica,leadership}.
    # enabled and concurrency.adjuster.min.isr.*)
    adjuster_replica_enabled: bool = True
    adjuster_leadership_enabled: bool = True
    min_isr_check_enabled: bool = False
    min_isr_cache_size: int = 5000
    min_isr_retention_ms: float = 720_000.0
    min_isr_num_check: int = 100
    min_progress_check_interval_ms: float = 5_000.0
    slow_task_threshold_ms: float = 90_000.0
    slow_task_backoff_ms: float = 60_000.0
    # max.num.cluster.movements: bound on TOTAL ongoing movements of any
    # kind (ExecutorConfig.java:76-79); caps both the inter-broker in-flight
    # set and a leadership batch
    total_movement_cap: int = 1250
    # leader.movement.timeout.ms (ExecutorConfig.java:139-141)
    leader_movement_timeout_ms: float = 180_000.0
    # concurrency.adjuster.interval.ms (ExecutorConfig.java:213): the AIMD
    # adjuster runs on its own cadence, not every progress tick
    adjuster_interval_ms: float = 360_000.0
    # {demotion,removal}.history.retention.time.ms
    demotion_history_retention_ms: float = 1_209_600_000.0
    removal_history_retention_ms: float = 1_209_600_000.0

    @classmethod
    def from_config(cls, cfg) -> "ExecutorConfigView":
        throttle = cfg.get_int("default.replication.throttle")
        return cls(
            adjuster_replica_enabled=cfg.get_boolean(
                "concurrency.adjuster.inter.broker.replica.enabled"),
            adjuster_leadership_enabled=cfg.get_boolean(
                "concurrency.adjuster.leadership.enabled"),
            min_isr_check_enabled=cfg.get_boolean(
                "concurrency.adjuster.min.isr.check.enabled"),
            min_isr_cache_size=cfg.get_int(
                "concurrency.adjuster.min.isr.cache.size"),
            min_isr_retention_ms=float(cfg.get_int(
                "concurrency.adjuster.min.isr.retention.ms")),
            min_isr_num_check=cfg.get_int(
                "concurrency.adjuster.num.min.isr.check"),
            min_progress_check_interval_ms=float(cfg.get_int(
                "min.execution.progress.check.interval.ms")),
            slow_task_threshold_ms=float(cfg.get_int(
                "task.execution.alerting.threshold.ms")),
            slow_task_backoff_ms=float(cfg.get_int(
                "slow.task.alerting.backoff.ms")),
            per_broker_cap=cfg.get_int("num.concurrent.partition.movements.per.broker"),
            cluster_cap=cfg.get_int("max.num.cluster.partition.movements"),
            intra_broker_cap=cfg.get_int("num.concurrent.intra.broker.partition.movements"),
            leadership_cap=cfg.get_int("num.concurrent.leader.movements"),
            progress_check_interval_ms=cfg.get_int("execution.progress.check.interval.ms"),
            throttle_bytes_per_sec=None if throttle < 0 else throttle,
            adjuster_enabled=cfg.get_boolean("concurrency.adjuster.enabled"),
            adjuster_max_per_broker=cfg.get_int(
                "concurrency.adjuster.max.partition.movements.per.broker"),
            adjuster_min_per_broker=cfg.get_int(
                "concurrency.adjuster.min.partition.movements.per.broker"),
            adjuster_max_leadership=cfg.get_int(
                "concurrency.adjuster.max.leadership.movements"),
            adjuster_min_leadership=cfg.get_int(
                "concurrency.adjuster.min.leadership.movements"),
            adjuster_limits=(
                ("BROKER_LOG_FLUSH_TIME_MS_999TH",
                 cfg.get_double("concurrency.adjuster.limit.log.flush.time.ms")),
                ("BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_999TH",
                 cfg.get_double("concurrency.adjuster.limit.follower.fetch.local.time.ms")),
                ("BROKER_PRODUCE_LOCAL_TIME_MS_999TH",
                 cfg.get_double("concurrency.adjuster.limit.produce.local.time.ms")),
                ("BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_999TH",
                 cfg.get_double("concurrency.adjuster.limit.consumer.fetch.local.time.ms")),
                ("BROKER_REQUEST_QUEUE_SIZE",
                 cfg.get_double("concurrency.adjuster.limit.request.queue.size")),
            ),
            adjuster_add_replica=cfg.get_int(
                "concurrency.adjuster.additive.increase.inter.broker.replica"),
            adjuster_add_leadership=cfg.get_int(
                "concurrency.adjuster.additive.increase.leadership"),
            adjuster_div_replica=cfg.get_int(
                "concurrency.adjuster.multiplicative.decrease.inter.broker.replica"),
            adjuster_div_leadership=cfg.get_int(
                "concurrency.adjuster.multiplicative.decrease.leadership"),
            total_movement_cap=cfg.get_int("max.num.cluster.movements"),
            leader_movement_timeout_ms=float(cfg.get_int(
                "leader.movement.timeout.ms")),
            adjuster_interval_ms=float(cfg.get_int(
                "concurrency.adjuster.interval.ms")),
            demotion_history_retention_ms=float(cfg.get_int(
                "demotion.history.retention.time.ms")),
            removal_history_retention_ms=float(cfg.get_int(
                "removal.history.retention.time.ms")),
        )


class MinIsrCache:
    """Bounded (topic -> min.insync.replicas) cache with entry freshness
    (Executor.java MinIsrCache role; ExecutorConfig concurrency.adjuster.
    min.isr.{cache.size, retention.ms}). Stale/evicted entries are re-fetched
    from the TopicConfigProvider on demand."""

    def __init__(self, provider, max_size: int = 5000,
                 retention_ms: float = 720_000.0):
        self._provider = provider
        self._max = max_size
        self._retention_ms = retention_ms
        self._entries: dict[str, tuple[int, float]] = {}  # topic -> (minIsr, ts)

    def min_isr(self, topic: str, now_ms: float) -> int:
        hit = self._entries.get(topic)
        if hit is not None and now_ms - hit[1] < self._retention_ms:
            return hit[0]
        value = self._provider.min_insync_replicas(topic)
        if len(self._entries) >= self._max:
            # evict the stalest entry
            oldest = min(self._entries, key=lambda t: self._entries[t][1])
            del self._entries[oldest]
        self._entries[topic] = (value, now_ms)
        return value


class ConcurrencyAdjuster:
    """AIMD movement-concurrency control from live broker metrics.

    Reference: Executor.java:335-448 (inner ConcurrencyAdjuster) +
    ExecutionUtils.recommendedConcurrency — if ANY alive broker exceeds a
    configured limit for one of the watched 999th-percentile latency / queue
    metrics, the concurrency is divided (multiplicative decrease, clamped to
    the configured min); if all brokers are healthy it is increased additively
    (clamped to the max). When the min-ISR check is enabled
    (concurrency.adjuster.min.isr.check.enabled), partitions at/under their
    topic's min.insync.replicas count as over-limit too — movement concurrency
    backs off while the cluster is fragile.
    """

    def __init__(self, cfg: ExecutorConfigView, min_isr_cache=None,
                 backend=None, clock=None):
        self._cfg = cfg
        self._min_isr = min_isr_cache
        self._backend = backend
        # the executor's clock (SimClock/WallClock): MinIsrCache freshness
        # must advance with the execution, not with a backend attribute that
        # may not exist (in which case entries would never expire)
        self._clock = clock or WallClock()
        self._min_isr_cursor = 0   # rotating sample window over partitions
        self.history: deque = deque(maxlen=100)

    def _min_isr_violations(self) -> list:
        """A rotating window of num.min.isr.check partitions whose in-sync
        replica count is at/below the topic's min.insync.replicas — the
        cursor advances every tick so the whole cluster is covered over
        successive checks, not just a fixed prefix. The effective ISR is the
        backend's reported one, falling back to replicas on alive brokers."""
        if (not self._cfg.min_isr_check_enabled or self._min_isr is None
                or self._backend is None):
            return []
        brokers = self._backend.brokers()
        now_ms = self._clock.now_ms()
        items = list(self._backend.partitions().items())
        n = self._cfg.min_isr_num_check
        start = self._min_isr_cursor % max(len(items), 1)
        self._min_isr_cursor = start + n
        window = items[start:start + n]
        if len(window) < n:   # wrap
            window += items[:n - len(window)]
        bad = []
        for (topic, part), info in window:
            isr = getattr(info, "isr", None)
            if isr is None:
                isr = [r for r in info.replicas
                       if brokers.get(r) is not None and brokers[r].alive]
            need = self._min_isr.min_isr(topic, now_ms)
            if len(isr) <= need:
                bad.append((topic, part, len(isr), need))
        return bad

    def _over_limit(self, broker_metrics: dict) -> list:
        over = []
        for b, metrics in broker_metrics.items():
            for name, limit in self._cfg.adjuster_limits:
                v = metrics.get(name)
                if v is not None and v > limit:
                    over.append((b, name, v, limit))
        over.extend(("minIsr", f"{t}-{p}", in_sync, need)
                    for t, p, in_sync, need in self._min_isr_violations())
        return over

    def recommend_replica_concurrency(self, current: int, broker_metrics: dict) -> int:
        over = self._over_limit(broker_metrics)
        if over:
            new = max(self._cfg.adjuster_min_per_broker,
                      current // self._cfg.adjuster_div_replica)
        else:
            new = min(self._cfg.adjuster_max_per_broker,
                      current + self._cfg.adjuster_add_replica)
        if new != current:
            self.history.append({"type": "INTER_BROKER_REPLICA", "from": current,
                                 "to": new, "overLimit": over[:3]})
        return new

    def recommend_leadership_concurrency(self, current: int, broker_metrics: dict) -> int:
        over = self._over_limit(broker_metrics)
        if over:
            new = max(self._cfg.adjuster_min_leadership,
                      current // self._cfg.adjuster_div_leadership)
        else:
            new = min(self._cfg.adjuster_max_leadership,
                      current + self._cfg.adjuster_add_leadership)
        if new != current:
            self.history.append({"type": "LEADERSHIP", "from": current,
                                 "to": new, "overLimit": over[:3]})
        return new


class Executor:
    def __init__(self, backend, config=None, clock=None, strategy_names=None,
                 sensors=None, fault_tolerance=None, tracer=None,
                 journal=None):
        from cruise_control_tpu.common.sensors import MetricRegistry
        self._sensors = sensors if sensors is not None else MetricRegistry()
        # causal span journal (common/tracing.py): every execution opens an
        # "execution" span under the caller's explicit parent handle (the
        # facade's operation span), with one "phase" child per executor
        # phase; every task-state transition lands as a {"kind": "task"}
        # journal event tied to the execution span — the durable census.
        self._tracer = tracer
        self._journal = journal
        # Executor sensor catalog (Sensors.md): ongoing-execution gauge +
        # started/stopped execution meters + the proposal-execution-timer
        # (whole 3-phase execution wall, on the injected clock — simulated
        # seconds in the sim, so heal executions feed the same catalog the
        # chaos campaigns aggregate)
        self._sensors.gauge("ongoing-execution",
                            lambda: int(self.has_ongoing_execution()))
        self._execution_meter = self._sensors.meter("execution-started")
        self._execution_stopped_meter = self._sensors.meter("execution-stopped")
        self._execution_timer = self._sensors.timer("proposal-execution-timer")
        self._backend = backend
        self._cfg = (ExecutorConfigView.from_config(config) if config is not None
                     else ExecutorConfigView())
        self._clock = clock or (SimClock(backend) if hasattr(backend, "advance")
                                else WallClock())
        # strategy catalog + default chain from config
        # (ExecutorConfig replica.movement.strategies = available plugin
        # classes; default.replica.movement.strategies = the chain used when
        # a request names none; ExecutionTaskPlanner.java:65-78)
        self._strategy_registry = None
        if config is not None:
            from cruise_control_tpu.executor.strategy import strategy_registry
            self._strategy_registry = strategy_registry(
                config.get_list("replica.movement.strategies"))
            if strategy_names is None:
                strategy_names = config.get_list(
                    "default.replica.movement.strategies")
        self._strategy = build_strategy(
            strategy_names or ["BaseReplicaMovementStrategy"],
            registry=self._strategy_registry)
        self._state = ExecutorState.NO_TASK_IN_PROGRESS
        self._stop_requested = False
        self._force_stop = False
        self._lock = threading.Lock()
        self._current_planner: ExecutionTaskPlanner | None = None
        self._history: list[dict] = []
        self._recently_removed_brokers: dict[int, float] = {}
        self._recently_demoted_brokers: dict[int, float] = {}
        self._execution_thread: threading.Thread | None = None
        self._proposal_generation: int | None = None
        self._reservation = None
        min_isr_cache = None
        self._notifier = None
        if config is not None:
            provider = config.get_configured_instance("topic.config.provider.class")
            if provider is not None:
                attach = getattr(provider, "attach", None)
                if callable(attach):
                    attach(backend)
                min_isr_cache = MinIsrCache(
                    provider, max_size=self._cfg.min_isr_cache_size,
                    retention_ms=self._cfg.min_isr_retention_ms)
            # ExecutorNotifier SPI (executor.notifier.class)
            self._notifier = config.get_configured_instance(
                "executor.notifier.class")
        self._adjuster = ConcurrencyAdjuster(self._cfg, min_isr_cache, backend,
                                             clock=self._clock)
        self._last_adjust_ms = -1e18  # concurrency.adjuster.interval.ms gate
        self._slow_task_alerts: dict[int, float] = {}  # task_id -> last alert ms
        # fault tolerance at the backend boundary (common/retries.py):
        # movement submission and progress verification retry transient
        # failures with jittered backoff ON THE INJECTED CLOCK and sit behind
        # per-class circuit breakers ("executor.submit" / "executor.verify").
        # When a breaker is open the execution PAUSES mid-batch — unsubmitted
        # tasks stay PENDING, in-flight census untouched — and resumes via
        # the breaker's half-open probe instead of wedging or crashing.
        # app.py passes its shared instance so REST serving degrades on the
        # same breaker state the executor observes.
        if fault_tolerance is None:
            from cruise_control_tpu.common.retries import BackendFaultTolerance
            fault_tolerance = BackendFaultTolerance(
                config, clock_ms=self._clock.now_ms, sensors=self._sensors)
        self._ft = fault_tolerance
        self._paused = False
        self._pause_ticks = 0
        self._pause_meter = self._sensors.meter("executor-backend-pauses")
        # HA leader-kill switch: kill() flips it (typically from a backend
        # schedule_at callback firing inside a progress sleep); every phase
        # loop polls it and raises ExecutorKilledError, freezing the census
        self._killed = False
        # failover adoption seed: adopt_census() stages the dead leader's
        # already-submitted inter-broker moves here; _inter_broker_phase
        # enters its loop with them as in-flight
        self._adopted_in_flight: list[ExecutionTask] = []

    @property
    def fault_tolerance(self):
        return self._ft

    @property
    def paused(self) -> bool:
        """True while the current execution is waiting out a backend
        failure/open breaker (mid-batch pause)."""
        return self._paused

    def _pause_tick(self, what: str) -> None:
        """One paused progress tick: record it and sleep the progress
        interval on the injected clock (the breaker's reset timeout runs on
        the same clock, so the next tick may probe HALF_OPEN)."""
        if not self._paused:
            LOG.warning("execution paused: backend %s unavailable "
                        "(breakers: %s)", what, self._ft.open_circuits())
        self._paused = True
        self._pause_ticks += 1
        self._pause_meter.mark()
        self._clock.sleep_ms(self._cfg.progress_check_interval_ms)

    def _resume_if_paused(self) -> None:
        if self._paused:
            self._paused = False
            LOG.info("execution resumed: backend reachable again")

    # -------------------------------------------------------- HA leader-kill
    def kill(self) -> None:
        """Simulate the controller process dying mid-execution. No cleanup
        runs: the next kill-check in any phase loop raises
        ExecutorKilledError and the finish path is skipped entirely, so the
        journal's last word on this execution is the true mid-batch census.
        A killed executor refuses all further executions."""
        self._killed = True

    @property
    def killed(self) -> bool:
        return self._killed

    def _check_killed(self) -> None:
        if self._killed:
            raise ExecutorKilledError(
                "executor killed mid-execution "
                f"(operation={getattr(self, '_operation', None)!r})")

    # ---------------------------------------------------------- reservation
    def reserve(self, owner: str) -> None:
        """setGeneratingProposalsForExecution role (Executor.java:828): only one
        party may generate-and-execute at a time."""
        with self._lock:
            if self._reservation is not None or self._state != ExecutorState.NO_TASK_IN_PROGRESS:
                raise RuntimeError(f"executor busy (state={self._state}, "
                                   f"reserved by {self._reservation})")
            self._reservation = owner

    def release(self, owner: str) -> None:
        with self._lock:
            if self._reservation == owner:
                self._reservation = None

    # ------------------------------------------------------------ lifecycle
    @property
    def state(self) -> str:
        return self._state

    def has_ongoing_execution(self) -> bool:
        return self._state not in (ExecutorState.NO_TASK_IN_PROGRESS,)

    def stop_execution(self, force: bool = False) -> None:
        """Graceful stop: no new tasks; force: cancel in-flight reassignments
        (znode deletion, ExecutionUtils.java:305-307)."""
        with self._lock:
            # count once per stopped execution, not per stop call
            newly_stopped = (self._state != ExecutorState.NO_TASK_IN_PROGRESS
                             and not self._stop_requested)
            self._stop_requested = True
            self._force_stop = force
        if newly_stopped:
            self._execution_stopped_meter.mark()

    def _expire_history(self) -> None:
        """Drop blocklist entries past their retention
        ({removal,demotion}.history.retention.time.ms, Executor.java:449-506)."""
        now = self._clock.now_ms()
        for hist, retention in (
                (self._recently_removed_brokers,
                 self._cfg.removal_history_retention_ms),
                (self._recently_demoted_brokers,
                 self._cfg.demotion_history_retention_ms)):
            # pop(..., None): concurrent REST threads may race this sweep
            for b in [b for b, ts in list(hist.items())
                      if now - ts > retention]:
                hist.pop(b, None)

    def recently_removed_brokers(self) -> set:
        self._expire_history()
        return set(self._recently_removed_brokers)

    def recently_demoted_brokers(self) -> set:
        self._expire_history()
        return set(self._recently_demoted_brokers)

    def drop_recently_removed_brokers(self, brokers) -> list:
        """POST /admin?drop_recently_removed_brokers (Executor.java
        drop*Brokers): un-blocklist brokers so proposals may target them."""
        dropped = [b for b in brokers if b in self._recently_removed_brokers]
        for b in dropped:
            del self._recently_removed_brokers[b]
        return dropped

    def drop_recently_demoted_brokers(self, brokers) -> list:
        dropped = [b for b in brokers if b in self._recently_demoted_brokers]
        for b in dropped:
            del self._recently_demoted_brokers[b]
        return dropped

    def set_concurrency(self, per_broker: int | None = None,
                        intra_broker: int | None = None,
                        leadership: int | None = None,
                        progress_check_interval_ms: float | None = None) -> dict:
        """POST /admin concurrency overrides (Executor.setRequestedMovementConcurrency)."""
        for name, v in (("concurrent_partition_movements_per_broker", per_broker),
                        ("concurrent_intra_broker_partition_movements", intra_broker),
                        ("concurrent_leader_movements", leadership),
                        ("execution_progress_check_interval_ms",
                         progress_check_interval_ms)):
            if v is not None and v <= 0:
                # a 0 cap would stall the execution loop forever
                raise ValueError(f"{name} must be > 0, got {v}")
        if per_broker is not None:
            self._cfg.per_broker_cap = int(per_broker)
        if intra_broker is not None:
            self._cfg.intra_broker_cap = int(intra_broker)
        if leadership is not None:
            self._cfg.leadership_cap = int(leadership)
        if progress_check_interval_ms is not None:
            # floor per ExecutorConfig min.execution.progress.check.interval.ms
            self._cfg.progress_check_interval_ms = max(
                float(progress_check_interval_ms),
                self._cfg.min_progress_check_interval_ms)
        return {"perBroker": self._cfg.per_broker_cap,
                "intraBroker": self._cfg.intra_broker_cap,
                "leadership": self._cfg.leadership_cap,
                "progressCheckIntervalMs": self._cfg.progress_check_interval_ms}

    def validate_strategies(self, strategy_names: list) -> None:
        """Raise ValueError early (before any optimization work) when a
        requested movement-strategy name is not in the configured catalog."""
        build_strategy(strategy_names, registry=self._strategy_registry)

    def note_removed_brokers(self, brokers) -> None:
        for b in brokers:
            self._recently_removed_brokers[b] = self._clock.now_ms()

    def note_demoted_brokers(self, brokers) -> None:
        for b in brokers:
            self._recently_demoted_brokers[b] = self._clock.now_ms()

    # ------------------------------------------------------------ execution
    def _alert_slow_tasks(self, in_flight: dict) -> None:
        """Alert on tasks in flight longer than the alerting threshold
        (ExecutorConfig task.execution.alerting.threshold.ms), re-alerting the
        same task only after slow.task.alerting.backoff.ms."""
        now = self._clock.now_ms()
        for t in in_flight.values():
            if t.start_ms < 0 or now - t.start_ms < self._cfg.slow_task_threshold_ms:
                continue
            last = self._slow_task_alerts.get(t.task_id, -1e18)
            if now - last < self._cfg.slow_task_backoff_ms:
                continue
            self._slow_task_alerts[t.task_id] = now
            self._sensors.meter("slow-task-alerts").mark()
            LOG.warning("slow task %s: %s in flight for %.0f s (threshold %.0f s)",
                        t.task_id, t.tp, (now - t.start_ms) / 1000.0,
                        self._cfg.slow_task_threshold_ms / 1000.0)

    def execute_proposals(self, proposals: list, blocking: bool = True,
                          context: dict | None = None,
                          strategy_names: list | None = None,
                          generation: int | None = None,
                          parent_span=None) -> None:
        """Run the 3-phase execution (Executor.executeProposals :567).
        ``strategy_names`` overrides the configured default movement-strategy
        chain for this execution (the REST replica_movement_strategies
        parameter role). ``generation`` is the metadata generation the
        proposals were computed against (the pipelined loop's staleness tag
        — recorded for observability; the pipeline drops stale sets BEFORE
        they reach here). ``parent_span`` is the caller's explicit causal
        handle: the execution span (and with it the whole task census)
        hangs under the operation that computed the proposals."""
        strategy = (build_strategy(strategy_names, registry=self._strategy_registry)
                    if strategy_names else self._strategy)
        with self._lock:
            if self._killed:
                raise ExecutorKilledError("executor killed; refusing new "
                                          "executions")
            if self._state != ExecutorState.NO_TASK_IN_PROGRESS:
                raise RuntimeError("an execution is already in progress")
            self._state = ExecutorState.STARTING_EXECUTION
            self._stop_requested = False
            self._force_stop = False
            self._proposal_generation = generation
        self._execution_meter.mark()
        # a fresh execution consults the current broker metrics immediately
        # (the reference's adjuster thread runs continuously; ours only runs
        # during executions, so re-arm the cadence gate at start)
        self._last_adjust_ms = -1e18
        planner = ExecutionTaskPlanner(strategy)
        if context is None:
            try:
                partitions = self._ft.call("executor.verify",
                                           self._backend.partitions)
                sizes = {tp: info.size_mb for tp, info in partitions.items()}
            except Exception:
                # strategy sort degrades gracefully without sizes; the
                # execution itself retries/pauses through the same breakers
                sizes = {}
            context = {"partition_size_mb": sizes}
        self._operation = context.get("operation", "proposal execution")
        self._slow_task_alerts.clear()
        planner.add_proposals(proposals, context)
        self._current_planner = planner
        # causal execution span + durable task census: transitions journal
        # through ExecutionTask.on_transition keyed by the task's PLAN INDEX
        # (tp + type + index are deterministic per (scenario, seed); the
        # process-global task_id is not)
        exec_span = None
        if self._tracer is not None:
            exec_span = self._tracer.span(
                "execution", self._operation, parent=parent_span,
                tasks=len(planner.all_tasks))
        if self._journal is not None:
            journal = self._journal
            trace = exec_span.trace_id if exec_span is not None else None
            span_id = exec_span.span_id if exec_span is not None else None
            for i, t in enumerate(planner.all_tasks):
                def on_transition(task, state, now, _i=i):
                    journal.append(
                        "task", i=_i, tp=list(task.tp),
                        ty=task.task_type.value, st=state.name,
                        trace=trace, span=span_id)
                t.on_transition = on_transition
                # initial census row: tasks are born PENDING (never via a
                # transition), carrying enough proposal payload for a
                # standby to rebuild the ExecutionProposal and adopt the
                # execution after a leader kill — all fields deterministic
                p = t.proposal
                journal.append(
                    "task", i=i, tp=list(t.tp), ty=t.task_type.value,
                    st="PENDING", trace=trace, span=span_id,
                    ol=p.old_leader, nl=p.new_leader,
                    orp=[list(r) for r in p.old_replicas],
                    nrp=[list(r) for r in p.new_replicas])
        if blocking:
            self._run_execution(planner, exec_span)
        else:
            self._execution_thread = threading.Thread(
                target=self._run_execution, args=(planner, exec_span),
                daemon=True)
            self._execution_thread.start()

    def wait_for_completion(self, timeout_s: float = 60.0) -> None:
        t = self._execution_thread
        if t is not None:
            t.join(timeout_s)
            if not t.is_alive():
                # drop the finished thread so repeated non-blocking
                # executions can never accumulate handler-thread references
                # (asserted by the REST fuzz thread-leak test)
                self._execution_thread = None

    def adopt_census(self, records: list, context: dict | None = None,
                     parent_span=None, blocking: bool = True) -> dict:
        """Failover adoption (HA takeover): resume a dead leader's execution
        from its journaled task census instead of aborting it.

        ``records`` carries one dict per plan-index task — the LAST
        journaled state plus the proposal payload from the initial PENDING
        row ({"i","tp","ty","st","ol","nl","orp","nrp"}). Terminal tasks
        (COMPLETED/ABORTED/DEAD) are skipped; PENDING tasks re-enter a fresh
        planner in their journaled order (the dead leader's strategy sort is
        baked into the plan indexes, so no re-sort); IN_PROGRESS
        inter-broker moves are adopted as in-flight — the backend still
        holds their reassignments and the normal completion polling finishes
        them, so failover ABORTS NOTHING. IN_PROGRESS leadership moves
        re-arm as PENDING (elections are idempotent; re-submitting one that
        already landed completes on the next progress check). IN_PROGRESS
        intra-broker (log-dir) moves also re-arm as PENDING: a journaled
        IN_PROGRESS row means the dead leader's ``alter_replica_logdirs``
        call had already returned (the transition is only journaled after
        the submit), and the call is declarative by ClusterBackend contract
        — it assigns replicas to target log dirs, so re-submitting a move
        that already landed re-asserts the same assignment (the phase also
        re-validates against current metadata first; asserted in
        tests/test_ha.py)."""
        from cruise_control_tpu.analyzer.proposals import ExecutionProposal
        with self._lock:
            if self._killed:
                raise ExecutorKilledError("executor killed; refusing "
                                          "census adoption")
            if self._state != ExecutorState.NO_TASK_IN_PROGRESS:
                raise RuntimeError("an execution is already in progress")
            self._state = ExecutorState.STARTING_EXECUTION
            self._stop_requested = False
            self._force_stop = False
            self._proposal_generation = None
        self._execution_meter.mark()
        self._last_adjust_ms = -1e18
        self._operation = (context or {}).get("operation", "census adoption")
        self._slow_task_alerts.clear()
        planner = ExecutionTaskPlanner(self._strategy)
        by_type: dict[TaskType, list] = {}
        in_flight_tasks: list[ExecutionTask] = []
        for r in sorted(records, key=lambda r: int(r["i"])):
            st = r["st"]
            if st not in ("PENDING", "IN_PROGRESS"):
                continue
            ty = TaskType(r["ty"])
            p = ExecutionProposal(
                topic=r["tp"][0], partition=int(r["tp"][1]),
                old_leader=int(r["ol"]), new_leader=int(r["nl"]),
                old_replicas=tuple((int(b), int(d)) for b, d in r["orp"]),
                new_replicas=tuple((int(b), int(d)) for b, d in r["nrp"]))
            t = ExecutionTask(p, ty)
            by_type.setdefault(ty, []).append(t)
            if st == "IN_PROGRESS" and ty is TaskType.INTER_BROKER_REPLICA_ACTION:
                in_flight_tasks.append(t)
        planner.adopt_tasks(by_type)
        self._current_planner = planner
        exec_span = None
        if self._tracer is not None:
            exec_span = self._tracer.span(
                "execution", self._operation, parent=parent_span,
                tasks=len(planner.all_tasks), adopted=True)
        if self._journal is not None:
            journal = self._journal
            trace = exec_span.trace_id if exec_span is not None else None
            span_id = exec_span.span_id if exec_span is not None else None
            for i, t in enumerate(planner.all_tasks):
                def on_transition(task, state, now, _i=i):
                    journal.append(
                        "task", i=_i, tp=list(task.tp),
                        ty=task.task_type.value, st=state.name,
                        trace=trace, span=span_id)
                t.on_transition = on_transition
                p = t.proposal
                journal.append(
                    "task", i=i, tp=list(t.tp), ty=t.task_type.value,
                    st="PENDING", trace=trace, span=span_id, adopted=True,
                    ol=p.old_leader, nl=p.new_leader,
                    orp=[list(r) for r in p.old_replicas],
                    nrp=[list(r) for r in p.new_replicas])
        # re-arm adopted in-flight moves before the phase loop: the
        # transition lands in the NEW leader's journal, and the phase entry
        # below treats them as already-submitted
        now = self._clock.now_ms()
        for t in in_flight_tasks:
            t.transition(TaskState.IN_PROGRESS, now)
        self._adopted_in_flight = list(in_flight_tasks)
        if blocking:
            self._run_execution(planner, exec_span)
        else:
            self._execution_thread = threading.Thread(
                target=self._run_execution, args=(planner, exec_span),
                daemon=True)
            self._execution_thread.start()
        n_total = len(planner.all_tasks)
        return {"adopted": n_total, "inFlight": len(in_flight_tasks)}

    # ----------------------------------------------------------- throttling
    def _set_throttles(self, planner: ExecutionTaskPlanner) -> tuple:
        """ReplicationThrottleHelper.java:28-46,159: set the global
        leader/follower replication rate AND per-topic throttled-replica
        lists ("partition:broker" entries — sources on the leader list,
        move destinations on the follower list) covering every inter-broker
        move of this execution."""
        if not self._cfg.throttle_bytes_per_sec:
            return False, []
        try:
            self._ft.call("executor.submit",
                          self._backend.set_replication_throttle,
                          self._cfg.throttle_bytes_per_sec,
                          sleep_ms=self._clock.sleep_ms)
        except Exception:
            # an unreachable throttle config must not kill the execution; it
            # proceeds unthrottled (the reference logs and continues too)
            LOG.exception("failed to set replication throttle; "
                          "executing unthrottled")
            self._sensors.meter("throttle-set-failures").mark()
            return False, []
        set_topic_config = getattr(self._backend, "set_topic_config", None)
        if set_topic_config is None:   # backend without topic-config support
            return True, []
        leader: dict[str, set] = {}
        follower: dict[str, set] = {}
        for t in planner.all_tasks:
            if t.task_type is not TaskType.INTER_BROKER_REPLICA_ACTION:
                continue
            p = t.proposal
            for b, _ in p.old_replicas:
                leader.setdefault(p.topic, set()).add(f"{p.partition}:{b}")
            for b in p.replicas_to_add:
                follower.setdefault(p.topic, set()).add(f"{p.partition}:{b}")
        topics = sorted(set(leader) | set(follower))
        applied = []
        for topic in topics:
            try:
                set_topic_config(topic, "leader.replication.throttled.replicas",
                                 ",".join(sorted(leader.get(topic, ()))))
                set_topic_config(topic,
                                 "follower.replication.throttled.replicas",
                                 ",".join(sorted(follower.get(topic, ()))))
            except Exception:
                LOG.exception("failed to set throttled-replica lists for %s",
                              topic)
                self._sensors.meter("throttle-set-failures").mark()
                continue
            applied.append(topic)
        return True, applied

    def _clear_throttles(self, throttled: bool, topics: list) -> None:
        """ReplicationThrottleHelper cleanup (:200): remove the rate and every
        per-topic list, including on stop/force-stop paths."""
        if not throttled:
            return
        try:
            self._ft.call("executor.submit",
                          self._backend.set_replication_throttle, None,
                          sleep_ms=self._clock.sleep_ms)
        except Exception:
            LOG.exception("failed to clear the replication throttle")
            self._sensors.meter("throttle-clear-failures").mark()
        set_topic_config = getattr(self._backend, "set_topic_config", None)
        if set_topic_config is None:
            return
        for topic in topics:
            try:
                set_topic_config(topic,
                                 "leader.replication.throttled.replicas", None)
                set_topic_config(topic,
                                 "follower.replication.throttled.replicas", None)
            except Exception:
                LOG.exception("failed to clear throttled-replica lists for %s",
                              topic)
                self._sensors.meter("throttle-clear-failures").mark()

    # ------------------------------------------------------------ internals
    def _run_execution(self, planner: ExecutionTaskPlanner,
                       exec_span=None) -> None:
        throttled, throttled_topics = False, []
        self._paused = False
        t0_ms = self._clock.now_ms()

        def _phase(name):
            return (exec_span.child("phase", name)
                    if exec_span is not None else None)
        try:
            throttled, throttled_topics = self._set_throttles(planner)
            ph = _phase("inter_broker")
            self._inter_broker_phase(planner)
            if ph is not None:
                ph.end()
            if not self._stop_requested:
                ph = _phase("intra_broker")
                self._intra_broker_phase(planner)
                if ph is not None:
                    ph.end()
            if not self._stop_requested:
                ph = _phase("leadership")
                self._leadership_phase(planner)
                if ph is not None:
                    ph.end()
        finally:
            if self._killed:
                # leader-kill freeze: no throttle cleanup, no timer/history
                # entry, no execution-span end, state stays mid-execution —
                # the journal ends where the process "died" and the standby
                # adopts exactly that census (ExecutorKilledError is already
                # propagating out of this frame)
                pass
            else:
                self._clear_throttles(throttled, throttled_topics)
                self._execution_timer.record(
                    max(self._clock.now_ms() - t0_ms, 0.0) / 1000.0)
                done = sum(1 for t in planner.all_tasks
                           if t.state is TaskState.COMPLETED)
                if exec_span is not None:
                    by_state: dict[str, int] = {}
                    for t in planner.all_tasks:
                        by_state[t.state.name] = by_state.get(t.state.name, 0) + 1
                    exec_span.end(completed=done, total=len(planner.all_tasks),
                                  stopped=self._stop_requested,
                                  aborted=by_state.get("ABORTED", 0),
                                  dead=by_state.get("DEAD", 0))
                self._history.append({
                    "finishedMs": self._clock.now_ms(),
                    "numTasks": len(planner.all_tasks),
                    "numCompleted": done,
                    "stopped": self._stop_requested,
                })
                with self._lock:
                    self._state = ExecutorState.NO_TASK_IN_PROGRESS
                    self._paused = False
                if self._notifier is not None:
                    # ExecutorNotifier SPI (executor.notifier.class): one
                    # notification per finished execution
                    from cruise_control_tpu.executor.notifier import (
                        ExecutorNotification,
                    )
                    n_lead = sum(1 for t in planner.all_tasks
                                 if t.task_type is TaskType.LEADER_ACTION
                                 and t.state is TaskState.COMPLETED)
                    try:
                        self._notifier.on_execution_finished(ExecutorNotification(
                            operation=self._operation,
                            success=not self._stop_requested
                            and done == len(planner.all_tasks),
                            stopped_by_user=self._stop_requested,
                            num_replica_movements=done - n_lead,
                            num_leadership_movements=n_lead))
                    except Exception:
                        LOG.exception("executor notifier failed")

    def _inter_broker_phase(self, planner: ExecutionTaskPlanner) -> None:
        self._state = ExecutorState.INTER_BROKER_REPLICA_MOVEMENT
        in_flight: dict[tuple, ExecutionTask] = {}
        in_flight_by_broker: dict[int, int] = {}
        # failover adoption: moves the dead leader already submitted enter
        # the loop as in-flight — the backend still holds the reassignments,
        # so the normal completion polling finishes them (never re-submitted,
        # never aborted)
        for t in self._adopted_in_flight:
            in_flight[t.tp] = t
            for b in t.brokers_involved:
                in_flight_by_broker[b] = in_flight_by_broker.get(b, 0) + 1
        self._adopted_in_flight = []
        while True:
            self._check_killed()
            if self._stop_requested:
                self._state = ExecutorState.STOPPING_EXECUTION
                if self._force_stop and in_flight:
                    try:
                        self._ft.call("executor.submit",
                                      self._backend.cancel_reassignments,
                                      list(in_flight),
                                      sleep_ms=self._clock.sleep_ms)
                    except NON_RETRYABLE_ERRORS:
                        raise
                    except Exception:
                        # cancellation unreachable: the reassignments are
                        # still running backend-side — keep polling instead
                        # of faking an ABORTED census
                        self._pause_tick("cancel")
                        continue
                    for t in in_flight.values():
                        t.transition(TaskState.ABORTING, self._clock.now_ms())
                        t.transition(TaskState.ABORTED, self._clock.now_ms())
                    in_flight.clear()
                if not in_flight:
                    return
            # completion check — verification failures skip the tick with the
            # census untouched (a task is only COMPLETED on positive evidence)
            try:
                ongoing = self._ft.call("executor.verify",
                                        self._backend.ongoing_reassignments)
            except NON_RETRYABLE_ERRORS:
                raise
            except Exception:
                self._pause_tick("verification")
                continue
            finished = [tp for tp in in_flight if tp not in ongoing]
            for tp in finished:
                t = in_flight.pop(tp)
                t.transition(TaskState.COMPLETED, self._clock.now_ms())
                for b in t.brokers_involved:
                    in_flight_by_broker[b] = max(0, in_flight_by_broker.get(b, 1) - 1)
            # dynamic concurrency: AIMD on live broker metrics on its own
            # cadence (ConcurrencyAdjuster role, Executor.java:335-448;
            # concurrency.adjuster.interval.ms :213-225); gated per movement
            # type (concurrency.adjuster.inter.broker.replica.enabled)
            if (self._cfg.adjuster_enabled and self._cfg.adjuster_replica_enabled
                    and self._adjuster_due()):
                try:
                    metrics = self._ft.call("executor.verify",
                                            self._backend.broker_metrics)
                    self._cfg.per_broker_cap = \
                        self._adjuster.recommend_replica_concurrency(
                            self._cfg.per_broker_cap, metrics)
                except Exception:
                    pass   # keep the current cap; metrics return next tick
            self._alert_slow_tasks(in_flight)
            if not self._stop_requested:
                batch = planner.next_inter_broker_tasks(
                    in_flight_by_broker, self._cfg.per_broker_cap,
                    min(self._cfg.cluster_cap, self._cfg.total_movement_cap),
                    len(in_flight))
                assignments = {t.tp: [b for b, _ in t.proposal.new_replicas]
                               for t in batch}
                if assignments:
                    # submit BEFORE any state transition: a failed submission
                    # leaves the batch PENDING (the planner re-picks it once
                    # the breaker's half-open probe succeeds) — pause, not
                    # wedge, and never a task marked IN_PROGRESS that the
                    # backend never saw
                    try:
                        self._ft.call("executor.submit",
                                      self._backend.alter_partition_reassignments,
                                      assignments,
                                      sleep_ms=self._clock.sleep_ms)
                    except NON_RETRYABLE_ERRORS:
                        raise
                    except Exception:
                        self._pause_tick("movement submission")
                        continue
                    for t in batch:
                        t.transition(TaskState.IN_PROGRESS, self._clock.now_ms())
                        in_flight[t.tp] = t
                        for b in t.brokers_involved:
                            in_flight_by_broker[b] = in_flight_by_broker.get(b, 0) + 1
            self._resume_if_paused()
            if not in_flight and not planner.remaining_inter_broker:
                return
            self._clock.sleep_ms(self._cfg.progress_check_interval_ms)

    def _intra_broker_phase(self, planner: ExecutionTaskPlanner) -> None:
        self._state = ExecutorState.INTRA_BROKER_REPLICA_MOVEMENT
        tasks = planner.next_intra_broker_tasks({}, self._cfg.intra_broker_cap)
        while tasks:
            self._check_killed()
            # re-validate against CURRENT metadata: a fault mid-execution
            # (RF shrink, reassignment landing) may have moved a replica off
            # the broker since the proposal was computed — submitting would
            # only be rejected backend-side, so the task goes DEAD like an
            # ineligible leadership election, and the rest of the batch
            # proceeds instead of the whole execution aborting
            try:
                partitions = self._ft.call("executor.verify",
                                           self._backend.partitions)
            except NON_RETRYABLE_ERRORS:
                raise
            except Exception:
                self._pause_tick("logdir move verification")
                if self._stop_requested:
                    return
                continue
            moves = {}
            live, dead = [], []
            for t in tasks:
                old = dict(t.proposal.old_replicas)
                info = partitions.get(t.tp)
                t_moves = {}
                for b, d in t.proposal.new_replicas:
                    if old.get(b) is not None and old[b] != d:
                        # logdir index -> name resolution happens backend-side;
                        # the proposal carries the index
                        if info is None or b not in info.replicas:
                            t_moves = None      # replica gone: task is dead
                            break
                        t_moves[(t.proposal.topic, t.proposal.partition, b)] = d
                if t_moves is None:
                    dead.append(t)
                else:
                    live.append(t)
                    moves.update(t_moves)
            if moves:
                # resolve + submit before transitioning: a failed batch stays
                # PENDING and is re-picked once the backend returns
                try:
                    resolved = self._ft.call(
                        "executor.verify", self._resolve_logdirs, moves)
                    self._ft.call("executor.submit",
                                  self._backend.alter_replica_logdirs,
                                  resolved, sleep_ms=self._clock.sleep_ms)
                except NON_RETRYABLE_ERRORS:
                    raise
                except Exception:
                    self._pause_tick("logdir move submission")
                    if self._stop_requested:
                        return
                    tasks = planner.next_intra_broker_tasks(
                        {}, self._cfg.intra_broker_cap)
                    continue
            self._resume_if_paused()
            now = self._clock.now_ms()
            for t in dead:
                t.transition(TaskState.DEAD, now)
            for t in live:
                t.transition(TaskState.IN_PROGRESS, now)
                t.transition(TaskState.COMPLETED, now)
            if self._stop_requested:
                return
            tasks = planner.next_intra_broker_tasks({}, self._cfg.intra_broker_cap)

    def _resolve_logdirs(self, moves: dict) -> dict:
        brokers = self._backend.brokers()
        out = {}
        for (topic, part, b), disk_idx in moves.items():
            logdirs = list(brokers[b].logdirs)
            idx = int(disk_idx)
            out[(topic, part, b)] = logdirs[idx] if idx < len(logdirs) else logdirs[0]
        return out

    def _adjuster_due(self) -> bool:
        now = self._clock.now_ms()
        if now - self._last_adjust_ms >= self._cfg.adjuster_interval_ms:
            self._last_adjust_ms = now
            return True
        return False

    def _leadership_phase(self, planner: ExecutionTaskPlanner) -> None:
        self._state = ExecutorState.LEADER_MOVEMENT
        while True:
            self._check_killed()
            if self._stop_requested:
                return
            if (self._cfg.adjuster_enabled
                    and self._cfg.adjuster_leadership_enabled
                    and self._adjuster_due()):
                try:
                    metrics = self._ft.call("executor.verify",
                                            self._backend.broker_metrics)
                    self._cfg.leadership_cap = \
                        self._adjuster.recommend_leadership_concurrency(
                            self._cfg.leadership_cap, metrics)
                except Exception:
                    pass   # keep the current cap
            batch = planner.next_leadership_tasks(
                min(self._cfg.leadership_cap, self._cfg.total_movement_cap))
            if not batch:
                return
            try:
                partitions = self._ft.call("executor.verify",
                                           self._backend.partitions)
                brokers = self._ft.call("executor.verify",
                                        self._backend.brokers)
            except NON_RETRYABLE_ERRORS:
                raise
            except Exception:
                self._pause_tick("leadership verification")
                continue
            elections = {}
            eligible, dead = [], []
            for t in batch:
                info = partitions.get(t.tp)
                target = t.proposal.new_leader
                # the target may have died since the proposal was computed
                # (fault mid-execution): submitting the election would only
                # fail backend-side — mark the task DEAD like the reference
                # abandoning a leadership task with an ineligible target
                if (info is not None and target in info.replicas
                        and brokers.get(target) is not None
                        and brokers[target].alive):
                    elections[t.tp] = target
                    eligible.append(t)
                else:
                    dead.append(t)
            if elections:
                # submit before transitioning (pause/resume semantics as in
                # the inter-broker phase: a failed election batch stays
                # PENDING, including its DEAD candidates — re-derived from
                # fresh metadata on resume)
                try:
                    self._ft.call("executor.submit",
                                  self._backend.elect_leaders, elections,
                                  sleep_ms=self._clock.sleep_ms)
                except NON_RETRYABLE_ERRORS:
                    raise
                except Exception:
                    self._pause_tick("leadership submission")
                    continue
            self._resume_if_paused()
            now = self._clock.now_ms()
            for t in dead:
                t.transition(TaskState.DEAD, now)
            for t in eligible:
                t.transition(TaskState.IN_PROGRESS, now)
            if elections:
                self._await_leadership(elections, planner, eligible)

    def _await_leadership(self, elections: dict, planner, batch: list) -> None:
        """Wait for submitted elections to take effect, up to
        leader.movement.timeout.ms per batch (ExecutorConfig.java:139-141);
        a task whose election hasn't landed by then is ABANDONED — it was
        submitted and started, so it transitions IN_PROGRESS -> ABORTING ->
        ABORTED (the reference's abandoned-leadership-task accounting;
        ``numAbortedTasks`` in state_json carries the census). DEAD stays
        reserved for elections that were never submittable (ineligible
        target, handled in the phase loop above)."""
        pending = {t.tp: t for t in batch if t.tp in elections}
        deadline = self._clock.now_ms() + self._cfg.leader_movement_timeout_ms
        while pending:
            self._check_killed()
            try:
                partitions = self._ft.call("executor.verify",
                                           self._backend.partitions)
            except NON_RETRYABLE_ERRORS:
                raise
            except Exception:
                # metadata unavailable: no landing evidence this poll; the
                # deadline below still bounds the wait
                if self._clock.now_ms() < deadline and not self._stop_requested:
                    self._pause_tick("leadership progress check")
                    continue
                partitions = {}
            landed = [tp for tp, t in pending.items()
                      if getattr(partitions.get(tp), "leader", None)
                      == t.proposal.new_leader]
            for tp in landed:
                pending.pop(tp).transition(TaskState.COMPLETED,
                                           self._clock.now_ms())
            if not pending:
                return
            if self._clock.now_ms() >= deadline or self._stop_requested:
                now = self._clock.now_ms()
                for t in pending.values():
                    t.transition(TaskState.ABORTING, now)
                    t.transition(TaskState.ABORTED, now)
                    self._sensors.meter("leadership-movement-timeouts").mark()
                    LOG.warning("leadership movement timed out for %s "
                                "(abandoned after %.0f ms)", t.tp,
                                self._cfg.leader_movement_timeout_ms)
                return
            self._clock.sleep_ms(min(
                self._cfg.progress_check_interval_ms,
                max(deadline - self._clock.now_ms(), 1.0)))

    # ---------------------------------------------------------------- state
    def state_json(self) -> dict:
        planner = self._current_planner
        out = {"state": self._state}
        if planner is not None:
            tasks = planner.all_tasks
            out["numTotalTasks"] = len(tasks)
            out["numFinishedTasks"] = sum(1 for t in tasks
                                          if t.state is TaskState.COMPLETED)
            out["numPendingTasks"] = sum(1 for t in tasks
                                         if t.state is TaskState.PENDING)
            out["numAbortedTasks"] = sum(1 for t in tasks
                                         if t.state is TaskState.ABORTED)
            # full per-state census: every task is in exactly one state and
            # the counts must sum to the plan (the scenario engine's
            # executor-accounting invariant reads this)
            by_state: dict[str, int] = {}
            for t in tasks:
                by_state[t.state.name] = by_state.get(t.state.name, 0) + 1
            out["numTasksByState"] = by_state
        out["executionHistory"] = self._history[-5:]
        out["numExecutions"] = len(self._history)
        out["numCompletedTasksTotal"] = sum(h["numCompleted"]
                                            for h in self._history)
        out["numPlannedTasksTotal"] = sum(h["numTasks"] for h in self._history)
        out["paused"] = self._paused
        out["numPauseTicks"] = self._pause_ticks
        out["killed"] = self._killed
        if getattr(self, "_proposal_generation", None) is not None:
            # pipelined loop: the metadata generation this execution's
            # proposals were computed against (staleness-tag observability)
            out["proposalGeneration"] = self._proposal_generation
        out["backendFaultTolerance"] = self._ft.state_json()
        if self._cfg.adjuster_enabled:
            out["concurrencyAdjuster"] = {
                "perBrokerCap": self._cfg.per_broker_cap,
                "leadershipCap": self._cfg.leadership_cap,
                "numAdjustments": len(self._adjuster.history),
                "recentAdjustments": list(self._adjuster.history)[-5:],
            }
        return out
