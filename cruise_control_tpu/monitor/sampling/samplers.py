"""MetricSampler SPI + implementations.

Reference: monitor/sampling/MetricSampler.java (SPI), AbstractMetricSampler,
CruiseControlMetricsReporterSampler (default: consumes the in-broker
reporter's __CruiseControlMetrics topic), prometheus/PrometheusMetricSampler
(:1-289), NoopSampler.

Here the default is a SimulatedMetricSampler that pulls per-partition /
per-broker metrics from a ClusterBackend (the simulated cluster stands in for
real Kafka, SURVEY §4.5). A real-cluster sampler would be another plugin.
"""
from __future__ import annotations

import dataclasses
from typing import Iterable, Protocol


@dataclasses.dataclass(frozen=True)
class PartitionSample:
    topic: str
    partition: int
    ts_ms: float
    values: dict          # partition model metric name -> value


@dataclasses.dataclass(frozen=True)
class BrokerSample:
    broker_id: int
    ts_ms: float
    values: dict          # broker model metric name -> value


@dataclasses.dataclass
class Samples:
    partition_samples: list
    broker_samples: list


class MetricSampler(Protocol):
    def configure(self, config, **extra) -> None: ...

    def get_samples(self, now_ms: float, partitions=None,
                    include_broker_samples: bool = True) -> Samples:
        """``partitions`` (optional list of (topic, partition)) restricts the
        fetch to a fetcher's assigned subset (MetricFetcherManager role);
        None = everything. ``include_broker_samples=False`` skips the broker-
        level fetch (only one fetcher per round collects it)."""
        ...

    def close(self) -> None: ...


class NoopSampler:
    """NoopSampler.java analogue."""

    def configure(self, config, **extra):
        pass

    def get_samples(self, now_ms: float, partitions=None,
                    include_broker_samples: bool = True) -> Samples:
        return Samples([], [])

    def close(self):
        pass


class SimulatedMetricSampler:
    """Samples the simulated cluster backend. The backend exposes
    ``partition_metrics()`` / ``broker_metrics()`` snapshots; this sampler
    stamps them with the collection time."""

    def __init__(self, backend=None):
        self._backend = backend

    def configure(self, config, backend=None, **extra):
        if backend is not None:
            self._backend = backend

    def get_samples(self, now_ms: float, partitions=None,
                    include_broker_samples: bool = True) -> Samples:
        if self._backend is None:
            return Samples([], [])
        wanted = set(partitions) if partitions is not None else None
        psamples = [PartitionSample(topic=t, partition=p, ts_ms=now_ms, values=vals)
                    for (t, p), vals in self._backend.partition_metrics().items()
                    if wanted is None or (t, p) in wanted]
        bsamples = [BrokerSample(broker_id=b, ts_ms=now_ms, values=vals)
                    for b, vals in self._backend.broker_metrics().items()] \
            if include_broker_samples else []
        return Samples(psamples, bsamples)

    def close(self):
        pass
