"""Ragged fleet convergence gating (PR 20): per-lane traced pass budgets,
quiesced-lane compaction and early install landing in batched launches.

The invariants:
1. **Per-lane parity** — a gated batched launch over a churn-skewed fleet
   (1 hot tenant past the dirty-seed budget + idle tenants under it) is
   bitwise identical PER TENANT to the same tenants run through the gated
   solo path: violation sets, certificate rows, proposal sets, final
   assignment arrays.
2. **Ungated toggle** — ``fleet.pass.gating.enabled: false`` restores the
   PR 19 uniform-budget fleet path and still produces the same per-tenant
   result sets (gating is a scheduling change, not a policy change); the
   ungated fleet never parks, never compacts and never lands early.
3. **Compaction fires where lanes quiesce and is inert** — idle lanes park
   at the first goal boundary and are compacted out of the working stack
   (counters prove it) without changing any tenant's results (invariant 1
   covers the values); under UNIFORM hot churn no lane parks and the
   compactor never fires.
4. **Early install ordering** — parked lanes land mid-launch (journal
   ``early`` installs) BEFORE the hot lane's landing, and each tenant's
   queued requests complete in (lane, seq) order.
5. **Traced budgets** — re-dispatching after a budget/mask VALUE change
   (different churn magnitudes, same lane classification) compiles
   nothing new.
"""
from __future__ import annotations

import json

import numpy as np
import pytest

from cruise_control_tpu.app import CruiseControl
from cruise_control_tpu.backend.simulated import SimulatedClusterBackend
from cruise_control_tpu.common.tracing import count_compiles
from cruise_control_tpu.config import cruise_control_config
from cruise_control_tpu.fleet import FleetScheduler
from cruise_control_tpu.pipeline import LANE_HEAL, LANE_REBALANCE

WINDOW_MS = 300_000.0
GOALS = ["ReplicaCapacityGoal", "ReplicaDistributionGoal",
         "LeaderReplicaDistributionGoal"]
SEEDS = (21, 22, 23)          # index 0 is the HOT tenant


def _backend(seed, num_brokers=10, num_partitions=60, rf=2):
    rng = np.random.default_rng(seed)
    be = SimulatedClusterBackend()
    for b in range(num_brokers):
        be.add_broker(b, f"r{b % 3}")
    for p in range(num_partitions):
        reps = [int(x) for x in rng.choice(num_brokers, size=rf,
                                           replace=False)]
        be.create_partition(f"t{p % 6}", p, reps,
                            size_mb=float(rng.uniform(10, 500)),
                            bytes_in_rate=float(rng.uniform(1, 50)),
                            bytes_out_rate=float(rng.uniform(1, 100)),
                            cpu_util=float(rng.uniform(0.1, 5)))
    return be


def _cfg(**over):
    props = {
        "anomaly.detection.interval.ms": 10_000_000,
        "goals": ",".join(GOALS),
        "hard.goals": "ReplicaCapacityGoal",
        # force chunked dispatch on the small fixture + arm lane
        # classification (dirty-set seeding)
        "analyzer.pass.chunk.min.replicas": 0,
        "analyzer.incremental.seed.dirty": True,
    }
    props.update(over)
    return cruise_control_config(props)


def _sample(cc, lo=0, hi=6):
    for i in range(lo, hi):
        cc.load_monitor.sample_once(now_ms=i * WINDOW_MS)


def _flip(be, n):
    """Flip the leaders of the first ``n`` partitions (sorted order) to the
    other end of their replica list — deterministic structural churn."""
    flips = {}
    for tp in sorted(be.partitions())[:n]:
        info = be.partitions()[tp]
        flips[tp] = (info.replicas[-1] if info.leader == info.replicas[0]
                     else info.replicas[0])
    be.elect_leaders(flips)


# 60 partitions * rf2 = 120 replicas; dirty-seed budget = 30 replicas.
# 45 flips puts the hot tenant PAST the budget (full lanes), 1 small
# replica move keeps the idle tenants under it (reduced lanes).
HOT_FLIPS, IDLE_FLIPS = 45, 1


def _nudge(be, n):
    """Move the last replica of the first ``n`` partitions one broker over
    (instant apply_assignment) — a small structural churn that dirties the
    EARLY distribution goals but leaves leadership intact, so a reduced
    idle lane can quiesce before the chain's last goal and PARK at a goal
    boundary (a leader flip would dirty the final goal and keep the lane
    in the stack to the end)."""
    from types import SimpleNamespace
    parts = be.partitions()
    brokers = sorted({b for info in parts.values() for b in info.replicas
                      } | {info.leader for info in parts.values()})
    nb = max(brokers) + 1
    props = []
    for tp in sorted(parts)[:n]:
        reps = list(parts[tp].replicas)
        leader = parts[tp].leader
        # move a NON-leader replica so leadership stays put
        mv = max(j for j, b in enumerate(reps) if b != leader)
        nxt = (reps[mv] + 1) % nb
        while nxt in reps:
            nxt = (nxt + 1) % nb
        reps[mv] = nxt
        props.append(SimpleNamespace(
            topic=tp[0], partition=tp[1],
            new_replicas=[(b, 0) for b in reps],
            new_leader=leader))
    be.apply_assignment(props)


def _churn(backends, hot=HOT_FLIPS, idle=IDLE_FLIPS):
    for i, be in enumerate(backends):
        if i == 0:
            _flip(be, hot)
        else:
            _nudge(be, idle)


def _sets(res):
    """(violated set, certificate rows, proposal rows) — the parity unit."""
    return (
        sorted(g.name for g in res.goal_results if g.violated_after),
        sorted((g.name, g.fixpoint_proven, g.moves_remaining,
                g.leads_remaining, g.swap_window_remaining)
               for g in res.goal_results),
        sorted((p.topic, p.partition, p.new_leader, p.new_replicas)
               for p in res.proposals))


def _assert_state_equal(a_res, b_res, who=""):
    for leaf in ("replica_broker", "replica_is_leader", "replica_disk"):
        a = np.asarray(getattr(a_res.final_state, leaf))
        b = np.asarray(getattr(b_res.final_state, leaf))
        assert np.array_equal(a, b), f"{who}:{leaf}"


def _build_fleet(gating: bool):
    fleet = FleetScheduler(config=_cfg(**{
        "fleet.pass.gating.enabled": gating}))
    for s in SEEDS:
        t = fleet.add_tenant(f"tenant-{s}", backend=_backend(s),
                             config=_cfg(**{
                                 "fleet.pass.gating.enabled": gating}))
        _sample(t.cc)
    return fleet


def _fleet_results(fleet):
    return {cid: fleet.app_for(cid).cached_proposals()
            for cid in fleet.cluster_ids}


def _apply_installed(fleet):
    """Apply each tenant's installed proposal cache to its backend — the
    executor's role in a real serving loop. Without it every round
    re-reads the unhealed cluster, later goals stay violated at round
    start, and no lane can ever quiesce enough to park."""
    for cid in fleet.cluster_ids:
        res = fleet.app_for(cid).cached_proposals()
        fleet.tenants[cid].cc.backend.apply_assignment(res.proposals)


@pytest.fixture(scope="module")
def skew():
    """The whole drive, run ONCE: solo reference rounds, a gated and an
    ungated fleet through the same full + settle + churn-skewed rounds
    (proposals applied between rounds, executor-style), then (gated fleet
    only) an admission-lane round for the early-install ordering, a budget
    VALUE toggle under a compile counter, and a uniform-churn round for
    compaction inertness."""
    out = {}

    # ---- solo gated reference (per-tenant ground truth): full round,
    # apply, settle round (absorbs the apply churn), apply, one skewed
    # churn round
    solo_r3 = {}
    for s in SEEDS:
        cc = CruiseControl(_backend(s), config=_cfg())
        _sample(cc)
        sess = cc.resident_session
        sess.sync()
        r1 = cc.goal_optimizer.optimizations(
            None, None, raise_on_failure=False, session=sess)
        cc.backend.apply_assignment(r1.proposals)
        cc.load_monitor.sample_once(now_ms=6 * WINDOW_MS)
        sess.sync()
        r2 = cc.goal_optimizer.optimizations(
            None, None, raise_on_failure=False, session=sess)
        cc.backend.apply_assignment(r2.proposals)
        if s == SEEDS[0]:
            _flip(cc.backend, HOT_FLIPS)
        else:
            _nudge(cc.backend, IDLE_FLIPS)
        cc.load_monitor.sample_once(now_ms=7 * WINDOW_MS)
        sess.sync()
        solo_r3[f"tenant-{s}"] = cc.goal_optimizer.optimizations(
            None, None, raise_on_failure=False, session=sess)
    out["solo_r3"] = solo_r3

    # ---- gated + ungated fleets through the same cadence
    fg, fu = _build_fleet(True), _build_fleet(False)
    for fleet in (fg, fu):
        fleet.run_round(now_ms=2_000_000.0)
        _apply_installed(fleet)
        for cid in fleet.cluster_ids:
            fleet.tenants[cid].cc.load_monitor.sample_once(
                now_ms=6 * WINDOW_MS)
        fleet.run_round(now_ms=2_030_000.0)
        _apply_installed(fleet)
        backends = [fleet.tenants[cid].cc.backend
                    for cid in fleet.cluster_ids]
        if fleet is fg:
            out["gated_counters_pre_r3"] = {
                cid: fg.tenants[cid].gating_json()
                for cid in fg.cluster_ids}
        _churn(backends)
        for cid in fleet.cluster_ids:
            fleet.tenants[cid].cc.load_monitor.sample_once(
                now_ms=7 * WINDOW_MS)
        fleet.run_round(now_ms=2_060_000.0)
    out["gated_r3"] = _fleet_results(fg)
    out["ungated_r3"] = _fleet_results(fu)
    out["gated_counters_r3"] = {cid: fg.tenants[cid].gating_json()
                                for cid in fg.cluster_ids}
    out["ungated_counters_r3"] = {cid: fu.tenants[cid].gating_json()
                                  for cid in fu.cluster_ids}

    # ---- round 4 on the gated fleet: heal+rebalance lanes through the
    # admission engine, journal slice captured for the ordering contract
    _apply_installed(fg)
    backends = [fg.tenants[cid].cc.backend for cid in fg.cluster_ids]
    _churn(backends)
    for cid in fg.cluster_ids:
        fg.tenants[cid].cc.load_monitor.sample_once(now_ms=8 * WINDOW_MS)
    mark = len(fg.journal.lines())
    hot = fg.cluster_ids[0]
    for cid in fg.cluster_ids:
        fg.enqueue(cid, LANE_HEAL, "skew-heal", now_ms=2_090_000.0)
    fg.enqueue(hot, LANE_REBALANCE, "skew-rebalance", now_ms=2_090_000.0)
    for _ in range(8):
        d = fg.dispatch_once(now_ms=2_091_000.0)
        if d is None or (d["launches"] == 0 and not d["failed"]):
            break
    out["r4_journal"] = [json.loads(x) for x in fg.journal.lines()[mark:]]
    out["hot"] = hot

    # ---- budget/mask VALUE toggle: one warm heal-lane dispatch fills the
    # last pool gap (the heal chain's boundary-probe programs, first hit
    # on this classification), then a second dispatch with DIFFERENT churn
    # magnitudes but identical lane classification must relaunch with
    # zero new compiles — budgets and seed masks are traced VALUES
    def heal_dispatch(hot_n, idle_n, w, now):
        _apply_installed(fg)
        # flips for ALL lanes (idles stay reduced — small churn — but the
        # final leader goal stays dirty so no lane PARKS): which goal
        # boundary a lane parks at is a cluster-state VALUE, and a park
        # profile the ladder hasn't seen (K=3 -> 2 -> 1 instead of
        # 3 -> 1) compiles its pow2 rung once like any new shape — that
        # is warm-up, not a budget-value recompile, so the toggle holds
        # the park profile fixed (no parks) and varies only the values
        for i, be in enumerate(backends):
            _flip(be, hot_n if i == 0 else idle_n)
        for cid in fg.cluster_ids:
            fg.tenants[cid].cc.load_monitor.sample_once(now_ms=w * WINDOW_MS)
        for cid in fg.cluster_ids:
            fg.enqueue(cid, LANE_HEAL, "toggle", now_ms=now)
        for _ in range(8):
            d = fg.dispatch_once(now_ms=now + 1_000.0)
            if d is None or (d["launches"] == 0 and not d["failed"]):
                break

    # same idle magnitude both times: the toggle varies budget/mask VALUES
    # (hot churn size, which replicas are dirty), not the lane
    # classification — a different idle magnitude can legitimately change
    # which boundary a lane parks at (a different compaction rung = a
    # different program, compiled once like any ladder step)
    heal_dispatch(HOT_FLIPS - 5, 2, 9, 2_120_000.0)
    with count_compiles() as tc:
        heal_dispatch(HOT_FLIPS - 7, 2, 10, 2_150_000.0)
    out["toggle_compiles"] = tc.count

    # ---- uniform churn: EVERY lane hot -> nobody parks, compactor inert
    before = {cid: fg.tenants[cid].gating_json() for cid in fg.cluster_ids}
    _apply_installed(fg)
    _churn(backends, hot=HOT_FLIPS, idle=HOT_FLIPS)
    for cid in fg.cluster_ids:
        fg.tenants[cid].cc.load_monitor.sample_once(now_ms=11 * WINDOW_MS)
    fg.run_round(now_ms=2_180_000.0)
    out["uniform_before"] = before
    out["uniform_after"] = {cid: fg.tenants[cid].gating_json()
                            for cid in fg.cluster_ids}

    yield out
    fg.shutdown()
    fu.shutdown()


def test_gated_batched_parity_bit_identical_to_gated_solo(skew):
    """Invariant 1: per-tenant verdicts, certificates, proposal sets and
    final assignment arrays of the gated batched churn round equal the
    gated solo runs bitwise — full-budget hot lane and reduced idle lanes
    alike."""
    for cid, solo in skew["solo_r3"].items():
        batched = skew["gated_r3"][cid]
        assert _sets(batched) == _sets(solo), cid
        _assert_state_equal(batched, solo, cid)


def test_gating_off_restores_pr19_path_same_sets(skew):
    """Invariant 2: the ungated fleet (PR 19 uniform-budget path) yields
    the same per-tenant result sets, and its lanes never park, compact or
    land early."""
    for cid, gated in skew["gated_r3"].items():
        assert _sets(gated) == _sets(skew["ungated_r3"][cid]), cid
        _assert_state_equal(gated, skew["ungated_r3"][cid], cid)
    for cid, c in skew["ungated_counters_r3"].items():
        assert c["parkedRounds"] == 0, cid
        assert c["compactedRounds"] == 0, cid
        assert c["earlyInstalls"] == 0, cid


def test_idle_lanes_park_and_compact_hot_lane_does_not(skew):
    """Invariant 3 (firing half): the churn-skewed round (r3 counter
    deltas — the settle round may legitimately park EVERY lane, hot
    included, since applying the warm heal leaves all lanes low-churn)
    parked and compacted every idle lane; the hot lane stayed in the
    working stack to the end. Invariant 1 already proved the values
    unchanged."""
    hot = skew["hot"]
    for cid, c in skew["gated_counters_r3"].items():
        pre = skew["gated_counters_pre_r3"][cid]
        d_park = c["parkedRounds"] - pre["parkedRounds"]
        d_comp = c["compactedRounds"] - pre["compactedRounds"]
        if cid == hot:
            assert d_park == 0, cid
            assert d_comp == 0, cid
        else:
            assert d_park >= 1, cid
            assert d_comp >= 1, cid
            assert c["skippedGoals"] >= 1, cid


def test_uniform_churn_never_parks_or_compacts(skew):
    """Invariant 3 (inert half): with every lane past the budget (uniform
    hot churn) no lane is reduced, so nobody parks and the compactor never
    fires."""
    for cid in skew["uniform_after"]:
        delta_park = (skew["uniform_after"][cid]["parkedRounds"]
                      - skew["uniform_before"][cid]["parkedRounds"])
        delta_comp = (skew["uniform_after"][cid]["compactedRounds"]
                      - skew["uniform_before"][cid]["compactedRounds"])
        assert delta_park == 0, cid
        assert delta_comp == 0, cid


def test_early_install_lands_parked_lanes_first_in_lane_seq_order(skew):
    """Invariant 4: the journal's install stream for the heal round shows
    (a) every parked idle lane landing EARLY and BEFORE the hot lane's
    landing, and (b) each tenant's queued requests completing in
    (lane, seq) order (the hot tenant's heal precedes its rebalance)."""
    installs = [e for e in skew["r4_journal"]
                if e.get("kind") == "admission" and e.get("ev") == "install"]
    assert installs, "no install events journaled"
    hot = skew["hot"]
    hot_pos = [i for i, e in enumerate(installs) if e["cid"] == hot]
    idle_pos = [i for i, e in enumerate(installs) if e["cid"] != hot]
    assert hot_pos and idle_pos
    # parked lanes landed before the hot lane's unwind...
    assert max(idle_pos) < min(hot_pos)
    # ...and were flagged as early landings
    for i in idle_pos:
        assert installs[i].get("early") is True, installs[i]
    # the hot tenant's requests completed in (lane, seq) order
    hot_lanes = [installs[i]["lane"] for i in hot_pos]
    assert hot_lanes == ["heal", "rebalance"]


def test_budget_value_toggle_compiles_nothing(skew):
    """Invariant 5: per-lane budgets and seed masks are traced operands —
    changing their VALUES (new churn magnitudes, same classification)
    relaunches entirely from the warmed program pool."""
    assert skew["toggle_compiles"] == 0
