"""Scenario-engine tests: the closed self-healing loop under scripted
failures (sim/ tentpole). Fast tier: backend fault-injection mechanics,
invariant checker units, the broker-death smoke scenario (sized for the
shared small-fixture compile bucket) + its determinism proof, and the two
cheap no-optimizer scenarios (metric gap, topic creation). Slow tier: the
full catalog — disk failure, slow broker, maintenance plan, 50-broker
death, compound cascade."""
import dataclasses

import pytest

from cruise_control_tpu.backend import SimulatedClusterBackend
from cruise_control_tpu.sim import (
    SCENARIOS, ClusterSpec, Scenario, ScenarioRunner, broker_death,
    build_backend, check_converged, check_tick, run_scenario,
)

# ------------------------------------------------------- backend mechanics


def _tiny_backend():
    be = SimulatedClusterBackend()
    be.add_broker(0, "r0").add_broker(1, "r1")
    be.create_partition("t", 0, [0, 1], size_mb=10.0)
    return be


def test_now_ms_is_a_method_and_advances():
    be = _tiny_backend()
    assert be.now_ms() == 0.0
    be.advance(1500.0)
    assert be.now_ms() == 1500.0


def test_schedule_at_fires_at_exact_time_mid_advance():
    be = _tiny_backend()
    fired = []
    be.schedule_at(1000.0, lambda now: fired.append(("a", now)))
    be.schedule_at(2500.0, lambda now: fired.append(("b", now)))
    be.advance(500.0)
    assert fired == []
    # one big step must split at each event time
    be.advance(10_000.0)
    assert fired == [("a", 1000.0), ("b", 2500.0)]


def test_schedule_at_now_fires_before_stepping():
    be = _tiny_backend()
    be.advance(100.0)
    fired = []
    be.schedule_at(100.0, lambda now: fired.append(now))
    be.advance(50.0)
    assert fired == [100.0]


def test_scheduled_callback_mutates_cluster_mid_reassignment():
    """A broker death scheduled inside a copy window lands mid-flight and
    the completed reassignment still elects an ALIVE leader."""
    be = _tiny_backend()
    be.add_broker(2, "r0")
    be.alter_partition_reassignments({("t", 0): [2, 1]})
    be.schedule_at(20.0, lambda now: be.kill_broker(2))
    be.advance(10_000.0)   # 10 MB at the default rate completes quickly
    info = be.partitions()[("t", 0)]
    assert set(info.replicas) == {2, 1}
    assert info.leader == 1           # dead broker 2 must not lead
    assert check_tick(be) == []


def test_metric_silence_gaps_all_three_metric_surfaces():
    be = _tiny_backend()
    assert 0 in be.broker_metrics()
    assert ("t", 0) in be.partition_metrics()
    be.set_metric_silence(0, True)
    assert 0 not in be.broker_metrics()
    assert 1 in be.broker_metrics()
    assert ("t", 0) not in be.partition_metrics()      # leader 0 silenced
    entities, _, _ = be.partition_metrics_columnar()
    assert ("t", 0) not in entities
    be.set_metric_silence(0, False)
    assert 0 in be.broker_metrics()
    assert ("t", 0) in be.partition_metrics()


# ------------------------------------------------------- invariant checker


def test_check_tick_flags_dead_leader_and_duplicates():
    be = _tiny_backend()
    assert check_tick(be) == []
    # reach into the internals to fabricate corruption (bump the metadata
    # generation so the cached partitions() snapshot is rebuilt)
    info = be._partitions[("t", 0)]
    info.replicas = [0, 0]
    be._meta_gen += 1
    assert any("duplicate" in v for v in check_tick(be))
    info.replicas = [0, 1]
    be._brokers[0].alive = False      # leader 0 now dead, no re-election
    be._meta_gen += 1
    assert any("dead broker" in v for v in check_tick(be))


def test_check_converged_flags_rf_and_dead_placement():
    be = _tiny_backend()
    expected = {("t", 0): 2}
    assert check_converged(be, expected) == []
    assert any("RF" in v for v in check_converged(be, {("t", 0): 3}))
    be.kill_broker(1)
    viol = check_converged(be, expected)
    assert any("dead broker 1" in v for v in viol)
    be2 = SimulatedClusterBackend()
    be2.add_broker(0, "r0", logdirs={"/d0": 100.0, "/d1": 100.0})
    be2.create_partition("t", 0, [0], logdir_by_broker={0: "/d1"})
    be2.fail_disk(0, "/d1")
    assert any("dead disk" in v for v in check_converged(be2, {("t", 0): 1}))


def test_build_backend_is_deterministic():
    spec = ClusterSpec(num_brokers=6, topics=(("a", 10, 2),),
                       logdirs_per_broker=2, seed=7)
    a, b = build_backend(spec), build_backend(spec)
    pa, pb = a.partitions(), b.partitions()
    assert list(pa) == list(pb)
    for tp in pa:
        assert pa[tp].replicas == pb[tp].replicas
        assert pa[tp].size_mb == pb[tp].size_mb
        assert pa[tp].logdir_by_broker == pb[tp].logdir_by_broker


# ------------------------------------------------- smoke scenario (tier 1)


@pytest.fixture(scope="module")
def smoke_runs():
    """Run the smoke scenario twice with the same seed: the pair feeds both
    the convergence asserts and the determinism proof (the second run reuses
    the compiled engine programs, so the pair costs ~one run)."""
    sc = SCENARIOS["broker-death-smoke"]
    return run_scenario(sc, seed=0), run_scenario(sc, seed=0)


def test_smoke_broker_death_converges(smoke_runs):
    r, _ = smoke_runs
    r.assert_ok()
    assert r.converged
    assert r.invariant_violations == []
    assert r.time_to_detect_ms is not None \
        and r.time_to_detect_ms <= 120_000.0
    assert r.time_to_heal_ms is not None and r.time_to_heal_ms <= 300_000.0
    assert r.proposals > 0 and r.executor_tasks > 0 and r.executions >= 1


def test_smoke_timeline_shape(smoke_runs):
    r, _ = smoke_runs
    assert r.timeline[0]["kind"] == "inject"
    assert "broker_death" in r.timeline[0]["event"]
    fixes = [e for e in r.timeline if e["kind"] == "anomaly"
             and e["type"] == "BROKER_FAILURE" and e["action"] == "FIX"]
    assert any(e.get("fix", {}).get("executed") for e in fixes)
    # the grace ladder defers before it fixes
    assert any(e["action"] == "CHECK" for e in r.timeline
               if e["kind"] == "anomaly")


def test_smoke_timeline_is_bit_identical_across_runs(smoke_runs):
    r1, r2 = smoke_runs
    assert r1.timeline == r2.timeline
    assert r1.to_json() == r2.to_json()


def test_flight_recorder_matches_runner_accounting(smoke_runs):
    """The scenario run populates the library-level detect/heal latency
    timers, and the flight recorder's RoundTraces agree with the runner's
    own time_to_heal_ms accounting — the runner consumes the SAME records
    the service serves, not private bookkeeping."""
    import pytest as _pytest
    r, r2 = smoke_runs
    # detect/heal TIMERS (simulated seconds) match the runner's numbers
    assert r.sensors["time-to-detect-timer"]["count"] == 1
    assert r.sensors["time-to-detect-timer"]["maxSec"] == _pytest.approx(
        r.time_to_detect_ms / 1000.0)
    assert r.sensors["time-to-heal-timer"]["count"] == 1
    assert r.sensors["time-to-heal-timer"]["maxSec"] == _pytest.approx(
        r.time_to_heal_ms / 1000.0)
    # the manager's per-type heal timer fired for the broker-failure FIX
    heal = r.sensors["broker_failure-self-healing-fix-timer"]
    assert heal["count"] >= 1
    # the executor timed its healing execution on the SIMULATED clock
    assert r.sensors["proposal-execution-timer"]["count"] >= 1
    # a FIX that completed at heal time can never exceed fault->heal latency
    assert heal["maxSec"] * 1000.0 <= r.time_to_heal_ms + 1e-6
    # the recorder captured the healing optimization round(s): the broker
    # failure fixes via REMOVE_BROKER; traces live on SIMULATED time
    fix_traces = [t for t in r.round_traces
                  if t["operation"] == "REMOVE_BROKER"]
    assert fix_traces, [t["operation"] for t in r.round_traces]
    assert all(t["num_proposals"] > 0 for t in fix_traces)
    # trace timestamps are simulated ms -> deterministic across reruns
    assert [t["ts_ms"] for t in r.round_traces] == \
        [t["ts_ms"] for t in r2.round_traces]
    assert [t["operation"] for t in r.round_traces] == \
        [t["operation"] for t in r2.round_traces]


def test_different_seed_changes_cluster_not_contract():
    sc = SCENARIOS["broker-death-smoke"]
    r = run_scenario(sc, seed=3)
    r.assert_ok()


def test_metric_gap_scenario_no_false_healing():
    r = run_scenario(SCENARIOS["metric-gap"])
    r.assert_ok()
    assert r.proposals == 0 and r.executions == 0
    handled = {e["type"] for e in r.timeline if e["kind"] == "anomaly"}
    assert "BROKER_FAILURE" not in handled


def test_topic_creation_scenario_converges():
    r = run_scenario(SCENARIOS["topic-creation"])
    r.assert_ok()
    assert any("topic_creation" in e.get("event", "") for e in r.timeline)


def test_runner_reports_unconverged_as_failure():
    """A contract the loop cannot meet must surface as a failure, not hang:
    zero-duration run with a broker death can never evacuate."""
    sc = dataclasses.replace(
        SCENARIOS["broker-death-smoke"], name="impossible",
        events=(broker_death(0.0, [3]),), duration_ms=30_000.0)
    r = run_scenario(sc)
    assert not r.converged
    assert any("did not converge" in f for f in r.failures)


# ------------------------------------------------------ full catalog (slow)


@pytest.mark.slow
def test_disk_failure_scenario():
    runner = ScenarioRunner(SCENARIOS["disk-failure"])
    r = runner.run()
    r.assert_ok()
    # post-heal: nothing lives on the failed disk (also in check_converged,
    # asserted here explicitly for the scenario's headline property)
    for info in runner.backend.partitions().values():
        assert info.logdir_by_broker.get(2) != "/logdir1"


@pytest.mark.slow
def test_slow_broker_scenario_demotes():
    r = run_scenario(SCENARIOS["slow-broker-demotion"])
    r.assert_ok()
    handled = {e["type"] for e in r.timeline if e["kind"] == "anomaly"}
    assert "METRIC_ANOMALY" in handled


@pytest.mark.slow
def test_maintenance_scenario_empties_broker():
    runner = ScenarioRunner(SCENARIOS["maintenance-remove-broker"])
    r = runner.run()
    r.assert_ok()
    assert all(4 not in info.replicas
               for info in runner.backend.partitions().values())


@pytest.mark.slow
def test_broker_death_50b_1k_scenario():
    r = run_scenario(SCENARIOS["broker-death-50b-1k"])
    r.assert_ok()
    assert r.time_to_heal_ms <= 600_000.0


@pytest.mark.slow
def test_compound_cascade_scenario():
    """Broker death DURING an ongoing throttled rebalance plus a mid-flight
    maintenance plan: the hardest catalog entry."""
    r = run_scenario(SCENARIOS["compound-cascade"])
    r.assert_ok()
    death = next(e for e in r.timeline if "broker_death" in e.get("event", ""))
    assert death["during_execution"], \
        "broker death must land inside the rebalance execution window"
    plans = [e for e in r.timeline if e.get("type") == "MAINTENANCE_EVENT"]
    assert len(plans) >= 2            # REBALANCE + DEMOTE_BROKER both handled
    assert r.executions >= 2


@pytest.mark.slow
def test_cascade_deterministic_across_runs():
    sc = SCENARIOS["compound-cascade"]
    assert run_scenario(sc).timeline == run_scenario(sc).timeline
