"""Rack-awareness goals.

Reference: analyzer/goals/RackAwareGoal.java:235 (hard: no two replicas of a
partition share a rack) and RackAwareDistributionGoal.java:415 (relaxed: allow
sharing only when #replicas > #racks, and then spread as evenly as possible).
State is the partition x rack membership count ``st.part_rack_count`` kept
incrementally by the engine.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from cruise_control_tpu.analyzer.env import ClusterEnv
from cruise_control_tpu.analyzer.goals.base import NEG_INF, GoalKernel
from cruise_control_tpu.analyzer.state import EngineState


def _replica_corack_count(env: ClusterEnv, st: EngineState) -> jnp.ndarray:
    """i32[R]: number of OTHER replicas of this replica's partition in this
    replica's current rack."""
    rack = env.broker_rack[st.replica_broker]
    return st.part_rack_count[env.replica_partition, rack] - 1


@dataclasses.dataclass(frozen=True)
class RackAwareGoal(GoalKernel):
    def __post_init__(self):
        object.__setattr__(self, "name", "RackAwareGoal")
        object.__setattr__(self, "is_hard", True)
        # acceptance depends only on per-(partition, rack) counts: the wave's
        # partition-first-touch rule keeps it single-move-exact
        object.__setattr__(self, "wave_safe", True)

    def broker_severity(self, env: ClusterEnv, st: EngineState):
        """Severity = count of rack-violating (or offline) replicas per broker."""
        viol = (_replica_corack_count(env, st) > 0) & env.replica_valid
        viol = viol | (st.replica_offline & env.replica_valid)
        return jax.ops.segment_sum(viol.astype(st.util.dtype), st.replica_broker,
                                   num_segments=env.num_brokers)

    def replica_key(self, env: ClusterEnv, st: EngineState, severity):
        viol = (_replica_corack_count(env, st) > 0) & env.replica_valid
        offline = st.replica_offline & env.replica_valid
        load = jnp.sum(st.effective_load(env), axis=1)
        key = jnp.where(viol | offline, -load, NEG_INF)  # cheapest first
        return jnp.where(offline, key + 1e12, key)

    def move_score(self, env: ClusterEnv, st: EngineState, cand):
        p = env.replica_partition[cand]
        rack_dst = env.broker_rack[None, :]                                  # [1, B]
        # row-gather then take-along-axis: a direct [K, B] fancy gather from
        # the [P, Kr] table materializes poorly inside the engine loop
        dst_rack_count = st.part_rack_count[p][:, env.broker_rack]           # [K, B]
        cur_rack = env.broker_rack[st.replica_broker[cand]][:, None]
        same_rack = rack_dst == cur_rack
        # count of partition replicas in destination rack, excluding self
        others = dst_rack_count - jnp.where(same_rack, 1, 0)
        feasible = others == 0
        # prefer low-utilization destinations (balance tiebreak)
        cap = jnp.maximum(jnp.sum(env.broker_capacity, axis=1), 1e-6)
        util_frac = jnp.sum(st.util, axis=1) / cap
        # per-candidate corack count (NOT the full [R] gather: move_score runs
        # once per applied move inside the engine's re-scoring loop)
        corack = st.part_rack_count[p, cur_rack[:, 0]] - 1                   # [K]
        was_violating = (corack > 0) | st.replica_offline[cand]
        score = 1.0 + 0.5 * (1.0 - util_frac)[None, :]
        return jnp.where(feasible & was_violating[:, None], score, NEG_INF)

    def accept_move(self, env: ClusterEnv, st: EngineState, cand):
        """Veto moves that would co-locate partition replicas in one rack."""
        p = env.replica_partition[cand]
        rack_dst = env.broker_rack[None, :]
        dst_rack_count = st.part_rack_count[p][:, env.broker_rack]
        cur_rack = env.broker_rack[st.replica_broker[cand]][:, None]
        others = dst_rack_count - jnp.where(rack_dst == cur_rack, 1, 0)
        return others == 0

    def violated(self, env: ClusterEnv, st: EngineState):
        viol = (_replica_corack_count(env, st) > 0) & env.replica_valid
        return jnp.any(viol)


@dataclasses.dataclass(frozen=True)
class RackAwareDistributionGoal(GoalKernel):
    """Relaxed rack awareness (RackAwareDistributionGoal.java:415): replicas of
    a partition are spread across racks as evenly as possible — a rack may hold
    ceil(RF / num_racks) replicas at most."""

    def __post_init__(self):
        object.__setattr__(self, "name", "RackAwareDistributionGoal")
        object.__setattr__(self, "is_hard", True)
        object.__setattr__(self, "wave_safe", True)   # per-(partition, rack)

    def _partition_rf(self, env: ClusterEnv) -> jnp.ndarray:
        return jnp.sum(env.partition_replicas >= 0, axis=1)                  # i32[P]

    def _max_per_rack(self, env: ClusterEnv) -> jnp.ndarray:
        rf = self._partition_rf(env)
        return jnp.ceil(rf / jnp.maximum(env.num_real_racks, 1)).astype(jnp.int32)

    def broker_severity(self, env: ClusterEnv, st: EngineState):
        limit = self._max_per_rack(env)                                      # [P]
        rack = env.broker_rack[st.replica_broker]
        count = st.part_rack_count[env.replica_partition, rack]
        viol = (count > limit[env.replica_partition]) & env.replica_valid
        viol = viol | (st.replica_offline & env.replica_valid)
        return jax.ops.segment_sum(viol.astype(st.util.dtype), st.replica_broker,
                                   num_segments=env.num_brokers)

    def replica_key(self, env: ClusterEnv, st: EngineState, severity):
        limit = self._max_per_rack(env)
        rack = env.broker_rack[st.replica_broker]
        count = st.part_rack_count[env.replica_partition, rack]
        viol = (count > limit[env.replica_partition]) & env.replica_valid
        offline = st.replica_offline & env.replica_valid
        load = jnp.sum(st.effective_load(env), axis=1)
        key = jnp.where(viol | offline, -load, NEG_INF)
        return jnp.where(offline, key + 1e12, key)

    def _max_per_rack_for(self, env: ClusterEnv, p):
        """i32[K] per-candidate rack limit (avoids the full [P] computation in
        the engine's per-move re-scoring loop)."""
        rf = jnp.sum(env.partition_replicas[p] >= 0, axis=1)                 # [K]
        return jnp.ceil(rf / jnp.maximum(env.num_real_racks, 1)).astype(jnp.int32)

    def move_score(self, env: ClusterEnv, st: EngineState, cand):
        p = env.replica_partition[cand]
        limit = self._max_per_rack_for(env, p)[:, None]                      # [K, 1]
        rack_dst = env.broker_rack[None, :]
        dst_count = st.part_rack_count[p][:, env.broker_rack]
        cur_rack = env.broker_rack[st.replica_broker[cand]][:, None]
        others = dst_count - jnp.where(rack_dst == cur_rack, 1, 0)
        feasible = others + 1 <= limit
        cap = jnp.maximum(jnp.sum(env.broker_capacity, axis=1), 1e-6)
        util_frac = jnp.sum(st.util, axis=1) / cap
        was_violating = ((st.part_rack_count[p, cur_rack[:, 0]] > limit[:, 0])
                         | st.replica_offline[cand])
        score = 1.0 + 0.5 * (1.0 - util_frac)[None, :]
        return jnp.where(feasible & was_violating[:, None], score, NEG_INF)

    def accept_move(self, env: ClusterEnv, st: EngineState, cand):
        p = env.replica_partition[cand]
        limit = self._max_per_rack_for(env, p)[:, None]
        rack_dst = env.broker_rack[None, :]
        dst_count = st.part_rack_count[p][:, env.broker_rack]
        cur_rack = env.broker_rack[st.replica_broker[cand]][:, None]
        others = dst_count - jnp.where(rack_dst == cur_rack, 1, 0)
        return others + 1 <= limit

    def violated(self, env: ClusterEnv, st: EngineState):
        limit = self._max_per_rack(env)
        rack = env.broker_rack[st.replica_broker]
        count = st.part_rack_count[env.replica_partition, rack]
        viol = (count > limit[env.replica_partition]) & env.replica_valid
        return jnp.any(viol)
