"""Scenario catalog: the scripted failure modes every PR must survive.

Each entry is a full closed-loop run (monitor -> detect -> notifier ladder
-> optimizer -> executor -> backend) with convergence bounds in SIMULATED
milliseconds. The smoke scenario is sized to stay inside the shared
small-fixture compile bucket (pad_cluster floors: <=16 brokers, <=1024
replicas, <=256 partitions, <=16 topics), so the tier-1 suite reuses the
same compiled engine programs as the rest of the fast tier instead of
paying a fresh XLA compile; the larger 50-broker / 1k-partition variant and
the compound cascade live in the slow tier.

``GV_OFF`` disables goal-violation detection where it would only add
optimizer noise to a scenario about a different detector (its first run is
scheduled at interval/2 — an astronomically large interval never fires).
"""
from __future__ import annotations

from cruise_control_tpu.sim.scenario import (
    ClusterSpec, Scenario, broker_death, clear_slow_broker, disk_failure,
    load_surge, maintenance_event, metric_gap, rack_surge, rf_drop,
    slow_broker, topic_creation,
)

GV_OFF = ("goal.violation.detection.interval.ms", 10_000_000_000)

_SMALL = ClusterSpec(num_brokers=12, num_racks=3,
                     topics=(("t0", 60, 2), ("t1", 60, 2)))


BROKER_DEATH_SMOKE = Scenario(
    name="broker-death-smoke",
    cluster=_SMALL,
    events=(broker_death(0.0, [3]),),
    duration_ms=900_000.0,
    tick_ms=15_000.0,
    # tier-1 budget: one detection pass before the grace ladder expires
    # (120 s backoff) and a 3-goal evacuation chain — the full 8-goal
    # self-healing chain is exercised by the slow-tier scenarios
    config=(GV_OFF,
            ("broker.failure.detection.backoff.ms", 120_000),
            ("self.healing.goals",
             "ReplicaCapacityGoal,DiskCapacityGoal,ReplicaDistributionGoal")),
    max_detect_ms=120_000.0,     # backoff/2 + scheduler phase + tick grid
    max_heal_ms=300_000.0,       # detect + 60 s grace + evacuation
    expect_detect_types=("BROKER_FAILURE",),
    expect_empty_brokers=(3,),
)

BROKER_DEATH_50B = Scenario(
    name="broker-death-50b-1k",
    cluster=ClusterSpec(num_brokers=50, num_racks=5,
                        topics=(("t0", 250, 2), ("t1", 250, 2),
                                ("t2", 250, 2), ("t3", 250, 2))),
    events=(broker_death(0.0, [7]),),
    duration_ms=1_800_000.0,
    tick_ms=15_000.0,
    config=(GV_OFF,),
    max_detect_ms=120_000.0,
    max_heal_ms=600_000.0,
    expect_detect_types=("BROKER_FAILURE",),
    expect_empty_brokers=(7,),
)

DISK_FAILURE = Scenario(
    name="disk-failure",
    cluster=ClusterSpec(num_brokers=12, num_racks=3,
                        topics=(("t0", 60, 2), ("t1", 60, 2)),
                        logdirs_per_broker=2),
    events=(disk_failure(0.0, broker_id=2, logdir="/logdir1"),),
    duration_ms=900_000.0,
    tick_ms=15_000.0,
    config=(GV_OFF,),
    max_detect_ms=120_000.0,
    max_heal_ms=300_000.0,
    expect_detect_types=("DISK_FAILURE",),
)

SLOW_BROKER = Scenario(
    name="slow-broker-demotion",
    cluster=_SMALL,
    events=(slow_broker(0.0, broker_id=5, flush_ms=5000.0, bytes_in=1.0),
            clear_slow_broker(300_000.0, broker_id=5)),
    duration_ms=1_200_000.0,
    tick_ms=15_000.0,
    config=(GV_OFF,
            ("metric.anomaly.detection.interval.ms", 30_000),
            ("slow.broker.demotion.score", 3)),
    max_detect_ms=240_000.0,     # needs demotion_score consecutive hits
    max_heal_ms=600_000.0,
    expect_detect_types=("METRIC_ANOMALY",),
    expect_nonleader_brokers=(5,),
)

METRIC_GAP = Scenario(
    name="metric-gap",
    cluster=_SMALL,
    events=(metric_gap(0.0, 180_000.0, [1, 2]),),
    duration_ms=900_000.0,
    tick_ms=15_000.0,
    # GV stays ON here: the loop keeps running its normal detection under
    # partial metric blindness and must not misread the gap as a failure
    config=(),
    expects_heal=True,           # convergence = nothing broke, nothing moved
    forbid_detect_types=("BROKER_FAILURE", "DISK_FAILURE"),
    settle_ticks=4,              # give a spurious failure time to surface
)

MAINTENANCE_REMOVE = Scenario(
    name="maintenance-remove-broker",
    cluster=_SMALL,
    events=(maintenance_event(0.0, "REMOVE_BROKER", brokers=[4]),),
    duration_ms=900_000.0,
    tick_ms=15_000.0,
    config=(GV_OFF,),
    max_detect_ms=90_000.0,      # plans poll on the base interval, no ladder
    max_heal_ms=300_000.0,
    expect_detect_types=("MAINTENANCE_EVENT",),
    expect_empty_brokers=(4,),
)

TOPIC_CREATION = Scenario(
    name="topic-creation",
    cluster=_SMALL,
    events=(topic_creation(0.0, "tnew", partitions=20, rf=2, size_mb=80.0),),
    duration_ms=900_000.0,
    tick_ms=15_000.0,
    config=(GV_OFF,),
    expects_heal=True,           # converge with the new topic replicated+led
    settle_ticks=2,
)

TOPIC_RF_REPAIR = Scenario(
    name="topic-rf-repair",
    cluster=_SMALL,
    # drop t0 to RF 1: the TopicReplicationFactorAnomalyFinder must detect
    # the under-replication and the repair PLAN must execute through the
    # executor (replica adds on least-loaded alive brokers, task-accounted)
    events=(rf_drop(0.0, "t0", 1),),
    duration_ms=900_000.0,
    tick_ms=15_000.0,
    config=(GV_OFF,
            ("self.healing.target.topic.replication.factor", 2),
            ("topic.anomaly.detection.interval.ms", 60_000)),
    max_detect_ms=120_000.0,
    max_heal_ms=300_000.0,
    expect_detect_types=("TOPIC_ANOMALY",),
)

UNDER_PROVISION_SURGE = Scenario(
    name="under-provision-surge",
    cluster=_SMALL,
    # 1.7x load surge against calibrated-low NW_IN capacity (see the chaos
    # campaign's calibrated twin, sim/campaign._provision_episode): the
    # GoalViolationDetector's capacity math must go UNDER_PROVISIONED, the
    # verdict must actuate a simulated broker add (SimulatedProvisioner),
    # and the loop must re-converge RIGHT_SIZED after the resize
    events=(load_surge(0.0, 1.7),),
    duration_ms=2_400_000.0,
    tick_ms=15_000.0,
    config=(("default.broker.capacity.nw.in", 2200.0),
            ("provisioner.class",
             "cruise_control_tpu.detector.provisioner.SimulatedProvisioner"),
            ("provision.actuation.cooldown.ms", 300_000),
            ("provision.max.added.brokers", 4),
            ("anomaly.detection.goals",
             "NetworkInboundCapacityGoal,DiskCapacityGoal,"
             "ReplicaDistributionGoal"),
            ("goal.violation.detection.interval.ms", 120_000)),
    expect_detect_types=("GOAL_VIOLATION",),
    expect_provision=("add_broker",),
)

COMPOUND_CASCADE = Scenario(
    name="compound-cascade",
    cluster=ClusterSpec(num_brokers=16, num_racks=4,
                        topics=(("t0", 100, 2), ("t1", 100, 2)),
                        skew=1.5),
    events=(
        # 1) operator rebalance of a skewed cluster (long, throttled run)
        maintenance_event(0.0, "REBALANCE"),
        # 2) broker dies while the rebalance is still copying (the plan is
        # detected by ~60 s and the throttled execution runs for simulated
        # minutes, so 90 s lands provably mid-flight)
        broker_death(90_000.0, [2]),
        # 3) operator plan lands mid-flight of the recovery
        maintenance_event(120_000.0, "DEMOTE_BROKER", brokers=[5]),
    ),
    duration_ms=3_600_000.0,
    tick_ms=30_000.0,
    config=(
        # throttle so replica copies take ~50 simulated s each — the death
        # provably lands inside the rebalance execution window
        ("default.replication.throttle", 2 * 1024 * 1024),
        ("goal.violation.detection.interval.ms", 300_000),
    ),
    max_heal_ms=1_800_000.0,
    expect_detect_types=("MAINTENANCE_EVENT", "BROKER_FAILURE"),
    expect_empty_brokers=(2,),
)

# ------------------------------------------------------ moving workloads
# The predictive-control scenario pack: load PROFILES instead of step
# faults. Events are emitted as ratio-factor surges (the backend API is
# multiplicative), every minute so each metric window sees one step of the
# profile — a coherent trend the forecaster can extrapolate. Capacity is
# calibrated low on NW_IN (like UNDER_PROVISION_SURGE) but the surge hits a
# topic/rack SUBSET: the breach is an imbalance a rebalance fixes, so both
# the reactive heal (baseline) and the pre-breach predicted heal
# (forecast.enabled) have real work, and campaigns can score
# prevented-vs-reacted counts + time-under-violation per mode.


def _profile_events(levels, topics=None, every_ms=60_000.0, offset_ms=0.0):
    """Absolute load profile [lvl0, lvl1, ...] (multiples of the base load,
    one step per metric window) -> ratio-factor load_surge events."""
    evs, prev = [], 1.0
    for i, level in enumerate(levels):
        evs.append(load_surge(offset_ms + i * every_ms,
                              round(level / prev, 6), topics=topics))
        prev = level
    return tuple(evs)


# forecast-on control plane: detection goals with calibrated NW_IN capacity,
# predictive detector each minute, sim-side ground-truth SLO probe on
_FORECAST_CFG = (
    ("forecast.enabled", True),
    ("forecast.horizon.ms", 300_000),
    ("forecast.slo.tracking.enabled", True),
    ("predicted.goal.violation.detection.interval.ms", 60_000),
    ("goal.violation.detection.interval.ms", 120_000),
    ("anomaly.detection.goals",
     "NetworkInboundCapacityGoal,DiskCapacityGoal,ReplicaDistributionGoal"),
    # calibrated so the hottest broker crosses the 0.8 utilization line at
    # ~2.2x of the surged topic's base load — LATE in every profile's ramp
    # (the forecaster has 3+ windows of visible trend by then), while the
    # per-broker AVERAGE at peak stays under the line, keeping the breach
    # rebalance-fixable rather than a provisioning deficit
    ("default.broker.capacity.nw.in", 3000.0),
)

# two diurnal half-cycles on t0 (sine-shaped, peak 2.5x, 20 min period)
_DIURNAL_LEVELS = (1.0, 1.38, 1.75, 2.06, 2.31, 2.45, 2.5, 2.45, 2.31,
                   2.06, 1.75, 1.38, 1.0, 1.0,
                   1.0, 1.38, 1.75, 2.06, 2.31, 2.45, 2.5, 2.45, 2.31,
                   2.06, 1.75, 1.38, 1.0)

MOVING_DIURNAL = Scenario(
    name="moving-diurnal",
    cluster=_SMALL,
    events=_profile_events(_DIURNAL_LEVELS, topics=["t0"]),
    duration_ms=3_600_000.0,
    tick_ms=15_000.0,
    config=_FORECAST_CFG,
    expects_heal=True,
    settle_ticks=2,
)

# flash crowd: a building ramp to 2.6x on t0, a 4-minute plateau, fast
# decay — the early sub-breach windows are the forecaster's signal
_FLASH_LEVELS = (1.0, 1.15, 1.35, 1.6, 1.9, 2.2, 2.6, 2.6, 2.6, 2.6,
                 1.8, 1.2, 1.0)

MOVING_FLASH_CROWD = Scenario(
    name="moving-flash-crowd",
    cluster=_SMALL,
    events=_profile_events(_FLASH_LEVELS, topics=["t0"]),
    duration_ms=2_400_000.0,
    tick_ms=15_000.0,
    config=_FORECAST_CFG,
    expects_heal=True,
    settle_ticks=2,
)

# hotspot drift: the surge MOVES across topics — t0 ramps hot then cools
# while t1 ramps, then t2. The forecaster must track per-entity trends
# (a global trend would cancel out).
MOVING_HOTSPOT_DRIFT = Scenario(
    name="moving-hotspot-drift",
    cluster=ClusterSpec(num_brokers=12, num_racks=3,
                        topics=(("t0", 40, 2), ("t1", 40, 2), ("t2", 40, 2))),
    events=(_profile_events((1.0, 1.5, 2.0, 2.4, 2.4, 1.6, 1.0),
                            topics=["t0"])
            + _profile_events((1.0, 1.5, 2.0, 2.4, 2.4, 1.6, 1.0),
                              topics=["t1"], offset_ms=300_000.0)
            + _profile_events((1.0, 1.5, 2.0, 2.4, 2.4, 1.6, 1.0),
                              topics=["t2"], offset_ms=600_000.0)),
    duration_ms=2_700_000.0,
    tick_ms=15_000.0,
    config=_FORECAST_CFG,
    expects_heal=True,
    settle_ticks=2,
)

# correlated rack-level surge: every partition replicated on rack r1 heats
# together (ratio steps compound to ~2.3x, then decay) — the failure-domain
# pattern where many entities trend up in lockstep
MOVING_RACK_SURGE = Scenario(
    name="moving-rack-surge",
    cluster=_SMALL,
    events=tuple(rack_surge(i * 60_000.0, f, "r1")
                 for i, f in enumerate((1.15, 1.15, 1.15, 1.15, 1.15, 1.15,
                                        1.0, 1.0,
                                        0.869565, 0.869565, 0.869565,
                                        0.869565, 0.869565, 0.869565))),
    duration_ms=2_400_000.0,
    tick_ms=15_000.0,
    config=_FORECAST_CFG,
    expects_heal=True,
    settle_ticks=2,
)

# tier-1 smoke: the shortest profile that still yields a PREDICTED verdict —
# rides the shared 12-broker compile bucket like broker-death-smoke
FORECAST_SMOKE = Scenario(
    name="forecast-smoke",
    cluster=_SMALL,
    events=_profile_events((1.0, 1.45, 1.9, 2.3, 2.6, 2.6), topics=["t0"]),
    duration_ms=1_200_000.0,
    tick_ms=15_000.0,
    config=_FORECAST_CFG,
    expects_heal=True,
    expect_detect_types=("PREDICTED_GOAL_VIOLATION",),
    settle_ticks=2,
)

SCENARIOS = {
    s.name: s for s in (
        BROKER_DEATH_SMOKE, BROKER_DEATH_50B, DISK_FAILURE, SLOW_BROKER,
        METRIC_GAP, MAINTENANCE_REMOVE, TOPIC_CREATION, TOPIC_RF_REPAIR,
        UNDER_PROVISION_SURGE, COMPOUND_CASCADE,
        MOVING_DIURNAL, MOVING_FLASH_CROWD, MOVING_HOTSPOT_DRIFT,
        MOVING_RACK_SURGE, FORECAST_SMOKE,
    )
}
