"""Concurrent REST fuzzing inside chaos campaigns.

PR 8 built the fault *injector* (sim/campaign.py draws compound backend
faults); this module turns the fuzzer on the service's own front door: a
seeded REST fuzzer (:class:`ApiFuzzer`) drives user tasks — rebalance /
stop / state / proposals, valid AND malformed parameters, User-Task-ID
resumption races — against a LIVE :class:`~cruise_control_tpu.api.server.
CruiseControlServer` over real HTTP, *while* a campaign episode injects
faults through :class:`FaultyBackend` (seeded transient errors, latency
spikes, partial responses at the backend boundary the PR's retry/breaker
layer defends).

Determinism contract (the campaign bar, extended to the REST surface):
the fuzzer runs in LOCKSTEP with the scenario tick loop — its request
schedule is a pure function of the fuzz seed, requests are issued
sequentially from the tick hook, and mutating operations block to
completion before the next request — so at any instant at most one thread
advances the simulated clock. Same (campaign, fuzz-seed) therefore
reproduces a bit-identical episode log: the scenario timeline, the fuzz
log (endpoint, params, status bucket, staleness flags, dedup verdicts) and
every invariant verdict. Wall-clock-dependent values (task UUIDs, start
timestamps, latency) are deliberately never recorded.

Invariants checked per episode (failures land in ``fuzz_failures``):

- **no undeclared 500s** — every response status must be in the op's
  declared set; degraded reads/writes are DECLARED as 503 + Retry-After,
  parameter garbage as 400/404/405/429, everything else 2xx.
- **user-task census consistent** — every task id the fuzzer ever saw in a
  ``User-Task-ID`` response header is listed by GET /user_tasks.
- **no duplicate executions from racing triggers** — resuming a completed
  mutating task via its User-Task-ID (sequentially and from two racing
  threads) returns the cached result and never re-executes (executor
  ``numExecutions`` stays flat).
"""
from __future__ import annotations

import dataclasses
import http.client
import json
import random
import threading
import urllib.parse
import zlib


class TransientBackendError(RuntimeError):
    """The injected backend fault: callers must retry, not die."""


def _hash01(key: str) -> float:
    """crc32-based stable uniform draw in [0, 1): process-independent
    (PYTHONHASHSEED-free) and stateless — the verdict for (method, time
    bucket) never depends on HOW MANY calls happened before it, so
    nondeterministic call counts (gauge scrapes, retries) can't shift the
    fault schedule."""
    return (zlib.crc32(key.encode("utf-8")) & 0xFFFFFFFF) / 2.0 ** 32


class FaultyBackend:
    """Seeded fault-injecting ClusterBackend wrapper.

    Control-plane-facing reads/writes fail transiently, run slow (a
    simulated-time latency spike) or return partial data inside the
    configured fault windows; the simulation surface (clock, scheduling,
    fault injection, ``inner``) always passes through untouched so the
    scenario engine and its invariant oracle keep ground truth.
    """

    FAULTED_READS = (
        "brokers", "partitions", "snapshot", "partition_metrics",
        "partition_metrics_columnar", "broker_metrics", "describe_logdirs",
        "ongoing_reassignments", "topic_configs",
    )
    FAULTED_WRITES = (
        "alter_partition_reassignments", "elect_leaders",
        "alter_replica_logdirs", "cancel_reassignments",
        "set_replication_throttle", "set_topic_config",
    )
    # partial responses only make sense for per-broker maps; structural
    # metadata stays whole (a partial partitions() would look like topic
    # deletion, which is a different fault)
    PARTIAL_CAPABLE = ("broker_metrics", "describe_logdirs")

    def __init__(self, inner, seed: int = 0, windows=((0.0, float("inf")),),
                 error_rate: float = 0.25, latency_rate: float = 0.0,
                 partial_rate: float = 0.0, latency_ms: float = 200.0,
                 bucket_ms: float = 1000.0):
        self.inner = inner
        self._seed = seed
        self._windows = tuple((float(a), float(b)) for a, b in windows)
        self._base_ms = 0.0          # arm() rebases windows to scenario start
        self._error_rate = error_rate
        self._latency_rate = latency_rate
        self._partial_rate = partial_rate
        self._latency_ms = latency_ms
        self._bucket_ms = bucket_ms
        self.fault_counts: dict[str, int] = {"error": 0, "latency": 0,
                                             "partial": 0}
        self._lock = threading.Lock()

    def arm(self, t0_ms: float) -> None:
        """Windows are relative to scenario start; the runner arms us at t0."""
        self._base_ms = float(t0_ms)

    def _in_window(self, now: float) -> bool:
        rel = now - self._base_ms
        return any(a <= rel < b for a, b in self._windows)

    def _verdict(self, method: str) -> str | None:
        now = float(self.inner.now_ms())
        if not self._in_window(now):
            return None
        bucket = int(now // self._bucket_ms)
        u = _hash01(f"{self._seed}/{method}/{bucket}")
        if u < self._error_rate:
            return "error"
        if u < self._error_rate + self._latency_rate:
            return "latency"
        if (u < self._error_rate + self._latency_rate + self._partial_rate
                and method in self.PARTIAL_CAPABLE):
            return "partial"
        return None

    def _faulted(self, method: str, *args, **kwargs):
        verdict = self._verdict(method)
        if verdict == "error":
            with self._lock:
                self.fault_counts["error"] += 1
            raise TransientBackendError(
                f"injected transient fault: {method} at "
                f"{self.inner.now_ms():.0f} ms")
        if verdict == "latency":
            with self._lock:
                self.fault_counts["latency"] += 1
            # a latency spike on SIMULATED time: the slow call burns sim
            # milliseconds, racing the scenario's scheduled faults
            self.inner.advance(self._latency_ms)
        result = getattr(self.inner, method)(*args, **kwargs)
        if verdict == "partial":
            with self._lock:
                self.fault_counts["partial"] += 1
            bucket = int(float(self.inner.now_ms()) // self._bucket_ms)
            result = {k: v for k, v in result.items()
                      if _hash01(f"{self._seed}/partial/{k}/{bucket}") >= 0.5}
        return result

    def __getattr__(self, name):
        inner_attr = getattr(self.inner, name)
        if name in self.FAULTED_READS or name in self.FAULTED_WRITES:
            def wrapped(*args, **kwargs):
                return self._faulted(name, *args, **kwargs)
            return wrapped
        return inner_attr


# --------------------------------------------------------------- the fuzzer
@dataclasses.dataclass(frozen=True)
class FuzzSpec:
    """Seeded request-schedule shape. The schedule is a pure function of
    (spec, fuzz_seed): op kinds drawn by weight, spread one-per-slot over
    ``ticks`` ticks starting at ``start_tick``."""
    ops: int = 16
    start_tick: int = 1
    ticks: int = 24
    mutate: bool = True        # include non-dry-run rebalance triggers
    weights: tuple = (
        ("state", 2.0), ("proposals", 2.0), ("rebalance_dryrun", 1.5),
        ("user_tasks", 1.0), ("metrics", 1.0), ("malformed", 2.0),
        ("rebalance_execute", 1.0), ("stop", 0.5), ("resume_race", 1.0),
        # the monitor read family (PR 11): /load and /partition_load ride
        # the monitor's model-build breaker, /kafka_cluster_state the
        # facade.read breaker — all must degrade to DECLARED 503s, never
        # raw 500s, under injected backend faults
        ("load", 0.75), ("partition_load", 0.75),
        ("kafka_cluster_state", 0.75),
    )


# malformed-request catalog: (label, method, path+query, expected statuses).
# Rotated deterministically by the schedule RNG.
_MALFORMED = (
    ("unknown_param", "GET", "/proposals?bogus_param=1", ("400",)),
    ("bad_int", "POST",
     "/rebalance?concurrent_leader_movements=banana&reason=fuzz", ("400",)),
    ("unknown_endpoint", "GET", "/definitely_not_an_endpoint", ("404",)),
    ("wrong_method", "GET", "/rebalance", ("405",)),
    ("bad_regex", "POST", "/rebalance?excluded_topics=[&reason=fuzz",
     ("400",)),
    ("missing_required", "POST", "/topic_configuration?reason=fuzz",
     ("400",)),
    ("bad_anomaly_type", "POST",
     "/admin?disable_self_healing_for=NOT_A_TYPE&reason=fuzz", ("400",)),
    ("bad_strategy", "POST",
     "/rebalance?replica_movement_strategies=NoSuchStrategy&reason=fuzz",
     ("400",)),
)


def _bucket(status: int) -> str:
    if 200 <= status < 300:
        return "2xx"
    return str(status)


def _classify(status: int, body: dict | None) -> str:
    """Status bucket with DECLARED application failures split out: a typed
    OptimizationFailureError (e.g. hard goals unsatisfiable on a genuinely
    under-provisioned cluster) is the reference's documented rebalance
    failure mode, not an undeclared crash — only untyped 500s stay '500'."""
    bucket = _bucket(status)
    if bucket == "500" and body is not None and str(
            body.get("errorMessage", "")).startswith(
            "OptimizationFailureError"):
        return "optfail"
    return bucket


class ApiFuzzer:
    """Lockstep REST fuzzer bound to a ScenarioRunner via its tick hook.

    Owns the live :class:`CruiseControlServer` (created lazily around the
    runner's app on first tick, real HTTP on a loopback port) and the
    deterministic request schedule. Results: ``log`` (bit-reproducible per
    fuzz seed), ``failures`` (invariant violations), ``observed_task_ids``.
    """

    def __init__(self, spec: FuzzSpec | None = None, fuzz_seed: int = 0,
                 name: str = "fuzz"):
        self.spec = spec or FuzzSpec()
        self.fuzz_seed = fuzz_seed
        self.name = name
        self.log: list[dict] = []
        self.failures: list[str] = []
        self.observed_task_ids: list[str] = []
        self._completed_mutations: list[tuple[str, str]] = []  # (task_id, query)
        self._server = None
        self._port = None
        self._schedule = self._draw_schedule()
        self._tick_index = 0
        self.requests = 0

    # ------------------------------------------------------------- schedule
    def _draw_schedule(self) -> dict[int, list]:
        rng = random.Random(f"{self.name}/fuzz/{self.fuzz_seed}")
        weights = [(k, w) for k, w in self.spec.weights
                   if self.spec.mutate or k not in ("rebalance_execute",)]
        total = sum(w for _, w in weights)
        by_tick: dict[int, list] = {}
        for i in range(self.spec.ops):
            x = rng.uniform(0.0, total)
            acc, kind = 0.0, weights[-1][0]
            for k, w in weights:
                acc += w
                if x <= acc:
                    kind = k
                    break
            detail = None
            if kind == "malformed":
                detail = rng.randrange(len(_MALFORMED))
            tick = self.spec.start_tick + rng.randrange(self.spec.ticks)
            by_tick.setdefault(tick, []).append((i, kind, detail))
        for ops in by_tick.values():
            ops.sort()           # issue in draw order within a tick
        return by_tick

    # ---------------------------------------------------------------- http
    def _ensure_server(self, runner) -> None:
        if self._server is not None:
            return
        from cruise_control_tpu.api.server import CruiseControlServer
        # generous max_block: lockstep ops complete inside one request, so
        # the clock has exactly one advancing thread at a time
        self._server = CruiseControlServer(
            runner.cc, host="127.0.0.1", port=0, max_block_ms=600_000.0,
            config=runner.cc.config)
        self._server.start()
        self._port = self._server.port

    def _request(self, method: str, path_query: str,
                 task_id: str | None = None):
        """One HTTP request; returns (status, body_dict|None, task_header)."""
        conn = http.client.HTTPConnection("127.0.0.1", self._port, timeout=600)
        try:
            headers = {"Content-Length": "0"} if method == "POST" else {}
            if task_id is not None:
                headers["User-Task-ID"] = task_id
            conn.request(method, "/kafkacruisecontrol" + path_query,
                         headers=headers)
            resp = conn.getresponse()
            raw = resp.read()
            self.requests += 1
            body = None
            ctype = resp.getheader("Content-Type") or ""
            if "json" in ctype:
                try:
                    body = json.loads(raw.decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    body = None
            tid = resp.getheader("User-Task-ID")
            if tid and tid not in self.observed_task_ids:
                self.observed_task_ids.append(tid)
            return resp.status, body, tid
        finally:
            conn.close()

    # ----------------------------------------------------------------- ops
    def tick(self, runner, now_ms: float) -> None:
        """ScenarioRunner tick hook: issue this tick's scheduled requests."""
        self._ensure_server(runner)
        self._tick_index += 1
        for i, kind, detail in self._schedule.get(self._tick_index, ()):
            entry = {"op": i, "kind": kind, "tick": self._tick_index}
            try:
                self._run_op(runner, kind, detail, i, entry)
            except Exception as e:  # noqa: BLE001 — an op crash is a finding
                entry["status"] = "client-error"
                self.failures.append(
                    f"op {i} ({kind}): client raised {type(e).__name__}: {e}")
            self.log.append(entry)

    def _expect(self, entry: dict, status: int, expected: tuple,
                body: dict | None = None) -> None:
        bucket = _classify(status, body)
        entry["status"] = bucket
        if bucket not in expected:
            self.failures.append(
                f"op {entry['op']} ({entry['kind']}): undeclared status "
                f"{status} (declared: {expected})")

    def _run_op(self, runner, kind: str, detail, i: int, entry: dict) -> None:
        degraded_ok = ("2xx", "503")
        # optimization surfaces may also fail with the TYPED hard-goal
        # failure (see _classify) — declared, deterministic per schedule
        optimize_ok = ("2xx", "503", "optfail")
        if kind == "state":
            status, _, _ = self._request(
                "GET", "/state?substates=EXECUTOR,ANOMALY_DETECTOR")
            self._expect(entry, status, ("2xx",))
        elif kind == "proposals":
            status, body, _ = self._request("GET", "/proposals")
            self._expect(entry, status, degraded_ok, body)
            if body is not None and "stale" in body:
                entry["stale"] = bool(body["stale"])
        elif kind == "rebalance_dryrun":
            status, body, _ = self._request(
                "POST", f"/rebalance?dryrun=true&reason=fuzz{i}")
            self._expect(entry, status, optimize_ok, body)
        elif kind == "rebalance_execute":
            query = f"/rebalance?dryrun=false&reason=fuzz{i}"
            status, body, tid = self._request("POST", query)
            self._expect(entry, status, optimize_ok, body)
            if status == 200 and tid:
                entry["executed"] = bool((body or {}).get("executed"))
                self._completed_mutations.append((tid, query))
                # User-Task-ID resumption must replay the CACHED result:
                # executor execution count stays flat (no duplicate
                # execution from re-triggering a completed mutation)
                before = runner.cc.executor.state_json()["numExecutions"]
                rstatus, _, rtid = self._request("POST", query, task_id=tid)
                after = runner.cc.executor.state_json()["numExecutions"]
                entry["resume_status"] = _bucket(rstatus)
                entry["resume_same_task"] = rtid == tid
                entry["dup_execution"] = after != before
                if after != before:
                    self.failures.append(
                        f"op {i} (rebalance_execute): resuming the completed "
                        f"task re-executed ({before} -> {after})")
                if rstatus != 200 or rtid != tid:
                    self.failures.append(
                        f"op {i} (rebalance_execute): resume returned "
                        f"{rstatus} / different task")
        elif kind == "stop":
            status, _, _ = self._request(
                "POST", f"/stop_proposal_execution?reason=fuzz{i}")
            self._expect(entry, status, ("2xx",))
        elif kind == "user_tasks":
            status, _, _ = self._request("GET", "/user_tasks")
            self._expect(entry, status, ("2xx",))
        elif kind == "load":
            # model-build read: degraded-mode contract is a declared 503
            # (monitor breaker open / injected fault), never a raw 500
            status, body, _ = self._request("GET", "/load")
            self._expect(entry, status, degraded_ok, body)
        elif kind == "partition_load":
            status, body, _ = self._request(
                "GET", "/partition_load?max_load=true")
            self._expect(entry, status, degraded_ok, body)
        elif kind == "kafka_cluster_state":
            status, body, _ = self._request("GET", "/kafka_cluster_state")
            self._expect(entry, status, degraded_ok, body)
        elif kind == "metrics":
            status, _, _ = self._request("GET", "/metrics")
            self._expect(entry, status, ("2xx",))
        elif kind == "malformed":
            label, method, pathq, expected = _MALFORMED[detail]
            entry["malformed"] = label
            status, _, _ = self._request(method, pathq)
            self._expect(entry, status, expected)
        elif kind == "resume_race":
            if not self._completed_mutations:
                entry["status"] = "skipped"   # deterministic: schedule-driven
                return
            tid, query = self._completed_mutations[-1]
            before = runner.cc.executor.state_json()["numExecutions"]
            results = [None, None]

            def poll(slot):
                results[slot] = self._request("POST", query, task_id=tid)

            threads = [threading.Thread(target=poll, args=(s,))
                       for s in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(600)
            after = runner.cc.executor.state_json()["numExecutions"]
            statuses = sorted(_bucket(r[0]) for r in results if r)
            same_task = all(r and r[2] == tid for r in results)
            entry["status"] = "/".join(statuses) or "client-error"
            entry["race_same_task"] = same_task
            entry["dup_execution"] = after != before
            if statuses != ["2xx", "2xx"] or not same_task:
                self.failures.append(
                    f"op {i} (resume_race): racing resumptions returned "
                    f"{statuses}, same_task={same_task}")
            if after != before:
                self.failures.append(
                    f"op {i} (resume_race): racing resumptions re-executed "
                    f"({before} -> {after})")
        else:
            raise ValueError(f"unknown fuzz op kind {kind!r}")

    # ------------------------------------------------------------ finalize
    def finalize(self) -> None:
        """Post-episode invariants + server teardown."""
        try:
            if self._server is not None and self.observed_task_ids:
                status, body, _ = self._request(
                    "GET", "/user_tasks?entries=10000")
                listed = {row.get("UserTaskId")
                          for row in (body or {}).get("userTasks", ())}
                if status != 200:
                    self.failures.append(
                        f"census: GET /user_tasks returned {status}")
                else:
                    missing = [t for t in self.observed_task_ids
                               if t not in listed]
                    if missing:
                        self.failures.append(
                            f"census: {len(missing)} task id(s) returned in "
                            f"User-Task-ID headers are missing from "
                            f"/user_tasks")
        finally:
            if self._server is not None:
                self._server.stop()
                self._server = None

    def log_json(self) -> list[dict]:
        return [dict(e) for e in self.log]


# ----------------------------------------------------- cluster-scoped fuzz
# malformed / unknown cluster_id catalog (PR 13 fleet routing): the invariant
# is that wrong-tenant access is a DECLARED 404 and garbage a DECLARED 400 —
# never a 500, never another tenant's data
_MALFORMED_CLUSTER = (
    ("traversal", "GET", "/state?cluster_id=..%2F..%2Fetc", ("400",)),
    ("empty", "GET", "/proposals?cluster_id=", ("400",)),
    ("overlong", "GET", "/state?cluster_id=" + "x" * 80, ("400",)),
    ("spacey", "GET", "/state?cluster_id=a%20b", ("400",)),
    ("unknown_state", "GET", "/state?cluster_id=no-such-tenant", ("404",)),
    ("unknown_proposals", "GET", "/proposals?cluster_id=ghost", ("404",)),
    ("unknown_rebalance", "POST",
     "/rebalance?cluster_id=ghost&dryrun=true&reason=fuzz", ("404",)),
    ("unknown_user_tasks", "GET", "/user_tasks?cluster_id=ghost", ("404",)),
    ("unknown_metrics", "GET", "/metrics?cluster_id=ghost", ("404",)),
    ("unknown_health", "GET", "/health?cluster_id=ghost", ("404",)),
)


class ClusterFuzzer:
    """Seeded fuzzer for the fleet's cluster-scoped REST routes, run against
    a live :class:`CruiseControlServer` mounted with a FleetScheduler.

    Op kinds: valid-tenant reads (state/proposals/user_tasks/metrics),
    valid-tenant dry-run rebalances, the malformed/unknown cluster_id
    catalog, and cross-tenant user-task resumption — sequential AND a
    two-thread race — whose invariant is a declared 404 on the WRONG tenant
    plus zero duplicate executions on the right one. The schedule is a pure
    function of (seed, ops); statuses/verdicts land in ``log`` and invariant
    violations in ``failures``.
    """

    def __init__(self, server, cluster_ids, seed: int = 0, ops: int = 32):
        self.server = server
        self.cluster_ids = list(cluster_ids)
        self.seed = seed
        self.ops = ops
        self.log: list[dict] = []
        self.failures: list[str] = []
        self.requests = 0

    def _request(self, method: str, path_query: str,
                 task_id: str | None = None):
        conn = http.client.HTTPConnection("127.0.0.1", self.server.port,
                                          timeout=600)
        try:
            headers = {"Content-Length": "0"} if method == "POST" else {}
            if task_id is not None:
                headers["User-Task-ID"] = task_id
            conn.request(method, "/kafkacruisecontrol" + path_query,
                         headers=headers)
            resp = conn.getresponse()
            raw = resp.read()
            self.requests += 1
            body = None
            if "json" in (resp.getheader("Content-Type") or ""):
                try:
                    body = json.loads(raw.decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    body = None
            return resp.status, body, resp.getheader("User-Task-ID")
        finally:
            conn.close()

    def _expect(self, entry, status, expected, body=None) -> None:
        bucket = _classify(status, body)
        entry["status"] = bucket
        if bucket not in expected:
            self.failures.append(
                f"cluster op {entry['op']} ({entry['kind']}): undeclared "
                f"status {status} (declared: {expected})")

    def run(self) -> dict:
        rng = random.Random(f"cluster-fuzz/{self.seed}")
        kinds = ("state", "proposals", "user_tasks", "metrics",
                 "rebalance_dryrun", "malformed", "cross_resume",
                 "cross_resume_race")
        last_task: tuple[str, str, str] | None = None   # (cid, tid, query)
        for i in range(self.ops):
            kind = kinds[rng.randrange(len(kinds))]
            cid = self.cluster_ids[rng.randrange(len(self.cluster_ids))]
            entry = {"op": i, "kind": kind, "cluster": cid}
            degraded_ok = ("2xx", "503")
            optimize_ok = ("2xx", "503", "optfail")
            if kind == "state":
                st, _, _ = self._request(
                    "GET", f"/state?cluster_id={cid}&substates=ANALYZER")
                self._expect(entry, st, ("2xx",))
            elif kind == "proposals":
                st, body, _ = self._request(
                    "GET", f"/proposals?cluster_id={cid}")
                self._expect(entry, st, degraded_ok, body)
            elif kind == "user_tasks":
                st, _, _ = self._request(
                    "GET", f"/user_tasks?cluster_id={cid}")
                self._expect(entry, st, ("2xx",))
            elif kind == "metrics":
                st, _, _ = self._request(
                    "GET", f"/metrics?cluster_id={cid}")
                self._expect(entry, st, ("2xx",))
            elif kind == "rebalance_dryrun":
                q = f"/rebalance?cluster_id={cid}&dryrun=true&reason=cf{i}"
                st, body, tid = self._request("POST", q)
                self._expect(entry, st, optimize_ok, body)
                if st == 200 and tid:
                    last_task = (cid, tid, q)
            elif kind == "malformed":
                label, method, pathq, expected = _MALFORMED_CLUSTER[
                    rng.randrange(len(_MALFORMED_CLUSTER))]
                entry["malformed"] = label
                st, _, _ = self._request(method, pathq)
                self._expect(entry, st, expected)
            elif kind in ("cross_resume", "cross_resume_race"):
                if last_task is None:
                    entry["status"] = "skipped"
                    self.log.append(entry)
                    continue
                own_cid, tid, q = last_task
                others = [c for c in self.cluster_ids if c != own_cid]
                if not others:
                    entry["status"] = "skipped"
                    self.log.append(entry)
                    continue
                wrong = others[rng.randrange(len(others))]
                wq = q.replace(f"cluster_id={own_cid}",
                               f"cluster_id={wrong}")
                app = self.server.fleet.app_for(own_cid)
                before = app.executor.state_json()["numExecutions"]
                if kind == "cross_resume":
                    st, body, rtid = self._request("POST", wq, task_id=tid)
                    self._expect(entry, st, ("404",), body)
                    if rtid == tid:
                        self.failures.append(
                            f"cluster op {i}: tenant {wrong} resolved tenant "
                            f"{own_cid}'s task id (data leak)")
                else:
                    results = [None, None]

                    def poll(slot):
                        results[slot] = self._request("POST", wq,
                                                      task_id=tid)

                    threads = [threading.Thread(target=poll, args=(s,))
                               for s in range(2)]
                    for t in threads:
                        t.start()
                    for t in threads:
                        t.join(600)
                    statuses = sorted(_bucket(r[0])
                                      for r in results if r)
                    entry["status"] = "/".join(statuses) or "client-error"
                    if statuses != ["404", "404"]:
                        self.failures.append(
                            f"cluster op {i} (cross_resume_race): racing "
                            f"wrong-tenant resumptions returned {statuses} "
                            f"(declared: 404/404)")
                after = app.executor.state_json()["numExecutions"]
                entry["dup_execution"] = after != before
                if after != before:
                    self.failures.append(
                        f"cluster op {i} ({kind}): wrong-tenant resumption "
                        f"executed ({before} -> {after})")
            self.log.append(entry)
        return {"seed": self.seed, "requests": self.requests,
                "log": [dict(e) for e in self.log],
                "failures": list(self.failures)}


# --------------------------------------------------------------- episodes
@dataclasses.dataclass
class FuzzEpisodeResult:
    """One scenario run with the fuzzer attached. ``to_json()`` is the
    bit-identical episode log: the scenario result + timeline, the fuzz log
    and every invariant verdict."""
    scenario_result: object
    fuzz_seed: int
    fuzz_log: list
    fuzz_failures: list
    requests: int
    fault_counts: dict
    # lifetime circuit trips per operation class (test surface for the
    # "transient episode heals with retries, breaker never trips" contract)
    breaker_open_counts: dict = dataclasses.field(default_factory=dict)

    @property
    def failures(self) -> list:
        return list(self.scenario_result.failures) + list(self.fuzz_failures)

    @property
    def ok(self) -> bool:
        return not self.failures

    def assert_ok(self) -> None:
        if self.failures:
            raise AssertionError(
                "fuzz episode failed:\n  " + "\n  ".join(self.failures))

    def to_json(self) -> dict:
        # NOTE: backend fault COUNTS are deliberately absent — wall-clock
        # cached sensor gauges (metadata-factor) may probe the faulted
        # backend a run-dependent number of times; the *schedule* is
        # stateless per (method, time bucket), so every recorded outcome
        # stays bit-identical, but raw hit counts would not
        out = self.scenario_result.to_json()
        out["timeline"] = list(self.scenario_result.timeline)
        out["fuzz_seed"] = self.fuzz_seed
        out["fuzz_log"] = [dict(e) for e in self.fuzz_log]
        out["fuzz_failures"] = list(self.fuzz_failures)
        out["fuzz_requests"] = self.requests
        return out


# default mid-episode fault window: opens after the first detections are in
# flight, closes well before the scenario deadline so heals can land
DEFAULT_FAULT_WINDOWS = ((45_000.0, 165_000.0),)


def run_fuzz_episode(scenario, seed: int = 0, fuzz_seed: int = 0,
                     fuzz_spec: FuzzSpec | None = None,
                     fault_windows=DEFAULT_FAULT_WINDOWS,
                     error_rate: float = 0.25, latency_rate: float = 0.1,
                     partial_rate: float = 0.1,
                     name: str | None = None) -> FuzzEpisodeResult:
    """Run one scenario with the REST fuzzer + FaultyBackend attached.
    Pure function of (scenario, seed, fuzz_seed, spec, windows, rates):
    same inputs => bit-identical ``to_json()`` document."""
    from cruise_control_tpu.sim.runner import ScenarioRunner

    faulty: dict = {}

    def wrap(backend):
        fb = FaultyBackend(backend, seed=fuzz_seed, windows=fault_windows,
                           error_rate=error_rate, latency_rate=latency_rate,
                           partial_rate=partial_rate)
        faulty["backend"] = fb
        return fb

    fuzzer = ApiFuzzer(fuzz_spec, fuzz_seed=fuzz_seed,
                       name=name or scenario.name)
    runner = ScenarioRunner(scenario, seed=seed, backend_wrap=wrap,
                            tick_hook=fuzzer.tick)
    try:
        res = runner.run()
    finally:
        fuzzer.finalize()
    fb = faulty.get("backend")
    breakers = runner.cc.fault_tolerance.state_json()["breakers"]
    return FuzzEpisodeResult(
        scenario_result=res, fuzz_seed=fuzz_seed,
        fuzz_log=fuzzer.log_json(), fuzz_failures=list(fuzzer.failures),
        requests=fuzzer.requests,
        fault_counts=dict(fb.fault_counts) if fb is not None else {},
        breaker_open_counts={name: br["openCount"]
                             for name, br in breakers.items()})


def run_fuzz_campaign(spec, seed: int = 0, fuzz_seed: int = 0,
                      fuzz_spec: FuzzSpec | None = None) -> dict:
    """Every episode of a campaign with the fuzzer + FaultyBackend attached
    (`bench.py --campaign <name> --fuzz`). Returns the aggregate document;
    same (campaign, seed, fuzz_seed) => bit-identical output."""
    from cruise_control_tpu.sim.campaign import (
        CAMPAIGNS, aggregate_slos, generate_episode,
    )
    if isinstance(spec, str):
        spec = CAMPAIGNS[spec]
    episodes = []
    for i in range(spec.episodes):
        sc = generate_episode(spec, seed, i)
        episodes.append(run_fuzz_episode(
            sc, seed=0, fuzz_seed=fuzz_seed + i, fuzz_spec=fuzz_spec,
            name=f"{spec.name}/{seed}"))
    return {
        "campaign": spec.name,
        "seed": seed,
        "fuzz_seed": fuzz_seed,
        "num_episodes": len(episodes),
        "converged_episodes": sum(
            1 for e in episodes if e.scenario_result.converged),
        "fuzz_requests": sum(e.requests for e in episodes),
        "slo": aggregate_slos([e.scenario_result for e in episodes]),
        "episodes": [e.to_json() for e in episodes],
        "failures": [f for e in episodes for f in e.failures],
    }
