"""Execution tasks + state machine.

Reference: executor/ExecutionTask.java with ExecutionTaskState.java
(PENDING -> IN_PROGRESS -> {COMPLETED, ABORTING -> ABORTED, DEAD}) and
ExecutionTaskManager.java (487: per-broker in-flight accounting).
"""
from __future__ import annotations

import dataclasses
import enum
import itertools

from cruise_control_tpu.analyzer.proposals import ExecutionProposal


class TaskType(enum.Enum):
    INTER_BROKER_REPLICA_ACTION = "INTER_BROKER_REPLICA_ACTION"
    INTRA_BROKER_REPLICA_ACTION = "INTRA_BROKER_REPLICA_ACTION"
    LEADER_ACTION = "LEADER_ACTION"


class TaskState(enum.Enum):
    PENDING = "PENDING"
    IN_PROGRESS = "IN_PROGRESS"
    ABORTING = "ABORTING"
    ABORTED = "ABORTED"
    DEAD = "DEAD"
    COMPLETED = "COMPLETED"


_ids = itertools.count()


@dataclasses.dataclass
class ExecutionTask:
    proposal: ExecutionProposal
    task_type: TaskType
    task_id: int = dataclasses.field(default_factory=lambda: next(_ids))
    state: TaskState = TaskState.PENDING
    start_ms: float = -1.0
    end_ms: float = -1.0

    # optional census observer ``(task, new_state, now_ms)`` — the executor
    # sets it per execution so every transition lands in the durable event
    # journal (class attribute, not a dataclass field: to_json/asdict and
    # the task's equality semantics stay untouched)
    on_transition = None

    @property
    def tp(self) -> tuple:
        return (self.proposal.topic, self.proposal.partition)

    @property
    def brokers_involved(self) -> set:
        """Brokers whose in-flight budget this task consumes (source + dest)."""
        if self.task_type is TaskType.LEADER_ACTION:
            return {self.proposal.new_leader}
        return set(self.proposal.replicas_to_add) | set(self.proposal.replicas_to_remove)

    def transition(self, new_state: TaskState, now_ms: float = 0.0) -> None:
        legal = {
            TaskState.PENDING: {TaskState.IN_PROGRESS, TaskState.DEAD},
            TaskState.IN_PROGRESS: {TaskState.COMPLETED, TaskState.ABORTING,
                                    TaskState.DEAD},
            TaskState.ABORTING: {TaskState.ABORTED, TaskState.DEAD},
        }
        if new_state not in legal.get(self.state, set()):
            raise ValueError(f"illegal transition {self.state} -> {new_state}")
        self.state = new_state
        if new_state is TaskState.IN_PROGRESS:
            self.start_ms = now_ms
        if new_state in (TaskState.COMPLETED, TaskState.ABORTED, TaskState.DEAD):
            self.end_ms = now_ms
        if self.on_transition is not None:
            self.on_transition(self, new_state, now_ms)

    def to_json(self) -> dict:
        return {"taskId": self.task_id, "type": self.task_type.value,
                "state": self.state.value, "proposal": self.proposal.to_json()}
