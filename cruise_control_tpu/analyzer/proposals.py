"""Proposal diffing: initial vs optimized assignment -> ExecutionProposals.

Reference: analyzer/AnalyzerUtils.getDiff (initial replica/leader distribution
vs the optimized ClusterModel -> Set<ExecutionProposal>) and
executor/ExecutionProposal.java (tp, old/new leader, old/new replica
(broker, logdir) lists).

The diff itself is pure numpy over the dense assignment arrays; the per-
partition ``ExecutionProposal`` objects are materialized LAZILY by
``ProposalSet`` — at 7k-broker scale an optimization can change >100k
partitions, and building 100k Python dataclasses eagerly costs seconds of
host time inside the proposal-computation window (the aggregate counts the
optimizer needs are computed vectorized instead).
"""
from __future__ import annotations

import dataclasses
from collections.abc import Sequence

import jax
import numpy as np

from cruise_control_tpu.analyzer.env import ClusterEnv
from cruise_control_tpu.analyzer.state import EngineState
from cruise_control_tpu.model.cluster_tensor import ClusterMeta


@dataclasses.dataclass(frozen=True)
class ExecutionProposal:
    topic: str
    partition: int
    old_leader: int                 # external broker id
    new_leader: int
    old_replicas: tuple             # tuple[(broker_id, logdir_index), ...]
    new_replicas: tuple

    @property
    def tp(self) -> str:
        return f"{self.topic}-{self.partition}"

    @property
    def replicas_to_add(self) -> tuple:
        old = {b for b, _ in self.old_replicas}
        return tuple(b for b, _ in self.new_replicas if b not in old)

    @property
    def replicas_to_remove(self) -> tuple:
        new = {b for b, _ in self.new_replicas}
        return tuple(b for b, _ in self.old_replicas if b not in new)

    @property
    def has_replica_action(self) -> bool:
        return bool(self.replicas_to_add or self.replicas_to_remove)

    @property
    def has_leader_action(self) -> bool:
        return self.old_leader != self.new_leader

    def data_to_move_mb(self, replica_disk_mb: float) -> float:
        return replica_disk_mb * len(self.replicas_to_add)

    def to_json(self) -> dict:
        return {
            "topicPartition": {"topic": self.topic, "partition": self.partition},
            "oldLeader": self.old_leader,
            "newLeader": self.new_leader,
            "oldReplicas": [b for b, _ in self.old_replicas],
            "newReplicas": [b for b, _ in self.new_replicas],
        }


class ProposalSet(Sequence):
    """Lazy sequence of ExecutionProposals over vectorized diff arrays.

    Aggregates the optimizer needs (replica-addition count, leadership-change
    count) are precomputed with numpy — iterating materializes objects one at
    a time, so callers that only need ``len`` or the counts never pay for
    object construction. Indexing/iteration yields real ``ExecutionProposal``
    instances, keeping the executor/tests/JSON paths unchanged.
    """

    def __init__(self, meta: ClusterMeta, part_idx: np.ndarray,
                 members: np.ndarray, valid_m: np.ndarray,
                 old_broker_ext: np.ndarray, new_broker_ext: np.ndarray,
                 old_disk: np.ndarray, new_disk: np.ndarray,
                 old_leader_ext: np.ndarray, new_leader_ext: np.ndarray,
                 num_additions: int):
        self._meta = meta
        self._part_idx = part_idx            # i64[Pc] internal partition index
        self._members = members              # i32[Pc, F] replica ids (-1 pad)
        self._valid = valid_m                # bool[Pc, F]
        self._old_b = old_broker_ext         # i64[Pc, F] external broker ids
        self._new_b = new_broker_ext
        self._old_d = old_disk               # i32[Pc, F]
        self._new_d = new_disk
        self._old_leader = old_leader_ext    # i64[Pc]
        self._new_leader = new_leader_ext
        self.num_replica_additions = int(num_additions)
        self.num_leadership_changes = int((old_leader_ext != new_leader_ext).sum())

    def __len__(self) -> int:
        return len(self._part_idx)

    def _make(self, i: int) -> ExecutionProposal:
        v = self._valid[i]
        topic, partition = self._meta.partition_ids[int(self._part_idx[i])]
        old_replicas = tuple(zip(self._old_b[i][v].tolist(),
                                 self._old_d[i][v].tolist()))
        new_replicas = tuple(zip(self._new_b[i][v].tolist(),
                                 self._new_d[i][v].tolist()))
        return ExecutionProposal(
            topic=topic, partition=int(partition),
            old_leader=int(self._old_leader[i]),
            new_leader=int(self._new_leader[i]),
            old_replicas=old_replicas, new_replicas=new_replicas)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self._make(j) for j in range(*i.indices(len(self)))]
        if i < 0:
            i += len(self)
        if not 0 <= i < len(self):
            raise IndexError(i)
        return self._make(i)


def diff_proposals(env: ClusterEnv, meta: ClusterMeta,
                   initial_broker: np.ndarray, initial_leader: np.ndarray,
                   initial_disk: np.ndarray, st: EngineState,
                   final: tuple | None = None,
                   host_statics: tuple | None = None) -> ProposalSet:
    """Compare assignments and emit one proposal per changed partition.

    ``final`` lets the caller pass already-fetched (broker, leader, disk) host
    arrays to avoid extra device round-trips, and ``host_statics``
    ``(members_table, replica_valid, replica_partition)`` does the same for
    the static membership arrays (they originate on the host — fetching them
    back is ~13 MB per optimization over a tunneled TPU). Entirely
    vectorized: no Python loop over partitions (AnalyzerUtils.getDiff role at
    1M-replica scale).
    """
    if final is not None:
        final_broker, final_leader, final_disk = (np.asarray(a) for a in final)
    else:
        final_broker, final_leader, final_disk = jax.device_get(
            (st.replica_broker, st.replica_is_leader, st.replica_disk))
    initial_broker = np.asarray(initial_broker)
    initial_leader = np.asarray(initial_leader)
    initial_disk = np.asarray(initial_disk)
    if host_statics is not None:
        members_table, valid, part_of = (np.asarray(a) for a in host_statics)
    else:
        members_table, valid, part_of = (np.asarray(a) for a in jax.device_get(
            (env.partition_replicas, env.replica_valid,
             env.replica_partition)))
    broker_ids = np.asarray(meta.broker_ids)

    changed_r = (final_broker != initial_broker) | (final_leader != initial_leader) \
        | (final_disk != initial_disk)
    changed_parts = np.unique(part_of[changed_r & valid])

    members = members_table[changed_parts]              # [Pc, F], -1 padded
    valid_m = members >= 0
    m = np.where(valid_m, members, 0)
    ib, fb = initial_broker[m], final_broker[m]         # internal ids [Pc, F]
    old_b_ext = np.where(valid_m, broker_ids[ib], -1)
    new_b_ext = np.where(valid_m, broker_ids[fb], -1)
    old_d = np.where(valid_m, initial_disk[m], 0).astype(np.int32)
    new_d = np.where(valid_m, final_disk[m], 0).astype(np.int32)

    # leadership: the member flagged leader, -1 if none (matches the old
    # behavior of taking the first flagged member)
    def leader_ext(leader_flags, brokers_ext):
        flags = np.where(valid_m, leader_flags[m], False)
        has = flags.any(axis=1)
        first = np.argmax(flags, axis=1)
        return np.where(has, brokers_ext[np.arange(len(first)), first], -1)

    old_leader = leader_ext(initial_leader, old_b_ext)
    new_leader = leader_ext(final_leader, new_b_ext)

    # replica additions: members whose new broker hosts no OLD copy of the
    # partition (replicas_to_add semantics), vectorized [Pc, F, F]
    in_old = (new_b_ext[:, :, None] == old_b_ext[:, None, :]).any(axis=2)
    num_additions = int((valid_m & ~in_old).sum())

    return ProposalSet(meta, changed_parts, members, valid_m,
                       old_b_ext, new_b_ext, old_d, new_d,
                       old_leader, new_leader, num_additions)
