"""bench.py summary emission contract: the LAST stdout line is one compact,
machine-parseable JSON document.

BENCH_r05 recorded ``"parsed": null`` because the single emitted line —
megabytes of embedded last_round_trace/sensors blobs — was truncated
mid-line by the driver's tail capture. The fix under test: ``Summary.emit``
prints the full document as a pretty block first, then ONE compact line
(bulky per-rung blobs stripped) that is always last and always small.
"""
from __future__ import annotations

import importlib.util
import json
import os
import sys


def _load_bench():
    """Import bench.py by path (it is a script at the repo root, not a
    package module); reuse an already-imported instance so repeated tests
    don't re-register signal handlers."""
    if "cc_bench" in sys.modules:
        return sys.modules["cc_bench"]
    path = os.path.join(os.path.dirname(__file__), os.pardir, "bench.py")
    spec = importlib.util.spec_from_file_location("cc_bench", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["cc_bench"] = mod
    spec.loader.exec_module(mod)
    return mod


def test_final_line_is_compact_parseable_json(tmp_path, monkeypatch, capsys):
    bench = _load_bench()
    monkeypatch.chdir(tmp_path)          # emit writes BENCH_partial.json
    s = bench.Summary()
    s.headline_requested = True
    # a rung fat enough to reproduce the truncation hazard: the embedded
    # trace/sensor blobs are what blew the old single line past the tail cap
    fat_rung = {
        "config": "7000b-1M", "wall_s": 123.4, "wall_s_cold": 456.7,
        "warm_measured": True, "violations_before": 10,
        "violations_after": 3, "violated_goals_after": ["A", "B", "C"],
        "num_replica_movements": 321888,
        "last_round_trace": {"goals": [{"name": f"G{i}", "passes": i,
                                        "fin_segments": 8,
                                        "fin_boundary": i * 3}
                                       for i in range(16)],
                             "blob": "x" * 200_000},
        "sensors": {f"sensor-{i}": {"type": "gauge", "value": i}
                    for i in range(400)},
        "pass_profile": {f"G{i}": {"passes": i, "segments": 8,
                                   "boundary": i} for i in range(16)},
    }
    s.rungs.append(fat_rung)
    s.headline = fat_rung
    s.emit(final=True)
    out = capsys.readouterr().out
    lines = out.rstrip("\n").splitlines()
    # the pretty block is above; the LAST line alone must parse
    last = lines[-1]
    doc = json.loads(last)
    # compact: small enough that no tail capture truncates it mid-line
    assert len(last) < 16_384, len(last)
    assert doc["complete"] is True
    assert doc["value"] == 123.4
    assert doc["unit"] == "s"
    assert doc["rungs"][0]["config"] == "7000b-1M"
    assert doc["rungs"][0]["violations_after"] == 3
    for bulky in bench.BULKY_RUNG_KEYS:
        assert bulky not in doc["rungs"][0], bulky
    # the pretty block above the line still carries the FULL document
    pretty = "\n".join(lines[:-1])
    full = json.loads(pretty)
    assert "last_round_trace" in full["rungs"][0]
    # BENCH_partial.json keeps the full single-line document (trace_view's
    # whole-file parse input)
    with open(tmp_path / "BENCH_partial.json") as f:
        partial = json.loads(f.read())
    assert "last_round_trace" in partial["rungs"][0]


def test_final_line_without_headline(tmp_path, monkeypatch, capsys):
    """A scenario-only / headline-less run still ends in one parseable
    compact line with honest metric attribution (the r05 convention)."""
    bench = _load_bench()
    monkeypatch.chdir(tmp_path)
    s = bench.Summary()
    s.headline_requested = False
    s.rungs.append({"config": "100b-10k", "wall_s": 0.7,
                    "last_round_trace": {"goals": []}})
    s.emit(final=True)
    last = capsys.readouterr().out.rstrip("\n").splitlines()[-1]
    doc = json.loads(last)
    assert doc["complete"] is True
    assert doc["value"] == 0.7
    assert "100b-10k" in doc["metric"]
