"""ExecutorNotifier SPI.

Reference: executor/ExecutorNotifier.java (ExecutorConfig
``executor.notifier.class``): notified once per finished proposal execution
with the outcome, so deployments can page/post on completion independently of
the anomaly notifier.
"""
from __future__ import annotations

import dataclasses
import logging

LOG = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class ExecutorNotification:
    """Outcome of one proposal execution (ExecutorNotification.java field
    role: what ran, who asked for it, how it ended)."""
    operation: str          # e.g. "rebalance", "self-healing:BROKER_FAILURE"
    success: bool
    stopped_by_user: bool
    num_replica_movements: int
    num_leadership_movements: int
    detail: str = ""

    def summary(self) -> str:
        state = ("stopped" if self.stopped_by_user
                 else "succeeded" if self.success else "FAILED")
        return (f"execution {state}: {self.operation} "
                f"({self.num_replica_movements} moves, "
                f"{self.num_leadership_movements} leadership)"
                + (f" — {self.detail}" if self.detail else ""))


class ExecutorNotifier:
    """SPI: receives an ExecutorNotification when an execution finishes."""

    def configure(self, config) -> None:
        pass

    def on_execution_finished(self, notification: ExecutorNotification) -> None:
        raise NotImplementedError


class LoggingExecutorNotifier(ExecutorNotifier):
    """Default: log the outcome (ExecutorNotifier's reference default logs
    via OPERATION_LOGGER)."""

    def __init__(self):
        self.notifications: list[ExecutorNotification] = []  # inspectable

    def on_execution_finished(self, notification: ExecutorNotification) -> None:
        self.notifications.append(notification)
        (LOG.info if notification.success else LOG.warning)(
            "%s", notification.summary())
