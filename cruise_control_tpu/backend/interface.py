"""ClusterBackend: the pluggable boundary to the managed cluster.

The reference talks to a real Kafka deployment through three transports
(SURVEY §2.10): the Kafka wire protocol (metrics consumer, sample-store
producer, AdminClient), ZooKeeper (reassignment znodes Executor.java:1272,
broker liveness watches BrokerFailureDetector.java:84, throttle configs
ReplicationThrottleHelper.java:36-42) and HTTP. This interface abstracts all
actuation + metadata behind one SPI so the framework runs identically against
the simulated backend (tests/dev — the embedded-Kafka role of
CCKafkaIntegrationTestHarness) or a thin adapter to a real cluster.
"""
from __future__ import annotations

import dataclasses
from typing import Protocol


@dataclasses.dataclass
class BrokerNode:
    broker_id: int
    rack: str
    alive: bool = True
    logdirs: dict = dataclasses.field(default_factory=dict)   # logdir -> capacity MB
    dead_logdirs: set = dataclasses.field(default_factory=set)
    cpu_capacity: float = 100.0
    nw_in_capacity: float = 50_000.0
    nw_out_capacity: float = 50_000.0


@dataclasses.dataclass
class PartitionInfo:
    topic: str
    partition: int
    replicas: list                      # broker ids, preferred leader first
    leader: int                         # broker id, -1 = none
    logdir_by_broker: dict = dataclasses.field(default_factory=dict)
    size_mb: float = 0.0
    bytes_in_rate: float = 0.0          # KB/s produced to the leader
    bytes_out_rate: float = 0.0         # KB/s consumed from the leader
    cpu_util: float = 0.0               # leader CPU percent
    isr: list | None = None             # in-sync replica ids; None = derive
    #                                     from replicas on alive brokers


class ClusterBackend(Protocol):
    """Everything the monitor/executor/detector layers need from the cluster."""

    # -- metadata (MetadataClient role) --
    def brokers(self) -> dict: ...                       # id -> BrokerNode
    def partitions(self) -> dict: ...                    # (topic, part) -> PartitionInfo
    def metadata_generation(self) -> int: ...

    # -- metrics (metrics-reporter topic / Prometheus role) --
    def partition_metrics(self) -> dict: ...             # (t, p) -> {metric: value}
    def broker_metrics(self) -> dict: ...                # id -> {metric: value}

    # -- actuation (ZK znodes + AdminClient role) --
    def alter_partition_reassignments(self, assignments: dict) -> None: ...
    def ongoing_reassignments(self) -> dict: ...
    def cancel_reassignments(self, tps: list) -> None: ...
    def elect_leaders(self, tps_to_leader: dict) -> None: ...
    def alter_replica_logdirs(self, moves: dict) -> None: ...
    def describe_logdirs(self) -> dict: ...              # broker -> {logdir: alive}
    def set_replication_throttle(self, rate_bytes_per_sec: int | None) -> None: ...
    def replication_throttle(self) -> int | None: ...
    # per-topic config writes (alterConfigs role): the throttle helper sets
    # leader/follower.replication.throttled.replicas lists per topic and
    # deletes them (value None) after execution
    def set_topic_config(self, topic: str, key: str, value) -> None: ...
    def topic_configs(self) -> dict: ...
