"""Second half of the DeterministicClusterTest replay matrix — see
tests/test_java_parity_matrix.py (split across two files so pytest-xdist's
loadfile scheduler spreads the XLA:CPU compile load over both workers)."""
import pytest

from tests.test_java_parity_matrix import MATRIX_B, _run_matrix_row


@pytest.mark.parametrize(
    "row_id,fixture_factory,chain,constraint,pattern,expected",
    MATRIX_B, ids=[m[0] for m in MATRIX_B])
def test_java_matrix_b(row_id, fixture_factory, chain, constraint, pattern,
                       expected):
    _run_matrix_row(fixture_factory, chain, constraint, pattern, expected)
