"""Flight recorder: always-on per-round traces + runtime compile sensors.

The reference's operability rests on its Dropwizard sensor catalog
(proposal-computation-timer, cluster-model-creation-timer, per-endpoint
request timers — docs/wiki Sensors.md); what it cannot answer is "what did
THIS proposal round spend its time on?". Until now neither could we: per-stage
timing, XLA compile events and device memory were only visible through
``bench.py``'s private bookkeeping or the blocking ``CC_PROFILE_SEGMENTS``
debug hack. This module is the library-level answer:

- :class:`RoundTrace` — one record per optimization round, assembled from data
  the engine already computes (per-goal ``GoalResult`` counters, the pass
  profile, session sync mode/seconds/donation, the last sampling round's
  seconds, XLA compile count delta, env/state device bytes). Assembly costs a
  few dict builds and ``nbytes`` reads on device-array *metadata* — no
  synchronization, no device copies, so the async dispatch pipeline and the
  donation protocol are untouched.
- :class:`FlightRecorder` — a bounded thread-safe ring buffer of traces,
  served by ``/state?substates=ROUND_TRACES`` and snapshotted by ``bench.py``
  and the sim ``ScenarioRunner`` (one schema everywhere).
- :class:`XlaCompileListener` — promotes bench-only compile counting to a
  library-level sensor: a process-wide ``jax.monitoring`` duration listener
  counting backend compiles (a persistent-cache hit deserializes and does NOT
  count — exactly the "new executable built" semantics the zero-new-compile
  contracts assert).
- :class:`CompileCounter` / :func:`count_compiles` — the log-record-based
  counter bench.py used to carry privately; kept because its semantics
  ("Compiling ..." records, which include cache-served compiles) are what the
  BENCH_* trajectory files were measured with.

Causal span journal (PR 12): the per-component sensors above answer "how is
the system doing"; they cannot answer "what happened to THIS anomaly". The
three classes below close that gap in the Dapper style:

- :class:`Span` / :class:`SpanTracer` — lightweight spans with explicit
  lineage (trace_id / span_id / parent_id), stamped from the INJECTED clock
  (simulated time in the sim, wall time in the service). Parents are passed
  as explicit handles down the call chain (detector verdict -> facade
  operation -> optimizer round -> executor phases), never through
  thread-local/context magic — the sim stays deterministic and span ids are
  reproducible per (scenario, seed).
- :class:`EventJournal` — an append-only size-rotated JSONL event log the
  recorder, span tracer, executor task census, breaker state machine and
  pipeline stage notes all write through. Records are serialized with
  sorted keys and carry ONLY deterministic fields (backend-clock timestamps,
  counts, ids — never wall seconds or compile counts), so the same
  (scenario, seed) produces a byte-identical journal in sim mode. A bounded
  in-memory ring of lines backs journal-less (in-memory) deployments and
  the sim's per-episode journal slices; a configured ``journal.path`` makes
  it the durable tail target an HA standby can consume.
- :func:`build_trace_trees` — reconstructs nested trace trees from span
  records (the tracer's ring or a journal file), shared by
  ``/state?substates=TRACES``, ``tools/journal_view.py`` and the
  tree-completeness tests.
"""
from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager

DEFAULT_CAPACITY = 64

# jax.monitoring event emitted once per XLA backend compile (not emitted when
# the persistent compilation cache serves the executable)
_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


# ---------------------------------------------------------------------------
# compile sensors
# ---------------------------------------------------------------------------
class XlaCompileListener:
    """Process-wide XLA compile counter (jax.monitoring based).

    ``install()`` registers the jax.monitoring listener once per process and
    returns the singleton; every GoalOptimizer construction calls it, so any
    process that optimizes — the service, the sim runner, bench — carries the
    sensor. Reads are cheap ints; the flight recorder uses count deltas to
    attribute compiles to rounds, and the registry exposes the running totals
    as ``xla-compile-count`` / ``xla-compile-seconds`` gauges.
    """

    _instance: "XlaCompileListener | None" = None
    _install_lock = threading.Lock()

    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._seconds = 0.0

    @classmethod
    def install(cls) -> "XlaCompileListener":
        with cls._install_lock:
            if cls._instance is None:
                inst = cls()
                import jax.monitoring

                def on_duration(name: str, secs: float, **kw) -> None:
                    if name == _BACKEND_COMPILE_EVENT:
                        with inst._lock:
                            inst._count += 1
                            inst._seconds += float(secs)

                jax.monitoring.register_event_duration_secs_listener(
                    on_duration)
                cls._instance = inst
            return cls._instance

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def seconds(self) -> float:
        with self._lock:
            return self._seconds

    def register_gauges(self, sensors) -> None:
        sensors.gauge("xla-compile-count", lambda: self.count)
        sensors.gauge("xla-compile-seconds", lambda: round(self.seconds, 3))


class CompileCounter:
    """Counts XLA compiles during a phase via jax_log_compiles records
    (the counter bench.py carried privately; semantics preserved: counts
    "Compiling ..." log records, which fire even when the persistent cache
    serves the executable)."""

    def __init__(self):
        import logging

        class _H(logging.Handler):
            def __init__(self, outer):
                super().__init__(level=logging.DEBUG)
                self._outer = outer

            def emit(self, record):
                try:
                    if "Compiling" in record.getMessage():
                        self._outer.count += 1
                except Exception:  # noqa: BLE001 — counting must never break a run
                    pass

        self.count = 0
        self._handler = _H(self)

    @property
    def handler(self):
        return self._handler


@contextmanager
def count_compiles():
    """``with count_compiles() as c: ...; c.count`` — the bench.py phase
    counter, now shared library code."""
    import logging

    import jax
    prev = bool(jax.config.jax_log_compiles)
    counter = CompileCounter()
    jax.config.update("jax_log_compiles", True)
    jax_logger = logging.getLogger("jax")
    jax_logger.addHandler(counter.handler)
    try:
        yield counter
    finally:
        jax_logger.removeHandler(counter.handler)
        jax.config.update("jax_log_compiles", prev)


def tree_device_bytes(tree) -> int:
    """Exact leaf-sum bytes of a device pytree — array METADATA only (no
    transfer, no block): safe on in-flight/donated-lineage buffers."""
    import jax
    return int(sum(x.nbytes for x in jax.tree_util.tree_leaves(tree)
                   if hasattr(x, "nbytes")))


# ---------------------------------------------------------------------------
# durable event journal
# ---------------------------------------------------------------------------
class EventJournal:
    """Append-only size-rotated JSONL event log (``journal.*`` config keys).

    One record per line, serialized with sorted keys and compact separators
    so identical event streams are identical BYTES — the sim's
    (scenario, seed) ⇒ byte-identical-journal contract rests on this plus
    the writers' discipline of journaling only deterministic fields.

    ``path`` empty/None keeps the journal purely in-memory (a bounded ring
    of the most recent ``memory_lines`` lines is always kept either way —
    it is what ``ScenarioResult.journal`` and the tests consume). With a
    path, files rotate at ``max_bytes`` per file into ``path.1``..``path.N``
    (newest suffix = most recently rotated), keeping at most ``max_files``
    rotated files. ``fsync``: "never" (default), "rotate" (fsync when a
    file fills), or "always" (fsync every append — the durable-tail setting
    an HA standby would use).
    """

    def __init__(self, path: str | None = None, max_bytes: int = 16_777_216,
                 max_files: int = 8, fsync: str = "never", clock_ms=None,
                 memory_lines: int = 65_536):
        self.path = path or None
        self.max_bytes = max(int(max_bytes), 4096)
        self.max_files = max(int(max_files), 1)
        self.fsync = fsync if fsync in ("never", "rotate", "always") else "never"
        self.clock_ms = clock_ms or (lambda: time.time() * 1000.0)
        self._lock = threading.Lock()
        self._mem: deque[str] = deque(maxlen=max(int(memory_lines), 16))
        self.events_appended = 0
        self.bytes_appended = 0
        self.dropped_from_memory = 0
        self.rotations = 0
        self._f = None
        self._file_bytes = 0
        if self.path:
            self._f = open(self.path, "a", encoding="utf-8")
            self._file_bytes = self._f.tell()

    # --------------------------------------------------------------- write
    def append(self, kind: str, **fields) -> None:
        """Journal one event. ``ts`` is stamped from the injected clock;
        callers must pass only deterministic fields (no wall seconds, no
        process-dependent ids). Never raises into the caller's path."""
        record = {"kind": kind, "ts": round(float(self.clock_ms()), 3)}
        record.update(fields)
        try:
            line = json.dumps(record, sort_keys=True,
                              separators=(",", ":"), default=str)
        except Exception:  # noqa: BLE001 — journaling must never fail a round
            import logging
            logging.getLogger(__name__).exception("unserializable journal event")
            return
        with self._lock:
            if len(self._mem) == self._mem.maxlen:
                self.dropped_from_memory += 1
            self._mem.append(line)
            self.events_appended += 1
            self.bytes_appended += len(line) + 1
            if self._f is not None:
                try:
                    if self._file_bytes + len(line) + 1 > self.max_bytes:
                        self._rotate_locked()
                    self._f.write(line + "\n")
                    self._file_bytes += len(line) + 1
                    if self.fsync == "always":
                        self._f.flush()
                        os.fsync(self._f.fileno())
                except OSError:
                    import logging
                    logging.getLogger(__name__).exception(
                        "journal write failed; continuing in-memory only")

    def _rotate_locked(self) -> None:
        """Caller holds the lock. path.N-1 -> path.N ... path -> path.1."""
        if self.fsync in ("rotate", "always"):
            self._f.flush()
            os.fsync(self._f.fileno())
        self._f.close()
        oldest = f"{self.path}.{self.max_files}"
        if os.path.exists(oldest):
            os.unlink(oldest)
        for i in range(self.max_files - 1, 0, -1):
            src = f"{self.path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i + 1}")
        os.replace(self.path, f"{self.path}.1")
        self._f = open(self.path, "a", encoding="utf-8")
        self._file_bytes = 0
        self.rotations += 1

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.flush()
                    if self.fsync != "never":
                        os.fsync(self._f.fileno())
                    self._f.close()
                except OSError:
                    pass
                self._f = None

    # ---------------------------------------------------------------- read
    def lines(self) -> list[str]:
        """The in-memory ring of recent journal lines (all of them for a
        short sim run) — the slice ``ScenarioResult`` carries."""
        with self._lock:
            return list(self._mem)

    def tail(self, cursor: int = 0) -> tuple[int, list[str], int]:
        """Follow API: events appended at/after absolute event index
        ``cursor``. Returns ``(new_cursor, lines, dropped)`` where
        ``new_cursor`` is the next cursor to pass and ``dropped`` counts
        events the bounded memory ring already evicted (a tailer that keeps
        up sees 0). In-process standbys tail this; file followers tailing
        another process's journal use :class:`JournalTailer` instead."""
        with self._lock:
            first = self.events_appended - len(self._mem)
            start = max(int(cursor), first)
            dropped = start - int(cursor) if cursor < first else 0
            mem = list(self._mem)
            return self.events_appended, mem[start - first:], dropped

    def state_json(self) -> dict:
        with self._lock:
            return {"path": self.path, "events": self.events_appended,
                    "bytes": self.bytes_appended,
                    "rotations": self.rotations,
                    "memoryLines": len(self._mem),
                    "droppedFromMemory": self.dropped_from_memory,
                    "fsync": self.fsync}


class JournalTailer:
    """Seam-safe follower of another process's on-disk journal file.

    Rotation renames ``path`` -> ``path.1`` (shifting older suffixes up) and
    reopens a fresh ``path``; a naive reader holding an open fd at an offset
    would keep reading the renamed file and never see the new one (drop), or
    reopen ``path`` and reread it from 0 (duplicate). The tailer remembers
    the INODE of the file it is reading: on each poll, if ``path`` now names
    a different inode, it (1) drains the previously-open fd to EOF — the
    rename preserved the inode so nothing written before the rotate is lost,
    (2) drains any ``path.K`` rotated files NEWER than the one it was
    reading (several rotations may land between polls; ``path.K-1`` rotated
    after ``path.K``), then (3) switches to the new ``path`` at offset 0.
    Partial (torn) tail lines are retained in a buffer until their newline
    arrives, so a line is never emitted twice nor split."""

    def __init__(self, path: str):
        self.path = path
        self._f = None
        self._ino = None
        self._buf = ""

    def _open(self, path: str):
        f = open(path, "r", encoding="utf-8")
        return f, os.fstat(f.fileno()).st_ino

    def _drain(self, f) -> list[str]:
        chunk = f.read()
        if not chunk:
            return []
        self._buf += chunk
        *complete, self._buf = self._buf.split("\n")
        return [ln for ln in complete if ln]

    def poll(self) -> list[str]:
        """New complete journal lines since the previous poll ([] when
        nothing landed or the file does not exist yet)."""
        out: list[str] = []
        try:
            cur_ino = os.stat(self.path).st_ino
        except OSError:
            return out
        if self._f is None:
            try:
                self._f, self._ino = self._open(self.path)
            except OSError:
                return out
        if self._ino != cur_ino:
            # rotated underneath us: finish the renamed file (same inode),
            # then any newer-rotated siblings, oldest first
            out.extend(self._drain(self._f))
            self._buf = ""       # a torn tail at rotate can't complete: the
            self._f.close()      # writer fsyncs whole lines before rotating
            rotated = []         # path.K newer than the inode we were on
            k = 1
            while True:
                p = f"{self.path}.{k}"
                try:
                    ino = os.stat(p).st_ino
                except OSError:
                    break
                if ino == self._ino:
                    break
                rotated.append(p)
                k += 1
            for p in reversed(rotated):   # oldest rotation first
                try:
                    f, _ = self._open(p)
                except OSError:
                    continue
                out.extend(self._drain(f))
                f.close()
                self._buf = ""
            try:
                self._f, self._ino = self._open(self.path)
            except OSError:
                self._f = None
                return out
        out.extend(self._drain(self._f))
        return out

    def pending_bytes(self) -> int:
        """Unread bytes in the CURRENT file (a lag estimate for gauges)."""
        if self._f is None:
            return 0
        try:
            return max(os.stat(self.path).st_size - self._f.tell(), 0)
        except OSError:
            return 0

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


# ---------------------------------------------------------------------------
# causal spans
# ---------------------------------------------------------------------------
def _norm_attrs(attrs: dict) -> dict:
    """JSON-native attr values: numpy scalars -> Python scalars (a stray
    np.int32 in a span attr must not poison /state?substates=TRACES)."""
    out = {}
    for k, v in attrs.items():
        if hasattr(v, "item") and getattr(v, "ndim", None) in (None, 0):
            try:
                v = v.item()
            except Exception:  # noqa: BLE001
                v = str(v)
        out[str(k)] = v
    return out


@dataclasses.dataclass
class Span:
    """One causally-linked unit of work. Lifetime: ``tracer.span(...)`` ->
    (optional ``child(...)`` handles passed down the call chain) ->
    ``end(**attrs)``, which stamps t1 and journals the span."""
    trace_id: str
    span_id: str
    parent_id: str | None
    span_kind: str               # verdict | operation | optimize | execution...
    name: str
    t0_ms: float
    t1_ms: float | None = None
    attrs: dict = dataclasses.field(default_factory=dict)
    _tracer: "SpanTracer | None" = dataclasses.field(
        default=None, repr=False, compare=False)

    def child(self, span_kind: str, name: str, **attrs) -> "Span | None":
        """Explicit-handle propagation: the child carries this span's
        trace_id and points back via parent_id."""
        if self._tracer is None:
            return None
        return self._tracer.span(span_kind, name, parent=self, **attrs)

    def end(self, **attrs) -> "Span":
        if self._tracer is not None and self.t1_ms is None:
            self._tracer._finish(self, attrs)
        return self

    def to_json(self) -> dict:
        return {"trace": self.trace_id, "span": self.span_id,
                "parent": self.parent_id, "span_kind": self.span_kind,
                "name": self.name, "t0": round(self.t0_ms, 3),
                "t1": None if self.t1_ms is None else round(self.t1_ms, 3),
                "attrs": dict(self.attrs)}


class SpanTracer:
    """Span factory + bounded ring of finished spans.

    Ids are a per-tracer counter (``s000042``; a root's trace_id reuses its
    span counter as ``t000042``) — deterministic wherever the call order is
    (the single-threaded sim), merely unique under the service's threads.
    Finished spans are journaled (one line per span, at end time so every
    record carries its full [t0, t1] extent) and retained in a ring of
    ``capacity`` for ``/state?substates=TRACES``.
    """

    def __init__(self, clock_ms=None, journal: EventJournal | None = None,
                 capacity: int = 1024):
        self.clock_ms = clock_ms or (lambda: time.time() * 1000.0)
        self.journal = journal
        self.capacity = max(int(capacity), 16)
        self._lock = threading.Lock()
        self._next = 0
        self._open: dict[str, Span] = {}
        self._done: deque[Span] = deque(maxlen=self.capacity)
        self.started = 0
        self.finished = 0

    def span(self, span_kind: str, name: str, parent: Span | None = None,
             **attrs) -> Span:
        with self._lock:
            sid = f"s{self._next:06d}"
            self._next += 1
            self.started += 1
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            trace_id, parent_id = f"t{sid[1:]}", None
        sp = Span(trace_id=trace_id, span_id=sid, parent_id=parent_id,
                  span_kind=span_kind, name=name,
                  t0_ms=float(self.clock_ms()), attrs=_norm_attrs(attrs),
                  _tracer=self)
        with self._lock:
            self._open[sid] = sp
            # leak bound: a span abandoned by an exception path stays open
            # forever; evict the oldest once the open set far exceeds the
            # ring (insertion-ordered dict -> oldest first)
            while len(self._open) > 4 * self.capacity:
                self._open.pop(next(iter(self._open)))
        return sp

    def _finish(self, span: Span, attrs: dict) -> None:
        span.attrs.update(_norm_attrs(attrs))
        span.t1_ms = float(self.clock_ms())
        with self._lock:
            self._open.pop(span.span_id, None)
            self._done.append(span)
            self.finished += 1
        if self.journal is not None:
            j = span.to_json()
            self.journal.append("span", trace=j["trace"], span=j["span"],
                                parent=j["parent"], span_kind=j["span_kind"],
                                name=j["name"], t0=j["t0"], t1=j["t1"],
                                attrs=j["attrs"])

    # ---------------------------------------------------------------- read
    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._done) + list(self._open.values())

    def to_json(self) -> dict:
        records = [s.to_json() for s in self.spans()]
        return {"capacity": self.capacity, "started": self.started,
                "finished": self.finished,
                "open": sum(1 for r in records if r["t1"] is None),
                "trees": build_trace_trees(records)}


def build_trace_trees(records: list) -> list:
    """Nest span records (dicts with trace/span/parent keys — the tracer's
    ring or journal ``span`` events) into per-trace trees.

    Returns ``[{"trace": tid, "roots": [span + "children": [...]],
    "orphans": [...]}, ...]`` sorted by trace id; ``orphans`` are spans
    whose parent never appeared (the tree-completeness tests assert none).
    """
    by_trace: dict[str, list] = {}
    for r in records:
        if not isinstance(r, dict) or "span" not in r:
            continue
        by_trace.setdefault(r.get("trace"), []).append(r)
    trees = []
    for tid in sorted(by_trace, key=str):
        spans = by_trace[tid]
        by_id = {r["span"]: dict(r, children=[]) for r in spans}
        roots, orphans = [], []
        for r in spans:
            node = by_id[r["span"]]
            parent = r.get("parent")
            if parent is None:
                roots.append(node)
            elif parent in by_id:
                by_id[parent]["children"].append(node)
            else:
                orphans.append(node)
        for node in by_id.values():
            node["children"].sort(key=lambda n: (n.get("t0") or 0.0, n["span"]))
        roots.sort(key=lambda n: (n.get("t0") or 0.0, n["span"]))
        trees.append({"trace": tid, "roots": roots, "orphans": orphans})
    return trees


# ---------------------------------------------------------------------------
# round traces
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class RoundTrace:
    """One optimization round, flight-recorder schema (all host-side data the
    round computed anyway; per-goal seconds are honest only at
    ``analyzer.profile.level=stage`` or ``measure_goal_durations=True`` —
    ``durations_measured`` says which)."""
    round_id: int
    ts_ms: float
    operation: str | None           # REBALANCE / PROPOSALS / FIX_* / None
    wall_s: float                   # whole optimizations() call
    sampling_s: float | None        # last noted monitor sampling round
    sync_mode: str | None           # resident session: "delta" | "rebuild"
    sync_s: float | None
    donated: bool                   # this round took the resident state
    profile_level: str              # off | pass | stage
    durations_measured: bool
    compiles: int                   # XLA backend compiles during the round
    env_bytes: int
    state_bytes: int
    num_proposals: int
    num_replica_movements: int
    num_leadership_movements: int
    goals: list = dataclasses.field(default_factory=list)
    # pipelined-service-loop lanes (PR 11): the ingest/sync/execute stage
    # spans that PREPARED this round (noted by the pipeline before the round
    # ran), each with the seconds it overlapped an in-flight optimize round —
    # the flight-recorder proof that sampling/sync are off the critical path
    stages: list = dataclasses.field(default_factory=list)
    # per-stage summary {stage: {"dur_s", "overlap_s", "overlap_frac"}};
    # empty on the blocking loop (nothing ever overlaps optimize there)
    overlap: dict = dataclasses.field(default_factory=dict)
    # causal lineage (PR 12): the trace this round belongs to, when an
    # explicit span handle reached the optimizer (detector verdict ->
    # operation -> this round); None for unparented rounds
    trace_id: str | None = None
    # incremental re-optimization (PR 16): how the round was produced —
    # "full" | "reduced" (dirty-set-seeded chain) | "revalidated" (the
    # whole-round certificate memo; revalidate_s is the re-check's wall
    # seconds, the round's only device work)
    round_mode: str = "full"
    revalidate_s: float = 0.0
    # convergence-gated pass scheduling (PR 19): chain totals of budgeted
    # passes dispatched vs avoided by the chunked early exit, goals whose
    # chunk loop quiesced, and reduced goals short-circuited to one probe
    passes_dispatched: int = 0
    passes_skipped: int = 0
    early_exit_goals: int = 0
    skipped_goals: int = 0
    # ragged fleet gating (PR 20): one row per tenant lane of a batched
    # launch (tenant index, round_mode, pass/skip counters, parked_early,
    # compacted_out) — empty for solo rounds / ungated fleets
    fleet_lanes: list = dataclasses.field(default_factory=list)

    def to_json(self) -> dict:
        out = dataclasses.asdict(self)
        out["wall_s"] = round(out["wall_s"], 4)
        return out


def goal_trace_rows(goal_results) -> list[dict]:
    """Per-goal trace rows from GoalResult records — the engine's pass-level
    profile (passes, per-branch action split, admission waves, finisher
    actions) plus the violation flags and (when measured) seconds."""
    return [{
        "name": g.name,
        "duration_s": round(g.duration_s, 4),
        "violated_before": g.violated_before,
        "violated_after": g.violated_after,
        "iterations": g.iterations,
        "passes": g.passes,
        "moves": g.move_actions,
        "leads": g.lead_actions,
        "swaps": g.swap_actions,
        "disk": g.disk_actions,
        "waves": g.move_waves,
        "finisher": g.finisher_actions,
        # segment-parallel finisher phase (PR 7): segments the applied waves
        # spread destinations over (0 = legacy waves) and admitted
        # cross-segment boundary rows re-validated by the budgeted admission
        "fin_segments": getattr(g, "finisher_segments", 0),
        "fin_boundary": getattr(g, "finisher_boundary", 0),
        # incremental round mode (PR 16): full | reduced | revalidated |
        # skipped — the flamegraph's which-goals-did-the-fast-path-skip
        # signal
        "mode": getattr(g, "mode", "full"),
        # convergence-gated dispatch (PR 19): budgeted passes the chunked
        # early exit avoided and the quiescing chunk index (-1 = ran to the
        # loop's own exit / chunking off)
        "passes_skipped": getattr(g, "passes_skipped", 0),
        "quiesce_chunk": getattr(g, "quiesce_chunk", -1),
    } for g in goal_results]


class FlightRecorder:
    """Bounded thread-safe ring buffer of :class:`RoundTrace` records.

    Always on and deliberately cheap: ``record`` is a lock + deque append.
    ``clock_ms`` is injectable so traces carry the backend's clock (simulated
    time in the sim; wall time in the service). ``note_sampling`` /
    ``note_operation`` let the layers that know those facts (monitor, facade)
    annotate the NEXT recorded round without the optimizer needing to know
    either — the operation note is thread-local so concurrent user-task
    rounds can't cross-tag each other.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY, clock_ms=None,
                 journal: EventJournal | None = None):
        self.capacity = int(capacity)
        self.clock_ms = clock_ms or (lambda: time.time() * 1000.0)
        self.journal = journal
        self._lock = threading.Lock()
        self._traces: deque[RoundTrace] = deque(maxlen=self.capacity)
        self._recorded = 0
        self._next_id = 0
        self._sampling_s: float | None = None
        self._tl = threading.local()
        # pipelined-loop lane bookkeeping: stage spans noted since the last
        # recorded round, KEYED BY OPTIMIZE-ROUND GENERATION (the generation
        # in flight — or last started — when the note landed). A plain list
        # raced the threaded pipeline: once the optimize interval rolled, a
        # stage noted for round G+1 was consumed by round G's record. Each
        # entry is (generation, span-dict); _opt_t0 is the monotonic start
        # of the optimize round currently in flight (None = none in flight).
        self._pending_stages: list[tuple[int, dict]] = []
        self._opt_t0: float | None = None
        self._opt_gen = 0

    # ------------------------------------------------------------ annotate
    def note_sampling(self, seconds: float) -> None:
        with self._lock:
            self._sampling_s = round(float(seconds), 4)

    def note_operation(self, operation: str) -> None:
        self._tl.operation = operation

    def _take_operation(self) -> str | None:
        op = getattr(self._tl, "operation", None)
        self._tl.operation = None
        return op

    # ------------------------------------------------------ pipeline lanes
    def note_optimize_start(self) -> int:
        """The optimizer marks its round's start so concurrently-noted stage
        spans can measure how much of their wall ran UNDER the in-flight
        round (the pipelined loop's overlap proof). Returns the round's
        GENERATION — the optimizer hands it back to ``record_round`` so
        stage notes landing for a LATER round (the optimize interval rolled
        before this round recorded) stay pending for that round."""
        with self._lock:
            self._opt_t0 = time.monotonic()
            self._opt_gen += 1
            return self._opt_gen

    def optimize_in_flight(self) -> bool:
        """True between note_optimize_start and the round's record_round —
        the pipelined loop uses it to sequence its overlapped stages."""
        with self._lock:
            return self._opt_t0 is not None

    def note_stage(self, stage: str, t0: float, t1: float, **extra) -> None:
        """Record one pipeline stage span (monotonic seconds). ``overlap_s``
        is the part of [t0, t1] spent while an optimize round was in flight —
        computed here, at note time, because by the time the round records
        its trace the concurrent span is history. Spans accumulate keyed by
        the optimize generation in flight and attach to THAT round's trace
        (or the next one, when none is in flight)."""
        t0, t1 = float(t0), float(t1)
        with self._lock:
            opt_t0 = self._opt_t0
            now = time.monotonic()
            overlap = 0.0
            if opt_t0 is not None:
                overlap = max(0.0, min(t1, now) - max(t0, opt_t0))
            span = {"stage": stage, "dur_s": round(max(t1 - t0, 0.0), 4),
                    "overlap_s": round(overlap, 4)}
            span.update(extra)
            self._pending_stages.append((self._opt_gen, span))
            del self._pending_stages[:-64]   # bounded like the trace ring
        if self.journal is not None:
            # deterministic fields only: the stage name + its own counters
            # (batches/executed/dropped), never wall seconds
            self.journal.append("stage", stage=stage, **extra)

    def _take_stages(self, upto_gen: int | None = None) -> tuple[list, dict]:
        """Consume pending stage spans noted for generations <= ``upto_gen``
        (None = everything); returns (stages, per-stage overlap summary).
        Later generations stay pending for the round that owns them. Caller
        holds no lock."""
        with self._lock:
            if upto_gen is None:
                upto_gen = self._opt_gen
            stages = [s for g, s in self._pending_stages if g <= upto_gen]
            self._pending_stages = [(g, s) for g, s in self._pending_stages
                                    if g > upto_gen]
            if self._opt_gen <= upto_gen:
                # only clear the in-flight marker when no NEWER round has
                # started — round G's record must not erase round G+1's t0
                self._opt_t0 = None
        summary: dict = {}
        for s in stages:
            agg = summary.setdefault(s["stage"],
                                     {"dur_s": 0.0, "overlap_s": 0.0})
            agg["dur_s"] += s["dur_s"]
            agg["overlap_s"] += s["overlap_s"]
        for agg in summary.values():
            agg["dur_s"] = round(agg["dur_s"], 4)
            agg["overlap_s"] = round(agg["overlap_s"], 4)
            agg["overlap_frac"] = round(
                agg["overlap_s"] / agg["dur_s"], 4) if agg["dur_s"] else 0.0
        return stages, summary

    # -------------------------------------------------------------- record
    def next_round_id(self) -> int:
        with self._lock:
            rid = self._next_id
            self._next_id += 1
            return rid

    def record(self, trace: RoundTrace) -> None:
        with self._lock:
            self._traces.append(trace)
            self._recorded += 1

    def record_round(self, *, wall_s: float, goal_results, compiles: int,
                     env, state, num_proposals: int,
                     num_replica_movements: int,
                     num_leadership_movements: int,
                     session_info: dict | None = None, donated: bool = False,
                     profile_level: str = "off",
                     durations_measured: bool = False,
                     trace_id: str | None = None,
                     opt_generation: int | None = None,
                     round_mode: str = "full",
                     revalidate_s: float = 0.0,
                     passes_dispatched: int = 0,
                     passes_skipped: int = 0,
                     early_exit_goals: int = 0,
                     skipped_goals: int = 0,
                     fleet_lanes: list | None = None) -> RoundTrace:
        """Assemble + record one round from what the optimizer already holds.
        ``opt_generation`` (from this round's ``note_optimize_start``) keys
        which pending stage notes belong to it. Never raises into the
        optimization path."""
        info = session_info or {}
        with self._lock:
            sampling_s = self._sampling_s
        stages, overlap = self._take_stages(opt_generation)
        try:
            trace = RoundTrace(
                round_id=self.next_round_id(),
                ts_ms=float(self.clock_ms()),
                operation=self._take_operation(),
                wall_s=wall_s,
                sampling_s=sampling_s,
                sync_mode=info.get("mode"),
                sync_s=info.get("sync_s"),
                donated=donated,
                profile_level=profile_level,
                durations_measured=durations_measured,
                compiles=int(compiles),
                env_bytes=tree_device_bytes(env),
                state_bytes=tree_device_bytes(state),
                num_proposals=int(num_proposals),
                num_replica_movements=int(num_replica_movements),
                num_leadership_movements=int(num_leadership_movements),
                goals=goal_trace_rows(goal_results),
                stages=stages,
                overlap=overlap,
                trace_id=trace_id,
                round_mode=round_mode,
                revalidate_s=round(float(revalidate_s), 4),
                passes_dispatched=int(passes_dispatched),
                passes_skipped=int(passes_skipped),
                early_exit_goals=int(early_exit_goals),
                skipped_goals=int(skipped_goals),
                fleet_lanes=list(fleet_lanes or []),
            )
        except Exception:  # noqa: BLE001 — tracing must never fail a round
            import logging
            logging.getLogger(__name__).exception("round trace assembly failed")
            return None
        self.record(trace)
        if self.journal is not None:
            # deterministic slice of the trace only: counts, modes and the
            # lineage tie — never wall seconds or compile counts (the same
            # (scenario, seed) must journal identical bytes even when one
            # run compiled and the other hit warm program caches)
            self.journal.append(
                "round", round=trace.round_id, op=trace.operation,
                trace=trace.trace_id, proposals=trace.num_proposals,
                moves=trace.num_replica_movements,
                leads=trace.num_leadership_movements,
                sync=trace.sync_mode, donated=trace.donated)
        return trace

    # ---------------------------------------------------------------- read
    def last(self) -> RoundTrace | None:
        with self._lock:
            return self._traces[-1] if self._traces else None

    def last_json(self) -> dict | None:
        t = self.last()
        return t.to_json() if t is not None else None

    def traces(self) -> list[RoundTrace]:
        with self._lock:
            return list(self._traces)

    def to_json(self) -> dict:
        with self._lock:
            traces = list(self._traces)
            recorded = self._recorded
        return {"capacity": self.capacity, "recorded": recorded,
                "traces": [t.to_json() for t in traces]}

    def register_gauges(self, sensors) -> None:
        """Last-round gauges on the MetricRegistry, so /metrics carries the
        newest round without parsing the trace substate."""
        def field(name, default=0):
            def read():
                t = self.last()
                v = getattr(t, name, None) if t is not None else None
                return default if v is None else v
            return read

        sensors.gauge("round-traces-recorded",
                      lambda: self.to_json()["recorded"])
        sensors.gauge("last-round-wall-seconds", field("wall_s", 0.0))
        sensors.gauge("last-round-sampling-seconds", field("sampling_s", 0.0))
        sensors.gauge("last-round-sync-seconds", field("sync_s", 0.0))
        sensors.gauge("last-round-compiles", field("compiles"))
        sensors.gauge("last-round-env-bytes", field("env_bytes"))
        sensors.gauge("last-round-state-bytes", field("state_bytes"))
        sensors.gauge("last-round-proposals", field("num_proposals"))


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------
def _prom_name(name: str, suffix: str = "") -> str:
    import re
    base = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    if base and base[0].isdigit():
        base = "_" + base
    return f"cc_{base}{suffix}"


def _fmt(v) -> str:
    f = float(v)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    return repr(f)


def render_prometheus(registry_json: dict) -> str:
    """Render one MetricRegistry snapshot (``MetricRegistry.to_json()``) in
    Prometheus text exposition format 0.0.4.

    Timers render as summaries (quantiles + _sum/_count) plus a ``_max``
    gauge; meters as a ``_total`` counter plus a one-minute-rate gauge;
    gauges as gauges (non-numeric / errored gauges are skipped — a dead gauge
    must not poison the scrape). The ingest side of this repo already parses
    this family of formats (monitor/sampling/prometheus.py), so a CC instance
    can scrape itself — the round-trip the tests run.
    """
    lines: list[str] = []
    for name in sorted(registry_json):
        snap = registry_json[name]
        kind = snap.get("type")
        if kind == "timer":
            m = _prom_name(name, "_seconds")
            total = snap.get("totalSec",
                             snap.get("meanSec", 0.0) * snap.get("count", 0))
            lines.append(f"# TYPE {m} summary")
            for q, key in (("0.5", "p50Sec"), ("0.95", "p95Sec"),
                           ("0.99", "p99Sec")):
                lines.append(f'{m}{{quantile="{q}"}} {_fmt(snap[key])}')
            lines.append(f"{m}_sum {_fmt(total)}")
            lines.append(f"{m}_count {snap['count']}")
            mx = _prom_name(name, "_seconds_max")
            lines.append(f"# TYPE {mx} gauge")
            lines.append(f"{mx} {_fmt(snap['maxSec'])}")
            # cumulative fixed-bucket histogram twin (its own family — a
            # summary and a histogram cannot share a metric name): exact
            # le-labelled counters Prometheus/Grafana can aggregate into
            # percentiles ACROSS scrapes/instances (histogram_quantile),
            # which the reservoir summary above fundamentally cannot
            buckets = snap.get("bucketsSec")
            if buckets:
                h = _prom_name(name, "_seconds_hist")
                lines.append(f"# TYPE {h} histogram")
                for le, cum in buckets:
                    lines.append(f'{h}_bucket{{le="{_fmt(le)}"}} {cum}')
                lines.append(f'{h}_bucket{{le="+Inf"}} {snap["count"]}')
                lines.append(f"{h}_sum {_fmt(total)}")
                lines.append(f"{h}_count {snap['count']}")
        elif kind == "meter":
            m = _prom_name(name, "_total")
            lines.append(f"# TYPE {m} counter")
            lines.append(f"{m} {snap['count']}")
            r = _prom_name(name, "_one_minute_rate")
            lines.append(f"# TYPE {r} gauge")
            lines.append(f"{r} {_fmt(snap['oneMinuteRatePerSec'])}")
        elif kind == "gauge":
            if "value" not in snap:
                continue        # errored gauge: skip, never poison the scrape
            try:
                val = _fmt(snap["value"])
            except (TypeError, ValueError):
                continue        # non-numeric gauge (strings etc.)
            m = _prom_name(name)
            lines.append(f"# TYPE {m} gauge")
            lines.append(f"{m} {val}")
    return "\n".join(lines) + "\n"
