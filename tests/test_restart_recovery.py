"""Restart recovery through the durable sample store (tier-1 smoke).

ROADMAP claimed "a restart forfeits all windows"; the FileSampleStore +
``LoadMonitor.start_up`` replay (SampleLoadingTask role, SURVEY §5) close
that: samples stored during normal operation rebuild the aggregation windows
in a FRESH monitor, and the rebuilt model is bit-identical to the
pre-restart one. ``bench.py`` e2e rungs report the recovery wall as
``restart_recovery_s``.
"""
import numpy as np

from cruise_control_tpu.app import CruiseControl
from cruise_control_tpu.backend import SimulatedClusterBackend
from cruise_control_tpu.config import cruise_control_config


def _backend():
    be = SimulatedClusterBackend()
    for b in range(4):
        be.add_broker(b, f"r{b % 2}")
    for p in range(16):
        be.create_partition("t", p, [(p % 4), (p + 1) % 4], size_mb=50.0 + p,
                            bytes_in_rate=5.0 + p, bytes_out_rate=11.0 + p,
                            cpu_util=0.5)
    return be


def _config(tmp_path):
    return cruise_control_config({
        "sample.store.path": str(tmp_path / "samples"),
        "num.metrics.windows": 5,
        "min.samples.per.metrics.window": 1,
        "metrics.window.ms": 60_000,
    })


def test_restart_replay_rebuilds_windows_bit_identical(tmp_path):
    be = _backend()
    cc1 = CruiseControl(be, _config(tmp_path))
    cc1.load_monitor.start_up()
    for i in range(6):
        cc1.load_monitor.sample_once(now_ms=i * 60_000.0)
    agg1 = cc1.load_monitor._partition_agg.aggregate()
    ct1, meta1 = cc1.load_monitor.cluster_model()
    cc1.shutdown()   # closes the store files

    # "restart": a fresh monitor over the same backend replays the store
    cc2 = CruiseControl(be, _config(tmp_path))
    replayed = cc2.load_monitor.start_up()
    assert replayed > 0
    # NO sampling after restart: every window must come from the replay
    agg2 = cc2.load_monitor._partition_agg.aggregate()
    assert list(agg1.window_starts_ms) == list(agg2.window_starts_ms)
    ct2, meta2 = cc2.load_monitor.cluster_model()
    assert meta1.partition_ids == meta2.partition_ids
    np.testing.assert_array_equal(np.asarray(ct1.leader_load),
                                  np.asarray(ct2.leader_load))
    np.testing.assert_array_equal(np.asarray(ct1.follower_load),
                                  np.asarray(ct2.follower_load))
    cc2.shutdown()


def test_restart_without_store_forfeits_windows(tmp_path):
    """The ROADMAP claim holds exactly when no store is configured — the
    replay is what closes it, not monitor magic."""
    import pytest

    from cruise_control_tpu.monitor.load_monitor import NotEnoughValidWindowsError
    be = _backend()
    cfg = cruise_control_config({"num.metrics.windows": 5,
                                 "min.samples.per.metrics.window": 1,
                                 "metrics.window.ms": 60_000})
    cc1 = CruiseControl(be, cfg)
    cc1.load_monitor.start_up()
    for i in range(6):
        cc1.load_monitor.sample_once(now_ms=i * 60_000.0)
    cc1.load_monitor.cluster_model()
    cc1.shutdown()
    cc2 = CruiseControl(be, cfg)
    assert cc2.load_monitor.start_up() == 0
    with pytest.raises(NotEnoughValidWindowsError):
        cc2.load_monitor.cluster_model()
    cc2.shutdown()


def test_attach_sample_store_records_from_then_on(tmp_path):
    """LoadMonitor.attach_sample_store: rounds before the attach are not
    persisted, rounds after are — the bench's restart-recovery seam."""
    from cruise_control_tpu.monitor.sampling.sample_store import FileSampleStore
    be = _backend()
    cfg = cruise_control_config({"num.metrics.windows": 5,
                                 "min.samples.per.metrics.window": 1,
                                 "metrics.window.ms": 60_000})
    cc = CruiseControl(be, cfg)
    cc.load_monitor.start_up()
    cc.load_monitor.sample_once(now_ms=0.0)          # not persisted
    store = FileSampleStore()
    store.configure(None, path=str(tmp_path / "late"))
    cc.load_monitor.attach_sample_store(store)
    cc.load_monitor.sample_once(now_ms=60_000.0)     # persisted
    cc.load_monitor.sample_once(now_ms=120_000.0)    # persisted (closes 60k)
    cc.shutdown()

    cc2 = CruiseControl(be, cruise_control_config({
        "sample.store.path": str(tmp_path / "late"),
        "num.metrics.windows": 5,
        "min.samples.per.metrics.window": 1,
        "metrics.window.ms": 60_000}))
    replayed = cc2.load_monitor.start_up()
    assert replayed > 0
    agg = cc2.load_monitor._partition_agg.aggregate()
    assert list(agg.window_starts_ms) == [60_000.0]  # only the late round
    cc2.shutdown()
