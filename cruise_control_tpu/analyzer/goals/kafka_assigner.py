"""Kafka-assigner mode goals.

Reference: analyzer/kafkaassigner/ — KafkaAssignerEvenRackAwareGoal.java
(509: replicas of each partition spread position-by-position round-robin
across racks => an even rack distribution) and
KafkaAssignerDiskUsageDistributionGoal.java (693: disk balancing that
preserves each broker's replica count by SWAPPING replicas between broker
pairs instead of moving them). The ``kafka_assigner`` request parameter
substitutes these for their standard counterparts
(GoalBasedOperationRunnable kafka-assigner mode).

The contract kept here is the outcome, not the scan order: STRICT rack
awareness (each replica of a partition on a distinct rack; RF above the
alive-rack count raises, KafkaAssignerEvenRackAwareGoal.java:302-356), and
swap-only disk balancing == replica-count-preserving actions.
"""
from __future__ import annotations

import dataclasses

from cruise_control_tpu.analyzer.goals.distribution import DiskUsageDistributionGoal
from cruise_control_tpu.analyzer.goals.rack import RackAwareGoal


@dataclasses.dataclass(frozen=True)
class KafkaAssignerEvenRackAwareGoal(RackAwareGoal):
    """STRICT rack awareness (each replica of a partition on a distinct
    rack), hard — the reference's even-rack goal enforces
    ensureRackAwareSatisfiable/ensureRackAware
    (KafkaAssignerEvenRackAwareGoal.java:302-356: throws when max RF exceeds
    the alive-rack count, and requires distinct racks per partition), i.e.
    RackAwareGoal's contract; the position-by-position round-robin is its
    packing order, not a weaker ceil-based spread."""

    def __post_init__(self):
        super().__post_init__()
        object.__setattr__(self, "name", "KafkaAssignerEvenRackAwareGoal")


@dataclasses.dataclass(frozen=True)
class KafkaAssignerDiskUsageDistributionGoal(DiskUsageDistributionGoal):
    """Disk balancing by swaps only: per-broker replica counts are preserved,
    matching the kafka-assigner tool's semantics
    (KafkaAssignerDiskUsageDistributionGoal.java swapReplicas)."""

    def __post_init__(self):
        super().__post_init__()
        object.__setattr__(self, "name", "KafkaAssignerDiskUsageDistributionGoal")
        object.__setattr__(self, "uses_replica_moves", False)
        object.__setattr__(self, "uses_leadership_moves", False)
        object.__setattr__(self, "uses_swaps", True)


# GoalBasedOperationRunnable's kafka-assigner substitution table
KAFKA_ASSIGNER_SUBSTITUTION = {
    "RackAwareGoal": "KafkaAssignerEvenRackAwareGoal",
    "RackAwareDistributionGoal": "KafkaAssignerEvenRackAwareGoal",
    "DiskUsageDistributionGoal": "KafkaAssignerDiskUsageDistributionGoal",
}


def kafka_assigner_goal_names(names: list[str]) -> list[str]:
    """Map a goal list into kafka-assigner mode, dropping goals with no
    assigner equivalent beyond the substitution (the reference mode runs
    exactly its two goals when none are requested)."""
    if not names:
        return ["KafkaAssignerEvenRackAwareGoal",
                "KafkaAssignerDiskUsageDistributionGoal"]
    out = []
    for n in names:
        mapped = KAFKA_ASSIGNER_SUBSTITUTION.get(n, n)
        if mapped not in out:
            out.append(mapped)
    return out
