"""Windowed metric sample aggregation.

Reference: cruise-control-core/.../monitor/sampling/aggregator/
MetricSampleAggregator.java:84 (addSample :141, aggregate :193) with
RawMetricValues.java's per-window validity/extrapolation rules (:290-345):

- count >= max(1, min_samples//2): use the window's own value
  (AVG: sum/count, MAX/LATEST: kept value); mark AVG_AVAILABLE when
  count < min_samples.
- else if the window is interior (not first/last of the buffer) and BOTH
  neighbors have >= min_samples: AVG_ADJACENT — AVG: pooled mean over the 3
  windows; MAX/LATEST: total / (3 if own count > 0 else 2).
- else if count > 0: FORCED_INSUFFICIENT (use what's there).
- else: value 0, NO_VALID_EXTRAPOLATION.

Entity validity (RawMetricValues.isValid :166): no NO_VALID_EXTRAPOLATION
window and at most ``max_allowed_extrapolations`` extrapolated windows.
Completeness ratios (MetricSampleCompleteness role) gate model generation in
the LoadMonitor.

The reference stores per-entity circular buffers of boxed objects; here the
store is three dense float arrays [E, W+1, M] (sum / max / latest) plus a
count matrix [E, W+1], and ``aggregate`` is pure vectorized numpy — the same
layout the model builder feeds to the TPU, so the windows axis reduces without
a per-entity loop.
"""
from __future__ import annotations

import dataclasses
import enum
import threading

import numpy as np

from cruise_control_tpu.monitor.metricdef import AggregationFunction, MetricDef


class Extrapolation(enum.IntEnum):
    NONE = 0
    AVG_AVAILABLE = 1
    AVG_ADJACENT = 2
    FORCED_INSUFFICIENT = 3
    NO_VALID_EXTRAPOLATION = 4


@dataclasses.dataclass
class AggregationResult:
    entities: list                      # row order
    window_starts_ms: list              # [Wq] completed-window start times, oldest first
    values: np.ndarray                  # f64[E, Wq, M]
    extrapolations: np.ndarray          # u8[E, Wq]
    entity_valid: np.ndarray            # bool[E]
    completeness_per_window: np.ndarray # f64[Wq] fraction of valid entities
    completeness: float                 # fraction of entities valid across all windows

    def values_for(self, entity) -> np.ndarray:
        return self.values[self.entities.index(entity)]


class MetricSampleAggregator:
    """Dense windowed aggregator. Thread-safe for concurrent add_sample."""

    def __init__(self, num_windows: int, window_ms: int, min_samples_per_window: int,
                 max_allowed_extrapolations: int, metric_def: MetricDef):
        self._num_windows = num_windows
        self._window_ms = window_ms
        self._min_samples = max(1, min_samples_per_window)
        self._half_min = max(1, min_samples_per_window // 2)
        self._max_extrapolations = max_allowed_extrapolations
        self._metric_def = metric_def
        self._agg_funcs = np.array([m.aggregation.value for m in metric_def.all()])
        self._is_avg = self._agg_funcs == AggregationFunction.AVG.value
        self._lock = threading.Lock()
        self._entities: dict = {}
        self._generation = 0
        M = metric_def.num_metrics
        # slot 0..num_windows-1 = history ring, slot num_windows = current window
        self._sum = np.zeros((0, num_windows + 1, M))
        self._max = np.full((0, num_windows + 1, M), -np.inf)
        self._latest = np.zeros((0, num_windows + 1, M))
        self._counts = np.zeros((0, num_windows + 1), np.int32)
        self._oldest_window: int | None = None   # absolute index of ring slot 0
        self._current_window: int | None = None  # absolute index of the active window
        self._first_window: int | None = None    # first window ever observed
        # aggregate() memo: (num_windows arg) -> result, valid until the next
        # accepted sample (generation-numbered cache invalidation role,
        # common/LongGenerationed.java). Sensors/gauges snapshot aggregate()
        # repeatedly; without this each read is a full O(E x W x M) pass.
        self._dirty = True
        self._agg_cache: dict[int | None, AggregationResult] = {}

    # -- geometry --
    def window_index(self, ts_ms: float) -> int:
        return int(ts_ms // self._window_ms)

    @property
    def num_windows(self) -> int:
        return self._num_windows

    @property
    def window_ms(self) -> int:
        return self._window_ms

    def clear(self) -> None:
        """Drop all samples and windows (MetricSampleAggregator.clear —
        the bootstrap-with-clearmetrics path)."""
        with self._lock:
            M = self._metric_def.num_metrics
            W1 = self._num_windows + 1
            self._entities = {}
            self._sum = np.zeros((0, W1, M))
            self._max = np.full((0, W1, M), -np.inf)
            self._latest = np.zeros((0, W1, M))
            self._counts = np.zeros((0, W1), np.int32)
            self._oldest_window = None
            self._current_window = None
            self._first_window = None
            self._generation += 1
            self._dirty = True

    @property
    def generation(self) -> int:
        return self._generation

    @property
    def num_entities(self) -> int:
        return len(self._entities)

    def _entity_row(self, entity) -> int:
        row = self._entities.get(entity)
        if row is None:
            row = len(self._entities)
            self._entities[entity] = row
            if row >= self._sum.shape[0]:
                # amortized doubling: a concatenate PER new entity is O(E)
                # copy each -> O(E^2) on the first sampling round (minutes at
                # 500k partitions); geometric growth keeps ingestion linear
                grow = max(64, self._sum.shape[0])
                W1 = self._num_windows + 1
                M = self._metric_def.num_metrics
                self._sum = np.concatenate([self._sum, np.zeros((grow, W1, M))])
                self._max = np.concatenate(
                    [self._max, np.full((grow, W1, M), -np.inf)])
                self._latest = np.concatenate(
                    [self._latest, np.zeros((grow, W1, M))])
                self._counts = np.concatenate(
                    [self._counts, np.zeros((grow, W1), np.int32)])
        return row

    def _slot_of(self, window: int) -> int | None:
        """Ring slot for an absolute completed-window index, or None if rolled out."""
        if self._oldest_window is None or window < self._oldest_window:
            return None
        if window >= self._current_window:
            return None
        off = window - self._oldest_window
        if off >= self._num_windows:
            return None
        return off

    def _roll_to(self, window: int) -> None:
        """Advance the active window; completed windows land in the history ring."""
        if self._current_window is None:
            self._current_window = window
            self._oldest_window = window - self._num_windows
            self._first_window = window
            return
        if window <= self._current_window:
            return
        steps = window - self._current_window
        W = self._num_windows
        # finalize current active slot into history ring, shifting left as needed
        shift = min(steps, W + 1)
        self._sum = np.roll(self._sum, -shift, axis=1)
        self._max = np.roll(self._max, -shift, axis=1)
        self._latest = np.roll(self._latest, -shift, axis=1)
        self._counts = np.roll(self._counts, -shift, axis=1)
        # clear the slots that wrapped around (they represent new windows)
        self._sum[:, W + 1 - shift:] = 0.0
        self._max[:, W + 1 - shift:] = -np.inf
        self._latest[:, W + 1 - shift:] = 0.0
        self._counts[:, W + 1 - shift:] = 0
        self._current_window = window
        self._oldest_window = window - W
        self._generation += 1

    # -- ingestion (hot path: O(1) vector ops per sample) --
    def add_sample(self, entity, ts_ms: float, values: dict) -> bool:
        """Record one sample. Stale samples older than the ring are rejected
        (MetricSampleAggregator.addSample returns false)."""
        window = self.window_index(ts_ms)  # the window covering ts
        with self._lock:
            if self._current_window is not None and window < self._oldest_window:
                return False
            self._roll_to(max(window, self._current_window or window))
            row = self._entity_row(entity)
            slot = (window - self._oldest_window
                    if window < self._current_window else self._num_windows)
            if slot < 0:
                return False
            vec = np.zeros(self._metric_def.num_metrics)
            mask = np.zeros(self._metric_def.num_metrics, bool)
            for name, v in values.items():
                mid = self._metric_def.info(name).metric_id
                vec[mid] = v
                mask[mid] = True
            self._sum[row, slot, mask] += vec[mask]
            self._max[row, slot, mask] = np.maximum(self._max[row, slot, mask], vec[mask])
            self._latest[row, slot, mask] = vec[mask]
            self._counts[row, slot] += 1
            self._dirty = True
            return True

    def add_samples(self, entities: list, ts_ms: float, values,
                    metric_names: list) -> int:
        """Bulk ingestion: N samples sharing ONE timestamp and ONE metric-name
        set, ``values`` [N, len(metric_names)]. One vectorized scatter into
        the ring instead of N python calls — the per-sample path costs ~20 us
        each, which is minutes per sampling round at 1M partitions."""
        import numpy as _np
        n = len(entities)
        if n == 0:
            return 0
        window = self.window_index(ts_ms)
        with self._lock:
            if self._current_window is not None and window < self._oldest_window:
                return 0
            self._roll_to(max(window, self._current_window or window))
            try:
                # steady state: every entity is known — C-speed dict gets
                rows = _np.fromiter(map(self._entities.__getitem__, entities),
                                    dtype=_np.int64, count=n)
            except KeyError:
                rows = _np.fromiter((self._entity_row(e) for e in entities),
                                    dtype=_np.int64, count=n)
            slot = (window - self._oldest_window
                    if window < self._current_window else self._num_windows)
            if slot < 0:
                return 0
            cols = _np.asarray([self._metric_def.info(m).metric_id
                                for m in metric_names], dtype=_np.int64)
            values = _np.asarray(values, dtype=float)
            idx = (rows[:, None], cols[None, :])
            if _np.unique(rows).size == n:
                # the common columnar round: ONE sample per entity — plain
                # fancy indexing instead of the (much slower) ufunc.at
                # scatter; both slices are views, writes land in the ring
                ssum = self._sum[:, slot, :]
                smax = self._max[:, slot, :]
                ssum[idx] += values
                smax[idx] = _np.maximum(smax[idx], values)
                self._latest[rows[:, None], slot, cols[None, :]] = values
                self._counts[rows, slot] += 1
            else:
                # np.*.at: duplicate entities within one batch accumulate
                # exactly like repeated add_sample calls would
                _np.add.at(self._sum[:, slot, :], idx, values)
                _np.maximum.at(self._max[:, slot, :], idx, values)
                self._latest[rows[:, None], slot, cols[None, :]] = values
                _np.add.at(self._counts[:, slot], rows, 1)
            self._dirty = True
            return n

    # -- aggregation --
    def aggregate(self, num_windows: int | None = None) -> AggregationResult:
        """Aggregate the most recent ``num_windows`` completed windows.
        Results are memoized until the next accepted sample."""
        with self._lock:
            if self._dirty:
                self._agg_cache.clear()
                self._dirty = False
            cached = self._agg_cache.get(num_windows)
            if cached is not None:
                return cached
            result = self._aggregate_locked(num_windows)
            self._agg_cache[num_windows] = result
            return result

    def window_view(self, num_windows: int | None = None
                    ) -> tuple[AggregationResult, int]:
        """Zero-copy windowed history view: ``(result, generation)``.

        Hands out the memoized :class:`AggregationResult` arrays directly —
        no re-copy — stamped with the generation they were computed under, so
        a consumer (the forecaster) can key its own caches on the stamp and
        skip recompute entirely while no new window has rolled. The pair is
        read under one lock acquisition: the stamp can never describe a
        different ring state than the arrays. Callers must treat the arrays
        as immutable."""
        with self._lock:
            gen = self._generation
            if self._dirty:
                self._agg_cache.clear()
                self._dirty = False
            cached = self._agg_cache.get(num_windows)
            if cached is None:
                cached = self._aggregate_locked(num_windows)
                self._agg_cache[num_windows] = cached
            return cached, gen

    def _aggregate_locked(self, num_windows: int | None = None) -> AggregationResult:
        """Full aggregation pass; caller holds the lock."""
        W = min(num_windows or self._num_windows, self._num_windows)
        E = len(self._entities)
        M = self._metric_def.num_metrics
        if E == 0 or self._current_window is None:
            return AggregationResult([], [], np.zeros((0, W, M)),
                                     np.zeros((0, W), np.uint8), np.zeros(0, bool),
                                     np.zeros(W), 0.0)
        # only windows that have actually existed (>= first observed window)
        n_exist = self._current_window - max(self._first_window, self._oldest_window)
        W = max(min(W, n_exist), 0)
        lo_slot = self._num_windows - W
        # slice off spare capacity rows (see _entity_row's doubling growth)
        counts = self._counts[:E, lo_slot:self._num_windows]         # [E, W]
        sums = self._sum[:E, lo_slot:self._num_windows]              # [E, W, M]
        maxs = self._max[:E, lo_slot:self._num_windows]
        lasts = self._latest[:E, lo_slot:self._num_windows]

        own = np.where(self._is_avg[None, None, :],
                       sums / np.maximum(counts[:, :, None], 1),
                       np.where(self._agg_funcs[None, None, :]
                                == AggregationFunction.MAX.value,
                                np.where(np.isfinite(maxs), maxs, 0.0), lasts))

        c = counts
        c_prev = np.pad(c, ((0, 0), (1, 0)))[:, :-1]                 # count of left neighbor
        c_next = np.pad(c, ((0, 0), (0, 1)))[:, 1:]
        s_prev = np.pad(sums, ((0, 0), (1, 0), (0, 0)))[:, :-1]
        s_next = np.pad(sums, ((0, 0), (0, 1), (0, 0)))[:, 1:]
        interior = np.zeros((E, W), bool)
        if W > 2:
            interior[:, 1:-1] = True

        sufficient = c >= self._half_min
        adjacent_ok = (interior & (c_prev >= self._min_samples)
                       & (c_next >= self._min_samples))
        own_some = c > 0

        # adjacent-pooled values
        pooled_cnt = np.maximum(c_prev + c + c_next, 1)[:, :, None]
        adj_avg = (s_prev + np.where(own_some[:, :, None], sums, 0.0) + s_next) / pooled_cnt
        nonavg_total = (np.pad(own, ((0, 0), (1, 0), (0, 0)))[:, :-1]
                        + np.where(own_some[:, :, None], own, 0.0)
                        + np.pad(own, ((0, 0), (0, 1), (0, 0)))[:, 1:])
        adj_nonavg = nonavg_total / np.where(own_some, 3.0, 2.0)[:, :, None]
        adj = np.where(self._is_avg[None, None, :], adj_avg, adj_nonavg)

        values = np.where(sufficient[:, :, None], own,
                          np.where(adjacent_ok[:, :, None], adj,
                                   np.where(own_some[:, :, None], own, 0.0)))
        extra = np.full((E, W), Extrapolation.NO_VALID_EXTRAPOLATION, np.uint8)
        extra[own_some] = Extrapolation.FORCED_INSUFFICIENT
        extra[adjacent_ok & ~sufficient] = Extrapolation.AVG_ADJACENT
        extra[sufficient & (c < self._min_samples)] = Extrapolation.AVG_AVAILABLE
        extra[c >= self._min_samples] = Extrapolation.NONE

        invalid_any = (extra == Extrapolation.NO_VALID_EXTRAPOLATION).any(axis=1)
        n_extrapolated = (extra != Extrapolation.NONE).sum(axis=1)
        entity_valid = ~invalid_any & (n_extrapolated <= self._max_extrapolations)

        window_ok = extra != Extrapolation.NO_VALID_EXTRAPOLATION
        completeness_per_window = window_ok.mean(axis=0)
        completeness = float(entity_valid.mean())

        start = (self._oldest_window + lo_slot)
        window_starts = [(start + i) * self._window_ms for i in range(W)]
        entities = [e for e, _ in sorted(self._entities.items(), key=lambda kv: kv[1])]
        return AggregationResult(entities, window_starts, values, extra,
                                 entity_valid, completeness_per_window, completeness)
