"""OptimizationVerifier analogue.

Reference: analyzer/OptimizationVerifier.java:53 — after an optimization,
assert (NEW_BROKERS) a new-broker rebalance only moves replicas TO the new
brokers, (BROKEN_BROKERS) dead brokers end up empty with no offline replicas,
(REGRESSION, :94-117) no per-resource distribution statistic regresses, plus
goal-specific invariants handled by the per-goal tests.
"""
from __future__ import annotations

import numpy as np


def verify_new_brokers(ct, meta, res) -> None:
    """Replicas may only move onto brokers flagged new (OptimizationVerifier
    NEW_BROKERS)."""
    new_ids = {meta.broker_ids[i]
               for i in np.flatnonzero(np.asarray(ct.broker_new))}
    for p in res.proposals:
        added = set(p.replicas_to_add)
        assert added <= new_ids, (
            f"{p.tp}: replicas moved to non-new brokers {added - new_ids}")


def verify_broken_brokers(ct, meta, res) -> None:
    """Dead brokers end up empty; nothing remains offline (BROKEN_BROKERS)."""
    st = res.final_state
    alive = np.asarray(res.env.broker_alive)
    rb = np.asarray(st.replica_broker)
    valid = np.asarray(res.env.replica_valid)
    on_dead = valid & ~alive[rb]
    assert not on_dead.any(), f"{int(on_dead.sum())} replicas left on dead brokers"
    assert not (np.asarray(st.replica_offline) & valid).any(), \
        "offline replicas remain after optimization"


_DIST_GOAL_BY_RESOURCE = {
    0: "CpuUsageDistributionGoal",
    1: "NetworkInboundUsageDistributionGoal",
    2: "NetworkOutboundUsageDistributionGoal",
    3: "DiskUsageDistributionGoal",
}


def verify_no_regression(res) -> None:
    """Distribution statistics must not regress (OptimizationVerifier
    :94-117: every goal's stats-comparator must rate the post state >= the
    pre state). A higher std is only a regression when the owning
    distribution goal also ends VIOLATED — earlier hard goals may legally
    trade balance for feasibility as long as the state stays in-band."""
    before, after = res.stats_before, res.stats_after
    violated = set(res.violated_goals_after)
    for r, goal_name in _DIST_GOAL_BY_RESOURCE.items():
        if not before["std"] or goal_name not in {g.name for g in res.goal_results}:
            continue
        b, a = before["std"][r], after["std"][r]
        assert not (a > b * 1.0001 + 1e-6 and goal_name in violated), \
            f"resource {r} std regressed {b:.4f} -> {a:.4f} with {goal_name} violated"
    if "ReplicaDistributionGoal" in {g.name for g in res.goal_results}:
        b, a = before["replica_count_std"], after["replica_count_std"]
        assert not (a > b * 1.0001 + 1e-6
                    and "ReplicaDistributionGoal" in violated), \
            f"replica-count std regressed {b:.4f} -> {a:.4f} while violated"
    assert after["num_offline_replicas"] <= before["num_offline_replicas"]


def verify(ct, meta, res, verifications=("REGRESSION",)) -> None:
    for v in verifications:
        if v == "NEW_BROKERS":
            verify_new_brokers(ct, meta, res)
        elif v == "BROKEN_BROKERS":
            verify_broken_brokers(ct, meta, res)
        elif v == "REGRESSION":
            verify_no_regression(res)
        else:
            raise ValueError(f"unknown verification {v}")
