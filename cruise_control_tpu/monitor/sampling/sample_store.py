"""SampleStore SPI + file-backed implementation.

Reference: monitor/sampling/SampleStore.java with KafkaSampleStore (default:
persists samples to two Kafka topics __KafkaCruiseControlPartitionMetricSamples
/ __KafkaCruiseControlModelTrainingSamples and replays them on startup — the
system's durable-history "checkpoint", SURVEY §5) plus NoopSampleStore.

FileSampleStore keeps the same contract against the local filesystem: append
JSONL shards, replay on startup to rebuild aggregation windows.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Protocol

from cruise_control_tpu.monitor.sampling.samplers import (
    BrokerSample, PartitionSample, Samples,
)


class SampleStore(Protocol):
    def configure(self, config, **extra) -> None: ...

    def store_samples(self, samples: Samples) -> None: ...

    def load_samples(self, loader) -> int: ...

    def close(self) -> None: ...


class NoopSampleStore:
    def configure(self, config, **extra):
        pass

    def store_samples(self, samples: Samples) -> None:
        pass

    def load_samples(self, loader) -> int:
        return 0

    def close(self):
        pass


class FileSampleStore:
    """Durable JSONL store. One file per sample kind; appends are fsync-free
    (the reference relies on Kafka's durability; we rely on the page cache —
    the data is reconstructible telemetry, not source of truth)."""

    PARTITION_FILE = "partition_samples.jsonl"
    BROKER_FILE = "broker_samples.jsonl"

    def __init__(self, path: str | None = None):
        self._path = path
        self._lock = threading.Lock()
        self._pf = None
        self._bf = None

    def configure(self, config, **extra):
        path = extra.get("path") or (config.get_string("sample.store.path")
                                     if config is not None else "")
        if path:
            self._path = path
        if self._path:
            os.makedirs(self._path, exist_ok=True)

    def _open(self):
        if self._pf is None and self._path:
            self._pf = open(os.path.join(self._path, self.PARTITION_FILE), "a")
            self._bf = open(os.path.join(self._path, self.BROKER_FILE), "a")

    def store_samples(self, samples: Samples) -> None:
        if not self._path:
            return
        with self._lock:
            self._open()
            for s in samples.all_partition_samples():
                self._pf.write(json.dumps({"t": s.topic, "p": s.partition,
                                           "ts": s.ts_ms, "v": s.values}) + "\n")
            for s in samples.broker_samples:
                self._bf.write(json.dumps({"b": s.broker_id, "ts": s.ts_ms,
                                           "v": s.values}) + "\n")
            self._pf.flush()
            self._bf.flush()

    def load_samples(self, loader) -> int:
        """Replay persisted samples through ``loader(samples)`` in batches
        (SampleLoadingTask role). Returns the number of samples replayed."""
        if not self._path:
            return 0
        n = 0
        ppath = os.path.join(self._path, self.PARTITION_FILE)
        bpath = os.path.join(self._path, self.BROKER_FILE)
        batch: list[PartitionSample] = []
        if os.path.exists(ppath):
            with open(ppath) as f:
                for line in f:
                    try:
                        d = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn tail write
                    batch.append(PartitionSample(topic=d["t"], partition=d["p"],
                                                 ts_ms=d["ts"], values=d["v"]))
                    n += 1
        bbatch: list[BrokerSample] = []
        if os.path.exists(bpath):
            with open(bpath) as f:
                for line in f:
                    try:
                        d = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    bbatch.append(BrokerSample(broker_id=d["b"], ts_ms=d["ts"],
                                               values=d["v"]))
                    n += 1
        if batch or bbatch:
            loader(Samples(batch, bbatch))
        return n

    def close(self):
        with self._lock:
            if self._pf:
                self._pf.close()
                self._pf = None
            if self._bf:
                self._bf.close()
                self._bf = None


class TopicSampleStore:
    """Sample store over the metrics-topic transport — the KafkaSampleStore
    shape: one topic per sample kind (__KafkaCruiseControlPartitionMetricSamples
    / __KafkaCruiseControlModelTrainingSamples), produced on store, consumed
    from offset 0 on startup replay. Uses the same length-prefixed log-file
    topic as the reporter (reporter/topic.FileMetricsTopic), so durability and
    replay semantics match the reporter pipeline's."""

    PARTITION_TOPIC = "__KafkaCruiseControlPartitionMetricSamples"
    BROKER_TOPIC = "__KafkaCruiseControlModelTrainingSamples"

    def __init__(self, path: str | None = None):
        self._path = path
        self._ptopic = None
        self._btopic = None

    def configure(self, config, **extra):
        path = extra.get("path") or (config.get_string("sample.store.path")
                                     if config is not None else "")
        if path:
            self._path = path
        if self._path:
            from cruise_control_tpu.reporter.topic import FileMetricsTopic
            os.makedirs(self._path, exist_ok=True)
            self._ptopic = FileMetricsTopic(
                os.path.join(self._path, self.PARTITION_TOPIC))
            self._btopic = FileMetricsTopic(
                os.path.join(self._path, self.BROKER_TOPIC))

    def store_samples(self, samples: Samples) -> None:
        if self._ptopic is None:
            return
        if samples.num_partition_samples():
            self._ptopic.append([
                json.dumps({"t": s.topic, "p": s.partition, "ts": s.ts_ms,
                            "v": s.values}).encode("utf-8")
                for s in samples.all_partition_samples()])
        if samples.broker_samples:
            self._btopic.append([
                json.dumps({"b": s.broker_id, "ts": s.ts_ms,
                            "v": s.values}).encode("utf-8")
                for s in samples.broker_samples])

    def load_samples(self, loader) -> int:
        if self._ptopic is None:
            return 0
        psamples = []
        for _off, rec in self._ptopic.consume(0):
            try:
                d = json.loads(rec)
            except json.JSONDecodeError:
                continue
            psamples.append(PartitionSample(topic=d["t"], partition=d["p"],
                                            ts_ms=d["ts"], values=d["v"]))
        bsamples = []
        for _off, rec in self._btopic.consume(0):
            try:
                d = json.loads(rec)
            except json.JSONDecodeError:
                continue
            bsamples.append(BrokerSample(broker_id=d["b"], ts_ms=d["ts"],
                                         values=d["v"]))
        if psamples or bsamples:
            loader(Samples(psamples, bsamples))
        return len(psamples) + len(bsamples)

    def close(self):
        pass


class ReadOnlyTopicSampleStore(TopicSampleStore):
    """Replays history but never produces — for standby/analysis instances
    pointed at another instance's topics (ReadOnlyKafkaSampleStore role)."""

    def store_samples(self, samples: Samples) -> None:
        pass


class OnExecutionSampleStore(TopicSampleStore):
    """Records partition samples only while an execution is in progress, to a
    dedicated topic (KafkaPartitionMetricSampleOnExecutionStore role) — a
    post-mortem trail of load during movement."""

    PARTITION_TOPIC = "__KafkaCruiseControlPartitionMetricSamplesOnExecution"

    def __init__(self, path: str | None = None, executor=None):
        super().__init__(path)
        self._executor = executor

    def configure(self, config, **extra):
        if "executor" in extra:
            self._executor = extra["executor"]
        super().configure(config, **extra)

    def store_samples(self, samples: Samples) -> None:
        if self._executor is not None and not self._executor.has_ongoing_execution():
            return
        super().store_samples(
            Samples(samples.partition_samples, [],
                    partition_blocks=list(samples.partition_blocks)))
