"""HaScenarioRunner: two controllers, one simulated backend, leader kill.

Extends the deterministic scenario loop with the HA controller pair from
``cruise_control_tpu.ha``:

- the **leader** is the base runner's facade (``self.cc``), configured with
  a durable file journal (``journal.fsync=always``) and a FileSampleStore —
  the two artifacts a real standby would tail across processes;
- a **standby** facade is built over the SAME ``SimulatedClusterBackend``
  (same metadata/metric oracle, its own monitor/analyzer/executor state),
  kept warm by a :class:`~cruise_control_tpu.ha.standby.StandbyController`
  tailing the leader's journal in-process and its sample store on disk;
- both run a :class:`~cruise_control_tpu.ha.lease.LeaderElector` against
  the backend's CAS lease, ticked on the scenario grid.

The ``leader_kill`` scenario event freezes the leader exactly like a
process death: ``Executor.kill()`` makes the next executor loop iteration
raise without running ANY cleanup (no throttle removal, no state reset, no
journal span-end), and the runner stops driving the leader's control loop.
The lease then lapses on the backend clock, the standby's CAS acquire
succeeds, and ``StandbyController.promote()`` adopts the frozen task census
— in-flight reassignments (still progressing inside the backend) resume
mid-batch with zero aborts. From the promotion tick on, the base loop's
``_drive_tick`` drives the promoted facade, so detection/heal continue on
the survivor.

Failover SLOs (all on simulated time, measured from the kill instant) land
in ``ScenarioResult.failover``: detect-lease-loss, promote, first-proposal,
adopted task counts. :func:`failover_parity_failures` is the campaign's
certification check — the promoted run must converge to the same verdict
set and the same final ground-truth assignment as a single-controller run
of the identical (scenario, seed) with the kill stripped.
"""
from __future__ import annotations

import dataclasses
import os
import tempfile

from cruise_control_tpu.executor.executor import ExecutorKilledError
from cruise_control_tpu.sim.runner import BASE_CONFIG, ScenarioRunner

# config keys the HA runner injects for the leader only; stripped from the
# recorded replay payload (the paths are process-dependent temp dirs — a
# replay injects its own)
_INJECTED_PATH_KEYS = ("journal.path", "sample.store.path")


def final_assignment(backend) -> dict:
    """Ground-truth ``{"topic-p": [leader, sorted replicas]}`` snapshot —
    the object failover parity compares across runs."""
    return {f"{t}-{p}": [info.leader, sorted(info.replicas)]
            for (t, p), info in sorted(backend.partitions().items())}


def verdict_set(result) -> set:
    """The run's anomaly verdicts as an order-free set of (type, action)."""
    return {(e["type"], e["action"]) for e in result.timeline
            if e["kind"] == "anomaly"}


def failover_parity_failures(ha_result, solo_result) -> list:
    """Certification: the HA run (leader killed mid-heal, standby promoted)
    must be outcome-equivalent to the single-controller run of the same
    (scenario, seed). Returns failure strings (empty = parity holds)."""
    out = []
    fo = ha_result.failover
    if not fo.get("promoted"):
        out.append("standby never promoted after leader kill")
        return out
    if fo.get("aborted_tasks", 0):
        out.append(f"{fo['aborted_tasks']} tasks aborted/dead on the "
                   "promoted controller — failover must adopt, not abort")
    hv, sv = verdict_set(ha_result), verdict_set(solo_result)
    if hv != sv:
        out.append(f"verdict sets diverge: ha-only={sorted(hv - sv)} "
                   f"solo-only={sorted(sv - hv)}")
    if ha_result.converged != solo_result.converged:
        out.append(f"convergence diverges: ha={ha_result.converged} "
                   f"solo={solo_result.converged}")
    if ha_result.final_assignment != solo_result.final_assignment:
        diff = [tp for tp in (set(ha_result.final_assignment)
                              | set(solo_result.final_assignment))
                if ha_result.final_assignment.get(tp)
                != solo_result.final_assignment.get(tp)]
        out.append(f"final assignments diverge on {len(diff)} partitions "
                   f"(first: {sorted(diff)[:3]})")
    return out


class HaScenarioRunner(ScenarioRunner):
    """Leader + warm standby over one backend; handles ``leader_kill``."""

    def __init__(self, scenario, seed: int = 0, **kw):
        if kw.get("pipelined"):
            raise ValueError("HaScenarioRunner drives the blocking loop; "
                             "pipelined mode is single-controller only")
        self._ha_dir = tempfile.mkdtemp(prefix="cc_sim_ha_")
        cfg = dict(scenario.config_dict())
        cfg["journal.path"] = os.path.join(self._ha_dir, "journal.jsonl")
        cfg.setdefault("journal.fsync", "always")
        cfg["sample.store.path"] = os.path.join(self._ha_dir, "samples")
        scenario = dataclasses.replace(scenario,
                                       config=tuple(sorted(cfg.items())))
        super().__init__(scenario, seed=seed, **kw)
        self.leader_cc = None
        self.standby_cc = None
        self.standby = None
        self._leader_elector = None
        self._leader_dead = False
        self._promoted = False
        self._kill_ms: float | None = None
        self._first_proposal_ms: float | None = None

    # ------------------------------------------------------------- wiring
    def _build(self):
        from cruise_control_tpu.app import CruiseControl
        from cruise_control_tpu.config import cruise_control_config
        from cruise_control_tpu.ha import LeaderElector, StandbyController

        super()._build()
        # replay payload determinism: drop the injected temp-dir paths
        self.result.scenario_spec["config"] = [
            [k, v] for k, v in self.result.scenario_spec["config"]
            if k not in _INJECTED_PATH_KEYS]
        self.leader_cc = self.cc
        self._leader_elector = LeaderElector.from_config(
            self.backend, "cc-a", self.leader_cc.config,
            journal=self.leader_cc.journal, sensors=self.leader_cc.sensors)
        self.leader_cc.ha = self._leader_elector
        if self._leader_elector.tick() != "leader":
            raise RuntimeError("initial election lost on a free lease")
        # the standby facade: SAME backend, its own in-memory journal, no
        # sample store of its own — state arrives only via the tails, which
        # is what makes the bit-identity claim meaningful
        props = dict(BASE_CONFIG)
        props.update(self.scenario.config_dict())
        props["journal.path"] = ""
        props["journal.fsync"] = "never"
        props["sample.store.path"] = ""
        self.standby_cc = CruiseControl(self.backend,
                                        cruise_control_config(props))
        self.standby_cc.start_up()
        self._attach_verifier(self.standby_cc)

        def _first_prop(operation, reason, res, executed):
            if self._promoted and self._first_proposal_ms is None:
                self._first_proposal_ms = float(self._now())
        self.standby_cc.optimization_observers.append(_first_prop)

        elector = LeaderElector.from_config(
            self.backend, "cc-b", self.standby_cc.config,
            journal=self.standby_cc.journal, sensors=self.standby_cc.sensors)
        self.standby = StandbyController(
            self.standby_cc,
            leader_journal=self.leader_cc.journal,
            leader_sample_path=os.path.join(self._ha_dir, "samples"),
            elector=elector,
            sync_interval_ms=self.scenario.tick_ms)

    # ----------------------------------------------------------- the events
    def _fire_custom(self, ev, now: float) -> None:
        if ev.kind != "leader_kill":
            super()._fire_custom(ev, now)
            return
        # process death, not shutdown: the executor freezes without cleanup
        # (throttles stay set, the census stays open in the journal), and
        # this runner never ticks the leader's loop or elector again — so
        # the lease lapses on the backend clock
        self._kill_ms = now
        self._leader_dead = True
        self.leader_cc.executor.kill()

    # ------------------------------------------------------------- the loop
    def _drive_tick(self, now: float) -> None:
        if not self._leader_dead:
            self._leader_elector.tick()
            try:
                super()._drive_tick(now)
            except ExecutorKilledError:
                # leader_kill fired inside this tick's blocking heal: the
                # leader "process" is gone mid-execution, exactly the
                # mid-batch freeze the standby must adopt
                self._record("leader_dead", self._now())
            else:
                # a blocking heal can swallow many renew intervals of
                # simulated time; re-assert the lease the moment it returns
                # (re-acquiring an expired lease you still own is legal CAS)
                # so the standby can only win while the leader is truly dead
                self._leader_elector.tick()
        elif self._promoted:
            # the survivor leads now: SAME lease discipline as the original
            # leader — renew on the grid, and re-assert the moment a
            # blocking heal (which can swallow many renew intervals of
            # simulated time) returns. standby.tick() with role=='leader'
            # ticks the elector; without this the promoted node's lease
            # would lapse and a restarted contender could split-brain it.
            self.standby.tick()
            super()._drive_tick(now)
            out = self.standby.tick()
            if out.get("demoted"):
                # impossible while the old leader stays dead; surfaced in
                # the timeline (and by convergence failing) if it ever fires
                self._record("ha_demoted", self._now())
        if not self._promoted:
            out = self.standby.tick()
            if out.get("promoted"):
                self._promoted = True
                self.cc = self.standby_cc          # the loop follows the survivor
                self._provision_cursor = 0
                self._record("ha_promoted", self._now(),
                             adoption=out.get("adoption"))

    def _extra_convergence_checks(self) -> list:
        out = super()._extra_convergence_checks()
        if self._kill_ms is not None:
            # certification gates after a kill: the standby must take over,
            # and the SURVIVOR must re-run detection all the way to its own
            # FIX verdict on the original fault before the episode settles —
            # adoption alone (finishing the dead leader's batch) is not
            # "resumed detection and optimization"
            if not self._promoted:
                out.append("standby not promoted after leader kill yet")
            else:
                t_prom = self.standby.promoted_ms - self._t0
                if not any(e["kind"] == "anomaly" and e["action"] == "FIX"
                           and e["t"] >= round(t_prom, 1)
                           for e in self.result.timeline):
                    out.append("promoted controller has not passed a FIX "
                               "verdict post-takeover yet")
        return out

    # ------------------------------------------------------------- finalize
    def _finalize(self, heal_candidate_ms) -> None:
        if self._kill_ms is not None:
            fo = {"promoted": self._promoted}
            el = self.standby.elector
            if el.elected_ms is not None:
                fo["detect_lease_loss_ms"] = round(
                    el.elected_ms - self._kill_ms, 1)
            if self.standby.promoted_ms is not None:
                fo["promote_ms"] = round(
                    self.standby.promoted_ms - self._kill_ms, 1)
            if self._first_proposal_ms is not None:
                fo["first_proposal_ms"] = round(
                    self._first_proposal_ms - self._kill_ms, 1)
            adoption = self.standby.adoption or {}
            fo["adopted_tasks"] = adoption.get("adopted", 0)
            fo["adopted_in_flight"] = adoption.get("inFlight", 0)
            fo["journal_lag_events"] = self.standby.journal_lag_events()
            fo["dropped_events"] = self.standby.dropped_events
            by_state = self.standby_cc.executor.state_json().get(
                "numTasksByState", {})
            fo["aborted_tasks"] = int(by_state.get("ABORTED", 0)
                                      + by_state.get("ABORTING", 0)
                                      + by_state.get("DEAD", 0))
            self.result.failover = fo
        super()._finalize(heal_candidate_ms)
        # the base finalize shut down ``self.cc`` (the survivor); release the
        # other facade's resources too — the dead leader's journal file
        # handle and sample store, or the never-promoted standby
        for cc in (self.leader_cc, self.standby_cc):
            if cc is not None and cc is not self.cc:
                try:
                    cc.shutdown()
                except Exception:
                    pass


def run_ha_scenario(scenario, seed: int = 0):
    """Build + run one scenario under the leader/standby pair."""
    return HaScenarioRunner(scenario, seed=seed).run()
