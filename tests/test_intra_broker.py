"""Intra-broker (JBOD) goal tests.

Reference test role: IntraBrokerDiskCapacityGoalTest /
DeterministicClusterTest JBOD variants (common/DeterministicCluster JBOD
fixtures) — dead-disk healing, per-logdir capacity, intra-broker balance,
executed through the intra-broker phase.
"""
import numpy as np
import pytest

# engine-path compile-heavy; the fast tier (-m 'not slow') covers the engine via
# test_model/test_analyzer_goals/test_optimizer
pytestmark = pytest.mark.slow

from cruise_control_tpu.analyzer import init_state, make_env
from cruise_control_tpu.analyzer.engine import EngineParams, optimize_goal
from cruise_control_tpu.analyzer.goals import make_goal
from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
from cruise_control_tpu.model.builder import ClusterModelBuilder


def _jbod_cluster(dead_disk=False, overfull=False):
    """2 brokers x 3 logdirs. Broker 0's disk0 is crowded; optionally dead or
    over capacity."""
    b = ClusterModelBuilder()
    for i in range(2):
        b.add_broker(i, rack=f"r{i}",
                     logdirs=["/d0", "/d1", "/d2"],
                     disk_capacity=[1000.0, 1000.0, 1000.0],
                     capacity={3: 3000.0},
                     dead_disks={"/d0"} if (dead_disk and i == 0) else set())
    p = 0
    # 6 partitions RF=2, all of broker 0's replicas on /d0
    for p in range(6):
        size = 300.0 if overfull else 120.0
        b.add_replica("t", p, 0, is_leader=True,
                      load=[1.0, 10.0, 20.0, size], logdir="/d0",
                      offline=(dead_disk))
        b.add_replica("t", p, 1, is_leader=False,
                      load=[1.0, 10.0, 20.0, size], logdir=f"/d{p % 3}")
    return b.build()


def _run(goal_name, ct, meta, prev=()):
    env = make_env(ct, meta)
    st = init_state(env, ct.replica_broker, ct.replica_is_leader,
                    ct.replica_offline, ct.replica_disk)
    goal = make_goal(goal_name)
    prev_goals = tuple(make_goal(n) for n in prev)
    st2, info = optimize_goal(env, st, goal, prev_goals,
                              EngineParams(max_iters=64))
    return env, st, st2, info


def test_capacity_goal_moves_replicas_off_overfull_disk():
    ct, meta = _jbod_cluster(overfull=True)   # 6*300=1800 on a 1000-cap disk
    env, st0, st, info = _run("IntraBrokerDiskCapacityGoal", ct, meta)
    assert not bool(info["violated_after"])
    # no replica left its broker: intra-broker goals only move between disks
    np.testing.assert_array_equal(np.asarray(st.replica_broker),
                                  np.asarray(st0.replica_broker))
    du = np.asarray(st.disk_util)
    assert (du[0] <= 0.8 * 1000.0 + 100.0).all()
    # total disk load per broker unchanged
    np.testing.assert_allclose(du[0].sum(), 1800.0, rtol=1e-5)


def test_capacity_goal_heals_dead_disk():
    ct, meta = _jbod_cluster(dead_disk=True)
    env, st0, st, info = _run("IntraBrokerDiskCapacityGoal", ct, meta)
    assert not bool(info["violated_after"])
    du = np.asarray(st.disk_util)
    assert du[0, 0] == pytest.approx(0.0, abs=1e-6)   # dead disk drained
    # healed replicas are no longer offline and stayed on broker 0
    rd = np.asarray(st.replica_disk)
    rb = np.asarray(st.replica_broker)
    off = np.asarray(st.replica_offline)
    b0 = rb == 0
    assert not off[b0 & np.asarray(env.replica_valid)].any()
    assert (rd[b0 & np.asarray(env.replica_valid)] != 0).all()


def test_distribution_goal_balances_disks_within_broker():
    ct, meta = _jbod_cluster()                # 720 MB all on broker0:/d0
    env, st0, st, info = _run("IntraBrokerDiskUsageDistributionGoal", ct, meta)
    assert not bool(info["violated_after"])
    np.testing.assert_array_equal(np.asarray(st.replica_broker),
                                  np.asarray(st0.replica_broker))
    du = np.asarray(st.disk_util)
    # broker 0 disks within the band around its 24% average (1.1 thresh, 0.9 margin)
    pct = du[0] / 1000.0
    avg = pct.mean()
    assert pct.max() <= avg * 1.09 + 1e-3
    # the violation measure is (near) zero once balanced
    assert float(info["stat"]) <= 1e-3


def test_capacity_accept_vetoes_overfilling_disk_move():
    """As a previously-optimized goal, IntraBrokerDiskCapacityGoal vetoes
    distribution moves that would overfill a logdir."""
    ct, meta = _jbod_cluster(overfull=True)
    env, st0, st, info = _run("IntraBrokerDiskUsageDistributionGoal", ct, meta,
                              prev=("IntraBrokerDiskCapacityGoal",))
    du = np.asarray(st.disk_util)
    assert (du[0] <= 0.8 * 1000.0 + 100.0 + 1e-3).all()


def test_optimizer_chain_emits_intra_broker_proposals():
    ct, meta = _jbod_cluster(overfull=True)
    opt = GoalOptimizer()
    res = opt.optimizations(ct, meta,
                            goal_names=["IntraBrokerDiskCapacityGoal",
                                        "IntraBrokerDiskUsageDistributionGoal"],
                            skip_hard_goal_check=True)
    assert "IntraBrokerDiskCapacityGoal" not in res.violated_goals_after
    assert res.proposals
    for p in res.proposals:
        old_brokers = [b for b, _ in p.old_replicas]
        new_brokers = [b for b, _ in p.new_replicas]
        assert old_brokers == new_brokers          # intra-broker: disk only
        assert any(od != nd for (_, od), (_, nd)
                   in zip(p.old_replicas, p.new_replicas))


def test_rebalance_disk_end_to_end():
    """POST /rebalance?rebalance_disk=true against the simulated backend:
    executed through the executor's intra-broker phase."""
    from cruise_control_tpu.app import CruiseControl
    from cruise_control_tpu.backend import SimulatedClusterBackend
    from cruise_control_tpu.config import cruise_control_config
    be = SimulatedClusterBackend()
    for i in range(2):
        be.add_broker(i, f"r{i}", logdirs={"/d0": 1000.0, "/d1": 1000.0,
                                           "/d2": 1000.0})
    for p in range(6):
        # all of broker 0's replicas land on /d0
        be.create_partition("t", p, [0, 1], size_mb=250.0, bytes_in_rate=10.0,
                            bytes_out_rate=20.0, cpu_util=1.0,
                            logdir_by_broker={0: "/d0", 1: f"/d{p % 3}"})
    cc = CruiseControl(be, cruise_control_config({
        "num.metrics.windows": 5, "min.samples.per.metrics.window": 1}))
    cc.start_up()
    for i in range(8):
        cc.load_monitor.sample_once(now_ms=i * 300_000.0)
    out = cc.rebalance(rebalance_disk=True, dry_run=False)
    assert out["executed"] is True
    # the backend's logdir layout actually changed: /d0 no longer over 80%
    used = {ld: 0.0 for ld in ("/d0", "/d1", "/d2")}
    for (t, p), info in be.partitions().items():
        ld = info.logdir_by_broker.get(0)
        if ld is not None:
            used[ld] += info.size_mb
    assert used["/d0"] <= 0.8 * 1000.0 + 100.0


def test_distribution_goal_fills_underutilized_disk():
    """Regression: a below-lower-band logdir must be fillable by draining
    in-band above-average disks (not only above-upper ones)."""
    b = ClusterModelBuilder()
    b.add_broker(0, rack="r0", logdirs=[f"/d{i}" for i in range(4)],
                 disk_capacity=[1000.0] * 4, capacity={3: 4000.0})
    b.add_broker(1, rack="r1", logdirs=["/d0"], disk_capacity=[1000.0])
    p = 0
    # disks 0-2 at 550 MB (many small replicas), disk 3 at 100 MB
    for d in range(3):
        for _ in range(11):
            b.add_replica("t", p, 0, is_leader=True,
                          load=[0.1, 1.0, 1.0, 50.0], logdir=f"/d{d}")
            b.add_replica("t", p, 1, is_leader=False,
                          load=[0.1, 1.0, 1.0, 50.0])
            p += 1
    for _ in range(2):
        b.add_replica("t", p, 0, is_leader=True,
                      load=[0.1, 1.0, 1.0, 50.0], logdir="/d3")
        p += 1
    ct, meta = b.build()
    env, st0, st, info = _run("IntraBrokerDiskUsageDistributionGoal", ct, meta)
    du0 = np.asarray(st0.disk_util)[0]
    du1 = np.asarray(st.disk_util)[0]
    assert du1.std() < du0.std()          # cold disk got filled
    assert du1[3] > du0[3]
    assert not bool(info["violated_after"])
