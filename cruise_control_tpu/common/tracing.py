"""Flight recorder: always-on per-round traces + runtime compile sensors.

The reference's operability rests on its Dropwizard sensor catalog
(proposal-computation-timer, cluster-model-creation-timer, per-endpoint
request timers — docs/wiki Sensors.md); what it cannot answer is "what did
THIS proposal round spend its time on?". Until now neither could we: per-stage
timing, XLA compile events and device memory were only visible through
``bench.py``'s private bookkeeping or the blocking ``CC_PROFILE_SEGMENTS``
debug hack. This module is the library-level answer:

- :class:`RoundTrace` — one record per optimization round, assembled from data
  the engine already computes (per-goal ``GoalResult`` counters, the pass
  profile, session sync mode/seconds/donation, the last sampling round's
  seconds, XLA compile count delta, env/state device bytes). Assembly costs a
  few dict builds and ``nbytes`` reads on device-array *metadata* — no
  synchronization, no device copies, so the async dispatch pipeline and the
  donation protocol are untouched.
- :class:`FlightRecorder` — a bounded thread-safe ring buffer of traces,
  served by ``/state?substates=ROUND_TRACES`` and snapshotted by ``bench.py``
  and the sim ``ScenarioRunner`` (one schema everywhere).
- :class:`XlaCompileListener` — promotes bench-only compile counting to a
  library-level sensor: a process-wide ``jax.monitoring`` duration listener
  counting backend compiles (a persistent-cache hit deserializes and does NOT
  count — exactly the "new executable built" semantics the zero-new-compile
  contracts assert).
- :class:`CompileCounter` / :func:`count_compiles` — the log-record-based
  counter bench.py used to carry privately; kept because its semantics
  ("Compiling ..." records, which include cache-served compiles) are what the
  BENCH_* trajectory files were measured with.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from contextlib import contextmanager

DEFAULT_CAPACITY = 64

# jax.monitoring event emitted once per XLA backend compile (not emitted when
# the persistent compilation cache serves the executable)
_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


# ---------------------------------------------------------------------------
# compile sensors
# ---------------------------------------------------------------------------
class XlaCompileListener:
    """Process-wide XLA compile counter (jax.monitoring based).

    ``install()`` registers the jax.monitoring listener once per process and
    returns the singleton; every GoalOptimizer construction calls it, so any
    process that optimizes — the service, the sim runner, bench — carries the
    sensor. Reads are cheap ints; the flight recorder uses count deltas to
    attribute compiles to rounds, and the registry exposes the running totals
    as ``xla-compile-count`` / ``xla-compile-seconds`` gauges.
    """

    _instance: "XlaCompileListener | None" = None
    _install_lock = threading.Lock()

    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._seconds = 0.0

    @classmethod
    def install(cls) -> "XlaCompileListener":
        with cls._install_lock:
            if cls._instance is None:
                inst = cls()
                import jax.monitoring

                def on_duration(name: str, secs: float, **kw) -> None:
                    if name == _BACKEND_COMPILE_EVENT:
                        with inst._lock:
                            inst._count += 1
                            inst._seconds += float(secs)

                jax.monitoring.register_event_duration_secs_listener(
                    on_duration)
                cls._instance = inst
            return cls._instance

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def seconds(self) -> float:
        with self._lock:
            return self._seconds

    def register_gauges(self, sensors) -> None:
        sensors.gauge("xla-compile-count", lambda: self.count)
        sensors.gauge("xla-compile-seconds", lambda: round(self.seconds, 3))


class CompileCounter:
    """Counts XLA compiles during a phase via jax_log_compiles records
    (the counter bench.py carried privately; semantics preserved: counts
    "Compiling ..." log records, which fire even when the persistent cache
    serves the executable)."""

    def __init__(self):
        import logging

        class _H(logging.Handler):
            def __init__(self, outer):
                super().__init__(level=logging.DEBUG)
                self._outer = outer

            def emit(self, record):
                try:
                    if "Compiling" in record.getMessage():
                        self._outer.count += 1
                except Exception:  # noqa: BLE001 — counting must never break a run
                    pass

        self.count = 0
        self._handler = _H(self)

    @property
    def handler(self):
        return self._handler


@contextmanager
def count_compiles():
    """``with count_compiles() as c: ...; c.count`` — the bench.py phase
    counter, now shared library code."""
    import logging

    import jax
    prev = bool(jax.config.jax_log_compiles)
    counter = CompileCounter()
    jax.config.update("jax_log_compiles", True)
    jax_logger = logging.getLogger("jax")
    jax_logger.addHandler(counter.handler)
    try:
        yield counter
    finally:
        jax_logger.removeHandler(counter.handler)
        jax.config.update("jax_log_compiles", prev)


def tree_device_bytes(tree) -> int:
    """Exact leaf-sum bytes of a device pytree — array METADATA only (no
    transfer, no block): safe on in-flight/donated-lineage buffers."""
    import jax
    return int(sum(x.nbytes for x in jax.tree_util.tree_leaves(tree)
                   if hasattr(x, "nbytes")))


# ---------------------------------------------------------------------------
# round traces
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class RoundTrace:
    """One optimization round, flight-recorder schema (all host-side data the
    round computed anyway; per-goal seconds are honest only at
    ``analyzer.profile.level=stage`` or ``measure_goal_durations=True`` —
    ``durations_measured`` says which)."""
    round_id: int
    ts_ms: float
    operation: str | None           # REBALANCE / PROPOSALS / FIX_* / None
    wall_s: float                   # whole optimizations() call
    sampling_s: float | None        # last noted monitor sampling round
    sync_mode: str | None           # resident session: "delta" | "rebuild"
    sync_s: float | None
    donated: bool                   # this round took the resident state
    profile_level: str              # off | pass | stage
    durations_measured: bool
    compiles: int                   # XLA backend compiles during the round
    env_bytes: int
    state_bytes: int
    num_proposals: int
    num_replica_movements: int
    num_leadership_movements: int
    goals: list = dataclasses.field(default_factory=list)
    # pipelined-service-loop lanes (PR 11): the ingest/sync/execute stage
    # spans that PREPARED this round (noted by the pipeline before the round
    # ran), each with the seconds it overlapped an in-flight optimize round —
    # the flight-recorder proof that sampling/sync are off the critical path
    stages: list = dataclasses.field(default_factory=list)
    # per-stage summary {stage: {"dur_s", "overlap_s", "overlap_frac"}};
    # empty on the blocking loop (nothing ever overlaps optimize there)
    overlap: dict = dataclasses.field(default_factory=dict)

    def to_json(self) -> dict:
        out = dataclasses.asdict(self)
        out["wall_s"] = round(out["wall_s"], 4)
        return out


def goal_trace_rows(goal_results) -> list[dict]:
    """Per-goal trace rows from GoalResult records — the engine's pass-level
    profile (passes, per-branch action split, admission waves, finisher
    actions) plus the violation flags and (when measured) seconds."""
    return [{
        "name": g.name,
        "duration_s": round(g.duration_s, 4),
        "violated_before": g.violated_before,
        "violated_after": g.violated_after,
        "iterations": g.iterations,
        "passes": g.passes,
        "moves": g.move_actions,
        "leads": g.lead_actions,
        "swaps": g.swap_actions,
        "disk": g.disk_actions,
        "waves": g.move_waves,
        "finisher": g.finisher_actions,
        # segment-parallel finisher phase (PR 7): segments the applied waves
        # spread destinations over (0 = legacy waves) and admitted
        # cross-segment boundary rows re-validated by the budgeted admission
        "fin_segments": getattr(g, "finisher_segments", 0),
        "fin_boundary": getattr(g, "finisher_boundary", 0),
    } for g in goal_results]


class FlightRecorder:
    """Bounded thread-safe ring buffer of :class:`RoundTrace` records.

    Always on and deliberately cheap: ``record`` is a lock + deque append.
    ``clock_ms`` is injectable so traces carry the backend's clock (simulated
    time in the sim; wall time in the service). ``note_sampling`` /
    ``note_operation`` let the layers that know those facts (monitor, facade)
    annotate the NEXT recorded round without the optimizer needing to know
    either — the operation note is thread-local so concurrent user-task
    rounds can't cross-tag each other.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY, clock_ms=None):
        self.capacity = int(capacity)
        self.clock_ms = clock_ms or (lambda: time.time() * 1000.0)
        self._lock = threading.Lock()
        self._traces: deque[RoundTrace] = deque(maxlen=self.capacity)
        self._recorded = 0
        self._next_id = 0
        self._sampling_s: float | None = None
        self._tl = threading.local()
        # pipelined-loop lane bookkeeping: stage spans noted since the last
        # recorded round (they fed the NEXT round), and the monotonic start
        # of the optimize round currently in flight (None = none in flight)
        self._pending_stages: list[dict] = []
        self._opt_t0: float | None = None

    # ------------------------------------------------------------ annotate
    def note_sampling(self, seconds: float) -> None:
        with self._lock:
            self._sampling_s = round(float(seconds), 4)

    def note_operation(self, operation: str) -> None:
        self._tl.operation = operation

    def _take_operation(self) -> str | None:
        op = getattr(self._tl, "operation", None)
        self._tl.operation = None
        return op

    # ------------------------------------------------------ pipeline lanes
    def note_optimize_start(self) -> None:
        """The optimizer marks its round's start so concurrently-noted stage
        spans can measure how much of their wall ran UNDER the in-flight
        round (the pipelined loop's overlap proof)."""
        with self._lock:
            self._opt_t0 = time.monotonic()

    def optimize_in_flight(self) -> bool:
        """True between note_optimize_start and the round's record_round —
        the pipelined loop uses it to sequence its overlapped stages."""
        with self._lock:
            return self._opt_t0 is not None

    def note_stage(self, stage: str, t0: float, t1: float, **extra) -> None:
        """Record one pipeline stage span (monotonic seconds). ``overlap_s``
        is the part of [t0, t1] spent while an optimize round was in flight —
        computed here, at note time, because by the time the round records
        its trace the concurrent span is history. Spans accumulate and attach
        to the NEXT recorded round (the round they prepared)."""
        t0, t1 = float(t0), float(t1)
        with self._lock:
            opt_t0 = self._opt_t0
            now = time.monotonic()
            overlap = 0.0
            if opt_t0 is not None:
                overlap = max(0.0, min(t1, now) - max(t0, opt_t0))
            span = {"stage": stage, "dur_s": round(max(t1 - t0, 0.0), 4),
                    "overlap_s": round(overlap, 4)}
            span.update(extra)
            self._pending_stages.append(span)
            del self._pending_stages[:-64]   # bounded like the trace ring

    def _take_stages(self) -> tuple[list, dict]:
        """Consume pending stage spans; returns (stages, per-stage overlap
        summary). Caller holds no lock."""
        with self._lock:
            stages = self._pending_stages
            self._pending_stages = []
            self._opt_t0 = None
        summary: dict = {}
        for s in stages:
            agg = summary.setdefault(s["stage"],
                                     {"dur_s": 0.0, "overlap_s": 0.0})
            agg["dur_s"] += s["dur_s"]
            agg["overlap_s"] += s["overlap_s"]
        for agg in summary.values():
            agg["dur_s"] = round(agg["dur_s"], 4)
            agg["overlap_s"] = round(agg["overlap_s"], 4)
            agg["overlap_frac"] = round(
                agg["overlap_s"] / agg["dur_s"], 4) if agg["dur_s"] else 0.0
        return stages, summary

    # -------------------------------------------------------------- record
    def next_round_id(self) -> int:
        with self._lock:
            rid = self._next_id
            self._next_id += 1
            return rid

    def record(self, trace: RoundTrace) -> None:
        with self._lock:
            self._traces.append(trace)
            self._recorded += 1

    def record_round(self, *, wall_s: float, goal_results, compiles: int,
                     env, state, num_proposals: int,
                     num_replica_movements: int,
                     num_leadership_movements: int,
                     session_info: dict | None = None, donated: bool = False,
                     profile_level: str = "off",
                     durations_measured: bool = False) -> RoundTrace:
        """Assemble + record one round from what the optimizer already holds.
        Never raises into the optimization path."""
        info = session_info or {}
        with self._lock:
            sampling_s = self._sampling_s
        stages, overlap = self._take_stages()
        try:
            trace = RoundTrace(
                round_id=self.next_round_id(),
                ts_ms=float(self.clock_ms()),
                operation=self._take_operation(),
                wall_s=wall_s,
                sampling_s=sampling_s,
                sync_mode=info.get("mode"),
                sync_s=info.get("sync_s"),
                donated=donated,
                profile_level=profile_level,
                durations_measured=durations_measured,
                compiles=int(compiles),
                env_bytes=tree_device_bytes(env),
                state_bytes=tree_device_bytes(state),
                num_proposals=int(num_proposals),
                num_replica_movements=int(num_replica_movements),
                num_leadership_movements=int(num_leadership_movements),
                goals=goal_trace_rows(goal_results),
                stages=stages,
                overlap=overlap,
            )
        except Exception:  # noqa: BLE001 — tracing must never fail a round
            import logging
            logging.getLogger(__name__).exception("round trace assembly failed")
            return None
        self.record(trace)
        return trace

    # ---------------------------------------------------------------- read
    def last(self) -> RoundTrace | None:
        with self._lock:
            return self._traces[-1] if self._traces else None

    def last_json(self) -> dict | None:
        t = self.last()
        return t.to_json() if t is not None else None

    def traces(self) -> list[RoundTrace]:
        with self._lock:
            return list(self._traces)

    def to_json(self) -> dict:
        with self._lock:
            traces = list(self._traces)
            recorded = self._recorded
        return {"capacity": self.capacity, "recorded": recorded,
                "traces": [t.to_json() for t in traces]}

    def register_gauges(self, sensors) -> None:
        """Last-round gauges on the MetricRegistry, so /metrics carries the
        newest round without parsing the trace substate."""
        def field(name, default=0):
            def read():
                t = self.last()
                v = getattr(t, name, None) if t is not None else None
                return default if v is None else v
            return read

        sensors.gauge("round-traces-recorded",
                      lambda: self.to_json()["recorded"])
        sensors.gauge("last-round-wall-seconds", field("wall_s", 0.0))
        sensors.gauge("last-round-sampling-seconds", field("sampling_s", 0.0))
        sensors.gauge("last-round-sync-seconds", field("sync_s", 0.0))
        sensors.gauge("last-round-compiles", field("compiles"))
        sensors.gauge("last-round-env-bytes", field("env_bytes"))
        sensors.gauge("last-round-state-bytes", field("state_bytes"))
        sensors.gauge("last-round-proposals", field("num_proposals"))


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------
def _prom_name(name: str, suffix: str = "") -> str:
    import re
    base = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    if base and base[0].isdigit():
        base = "_" + base
    return f"cc_{base}{suffix}"


def _fmt(v) -> str:
    f = float(v)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    return repr(f)


def render_prometheus(registry_json: dict) -> str:
    """Render one MetricRegistry snapshot (``MetricRegistry.to_json()``) in
    Prometheus text exposition format 0.0.4.

    Timers render as summaries (quantiles + _sum/_count) plus a ``_max``
    gauge; meters as a ``_total`` counter plus a one-minute-rate gauge;
    gauges as gauges (non-numeric / errored gauges are skipped — a dead gauge
    must not poison the scrape). The ingest side of this repo already parses
    this family of formats (monitor/sampling/prometheus.py), so a CC instance
    can scrape itself — the round-trip the tests run.
    """
    lines: list[str] = []
    for name in sorted(registry_json):
        snap = registry_json[name]
        kind = snap.get("type")
        if kind == "timer":
            m = _prom_name(name, "_seconds")
            total = snap.get("totalSec",
                             snap.get("meanSec", 0.0) * snap.get("count", 0))
            lines.append(f"# TYPE {m} summary")
            for q, key in (("0.5", "p50Sec"), ("0.95", "p95Sec"),
                           ("0.99", "p99Sec")):
                lines.append(f'{m}{{quantile="{q}"}} {_fmt(snap[key])}')
            lines.append(f"{m}_sum {_fmt(total)}")
            lines.append(f"{m}_count {snap['count']}")
            mx = _prom_name(name, "_seconds_max")
            lines.append(f"# TYPE {mx} gauge")
            lines.append(f"{mx} {_fmt(snap['maxSec'])}")
        elif kind == "meter":
            m = _prom_name(name, "_total")
            lines.append(f"# TYPE {m} counter")
            lines.append(f"{m} {snap['count']}")
            r = _prom_name(name, "_one_minute_rate")
            lines.append(f"# TYPE {r} gauge")
            lines.append(f"{r} {_fmt(snap['oneMinuteRatePerSec'])}")
        elif kind == "gauge":
            if "value" not in snap:
                continue        # errored gauge: skip, never poison the scrape
            try:
                val = _fmt(snap["value"])
            except (TypeError, ValueError):
                continue        # non-numeric gauge (strings etc.)
            m = _prom_name(name)
            lines.append(f"# TYPE {m} gauge")
            lines.append(f"{m} {val}")
    return "\n".join(lines) + "\n"
