import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault('JAX_COMPILATION_CACHE_DIR', '/tmp/jax_cache_cc_tpu')
import jax, jax.numpy as jnp
jax.config.update('jax_compilation_cache_dir', '/tmp/jax_cache_cc_tpu')
import dataclasses
from cruise_control_tpu.model.random_cluster import RandomClusterSpec, generate_scale
from cruise_control_tpu.model.cluster_tensor import pad_cluster
from cruise_control_tpu.analyzer.env import make_env, padded_partition_table, BalancingConstraint, OptimizationOptions
from cruise_control_tpu.analyzer.state import init_state
from cruise_control_tpu.analyzer.goals import make_goals
from cruise_control_tpu.analyzer import engine as E
from cruise_control_tpu.analyzer.optimizer import GoalOptimizer, _budget_scale

shape = sys.argv[1] if len(sys.argv) > 1 else "r3"
if shape == "r3":
    spec = RandomClusterSpec(num_brokers=1000, num_racks=20, num_topics=400,
                             num_partitions=50000, max_replication=3, skew=1.0,
                             seed=3141, target_cpu_util=0.45)
else:
    spec = RandomClusterSpec(num_brokers=7000, num_racks=40, num_topics=2000,
                             num_partitions=500000, max_replication=3, skew=1.0,
                             seed=3142, target_cpu_util=0.45)
ct, meta = generate_scale(spec)
ct, meta = pad_cluster(ct, meta)
opt = GoalOptimizer()
params = opt._scaled_params(ct) if hasattr(opt, '_scaled_params') else None
if params is None:
    params = dataclasses.replace(
        opt._params,
        num_candidates=min(1760, max(64, ct.num_brokers // 4, ct.num_replicas // 64)),
        num_leader_candidates=min(1024, max(32, ct.num_brokers // 8)),
        num_swap_candidates=max(32, ct.num_brokers // 32),
        num_dst_choices=min(128, max(16, ct.num_brokers // 100)),
        tail_pass_budget=min(1024, 64 * _budget_scale(ct.num_replicas) ** 2),
        stall_retries=min(32, 8 * _budget_scale(ct.num_replicas)))
print("R", ct.num_replicas, "B", ct.num_brokers, "K", params.num_candidates,
      "T", params.num_dst_choices, "tail", params.tail_pass_budget, flush=True)
env = make_env(ct, meta, partition_table=padded_partition_table(ct))
st = init_state(env, ct.replica_broker, ct.replica_is_leader,
                ct.replica_offline, ct.replica_disk)
goals = make_goals(["DiskUsageDistributionGoal"], BalancingConstraint(), OptimizationOptions())
goal = goals[0]

zero = jnp.int32(0)
@jax.jit
def one_pass(env, st):
    sev = goal.broker_severity(env, st)
    return E._move_branch_batched(env, st, goal, (), params, sev, zero)

@jax.jit
def one_swap(env, st):
    sev = goal.broker_severity(env, st)
    return E._swap_branch_batched(env, st, goal, (), params, sev, zero)

for name, fn in (("move_pass", one_pass), ("swap_pass", one_swap)):
    t0=time.monotonic(); r = fn(env, st); jax.block_until_ready(r[0].util); tc=time.monotonic()-t0
    t0 = time.monotonic()
    for _ in range(20):
        r = fn(env, st)
    jax.block_until_ready(r[0].util)
    print(f"{name}: compile+1={tc:.2f}s warm={(time.monotonic()-t0)/20*1e3:.1f}ms n={int(r[1])}", flush=True)
