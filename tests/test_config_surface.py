"""Reference config-key surface: aliases, rejections, and consumption.

Every key family added for parity with the reference's ~245-key surface
(config/constants/*.java) must be CONSUMED, not just defined — these tests
drive each family through its consumer: alias folding (ConfigDef.alias_of),
load-time rejection of JVM-only values, CORS / access log / reason-required /
UI serving / parameter+request class overrides in the HTTP server, JWT
cookie+audience+RS256, SPNEGO service principal, trusted-proxy IP allowlist,
min-ISR concurrency backoff, executor notifier, purgatory and user-task
cache caps, and the maintenance idempotence cache.
"""
import base64
import json
import urllib.error
import urllib.request

import pytest

from cruise_control_tpu.config import ConfigException, cruise_control_config
from cruise_control_tpu.config.defaults import (
    CRUISE_CONTROL_CONFIG_DEF, endpoint_config_stem,
)


# ---------------------------------------------------------------- definitions
def test_every_key_read_in_source_is_registered():
    """The inverse of the consumption guard: every config key the source
    tree reads by literal name must be DEFINED in defaults.py (canonical or
    alias). A `config.get_*("some.new.key")` without a matching
    `_D.define(...)` — e.g. an analyzer.pass.* knob added without
    registration — fails this test."""
    import pathlib
    import re

    root = pathlib.Path(__file__).resolve().parents[1] / "cruise_control_tpu"
    pat = re.compile(
        r"""\.get_(?:int|long|boolean|double|string|list|"""
        r"""configured_instances?)\(\s*\n?\s*["']([a-z0-9._]+)["']""")
    read = set()
    for p in root.rglob("*.py"):
        read |= set(pat.findall(p.read_text()))
    assert len(read) >= 240, "literal-key scan regressed"
    unknown = sorted(read - set(CRUISE_CONTROL_CONFIG_DEF.keys()))
    assert not unknown, (
        f"{len(unknown)} keys read in source but never defined: {unknown}")


def test_pass_gating_keys_defined_with_guardrails():
    """The convergence-gated scheduling family (PR 19): registered, typed,
    defaulted, and validator-guarded."""
    keys = CRUISE_CONTROL_CONFIG_DEF.keys()
    expect = {
        "analyzer.pass.chunk": 8,
        "analyzer.pass.chunk.min.replicas": 8192,
        "analyzer.pass.adaptive.budgets": True,
        "analyzer.pass.adaptive.floor.passes": 4,
        "analyzer.pass.certificate.skip": True,
        "analyzer.pass.goal.shortcircuit": True,
    }
    cfg = cruise_control_config()
    for name, default in expect.items():
        assert name in keys, name
        if isinstance(default, bool):
            assert cfg.get_boolean(name) is default, name
        else:
            assert cfg.get_int(name) == default, name
    # validator floors: a negative chunk is rejected at load time
    with pytest.raises(ConfigException):
        cruise_control_config({"analyzer.pass.chunk": -1})
    with pytest.raises(ConfigException):
        cruise_control_config({"analyzer.pass.adaptive.floor.passes": 0})


def test_fleet_gating_keys_defined_with_guardrails():
    """The ragged fleet gating family (PR 20): registered, BOOLEAN-typed,
    on by default, and type-guarded at load time."""
    keys = CRUISE_CONTROL_CONFIG_DEF.keys()
    expect = {
        "fleet.pass.gating.enabled": True,
        "fleet.pass.compaction.enabled": True,
        "fleet.pass.early.install.enabled": True,
    }
    cfg = cruise_control_config()
    for name, default in expect.items():
        assert name in keys, name
        assert cfg.get_boolean(name) is default, name
    # a non-boolean value is rejected at load time
    with pytest.raises(ConfigException):
        cruise_control_config({"fleet.pass.gating.enabled": "sometimes"})
    # off-toggles load cleanly (the PR 19 parity baseline)
    off = cruise_control_config({"fleet.pass.gating.enabled": False,
                                 "fleet.pass.compaction.enabled": False,
                                 "fleet.pass.early.install.enabled": False})
    for name in expect:
        assert off.get_boolean(name) is False, name


def test_key_surface_size_matches_reference_scale():
    keys = CRUISE_CONTROL_CONFIG_DEF.keys()
    canonical = [k for k in keys.values() if k.alias_of is None]
    # reference: ~245 .define(...) across the 8 constants classes
    assert len(canonical) >= 240, len(canonical)


def test_every_alias_targets_a_canonical_key():
    keys = CRUISE_CONTROL_CONFIG_DEF.keys()
    for k in keys.values():
        if k.alias_of is not None:
            target = keys[k.alias_of]
            assert target.alias_of is None, (k.name, k.alias_of)


def test_alias_read_and_write():
    cfg = cruise_control_config({"num.partition.metrics.windows": 7})
    assert cfg.get_int("num.metrics.windows") == 7
    assert cfg.get_int("num.partition.metrics.windows") == 7
    # reference SSL spelling lands on the PEM keys
    cfg = cruise_control_config({"webserver.ssl.keystore.location": "/c.pem"})
    assert cfg.get_string("webserver.ssl.cert.location") == "/c.pem"
    # failed.brokers.zk.path is accepted as the persistence path
    cfg = cruise_control_config({"failed.brokers.zk.path": "/tmp/fb.json"})
    assert cfg.get_string("failed.brokers.storage.path") == "/tmp/fb.json"


def test_alias_conflict_rejected():
    with pytest.raises(ConfigException):
        cruise_control_config({"num.metrics.windows": 5,
                               "num.partition.metrics.windows": 7})


def test_jvm_only_values_rejected_at_load():
    with pytest.raises(ConfigException):
        cruise_control_config({"zookeeper.security.enabled": True})
    with pytest.raises(ConfigException):
        cruise_control_config({"webserver.ssl.keystore.type": "JKS"})
    with pytest.raises(ConfigException):
        cruise_control_config({"webserver.ssl.include.protocols": "SSLv3"})
    with pytest.raises(ConfigException):
        cruise_control_config({"trusted.proxy.services.ip.regex": "("})


def test_endpoint_parameter_and_request_class_keys_exist():
    from cruise_control_tpu.api.endpoints import EndPoint
    keys = CRUISE_CONTROL_CONFIG_DEF.keys()
    for ep in EndPoint:
        stem = endpoint_config_stem(ep.path)
        assert f"{stem}.parameters.class" in keys, ep
        assert f"{stem}.request.class" in keys, ep
    assert "stop.proposal.parameters.class" in keys   # the irregular stem


# ------------------------------------------------------------------- security
def _hs_token(secret, principal, **claims):
    from cruise_control_tpu.api.security import JwtSecurityProvider
    return JwtSecurityProvider.make_token(secret, principal, **claims)


def test_jwt_cookie_and_audience():
    from cruise_control_tpu.api.security import AuthError, JwtSecurityProvider
    p = JwtSecurityProvider("s3", cookie_name="jwt",
                            expected_audiences=["cruise", "other"])
    tok = _hs_token("s3", "bob", role="USER")
    # audience enforcement: token without aud is rejected
    with pytest.raises(AuthError):
        p.authenticate({"Authorization": f"Bearer {tok}"})
    # mint with matching aud via payload injection
    import base64 as b64
    import hashlib
    import hmac as hm
    import json as js

    def enc(o):
        return b64.urlsafe_b64encode(js.dumps(o).encode()).rstrip(b"=").decode()
    hb = f"{enc({'alg': 'HS256'})}.{enc({'sub': 'bob', 'role': 'USER', 'aud': 'cruise'})}"
    sig = hm.new(b"s3", hb.encode(), hashlib.sha256).digest()
    tok2 = f"{hb}.{b64.urlsafe_b64encode(sig).rstrip(b'=').decode()}"
    # via the configured cookie instead of the Authorization header
    assert p.authenticate({"Cookie": f"jwt={tok2}"}) == ("bob", "USER")


def test_jwt_provider_url_redirects():
    from cruise_control_tpu.api.security import AuthError, JwtSecurityProvider
    p = JwtSecurityProvider("s", provider_url="https://login.example/jwt")
    with pytest.raises(AuthError) as ei:
        p.authenticate({})
    assert ei.value.status == 302
    assert ei.value.extra_headers["Location"] == "https://login.example/jwt"


def test_spnego_service_principal_binding():
    from cruise_control_tpu.api.security import (
        AuthError, SpnegoSecurityProvider, hmac_token_validator,
        make_spnego_token,
    )
    validator = hmac_token_validator("k")
    p = SpnegoSecurityProvider(validator, default_role="ADMIN",
                               service_principal="HTTP/cc@REALM")
    good = make_spnego_token("k", "alice@REALM", service="HTTP/cc@REALM")
    assert p.authenticate({"Authorization": f"Negotiate {good}"})[0] == "alice"
    wrong_svc = make_spnego_token("k", "alice@REALM", service="HTTP/other@REALM")
    with pytest.raises(AuthError):
        p.authenticate({"Authorization": f"Negotiate {wrong_svc}"})


def test_trusted_proxy_ip_regex():
    from cruise_control_tpu.api.security import (
        AuthError, BasicSecurityProvider, TrustedProxySecurityProvider,
    )
    delegate = BasicSecurityProvider({"proxy": ("pw", "ADMIN"),
                                      "joe": ("x", "USER")})
    p = TrustedProxySecurityProvider(delegate, ["proxy"],
                                     user_roles={"joe": "USER"},
                                     ip_regex=r"10\.0\.0\.\d+")
    hdrs = {"Authorization": "Basic " + base64.b64encode(b"proxy:pw").decode(),
            "X-Do-As": "joe"}
    assert p.authenticate(hdrs, client_ip="10.0.0.7") == ("joe", "USER")
    with pytest.raises(AuthError):
        p.authenticate(hdrs, client_ip="192.168.1.1")


# ------------------------------------------------------------------- executor
def _one_broker_backend():
    from cruise_control_tpu.backend import SimulatedClusterBackend
    be = SimulatedClusterBackend()
    for b in range(3):
        be.add_broker(b, f"r{b}")
    be.create_partition("t", 0, [0, 1], size_mb=10.0)
    be.create_partition("u", 0, [1, 2], size_mb=10.0)
    return be


def test_min_isr_check_forces_concurrency_decrease():
    from cruise_control_tpu.executor.executor import (
        ConcurrencyAdjuster, ExecutorConfigView, MinIsrCache,
    )
    from cruise_control_tpu.backend.topic_config import (
        BackendTopicConfigProvider,
    )
    be = _one_broker_backend()
    # minIsr 1: healthy RF-2 partitions (ISR 2 > 1) are safe; losing a broker
    # puts t-0 AT min ISR (1 <= 1), which must block increases
    be.set_topic_config("t", "min.insync.replicas", 1)
    provider = BackendTopicConfigProvider(be)
    cfg = ExecutorConfigView(adjuster_enabled=True, min_isr_check_enabled=True,
                             per_broker_cap=6)
    adj = ConcurrencyAdjuster(cfg, MinIsrCache(provider), be)
    # all brokers healthy, all replicas in sync -> additive increase
    assert adj.recommend_replica_concurrency(6, {}) == 7
    # kill a broker hosting t-0: ISR(t-0) drops to 1 <= minIsr 1 -> decrease
    be.kill_broker(0)
    assert adj.recommend_replica_concurrency(6, {}) == 3
    # with the check disabled the same state increases again
    cfg2 = ExecutorConfigView(adjuster_enabled=True, min_isr_check_enabled=False)
    adj2 = ConcurrencyAdjuster(cfg2, MinIsrCache(provider), be)
    assert adj2.recommend_replica_concurrency(6, {}) == 7


def test_min_isr_cache_caps_and_refreshes():
    from cruise_control_tpu.executor.executor import MinIsrCache

    class CountingProvider:
        def __init__(self):
            self.calls = 0

        def min_insync_replicas(self, topic):
            self.calls += 1
            return 1

    p = CountingProvider()
    cache = MinIsrCache(p, max_size=2, retention_ms=100.0)
    cache.min_isr("a", 0.0)
    cache.min_isr("a", 50.0)          # fresh -> cached
    assert p.calls == 1
    cache.min_isr("a", 200.0)         # stale -> re-fetched
    assert p.calls == 2
    cache.min_isr("b", 200.0)
    cache.min_isr("c", 200.0)         # evicts the stalest
    assert p.calls == 4
    assert len(cache._entries) == 2


def test_executor_notifier_receives_outcome():
    from cruise_control_tpu.executor.executor import Executor
    from cruise_control_tpu.executor.notifier import LoggingExecutorNotifier
    be = _one_broker_backend()
    cfg = cruise_control_config({"execution.progress.check.interval.ms": 1})
    ex = Executor(be, config=cfg)
    assert isinstance(ex._notifier, LoggingExecutorNotifier)
    from cruise_control_tpu.analyzer.proposals import ExecutionProposal
    prop = ExecutionProposal(topic="t", partition=0, old_leader=0, new_leader=0,
                             old_replicas=((0, 0), (1, 0)),
                             new_replicas=((2, 0), (1, 0)))
    ex.execute_proposals([prop], blocking=True,
                         context={"partition_size_mb": {("t", 0): 10.0},
                                  "operation": "test-op"})
    notes = ex._notifier.notifications
    assert len(notes) == 1 and notes[0].operation == "test-op"
    assert notes[0].success and not notes[0].stopped_by_user


def test_progress_check_interval_floor():
    from cruise_control_tpu.executor.executor import Executor
    be = _one_broker_backend()
    cfg = cruise_control_config(
        {"min.execution.progress.check.interval.ms": 2000})
    ex = Executor(be, config=cfg)
    out = ex.set_concurrency(progress_check_interval_ms=500.0)
    assert out["progressCheckIntervalMs"] == 2000.0


# ------------------------------------------------------------------ detector
def test_broker_failure_fixability_thresholds():
    from cruise_control_tpu.detector.anomalies import AnomalyType, BrokerFailures
    from cruise_control_tpu.detector.notifier import Action, SelfHealingNotifier
    n = SelfHealingNotifier()
    n.configure(cruise_control_config({
        "self.healing.enabled": True,
        "broker.failure.alert.threshold.ms": 0,
        "broker.failure.self.healing.threshold.ms": 0,
        "fixable.failed.broker.count.threshold": 2,
        "fixable.failed.broker.percentage.threshold": 0.5,
    }), num_brokers_supplier=lambda: 10)
    fixable = BrokerFailures(anomaly_type=AnomalyType.BROKER_FAILURE,
                             detected_ms=0.0, failed_brokers={1: 0.0})
    assert n.on_anomaly(fixable, 1.0).action is Action.FIX
    too_many = BrokerFailures(anomaly_type=AnomalyType.BROKER_FAILURE,
                              detected_ms=0.0,
                              failed_brokers={b: 0.0 for b in range(3)})
    assert n.on_anomaly(too_many, 1.0).action is Action.IGNORE


def test_idempotence_cache_cap_and_disable():
    from cruise_control_tpu.detector.maintenance import IdempotenceCache
    c = IdempotenceCache(retention_ms=1e9, max_size=2)
    assert not c.seen_before("a", 0)
    assert c.seen_before("a", 1)
    assert not c.seen_before("b", 2)
    assert not c.seen_before("c", 3)       # evicts "a"
    assert not c.seen_before("a", 4)       # forgotten again
    off = IdempotenceCache(enabled=False)
    assert not off.seen_before("x", 0)
    assert not off.seen_before("x", 1)     # pass-through


def test_recent_anomalies_by_type_capped():
    from cruise_control_tpu.detector.anomalies import Anomaly, AnomalyType
    from cruise_control_tpu.detector.manager import AnomalyDetectorManager
    m = AnomalyDetectorManager(num_cached_recent_states=2)
    for i in range(5):
        m.add_anomaly(Anomaly(anomaly_type=AnomalyType.TOPIC_ANOMALY,
                              detected_ms=float(i)))
    m.handle_anomalies(10.0)
    recents = m.state_json()["recentAnomaliesByType"]["TOPIC_ANOMALY"]
    assert len(recents) == 2


# ---------------------------------------------------------------- api caches
def test_purgatory_caps():
    from cruise_control_tpu.api.endpoints import EndPoint
    from cruise_control_tpu.api.purgatory import Purgatory
    p = Purgatory(max_requests=2)
    p.add(EndPoint.REBALANCE, {}, "op")
    p.add(EndPoint.REBALANCE, {}, "op")
    with pytest.raises(ValueError):
        p.add(EndPoint.REBALANCE, {}, "op")


def test_user_task_per_type_completed_cap():
    from cruise_control_tpu.api.endpoints import EndPoint, EndpointType
    from cruise_control_tpu.api.user_tasks import UserTaskManager
    now = [0.0]
    m = UserTaskManager(max_cached_completed=100,
                        max_cached_completed_by_type={
                            EndpointType.KAFKA_ADMIN: 2},
                        time_fn=lambda: now[0])
    for i in range(4):
        t = m.get_or_create_task(f"c{i}", EndPoint.REBALANCE, "POST",
                                 {"i": i}, lambda prog: {"ok": True})
        t.future.result(timeout=30)
        now[0] += 10.0
        m._expire()
    admin_done = [t for t in m.all_tasks()
                  if t.endpoint is EndPoint.REBALANCE and t.done]
    assert len(admin_done) == 2


# ------------------------------------------------------- server key families
class UpperCaseReasonParams:
    """parameters.class override used by the server test below."""

    def parse(self, endpoint, query):
        from cruise_control_tpu.api.endpoints import parse_params
        params = parse_params(endpoint, query)
        if params.get("reason"):
            params["reason"] = params["reason"].upper()
        return params


class CannedStateRequest:
    """request.class override: answers without touching the app."""

    def handle(self, server, method, endpoint, params, client, task_id_header):
        return 200, {"version": 1, "canned": True}, {}


def _mini_app(props=None):
    from cruise_control_tpu.app import CruiseControl
    from cruise_control_tpu.backend import SimulatedClusterBackend
    be = SimulatedClusterBackend()
    for b in range(3):
        be.add_broker(b, f"r{b}")
    be.create_partition("t", 0, [0, 1], size_mb=10.0)
    return CruiseControl(be, cruise_control_config(props or {}))


def _get(url, method="GET", headers=None, body=None):
    req = urllib.request.Request(url, method=method, data=body,
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=120) as r:
            return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


@pytest.fixture(scope="module")
def surface_server(tmp_path_factory):
    from cruise_control_tpu.api import CruiseControlServer
    ui = tmp_path_factory.mktemp("ui")
    (ui / "index.html").write_text("<html>cc-ui</html>")
    access_log = tmp_path_factory.mktemp("logs") / "access.log"
    props = {
        "webserver.http.cors.enabled": True,
        "webserver.http.cors.origin": "https://ops.example",
        "webserver.accesslog.enabled": True,
        "webserver.accesslog.path": str(access_log),
        "request.reason.required": True,
        "webserver.session.path": "/kafkacruisecontrol",
        "webserver.ui.diskpath": str(ui),
        "state.request.class":
            "tests.test_config_surface.CannedStateRequest",
        "pause.sampling.parameters.class":
            "tests.test_config_surface.UpperCaseReasonParams",
    }
    cc = _mini_app(props)
    srv = CruiseControlServer(cc, port=0, max_block_ms=60_000.0,
                              config=cc.config)
    srv.start()
    yield srv, access_log
    srv.stop()


def test_cors_headers_and_preflight(surface_server):
    srv, _ = surface_server
    status, _, headers = _get(f"{srv.base_url}/state")
    assert headers["Access-Control-Allow-Origin"] == "https://ops.example"
    status, _, headers = _get(f"{srv.base_url}/state", method="OPTIONS")
    assert status == 204


def test_request_class_override(surface_server):
    srv, _ = surface_server
    status, body, _ = _get(f"{srv.base_url}/state")
    assert status == 200 and json.loads(body)["canned"] is True


def test_reason_required_on_posts(surface_server):
    srv, _ = surface_server
    status, body, _ = _get(f"{srv.base_url}/pause_sampling", method="POST")
    assert status == 400 and b"reason" in body
    status, body, _ = _get(f"{srv.base_url}/pause_sampling?reason=ops",
                           method="POST")
    assert status == 200


def test_parameters_class_override(surface_server):
    # UpperCaseReasonParams upper-cases the reason before dispatch
    srv, _ = surface_server
    status, body, _ = _get(f"{srv.base_url}/pause_sampling?reason=drain",
                           method="POST")
    assert status == 200
    assert srv.app.load_monitor.pause_reason == "DRAIN"


def test_session_cookie_path(surface_server):
    srv, _ = surface_server
    _, _, headers = _get(f"{srv.base_url}/state")
    assert "Path=/kafkacruisecontrol" in headers.get("Set-Cookie", "")


def test_ui_served_from_diskpath(surface_server):
    srv, _ = surface_server
    base = srv.base_url[:-len("/kafkacruisecontrol")]
    status, body, headers = _get(f"{base}/index.html")
    assert status == 200 and b"cc-ui" in body
    assert "text/html" in headers["Content-Type"]
    # traversal is refused
    status, _, _ = _get(f"{base}/../../etc/passwd")
    assert status != 200 or b"cc-ui" in body


def test_access_log_written(surface_server):
    srv, access_log = surface_server
    _get(f"{srv.base_url}/state")
    content = access_log.read_text()
    assert "/kafkacruisecontrol/state" in content and '" 200 ' in content


def test_rs256_jwt_verification_from_pem(tmp_path):
    """jwt.auth.certificate.location path: RS256 tokens verified against a
    PEM public key / X.509 cert via the stdlib DER walk (the reference's
    JwtLoginService verifies RS256 against the IdP certificate)."""
    import shutil
    import subprocess
    if shutil.which("openssl") is None:
        pytest.skip("openssl not available")
    key = tmp_path / "k.pem"
    pub = tmp_path / "p.pem"
    cert = tmp_path / "c.pem"
    subprocess.run(["openssl", "genrsa", "-out", str(key), "2048"],
                   check=True, capture_output=True)
    subprocess.run(["openssl", "rsa", "-in", str(key), "-pubout",
                    "-out", str(pub)], check=True, capture_output=True)
    subprocess.run(["openssl", "req", "-new", "-x509", "-key", str(key),
                    "-out", str(cert), "-days", "1", "-subj", "/CN=t"],
                   check=True, capture_output=True)
    from cruise_control_tpu.api.security import (
        AuthError, JwtSecurityProvider, rsa_public_key_from_pem,
    )

    def b64u(b):
        return base64.urlsafe_b64encode(b).rstrip(b"=").decode()

    head = b64u(json.dumps({"alg": "RS256"}).encode())
    body = b64u(json.dumps({"sub": "alice", "role": "ADMIN"}).encode())
    si = tmp_path / "si.bin"
    si.write_bytes(f"{head}.{body}".encode())
    sig_f = tmp_path / "sig.bin"
    subprocess.run(["openssl", "dgst", "-sha256", "-sign", str(key),
                    "-out", str(sig_f), str(si)], check=True,
                   capture_output=True)
    tok = f"{head}.{body}.{b64u(sig_f.read_bytes())}"
    n_e = rsa_public_key_from_pem(pub.read_text())
    p = JwtSecurityProvider(rs256_key=n_e)
    assert p.authenticate({"Authorization": f"Bearer {tok}"}) == ("alice", "ADMIN")
    # the same key is recoverable from the X.509 certificate
    assert rsa_public_key_from_pem(cert.read_text()) == n_e
    # tampered payload is rejected
    bad = f"{head}.{b64u(json.dumps({'sub': 'mallory', 'role': 'ADMIN'}).encode())}.{b64u(sig_f.read_bytes())}"
    with pytest.raises(AuthError):
        p.authenticate({"Authorization": f"Bearer {bad}"})


def test_maintenance_event_stops_ongoing_execution():
    """maintenance.event.stop.ongoing.execution: a FIXed maintenance plan
    preempts a running proposal execution before being handled."""
    from cruise_control_tpu.detector.anomalies import AnomalyType, MaintenanceEvent
    from cruise_control_tpu.detector.manager import AnomalyDetectorManager
    from cruise_control_tpu.detector.notifier import Action, NotificationResult

    calls = []

    class CC:
        class executor:
            @staticmethod
            def has_ongoing_execution():
                return True

        @staticmethod
        def stop_proposal_execution(force=False):
            calls.append(("stop", force))
            return {}

    class FixAll:
        def on_anomaly(self, anomaly, now_ms):
            return NotificationResult(Action.FIX)

        def self_healing_enabled(self):
            return {}

    cc = CC()
    m = AnomalyDetectorManager(notifier=FixAll(), cruise_control=cc,
                               maintenance_stops_ongoing_execution=True)
    ev = MaintenanceEvent(anomaly_type=AnomalyType.MAINTENANCE_EVENT,
                          detected_ms=0.0, plan_type="REBALANCE")
    ev.fix = lambda cc: calls.append(("fix",)) or {}
    m.add_anomaly(ev)
    m.handle_anomalies(1.0)
    assert calls == [("stop", False), ("fix",)]
    # with the flag off, no stop happens
    calls.clear()
    m2 = AnomalyDetectorManager(notifier=FixAll(), cruise_control=cc,
                                maintenance_stops_ongoing_execution=False)
    ev2 = MaintenanceEvent(anomaly_type=AnomalyType.MAINTENANCE_EVENT,
                           detected_ms=0.0, plan_type="REBALANCE")
    ev2.fix = lambda cc: calls.append(("fix",)) or {}
    m2.add_anomaly(ev2)
    m2.handle_anomalies(1.0)
    assert calls == [("fix",)]


def test_skip_loading_samples_bypasses_store_replay():
    from cruise_control_tpu.monitor.load_monitor import LoadMonitor

    class Store:
        def __init__(self):
            self.loaded = 0

        def configure(self, config, **extra):
            pass

        def store_samples(self, samples):
            pass

        def load_samples(self, loader):
            self.loaded += 1
            return 0

        def close(self):
            pass

    st1 = Store()
    lm = LoadMonitor(config=cruise_control_config(), sample_store=st1)
    lm.start_up()
    assert st1.loaded == 1
    st2 = Store()
    lm2 = LoadMonitor(config=cruise_control_config(
        {"skip.loading.samples": True}), sample_store=st2)
    lm2.start_up()
    assert st2.loaded == 0


def test_custom_partition_assignor_class_used():
    from cruise_control_tpu.monitor.fetcher import MetricFetcherManager

    class RecordingAssignor:
        def __init__(self):
            self.calls = 0

        def configure(self, config):
            pass

        def assign(self, partitions, num_fetchers):
            self.calls += 1
            return [list(partitions)]

    class Sampler:
        supports_partition_scoped_fetch = True

        def get_samples(self, now_ms, partitions=None,
                        include_broker_samples=True):
            from cruise_control_tpu.monitor.sampling.samplers import Samples
            return Samples([], [])

    a = RecordingAssignor()
    mgr = MetricFetcherManager(Sampler(), num_fetchers=2, assignor=a)
    mgr.fetch_once(0.0, [("t", 0), ("t", 1)])
    assert a.calls == 1


# ------------------------------------------------------- full consumption
def test_every_canonical_key_is_consumed(tmp_path):
    """Anti-dead-key guard (the reference consumes every key it defines via
    getConfiguredInstance/getLong/...): instrument Config reads, drive the
    whole stack — app wiring, detectors + a self-healing fix, proposals
    cache/precompute, server + every security provider, SSL context, the
    pluggable samplers/notifiers, the RPC backend seam — and assert every
    canonical key was READ somewhere. A key that only exists in defaults.py
    fails this test."""
    from cruise_control_tpu.config import configdef
    from cruise_control_tpu.main import (
        build_app, build_sampling_loop, build_server, build_ssl_context,
    )

    tracker = set()
    configdef.READ_TRACKER = tracker
    tmp = str(tmp_path)
    try:
        cfg = cruise_control_config({
            "webserver.http.port": 0,
            "webserver.accesslog.enabled": True,
            "webserver.accesslog.path": f"{tmp}/access.log",
            "webserver.http.cors.enabled": True,
            "webserver.ui.diskpath": tmp,
            "self.healing.enabled": True,
            "sample.store.path": tmp,
            "maintenance.event.topic.path": f"{tmp}/maint.jsonl",
            "two.step.verification.enabled": True,
            # predictive control plane (PR 17): the forecast wiring reads
            # the forecast.* knob family + the predicted-detector cadence
            "forecast.enabled": True,
            "broker.failure.alert.threshold.ms": 0,
            "broker.failure.self.healing.threshold.ms": 0,
            "num.metrics.windows": 2,
            "min.samples.per.metrics.window": 1,
            # short goal chains: this test proves KEY READS, not
            # optimization quality — the full 16-goal chain would compile
            # for minutes on the CPU test platform
            "goals": ["RackAwareGoal", "ReplicaDistributionGoal"],
            "hard.goals": ["RackAwareGoal"],
            "default.goals": ["ReplicaDistributionGoal"],
            "anomaly.detection.goals": ["ReplicaDistributionGoal"],
            "self.healing.goals": ["ReplicaDistributionGoal"],
            "intra.broker.goals": ["IntraBrokerDiskCapacityGoal"],
            "topic.anomaly.finder.class": [
                "cruise_control_tpu.detector.topic_anomaly."
                "TopicReplicationFactorAnomalyFinder",
                "cruise_control_tpu.detector.topic_anomaly."
                "PartitionSizeAnomalyFinder"],
        })
        cc = build_app(cfg)
        be = cc.backend
        for b in range(4):
            be.add_broker(b, f"r{b % 2}")
        for p in range(8):
            be.create_partition("t", p, [p % 4, (p + 1) % 4], size_mb=10.0)
        cc.start_up()
        build_sampling_loop(cc, cfg)
        # the pipelined steady loop (main.py service.pipeline.enabled branch)
        # reads the service.pipeline.* family
        if cfg.get_boolean("service.pipeline.enabled"):
            from cruise_control_tpu.pipeline import PipelinedServiceLoop
            PipelinedServiceLoop(cc, cfg)
        # fleet mode (PR 13): the scheduler reads the fleet.* family
        from cruise_control_tpu.fleet import FleetScheduler
        FleetScheduler(config=cfg).shutdown()
        # fleet-in-main + HA (PR 15): the multi-tenant boot reads
        # fleet.cluster.ids, the leader elector reads ha.lease.*
        from cruise_control_tpu.main import build_fleet
        build_fleet(cc, cfg, {}, {})
        from cruise_control_tpu.ha import LeaderElector
        LeaderElector.from_config(be, "config-surface", cfg)
        cc.load_monitor.sample_once(now_ms=0.0)
        cc.load_monitor.sample_once(now_ms=300000.0)
        # self-healing fix path reads the healing-goal + exclusion keys
        be.kill_broker(3)
        cc.anomaly_detector.run_detection_round(be.now_ms() + 1.0)
        cc.anomaly_detector.handle_anomalies(be.now_ms() + 2.0)
        cc.cached_proposals()
        cc.start_proposal_precompute()
        cc.partition_load(limit=3)
        try:
            cc.rebalance(rebalance_disk=True, dry_run=True)
        except Exception:
            pass
        _srv = build_server(cc, cfg); _srv.start(); _srv.stop()
        cc.shutdown()

        # each security provider reads its own key family
        cred = tmp_path / "cred"
        cred.write_text("u: p, ADMIN\n")
        for sec in (
            {"webserver.security.provider": "BASIC"},
            {"webserver.security.provider": "JWT",
             "jwt.secret.file": str(cred)},
            {"webserver.security.provider": "SPNEGO",
             "spnego.principal.secret.file": str(cred)},
            {"webserver.security.provider": "TRUSTED_PROXY",
             "trusted.proxy.services": "nuage",
             "spnego.principal.secret.file": str(cred)},
        ):
            c2 = cruise_control_config({
                "webserver.http.port": 0,
                "webserver.security.enable": True,
                "webserver.auth.credentials.file": str(cred), **sec})
            _s2 = build_server(cc, c2); _s2.start(); _s2.stop()
        # SSL family: reads all webserver.ssl.* before the (failing) cert IO
        with pytest.raises(Exception):
            build_ssl_context(cruise_control_config({
                "webserver.ssl.enable": True,
                "webserver.ssl.cert.location": str(cred),
                "webserver.ssl.key.location": str(cred),
                "webserver.ssl.key.password": "x"}))
        # pluggable samplers
        cruise_control_config({
            "metric.sampler.class": "cruise_control_tpu.monitor.sampling."
                                    "prometheus.PrometheusMetricSampler",
            "prometheus.server.endpoint": "localhost:9090",
        }).get_configured_instance("metric.sampler.class")
        cruise_control_config({
            "metric.sampler.class":
                "cruise_control_tpu.monitor.sampling.reporter_sampler."
                "CruiseControlMetricsReporterSampler",
            "metrics.reporter.topic.path": f"{tmp}/metrics.jsonl",
        }).get_configured_instance("metric.sampler.class")
        # webhook notifier families
        for cls in ("SlackSelfHealingNotifier", "AlertaSelfHealingNotifier"):
            cruise_control_config({
                "anomaly.notifier.class":
                    f"cruise_control_tpu.detector.notifier.{cls}",
            }).get_configured_instance("anomaly.notifier.class")
        # RPC client timeout keys (configure() without spawning a sidecar)
        from cruise_control_tpu.backend.rpc import RpcClusterBackend
        rb = RpcClusterBackend.__new__(RpcClusterBackend)
        rb.configure(cruise_control_config())
        # wire-provider seam (build_app's RPC branch)
        cruise_control_config().get_configured_instance(
            "network.client.provider.class")
    finally:
        configdef.READ_TRACKER = None

    keys = CRUISE_CONTROL_CONFIG_DEF.keys()
    canonical = {n for n, k in keys.items() if k.alias_of is None}
    unread = sorted(canonical - tracker)
    assert not unread, f"{len(unread)} canonical keys defined but never read: {unread}"
