"""Pass-pipeline parity certification (PR 4 tentpole contract).

The engine's warm-pass fast paths — eligible-set-compacted candidate keying
(`engine._select_candidates`), the pass-invariant chain-acceptance cache
(`GoalKernel.accept_move_rooms` folded by `engine._combined_move_rooms`) and
rank-banded multi-wave passes (`EngineParams.pass_waves`) — must be
TOGGLEABLE and, on seeded fixtures, BIT-IDENTICAL to the knobs-off pipeline:
same final assignments, same violation outcomes, same fixpoint certificates.
These tests are that certificate, plus the zero-new-XLA-compiles contract for
budget-leaf knob toggles (EngineParams' traced leaves must never force a
recompile).
"""
import dataclasses
import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.analyzer import engine as E
from cruise_control_tpu.analyzer import init_state, make_env
from cruise_control_tpu.analyzer.engine import EngineParams
from cruise_control_tpu.analyzer.goals import make_goals
from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
from cruise_control_tpu.model.random_cluster import RandomClusterSpec, generate

# knob-on / knob-off parameter points. max_pass_waves is static (selection
# width + wave-loop bound); pass_waves is the TRACED wave count. The OFF
# point is the legacy single-wave, full-R-keying, per-goal-mask pipeline.
# PARAMS_ON is the certified-bit-identical pipeline point: compacted
# keying + chain cache + the widened selection / wave-loop machinery at ONE
# wave. pass_waves > 1 (PARAMS_WAVES) is a deliberate greedy-order change —
# later bands are stale-ranked exploration, the same contract as the
# engine's 0.95-recall approx top-k — so its parity clause is OUTCOME
# parity (violations + certificates), not bitwise assignments, plus an
# exact fallback at pass_waves=1.
PARAMS_OFF = EngineParams(max_pass_waves=1, pass_waves=1,
                          compact_keying=False, chain_cache=False)
PARAMS_ON = EngineParams(max_pass_waves=4, pass_waves=1,
                         compact_keying=True, chain_cache=True)
PARAMS_WAVES = EngineParams(max_pass_waves=4, pass_waves=4,
                            compact_keying=True, chain_cache=True)

CHAIN = ["RackAwareGoal", "DiskCapacityGoal", "CpuCapacityGoal",
         "ReplicaDistributionGoal", "DiskUsageDistributionGoal",
         "LeaderReplicaDistributionGoal"]


def _cluster(seed=777):
    """Seeded fixture big enough that K (64) < R: the widened selection has
    real rank bands and the compaction pool has a real eligible prefix."""
    return generate(RandomClusterSpec(
        num_brokers=24, num_racks=4, num_topics=12, num_partitions=300,
        max_replication=2, skew=2.0, seed=seed))


def _run(params, ct, meta, goal_names=CHAIN):
    opt = GoalOptimizer(engine_params=params)
    return opt.optimizations(ct, meta, goal_names=goal_names,
                             raise_on_failure=False,
                             skip_hard_goal_check=True)


def _assert_bit_identical(ra, rb, label):
    np.testing.assert_array_equal(
        np.asarray(ra.final_state.replica_broker),
        np.asarray(rb.final_state.replica_broker), err_msg=label)
    np.testing.assert_array_equal(
        np.asarray(ra.final_state.replica_is_leader),
        np.asarray(rb.final_state.replica_is_leader), err_msg=label)
    np.testing.assert_array_equal(
        np.asarray(ra.final_state.replica_disk),
        np.asarray(rb.final_state.replica_disk), err_msg=label)
    assert ra.violated_goals_before == rb.violated_goals_before, label
    assert ra.violated_goals_after == rb.violated_goals_after, label
    assert ra.num_replica_movements == rb.num_replica_movements, label
    assert ra.num_leadership_movements == rb.num_leadership_movements, label
    for ga, gb in zip(ra.goal_results, rb.goal_results):
        assert (ga.fixpoint_proven, ga.hit_max_iters, ga.moves_remaining,
                ga.leads_remaining, ga.swap_window_remaining) == \
               (gb.fixpoint_proven, gb.hit_max_iters, gb.moves_remaining,
                gb.leads_remaining, gb.swap_window_remaining), \
            (label, ga.name)


def test_pipeline_knobs_bit_identical_to_legacy():
    """All three knobs ON vs all OFF: bit-identical assignments, violation
    outcomes and certificate fields on the seeded fixture."""
    ct, meta = _cluster()
    _assert_bit_identical(_run(PARAMS_ON, ct, meta),
                          _run(PARAMS_OFF, ct, meta), "all-knobs")


@pytest.mark.parametrize("knob", [
    {"compact_keying": True},
    {"chain_cache": True},
    {"max_pass_waves": 4},          # widened selection + wave loop, 1 wave
])
def test_each_knob_falls_back_cleanly(knob):
    """Each knob toggled INDIVIDUALLY against the all-off baseline stays
    bit-identical — so disabling any one of them in production falls back
    to a certified-equivalent pipeline."""
    ct, meta = _cluster(seed=778)
    pa = dataclasses.replace(PARAMS_OFF, **knob)
    _assert_bit_identical(_run(pa, ct, meta), _run(PARAMS_OFF, ct, meta),
                          str(knob))


def test_multi_wave_outcome_parity_and_exact_fallback():
    """pass_waves > 1 reorders the greedy trajectory by design (stale-ranked
    later bands). Its contract: IDENTICAL violation outcomes and
    certificate fields on the seeded fixture — and setting pass_waves back
    to 1 (a traced leaf, no recompile) is bit-identical to the legacy
    pipeline again."""
    ct, meta = _cluster(seed=777)
    rw = _run(PARAMS_WAVES, ct, meta)
    r1 = _run(PARAMS_OFF, ct, meta)
    assert rw.violated_goals_before == r1.violated_goals_before
    assert rw.violated_goals_after == r1.violated_goals_after
    for gw, g1 in zip(rw.goal_results, r1.goal_results):
        assert (gw.fixpoint_proven, gw.hit_max_iters) == \
               (g1.fixpoint_proven, g1.hit_max_iters), gw.name
    # multi-wave actually exercised the wave machinery
    assert sum(g.move_waves for g in rw.goal_results) > 0
    # exact fallback: waves dialed back to 1 == legacy, bit for bit
    _assert_bit_identical(
        _run(dataclasses.replace(PARAMS_WAVES, pass_waves=1), ct, meta),
        r1, "waves-fallback")


def test_rooms_exactly_reproduce_accept_move_masks():
    """Every goal exposing accept_move_rooms must reproduce its own
    accept_move mask EXACTLY through the folded rooms comparison (the
    chain-cache's soundness contract), on the seeded fixture's initial
    state over every valid replica."""
    ct, meta = _cluster(seed=779)
    env = make_env(ct, meta)
    st = init_state(env, ct.replica_broker, ct.replica_is_leader,
                    ct.replica_offline, ct.replica_disk)
    cand = jnp.arange(env.num_replicas, dtype=jnp.int32)
    d = E._move_delta_rows(env, st, cand)
    src_b = st.replica_broker[cand]
    goals = make_goals([
        "DiskCapacityGoal", "CpuCapacityGoal", "NetworkInboundCapacityGoal",
        "NetworkOutboundCapacityGoal", "ReplicaCapacityGoal",
        "PotentialNwOutGoal", "ReplicaDistributionGoal",
        "LeaderReplicaDistributionGoal", "DiskUsageDistributionGoal",
        "CpuUsageDistributionGoal", "NetworkInboundUsageDistributionGoal",
        "NetworkOutboundUsageDistributionGoal"])
    checked = 0
    for g in goals:
        rooms = g.accept_move_rooms(env, st)
        assert rooms is not None, g.name
        ref = np.asarray(g.accept_move(env, st, cand))
        got = np.asarray(E._rooms_move_mask(rooms, d, src_b))
        valid = np.asarray(env.replica_valid)
        np.testing.assert_array_equal(got[valid], ref[valid], err_msg=g.name)
        checked += 1
    assert checked == 12


def test_combined_rooms_match_sequential_masks():
    """The FOLDED (min-combined) rooms of a whole chain equal the AND of the
    per-goal masks — folding must not lose a veto."""
    ct, meta = _cluster(seed=780)
    env = make_env(ct, meta)
    st = init_state(env, ct.replica_broker, ct.replica_is_leader,
                    ct.replica_offline, ct.replica_disk)
    cand = jnp.arange(env.num_replicas, dtype=jnp.int32)
    goals = tuple(make_goals([
        "DiskCapacityGoal", "ReplicaCapacityGoal", "ReplicaDistributionGoal",
        "DiskUsageDistributionGoal", "LeaderReplicaDistributionGoal"]))
    rooms, custom = E._combined_move_rooms(goals, env, st)
    assert not custom          # all five have interval forms
    got = np.asarray(E._rooms_move_mask(
        rooms, E._move_delta_rows(env, st, cand), st.replica_broker[cand]))
    ref = np.ones_like(got)
    for g in goals:
        ref &= np.asarray(g.accept_move(env, st, cand))
    valid = np.asarray(env.replica_valid)
    np.testing.assert_array_equal(got[valid], ref[valid])


def test_compacted_selection_matches_full_sweep():
    """_select_candidates with compaction ON == full-R sweep, across
    eligibility regimes (sparse, dense, pool overflow) and stall salting.
    Padding slots may differ but only with kv == -inf (inert downstream)."""
    rng = np.random.default_rng(42)
    R = 4096
    base = jnp.asarray(rng.random(R), jnp.float32)
    p_on = EngineParams(compact_keying=True, compact_pool=1024)
    p_off = EngineParams(compact_keying=False)
    for frac in (0.01, 0.1, 0.5, 1.0):   # 0.5/1.0 overflow the 1024 pool
        elig = jnp.asarray(rng.random(R) < frac)
        key = jnp.where(elig, base, -jnp.inf)
        for stall in (0, 3):
            for exact in (False, True):
                kv_c, c_c = E._select_candidates(
                    key, 64, jnp.int32(stall), exact, p_on)
                kv_f, c_f = E._select_candidates(
                    key, 64, jnp.int32(stall), exact, p_off)
                np.testing.assert_array_equal(np.asarray(kv_c),
                                              np.asarray(kv_f),
                                              err_msg=f"{frac}/{stall}")
                live = np.asarray(kv_f) > -np.inf
                np.testing.assert_array_equal(np.asarray(c_c)[live],
                                              np.asarray(c_f)[live],
                                              err_msg=f"{frac}/{stall}")


def test_budget_leaf_toggle_zero_recompiles():
    """Toggling ONLY traced budget leaves — pass_waves included — must reuse
    the compiled goal program: zero new XLA compiles (the EngineParams
    pytree-split contract that keeps warmup + the persistent cache honest)."""
    ct, meta = _cluster(seed=781)
    opt = GoalOptimizer(engine_params=PARAMS_ON)
    kw = dict(goal_names=CHAIN, raise_on_failure=False,
              skip_hard_goal_check=True)
    opt.optimizations(ct, meta, **kw)    # compile

    class Counter(logging.Handler):
        def __init__(self):
            super().__init__(level=logging.DEBUG)
            self.count = 0

        def emit(self, record):
            if "Compiling" in record.getMessage():
                self.count += 1

    handler = Counter()
    prev = bool(jax.config.jax_log_compiles)
    jax.config.update("jax_log_compiles", True)
    logging.getLogger("jax").addHandler(handler)
    try:
        for tweak in ({"pass_waves": 2}, {"pass_waves": 1},
                      {"tail_pass_budget": 7, "stall_retries": 3},
                      {"max_iters": 11, "sat_tail_passes": 2}):
            opt2 = GoalOptimizer(engine_params=dataclasses.replace(
                PARAMS_ON, **tweak))
            opt2.optimizations(ct, meta, **kw)
    finally:
        logging.getLogger("jax").removeHandler(handler)
        jax.config.update("jax_log_compiles", prev)
    assert handler.count == 0, f"{handler.count} recompiles on budget toggles"


@pytest.mark.slow
def test_finisher_certificate_parity_with_knobs():
    """Certificate parity under the knobs with the exhaustive finisher
    FORCED on (small clusters normally skip it): the fixpoint certificate
    fields and the final state must be bit-identical knobs-on vs knobs-off
    — the chain cache also rewires the finisher's exhaustive move scan."""
    ct, meta = _cluster(seed=782)
    from cruise_control_tpu.model.cluster_tensor import pad_cluster
    ct, meta = pad_cluster(ct, meta)
    env = make_env(ct, meta)
    st0 = init_state(env, ct.replica_broker, ct.replica_is_leader,
                     ct.replica_offline, ct.replica_disk)
    goals = make_goals(CHAIN)
    prev = tuple(goals[:-2])
    goal = goals[-2]                      # DiskUsageDistributionGoal
    outs = []
    for p in (PARAMS_ON, PARAMS_OFF):
        p = dataclasses.replace(p, finisher_rounds=2, tail_pass_budget=6,
                                stall_retries=2, tail_total_budget=12)
        st, info = E.optimize_goal(env, st0, goal, prev, p)
        outs.append((jax.device_get(st), jax.device_get(info)))
    (st_a, info_a), (st_b, info_b) = outs
    np.testing.assert_array_equal(np.asarray(st_a.replica_broker),
                                  np.asarray(st_b.replica_broker))
    for k in ("fixpoint_proven", "moves_remaining", "leads_remaining",
              "swap_window_remaining", "violated_after", "iterations"):
        assert np.asarray(info_a[k]) == np.asarray(info_b[k]), k
