"""Deterministic fault-injection scenario engine.

Closes the self-healing loop end to end on simulated time: scripted fault
timelines (scenario.py) drive a SimulatedClusterBackend + LoadMonitor +
AnomalyDetectorManager + GoalOptimizer + Executor stack (runner.py), with
cluster-safety invariants checked every tick and at convergence
(invariants.py) and a catalog of required failure modes (catalog.py).
"""
from cruise_control_tpu.sim.catalog import SCENARIOS
from cruise_control_tpu.sim.invariants import (
    check_converged, check_executor_accounting, check_tick,
)
from cruise_control_tpu.sim.runner import (
    BASE_CONFIG, ScenarioResult, ScenarioRunner, run_scenario,
)
from cruise_control_tpu.sim.scenario import (
    ClusterSpec, Scenario, ScenarioEvent, broker_death, broker_restart,
    build_backend, clear_slow_broker, disk_failure, load_surge,
    maintenance_event, metric_gap, rf_drop, scenario_from_json,
    scenario_to_json, slow_broker, topic_creation,
)
from cruise_control_tpu.sim.campaign import (
    CAMPAIGNS, CampaignResult, CampaignRunner, CampaignSpec,
    generate_episode, run_campaign,
)
from cruise_control_tpu.sim.api_fuzz import (
    ApiFuzzer, FaultyBackend, FuzzEpisodeResult, FuzzSpec,
    TransientBackendError, run_fuzz_campaign, run_fuzz_episode,
)

__all__ = [
    "SCENARIOS", "check_converged", "check_executor_accounting", "check_tick",
    "BASE_CONFIG", "ScenarioResult", "ScenarioRunner", "run_scenario",
    "ClusterSpec", "Scenario", "ScenarioEvent", "broker_death",
    "broker_restart", "build_backend", "clear_slow_broker", "disk_failure",
    "load_surge", "maintenance_event", "metric_gap", "rf_drop",
    "scenario_from_json", "scenario_to_json", "slow_broker", "topic_creation",
    "CAMPAIGNS", "CampaignResult", "CampaignRunner", "CampaignSpec",
    "generate_episode", "run_campaign",
    "ApiFuzzer", "FaultyBackend", "FuzzEpisodeResult", "FuzzSpec",
    "TransientBackendError", "run_fuzz_campaign", "run_fuzz_episode",
]
