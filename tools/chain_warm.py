import os, time, sys
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_cc_tpu")
import jax
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_cc_tpu")
from cruise_control_tpu.model.random_cluster import RandomClusterSpec, generate_scale
from cruise_control_tpu.analyzer.optimizer import GoalOptimizer

ct, meta = generate_scale(RandomClusterSpec(
    num_brokers=7000, num_racks=40, num_topics=2000,
    num_partitions=500000, max_replication=3, skew=1.0, seed=3142,
    target_cpu_util=0.45))
opt = GoalOptimizer()
opt._fused_min_replicas = -1   # per-goal programs (async pipelined)
walls = []
for i in range(3):
    t0 = time.monotonic()
    res = opt.optimizations(ct, meta, raise_on_failure=False,
                            skip_hard_goal_check=True)
    walls.append(round(time.monotonic() - t0, 2))
    print(f"run {i}: {walls[-1]}s", flush=True)
print("walls", walls)
print("violated:", res.violated_goals_after)
print("exhausted:", [g.name for g in res.goal_results if g.hit_max_iters])
print("proven:", [g.name for g in res.goal_results
                  if g.violated_after and g.fixpoint_proven])
