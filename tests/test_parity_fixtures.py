"""DeterministicCluster parity tests.

Reference: analyzer/DeterministicClusterTest.java:60 — parameterized
(fixture x goal-list) runs over common/DeterministicCluster.java topologies
verified by OptimizationVerifier. Each case here encodes the reference
fixture's hand-derivable expected outcome; move lists are implementation-
defined, violation outcomes are the contract (SURVEY §7 hard part 1).
"""
import dataclasses

import numpy as np
import pytest

# engine-path compile-heavy; the fast tier (-m 'not slow') covers the engine via
# test_model/test_analyzer_goals/test_optimizer
pytestmark = pytest.mark.slow

from cruise_control_tpu.analyzer.optimizer import (
    GoalOptimizer, OptimizationFailureError,
)
from cruise_control_tpu.model import fixtures
from optimization_verifier import verify

DEFAULT_CHAIN = [
    "RackAwareGoal", "RackAwareDistributionGoal", "MinTopicLeadersPerBrokerGoal",
    "ReplicaCapacityGoal", "DiskCapacityGoal", "NetworkInboundCapacityGoal",
    "NetworkOutboundCapacityGoal", "CpuCapacityGoal", "ReplicaDistributionGoal",
    "PotentialNwOutGoal", "DiskUsageDistributionGoal",
    "NetworkInboundUsageDistributionGoal", "NetworkOutboundUsageDistributionGoal",
    "CpuUsageDistributionGoal", "LeaderReplicaDistributionGoal",
    "LeaderBytesInDistributionGoal", "TopicReplicaDistributionGoal",
    "PreferredLeaderElectionGoal",
]


def _optimize(ct, meta, goals, **kw):
    opt = GoalOptimizer()
    return opt.optimizations(ct, meta, goal_names=goals,
                             skip_hard_goal_check=True, **kw)


def test_unbalanced_default_chain_heals():
    """unbalanced(): both half-capacity partitions on broker 0 — the chain
    must spread them (CPU 50+50 = cap 100 > threshold 70) and end clean."""
    ct, meta = fixtures.unbalanced()
    res = _optimize(ct, meta, DEFAULT_CHAIN, raise_on_failure=True)
    hard = {"RackAwareGoal", "RackAwareDistributionGoal", "ReplicaCapacityGoal",
            "DiskCapacityGoal", "NetworkInboundCapacityGoal",
            "NetworkOutboundCapacityGoal", "CpuCapacityGoal"}
    assert not (set(res.violated_goals_after) & hard)
    # the two partitions no longer share a broker (env arrays are padded;
    # padded brokers are not alive)
    st = res.final_state
    counts = np.asarray(st.replica_count)[np.asarray(res.env.broker_alive)]
    assert counts.max() <= 1
    verify(ct, meta, res, ["REGRESSION"])


def test_unbalanced2_replica_distribution():
    """unbalanced2(): replica counts 5/1/0 -> balanced 2/2/2 by
    ReplicaDistributionGoal (reference balance pct 1.10 over avg 2)."""
    ct, meta = fixtures.unbalanced2()
    res = _optimize(ct, meta, ["ReplicaDistributionGoal"])
    assert "ReplicaDistributionGoal" not in res.violated_goals_after
    counts = np.sort(np.asarray(res.final_state.replica_count)[:3])
    # reference band math: avg 2, upper = ceil(2 * 1.09) = 3, lower =
    # floor(2 * 0.91) = 1 (ReplicaDistributionAbstractGoal limits) — counts
    # must land inside [1, 3]; 5/1/0 is out, 2/2/2 and 3/2/1 are both legal
    assert counts[0] >= 1 and counts[-1] <= 3
    assert counts.sum() == 6
    verify(ct, meta, res, ["REGRESSION"])


def test_unbalanced_with_a_follower_leadership():
    """unbalancedWithAFollower(): T1-0 has a follower on broker 2, but moving
    leadership there would push broker 2 itself over the balance threshold
    (150k > upper ~109k) — the reference REJECTS the transfer
    (LeaderBytesInDistributionGoal.java:127 newDestLeaderBytesIn check) and
    the goal stays violated. Parity means we refuse it too."""
    ct, meta = fixtures.unbalanced_with_a_follower()
    res = _optimize(ct, meta, ["LeaderBytesInDistributionGoal"])
    st = res.final_state
    leaders = np.asarray(st.leader_count)
    assert leaders[0] == 2                 # transfer correctly rejected
    assert "LeaderBytesInDistributionGoal" in res.violated_goals_after


def test_preferred_leader_election_moves_to_position_zero():
    """unbalanced3(): leadership must return to the position-0 replicas on
    broker 1 (PreferredLeaderElectionGoal.java contract)."""
    ct, meta = fixtures.preferred_leader_skewed()
    res = _optimize(ct, meta, ["PreferredLeaderElectionGoal"])
    st = res.final_state
    leaders = np.asarray(st.leader_count)
    assert leaders[meta.broker_index(1)] == 2
    assert leaders[meta.broker_index(0)] == 0
    assert res.num_leadership_movements == 2


def test_rack_aware_satisfiable_fixed_by_one_move():
    ct, meta = fixtures.rack_aware_satisfiable()
    res = _optimize(ct, meta, ["RackAwareGoal"], raise_on_failure=True)
    assert "RackAwareGoal" not in res.violated_goals_after
    st = res.final_state
    prc = np.asarray(st.part_rack_count)
    assert (prc[0] <= 1).all() and prc[0].sum() == 2   # one replica per rack
    assert res.num_replica_movements == 1
    verify(ct, meta, res, ["REGRESSION"])


def test_rack_aware_unsatisfiable_raises():
    """RF=3 with 2 racks: OptimizationFailureException parity
    (DeterministicClusterTest expectedException case)."""
    ct, meta = fixtures.rack_aware_unsatisfiable()
    with pytest.raises(OptimizationFailureError):
        _optimize(ct, meta, ["RackAwareGoal"], raise_on_failure=True)


def test_unbalanced4_disk_distribution_swaps():
    """unbalanced4(): RF=1 linear loads 51k..72k split 222k/270k across two
    brokers; DiskUsageDistributionGoal must bring both within the 1.10
    balance band (avg 246k -> [~221k, ~268k] with margin 0.9)."""
    ct, meta = fixtures.unbalanced_two_brokers()
    res = _optimize(ct, meta, ["DiskUsageDistributionGoal"])
    assert "DiskUsageDistributionGoal" not in res.violated_goals_after
    util = np.asarray(res.final_state.util)[:, 3]
    avg = util[:2].mean()
    dev = (1.10 - 1.0) * 0.9
    assert util[:2].max() <= avg * (1 + dev) + 100.0
    assert util[:2].min() >= avg * (1 - dev) - 100.0
    verify(ct, meta, res, ["REGRESSION"])


def test_unbalanced4_intra_broker_disk_distribution():
    """unbalanced4() also seeds each broker's two logdirs unevenly; the
    intra-broker goal balances them without any inter-broker movement
    (DeterministicClusterTest IntraBrokerDiskUsageDistributionGoal case)."""
    ct, meta = fixtures.unbalanced_two_brokers()
    res = _optimize(ct, meta, ["IntraBrokerDiskUsageDistributionGoal"])
    st = res.final_state
    # final state is bucket-padded; compare the real replica prefix only
    R = ct.num_replicas
    np.testing.assert_array_equal(np.asarray(st.replica_broker)[:R],
                                  np.asarray(ct.replica_broker))
    assert "IntraBrokerDiskUsageDistributionGoal" not in res.violated_goals_after


def test_new_broker_rebalance_only_targets_new_brokers():
    """OptimizationVerifier NEW_BROKERS: with broker 2 flagged new, the
    rebalance may only move replicas onto it."""
    ct, meta = fixtures.unbalanced2()
    new = np.zeros(ct.num_brokers, bool)
    new[meta.broker_index(2)] = True
    import jax.numpy as jnp
    ct = dataclasses.replace(ct, broker_new=jnp.asarray(new))
    res = _optimize(ct, meta, ["ReplicaDistributionGoal"])
    verify(ct, meta, res, ["NEW_BROKERS", "REGRESSION"])
    assert res.proposals, "expected the new broker to receive replicas"


def test_broken_broker_self_healing():
    """OptimizationVerifier BROKEN_BROKERS over the dead-broker fixture."""
    ct, meta = fixtures.dead_broker_cluster()
    res = _optimize(ct, meta, ["RackAwareGoal", "ReplicaCapacityGoal",
                               "DiskCapacityGoal", "ReplicaDistributionGoal"])
    verify(ct, meta, res, ["BROKEN_BROKERS"])


def test_overfull_cluster_raises_with_provision_recommendation():
    """VERDICT item 7: an over-full cluster raises OptimizationFailureError
    carrying an UNDER_PROVISIONED recommendation with a broker count
    (reference OptimizationFailureException + ProvisionRecommendation)."""
    from cruise_control_tpu.detector.provisioner import ProvisionStatus
    from cruise_control_tpu.model.builder import ClusterModelBuilder
    b = ClusterModelBuilder()
    for i in range(3):
        b.add_broker(i, rack=f"r{i}", capacity={3: 1000.0})
    # 9 x 320 MB = 2880 > 3 brokers x 1000 x 0.8 = 2400 allowed (the 100 MB
    # disk epsilon would swallow a deficit smaller than that per broker)
    for p in range(9):
        b.add_replica("T1", p, broker_id=p % 3, is_leader=True,
                      load=[1.0, 10.0, 20.0, 320.0])
    ct, meta = b.build()
    with pytest.raises(OptimizationFailureError) as ei:
        _optimize(ct, meta, ["DiskCapacityGoal"], raise_on_failure=True)
    rec = ei.value.recommendation
    assert rec is not None
    assert rec.status is ProvisionStatus.UNDER_PROVISIONED
    # deficit 300 MB / (1000 * 0.8) -> 1 more broker
    assert rec.num_brokers == 1
    assert "DISK" in rec.reason


def test_goal_violation_detector_reports_under_provisioned():
    from cruise_control_tpu.backend import SimulatedClusterBackend
    from cruise_control_tpu.config import cruise_control_config
    from cruise_control_tpu.detector.detectors import GoalViolationDetector
    from cruise_control_tpu.detector.provisioner import (
        NoopProvisioner, ProvisionStatus,
    )
    from cruise_control_tpu.monitor import LoadMonitor
    from cruise_control_tpu.monitor.sampling.samplers import SimulatedMetricSampler
    be = SimulatedClusterBackend()
    for i in range(2):
        be.add_broker(i, f"r{i}", logdirs={"/d": 1000.0})
    for p in range(8):
        be.create_partition("T1", p, [p % 2], size_mb=250.0, bytes_in_rate=5.0)
    lm = LoadMonitor(config=cruise_control_config(
        {"min.samples.per.metrics.window": 1}), backend=be,
        sampler=SimulatedMetricSampler(be))
    lm.start_up()
    for i in range(8):
        lm.sample_once(now_ms=i * 300_000.0)
    det = GoalViolationDetector(GoalOptimizer(), lm, ["DiskCapacityGoal"],
                                provisioner=NoopProvisioner())
    det.run_once(0.0)
    assert det.last_provision is not None
    assert det.last_provision.status is ProvisionStatus.UNDER_PROVISIONED
    assert det.last_provision.num_brokers >= 1
