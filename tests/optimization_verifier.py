"""OptimizationVerifier analogue.

Reference: analyzer/OptimizationVerifier.java:53 — after an optimization,
assert (NEW_BROKERS) a new-broker rebalance only moves replicas TO the new
brokers, (BROKEN_BROKERS) dead brokers end up empty with no offline replicas,
(REGRESSION, :94-117) no per-resource distribution statistic regresses, plus
goal-specific invariants handled by the per-goal tests.
"""
from __future__ import annotations

import numpy as np


def verify_new_brokers(ct, meta, res) -> None:
    """Replicas may only move onto brokers flagged new (OptimizationVerifier
    NEW_BROKERS)."""
    new_ids = {meta.broker_ids[i]
               for i in np.flatnonzero(np.asarray(ct.broker_new))}
    for p in res.proposals:
        added = set(p.replicas_to_add)
        assert added <= new_ids, (
            f"{p.tp}: replicas moved to non-new brokers {added - new_ids}")


def verify_broken_brokers(ct, meta, res) -> None:
    """Dead brokers end up empty; nothing remains offline (BROKEN_BROKERS)."""
    st = res.final_state
    alive = np.asarray(res.env.broker_alive)
    rb = np.asarray(st.replica_broker)
    valid = np.asarray(res.env.replica_valid)
    on_dead = valid & ~alive[rb]
    assert not on_dead.any(), f"{int(on_dead.sum())} replicas left on dead brokers"
    assert not (np.asarray(st.replica_offline) & valid).any(), \
        "offline replicas remain after optimization"


def verify_no_regression(res) -> None:
    """ROLLING per-goal monotonicity (OptimizationVerifier.verifyRegression
    :94-117 semantics: each goal's stats comparator rates its post-run state
    against the state THE GOAL STARTED FROM — `preStats = entry.getValue()`
    rolls forward — NOT against the pre-chain state; an earlier goal may
    legally worsen a later goal's statistic as long as the later goal's own
    run doesn't regress its own measure)."""
    for g in res.goal_results:
        assert g.stat_after <= g.stat_before * 1.0001 + 1e-6, (
            f"{g.name} regressed its own stat during its run: "
            f"{g.stat_before:.4f} -> {g.stat_after:.4f}")
    before, after = res.stats_before, res.stats_after
    assert after["num_offline_replicas"] <= before["num_offline_replicas"]


def verify(ct, meta, res, verifications=("REGRESSION",)) -> None:
    for v in verifications:
        if v == "NEW_BROKERS":
            verify_new_brokers(ct, meta, res)
        elif v == "BROKEN_BROKERS":
            verify_broken_brokers(ct, meta, res)
        elif v == "REGRESSION":
            verify_no_regression(res)
        else:
            raise ValueError(f"unknown verification {v}")
