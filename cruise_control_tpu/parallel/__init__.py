from cruise_control_tpu.parallel.sharding import (
    BROKER_AXIS, count_collectives, committed_per_device_bytes, make_mesh,
    replicate, shard_cluster,
)
from cruise_control_tpu.parallel import shard_ops

__all__ = ["BROKER_AXIS", "count_collectives", "committed_per_device_bytes",
           "make_mesh", "replicate", "shard_cluster", "shard_ops"]
