"""Simulated cluster backend.

Fills the role of the reference's embedded test cluster
(CCKafkaIntegrationTestHarness + CCEmbeddedBroker/CCEmbeddedZookeeper,
cruise-control-metrics-reporter/src/test/.../utils/CCEmbeddedBroker.java:21)
AND of a dev/demo target: a fully in-process cluster with brokers, partitions,
replica placement, leadership, metric emission with configurable noise, and
time-based replica-movement execution with throttling.

Reassignments do not complete instantly: each added replica must "copy"
``size_mb`` at the (throttled) replication rate; ``advance(dt)`` moves
simulated time forward. This is what makes executor tests meaningful
(progress polling, concurrency caps, throttle behavior) without a JVM.
"""
from __future__ import annotations

import dataclasses
import threading

import numpy as np

from cruise_control_tpu.backend.interface import (
    BrokerNode, ClusterSnapshot, PartitionInfo,
)


@dataclasses.dataclass
class _InFlight:
    tp: tuple
    adding: list                    # broker ids still copying
    target: list                    # final replica list
    copied_mb: dict = dataclasses.field(default_factory=dict)


DEFAULT_REPLICATION_RATE_KBPS = 100_000.0   # unthrottled copy rate per replica


class SimulatedClusterBackend:
    """In-process cluster. All public methods are thread-safe."""

    def __init__(self, metric_noise: float = 0.0, seed: int = 0):
        self._lock = threading.RLock()
        self._brokers: dict[int, BrokerNode] = {}
        self._partitions: dict[tuple, PartitionInfo] = {}
        self._inflight: dict[tuple, _InFlight] = {}
        self._throttle: int | None = None
        self._meta_gen = 0
        self._now_ms = 0.0
        self._noise = metric_noise
        self._rng = np.random.default_rng(seed)
        self._metric_overrides: dict[int, dict[str, float]] = {}
        self._silenced: set[int] = set()    # brokers with a metric gap
        self._leadership_latency_ms = 0.0   # slow-election fault injection
        # (at_ms, seq, callback) fault events fired at their exact simulated
        # time from advance() — the scenario engine's injection mechanism
        self._scheduled: list[tuple] = []
        self._sched_seq = 0
        self._topic_configs: dict[str, dict] = {}
        # coordination leases (ZK-ephemeral-node role): key -> {holder,
        # expiresMs, epoch}; expiry is judged on the SIMULATED clock, so
        # election/renewal/failover in sim campaigns is bit-reproducible
        self._leases: dict[str, dict] = {}
        self._partitions_snapshot: tuple | None = None   # (meta_gen, dict)
        # --- incremental columnar state (ClusterSnapshot source) ---
        # one row per partition in CREATION order; every partition mutator
        # patches only the touched rows (O(changes)), and ``snapshot()``
        # assembles the sorted CSR view with a few vectorized gathers, cached
        # per metadata generation. ``_c_stride`` is the replica-slot capacity
        # per row (grown when a partition's RF exceeds it).
        self._c_dix: dict[int, dict] = {}       # broker -> {logdir: index}
        self._c_rows: dict[tuple, int] = {}     # tp -> row
        self._c_tps: list[tuple] = []           # row -> tp
        self._c_topic: list[str] = []           # row -> topic name
        self._c_stride = 4
        self._c_nrep = np.zeros(0, np.int64)
        self._c_leader = np.zeros(0, np.int64)
        self._c_rep_bid = np.zeros((0, self._c_stride), np.int64)
        self._c_rep_disk = np.zeros((0, self._c_stride), np.int64)
        self._c_metrics = np.zeros((0, 4), np.float64)  # cpu, size, b_in, b_out
        self._c_order: np.ndarray | None = None  # sorted-row permutation cache
        self._col_snapshot: tuple | None = None  # (meta_gen, ClusterSnapshot)

    def configure(self, config, **extra):
        pass

    # ------------------------------------------- columnar state maintenance
    def _c_logdir_index(self, broker: int, logdir) -> int:
        """Logdir name -> index in the broker's logdir order (0 = unknown,
        the same fallback the dict-consuming model build applies)."""
        lut = self._c_dix.get(broker)
        if lut is None:
            lut = self._c_dix[broker] = {
                ld: d for d, ld in enumerate(self._brokers[broker].logdirs)}
        return lut.get(logdir, 0)

    def _c_update(self, tp: tuple) -> None:
        """Write one partition's columnar row from its PartitionInfo
        (O(RF); called by every mutator that touches the partition)."""
        info = self._partitions[tp]
        row = self._c_rows.get(tp)
        if row is None:
            row = len(self._c_tps)
            self._c_rows[tp] = row
            self._c_tps.append(tp)
            self._c_topic.append(tp[0])
            self._c_order = None            # sorted view must be rebuilt
            if row >= self._c_nrep.shape[0]:
                grow = max(64, self._c_nrep.shape[0])
                S = self._c_stride
                self._c_nrep = np.concatenate(
                    [self._c_nrep, np.zeros(grow, np.int64)])
                self._c_leader = np.concatenate(
                    [self._c_leader, np.full(grow, -1, np.int64)])
                self._c_rep_bid = np.concatenate(
                    [self._c_rep_bid, np.full((grow, S), -1, np.int64)])
                self._c_rep_disk = np.concatenate(
                    [self._c_rep_disk, np.zeros((grow, S), np.int64)])
                self._c_metrics = np.concatenate(
                    [self._c_metrics, np.zeros((grow, 4), np.float64)])
        n = len(info.replicas)
        if n > self._c_stride:
            S = max(n, self._c_stride * 2)
            pad = ((0, 0), (0, S - self._c_stride))
            self._c_rep_bid = np.pad(self._c_rep_bid, pad, constant_values=-1)
            self._c_rep_disk = np.pad(self._c_rep_disk, pad)
            self._c_stride = S
        self._c_nrep[row] = n
        self._c_leader[row] = info.leader
        self._c_rep_bid[row, :n] = info.replicas
        self._c_rep_bid[row, n:] = -1
        ld_of = info.logdir_by_broker
        self._c_rep_disk[row, :n] = [
            self._c_logdir_index(b, ld_of.get(b)) for b in info.replicas]
        self._c_rep_disk[row, n:] = 0
        self._c_metrics[row] = (info.cpu_util, info.size_mb,
                                info.bytes_in_rate, info.bytes_out_rate)

    def snapshot(self) -> ClusterSnapshot:
        """Columnar metadata snapshot (cached per metadata generation).
        Row maintenance is O(changes) in the mutators; assembly here is a
        handful of vectorized gathers over the row store."""
        with self._lock:
            cached = self._col_snapshot
            if cached is not None and cached[0] == self._meta_gen:
                return cached[1]
            n = len(self._c_tps)
            if self._c_order is None:
                self._c_order = np.fromiter(
                    (self._c_rows[tp] for tp in sorted(self._c_rows)),
                    dtype=np.int64, count=n)
            order = self._c_order
            nrep = self._c_nrep[order]
            rep_ptr = np.zeros(n + 1, np.int64)
            np.cumsum(nrep, out=rep_ptr[1:])
            mask = np.arange(self._c_stride)[None, :] < nrep[:, None]
            bid_rows = self._c_rep_bid[order]
            leader = self._c_leader[order]
            topics = sorted(set(self._c_topic))
            tindex = {t: i for i, t in enumerate(topics)}
            topic_rows = [self._c_topic[r] for r in order] if n else []
            broker_ids = np.asarray(sorted(self._brokers), np.int64)
            snap = ClusterSnapshot(
                generation=self._meta_gen,
                topics=topics,
                partition_keys=[self._c_tps[r] for r in order],
                partition_topic=np.fromiter((tindex[t] for t in topic_rows),
                                            dtype=np.int64, count=n),
                partition_leader=leader,
                rep_ptr=rep_ptr,
                rep_bid=bid_rows[mask],
                rep_leader=(bid_rows == leader[:, None])[mask],
                rep_disk=self._c_rep_disk[order][mask],
                broker_ids=broker_ids,
                broker_alive=np.asarray(
                    [self._brokers[b].alive for b in broker_ids], bool),
                broker_rack=[self._brokers[b].rack for b in broker_ids],
                broker_logdirs=[list(self._brokers[b].logdirs) or ["/logdir0"]
                                for b in broker_ids])
            self._col_snapshot = (self._meta_gen, snap)
            return snap

    # -- per-topic config (TopicConfigProvider source; the real cluster's
    #    describeConfigs analogue) --
    def set_topic_config(self, topic: str, key: str, value) -> None:
        """``value=None`` deletes the entry (the alterConfigs DELETE op the
        throttle-helper cleanup uses, ReplicationThrottleHelper.java:200)."""
        with self._lock:
            if value is None:
                cfgs = self._topic_configs.get(topic)
                if cfgs is not None:
                    cfgs.pop(key, None)
                    if not cfgs:
                        del self._topic_configs[topic]
            else:
                self._topic_configs.setdefault(topic, {})[key] = value

    def topic_configs(self) -> dict:
        with self._lock:
            return {t: dict(c) for t, c in self._topic_configs.items()}

    # ------------------------------------------------------------------ setup
    def add_broker(self, broker_id: int, rack: str, logdirs: dict | None = None,
                   cpu_capacity: float = 100.0, nw_in_capacity: float = 50_000.0,
                   nw_out_capacity: float = 50_000.0) -> "SimulatedClusterBackend":
        with self._lock:
            self._brokers[broker_id] = BrokerNode(
                broker_id=broker_id, rack=rack,
                logdirs=dict(logdirs or {"/logdir0": 500_000.0}),
                cpu_capacity=cpu_capacity, nw_in_capacity=nw_in_capacity,
                nw_out_capacity=nw_out_capacity)
            self._c_dix.pop(broker_id, None)   # logdir order may have changed
            self._meta_gen += 1
        return self

    def create_partition(self, topic: str, partition: int, replicas: list,
                         size_mb: float = 0.0, bytes_in_rate: float = 0.0,
                         bytes_out_rate: float = 0.0, cpu_util: float = 0.0,
                         logdir_by_broker: dict | None = None) -> "SimulatedClusterBackend":
        with self._lock:
            for b in replicas:
                if b not in self._brokers:
                    raise ValueError(f"unknown broker {b}")
            logdirs = dict(logdir_by_broker or {})
            for b in replicas:
                logdirs.setdefault(b, next(iter(self._brokers[b].logdirs)))
            self._partitions[(topic, partition)] = PartitionInfo(
                topic=topic, partition=partition, replicas=list(replicas),
                leader=replicas[0], logdir_by_broker=logdirs, size_mb=size_mb,
                bytes_in_rate=bytes_in_rate, bytes_out_rate=bytes_out_rate,
                cpu_util=cpu_util)
            self._c_update((topic, partition))
            self._meta_gen += 1
        return self

    # ------------------------------------------------------- fault injection
    def kill_broker(self, broker_id: int) -> None:
        with self._lock:
            self._brokers[broker_id].alive = False
            for tp, info in self._partitions.items():
                if info.leader == broker_id:
                    survivors = [b for b in info.replicas
                                 if self._brokers[b].alive]
                    info.leader = survivors[0] if survivors else -1
                    self._c_update(tp)
            self._meta_gen += 1

    def restart_broker(self, broker_id: int) -> None:
        with self._lock:
            self._brokers[broker_id].alive = True
            self._meta_gen += 1

    def fail_disk(self, broker_id: int, logdir: str) -> None:
        with self._lock:
            self._brokers[broker_id].dead_logdirs.add(logdir)
            self._meta_gen += 1

    def shrink_replicas(self, topic: str, target_rf: int) -> int:
        """Fault injection: drop tail replicas of every partition of
        ``topic`` down to ``target_rf`` (the under-replicated-topic anomaly a
        TopicReplicationFactorAnomalyFinder must detect and repair). The
        leader survives when it can; partitions with an in-flight
        reassignment are skipped (their replica list is owned by the copy
        machinery). Returns the number of partitions shrunk."""
        with self._lock:
            changed = 0
            for tp, info in self._partitions.items():
                if (tp[0] != topic or tp in self._inflight
                        or len(info.replicas) <= target_rf):
                    continue
                keep = list(info.replicas)
                if info.leader in keep:
                    keep = [info.leader] + [b for b in keep if b != info.leader]
                dropped = keep[max(target_rf, 1):]
                info.replicas = keep[:max(target_rf, 1)]
                for b in dropped:
                    info.logdir_by_broker.pop(b, None)
                if info.leader not in info.replicas:
                    alive = [b for b in info.replicas
                             if self._brokers[b].alive]
                    info.leader = alive[0] if alive else -1
                self._c_update(tp)
                changed += 1
            if changed:
                self._meta_gen += 1
            return changed

    def scale_partition_load(self, factor: float, topics=None) -> None:
        """Fault injection: multiply the cpu/bytes-in/bytes-out rates of every
        partition (optionally restricted to ``topics``) — a traffic surge the
        GoalViolationDetector's provision math must flag UNDER_PROVISIONED.
        Disk size is deliberately untouched: a surge is load, not data."""
        with self._lock:
            for tp, info in self._partitions.items():
                if topics is not None and tp[0] not in topics:
                    continue
                info.cpu_util *= factor
                info.bytes_in_rate *= factor
                info.bytes_out_rate *= factor
                self._c_update(tp)
            self._meta_gen += 1

    def scale_rack_load(self, factor: float, rack: str) -> None:
        """Fault injection: multiply the cpu/bytes rates of every partition
        with a replica on ``rack``'s brokers — a correlated failure-domain
        surge (one rack's tenants get hot together). Like
        :meth:`scale_partition_load`, load only; disk size untouched."""
        with self._lock:
            rack_brokers = {b for b, info in self._brokers.items()
                            if info.rack == rack}
            for tp, info in self._partitions.items():
                if rack_brokers.isdisjoint(info.replicas):
                    continue
                info.cpu_util *= factor
                info.bytes_in_rate *= factor
                info.bytes_out_rate *= factor
                self._c_update(tp)
            self._meta_gen += 1

    def decommission_broker(self, broker_id: int) -> None:
        """Remove an EMPTY broker from the cluster (the provisioner's
        OVER_PROVISIONED actuation; the reference delegates this to a cloud
        autoscaler behind the Provisioner SPI). Refuses while the broker
        still hosts replicas or is a reassignment target — drain first."""
        with self._lock:
            hosting = sum(1 for info in self._partitions.values()
                          if broker_id in info.replicas)
            if hosting:
                raise RuntimeError(
                    f"broker {broker_id} still hosts {hosting} replicas")
            for tp, fl in self._inflight.items():
                if broker_id in fl.target or broker_id in fl.adding:
                    raise RuntimeError(
                        f"broker {broker_id} is a reassignment target for {tp}")
            del self._brokers[broker_id]
            self._c_dix.pop(broker_id, None)
            self._metric_overrides.pop(broker_id, None)
            self._silenced.discard(broker_id)
            self._meta_gen += 1

    def set_metric_silence(self, broker_id: int, silent: bool) -> None:
        """Fault injection: a silenced broker stops emitting broker metrics
        and leader partition metrics (a reporting gap, NOT a failure — the
        broker stays alive in metadata)."""
        with self._lock:
            if silent:
                self._silenced.add(broker_id)
            else:
                self._silenced.discard(broker_id)

    # ---------------------------------------------------------------- leases
    def lease_acquire(self, key: str, holder: str, ttl_ms: float) -> dict:
        """Atomic compare-and-swap lease (ClusterBackend protocol): grant
        when the key is free, the current lease has expired on the backend
        clock, or ``holder`` already owns it (renewal — including
        re-asserting its own EXPIRED lease after e.g. a long blocking heal).
        The epoch is a fencing token: it increments only when OWNERSHIP
        changes, never on a same-holder renewal or re-assert."""
        with self._lock:
            now = self._now_ms
            cur = self._leases.get(key)
            if cur is not None and cur["holder"] != holder \
                    and cur["expiresMs"] > now:
                out = dict(cur, key=key, acquired=False)
                return out
            epoch = (cur["epoch"] if cur is not None
                     and cur["holder"] == holder
                     else (cur["epoch"] + 1 if cur is not None else 1))
            self._leases[key] = {"holder": holder,
                                 "expiresMs": now + float(ttl_ms),
                                 "epoch": epoch}
            return dict(self._leases[key], key=key, acquired=True)

    def lease_release(self, key: str, holder: str) -> bool:
        """Voluntary release; a no-op unless ``holder`` owns the lease."""
        with self._lock:
            cur = self._leases.get(key)
            if cur is None or cur["holder"] != holder:
                return False
            del self._leases[key]
            return True

    def lease_get(self, key: str) -> dict | None:
        with self._lock:
            cur = self._leases.get(key)
            if cur is None:
                return None
            return dict(cur, key=key,
                        expired=cur["expiresMs"] <= self._now_ms)

    # ---------------------------------------------------------------- clock
    def now_ms(self) -> float:
        """Canonical ClusterBackend clock accessor (method, like the RPC
        client and every other backend — see ClusterBackend protocol)."""
        return self._now_ms

    def schedule_at(self, at_ms: float, callback) -> None:
        """Register ``callback(now_ms)`` to fire when simulated time reaches
        ``at_ms`` — from whichever ``advance`` call crosses it, including the
        executor's own progress-poll sleeps. This is what lets the scenario
        engine inject a broker death in the middle of a blocking proposal
        execution at an exact, reproducible simulated time."""
        with self._lock:
            self._scheduled.append((float(at_ms), self._sched_seq, callback))
            self._sched_seq += 1

    def advance(self, dt_ms: float) -> None:
        """Advance simulated time, stopping at every scheduled fault event so
        callbacks observe (and mutate) the cluster at their exact time."""
        remaining = float(dt_ms)
        while True:
            # fire everything due at the CURRENT time first (an event
            # scheduled at exactly now must not slip a whole step)
            with self._lock:
                now = self._now_ms
                due = sorted(e for e in self._scheduled if e[0] <= now)
                self._scheduled = [e for e in self._scheduled if e[0] > now]
            for _, _, cb in due:
                cb(now)
            if remaining <= 0:
                return
            with self._lock:
                pending = [t for t, _, _ in self._scheduled if t > now]
                next_due = min(pending) if pending else None
            step = remaining
            if next_due is not None and next_due < now + remaining:
                step = max(next_due - now, 0.0)
            self._advance_step(step)
            remaining -= step

    def _advance_step(self, dt_ms: float) -> None:
        """Progress in-flight reassignments over an event-free interval."""
        with self._lock:
            self._now_ms += dt_ms
            rate_kbps = (self._throttle / 1024.0 if self._throttle
                         else DEFAULT_REPLICATION_RATE_KBPS)
            done_tps = []
            for tp, fl in self._inflight.items():
                info = self._partitions[tp]
                mb = rate_kbps * (dt_ms / 1000.0) / 1024.0
                still = []
                touched = False
                for b in fl.adding:
                    fl.copied_mb[b] = fl.copied_mb.get(b, 0.0) + mb
                    if fl.copied_mb[b] >= info.size_mb:
                        # replica caught up: joins the replica list
                        if b not in info.replicas:
                            info.replicas.append(b)
                            info.logdir_by_broker.setdefault(
                                b, next(iter(self._brokers[b].logdirs)))
                            touched = True
                    else:
                        still.append(b)
                fl.adding = still
                if not still:
                    # drop replicas not in the target list
                    removed = [b for b in info.replicas if b not in fl.target]
                    info.replicas = [b for b in fl.target]
                    for b in removed:
                        info.logdir_by_broker.pop(b, None)
                    if (info.leader not in info.replicas
                            or not self._brokers[info.leader].alive):
                        # a broker may die mid-reassignment: leadership must
                        # land on an ALIVE member of the new replica list
                        # (ISR election role), never a dead target
                        alive = [b for b in info.replicas
                                 if self._brokers[b].alive]
                        info.leader = alive[0] if alive else -1
                    done_tps.append(tp)
                    touched = True
                if touched:
                    self._c_update(tp)
            for tp in done_tps:
                del self._inflight[tp]
            if done_tps:
                self._meta_gen += 1

    # -------------------------------------------------------------- metadata
    def brokers(self) -> dict:
        with self._lock:
            return {b: dataclasses.replace(n, logdirs=dict(n.logdirs),
                                           dead_logdirs=set(n.dead_logdirs))
                    for b, n in self._brokers.items()}

    def partitions(self) -> dict:
        """Metadata snapshot, cached per metadata generation (every mutator
        bumps ``_meta_gen``): the deep copy costs ~10 us per partition, and
        the monitor/executor/detector layers read this several times per
        round at up to 500k partitions. Callers must treat the returned
        snapshot as immutable."""
        with self._lock:
            cached = self._partitions_snapshot
            if cached is not None and cached[0] == self._meta_gen:
                return cached[1]
            snap = {tp: dataclasses.replace(
                        info, replicas=list(info.replicas),
                        logdir_by_broker=dict(info.logdir_by_broker))
                    for tp, info in self._partitions.items()}
            self._partitions_snapshot = (self._meta_gen, snap)
            return snap

    def metadata_generation(self) -> int:
        with self._lock:
            return self._meta_gen

    # --------------------------------------------------------------- metrics
    def _jitter(self, v: float) -> float:
        if self._noise <= 0 or v == 0:
            return v
        return float(v * (1.0 + self._rng.normal(0, self._noise)))

    def partition_metrics(self) -> dict:
        """Model-metric rows per partition (CruiseControlMetricsProcessor
        output shape: CPU_USAGE / DISK_USAGE / LEADER_BYTES_IN / LEADER_BYTES_OUT)."""
        with self._lock:
            out = {}
            for tp, info in self._partitions.items():
                if (info.leader < 0 or not self._brokers[info.leader].alive
                        or info.leader in self._silenced):
                    continue
                out[tp] = {
                    "CPU_USAGE": self._jitter(info.cpu_util),
                    "DISK_USAGE": self._jitter(info.size_mb),
                    "LEADER_BYTES_IN": self._jitter(info.bytes_in_rate),
                    "LEADER_BYTES_OUT": self._jitter(info.bytes_out_rate),
                }
            return out

    PARTITION_METRIC_COLUMNS = ("CPU_USAGE", "DISK_USAGE",
                                "LEADER_BYTES_IN", "LEADER_BYTES_OUT")

    def partition_metrics_columnar(self):
        """(entities, metric_names, values[N, 4]) — the columnar twin of
        ``partition_metrics()``: one vectorized pass over the row store
        instead of 500k small dicts + 2M jitter calls per sampling round.
        Rows cover partitions with an alive leader, like the dict path."""
        with self._lock:
            n = len(self._c_tps)
            leader = self._c_leader[:n]
            alive_ids = np.asarray(
                sorted(b for b, node in self._brokers.items()
                       if node.alive and b not in self._silenced),
                np.int64)
            mask = (leader >= 0) & np.isin(leader, alive_ids)
            rows = np.flatnonzero(mask)
            values = self._c_metrics[rows].copy()
            if self._noise > 0 and values.size:
                jitter = 1.0 + self._rng.normal(0, self._noise, values.shape)
                values = np.where(values != 0, values * jitter, values)
            entities = [self._c_tps[r] for r in rows]
            return entities, list(self.PARTITION_METRIC_COLUMNS), values

    def broker_metrics(self) -> dict:
        with self._lock:
            # vectorized accumulate-by-leader over the columnar row store
            # (the former per-partition Python loop was ~seconds per
            # sampling round at 500k partitions)
            n = len(self._c_tps)
            leader = self._c_leader[:n]
            ids = np.asarray(sorted(self._brokers), np.int64)
            sums = np.zeros((ids.size, 3))          # cpu, b_in, b_out
            mask = leader >= 0
            if mask.any():
                pos = np.searchsorted(ids, leader[mask])
                np.add.at(sums, pos,
                          self._c_metrics[:n][mask][:, [0, 2, 3]])
            out = {}
            for bi, b in enumerate(ids.tolist()):
                node = self._brokers[b]
                if not node.alive or b in self._silenced:
                    continue
                cpu, lin, lout = sums[bi]
                out[b] = {
                    "BROKER_CPU_UTIL": self._jitter(cpu),
                    "ALL_TOPIC_BYTES_IN": self._jitter(lin),
                    "ALL_TOPIC_BYTES_OUT": self._jitter(lout),
                    "BROKER_LOG_FLUSH_TIME_MS_MEAN": self._jitter(1.0),
                    "BROKER_LOG_FLUSH_TIME_MS_999TH": self._jitter(5.0),
                }
                out[b].update(self._metric_overrides.get(b, {}))
            return out

    def override_broker_metric(self, broker_id: int, metric: str,
                               value: float | None) -> None:
        """Fault injection: pin a broker metric (None clears the override) —
        drives slow-broker / concurrency-adjuster scenarios in tests."""
        with self._lock:
            if value is None:
                self._metric_overrides.get(broker_id, {}).pop(metric, None)
            else:
                self._metric_overrides.setdefault(broker_id, {})[metric] = value

    # -------------------------------------------------------------- actuation
    def alter_partition_reassignments(self, assignments: dict) -> None:
        """Start reassignments: {(topic, part): [target broker ids]}
        (the ZK reassignment-znode write, Executor.java:1272)."""
        with self._lock:
            for tp, target in assignments.items():
                info = self._partitions[tp]
                for b in target:
                    if b not in self._brokers:
                        raise ValueError(f"unknown broker {b} for {tp}")
                adding = [b for b in target if b not in info.replicas]
                if tp in self._inflight:
                    raise RuntimeError(f"reassignment already in flight for {tp}")
                self._inflight[tp] = _InFlight(tp=tp, adding=adding,
                                               target=list(target))
                if not adding:
                    # pure shrink/reorder completes on next advance
                    pass
            self._meta_gen += 1

    def apply_assignment(self, proposals) -> int:
        """Instantly complete an execution-proposal set: the cluster jumps
        to the proposals' target placement (replica sets, leadership,
        logdirs) as if every reassignment had finished — the bench/test
        convergence helper for measuring steady-state rounds against a
        cluster that actually REACHED the optimizer's target, without
        simulating hours of copy throttling. Partitions with an in-flight
        reassignment are skipped (their replica list is owned by the copy
        machinery). Returns the number of partitions touched."""
        with self._lock:
            n = 0
            for p in proposals:
                tp = (p.topic, p.partition)
                info = self._partitions.get(tp)
                if info is None or tp in self._inflight:
                    continue
                new_b = [b for b, _ in p.new_replicas]
                if any(b not in self._brokers for b in new_b):
                    raise ValueError(f"unknown broker in target for {tp}")
                removed = [b for b in info.replicas if b not in new_b]
                info.replicas = new_b
                for b, ld in p.new_replicas:
                    lds = list(self._brokers[b].logdirs)
                    info.logdir_by_broker[b] = (
                        lds[ld] if 0 <= ld < len(lds) else lds[0])
                for b in removed:
                    info.logdir_by_broker.pop(b, None)
                leader = p.new_leader
                if (leader not in info.replicas
                        or not self._brokers[leader].alive):
                    alive = [b for b in info.replicas
                             if self._brokers[b].alive]
                    leader = alive[0] if alive else -1
                info.leader = leader
                self._c_update(tp)
                n += 1
            if n:
                self._meta_gen += 1
            return n

    def ongoing_reassignments(self) -> dict:
        with self._lock:
            return {tp: {"adding": list(fl.adding), "target": list(fl.target)}
                    for tp, fl in self._inflight.items()}

    def cancel_reassignments(self, tps: list) -> None:
        """Force-stop: delete the 'znode' (ExecutionUtils.java:305-307)."""
        with self._lock:
            for tp in tps:
                fl = self._inflight.pop(tp, None)
                if fl is None:
                    continue
                info = self._partitions[tp]
                # adding replicas that finished stay; unfinished are dropped
                info.replicas = [b for b in info.replicas]
            self._meta_gen += 1

    def set_leadership_latency_ms(self, ms: float) -> None:
        """Fault injection: preferred-leader elections stop landing
        instantly — each submitted election takes effect ``ms`` simulated ms
        later (from whichever ``advance`` crosses it). Lets the executor's
        ``leader.movement.timeout.ms`` abandonment path and the campaign's
        slow-progress scenarios run against real (simulated) slowness."""
        with self._lock:
            self._leadership_latency_ms = max(float(ms), 0.0)

    def elect_leaders(self, tps_to_leader: dict) -> None:
        with self._lock:
            for tp, leader in tps_to_leader.items():
                info = self._partitions[tp]
                if leader not in info.replicas:
                    raise ValueError(f"{leader} not a replica of {tp}")
                if not self._brokers[leader].alive:
                    raise ValueError(f"broker {leader} is dead")
            latency = self._leadership_latency_ms
            if latency <= 0:
                for tp, leader in tps_to_leader.items():
                    self._partitions[tp].leader = leader
                    self._c_update(tp)
                self._meta_gen += 1
                return
            # slow-election mode: validation happened above (submission
            # succeeds), but the flip lands later; by then the cluster may
            # have changed, so the apply re-validates and silently drops a
            # now-ineligible election (a lost election, like the real thing)
            for tp, leader in tps_to_leader.items():
                def _apply(now, tp=tp, leader=leader):
                    with self._lock:
                        info = self._partitions.get(tp)
                        if (info is None or leader not in info.replicas
                                or not self._brokers[leader].alive):
                            return
                        info.leader = leader
                        self._c_update(tp)
                        self._meta_gen += 1
                self.schedule_at(self._now_ms + latency, _apply)

    def alter_replica_logdirs(self, moves: dict) -> None:
        """Intra-broker move: {(topic, part, broker): logdir}
        (AdminClient.alterReplicaLogDirs, ExecutorAdminUtils.java:70-88)."""
        with self._lock:
            for (topic, part, broker), logdir in moves.items():
                info = self._partitions[(topic, part)]
                if broker not in info.replicas:
                    raise ValueError(f"{broker} not a replica of {(topic, part)}")
                if logdir not in self._brokers[broker].logdirs:
                    raise ValueError(f"unknown logdir {logdir} on broker {broker}")
                info.logdir_by_broker[broker] = logdir
                self._c_update((topic, part))
            self._meta_gen += 1

    def describe_logdirs(self) -> dict:
        with self._lock:
            return {b: {ld: (ld not in n.dead_logdirs) and n.alive
                        for ld in n.logdirs}
                    for b, n in self._brokers.items()}

    def set_replication_throttle(self, rate_bytes_per_sec: int | None) -> None:
        with self._lock:
            self._throttle = rate_bytes_per_sec

    def replication_throttle(self) -> int | None:
        with self._lock:
            return self._throttle
