"""Incremental re-optimization (PR 16): certificate re-validation memo,
dirty-set candidate seeding, and the session-lifecycle carryover contract.

The invariants:
1. A zero-churn, drift-free steady round after a full round takes the
   whole-round certificate memo — 0 goals re-executed, zero new compiles,
   result identical to re-running the chain, no donation.
2. The carryover survives donation and fleet spill/readmit, drops its
   drift baseline on a shadow sync (conservative: one full round
   re-establishes it), and is INVALIDATED on epoch fallback (broker-set
   change) — a stale memo can never be served.
3. Dirty-set seeding (opt-in) keeps the one-sided parity contract vs the
   full path: violations only shrink, certificates only appear; the
   reduced<->full flip and the revalidate toggle add zero new XLA compiles
   (the masks are traced values).
"""
from __future__ import annotations

import numpy as np

from cruise_control_tpu.analyzer.session import ResidentClusterSession
from cruise_control_tpu.backend.simulated import SimulatedClusterBackend
from cruise_control_tpu.config import cruise_control_config
from cruise_control_tpu.monitor.load_monitor import LoadMonitor
from cruise_control_tpu.monitor.sampling.samplers import SimulatedMetricSampler

GOALS = ["ReplicaCapacityGoal", "ReplicaDistributionGoal",
         "LeaderReplicaDistributionGoal"]


def _backend(seed=0, num_brokers=10, num_partitions=60, rf=2):
    rng = np.random.default_rng(seed)
    be = SimulatedClusterBackend()
    for b in range(num_brokers):
        be.add_broker(b, f"r{b % 3}")
    for p in range(num_partitions):
        reps = [int(x) for x in rng.choice(num_brokers, size=rf,
                                           replace=False)]
        be.create_partition(f"t{p % 6}", p, reps,
                            size_mb=float(rng.uniform(10, 500)),
                            bytes_in_rate=float(rng.uniform(1, 50)),
                            bytes_out_rate=float(rng.uniform(1, 100)),
                            cpu_util=float(rng.uniform(0.1, 5)))
    return be


def _monitored(be, rounds=6, start_round=0):
    lm = LoadMonitor(backend=be, sampler=SimulatedMetricSampler(be))
    lm.start_up()
    for i in range(start_round, start_round + rounds):
        lm.sample_once(now_ms=i * 300_000.0)
    return lm


def _optimizer(extra=None):
    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
    cfg = {"goals": ",".join(GOALS), "hard.goals": "ReplicaCapacityGoal"}
    cfg.update(extra or {})
    return GoalOptimizer(config=cruise_control_config(cfg))


def _round(opt, sess):
    return opt.optimizations(None, session=sess, goal_names=GOALS,
                             raise_on_failure=False,
                             skip_hard_goal_check=True)


def _steady(opt, sess, lm, t):
    """sample -> sync -> optimize: one steady service round."""
    lm.sample_once(now_ms=t * 300_000.0)
    info = sess.sync()
    return info, _round(opt, sess)


def test_zero_churn_round_revalidates():
    """The tentpole: round 1 rebuild+full, round 2 delta+full (establishes
    the drift baseline), round 3 zero-churn -> whole-round memo with every
    goal revalidated, zero compiles, verdicts/proposals identical, and the
    resident session untouched (no donation)."""
    be = _backend()
    lm = _monitored(be)
    sess = ResidentClusterSession(lm)
    opt = _optimizer()

    assert sess.sync()["mode"] == "rebuild"
    r1 = _round(opt, sess)
    assert r1.round_mode == "full"          # rebuilt round never memoizes

    info, r2 = _steady(opt, sess, lm, 6)
    assert info["mode"] == "delta"
    assert r2.round_mode == "full"          # no baseline yet -> drift inf

    donated_before = sess.donated_rounds
    info, r3 = _steady(opt, sess, lm, 7)
    assert info["mode"] == "delta"
    assert r3.round_mode == "revalidated", (
        sess.pending_delta_json(), r3.round_mode)
    assert sess.revalidated_rounds == 1
    # no donation: the memo only peeked at the resident state
    assert sess.donated_rounds == donated_before
    # 0 goals re-executed, all carried
    assert all(g.mode == "revalidated" for g in r3.goal_results)
    # verdict + proposal identity with the carried full round
    assert r3.violated_goals_after == r2.violated_goals_after
    assert r3.num_replica_movements == r2.num_replica_movements
    assert len(r3.proposals) == len(r2.proposals)
    # zero new XLA compiles and the re-check cost is recorded
    assert r3.round_trace.compiles == 0
    assert r3.round_trace.round_mode == "revalidated"
    assert r3.revalidate_s >= 0.0
    assert r3.round_trace.goals[0]["mode"] == "revalidated"

    # memo rounds keep memoizing while nothing changes
    _, r4 = _steady(opt, sess, lm, 8)
    assert r4.round_mode == "revalidated"
    assert sess.revalidated_rounds == 2


def test_forced_rerun_without_sync_stays_full():
    """A re-run of an unchanged model (no sync between optimizes) must NOT
    memoize: rd['syncs'] == 0 keeps forced refreshes honest."""
    be = _backend(seed=5)
    lm = _monitored(be)
    sess = ResidentClusterSession(lm)
    opt = _optimizer()
    sess.sync()
    _round(opt, sess)
    _steady(opt, sess, lm, 6)               # establish baseline
    r = _round(opt, sess)                   # optimize again, NO sync
    assert r.round_mode == "full"
    assert sess.revalidated_rounds == 0


def test_churn_invalidates_memo_and_leadership_roundtrip():
    """Real churn falls back to the full program; once the disturbance is
    optimized through and the stream goes quiet again, the memo resumes."""
    be = _backend(seed=1)
    lm = _monitored(be)
    sess = ResidentClusterSession(lm)
    opt = _optimizer()
    sess.sync()
    _round(opt, sess)
    _steady(opt, sess, lm, 6)

    # leadership flip = churn > 0 -> full round
    info = be.partitions()[("t1", 1)]
    be.elect_leaders({("t1", 1): info.replicas[-1]})
    inf, r = _steady(opt, sess, lm, 7)
    assert inf["churn"] > 0
    assert r.round_mode == "full"

    # quiet again: the churn round itself re-baselined (it was a full
    # round), so the memo resumes on the very next quiet round
    _, r = _steady(opt, sess, lm, 8)
    assert r.round_mode == "revalidated"


def test_carryover_survives_spill_readmit():
    """Fleet spill/readmit: the carryover is host-side and the memo's
    revalidation view readmits the spilled env — a spilled steady tenant
    still revalidates."""
    be = _backend(seed=2)
    lm = _monitored(be)
    sess = ResidentClusterSession(lm)
    opt = _optimizer()
    sess.sync()
    _round(opt, sess)
    _steady(opt, sess, lm, 6)
    _, r = _steady(opt, sess, lm, 7)
    assert r.round_mode == "revalidated"

    assert sess.spill()
    assert sess.carryover is not None        # carryover is host-side
    _, r = _steady(opt, sess, lm, 8)         # sync readmits, then memo
    assert r.round_mode == "revalidated"
    assert sess.readmits >= 1


def test_epoch_fallback_invalidates_carryover():
    """Broker-set change -> rebuild (new epoch) -> carryover cleared; the
    next round runs full and can never serve the stale memo."""
    be = _backend(seed=3)
    lm = _monitored(be)
    sess = ResidentClusterSession(lm)
    opt = _optimizer()
    sess.sync()
    _round(opt, sess)
    _steady(opt, sess, lm, 6)
    _, r = _steady(opt, sess, lm, 7)
    assert r.round_mode == "revalidated"
    assert sess.carryover is not None

    be.add_broker(99, "r0")
    lm.sample_once(now_ms=8 * 300_000.0)
    info = sess.sync()
    assert info["mode"] == "rebuild"
    assert sess.carryover is None
    r = _round(opt, sess)
    assert r.round_mode == "full"

    # invalidate() clears it too
    sess.note_carryover(object())
    assert sess.carryover is not None
    sess.invalidate()
    assert sess.carryover is None


def test_shadow_sync_drops_drift_baseline():
    """note_carryover with a stale taken_generation (a shadow sync landed
    mid-round) drops the drift baseline: the carryover survives but the
    next round's drift reads inf -> full round (conservative)."""
    be = _backend(seed=4)
    lm = _monitored(be)
    sess = ResidentClusterSession(lm)
    opt = _optimizer()
    sess.sync()
    _round(opt, sess)
    _steady(opt, sess, lm, 6)
    assert sess.carryover is not None

    # emulate the shadow race: save a carryover against a generation that
    # is no longer current
    sess.note_carryover(sess.carryover,
                        taken_generation=sess.sync_generation - 1)
    _, r = _steady(opt, sess, lm, 7)
    assert r.round_mode == "full"            # baseline dropped -> drift inf
    _, r = _steady(opt, sess, lm, 8)
    assert r.round_mode == "revalidated"     # re-established


def test_chain_change_misses_memo():
    """A different goal chain must not reuse the carried round."""
    be = _backend(seed=6)
    lm = _monitored(be)
    sess = ResidentClusterSession(lm)
    opt = _optimizer()
    sess.sync()
    _round(opt, sess)
    _steady(opt, sess, lm, 6)
    lm.sample_once(now_ms=7 * 300_000.0)
    sess.sync()
    r = opt.optimizations(None, session=sess,
                          goal_names=GOALS[:2], raise_on_failure=False,
                          skip_hard_goal_check=True)
    assert r.round_mode == "full"


def test_revalidate_off_runs_full_rounds():
    be = _backend(seed=7)
    lm = _monitored(be)
    sess = ResidentClusterSession(lm)
    opt = _optimizer({"analyzer.incremental.revalidate": False})
    sess.sync()
    _round(opt, sess)
    _steady(opt, sess, lm, 6)
    _, r = _steady(opt, sess, lm, 7)
    assert r.round_mode == "full"
    assert sess.revalidated_rounds == 0


def test_seed_dirty_reduced_round_one_sided_parity():
    """Dirty-set seeding (opt-in): a small-churn round runs reduced on the
    goals the carried round left satisfied, with full-R fallback for any
    reduced goal ending violated-unproven. Parity vs the seed-off path is
    one-sided by construction: violations only shrink, certificates only
    appear."""
    results = {}
    for label, extra in (("full", {}),
                         ("reduced",
                          {"analyzer.incremental.seed.dirty": True})):
        be = _backend(seed=8)
        lm = _monitored(be)
        sess = ResidentClusterSession(lm)
        opt = _optimizer(extra)
        sess.sync()
        _round(opt, sess)
        # small churn: one leadership flip + one reassignment
        info = be.partitions()[("t2", 2)]
        be.elect_leaders({("t2", 2): info.replicas[-1]})
        lm.sample_once(now_ms=6 * 300_000.0)
        inf = sess.sync()
        assert inf["churn"] > 0
        results[label] = _round(opt, sess)

    full, red = results["full"], results["reduced"]
    assert full.round_mode == "full"
    # the reduced round is reduced only if some goal was satisfied at the
    # carried round's end; with this fixture at least one is
    assert red.round_mode == "reduced"
    assert any(g.mode == "reduced" for g in red.goal_results)
    viol_full = set(full.violated_goals_after)
    viol_red = set(red.violated_goals_after)
    assert viol_red.issubset(viol_full), (viol_red, viol_full)
    certs_full = {g.name for g in full.goal_results if g.fixpoint_proven}
    certs_red = {g.name for g in red.goal_results if g.fixpoint_proven}
    assert certs_full.issubset(certs_red), (certs_full, certs_red)


def test_knob_toggles_add_zero_compiles():
    """The parity contract's compile clause: with incremental enabled, the
    seed.dirty and revalidate toggles are VALUE-only — after the masked
    programs are warm, flipping either knob compiles nothing new."""
    be = _backend(seed=9)
    lm = _monitored(be)
    sess = ResidentClusterSession(lm)
    opt = _optimizer()
    sess.sync()
    r = _round(opt, sess)          # warms the masked chain (all-ones)
    _steady(opt, sess, lm, 6)      # warms memo re-check priming

    listener = opt._compile_listener
    n0 = listener.count
    # revalidate toggle: memo on (round 3) ...
    _, r = _steady(opt, sess, lm, 7)
    assert r.round_mode == "revalidated"
    # ... then off: the full masked chain re-runs, same executables
    opt._revalidate = False
    _, r = _steady(opt, sess, lm, 8)
    assert r.round_mode == "full"
    opt._revalidate = True
    # seed.dirty toggle: the dirty masks ride the SAME masked programs
    opt._seed_dirty = True
    info = be.partitions()[("t1", 1)]
    be.elect_leaders({("t1", 1): info.replicas[-1]})
    lm.sample_once(now_ms=9 * 300_000.0)
    sess.sync()
    r = _round(opt, sess)
    # the reduced chain itself must add nothing; only a triggered full-R
    # fallback may compile its per-goal program (first trigger only)
    if r.fallback_goals == 0:
        assert listener.count == n0, (listener.count, n0)
    opt._seed_dirty = False
    _, r = _steady(opt, sess, lm, 10)
    assert r.round_mode in ("full", "revalidated")


def test_dirty_replica_mask_targets_touched_sets():
    """dirty_replica_mask flags exactly the replicas on dirty brokers or in
    dirty topics, never the padding slots."""
    be = _backend(seed=10)
    lm = _monitored(be)
    sess = ResidentClusterSession(lm)
    sess.sync()
    rb = sess._h["replica_broker"]
    valid = sess._h["replica_valid"]

    mask = sess.dirty_replica_mask({0}, set())
    assert mask.dtype == bool and mask.shape == rb.shape
    np.testing.assert_array_equal(mask, (rb == 0) & valid)
    assert not mask[~valid].any()

    mask_t = sess.dirty_replica_mask(set(), {0})
    pt = np.asarray(sess._prev_snapshot.partition_topic)
    rp = sess._h["replica_partition"]
    in_topic0 = np.zeros_like(valid)
    ok = (rp >= 0) & (rp < pt.size)
    in_topic0[ok] = pt[rp[ok]] == 0
    np.testing.assert_array_equal(mask_t, in_topic0 & valid)

    assert not sess.dirty_replica_mask(set(), set()).any()
