"""Segment-parallel finisher + compensated accounting certification (PR 7).

The two contracts this PR adds to the engine:

1. SEGMENT-PARALLEL FINISHER (``EngineParams.max_finisher_segments`` /
   ``finisher_segments``): the finisher's applied waves spread each scan
   candidate across interaction-disjoint broker segments and admit the
   flattened [K * S] action rows in ONE batched program. Parity bar (the
   PR 4/5 style): segments-on == segments-off identical violation sets and
   ``fixpoint_proven`` certificate sets on the seeded parity fixtures with
   the finisher forced on; the ACTIVE segment count is a traced budget leaf
   (toggling it compiles nothing new); the applied set stays consistent
   with a from-scratch ``refresh`` (the sequential-equivalence evidence —
   every derived tally matches the assignment the wave produced).

2. COMPENSATED (Kahan) ACCOUNTING (``EngineState.util_residual`` /
   ``leader_util_residual``): the f32 rounding error of the incremental
   scatter accounting is carried beside the accumulators, the bf16 sweep
   policy reads the compensated sums (engine._sweep_state), and the
   compensation may never LOSE accuracy — ``util + residual`` is at least
   as close to the exact sum as ``util`` alone, so a tail gain f32 sees is
   never a rounding casualty of the compensated path.

Only the pre-registered ``slow`` marker is used (tests/conftest.py).
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.analyzer.engine import (
    EngineParams, _sweep_state, optimize_goal,
)
from cruise_control_tpu.analyzer.env import make_env, padded_partition_table
from cruise_control_tpu.analyzer.goals import make_goals
from cruise_control_tpu.analyzer.optimizer import (
    BF16_AUTO_MIN_REPLICAS, GoalOptimizer, _resolve_compute_dtype,
)
from cruise_control_tpu.analyzer.state import (
    apply_moves_batched, init_state, refresh,
)
from cruise_control_tpu.config import cruise_control_config
from cruise_control_tpu.model.cluster_tensor import pad_cluster
from cruise_control_tpu.model.random_cluster import RandomClusterSpec, generate

CHAIN = ["RackAwareGoal", "DiskCapacityGoal", "CpuCapacityGoal",
         "ReplicaDistributionGoal", "DiskUsageDistributionGoal",
         "LeaderReplicaDistributionGoal"]


def _cluster(seed=777, brokers=24, partitions=300):
    return generate(RandomClusterSpec(
        num_brokers=brokers, num_racks=4, num_topics=12,
        num_partitions=partitions, max_replication=2, skew=2.0, seed=seed))


def _run(ct, meta, params, config=None):
    opt = GoalOptimizer(config=config, engine_params=params)
    return opt.optimizations(ct, meta, goal_names=CHAIN,
                             raise_on_failure=False,
                             skip_hard_goal_check=True)


# ------------------------------------------------------------ outcome parity
def test_segments_on_off_outcome_parity():
    """Segments-on vs segments-off (static legacy waves): identical
    violation sets and fixpoint-certificate sets on the seeded parity
    fixtures, finisher forced on (small fixtures normally skip it)."""
    cfg = cruise_control_config({"analyzer.finisher.min.replicas": 0})
    for seed in (777, 881):
        ct, meta = _cluster(seed=seed)
        r_on = _run(ct, meta, EngineParams(finisher_segments=8,
                                           max_finisher_segments=8),
                    config=cfg)
        r_off = _run(ct, meta, EngineParams(finisher_segments=0,
                                            max_finisher_segments=0),
                     config=cfg)
        assert (r_on.violated_goals_after
                == r_off.violated_goals_after), f"seed={seed}"
        cert_on = {g.name for g in r_on.goal_results
                   if g.violated_after and g.fixpoint_proven}
        cert_off = {g.name for g in r_off.goal_results
                    if g.violated_after and g.fixpoint_proven}
        assert cert_on == cert_off, f"seed={seed}"
        # the off run reports the legacy wave (segments=0) in its profile
        assert all(g.finisher_segments == 0 for g in r_off.goal_results)


def test_segment_waves_apply_and_stay_refresh_consistent():
    """With the budgeted loop crippled the segmented finisher must land the
    actions itself; afterwards EVERY derived tally matches a from-scratch
    refresh of the assignment it produced (the sequential-equivalence
    evidence: the batched segment wave bookkeeping equals rebuilding from
    the final placement), and the segment/boundary counters surface."""
    ct, meta = _cluster(seed=881, brokers=32, partitions=800)
    ct, meta = pad_cluster(ct, meta)
    env = make_env(ct, meta, partition_table=padded_partition_table(ct))
    st = init_state(env, ct.replica_broker, ct.replica_is_leader,
                    ct.replica_offline, ct.replica_disk)
    goals = make_goals(["DiskUsageDistributionGoal",
                        "LeaderReplicaDistributionGoal"])
    params = EngineParams(max_iters=2, stall_retries=0, tail_pass_budget=1,
                          tail_total_budget=2, finisher_rounds=10,
                          finisher_candidates=64, finisher_waves=4,
                          finisher_segments=8, max_finisher_segments=8)
    prev = ()
    fin_actions = 0
    segs = 0
    for g in goals:
        st, info = optimize_goal(env, st, g, prev, params)
        prev = prev + (g,)
        fin_actions += int(info["finisher_actions"])
        segs = max(segs, int(info["finisher_segments"]))
    assert fin_actions > 0, "crippled budgets: the finisher must act"
    assert segs == 8
    r = refresh(env, st)
    np.testing.assert_array_equal(np.asarray(st.replica_count),
                                  np.asarray(r.replica_count))
    np.testing.assert_array_equal(np.asarray(st.leader_count),
                                  np.asarray(r.leader_count))
    np.testing.assert_array_equal(np.asarray(st.part_rack_count),
                                  np.asarray(r.part_rack_count))
    np.testing.assert_array_equal(np.asarray(st.topic_broker_count),
                                  np.asarray(r.topic_broker_count))
    # float tallies: incremental vs recomputed within f32 accumulation noise
    np.testing.assert_allclose(np.asarray(st.util), np.asarray(r.util),
                               rtol=1e-4, atol=1e-2)


def test_segment_toggle_is_traced_zero_new_compiles():
    """``finisher_segments`` (active count) is a traced budget leaf —
    toggling it reuses the compiled programs; ``max_finisher_segments``
    (spread width) is static — flipping it changes the treedef (documented
    recompile)."""
    import logging

    p8 = EngineParams(finisher_segments=8, max_finisher_segments=8)
    assert (jax.tree_util.tree_structure(p8)
            == jax.tree_util.tree_structure(
                dataclasses.replace(p8, finisher_segments=1)))
    assert (jax.tree_util.tree_structure(p8)
            != jax.tree_util.tree_structure(
                dataclasses.replace(p8, max_finisher_segments=0)))

    cfg = cruise_control_config({"analyzer.finisher.min.replicas": 0})
    ct, meta = _cluster(seed=779)
    _run(ct, meta, p8, config=cfg)       # compile

    class Counter(logging.Handler):
        def __init__(self):
            super().__init__(level=logging.DEBUG)
            self.count = 0

        def emit(self, record):
            if "Compiling" in record.getMessage():
                self.count += 1

    handler = Counter()
    prev = bool(jax.config.jax_log_compiles)
    jax.config.update("jax_log_compiles", True)
    logging.getLogger("jax").addHandler(handler)
    try:
        for segs in (1, 3, 8):
            _run(ct, meta, dataclasses.replace(p8, finisher_segments=segs),
                 config=cfg)
    finally:
        logging.getLogger("jax").removeHandler(handler)
        jax.config.update("jax_log_compiles", prev)
    assert handler.count == 0, \
        f"{handler.count} recompiles on finisher_segments toggles"


# ------------------------------------------------ compensated accounting
def test_kahan_residual_never_loses_accuracy():
    """Apply waves of deliberately cancellation-heavy moves (tiny loads
    against large accumulators — the tail-gain regime): ``util + residual``
    must be at least as close to the f64-exact accounting as ``util``
    alone, elementwise, and strictly closer somewhere (the compensation
    does real work on this construction)."""
    ct, meta = _cluster(seed=42, brokers=16, partitions=400)
    ct, meta = pad_cluster(ct, meta)
    env = make_env(ct, meta, partition_table=padded_partition_table(ct))
    st = init_state(env, ct.replica_broker, ct.replica_is_leader,
                    ct.replica_offline, ct.replica_disk)
    rng = np.random.default_rng(0)
    R = env.num_replicas
    B = env.num_brokers
    valid = np.asarray(ct.replica_valid)
    # f64 shadow of the accounting the waves perform
    exact = np.asarray(st.util, np.float64)
    lead = np.asarray(st.replica_is_leader)
    ll = np.asarray(env.leader_load, np.float64)
    fl = np.asarray(env.follower_load, np.float64)
    part = np.asarray(env.replica_partition)
    stx = st
    moved_parts: set[int] = set()
    for wave in range(6):
        picks, dsts = [], []
        for r in rng.permutation(np.flatnonzero(valid))[:200]:
            if int(part[r]) in moved_parts or len(picks) >= 16:
                continue
            moved_parts.add(int(part[r]))
            picks.append(int(r))
            dsts.append(int(rng.integers(0, B)))
        picks_a = jnp.asarray(picks, jnp.int32)
        dsts_a = jnp.asarray(dsts, jnp.int32)
        mask = jnp.ones(len(picks), bool)
        src = np.asarray(stx.replica_broker)[picks]
        stx = apply_moves_batched(env, stx, picks_a, dsts_a, mask)
        for i, r in enumerate(picks):
            row = ll[r] if lead[r] else fl[r]
            exact[src[i]] -= row
            exact[dsts[i]] += row
    raw_err = np.abs(np.asarray(stx.util, np.float64) - exact)
    comp_err = np.abs(np.asarray(stx.util, np.float64)
                      + np.asarray(stx.util_residual, np.float64) - exact)
    # never lose: compensated error <= raw error everywhere (tiny slack for
    # the second-order error of the estimate itself)
    assert np.all(comp_err <= raw_err + 1e-4), \
        (comp_err.max(), raw_err.max())
    if raw_err.max() > 0:
        assert comp_err.sum() <= raw_err.sum()


def test_sweep_state_reads_compensated_view():
    """Under the bf16 policy the sweep view's broker accumulators are the
    COMPENSATED f32 sums (util + residual) — not a bf16 downcast; under f32
    the view is the identity (bit-exact fallback)."""
    ct, meta = _cluster(seed=43, brokers=16, partitions=200)
    ct, meta = pad_cluster(ct, meta)
    env = make_env(ct, meta, partition_table=padded_partition_table(ct))
    st = init_state(env, ct.replica_broker, ct.replica_is_leader,
                    ct.replica_offline, ct.replica_disk)
    # plant a residual the view must surface
    st = dataclasses.replace(
        st, util_residual=jnp.full_like(st.util, 1e-3))
    sw = _sweep_state(st, EngineParams(compute_dtype="bfloat16"))
    assert sw.util.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(sw.util),
                               np.asarray(st.util + st.util_residual))
    assert _sweep_state(st, EngineParams(compute_dtype="float32")) is st
    assert _sweep_state(st, EngineParams()) is st


def test_auto_dtype_resolution():
    """'auto' resolves to bf16 at the >= 256k-replica threshold and f32
    below; explicit pins win at every level."""
    assert _resolve_compute_dtype("auto", "auto", 1000) == "float32"
    assert _resolve_compute_dtype(
        "auto", "auto", BF16_AUTO_MIN_REPLICAS) == "bfloat16"
    assert _resolve_compute_dtype(
        "auto", "auto", BF16_AUTO_MIN_REPLICAS - 1) == "float32"
    assert _resolve_compute_dtype(
        "auto", "float32", 10 * BF16_AUTO_MIN_REPLICAS) == "float32"
    assert _resolve_compute_dtype("auto", "bfloat16", 1000) == "bfloat16"
    assert _resolve_compute_dtype("float32", "bfloat16", 10**7) == "float32"
    assert _resolve_compute_dtype("bfloat16", "float32", 8) == "bfloat16"


@pytest.mark.slow
def test_segments_parity_bf16_matrix():
    """Slow matrix: {segments on/off} x {f32/bf16} on the parity seeds with
    the finisher forced — violation and certificate sets identical across
    all four cells per seed (the rung-ladder A/B's fixture-scale mirror)."""
    cfg = cruise_control_config({"analyzer.finisher.min.replicas": 0})
    for seed in (777, 881, 1234):
        ct, meta = _cluster(seed=seed)
        cells = {}
        for segs in (8, 0):
            for dt in ("float32", "bfloat16"):
                r = _run(ct, meta, EngineParams(
                    finisher_segments=segs, max_finisher_segments=segs,
                    compute_dtype=dt), config=cfg)
                cells[(segs, dt)] = (
                    tuple(r.violated_goals_after),
                    frozenset(g.name for g in r.goal_results
                              if g.violated_after and g.fixpoint_proven))
        vals = set(cells.values())
        assert len(vals) == 1, f"seed={seed}: {cells}"
