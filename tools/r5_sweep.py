"""Round-5 budget sweep at the headline rung.

EngineParams' budget fields are traced pytree leaves now, so every config
below shares ONE set of compiled programs — the sweep pays a single compile
(usually a persistent-cache hit) and then ~25 s per warm config instead of
~15 min of XLA recompiles per config on this 1-core host.

Usage: python tools/r5_sweep.py [config ...]   (default: all)
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_cc_tpu")
import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_cc_tpu")
import dataclasses  # noqa: E402

from cruise_control_tpu.analyzer.engine import EngineParams  # noqa: E402
from cruise_control_tpu.analyzer.optimizer import GoalOptimizer  # noqa: E402
from cruise_control_tpu.model.random_cluster import (  # noqa: E402
    RandomClusterSpec, generate_scale,
)

CONFIGS = {
    "default": {},
    "tail48": {"tail_total_budget": 48, "tail_pass_budget": 32},
    "tail16": {"tail_total_budget": 16, "tail_pass_budget": 16,
               "stall_retries": 4},
    "satlean": {"sat_tail_passes": 4, "sat_stall_retries": 1},
    "slope": {"stat_window": 12, "stat_slope_min": 3e-3},
    "lean": {"tail_total_budget": 48, "tail_pass_budget": 32,
             "sat_tail_passes": 4, "sat_stall_retries": 1,
             "stat_window": 12, "stat_slope_min": 3e-3},
}


def main():
    names = sys.argv[1:] or list(CONFIGS)
    print("generating rung-4 cluster...", flush=True)
    ct, meta = generate_scale(RandomClusterSpec(
        num_brokers=7000, num_racks=40, num_topics=2000,
        num_partitions=500000, max_replication=3, skew=1.0, seed=3142,
        target_cpu_util=0.45))
    warmed = False
    for name in names:
        params = dataclasses.replace(EngineParams(), **CONFIGS[name])
        opt = GoalOptimizer(engine_params=params)
        runs = 2 if not warmed else 1   # first config warms the compile cache
        for i in range(runs):
            t0 = time.monotonic()
            res = opt.optimizations(ct, meta, raise_on_failure=False,
                                    skip_hard_goal_check=True)
            wall = time.monotonic() - t0
        warmed = True
        out = {
            "config": name,
            "wall_s": round(wall, 2),
            "violations_after": len(res.violated_goals_after),
            "violated": res.violated_goals_after,
            "exhausted": [g.name for g in res.goal_results if g.hit_max_iters],
            "proven": [g.name for g in res.goal_results
                       if g.violated_after and g.fixpoint_proven],
            "moves": res.num_replica_movements,
            "leads": res.num_leadership_movements,
            "deep": {g.name[:12]: {"passes": g.passes,
                                   "fin_rounds": g.finisher_rounds,
                                   "actions": g.iterations}
                     for g in res.goal_results
                     if g.passes > 40 or g.finisher_rounds > 0},
        }
        print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
