"""Pluggable OptimizationOptions generation.

Reference: analyzer/OptimizationOptionsGenerator.java (AnalyzerConfig
``optimization.options.generator.class``) — a seam letting deployments derive
per-run OptimizationOptions (e.g. force fast mode during business hours)
instead of the defaults. The app asks the configured generator for the
options of every internally-triggered optimization.
"""
from __future__ import annotations

from cruise_control_tpu.analyzer.env import OptimizationOptions


class DefaultOptimizationOptionsGenerator:
    """Passes through the options the caller built (reference
    DefaultOptimizationOptionsGenerator behavior)."""

    def configure(self, config) -> None:  # CruiseControlConfigurable seam
        self._config = config

    def optimization_options(self, base: OptimizationOptions,
                             operation: str = "") -> OptimizationOptions:
        """Return the options an optimization should run with. ``base`` is
        what the operation itself requested; ``operation`` names the caller
        (rebalance / remove_brokers / self-healing / ...)."""
        del operation
        return base
