"""Device-count A/B harness for the shard-explicit engine (PR 9):

    {1, 2, 4, 8} virtual host devices x one kernel-coverage goal chain,

one command, one subprocess per device count (the virtual device count must
be fixed via XLA_FLAGS before the first JAX import, so cells cannot share a
process). Per cell: cold + warm chain wall, violation verdicts, applied
actions, real per-device committed bytes, and a digest of the final
assignment — the parent asserts every mesh size's digest equals the
1-device digest (the shard_map engine's bit-identity contract, measured
here rather than assumed) and prints a pretty table plus ONE compact
machine-parseable JSON last line in the bench.py style.

Usage: shard_ab.py [--devices 1,2,4,8] [--brokers 32] [--partitions 600]
"""
import hashlib
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

GOALS = ["RackAwareGoal", "DiskCapacityGoal", "CpuCapacityGoal",
         "ReplicaDistributionGoal", "DiskUsageDistributionGoal",
         "LeaderReplicaDistributionGoal"]


def _child(n: int, brokers: int, partitions: int) -> None:
    """One cell: runs in its own process with n virtual devices."""
    import dataclasses

    import jax
    import numpy as np

    from cruise_control_tpu.analyzer import (
        EngineParams, init_state, make_env, optimize_goal,
    )
    from cruise_control_tpu.analyzer.goals import make_goals
    from cruise_control_tpu.model.cluster_tensor import pad_cluster
    from cruise_control_tpu.model.random_cluster import (
        RandomClusterSpec, generate,
    )
    from cruise_control_tpu.parallel import make_mesh
    from cruise_control_tpu.parallel.sharding import (
        committed_per_device_bytes, replicate,
    )

    ct, meta = generate(RandomClusterSpec(
        num_brokers=brokers, num_racks=4, num_topics=16,
        num_partitions=partitions, max_replication=3, skew=1.2, seed=3143,
        target_cpu_util=0.45))
    ct, meta = pad_cluster(ct, meta)
    env = make_env(ct, meta)
    st = init_state(env, ct.replica_broker, ct.replica_is_leader,
                    ct.replica_offline, ct.replica_disk)
    params = EngineParams(max_iters=24, stall_retries=2, tail_pass_budget=8,
                          tail_total_budget=24, finisher_rounds=2,
                          finisher_candidates=64, finisher_waves=2,
                          scan_chunk=256)
    if n > 1:
        mesh = make_mesh(n)
        env, st0 = replicate(env, mesh), replicate(st, mesh)
        params = dataclasses.replace(params, mesh=mesh)
    else:
        st0 = st
    goals = make_goals(GOALS)

    def run(s):
        prev, viol, acts = (), [], 0
        for g in goals:
            s, info = optimize_goal(env, s, g, prev, params)
            prev = prev + (g,)
            viol.append(bool(jax.device_get(info["violated_after"])))
            acts += int(jax.device_get(info["iterations"]))
        jax.block_until_ready(s.util)
        return s, viol, acts

    t0 = time.monotonic()
    _s, _v, _a = run(st0)
    cold = round(time.monotonic() - t0, 2)
    t0 = time.monotonic()
    s, viol, acts = run(st0)
    warm = round(time.monotonic() - t0, 2)
    digest = hashlib.sha256(
        np.asarray(s.replica_broker).tobytes()
        + np.asarray(s.replica_is_leader).tobytes()).hexdigest()[:16]
    print(json.dumps({
        "n": n, "brokers": env.num_brokers, "replicas": env.num_replicas,
        "wall_s_cold": cold, "wall_s_warm": warm, "actions": acts,
        "violations_after": viol, "assignment_digest": digest,
        "per_device_bytes": {str(d): int(v) for d, v in sorted(
            committed_per_device_bytes((env, s)).items())},
    }))


def main() -> None:
    argv = sys.argv[1:]
    if argv and argv[0] == "--child":
        _child(int(argv[1]), int(argv[2]), int(argv[3]))
        return

    def _opt(name, default):
        return (argv[argv.index(name) + 1] if name in argv else default)

    devices = [int(x) for x in _opt("--devices", "1,2,4,8").split(",")]
    brokers = int(_opt("--brokers", "32"))
    partitions = int(_opt("--partitions", "600"))
    cells = []
    for n in devices:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        flags = " ".join(f for f in env.get("XLA_FLAGS", "").split()
                         if "host_platform_device_count" not in f)
        env["XLA_FLAGS"] = (flags + f" --xla_force_host_platform_device_"
                                    f"count={max(n, 1)}").strip()
        env.setdefault("JAX_COMPILATION_CACHE_DIR",
                       f"/tmp/jax_cache_cc_multichip_{n}")
        env.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
        env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child",
             str(n), str(brokers), str(partitions)],
            env=env, cwd=REPO, capture_output=True, text=True)
        if proc.returncode != 0:
            print(proc.stderr[-2000:], file=sys.stderr)
            raise SystemExit(f"cell n={n} failed rc={proc.returncode}")
        cell = json.loads(proc.stdout.strip().splitlines()[-1])
        cells.append(cell)
        mem = max(cell["per_device_bytes"].values())
        print(f"  n={n}: warm={cell['wall_s_warm']}s cold={cell['wall_s_cold']}s "
              f"actions={cell['actions']} "
              f"viol={sum(cell['violations_after'])} "
              f"per-dev={mem / 1e6:.2f}MB digest={cell['assignment_digest']}",
              file=sys.stderr, flush=True)
    ref = cells[0]["assignment_digest"]
    parity = all(c["assignment_digest"] == ref for c in cells)
    if not parity:
        print("PARITY FAILURE: assignment digests differ across device "
              "counts", file=sys.stderr)
    print(json.dumps({"shard_ab": {
        "goals": GOALS, "devices": devices, "parity": parity,
        "cells": cells}}))
    if not parity:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
